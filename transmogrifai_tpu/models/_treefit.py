"""Histogram-based decision-tree learning in pure JAX — the TPU-native
replacement for Spark MLlib trees and xgboost4j's C++/JNI core
(reference: ``OpRandomForestClassifier.scala``, ``OpGBTClassifier.scala``,
``OpXGBoostClassifier.scala:46``; Rabit allreduce ``:74-90``).

Design (SURVEY §7 step 8): **static shapes everywhere** so the whole
(fold × hyperparameter) grid vmaps onto the mesh.

* Features are quantile-binned once per fit (``n_bins=32``, Spark's
  ``maxBins`` default) — binning depends only on X, so under a fold-vmap
  XLA computes it once.
* A tree is grown **level-wise** under one ``lax.scan`` over levels: every
  sample carries a node index in [0, 2^d); per level one batched matmul
  builds the [slots, features, bins, channels] histogram (Rabit's allreduce
  becomes a ``psum`` when the batch axis is sharded), a cumulative sum over
  bins scores every (feature, threshold) candidate, and an argmax picks the
  split. Nodes that stop splitting route all samples left via a dummy
  (+inf threshold) split, so the fixed-depth routing stays correct.
* The scan keeps a **constant active-slot count** per level, so the level
  body has one shape and is traced/compiled once — the round-1 design
  unrolled the level loop in Python, which made XLA compile minutes of HLO
  per tree family (the round-1 bench spent 100+s compiling).
* Hyperparameters that only gate values (minInstancesPerNode, minInfoGain,
  eta, minChildWeight, numTrees/numRound, subsample rate, **and maxDepth**)
  are *traced* scalars → the whole grid vmaps into ONE program per family.
  ``maxDepth`` gates splitting per level (``level < depth_limit``); the
  static scan length is the grid's max depth.
* Ensembles run under ``lax.scan`` (bounded memory; XLA pipelines the
  per-tree work); RF bootstraps with Poisson(subsample) weights.

Tree layout: level-order arrays ``feat``/``thr`` of length 2^D − 1 and
``leaf`` of shape [2^D, K]; routing is ``node = 2*node + (x[feat] > thr)``.
"""
from __future__ import annotations

import contextlib
import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_EPS = 1e-12
_NEG = -1e30


# ---------------------------------------------------------------------------
# Mesh threading (the histogram-allreduce analog: Rabit → psum)
# ---------------------------------------------------------------------------

#: the (data, grid) mesh the tree engine's kernel dispatches shard over —
#: a module global (not thread-local) because the CV engine TRACES its
#: fused programs on ThreadPoolExecutor workers while the scope is held
#: by the dispatching thread. Consumers read it at trace time only.
_TREE_MESH = [None]

#: requested feature-axis shard count (1 = off). Like ``_TREE_MESH`` a
#: module global read at trace time: the runner installs it run-scoped
#: (``customParams.featureShards``) and restores in ``finally``; it only
#: ENGAGES when the active tree mesh's ``grid`` axis matches it exactly
#: (see ``_feature_shard_count``), so a stale value over the wrong mesh
#: fails open to the current path instead of mis-sharding.
_FEATURE_SHARDS = [1]


def set_feature_shards(n: int) -> int:
    """Install the requested feature-axis shard count (1 = off);
    returns the previous value for ``finally``-restore."""
    n = int(n)
    if n < 1:
        raise ValueError(f"feature shards must be >= 1, got {n}")
    prev = _FEATURE_SHARDS[0]
    _FEATURE_SHARDS[0] = n
    return prev


@contextlib.contextmanager
def feature_shards_scope(n: int):
    """Scoped :func:`set_feature_shards` (tests, bench legs)."""
    prev = set_feature_shards(n)
    try:
        yield
    finally:
        _FEATURE_SHARDS[0] = prev


def active_feature_shards() -> int:
    """The requested feature-axis shard count (1 = off)."""
    return _FEATURE_SHARDS[0]


@contextlib.contextmanager
def tree_mesh_scope(mesh):
    """Install ``mesh`` as the tree engine's sharding substrate for the
    duration of the block (trace-time: every ``grow_tree`` traced inside
    consults it). The degenerate 1-device mesh — and ``None``/``False``
    — resolve to "no sharding", so the single-device trace is EXACTLY
    the pre-mesh program (the PR 6 discipline). Re-entrant; the previous
    scope is restored on exit. Two concurrent validates installing
    DIFFERENT meshes would race — the runner serializes runs, and the
    compiled-executable caches key on the mesh topology anyway."""
    from ..parallel.mesh import mesh_if_multi
    prev = _TREE_MESH[0]
    _TREE_MESH[0] = mesh_if_multi(mesh)
    try:
        yield
    finally:
        _TREE_MESH[0] = prev


def active_tree_mesh():
    """The mesh installed by :func:`tree_mesh_scope`, or None (already
    ``mesh_if_multi``-normalized: never a 1-device mesh)."""
    return _TREE_MESH[0]


def _rng_replicated(draw, *keys):
    """Evaluate the RNG ``draw(*keys)`` pinned against GSPMD partitioning.

    Over a mesh with a real ``grid`` axis, GSPMD's backward sharding
    propagation can push a grid-sharded layout from a downstream
    ``shard_map`` into the threefry computation itself — and with the
    non-partitionable threefry (``jax_threefry_partitionable=False``,
    this JAX version's default) a sharded evaluation CHANGES the drawn
    values, not just their layout. Bootstrap weights and per-node
    feature masks then silently differ between the sharded and solo
    programs. A shard_map body is compiled per device verbatim, so
    wrapping the draw in a fully-replicated shard_map makes every
    device evaluate the identical unsharded draw: the stream matches
    the meshless program bit-for-bit. With no mesh (or a grid axis of
    1, where nothing can mis-shard) the draw runs untouched — the
    exact pre-shard jaxpr."""
    mesh = active_tree_mesh()
    if mesh is None or int(mesh.shape.get("grid", 1)) <= 1:
        return draw(*keys)
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    in_specs = tuple(P(*([None] * jnp.ndim(k))) for k in keys)
    out = jax.eval_shape(draw, *keys)
    out_specs = jax.tree_util.tree_map(
        lambda a: P(*([None] * len(a.shape))), out)
    return shard_map(draw, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)(*keys)


def _sharded_cumhist(mesh, stats, node, XbT, n_nodes, n_bins, *,
                     bc=None, sparse01=False):
    """Data-parallel histogram build over the mesh ``data`` axis: each
    shard streams ITS rows through the Pallas ``cumhist`` kernel and the
    per-shard partial histograms merge with one ``psum`` — histograms
    are monoids, so the merged result equals the single-device pass
    (exactly, for the integer count channels; weighted channels see the
    same f32 partial-sum algebra GSPMD gives the XLA matmul path). This
    is the xgboost4j/Rabit histogram allreduce as a collective the
    compiler schedules over ICI (_treefit module docstring, PAPER.md
    §L0/L4), and the reason tree fits scale with the mesh instead of
    replicating the kernel's operands to every chip (GSPMD cannot
    partition a custom call it cannot see into)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ._pallas_hist import _tk_tally, cumhist
    _tk_tally("sharded_hist_traces")
    in_specs = [P("data", None), P("data"), P(None, "data")]
    args = [stats, node, XbT]
    if bc is not None:
        in_specs.append(P(None, "data"))
        args.append(bc)

    def body(st, nd, xb, *rest):
        h = cumhist(st, nd, xb, n_nodes, n_bins,
                    bc=(rest[0] if rest else None), sparse01=sparse01)
        return lax.psum(h, "data")

    return shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                     out_specs=P(None, None, None, None),
                     check_rep=False)(*args)


def _sharded_route_level(mesh, XbT, slot, g, f_idx, t_idx, lchild,
                         rchild, do_split, A_parent, A_child):
    """Row-sharded level routing: the per-row (slot, g) update streams
    each shard's rows through the Pallas ``route_level`` kernel; the
    split tables (tiny, post-psum ⇒ replicated) broadcast. Outputs stay
    row-sharded — routing state never leaves its shard."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ._pallas_hist import _tk_tally, route_level
    _tk_tally("sharded_route_traces")

    def body(xb, sl, gg, fi, ti, lc, rc, ds):
        return route_level(xb, sl, gg, fi, ti, lc, rc, ds,
                           A_parent, A_child)

    return shard_map(
        body, mesh=mesh,
        in_specs=(P(None, "data"), P("data"), P("data"),
                  P(), P(), P(), P(), P()),
        out_specs=(P("data"), P("data")), check_rep=False,
    )(XbT, slot, g, f_idx, t_idx, lchild, rchild, do_split)


def _feature_sharded_split(mesh, stats, node, XblkS, A, nb, *, kind,
                           min_instances, lam, mcw, mask_afS, bcS=None,
                           sparse01=False, half=None, prevS=None,
                           rank=None):
    """Feature-axis-sharded histogram + fused split scan for ONE block
    (the VMEM half of the tentpole): the block's columns are pre-split
    into ``G = mesh.shape['grid']`` contiguous sub-blocks (zero-padded
    to equal width ``Flg``, pads masked out), and each grid shard runs
    the EXISTING Pallas ``cumhist`` + ``split_scan`` over its own
    [Flg, n] slice — per-chip kernel working set shrinks 1/G, which is
    what lets matrices wider than one chip's VMEM envelope train at all.
    Rows still shard over ``data`` (partial histograms psum-merge
    exactly as :func:`_sharded_cumhist`).

    Returns per-shard local winners stacked on a leading grid axis —
    ``(score [G, A], local flat idx [G, A], valid [G, A], winner left
    stats [G, A, C], histogram [G, A, C, nb, Flg] still grid-sharded
    for next-level sibling subtraction, node totals [A, C])`` — and the
    caller merges them by the same ``(score desc, global idx asc)`` rule
    the per-block merge already uses, so the cross-shard merge is one
    tiny allgather of [G, A] scalars, not a histogram exchange.

    Bit-parity with the single-shard pass holds by construction: each
    feature's histogram lane and candidate score depend only on that
    feature (identical kernel math at any block width), and contiguous
    column chunks keep the t-major global candidate order — real
    candidates rank identically, pad candidates carry the masked
    sentinel score and can only "win" when no valid split exists (where
    the winner's identity is dead downstream).

    ``prevS``/``rank``/``half`` engage the sibling-subtraction variant:
    ``node`` is then the even-slot map at ``half`` parent slots and the
    previous level's grid-sharded histogram is gathered at ``rank``
    per shard. Node totals replicate via a psum-selected shard-0
    feature-0 lane — the exact lane the unsharded path reads."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ._pallas_hist import _tk_tally, cumhist, split_scan
    _tk_tally("feature_shard_traces")
    C = stats.shape[1]
    Flg = int(XblkS.shape[1])
    use_prev = prevS is not None
    in_specs = [P("data", None), P("data"), P("grid", None, "data"),
                P("grid", None, None), P()]
    args = [stats, node, XblkS, mask_afS, jnp.asarray(min_instances)]
    if bcS is not None:
        in_specs.append(P("grid", None, "data"))
        args.append(bcS)
    if mcw is not None:
        in_specs.append(P())
        args.append(jnp.asarray(mcw))
    if use_prev:
        in_specs.extend([P("grid", None, None, None, None), P()])
        args.extend([prevS, rank])

    def body(st, nd, xbS, mafS, mi, *rest):
        ri = 0
        bcl = None
        if bcS is not None:
            bcl = rest[ri][0]
            ri += 1
        mcw_l = None
        if mcw is not None:
            mcw_l = rest[ri]
            ri += 1
        if use_prev:
            ev = lax.psum(cumhist(st, nd, xbS[0], half, nb, bc=bcl,
                                  sparse01=sparse01), "data")
            parent = rest[ri][0][rest[ri + 1]]     # [half, C, nb, Flg]
            cumb = jnp.stack([ev, parent - ev], axis=1).reshape(
                (A,) + ev.shape[1:])               # interleave 2i/2i+1
        else:
            cumb = lax.psum(cumhist(st, nd, xbS[0], A, nb, bc=bcl,
                                    sparse01=sparse01), "data")
        sc, ix, ok = split_scan(cumb, kind, mi, lam=lam,
                                min_child_weight=mcw_l, mask=mafS[0])
        size = (nb - 1) * Flg
        lb = jnp.take_along_axis(
            cumb[:, :, :-1, :].reshape(A, C, size),
            jnp.clip(ix, 0, max(size - 1, 0))[:, None, None],
            axis=2)[:, :, 0]                       # [A, C] local winner
        tst = lax.psum(
            jnp.where(lax.axis_index("grid") == 0, cumb[:, :, -1, 0],
                      jnp.zeros((A, C), cumb.dtype)), "grid")
        return (sc[None], ix[None], ok[None], lb[None], cumb[None], tst)

    return shard_map(
        body, mesh=mesh, in_specs=tuple(in_specs),
        out_specs=(P("grid", None), P("grid", None), P("grid", None),
                   P("grid", None, None),
                   P("grid", None, None, None, None), P(None, None)),
        check_rep=False)(*args)


def _fs_block_mask(cols, G, Flg, A, feat_mask, node_mask, dtype):
    """[G, A, Flg] candidate mask for one feature-sharded block: the
    existing feature/per-node masks over the block's real columns, zero
    over the width pad (contiguous chunks: global feature s·Flg + f)."""
    fb_n = len(cols)
    m = jnp.ones((A, fb_n), dtype)
    if feat_mask is not None:
        m = m * jnp.broadcast_to(
            feat_mask[jnp.asarray(cols)][None, :], (A, fb_n)).astype(dtype)
    if node_mask is not None:
        m = m * node_mask[:, jnp.asarray(cols)].astype(dtype)
    pad = G * Flg - fb_n
    if pad:
        m = jnp.concatenate([m, jnp.zeros((A, pad), dtype)], axis=1)
    return m.reshape(A, G, Flg).transpose(1, 0, 2)


# ---------------------------------------------------------------------------
# Binning
# ---------------------------------------------------------------------------

#: rows used for the quantile sketch at large n — full-column device sorts
#: inside every fit were ~13% of the round-3 2M-row profile; Spark's
#: approxQuantile and xgboost's quantile sketch are likewise approximate
QUANTILE_SAMPLE_ROWS = 262_144


#: fixed key for the quantile-sketch row permutation: the subsample must
#: be deterministic per row count (compiled-executable reuse) but must
#: not depend on row ORDER
_QUANTILE_SEED = 0x51EED


def quantile_bin_edges(X: jnp.ndarray, n_bins: int) -> jnp.ndarray:
    """Per-feature interior quantile edges → [F, n_bins - 1].

    Beyond QUANTILE_SAMPLE_ROWS the sketch uses a SEEDED-PERMUTATION
    strided subsample (deterministic, jit-static shape): the previous
    raw ``X[::stride]`` slice made the sketch a function of row order —
    time-sorted or class-clustered inputs (every event-log reader emits
    key-grouped rows) systematically over- or under-sampled parts of
    the distribution, so the same column produced different edges
    sorted vs shuffled. A fixed-key permutation of row indices draws
    the same-size sample uniformly whatever the order."""
    n = X.shape[0]
    stride = max(1, -(-n // QUANTILE_SAMPLE_ROWS))
    qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    if stride == 1:
        return jnp.quantile(X, qs, axis=0).T
    idx = _rng_replicated(
        lambda k: jax.random.permutation(k, n),
        jax.random.PRNGKey(_QUANTILE_SEED))[:-(-n // stride)]
    return jnp.quantile(X[idx], qs, axis=0).T


def binarize(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """bin[i, f] = #{edges[f] < x[i, f]} ∈ [0, n_bins-1]; bin ≤ t ⟺
    x ≤ edges[f, t], matching the stored split threshold.

    One fused compare-accumulate pass over X — ``jnp.searchsorted``'s
    default lowering is a binary-search *scan* carrying [n] state per
    step, which ran on the TPU as serialized while-loops."""
    return jnp.sum(X[:, :, None] > edges[None, :, :], axis=2,
                   dtype=jnp.int32)


# ---------------------------------------------------------------------------
# Split criteria.
#
# A criterion exposes two views of its impurity gain:
#
# * ``score(cum)`` — a cheap per-candidate statistic over the CUMULATIVE
#   histogram [A, C, bins, F] (channel axis 1) that is MONOTONE in the true
#   gain within a node, used only for the argmax. Because the node's own
#   impurity and total mass are constant across the node's (feature, bin)
#   candidates, the expensive normalization terms drop out — the full-gain
#   formula took ~10 elementwise passes over [A, B-1, F] tensors per level
#   (~half the CV sweep's device time); the score takes ~2.
# * ``gain(l, t)`` — the EXACT reference gain, evaluated only at the
#   winning candidate's [A, C] left/total stats (for the minInfoGain stop
#   rule, Spark/XGBoost parity).
#
# Channel-major layout note: with row-major (minor = last) layouts a
# channels-last [A, F, B, C] tensor puts C=3..5 in the TPU lane dimension,
# which the (8, 128) tiling pads to 128 lanes — a ~30-40× physical blowup.
# Channel-major keeps F (≥100) minor, so tensors stay dense. Leaf fns take
# channels-LAST [nodes, C] summaries (tiny, built by one matmul).
# ---------------------------------------------------------------------------

class VarianceCriterion:
    """Spark Variance impurity. Channels: (w, w·y, w·y², count).

    gain = imp(P) − wL/W·imp(L) − wR/W·imp(R) with imp = E[y²] − E[y]²
         = imp(P) − Σ(w·y²)/W + [sL²/wL + sR²/wR]/W,
    so argmax(gain) = argmax(sL²/wL + sR²/wR) within a node.
    """

    #: inlined form in the fused split-scan kernel (_pallas_hist)
    kernel_kind = "variance"

    def kernel_params(self):
        return 0.0, None            # (static lam, traced mcw)

    def score(self, cum):
        sL = cum[:, 1, :-1, :]
        wL = cum[:, 0, :-1, :]
        sT = cum[:, 1, -1:, :]
        wT = cum[:, 0, -1:, :]
        sR = sT - sL
        wR = wT - wL
        return sL * sL / jnp.maximum(wL, _EPS) \
            + sR * sR / jnp.maximum(wR, _EPS)

    def extra_ok(self, cum):
        return None

    def gain(self, l, t):
        def imp(w, s1, s2):
            w = jnp.maximum(w, _EPS)
            return s2 / w - (s1 / w) ** 2
        W = jnp.maximum(t[:, 0], _EPS)
        wL, wR = l[:, 0], t[:, 0] - l[:, 0]
        return imp(t[:, 0], t[:, 1], t[:, 2]) \
            - (wL / W) * imp(wL, l[:, 1], l[:, 2]) \
            - (wR / W) * imp(wR, t[:, 1] - l[:, 1], t[:, 2] - l[:, 2])


def variance_leaf(s):
    """Weighted mean target → [1]."""
    return (s[..., 1] / jnp.maximum(s[..., 0], _EPS))[..., None]


class GiniCriterion:
    """Spark Gini impurity. Channels: (per-class weight …, count).

    gain = imp(P) − wL/W·imp(L) − wR/W·imp(R) with imp = 1 − Σ p²
         = imp(P) − 1 + [Σc lc²/wL + Σc rc²/wR]/W,
    so argmax(gain) = argmax(Σ lc²/wL + Σ rc²/wR) within a node.
    """

    kernel_kind = "gini"

    def kernel_params(self):
        return 0.0, None

    def score(self, cum):
        cls_l = cum[:, :-1, :-1, :]                   # [A, K, B-1, F]
        cls_t = cum[:, :-1, -1:, :]
        cls_r = cls_t - cls_l
        wL = cls_l.sum(1)
        wR = cls_r.sum(1)
        return (cls_l * cls_l).sum(1) / jnp.maximum(wL, _EPS) \
            + (cls_r * cls_r).sum(1) / jnp.maximum(wR, _EPS)

    def extra_ok(self, cum):
        return None

    def gain(self, l, t):
        cls_l = l[:, :-1]
        cls_t = t[:, :-1]
        cls_r = cls_t - cls_l

        def imp(cls):
            w = jnp.maximum(cls.sum(1), _EPS)
            return 1.0 - (cls * cls).sum(1) / (w * w), w
        iT, W = imp(cls_t)
        iL, wL = imp(cls_l)
        iR, wR = imp(cls_r)
        W = jnp.maximum(W, _EPS)
        return iT - (wL / W) * iL - (wR / W) * iR


def gini_leaf(s):
    """Per-class probabilities → [C]."""
    cls = s[..., :-1]
    return cls / jnp.maximum(cls.sum(-1, keepdims=True), _EPS)


class XGBCriterion:
    """XGBoost gain: ½(G_L²/(H_L+λ) + G_R²/(H_R+λ) − G²/(H+λ)).
    Channels: (g, h, count). min_child_weight masks on hessian mass."""

    kernel_kind = "xgb"

    def __init__(self, lam, min_child_weight):
        self.lam = lam
        self.min_child_weight = min_child_weight

    def kernel_params(self):
        # lam is a static family constant; min_child_weight is a traced
        # grid hyperparameter — the kernel takes it as an operand
        return float(self.lam), self.min_child_weight

    def score(self, cum):
        gL = cum[:, 0, :-1, :]
        hL = cum[:, 1, :-1, :]
        gT = cum[:, 0, -1:, :]
        hT = cum[:, 1, -1:, :]
        gR = gT - gL
        hR = hT - hL
        return gL * gL / (hL + self.lam + _EPS) \
            + gR * gR / (hR + self.lam + _EPS)

    def extra_ok(self, cum):
        hL = cum[:, 1, :-1, :]
        hT = cum[:, 1, -1:, :]
        return (hL >= self.min_child_weight) & \
            (hT - hL >= self.min_child_weight)

    def gain(self, l, t):
        def s(g, h):
            return g * g / (h + self.lam + _EPS)
        return 0.5 * (s(l[:, 0], l[:, 1])
                      + s(t[:, 0] - l[:, 0], t[:, 1] - l[:, 1])
                      - s(t[:, 0], t[:, 1]))


def make_xgb_leaf(lam):
    def leaf(s):
        return (-s[..., 0] / (s[..., 1] + lam + _EPS))[..., None]
    return leaf


# ---------------------------------------------------------------------------
# Level-wise tree growing
# ---------------------------------------------------------------------------

def _level_cumhist(stats, node, Xb, n_nodes, n_bins,
                   feature_chunk: int = 512):
    """[n, C] sample stats → [n_nodes, C, n_bins, F] CUMULATIVE histograms.

    cum[s,c,t,f] = Σ_i 1[node_i=s]·1[Xb_if ≤ t]·stats_ic, computed as one
    MXU matmul per feature chunk: (one_hot(node) ⊗ stats)ᵀ @ tri(bins) —
    the bins operand is the lower-triangular "bin ≤ t" indicator, so the
    matmul emits left-cumulative sums directly and no separate cumsum pass
    over the [A, C, B, F] tensor is needed (that pass was ~8% of the CV
    sweep). A vmapped segment_sum would materialize a [F, n, S] one-hot
    scatter in HBM; chunking bounds the peak at n·chunk·B floats. Output is
    channel-major (see split-criteria note) so the feature axis stays in
    the TPU lane dimension, and the (t, f)-major column order means the
    matmul output reshapes straight to [A, C, B, Fc] with no transpose.
    """
    n, F = Xb.shape
    C = stats.shape[1]
    # f32 matmuls run at a fraction of MXU bf16 throughput; bf16 operands
    # with f32 accumulation keep COUNT channels exact (sums of exact 1.0s
    # in an f32 accumulator) and only add ~1e-3 relative rounding to the
    # weighted stat channels. The f64 (CPU test) path stays exact.
    mm_dtype = jnp.bfloat16 if stats.dtype == jnp.float32 else stats.dtype
    NS = (jax.nn.one_hot(node, n_nodes, dtype=stats.dtype)[:, :, None]
          * stats[:, None, :]).reshape(n, n_nodes * C).astype(mm_dtype)
    bins_iota = jnp.arange(n_bins, dtype=Xb.dtype)
    outs = []
    for f0 in range(0, F, feature_chunk):
        f1 = min(f0 + feature_chunk, F)
        Bc = (Xb[:, None, f0:f1] <= bins_iota[None, :, None]
              ).astype(mm_dtype).reshape(n, n_bins * (f1 - f0))
        h = jnp.matmul(NS.T, Bc,
                       preferred_element_type=stats.dtype)
        outs.append(h.reshape(n_nodes, C, n_bins, f1 - f0))
    if len(outs) == 1:
        return outs[0]
    return jnp.concatenate(outs, axis=3)               # [A, C, B, F]


def grow_tree(Xb: jnp.ndarray, edges: jnp.ndarray, stats: jnp.ndarray,
              crit, leaf_fn: Callable, max_depth: int,
              n_bins: int, min_instances, min_info_gain,
              depth_limit=None, feat_mask=None, max_active_nodes: int = 128,
              col_blocks=None, node_feat_key=None, node_feat_k=None,
              unroll: bool = False, XbT: Optional[jnp.ndarray] = None,
              prepared=None
              ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                         jnp.ndarray, jnp.ndarray]:
    """Grow one tree level-wise; returns (feat [2^D−1], thr [2^D−1],
    leaf [2^D, K], node [n] final sample→leaf assignment, gain [2^D−1]).

    ``min_instances`` / ``min_info_gain`` / ``depth_limit`` may be traced
    scalars — ``depth_limit`` stops splitting past that level while the
    static loop runs to ``max_depth`` (nodes that stop route all samples
    left through +inf thresholds, so routing to depth ``max_depth`` is
    exact). ``feat_mask`` [F] bool restricts candidate features (per-TREE
    column subsampling). ``node_feat_key``/``node_feat_k`` instead draw an
    exactly-k candidate mask PER (level, slot): Spark RF samples features
    per NODE (``featureSubsetStrategy``, used by
    ``OpRandomForestClassifier.scala:159`` via MLlib's RandomForest), and
    per-node draws decorrelate trees beyond a per-tree mask on correlated
    features. The [A, F] uniform-threshold draw folds the level index into
    the key, so the level loop body stays one compiled program per shape.

    ``col_blocks`` — static list of (column-index ndarray, bins, thr_fn)
    partitioning the features into histogram blocks with different bin
    counts. AutoML feature matrices are dominated by one-hot indicator
    columns (Titanic: 470 of 498); giving those a 2-bin block instead of
    the full 32 quantile bins cuts the histogram/score tensors ~8×. The
    candidate axis is the concatenation of every block's (bins−1)·F_b
    (feature, threshold) pairs; ``thr_fn(f_local, t) -> real threshold``
    recovers the stored split value per block. None → one full-width block.

    Active-node compaction: a dense level-wise build would need a
    [2^d, F, B, C] histogram per level — 1.5 GB per grid instance at depth
    12 — even though most of those nodes are empty. Instead each level keeps
    at most ``cap = min(max_active_nodes, 2^(max_depth-1))`` live nodes in a
    compact slot space (ranked by parent split gain). With min-instances ≥
    n/cap this is exact; beyond that the lowest-gain subtrees are truncated,
    which matches leaf-wise growers' behavior under a node budget.

    **Two drivers, one level body.**  Default: a ``lax.scan`` over levels
    with a CONSTANT slot count, traced and compiled once regardless of
    depth — right when rows are few and compile time dominates.  With
    ``unroll=True`` the level loop is a Python loop with a PER-LEVEL slot
    count ``A_d = min(2^d, cap)``: level d has at most 2^d nodes, so the
    histogram matmul (O(n·A·C·bins·F) on the MXU) stops paying the full
    cap=128 at every level — a ~3× FLOP cut for a depth-9 tree and the
    round-4 fix for the 0.001%-MFU profile.  Unrolling compiles one body
    per level; callers enable it at large row counts where compute
    dominates compile (the CV engine groups grid points by static depth
    first, see ``models/tuning.py``).

    Leaf values are scatter-built from the level histograms (a node's
    total stats are already in its cumulative histogram): the previous
    design's ``one_hot(g, 2^D)ᵀ @ stats`` matmul materialized an
    [n, 2^D] bf16 operand — 1.8 GB per tree at 2M rows, depth 9.

    ``XbT`` — optional TRANSPOSED [F, n] bin matrix (lane-compact, the
    layout the Pallas kernels stream; device_prep provides it pre-padded
    at scale). Either Xb or XbT must be given; the other orientation is
    derived only when the active path needs it.
    """
    from ._pallas_hist import (cumhist, route_level, split_scan,
                               split_scan_ok)
    if prepared is None:
        prepared = prepare_blocks(Xb, XbT, edges, n_bins, col_blocks,
                                  stats.dtype)
    if len(prepared) == 4:
        use_pallas, Xmat_full, blocks, fs_G = prepared
    else:           # pre-feature-shard 3-tuple (external callers)
        use_pallas, Xmat_full, blocks = prepared
        fs_G = 0
    if use_pallas:
        XbT_full = Xmat_full
        F, n = XbT_full.shape
    else:
        Xb_full = Xmat_full
        n, F = Xb_full.shape
    B = n_bins
    C = stats.shape[1]
    D = max_depth
    cap = max(2, min(max_active_nodes, 1 << max(D - 1, 1)))
    if unroll:
        cap -= cap % 2      # sibling interleave pairs child slots
    mmd = jnp.bfloat16 if stats.dtype == jnp.float32 else stats.dtype
    total_nodes = (1 << D) - 1
    n_leaves = 1 << D
    # mesh-sharded kernel dispatch (the tentpole): only the kernel path
    # needs the explicit shard_map — GSPMD already partitions the XLA
    # matmul path's contraction over a sharded batch axis, but a Pallas
    # custom call is opaque to it, so without this the 8-device mesh ran
    # every histogram replicated/single-device. Rows must split evenly
    # (device_prep pads to ROW_ALIGN × data under a tree-mesh scope).
    tmesh = active_tree_mesh() if use_pallas else None
    if tmesh is not None and n % int(tmesh.shape["data"]) != 0:
        tmesh = None
    # fused split-scan kernel: one VMEM pass per (level, block) replaces
    # the serialized XLA score/mask/argmax chain; any block outside the
    # kernel's envelope keeps the whole level on the XLA selection path
    # (the two paths must pick candidates over the SAME flat axis).
    # Feature-sharded blocks check the PER-SHARD width — fitting the
    # scan kernel's envelope at 1/G width is the point of sharding.
    use_scan = use_pallas and all(
        split_scan_ok(cap, nb, (blk.shape[1] if fs_G else len(cols)))
        for cols, nb, _tf, blk, _bc, _sp in blocks)
    if fs_G:
        # prepare_blocks engaged sharding under the same mesh scope and
        # row count, so the mesh gate above cannot have dropped it; the
        # scan envelope was pre-checked at n_nodes=1024.
        if tmesh is None or not use_scan:
            raise ValueError(
                "featureShards: prepared blocks are grid-stacked but the "
                "sharded level body cannot engage (cap "
                f"{cap} > 1024, or mesh/rows changed since prepare)")

    def block_hist(st, nd, xb, a, nb, bc, sp):
        if tmesh is not None:
            return _sharded_cumhist(tmesh, st, nd, xb, a, nb, bc=bc,
                                    sparse01=sp)
        return cumhist(st, nd, xb, a, nb, bc=bc, sparse01=sp)

    def level(d, A, A_next, slot, g, gpos, alive, feat, thr, gain, leafS,
              prev=None):
        """One level at A parent slots → A_next child slots. ``d`` may be
        traced (scan driver) or a Python int (unrolled driver).

        ``prev`` — optional (previous level's per-block histograms,
        child-pair → parent-slot map): with it, only the LEFT children
        (even slots, half of A) are histogrammed and each right sibling
        is the parent minus the left (LightGBM's subtraction trick —
        children partition their parent's rows). Counts stay exact
        (integer sums in an f32/f64 accumulator); weighted channels pick
        up accumulation-order rounding, and for strongly UNBALANCED
        splits the parent's bf16-operand rounding can dominate a small
        right child's weighted sums (ADVICE r4) — LightGBM refines this
        by histogramming the smaller child directly, which needs a
        data-dependent branch this static-shape jit deliberately avoids.
        ``TMOG_SIBLING=0`` disables subtraction where that noise matters
        more than the 2× histogram-FLOP saving. Used by the unrolled
        driver (the scan driver would pay the level-0 special case as a
        traced branch).
        """
        if node_feat_key is not None:
            # per-node candidate draw: exactly node_feat_k features per
            # slot, re-drawn every level (slot identity changes per level,
            # so (level, slot) ≡ node)
            ku = jax.random.fold_in(node_feat_key, d)
            u = _rng_replicated(
                lambda k: jax.random.uniform(k, (A, F)), ku)
            kth = jnp.sort(u, axis=1)[:, node_feat_k - 1][:, None]
            node_mask = u <= kth                       # [A, F]
        else:
            node_mask = None
        if prev is not None:
            half = A // 2
            # left children live in the EVEN slots by construction
            # (lchild = 2·inv); everything else → dead sentinel
            node_even = jnp.where((slot < A) & (slot % 2 == 0),
                                  slot // 2, half)
        if fs_G:
            # feature-axis-sharded level: every block's histogram + fused
            # split scan runs per grid shard over its own column slice
            # (_feature_sharded_split); the (blocks × shards) local
            # winners merge below by the SAME (score desc, global idx
            # asc) rule the per-block merge uses. Candidate indices live
            # in the G·Flg-padded t-major flat space — contiguous column
            # chunks keep real candidates in the unsharded relative
            # order, and pads carry the masked sentinel score.
            lam_s, mcw = crit.kernel_params()
            parts, cums = [], []
            tstats = None
            off_b = 0
            for bi, (cols, nb, _thr_fn, XblkS, bcS, sp) in \
                    enumerate(blocks):
                Flg = XblkS.shape[1]
                mask_afS = _fs_block_mask(cols, fs_G, Flg, A, feat_mask,
                                          node_mask, stats.dtype)
                kw = (dict(half=half, prevS=prev[0][bi], rank=prev[1])
                      if prev is not None else {})
                sc, ix, ok, lb, hist, tst = _feature_sharded_split(
                    tmesh, stats,
                    node_even if prev is not None else slot,
                    XblkS, A, nb, kind=crit.kernel_kind,
                    min_instances=min_instances, lam=lam_s, mcw=mcw,
                    mask_afS=mask_afS, bcS=bcS, sparse01=sp, **kw)
                # local t-major idx t·Flg + f → global padded-flat idx
                gi = (off_b + (ix // Flg) * (fs_G * Flg)
                      + jnp.arange(fs_G, dtype=jnp.int32)[:, None] * Flg
                      + ix % Flg)
                parts.extend((sc[s], gi[s], ok[s], lb[s])
                             for s in range(fs_G))
                cums.append(hist)
                if bi == 0:
                    tstats = tst
                off_b += (nb - 1) * (fs_G * Flg)
            bs, best, valid, lstats = parts[0][0], parts[0][1], \
                parts[0][2], parts[0][3]
            for s_k, gi_k, v_k, lb_k in parts[1:]:
                take = (s_k > bs) | ((s_k == bs) & (gi_k < best))
                best = jnp.where(take, gi_k, best)
                valid = jnp.where(take, v_k, valid)
                lstats = jnp.where(take[:, None], lb_k, lstats)
                bs = jnp.where(take, s_k, bs)
            f_idx = jnp.zeros((A,), jnp.int32)
            t_idx = jnp.zeros((A,), jnp.int32)
            thr_v = jnp.zeros((A,), edges.dtype)
            off = 0
            for cols, nb, thr_fn, XblkS, _bc, _sp in blocks:
                Fb_pad = fs_G * XblkS.shape[1]
                size = (nb - 1) * Fb_pad
                inb = (best >= off) & (best < off + size)
                local = jnp.clip(best - off, 0, max(size - 1, 0))
                # pad-candidate feature indices clamp to the last real
                # column — reachable only when NO candidate is valid,
                # where do_split kills every downstream use
                fb = jnp.minimum((local % Fb_pad).astype(jnp.int32),
                                 len(cols) - 1)
                tb = (local // Fb_pad).astype(jnp.int32)
                f_idx = jnp.where(inb, jnp.asarray(cols, jnp.int32)[fb],
                                  f_idx)
                t_idx = jnp.where(inb, tb, t_idx)
                thr_v = jnp.where(inb, thr_fn(jnp.asarray(cols)[fb], tb),
                                  thr_v)
                off += size
        else:
            # per-block cumulative histograms over slots; idle
            # (slot == A) → 0. Candidate axis = concat of every block's
            # (bins−1)·F_b pairs.
            flats, oks, cums, parts = [], [], [], []
            off_b = 0
            for bi, (cols, nb, _thr_fn, Xblk, bc, sp) in enumerate(blocks):
                if prev is not None:
                    if use_pallas:
                        ev = block_hist(stats, node_even, Xblk, half, nb,
                                        bc, sp)
                    else:
                        ev = _level_cumhist(stats, node_even, Xblk, half,
                                            nb)
                    parent = prev[0][bi][prev[1]]      # [half, C, nb, Fb]
                    cumb = jnp.stack([ev, parent - ev], axis=1).reshape(
                        (A,) + ev.shape[1:])           # interleave 2i/2i+1
                elif use_pallas:
                    # fused VMEM kernel over the transposed block [Fb, n]
                    cumb = block_hist(stats, slot, Xblk, A, nb, bc, sp)
                else:
                    cumb = _level_cumhist(stats, slot, Xblk, A, nb)
                # [A, C, nb, Fb]
                if use_scan:
                    # fused split scan: score+masks+argmax in one kernel
                    # pass; the feature/per-node masks combine into ONE
                    # [A, Fb] operand (tiny — the [A, B-1, Fb] expansion
                    # happens in VMEM, not HBM)
                    fb_n = len(cols)
                    mask_af = None
                    if feat_mask is not None:
                        mask_af = jnp.broadcast_to(
                            feat_mask[jnp.asarray(cols)][None, :],
                            (A, fb_n)).astype(stats.dtype)
                    if node_mask is not None:
                        nm = node_mask[:, jnp.asarray(cols)].astype(
                            stats.dtype)
                        mask_af = nm if mask_af is None else mask_af * nm
                    lam_s, mcw = crit.kernel_params()
                    sc_b, ix_b, ok_b = split_scan(
                        cumb, crit.kernel_kind, min_instances, lam=lam_s,
                        min_child_weight=mcw, mask=mask_af)
                    parts.append((off_b, sc_b, ix_b, ok_b))
                else:
                    sb = crit.score(cumb)             # [A, nb-1, Fb]
                    lcb = cumb[:, -1, :-1, :]
                    tcb = cumb[:, -1, -1:, :]
                    okb = (lcb >= min_instances) \
                        & (tcb - lcb >= min_instances)
                    extra = crit.extra_ok(cumb)
                    if extra is not None:
                        okb = okb & extra
                    if feat_mask is not None:
                        okb = okb \
                            & feat_mask[jnp.asarray(cols)][None, None, :]
                    if node_mask is not None:
                        okb = okb \
                            & node_mask[:, jnp.asarray(cols)][:, None, :]
                    flats.append(jnp.where(okb, sb, _NEG).reshape(A, -1))
                    oks.append(okb.reshape(A, -1))
                cums.append(cumb)
                off_b += (nb - 1) * len(cols)
            if use_scan:
                # merge per-block winners on the SAME flat candidate axis
                # the XLA concat+argmax walks: score desc, global flat
                # idx asc (argmax's first-occurrence tie rule)
                _o0, bs, bi0, bv = parts[0][0], parts[0][1], \
                    parts[0][2], parts[0][3]
                best = _o0 + bi0
                valid = bv
                for o_k, s_k, i_k, v_k in parts[1:]:
                    gi = o_k + i_k
                    take = (s_k > bs) | ((s_k == bs) & (gi < best))
                    best = jnp.where(take, gi, best)
                    valid = jnp.where(take, v_k, valid)
                    bs = jnp.where(take, s_k, bs)
            else:
                flat = jnp.concatenate(flats, axis=1) if len(flats) > 1 \
                    else flats[0]
                ok_flat = jnp.concatenate(oks, axis=1) if len(oks) > 1 \
                    else oks[0]
                best = jnp.argmax(flat, axis=1)
                valid = jnp.take_along_axis(ok_flat, best[:, None],
                                            axis=1)[:, 0]
            # decode the winning candidate per block; exact reference
            # gain is evaluated only at the winner ([A, C] stats)
            f_idx = jnp.zeros((A,), jnp.int32)
            t_idx = jnp.zeros((A,), jnp.int32)
            thr_v = jnp.zeros((A,), edges.dtype)
            lstats = jnp.zeros((A, C), stats.dtype)
            off = 0
            for (cols, nb, thr_fn, _Xblk, _bc, _sp), cumb in zip(blocks,
                                                                 cums):
                fb_n = len(cols)
                size = (nb - 1) * fb_n
                inb = (best >= off) & (best < off + size)
                local = jnp.clip(best - off, 0, max(size - 1, 0))
                fb = (local % fb_n).astype(jnp.int32)
                tb = (local // fb_n).astype(jnp.int32)
                f_idx = jnp.where(inb, jnp.asarray(cols, jnp.int32)[fb],
                                  f_idx)
                t_idx = jnp.where(inb, tb, t_idx)
                thr_v = jnp.where(inb, thr_fn(jnp.asarray(cols)[fb], tb),
                                  thr_v)
                lb = jnp.take_along_axis(
                    cumb[:, :, :-1, :].reshape(A, C, size),
                    local[:, None, None], axis=2)[:, :, 0]
                lstats = jnp.where(inb[:, None], lb, lstats)
                off += size
            tstats = cums[0][:, :, -1, 0]
        best_gain = crit.gain(lstats, tstats)
        do_split = alive & valid \
            & (best_gain >= jnp.maximum(min_info_gain, 1e-10))
        if depth_limit is not None:
            do_split = do_split & (d < depth_limit)
        f_idx = jnp.where(do_split, f_idx, 0)
        thr_rec = jnp.where(do_split, thr_v, jnp.inf)

        # next level: rank splitting slots by gain, allocate child slots
        rank = jnp.argsort(jnp.where(do_split, -best_gain, jnp.inf))
        inv = jnp.zeros((A,), jnp.int32).at[rank].set(
            jnp.arange(A, dtype=jnp.int32))
        parent_ok = do_split & (inv < A_next // 2)
        lchild = jnp.where(parent_ok, 2 * inv, A_next)
        rchild = jnp.where(parent_ok, 2 * inv + 1, A_next)

        if use_pallas:
            # single streamed VMEM pass (see _pallas_hist._route_kernel);
            # the XLA alternative below materializes ~3 [n, A] tensors.
            # Under a tree mesh each shard routes ITS rows (split tables
            # are replicated post-psum) — routing state never crosses
            # shards.
            if tmesh is not None:
                slot2, g2 = _sharded_route_level(
                    tmesh, XbT_full, slot, g, f_idx, t_idx, lchild,
                    rchild, do_split, A, A_next)
            else:
                slot2, g2 = route_level(XbT_full, slot, g, f_idx, t_idx,
                                        lchild, rchild, do_split, A,
                                        A_next)
        else:
            # gather-free sample routing: per-sample table lookups run on
            # the TPU scalar core; instead select each sample's split
            # feature with a one-hot matmul (MXU) and its slot-table
            # values with masked [n, A] reductions (VPU).
            oh = jax.nn.one_hot(slot, A, dtype=mmd)   # [n, A]; idle → 0-row
            sel = jax.nn.one_hot(f_idx, F, dtype=mmd)  # [A, F]
            xf = jnp.matmul(Xb_full.astype(mmd), sel.T,
                            preferred_element_type=stats.dtype)   # [n, A]
            Q = (xf > t_idx[None, :].astype(xf.dtype)) \
                & do_split[None, :]                   # [n, A]
            ohb = oh > 0
            go_right = jnp.any(ohb & Q, axis=1)
            g2 = 2 * g + go_right.astype(jnp.int32)
            child = jnp.where(Q, rchild[None, :], lchild[None, :])
            slot2 = jnp.where(slot == A, A_next,
                              jnp.sum(jnp.where(ohb, child, 0), axis=1,
                                      dtype=jnp.int32))
        gpos2 = (jnp.zeros((A_next,), jnp.int32)
                 .at[lchild].set(2 * gpos, mode="drop")
                 .at[rchild].set(2 * gpos + 1, mode="drop"))
        alive2 = (jnp.zeros((A_next,), bool)
                  .at[lchild].set(parent_ok, mode="drop")
                  .at[rchild].set(parent_ok, mode="drop"))

        # record splits: node (d, j) lives at flat index (2^d - 1) + j
        off_d = jnp.left_shift(jnp.int32(1), d) - 1
        idx = jnp.where(alive, off_d + gpos, total_nodes)
        feat = feat.at[idx].set(f_idx, mode="drop")
        thr = thr.at[idx].set(thr_rec, mode="drop")
        gain = gain.at[idx].set(
            jnp.where(do_split, best_gain, 0).astype(stats.dtype),
            mode="drop")
        # leaf stats: a node that stops splitting is a leaf covering the
        # g-range [gpos << (D-d), …); its rows' final g is exactly
        # gpos << (D-d) (g doubles with +0 once a row's slot is dead).
        # A split whose children leave the slot budget (truncation) or
        # that happens at the last level yields two leaf children.
        dying = alive & ~do_split
        leafS = leafS.at[
            jnp.where(dying, jnp.left_shift(gpos, D - d), n_leaves)
        ].set(tstats, mode="drop")
        is_last = (d == D - 1)
        emit_children = do_split & (~parent_ok | is_last)
        sh = D - d - 1
        li = jnp.where(emit_children,
                       jnp.left_shift(2 * gpos, sh), n_leaves)
        ri = jnp.where(emit_children,
                       jnp.left_shift(2 * gpos + 1, sh), n_leaves)
        leafS = (leafS.at[li].set(lstats, mode="drop")
                 .at[ri].set(tstats - lstats, mode="drop"))
        new_prev = (cums, rank[:A_next // 2])
        return (slot2, g2, gpos2, alive2, feat, thr, gain, leafS,
                new_prev)

    feat0 = jnp.zeros((total_nodes,), jnp.int32)
    thr0 = jnp.full((total_nodes,), jnp.inf, edges.dtype)
    gain0 = jnp.zeros((total_nodes,), stats.dtype)
    leafS0 = jnp.zeros((n_leaves, C), stats.dtype)
    slot0 = jnp.zeros((n,), jnp.int32)
    g0 = jnp.zeros((n,), jnp.int32)

    if unroll:
        # per-level slot growth; every level body is its own trace.
        # Levels past the first use sibling subtraction (see level()).
        import os as _os
        sibling = _os.environ.get("TMOG_SIBLING", "1") != "0"
        slot, g = slot0, g0
        gpos = jnp.zeros((1,), jnp.int32)
        alive = jnp.ones((1,), bool)
        feat, thr, gain, leafS = feat0, thr0, gain0, leafS0
        prev = None
        for d in range(D):
            A = min(1 << d, cap)
            A_next = min(1 << (d + 1), cap)
            (slot, g, gpos, alive, feat, thr, gain, leafS,
             new_prev) = level(d, A, A_next, slot, g, gpos, alive,
                               feat, thr, gain, leafS, prev=prev)
            prev = new_prev if sibling else None
    else:
        def body(carry, d):
            return level(d, cap, cap, *carry)[:8], None
        gpos0 = jnp.zeros((cap,), jnp.int32)
        alive0 = jnp.arange(cap) == 0
        (slot, g, gpos, alive, feat, thr, gain, leafS), _ = lax.scan(
            body, (slot0, g0, gpos0, alive0, feat0, thr0, gain0, leafS0),
            jnp.arange(D, dtype=jnp.int32))

    leaf = leaf_fn(leafS)
    return feat, thr, leaf, g, gain


def predict_tree(feat, thr, leaf, X, max_depth: int) -> jnp.ndarray:
    """Route [n, F] rows through one tree → [n, K] leaf values."""
    n = X.shape[0]

    def body(d, node):
        off = jnp.left_shift(jnp.int32(1), d) - 1
        f = feat[off + node]
        t = thr[off + node]
        x = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
        return 2 * node + (x > t).astype(jnp.int32)

    node = lax.fori_loop(0, max_depth, body, jnp.zeros((n,), jnp.int32))
    return leaf[node]


def predict_ensemble(feat, thr, leaf, tree_w, X, max_depth: int,
                     tree_chunk: int = 16) -> jnp.ndarray:
    """Weighted sum over [T, …] stacked trees → [n, K].

    Large row counts route through the Pallas predict kernel (the whole
    descent as VPU mask math — XLA's per-row gathers ran on the scalar
    core and dominated eval/scoring at 2M rows). Otherwise trees are
    routed in vmapped chunks (one batched fori_loop routes
    ``tree_chunk`` trees at once) under a scan that bounds the [chunk, n, K]
    intermediate — a per-tree scan would serialize T × max_depth tiny
    gather steps. The chunk also shrinks with n: the [c, n, K] leaf tensor
    tile-pads K→128 on TPU, so c is capped at ~1GB of padded transient."""
    T = feat.shape[0]
    n = X.shape[0]
    from ._pallas_hist import predict_kernel_ok, predict_trees
    if isinstance(n, int) and predict_kernel_ok(
            n, X.shape[1], max_depth, leaf.shape[-1], T=T):
        return predict_trees(X, feat, thr,
                             leaf * tree_w[:, None, None], max_depth)
    if isinstance(n, int):
        byte_cap = max(1, int(1e9 // (max(n, 1) * 128 * 4)))
    else:   # symbolic batch dim (jax.export serving artifact): no shrink
        byte_cap = tree_chunk
    c = max(1, min(tree_chunk, T, byte_cap))
    pad = (-T) % c
    if pad:
        feat = jnp.concatenate([feat, jnp.zeros((pad,) + feat.shape[1:],
                                                feat.dtype)])
        thr = jnp.concatenate([thr, jnp.full((pad,) + thr.shape[1:],
                                             jnp.inf, thr.dtype)])
        leaf = jnp.concatenate([leaf, jnp.zeros((pad,) + leaf.shape[1:],
                                                leaf.dtype)])
        tree_w = jnp.concatenate([tree_w, jnp.zeros((pad,), tree_w.dtype)])
    nc = (T + pad) // c

    def chunked(a):
        return a.reshape((nc, c) + a.shape[1:])

    def body(acc, tree):
        f, t, l, w = tree
        vals = jax.vmap(
            lambda fi, ti, li: predict_tree(fi, ti, li, X, max_depth)
        )(f, t, l)                                     # [c, n, K]
        return acc + jnp.einsum("t,tnk->nk", w, vals), None

    init = jnp.zeros((X.shape[0], leaf.shape[-1]), leaf.dtype)
    out, _ = lax.scan(body, init, (chunked(feat), chunked(thr),
                                   chunked(leaf), chunked(tree_w)))
    return out


# ---------------------------------------------------------------------------
# Random forest
# ---------------------------------------------------------------------------

def poisson_bootstrap_weights(key, rate, n: int, dtype,
                              k_max: int = 8) -> jnp.ndarray:
    """Poisson(rate) bootstrap draws via inverse-CDF over ONE uniform.

    ``jax.random.poisson``'s Knuth/rejection machinery runs a while loop
    whose threefry pair transients have a 2-minor layout that TPU tiling
    pads 64× — a 10 GB HLO temp at 2M rows under the CV fold×chunk vmap.
    Spark's subsamplingRate keeps rate ≤ 1, where truncating the inverse
    CDF at k_max=8 loses < 1e-8 of mass (the tail lands on k_max); every
    intermediate here is a lane-compact [n] vector. ``rate`` may be a
    traced scalar (grid hyperparameter)."""
    ks = jnp.arange(k_max + 1, dtype=jnp.float32)
    fact = jnp.asarray(
        np.cumprod(np.concatenate([[1.0], np.arange(1.0, k_max + 1)])),
        jnp.float32)
    r = jnp.maximum(jnp.asarray(rate, jnp.float32), 1e-9)
    cdf = jnp.cumsum(jnp.power(r, ks) * jnp.exp(-r) / fact)
    u = _rng_replicated(
        lambda k: jax.random.uniform(k, (n,), jnp.float32), key)
    w = jnp.zeros((n,), jnp.float32)
    for i in range(k_max):
        w = w + (u > cdf[i]).astype(jnp.float32)
    return w.astype(dtype)


def _feature_masks(key, n_trees: int, n_feat: int, k: int) -> jnp.ndarray:
    """[T, F] bool, exactly-k random features per tree (featureSubsetStrategy
    'auto' — per-tree rather than Spark's per-node, same spirit)."""
    if k >= n_feat:
        return jnp.ones((n_trees, n_feat), bool)
    u = _rng_replicated(
        lambda kk: jax.random.uniform(kk, (n_trees, n_feat)), key)
    kth = jnp.sort(u, axis=1)[:, k - 1][:, None]
    return u <= kth


def compute_bins(X, n_bins, binary_mask=None):
    """Jittable one-shot binning: [n, F] reals → (Xb int32, edges).

    Binary indicator columns are re-binned to {0, 1} so the routing
    compare ``bin > t_idx`` works with the block-local threshold index 0.
    The CV engine calls this ONCE per (data, family-binning-config) and
    passes the result to every fold × grid fit — round 3 recomputed the
    quantile sort + binarize inside every dispatched fit (~13% of the
    2M-row profile)."""
    edges = quantile_bin_edges(X, n_bins)
    Xb = binarize(X, edges)
    if binary_mask is not None and np.asarray(binary_mask).any():
        Xb = jnp.where(jnp.asarray(np.asarray(binary_mask, bool))[None, :],
                       (X > 0.5).astype(jnp.int32), Xb)
    return Xb, edges


def make_col_blocks(edges, n_bins, binary_mask=None):
    """Static col_blocks for :func:`grow_tree` from a host-side [F] bool
    indicator-column mask — or None when there is no binary column worth
    splitting off (data-dependent shapes are not jittable, so the caller
    detects indicator columns on the host)."""
    if binary_mask is None or not np.asarray(binary_mask).any():
        return None
    bmask = np.asarray(binary_mask, bool)
    bin_cols = np.nonzero(bmask)[0]
    cont_cols = np.nonzero(~bmask)[0]
    blocks = []
    if len(cont_cols):
        blocks.append((cont_cols, n_bins,
                       lambda fl, tl: edges[fl, tl]))
    blocks.append((bin_cols, 2,
                   lambda fl, tl: jnp.full(fl.shape, 0.5, edges.dtype)))
    return blocks


def prepare_bins(X, n_bins, binary_mask=None):
    """Quantile-bin X; binary indicator columns get a 2-bin block.
    Returns (Xb, edges, col_blocks) — see compute_bins/make_col_blocks."""
    Xb, edges = compute_bins(X, n_bins, binary_mask)
    return Xb, edges, make_col_blocks(edges, n_bins, binary_mask)


def _feature_shard_count(use_pallas: bool, n: int, col_blocks) -> int:
    """Effective feature-axis shard count G for this fit, or 0 (off).

    Engages ONLY when every condition of the sharded trace holds —
    kernel path on, ``featureShards`` requested (> 1), the active tree
    mesh's ``grid`` axis sized EXACTLY to the request, rows dividing the
    ``data`` axis (the same even-sharding check ``grow_tree`` applies),
    and every block's per-shard candidate width inside the fused
    split-scan envelope (the sharded level body runs the scan kernel
    per shard). Anything else fails open to the current path — the
    degenerate ``featureShards=1`` / ``grid=1`` resolve to the exact
    pre-shard program."""
    from ._pallas_hist import split_scan_enabled, split_scan_ok
    req = int(_FEATURE_SHARDS[0])
    if not use_pallas or req <= 1 or not split_scan_enabled():
        return 0
    tmesh = active_tree_mesh()
    if tmesh is None or int(tmesh.shape.get("grid", 1)) != req:
        return 0
    if n % int(tmesh.shape["data"]) != 0:
        return 0
    for cols, nb, _tf in col_blocks:
        if not split_scan_ok(1024, nb, -(-len(cols) // req)):
            return 0
    return req


def prepare_blocks(Xb, XbT, edges, n_bins, col_blocks, stats_dtype,
                   max_depth: Optional[int] = None):
    """(use_pallas, full matrix in the active orientation, blocks,
    feature-shard count G | 0) — each block is (cols, bins, thr_fn,
    block matrix, bc|None, sparse01). Under an engaged feature-shard
    scope (``_feature_shard_count``) the block matrix is instead the
    grid-stacked [G, Flg, n] sub-block tensor (columns zero-padded to
    G·Flg) and ``bc`` the per-shard [G, bins·Flg, n] indicator stack —
    the operands :func:`_feature_sharded_split` shards over the mesh.

    Called ONCE per fit, OUTSIDE the tree/round scans: the precomputed
    bin indicator ``bc`` ([B·Fb, n] — see _pallas_hist.make_bc) is a
    multi-GB fit-invariant and must not rely on XLA hoisting it out of a
    while body.

    2-bin indicator blocks on the kernel path take the sparsity-aware
    ``sparse01`` kernel instead (the wide-sparse path): their bin matrix
    IS the bin indicator, so no ``bc`` is materialized at all — at
    Titanic-like 470-of-498 indicator columns that is most of the
    would-be indicator bytes, and at a wide text-hash matrix nearly all
    of them. ``TMOG_SPARSE01=0`` reverts to the dense indicator."""
    from ._pallas_hist import (bc_cache_ok, make_bc,
                               pallas_histograms_enabled,
                               sparse01_enabled)
    use_pallas = pallas_histograms_enabled()
    if use_pallas and max_depth is not None and max_depth > 24:
        # route_level carries the per-sample leaf path g in f32 lanes —
        # exact only below 2^24. Spark allows maxDepth up to 30; deeper
        # grids take the int32 XLA path instead of mis-routing (ADVICE r4).
        use_pallas = False
    if use_pallas:
        Xmat = XbT if XbT is not None else Xb.T
        F, n = Xmat.shape
    else:
        Xmat = Xb if Xb is not None else XbT.T
        n, F = Xmat.shape
    if col_blocks is None:
        B = n_bins
        col_blocks = [(np.arange(F), B, lambda fl, tl: edges[fl, tl])]
    bc_dt = jnp.bfloat16 if stats_dtype == jnp.float32 else stats_dtype
    sp01 = use_pallas and sparse01_enabled()
    fs_G = _feature_shard_count(use_pallas, n, col_blocks)
    blocks = []
    for cols, nb, thr_fn in col_blocks:
        cols = np.asarray(cols)
        # make_col_blocks only emits nb == 2 for binary_mask columns,
        # whose bins are {0, 1} by construction (compute_bins re-bins
        # them to (x > 0.5)) — the sparse kernel's contract
        sparse = sp01 and nb == 2
        if fs_G:
            Flg = -(-len(cols) // fs_G)
            blk = Xmat[cols, :]
            pad = fs_G * Flg - len(cols)
            if pad:
                blk = jnp.concatenate(
                    [blk, jnp.zeros((pad, n), blk.dtype)], axis=0)
            blk = blk.reshape(fs_G, Flg, n)
            bc = (jnp.stack([make_bc(blk[s], nb, bc_dt)
                             for s in range(fs_G)])
                  if not sparse and bc_cache_ok(
                      n, Flg, nb, itemsize=jnp.dtype(bc_dt).itemsize)
                  else None)
        elif use_pallas:
            blk = Xmat[cols, :]
            bc = (make_bc(blk, nb, bc_dt)
                  if not sparse and bc_cache_ok(
                      n, len(cols), nb,
                      itemsize=jnp.dtype(bc_dt).itemsize)
                  else None)
        else:
            blk = Xmat[:, cols]
            bc = None
        blocks.append((cols, nb, thr_fn, blk, bc, sparse))
    return use_pallas, Xmat, blocks, fs_G


def _resolve_prebinned(X, y, w, n_bins, binary_mask, prebinned):
    """(Xb|None, XbT|None, edges, col_blocks, n, padded y, padded w).

    ``prebinned`` is (mat, edges, col_blocks, transposed) — transposed
    mats are the lane-compact [F, n] layout the Pallas kernels stream
    (device_prep may also have ROW_ALIGN-padded their rows; y/w are
    zero-padded here to follow)."""
    if prebinned is not None:
        mat, edges, col_blocks, is_T = prebinned
        Xb, XbT = (None, mat) if is_T else (mat, None)
        n = mat.shape[1] if is_T else mat.shape[0]
    else:
        Xb, edges, col_blocks = prepare_bins(X, n_bins, binary_mask)
        XbT, n = None, Xb.shape[0]
    if n != y.shape[0]:
        pad = n - y.shape[0]
        y = jnp.concatenate([y, jnp.zeros((pad,), y.dtype)])
        w = jnp.concatenate([w, jnp.zeros((pad,), w.dtype)])
    return Xb, XbT, edges, col_blocks, n, y, w


def fit_forest(X, y, w, *, task: str, n_classes: int, n_trees: int,
               max_depth: int, n_bins: int, min_instances, min_info_gain,
               num_trees_used, subsample_rate, depth_limit=None,
               max_active_nodes: int = 128, tree_chunk: int = 1,
               binary_mask=None, seed: int = 7,
               per_node_features: bool = True,
               prebinned=None, unroll: bool = False):
    """Random forest via scanned bootstrap trees.

    Traced: min_instances, min_info_gain, num_trees_used (≤ n_trees,
    masks extra trees), subsample_rate, depth_limit. Returns params dict.

    Bootstrap trees are independent, so they are grown ``tree_chunk`` at a
    time (vmap inside the scan): fewer, larger device steps — per-step
    histogram work is batched onto the MXU instead of serializing
    T × depth small steps. ``tree_chunk`` bounds the transient
    [chunk, A, F, B, C] histogram memory.

    ``prebinned`` — optional (Xb, edges, col_blocks) computed once by the
    caller (see compute_bins); skips in-fit binning so the CV engine bins
    the data exactly once per sweep. ``unroll`` — per-level slot growth
    (see grow_tree); pair with a static ``max_depth`` at large n.

    Bootstrap Poisson weights are drawn per tree inside the tree scan
    (key folded on the tree index — chunk-size invariant): the previous
    up-front [n_trees, n] draw materialized 360 MB per grid instance at
    2M rows."""
    key = jax.random.PRNGKey(seed)
    k_boot, k_feat = jax.random.split(key)
    Xb, XbT, edges, col_blocks, n, y, w = _resolve_prebinned(
        X, y, w, n_bins, binary_mask, prebinned)
    F = Xb.shape[1] if Xb is not None else XbT.shape[0]
    dt = w.dtype
    prepared = prepare_blocks(Xb, XbT, edges, n_bins, col_blocks, dt,
                              max_depth=max_depth)
    rate = jnp.broadcast_to(jnp.asarray(subsample_rate, jnp.float32), ())
    per_node = False
    feat_k = F
    if n_trees == 1:
        fmask = jnp.ones((1, F), bool)
    else:
        k = max(1, int(round(np.sqrt(F))) if task == "classification"
                else max(1, F // 3))
        per_node = per_node_features and k < F
        if per_node:
            # Spark-parity per-NODE candidate sampling: masks are drawn
            # inside grow_tree's level scan from a per-tree key
            feat_k = k
            fmask = jnp.ones((n_trees, F), bool)
        else:
            fmask = _feature_masks(k_feat, n_trees, F, k)
    fkeys = jax.random.split(k_feat, n_trees)

    if task == "classification":
        onehot = jax.nn.one_hot(y.astype(jnp.int32), n_classes, dtype=dt)
        def make_stats(wt):
            return jnp.concatenate(
                [onehot * wt[:, None], (wt > 0).astype(dt)[:, None]], 1)
        crit, leaf_fn = GiniCriterion(), gini_leaf
    else:
        def make_stats(wt):
            return jnp.stack(
                [wt, wt * y, wt * y * y, (wt > 0).astype(dt)], axis=1)
        crit, leaf_fn = VarianceCriterion(), variance_leaf

    def fit_one(tid, fm, fk):
        if n_trees == 1:
            bw = jnp.ones((n,), dt)             # single DT: no bootstrap
        else:
            bw = poisson_bootstrap_weights(
                jax.random.fold_in(k_boot, tid), rate, n, dt)
        wt = w * bw
        feat, thr, leaf, node, gain = grow_tree(
            Xb, edges, make_stats(wt), crit, leaf_fn, max_depth,
            n_bins, min_instances, min_info_gain, depth_limit=depth_limit,
            feat_mask=None if per_node else fm,
            max_active_nodes=max_active_nodes,
            col_blocks=col_blocks,
            node_feat_key=fk if per_node else None,
            node_feat_k=feat_k, unroll=unroll, prepared=prepared)
        return feat, thr, leaf, node, gain

    c = max(1, min(tree_chunk, n_trees))
    pad = (-n_trees) % c
    tids = jnp.arange(n_trees + pad, dtype=jnp.int32)
    if pad:
        fmask = jnp.concatenate([fmask, jnp.ones((pad, F), bool)])
        fkeys = jnp.concatenate([fkeys, jnp.zeros((pad,) + fkeys.shape[1:],
                                                  fkeys.dtype)])
    nc = (n_trees + pad) // c

    def body(_, per_chunk):
        tid, fm, fk = per_chunk                 # [c], [c, F], [c, key]
        return None, jax.vmap(fit_one)(tid, fm, fk)
    _, (feat, thr, leaf, node, gain) = lax.scan(
        body, None, (tids.reshape(nc, c), fmask.reshape(nc, c, F),
                     fkeys.reshape((nc, c) + fkeys.shape[1:])))
    feat = feat.reshape((nc * c,) + feat.shape[2:])[:n_trees]
    thr = thr.reshape((nc * c,) + thr.shape[2:])[:n_trees]
    leaf = leaf.reshape((nc * c,) + leaf.shape[2:])[:n_trees]
    node = node.reshape((nc * c,) + node.shape[2:])[:n_trees]
    gain = gain.reshape((nc * c,) + gain.shape[2:])[:n_trees]
    tree_w = (jnp.arange(n_trees) < num_trees_used).astype(dt)
    tree_w = tree_w / jnp.maximum(tree_w.sum(), 1.0)
    # train_node caches the fit-time sample→leaf routing: predicting the
    # TRAINING matrix (the CV sweep's case) is then leaf gathers only — no
    # per-level tree routing (which runs on the slow scalar core).
    return {"feat": feat, "thr": thr, "leaf": leaf, "tree_w": tree_w,
            "train_node": node, "gain": gain * tree_w[:, None]}


# ---------------------------------------------------------------------------
# Gradient boosting (Spark GBT: first-order, variance splits on residuals)
# ---------------------------------------------------------------------------

def fit_gbt(X, y, w, *, task: str, n_rounds: int, max_depth: int,
            n_bins: int, min_instances, min_info_gain, step_size,
            num_rounds_used, depth_limit=None, max_active_nodes: int = 128,
            binary_mask=None, prebinned=None, unroll: bool = False):
    """Spark-style GBT: each round fits a weighted regression tree to the
    pseudo-residuals; classification uses logloss on y' ∈ {−1,+1} with
    margin F, prob = σ(2F) (GBTClassificationModel semantics)."""
    Xb, XbT, edges, col_blocks, n, y, w = _resolve_prebinned(
        X, y, w, n_bins, binary_mask, prebinned)
    dt = w.dtype
    prepared = prepare_blocks(Xb, XbT, edges, n_bins, col_blocks, dt,
                              max_depth=max_depth)
    ypm = 2.0 * y - 1.0

    def residual(Fm):
        if task == "classification":
            return 2.0 * ypm / (1.0 + jnp.exp(2.0 * ypm * Fm))
        return y - Fm

    def body(Fm, t):
        r = residual(Fm)
        stats = jnp.stack([w, w * r, w * r * r,
                           (w > 0).astype(dt)], axis=1)
        feat, thr, leaf, node, gain = grow_tree(
            Xb, edges, stats, VarianceCriterion(), variance_leaf, max_depth,
            n_bins, min_instances, min_info_gain, depth_limit=depth_limit,
            max_active_nodes=max_active_nodes, col_blocks=col_blocks,
            unroll=unroll, prepared=prepared)
        use = (t < num_rounds_used).astype(dt)
        scale = use * step_size
        Fm = Fm + scale * leaf[node][:, 0]
        return Fm, (feat, thr, leaf * scale, gain * use)
    F0 = jnp.zeros((n,), dt)
    Fm, (feat, thr, leaf, gain) = lax.scan(body, F0, jnp.arange(n_rounds))
    # train_margin caches the final boosted margin on the training matrix
    # (see fit_forest.train_node) — CV predict needs no routing at all.
    return {"feat": feat, "thr": thr, "leaf": leaf,
            "tree_w": jnp.ones((n_rounds,), dt), "train_margin": Fm,
            "gain": gain}


# ---------------------------------------------------------------------------
# XGBoost-equivalent (second-order, L2 leaf regularization)
# ---------------------------------------------------------------------------

def fit_xgb(X, y, w, *, task: str, n_rounds: int, max_depth: int,
            n_bins: int, eta, lam, min_child_weight, num_rounds_used,
            depth_limit=None, max_active_nodes: int = 128,
            binary_mask=None, prebinned=None, unroll: bool = False):
    """Second-order boosting: g/h from logistic (classification) or squared
    (regression) loss; leaf = −G/(H+λ) (xgboost4j replacement — Rabit's
    histogram allreduce becomes psum under a sharded batch axis)."""
    Xb, XbT, edges, col_blocks, n, y, w = _resolve_prebinned(
        X, y, w, n_bins, binary_mask, prebinned)
    dt = w.dtype
    prepared = prepare_blocks(Xb, XbT, edges, n_bins, col_blocks, dt,
                              max_depth=max_depth)
    crit = XGBCriterion(lam, min_child_weight)
    leaf_fn = make_xgb_leaf(lam)

    def grads(Fm):
        if task == "classification":
            p = jax.nn.sigmoid(Fm)
            return w * (p - y), w * jnp.maximum(p * (1.0 - p), 1e-6)
        return w * (Fm - y), w

    def body(Fm, t):
        g, h = grads(Fm)
        stats = jnp.stack([g, h, (w > 0).astype(dt)], axis=1)
        feat, thr, leaf, node, gain = grow_tree(
            Xb, edges, stats, crit, leaf_fn, max_depth, n_bins,
            jnp.asarray(0.0, dt), jnp.asarray(-1e29, dt),
            depth_limit=depth_limit, max_active_nodes=max_active_nodes,
            col_blocks=col_blocks, unroll=unroll, prepared=prepared)
        use = (t < num_rounds_used).astype(dt)
        scale = use * eta
        Fm = Fm + scale * leaf[node][:, 0]
        return Fm, (feat, thr, leaf * scale, gain * use)
    F0 = jnp.zeros((n,), dt)
    Fm, (feat, thr, leaf, gain) = lax.scan(body, F0, jnp.arange(n_rounds))
    return {"feat": feat, "thr": thr, "leaf": leaf,
            "tree_w": jnp.ones((n_rounds,), dt), "train_margin": Fm,
            "gain": gain}


# ---------------------------------------------------------------------------
# Ensemble → Prediction triple (pred, raw, prob)
# ---------------------------------------------------------------------------

def rf_head(out, dtype, task: str):
    """[n, K] weighted leaf aggregate → Prediction triple (shared by the
    routed predict path and the CV train-cache path). ``dtype`` is the
    prediction dtype (raw X is absent on the prebinned CV path)."""
    if task == "classification":
        probs = out / jnp.maximum(out.sum(-1, keepdims=True), _EPS)
        pred = jnp.argmax(probs, axis=-1).astype(dtype)
        return pred, probs, probs
    empty = jnp.zeros((out.shape[0], 0), dtype)
    return out[:, 0], empty, empty


def margin_head(m, margin_scale, dtype, task: str):
    """[n] boosted margin → Prediction triple. GBT uses prob = σ(2F),
    XGB σ(F) (shared by routed and train-cache paths)."""
    if task == "classification":
        p1 = jax.nn.sigmoid(margin_scale * m)
        prob = jnp.stack([1.0 - p1, p1], axis=1)
        raw = jnp.stack([-m, m], axis=1)
        pred = (p1 > 0.5).astype(dtype)
        return pred, raw, prob
    empty = jnp.zeros((m.shape[0], 0), dtype)
    return m, empty, empty


@functools.partial(jax.jit, static_argnames=("max_depth", "n_classes"))
def predict_rf_classification(params, X, max_depth: int, n_classes: int):
    probs = predict_ensemble(params["feat"], params["thr"], params["leaf"],
                             params["tree_w"], X, max_depth)
    return rf_head(probs, X.dtype, "classification")


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_rf_regression(params, X, max_depth: int):
    out = predict_ensemble(params["feat"], params["thr"], params["leaf"],
                           params["tree_w"], X, max_depth)
    return rf_head(out, X.dtype, "regression")


@functools.partial(jax.jit, static_argnames=("max_depth", "margin_scale"))
def predict_margin_classification(params, X, max_depth: int,
                                  margin_scale: float = 1.0):
    """GBT (margin_scale=2: prob = σ(2F)) and XGB (=1) binary heads."""
    m = predict_ensemble(params["feat"], params["thr"], params["leaf"],
                         params["tree_w"], X, max_depth)[:, 0]
    return margin_head(m, margin_scale, X.dtype, "classification")


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_margin_regression(params, X, max_depth: int):
    m = predict_ensemble(params["feat"], params["thr"], params["leaf"],
                         params["tree_w"], X, max_depth)[:, 0]
    return margin_head(m, 1.0, X.dtype, "regression")
