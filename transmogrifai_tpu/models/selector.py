"""ModelSelector — AutoML model selection over a batched CV grid.

Parity: ``core/.../impl/selector/ModelSelector.scala:135-196`` and the
factories ``BinaryClassificationModelSelector`` /
``MultiClassificationModelSelector`` / ``RegressionModelSelector``
(``core/.../impl/classification/BinaryClassificationModelSelector.scala:47-245``).

``fit``: splitter prepares → validator sweeps every (family × grid × fold)
as one batched JAX computation per family → best estimator refit on the full
prepared train → train (and holdout, via ``has_test_eval``) evaluation →
``SelectedModel`` + ``ModelSelectorSummary``.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columns import ColumnStore, PredictionColumn
from ..evaluators import metrics as M
from ..stages.base import register_stage
from .base import (ModelFamily, PredictorEstimator, PredictorModel,
                   extract_xy)
from .linear import (LinearRegressionFamily, LogisticRegressionFamily,
                     NaiveBayesFamily)
from .tuning import (CrossValidation, DataBalancer, DataCutter, DataSplitter,
                     Splitter, TrainValidationSplit, ValidatorSummary)

__all__ = ["ModelSelector", "SelectedModel", "ModelSelectorSummary",
           "BinaryClassificationModelSelector",
           "MultiClassificationModelSelector", "RegressionModelSelector"]


class ModelSelectorSummary:
    """Validation results + data prep + evals (ModelSelectorSummary.scala)."""

    def __init__(self, validator_summary: ValidatorSummary,
                 splitter_summary: Dict[str, Any],
                 train_evaluation: Dict[str, float],
                 holdout_evaluation: Optional[Dict[str, float]] = None,
                 best_model_name: str = "", best_model_params: Dict = None):
        self.validator_summary = validator_summary
        self.splitter_summary = splitter_summary
        self.train_evaluation = train_evaluation
        self.holdout_evaluation = holdout_evaluation
        self.best_model_name = best_model_name
        self.best_model_params = best_model_params or {}

    def to_json(self) -> Dict[str, Any]:
        return {
            "bestModelName": self.best_model_name,
            "bestModelParams": self.best_model_params,
            "validationResults": self.validator_summary.to_json(),
            "dataPrepSummary": self.splitter_summary,
            "trainEvaluation": self.train_evaluation,
            "holdoutEvaluation": self.holdout_evaluation,
        }

    def pretty(self) -> str:
        import json
        return json.dumps(self.to_json(), indent=2, default=str)


@register_stage
class SelectedModel(PredictorModel):
    """The winning fitted model wrapped with selection metadata
    (ModelSelector.scala:216-255)."""

    operation_name = "modelSelector"

    def __init__(self, inner: Optional[PredictorModel] = None,
                 task: str = "binary",
                 label_mapping: Optional[Sequence[float]] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.inner = inner
        self.task = task
        #: model class id → original label value, when a DataCutter dropped
        #: rare labels and re-indexed the rest (DataCutter.scala metadata
        #: fix-up analog)
        self.label_mapping = list(label_mapping) if label_mapping else None
        self.selector_summary: Optional[ModelSelectorSummary] = None

    def predict_device(self, Xd):
        """Device-side Prediction triple incl. label de-mapping (pure jax;
        export/serving path — label values round through the device dtype,
        f32 when x64 is off)."""
        pred, raw, prob = self.inner.predict_device(Xd)
        if self.label_mapping is not None:
            lm = jnp.asarray(self.label_mapping)
            pred = lm[jnp.clip(pred.astype(jnp.int32), 0, len(lm) - 1)]
        return pred, raw, prob

    def predict_arrays(self, X):
        # host path: de-map in exact float64 (arbitrary original label
        # values survive), and tolerate inner models that only implement
        # predict_arrays
        pred, raw, prob = self.inner.predict_arrays(X)
        if self.label_mapping is not None:
            lm = np.asarray(self.label_mapping, dtype=np.float64)
            pred = lm[np.clip(np.asarray(pred).astype(np.int64), 0,
                              len(lm) - 1)]
        return pred, raw, prob

    def has_test_eval(self) -> bool:
        return True

    def evaluate_model(self, test: ColumnStore) -> None:
        """Holdout evaluation during workflow fit (HasTestEval)."""
        X, y = extract_xy(test, self.input_features[0].name,
                          self.input_features[1].name)
        pred, _raw, prob = self.predict_arrays(X)
        metrics = _task_metrics(self.task, y, pred, prob)
        if self.selector_summary is not None:
            self.selector_summary.holdout_evaluation = metrics

    def get_params(self):
        p = super().get_params()
        p.pop("inner", None)  # reconstructed from model state
        return p

    def get_model_state(self):
        inner_state = self.inner.get_model_state()
        inner_params = self.inner.get_params()
        inner_params.pop("uid", None)
        return {
            "inner_class": type(self.inner).__name__,
            "inner_params": inner_params,
            "inner_state": inner_state,
        }

    def apply_model_state(self, state) -> None:
        from ..stages.base import STAGE_REGISTRY
        name = state["inner_class"]
        if name not in STAGE_REGISTRY:
            raise KeyError(
                f"Model class {name!r} is not registered — import its "
                "module before loading the workflow model")
        cls = STAGE_REGISTRY[name]
        self.inner = cls(**state["inner_params"])
        inner_state = state["inner_state"]
        if hasattr(self.inner, "apply_model_state"):
            self.inner.apply_model_state(inner_state)
        else:
            for k, v in inner_state.items():
                setattr(self.inner, k, v)

    def summary(self):
        out = {"model": "SelectedModel", "task": self.task}
        if self.selector_summary is not None:
            out.update(self.selector_summary.to_json())
        return out


def _task_metrics(task: str, y, pred, prob) -> Dict[str, float]:
    if task == "binary":
        scores = prob[:, 1] if prob.ndim == 2 and prob.shape[1] >= 2 else pred
        return M.binary_metrics(y, pred, scores)
    if task == "multiclass":
        out = M.multiclass_metrics(y, pred)
        if prob is not None and np.ndim(prob) == 2 and prob.shape[1] >= 2:
            # topN × confidence-band counts ride in the selector summary
            # like the reference's MultiClassificationMetrics
            # (OpMultiClassificationEvaluator.scala:120-132)
            out["ThresholdMetrics"] = M.multiclass_threshold_metrics(y, prob)
        return out
    return M.regression_metrics(y, pred)


@register_stage
class ModelSelector(PredictorEstimator):
    """Estimator(label, features) → Prediction via validated model selection."""

    operation_name = "modelSelector"

    def __init__(self, validator: Optional[Any] = None,
                 splitter: Optional[Splitter] = None,
                 families: Optional[Sequence[ModelFamily]] = None,
                 task: str = "binary",
                 mesh=None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.validator = validator
        self.splitter = splitter
        self.families = list(families or [])
        self.task = task
        self.mesh = mesh
        self.best_estimator_: Optional[Tuple[ModelFamily, Dict]] = None
        #: set alongside best_estimator_ by the workflow-CV path so
        #: fit_columns can skip re-validation (ModelSelector.scala:135-156
        #: bestEstimator.getOrElse)
        self.precomputed_summary_: Optional[ValidatorSummary] = None

    # workflow-level CV hook (ModelSelector.findBestEstimator :112-121)
    def find_best_estimator(self, store: ColumnStore
                            ) -> Tuple[ModelFamily, Dict, ValidatorSummary]:
        X, y = extract_xy(store, self.label_name, self.features_name)
        if self.splitter is not None:
            # estimate BEFORE dropping (DataBalancer.estimate sees full
            # counts), then drop rare labels and re-index contiguously.
            # keep-all skips the boolean-index copy so the matrix keeps
            # its identity for the device-upload cache (device_put_f32)
            self.splitter.pre_validation_prepare(y)
            keep = self.splitter.keep_mask(y)
            if not keep.all():
                X, y = X[keep], y[keep]
            y = self.splitter.relabel(y)
            base_w = self.splitter.sample_weights(y)
            # physical sampling (Spark's rebalance/maxTrainingSample): the
            # sweep trains on the rows Spark would, not 10× them (see
            # Splitter.physical_sample)
            sub, base_w = self.splitter.physical_sample(y, base_w)
            if sub is not None:
                X, y = X[sub], y[sub]
        else:
            base_w = None
        self._maybe_set_classes(y)
        from .trees import detect_binary_columns
        bmask = detect_binary_columns(X)
        for fam in self.families:
            if hasattr(fam, "binary_mask"):
                fam.binary_mask = bmask
        best_family, best_hparams, vsummary = self.validator.validate(
            self.families, X, y, base_weights=base_w, mesh=self.mesh)
        self.best_estimator_ = (best_family, best_hparams)
        return best_family, best_hparams, vsummary

    def _maybe_set_classes(self, y: np.ndarray) -> None:
        n_classes = max(int(y.max()) + 1 if len(y) else 2, 2)
        for fam in self.families:
            if hasattr(fam, "n_classes"):
                fam.n_classes = n_classes

    def fit_columns(self, store: ColumnStore) -> SelectedModel:
        X, y = extract_xy(store, self.label_name, self.features_name)
        if self.best_estimator_ is not None \
                and self.precomputed_summary_ is not None:
            # workflow-level CV already found the winner with in-fold
            # feature engineering — skip selector-level validation but
            # replay the prepare side effects (splitter state, class count,
            # binary-column mask) that find_best_estimator would have set
            best_family, best_hparams = self.best_estimator_
            vsummary = self.precomputed_summary_
            if self.splitter is not None:
                self.splitter.pre_validation_prepare(y)
                keep = self.splitter.keep_mask(y)
                self._maybe_set_classes(self.splitter.relabel(y[keep]))
            else:
                self._maybe_set_classes(y)
            from .trees import detect_binary_columns
            bmask = detect_binary_columns(X)
            for fam in self.families:
                if hasattr(fam, "binary_mask"):
                    fam.binary_mask = bmask
        else:
            best_family, best_hparams, vsummary = \
                self.find_best_estimator(store)

        # final refit on the full prepared train (ModelSelector.scala:158-159
        # — "prepared" = after the splitter's sampling, same as the sweep)
        if self.splitter is not None:
            keep = self.splitter.keep_mask(y)
            Xk = X if keep.all() else X[keep]
            yk = self.splitter.relabel(y if keep.all() else y[keep])
            w = self.splitter.sample_weights(yk)
            sub, w = self.splitter.physical_sample(yk, w)
            if sub is not None:
                Xk, yk = Xk[sub], yk[sub]
        else:
            Xk, yk = X, y
            w = np.ones_like(yk)
        import logging as _logging
        import time as _time
        _log = _logging.getLogger(__name__)
        tr0 = _time.perf_counter()
        single = best_family.clone_single(best_hparams)
        from .base import device_put_f32
        Xd = device_put_f32(Xk)
        if hasattr(single, "fit_prepared"):
            # tree refit: bin once, static-depth unrolled fit at large n,
            # train predictions straight from the fit-time caches. Same
            # Mosaic fallback as the sweep — the refit compiles a fresh
            # width-1 program the sweep's shapes never exercised, and a
            # kernel rejection here must not kill the run after the
            # sweep succeeded. The refit shards over the SAME mesh as
            # the sweep (tree_mesh_scope → shard_map partial histograms
            # + psum) — the final fit is the biggest single tree fit of
            # the run and must not fall back to one device.
            from ._pallas_hist import with_pallas_fallback
            from ._treefit import tree_mesh_scope

            def _refit():
                params, Xarg = single.fit_prepared(
                    Xd, jnp.asarray(yk), jnp.asarray(w))
                return (params, single.predict_batch(params, Xarg,
                                                     on_train=True))
            with tree_mesh_scope(self.mesh):
                params, (pred_d, _raw_d, prob_d) = \
                    with_pallas_fallback(_refit)
        else:
            grid = single.stack_grid()
            params = jax.jit(lambda X, y, w: single.fit_batch(
                X, y, w, grid))(Xd, jnp.asarray(yk), jnp.asarray(w))
            pred_d, _raw_d, prob_d = single.predict_batch(params, Xd)
        # ONE batched pull for fitted params + train predictions (per-array
        # pulls each pay the device link's round-trip latency)
        params, pred, prob = jax.device_get((params, pred_d, prob_d))
        _log.info("final refit (fit+train-predict+pull): %.2fs",
                  _time.perf_counter() - tr0)
        inner = single.realize(_index_pytree(params, 0), best_hparams)

        # train evaluation over the rows the model was actually trained on
        # (DataCutter-dropped labels are out of scope for the model);
        # prebinned tree predictions may carry ROW_ALIGN padding — slice
        pred0 = np.asarray(pred)[0][:len(yk)]
        prob0 = np.asarray(prob)[0]
        if prob0.ndim == 2 and prob0.shape[0] > len(yk):
            prob0 = prob0[:len(yk)]
        train_eval = _task_metrics(self.task, yk, pred0, prob0)

        mapping = (self.splitter.original_labels() if self.splitter
                   else None)
        model = SelectedModel(inner=inner, task=self.task,
                              label_mapping=mapping)
        model.selector_summary = ModelSelectorSummary(
            validator_summary=vsummary,
            splitter_summary=self.splitter.summary if self.splitter else {},
            train_evaluation=train_eval,
            best_model_name=best_family.name,
            best_model_params=best_hparams)
        return model


def _index_pytree(tree, i: int):
    return jax.tree_util.tree_map(lambda a: np.asarray(a)[i], tree)


# ---------------------------------------------------------------------------
# Factories (BinaryClassificationModelSelector.scala etc.)
# ---------------------------------------------------------------------------

class _SelectorFactory:
    task = "binary"
    default_metric = "AuPR"

    @classmethod
    def default_families(cls) -> List[ModelFamily]:
        raise NotImplementedError

    @classmethod
    def default_splitter(cls) -> Optional[Splitter]:
        return None

    @classmethod
    def with_cross_validation(cls, num_folds: int = 3,
                              validation_metric: Optional[str] = None,
                              families: Optional[Sequence[ModelFamily]] = None,
                              splitter: Optional[Splitter] = None,
                              seed: int = 42, stratify: bool = False,
                              mesh=None) -> ModelSelector:
        metric = validation_metric or cls.default_metric
        return ModelSelector(
            validator=CrossValidation(num_folds=num_folds, metric_name=metric,
                                      task=cls.task, seed=seed,
                                      stratify=stratify),
            splitter=splitter if splitter is not None else cls.default_splitter(),
            families=families if families is not None else cls.default_families(),
            task=cls.task, mesh=mesh)

    @classmethod
    def with_train_validation_split(cls, train_ratio: float = 0.75,
                                    validation_metric: Optional[str] = None,
                                    families: Optional[Sequence[ModelFamily]] = None,
                                    splitter: Optional[Splitter] = None,
                                    seed: int = 42,
                                    mesh=None) -> ModelSelector:
        metric = validation_metric or cls.default_metric
        return ModelSelector(
            validator=TrainValidationSplit(train_ratio=train_ratio,
                                           metric_name=metric, task=cls.task,
                                           seed=seed),
            splitter=splitter if splitter is not None else cls.default_splitter(),
            families=families if families is not None else cls.default_families(),
            task=cls.task, mesh=mesh)


class BinaryClassificationModelSelector(_SelectorFactory):
    """Defaults: LR + RF + GBT + LinearSVC on (:52-128); metric auPR."""

    task = "binary"
    default_metric = "AuPR"

    @classmethod
    def default_families(cls) -> List[ModelFamily]:
        fams: List[ModelFamily] = [LogisticRegressionFamily()]
        try:
            from .trees import RandomForestFamily, GBTFamily
            fams += [RandomForestFamily(), GBTFamily()]
        except ImportError:
            pass
        try:
            from .svm import LinearSVCFamily
            fams.append(LinearSVCFamily())
        except ImportError:
            pass
        return fams

    @classmethod
    def default_splitter(cls) -> Splitter:
        return DataBalancer()


class MultiClassificationModelSelector(_SelectorFactory):
    """Defaults: LR / RF / NB / DT; metric F1."""

    task = "multiclass"
    default_metric = "F1"

    @classmethod
    def default_families(cls) -> List[ModelFamily]:
        fams: List[ModelFamily] = [LogisticRegressionFamily(),
                                   NaiveBayesFamily()]
        try:
            from .trees import DecisionTreeFamily, RandomForestFamily
            fams += [RandomForestFamily(), DecisionTreeFamily()]
        except ImportError:
            pass
        return fams

    @classmethod
    def default_splitter(cls) -> Splitter:
        return DataCutter()


class RegressionModelSelector(_SelectorFactory):
    """Defaults: LinReg / RF / GBT / GLM; metric RMSE."""

    task = "regression"
    default_metric = "RootMeanSquaredError"

    @classmethod
    def default_families(cls) -> List[ModelFamily]:
        fams: List[ModelFamily] = [LinearRegressionFamily()]
        try:
            from .trees import RandomForestFamily, GBTFamily
            fams += [RandomForestFamily(task="regression"),
                     GBTFamily(task="regression")]
        except ImportError:
            pass
        return fams

    @classmethod
    def default_splitter(cls) -> Splitter:
        return DataSplitter()
