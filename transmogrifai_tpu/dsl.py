"""Feature DSL — rich operations on Feature objects.

Parity: ``core/.../dsl/`` (``RichNumericFeature``, ``RichTextFeature``,
``RichFeaturesCollection``) and ``impl/feature/MathTransformers.scala``.
Importing this module (done by the package ``__init__``) attaches the
operators to :class:`~transmogrifai_tpu.features.Feature`:

    family_size = sib_sp + par_ch + 1
    cost = family_size * fare
    pivoted = sex.pivot()
    normed = age.fill_missing_with_mean().z_normalize()

Null semantics follow the reference truth tables
(``MathTransformers.scala``): plus/minus treat empty as identity; multiply/
divide require both sides and drop non-finite results.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Type

import numpy as np

from .columns import Column, ColumnStore, NumericColumn, column_from_values
from .features import Feature
from .stages.base import (Estimator, FittedModel, FixedArity, InputSpec,
                          Transformer, register_stage)
from .types import feature_types as ft

__all__ = ["MathBinaryTransformer", "MathScalarTransformer",
           "FillMissingWithMean", "ScalarNormalizer", "AliasTransformer",
           "MapTransformer", "transmogrify"]


def _num_col(store: ColumnStore, f: Feature) -> NumericColumn:
    col = store[f.name]
    assert isinstance(col, NumericColumn), f"{f.name} is not numeric"
    return col


@register_stage
class MathBinaryTransformer(Transformer):
    """+, -, *, / of two numeric features (MathTransformers.scala)."""

    output_type = ft.Real

    def __init__(self, op: str = "add", uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.op = op
        self.operation_name = {"add": "plus", "subtract": "minus",
                               "multiply": "multiply", "divide": "divide"}[op]

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPNumeric, ft.OPNumeric)

    def transform_columns(self, store: ColumnStore) -> Column:
        a = _num_col(store, self.input_features[0])
        b = _num_col(store, self.input_features[1])
        av = a.values.astype(np.float64)
        bv = b.values.astype(np.float64)
        am, bm = a.mask, b.mask
        if self.op in ("add", "subtract"):
            sign = 1.0 if self.op == "add" else -1.0
            vals = np.where(am, av, 0.0) + sign * np.where(bm, bv, 0.0)
            mask = am | bm
        else:
            with np.errstate(divide="ignore", invalid="ignore"):
                vals = av * bv if self.op == "multiply" else av / bv
            mask = am & bm & np.isfinite(vals)
            vals = np.where(mask, vals, 0.0)
        return NumericColumn(ft.Real, vals, mask)


@register_stage
class MathScalarTransformer(Transformer):
    """Numeric feature op scalar (plusS/minusS/multiplyS/divideS)."""

    output_type = ft.Real

    def __init__(self, op: str = "add", scalar: float = 0.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.op = op
        self.scalar = float(scalar)
        self.operation_name = {"add": "plusS", "subtract": "minusS",
                               "rsubtract": "rminusS", "multiply": "multiplyS",
                               "divide": "divideS", "rdivide": "rdivideS"}[op]

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPNumeric)

    def transform_columns(self, store: ColumnStore) -> Column:
        a = _num_col(store, self.input_features[0])
        av = a.values.astype(np.float64)
        s = self.scalar
        with np.errstate(divide="ignore", invalid="ignore"):
            vals = {"add": av + s, "subtract": av - s, "rsubtract": s - av,
                    "multiply": av * s, "divide": av / s,
                    "rdivide": s / av}[self.op]
        mask = a.mask & np.isfinite(vals)
        return NumericColumn(ft.Real, np.where(mask, vals, 0.0), mask)


@register_stage
class FillMissingWithMean(Estimator):
    """Real → RealNN imputing train mean (RichNumericFeature.fillMissingWithMean)."""

    operation_name = "fillWithMean"
    output_type = ft.RealNN

    def __init__(self, default: float = 0.0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.default = default

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPNumeric)

    def fit_columns(self, store: ColumnStore) -> "FillMissingWithMeanModel":
        col = _num_col(store, self.input_features[0])
        mean = (float(col.values[col.mask].astype(np.float64).mean())
                if col.mask.any() else self.default)
        return FillMissingWithMeanModel(mean=mean)


@register_stage
class FillMissingWithMeanModel(FittedModel):
    operation_name = "fillWithMean"
    output_type = ft.RealNN

    def __init__(self, mean: float = 0.0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.mean = mean

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPNumeric)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = _num_col(store, self.input_features[0])
        vals = np.where(col.mask, col.values.astype(np.float64), self.mean)
        return NumericColumn(ft.RealNN, vals, np.ones(len(col), dtype=bool))

    def get_model_state(self):
        return {"mean": self.mean}


@register_stage
class ScalarNormalizer(Estimator):
    """RealNN → RealNN z-normalization (OpScalarStandardScaler.scala)."""

    operation_name = "zNormalize"
    output_type = ft.RealNN

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPNumeric)

    def fit_columns(self, store: ColumnStore) -> "ScalarNormalizerModel":
        col = _num_col(store, self.input_features[0])
        vals = col.values[col.mask].astype(np.float64)
        mean = float(vals.mean()) if vals.size else 0.0
        std = float(vals.std()) if vals.size else 1.0
        return ScalarNormalizerModel(mean=mean, std=std if std > 1e-12 else 1.0)


@register_stage
class ScalarNormalizerModel(FittedModel):
    operation_name = "zNormalize"
    output_type = ft.RealNN

    def __init__(self, mean: float = 0.0, std: float = 1.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.mean = mean
        self.std = std

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPNumeric)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = _num_col(store, self.input_features[0])
        vals = (col.values.astype(np.float64) - self.mean) / self.std
        vals = np.where(col.mask, vals, 0.0)
        return NumericColumn(ft.RealNN, vals, np.ones(len(col), dtype=bool))

    def get_model_state(self):
        return {"mean": self.mean, "std": self.std}


@register_stage
class AliasTransformer(Transformer):
    """Identity rename (AliasTransformer)."""

    operation_name = "alias"

    def __init__(self, name: str = "alias", uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.name = name
        self.output_type = ft.FeatureType

    @property
    def input_spec(self) -> InputSpec:
        class _Any(InputSpec):
            def check(self, features):
                if len(features) != 1:
                    raise TypeError("alias takes exactly one input")
        return _Any()

    def get_output(self) -> Feature:
        if self._output_feature is None:
            f = self.input_features[0]
            self._output_feature = Feature(
                name=self.name, ftype=f.ftype, is_response=f.is_response,
                origin_stage=self, parents=self.input_features)
        return self._output_feature

    def transform_columns(self, store: ColumnStore) -> Column:
        return store[self.input_features[0].name]


@register_stage
class MapTransformer(Transformer):
    """Row-wise value map (RichFeature.map). The function round-trips via
    utils.fn_io (named fns by qualified name, lambdas by marshaled code —
    the Python analog of the reference's macro-captured sources)."""

    def __init__(self, fn: Callable[[Any], Any] = None,
                 input_type: Type[ft.FeatureType] = ft.FeatureType,
                 output_type: Type[ft.FeatureType] = ft.FeatureType,
                 operation_name: str = "map",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        if isinstance(fn, dict):  # decoded from model.json
            from .utils.fn_io import decode_fn
            fn = decode_fn(fn)
        self.fn = fn
        self._input_type = input_type
        self.output_type = output_type
        self.operation_name = operation_name

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(self._input_type)

    def get_params(self):
        from .utils.fn_io import encode_fn
        p = super().get_params()
        p["fn"] = encode_fn(self.fn)
        p["input_type"] = self._input_type
        return p

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        return column_from_values(
            self.output_type, [self.fn(col.get_raw(i))
                               for i in range(len(col))])


# ---------------------------------------------------------------------------
# Feature method attachment (RichFeature et al.)
# ---------------------------------------------------------------------------

def _binary_math(op):
    def method(self: Feature, other):
        if isinstance(other, Feature):
            return self.transform_with(MathBinaryTransformer(op=op), other)
        return self.transform_with(
            MathScalarTransformer(op=op, scalar=float(other)))
    return method


def _rbinary_math(op, rop):
    def method(self: Feature, other):
        return self.transform_with(
            MathScalarTransformer(op=rop, scalar=float(other)))
    return method


def _pivot(self: Feature, top_k: int = 20, min_support: int = 1):
    from .ops.onehot import OneHotVectorizer
    return self.transform_with(
        OneHotVectorizer(top_k=top_k, min_support=min_support))


def _fill_missing_with_mean(self: Feature, default: float = 0.0):
    return self.transform_with(FillMissingWithMean(default=default))


def _z_normalize(self: Feature):
    return self.transform_with(ScalarNormalizer())


def _map_to(self: Feature, fn, output_type, operation_name: str = "map"):
    return self.transform_with(
        MapTransformer(fn, self.ftype, output_type, operation_name))


def _alias(self: Feature, name: str):
    return self.transform_with(AliasTransformer(name=name))


def _tokenize(self: Feature, **kw):
    from .ops.text import TextTokenizer
    return self.transform_with(TextTokenizer(**kw))


def _vectorize_collection(features: Sequence[Feature]):
    from .ops.transmogrifier import transmogrify as _tm
    return _tm(features)


def _to_email_prefix(self: Feature):
    from .ops.text_suite import EmailParser
    return self.transform_with(EmailParser(part="prefix"))


def _to_email_domain(self: Feature):
    from .ops.text_suite import EmailParser
    return self.transform_with(EmailParser(part="domain"))


def _to_url_protocol(self: Feature):
    from .ops.text_suite import UrlParser
    return self.transform_with(UrlParser(part="protocol"))


def _to_url_domain(self: Feature):
    from .ops.text_suite import UrlParser
    return self.transform_with(UrlParser(part="domain"))


def _is_valid_phone(self: Feature, default_region: str = "US"):
    from .ops.text_suite import PhoneNumberParser
    return self.transform_with(
        PhoneNumberParser(default_region=default_region, output="valid"))


def _detect_mime_types(self: Feature):
    from .ops.text_suite import MimeTypeDetector
    return self.transform_with(MimeTypeDetector())


def _ngram_similarity(self: Feature, other: Feature, n: int = 3):
    from .ops.text_suite import NGramSimilarity
    return self.transform_with(NGramSimilarity(n=n), other)


def _count_vectorize(self: Feature, *others: Feature, **kw):
    from .ops.text_suite import OpCountVectorizer
    return self.transform_with(OpCountVectorizer(**kw), *others)


def _bucketize(self: Feature, splits=None, **kw):
    from .ops.numeric import NumericBucketizer
    return self.transform_with(NumericBucketizer(
        splits=list(splits) if splits is not None else None, **kw))


def _to_unit_circle(self: Feature, **kw):
    from .ops.dates import DateToUnitCircleVectorizer
    return self.transform_with(DateToUnitCircleVectorizer(**kw))


def _combine(self: Feature, *others: Feature):
    from .ops.vectors import VectorsCombiner
    return self.transform_with(VectorsCombiner(), *others)


def _to_percentile(self: Feature, **kw):
    from .ops.calibrators import PercentileCalibrator
    return self.transform_with(PercentileCalibrator(**kw))


def _lda(self: Feature, n_topics: int = 10, **kw):
    from .ops.topics import OpLDA
    return self.transform_with(OpLDA(n_topics=n_topics, **kw))


def _word2vec(self: Feature, dim: int = 32, **kw):
    from .ops.topics import OpWord2Vec
    return self.transform_with(OpWord2Vec(dim=dim, **kw))


def _indexed(self: Feature, **kw):
    from .ops.indexers import OpStringIndexerNoFilter
    return self.transform_with(OpStringIndexerNoFilter(**kw))


def _deindexed(self: Feature, prediction: Feature, **kw):
    from .ops.indexers import PredictionDeIndexer
    return self.transform_with(PredictionDeIndexer(**kw), prediction)


def _filter_keys(self: Feature, allow=None, block=(), **kw):
    from .ops.maps import FilterMapKeys
    return self.transform_with(FilterMapKeys(allow=allow, block=block, **kw))


def _extract_key(self: Feature, key: str, **kw):
    from .ops.maps import ExtractMapKey
    return self.transform_with(ExtractMapKey(key=key, **kw))


def _sanity_check(self: Feature, features: Feature,
                  remove_bad_features: bool = True, **kw):
    from .ops.sanity_checker import SanityChecker
    checker = SanityChecker(remove_bad_features=remove_bad_features, **kw)
    checker.set_input(self, features)
    return checker.get_output()


Feature.__add__ = _binary_math("add")
Feature.__sub__ = _binary_math("subtract")
Feature.__mul__ = _binary_math("multiply")
Feature.__truediv__ = _binary_math("divide")
Feature.__radd__ = _binary_math("add")
Feature.__rmul__ = _binary_math("multiply")
Feature.__rsub__ = _rbinary_math("subtract", "rsubtract")
Feature.__rtruediv__ = _rbinary_math("divide", "rdivide")
Feature.pivot = _pivot
Feature.fill_missing_with_mean = _fill_missing_with_mean
Feature.z_normalize = _z_normalize
Feature.map_to = _map_to
Feature.alias = _alias
Feature.tokenize = _tokenize
Feature.sanity_check = _sanity_check
Feature.to_email_prefix = _to_email_prefix
Feature.to_email_domain = _to_email_domain
Feature.to_url_protocol = _to_url_protocol
Feature.to_url_domain = _to_url_domain
Feature.is_valid_phone = _is_valid_phone
Feature.detect_mime_types = _detect_mime_types
Feature.ngram_similarity = _ngram_similarity
Feature.count_vectorize = _count_vectorize
Feature.indexed = _indexed
Feature.deindexed = _deindexed
Feature.bucketize = _bucketize
Feature.to_unit_circle = _to_unit_circle
Feature.combine = _combine
Feature.to_percentile = _to_percentile
Feature.lda = _lda
Feature.word2vec = _word2vec
Feature.filter_keys = _filter_keys
Feature.extract_key = _extract_key

transmogrify = _vectorize_collection
