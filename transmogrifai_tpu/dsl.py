"""Feature DSL — rich operations on Feature objects.

Parity: ``core/.../dsl/`` (``RichNumericFeature``, ``RichTextFeature``,
``RichFeaturesCollection``) and ``impl/feature/MathTransformers.scala``.
Importing this module (done by the package ``__init__``) attaches the
operators to :class:`~transmogrifai_tpu.features.Feature`:

    family_size = sib_sp + par_ch + 1
    cost = family_size * fare
    pivoted = sex.pivot()
    normed = age.fill_missing_with_mean().z_normalize()

Null semantics follow the reference truth tables
(``MathTransformers.scala``): plus/minus treat empty as identity; multiply/
divide require both sides and drop non-finite results.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Type

import numpy as np

from .columns import Column, ColumnStore, NumericColumn, column_from_values
from .features import Feature
from .stages.base import (Estimator, FittedModel, FixedArity, InputSpec,
                          Transformer, register_stage)
from .types import feature_types as ft

__all__ = ["MathBinaryTransformer", "MathScalarTransformer",
           "FillMissingWithMean", "ScalarNormalizer", "AliasTransformer",
           "MapTransformer", "transmogrify"]


def _num_col(store: ColumnStore, f: Feature) -> NumericColumn:
    col = store[f.name]
    assert isinstance(col, NumericColumn), f"{f.name} is not numeric"
    return col


@register_stage
class MathBinaryTransformer(Transformer):
    """+, -, *, / of two numeric features (MathTransformers.scala)."""

    output_type = ft.Real

    def __init__(self, op: str = "add", uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.op = op
        self.operation_name = {"add": "plus", "subtract": "minus",
                               "multiply": "multiply", "divide": "divide"}[op]

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPNumeric, ft.OPNumeric)

    def transform_columns(self, store: ColumnStore) -> Column:
        a = _num_col(store, self.input_features[0])
        b = _num_col(store, self.input_features[1])
        av = a.values.astype(np.float64)
        bv = b.values.astype(np.float64)
        am, bm = a.mask, b.mask
        if self.op in ("add", "subtract"):
            sign = 1.0 if self.op == "add" else -1.0
            vals = np.where(am, av, 0.0) + sign * np.where(bm, bv, 0.0)
            mask = am | bm
        else:
            with np.errstate(divide="ignore", invalid="ignore"):
                vals = av * bv if self.op == "multiply" else av / bv
            mask = am & bm & np.isfinite(vals)
            vals = np.where(mask, vals, 0.0)
        return NumericColumn(ft.Real, vals, mask)


@register_stage
class MathScalarTransformer(Transformer):
    """Numeric feature op scalar (plusS/minusS/multiplyS/divideS)."""

    output_type = ft.Real

    def __init__(self, op: str = "add", scalar: float = 0.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.op = op
        self.scalar = float(scalar)
        self.operation_name = {"add": "plusS", "subtract": "minusS",
                               "rsubtract": "rminusS", "multiply": "multiplyS",
                               "divide": "divideS", "rdivide": "rdivideS"}[op]

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPNumeric)

    def transform_columns(self, store: ColumnStore) -> Column:
        a = _num_col(store, self.input_features[0])
        av = a.values.astype(np.float64)
        s = self.scalar
        with np.errstate(divide="ignore", invalid="ignore"):
            vals = {"add": av + s, "subtract": av - s, "rsubtract": s - av,
                    "multiply": av * s, "divide": av / s,
                    "rdivide": s / av}[self.op]
        mask = a.mask & np.isfinite(vals)
        return NumericColumn(ft.Real, np.where(mask, vals, 0.0), mask)


@register_stage
class MathUnaryTransformer(Transformer):
    """Unary numeric math (abs/ceil/floor/round/exp/log/sqrt/power —
    ``RichNumericFeature.scala`` unary surface + ``MathTransformers``).
    Domain violations (log of ≤0, sqrt of <0, non-finite results) null
    the row, matching the reference's Option-returning transformers."""

    output_type = ft.Real

    def __init__(self, op: str = "abs", arg: float = 0.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.op = op
        self.arg = float(arg)
        self.operation_name = {
            "abs": "abs", "ceil": "ceil", "floor": "floor",
            "round": "round", "exp": "exp", "log": "logN",
            "sqrt": "sqrt", "power": "power"}[op]

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPNumeric)

    def transform_columns(self, store: ColumnStore) -> Column:
        a = _num_col(store, self.input_features[0])
        av = a.values.astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore", over="ignore"):
            vals = {
                "abs": lambda: np.abs(av),
                "ceil": lambda: np.ceil(av),
                "floor": lambda: np.floor(av),
                "round": lambda: np.round(av, int(self.arg)),
                "exp": lambda: np.exp(av),
                # log base arg (reference log(base); default natural)
                "log": lambda: (np.log(av) if self.arg in (0.0, np.e)
                                else np.log(av) / np.log(self.arg)),
                "sqrt": lambda: np.sqrt(av),
                "power": lambda: np.power(av, self.arg),
            }[self.op]()
        mask = a.mask & np.isfinite(vals)
        return NumericColumn(ft.Real, np.where(mask, vals, 0.0), mask)


@register_stage
class FillMissingWithMean(Estimator):
    """Real → RealNN imputing train mean (RichNumericFeature.fillMissingWithMean)."""

    operation_name = "fillWithMean"
    output_type = ft.RealNN

    def __init__(self, default: float = 0.0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.default = default

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPNumeric)

    def fit_columns(self, store: ColumnStore) -> "FillMissingWithMeanModel":
        col = _num_col(store, self.input_features[0])
        mean = (float(col.values[col.mask].astype(np.float64).mean())
                if col.mask.any() else self.default)
        return FillMissingWithMeanModel(mean=mean)

    # -- fused fit-statistics opt-in (fitstats.py) -------------------------
    def stat_requests(self, store):
        from .fitstats import StatRequest
        return [StatRequest("mean", self.input_features[0].name)]

    def fit_columns_from_stats(self, store, stats):
        mean = stats.value("mean", self.input_features[0].name)
        return FillMissingWithMeanModel(
            mean=self.default if mean is None else mean)


@register_stage
class FillMissingWithMeanModel(FittedModel):
    operation_name = "fillWithMean"
    output_type = ft.RealNN

    def __init__(self, mean: float = 0.0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.mean = mean

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPNumeric)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = _num_col(store, self.input_features[0])
        vals = np.where(col.mask, col.values.astype(np.float64), self.mean)
        return NumericColumn(ft.RealNN, vals, np.ones(len(col), dtype=bool))

    def get_model_state(self):
        return {"mean": self.mean}


@register_stage
class ScalarNormalizer(Estimator):
    """RealNN → RealNN z-normalization (OpScalarStandardScaler.scala)."""

    operation_name = "zNormalize"
    output_type = ft.RealNN

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPNumeric)

    def fit_columns(self, store: ColumnStore) -> "ScalarNormalizerModel":
        col = _num_col(store, self.input_features[0])
        # f64 accumulation like FillMissingWithMean: an f32-backed column
        # store at 1e7-scale values would otherwise lose the mean's low
        # digits and blow up the centered variance (regression test in
        # tests/test_fitstats.py)
        vals = col.values[col.mask].astype(np.float64)
        mean = float(vals.mean()) if vals.size else 0.0
        std = float(vals.std()) if vals.size else 1.0
        return ScalarNormalizerModel(mean=mean, std=std if std > 1e-12 else 1.0)

    # -- fused fit-statistics opt-in (fitstats.py) -------------------------
    def stat_requests(self, store):
        from .fitstats import StatRequest
        name = self.input_features[0].name
        return [StatRequest("mean", name), StatRequest("std", name)]

    def fit_columns_from_stats(self, store, stats):
        name = self.input_features[0].name
        mean = stats.value("mean", name)
        std = stats.value("std", name)
        mean = 0.0 if mean is None else mean
        std = 1.0 if std is None else std
        return ScalarNormalizerModel(mean=mean,
                                     std=std if std > 1e-12 else 1.0)


@register_stage
class ScalarNormalizerModel(FittedModel):
    operation_name = "zNormalize"
    output_type = ft.RealNN

    def __init__(self, mean: float = 0.0, std: float = 1.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.mean = mean
        self.std = std

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPNumeric)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = _num_col(store, self.input_features[0])
        vals = (col.values.astype(np.float64) - self.mean) / self.std
        vals = np.where(col.mask, vals, 0.0)
        return NumericColumn(ft.RealNN, vals, np.ones(len(col), dtype=bool))

    def get_model_state(self):
        return {"mean": self.mean, "std": self.std}


@register_stage
class AliasTransformer(Transformer):
    """Identity rename (AliasTransformer)."""

    operation_name = "alias"

    def __init__(self, name: str = "alias", uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.name = name
        self.output_type = ft.FeatureType

    @property
    def input_spec(self) -> InputSpec:
        class _Any(InputSpec):
            def check(self, features):
                if len(features) != 1:
                    raise TypeError("alias takes exactly one input")
        return _Any()

    def get_output(self) -> Feature:
        if self._output_feature is None:
            f = self.input_features[0]
            self._output_feature = Feature(
                name=self.name, ftype=f.ftype, is_response=f.is_response,
                origin_stage=self, parents=self.input_features)
        return self._output_feature

    def transform_columns(self, store: ColumnStore) -> Column:
        return store[self.input_features[0].name]


@register_stage
class MapTransformer(Transformer):
    """Row-wise value map (RichFeature.map). The function round-trips via
    utils.fn_io (named fns by qualified name, lambdas by marshaled code —
    the Python analog of the reference's macro-captured sources)."""

    def __init__(self, fn: Callable[[Any], Any] = None,
                 input_type: Type[ft.FeatureType] = ft.FeatureType,
                 output_type: Type[ft.FeatureType] = ft.FeatureType,
                 operation_name: str = "map",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        if isinstance(fn, dict):  # decoded from model.json
            from .utils.fn_io import decode_fn
            fn = decode_fn(fn)
        self.fn = fn
        self._input_type = input_type
        self.output_type = output_type
        self.operation_name = operation_name

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(self._input_type)

    def get_params(self):
        from .utils.fn_io import encode_fn
        p = super().get_params()
        p["fn"] = encode_fn(self.fn)
        p["input_type"] = self._input_type
        return p

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        return column_from_values(
            self.output_type, [self.fn(col.get_raw(i))
                               for i in range(len(col))])


# ---------------------------------------------------------------------------
# Feature method attachment (RichFeature et al.)
# ---------------------------------------------------------------------------

def _binary_math(op):
    def method(self: Feature, other):
        if isinstance(other, Feature):
            return self.transform_with(MathBinaryTransformer(op=op), other)
        return self.transform_with(
            MathScalarTransformer(op=op, scalar=float(other)))
    return method


def _rbinary_math(op, rop):
    def method(self: Feature, other):
        return self.transform_with(
            MathScalarTransformer(op=rop, scalar=float(other)))
    return method


def _pivot(self: Feature, top_k: int = 20, min_support: int = 1):
    from .ops.onehot import OneHotVectorizer
    return self.transform_with(
        OneHotVectorizer(top_k=top_k, min_support=min_support))


def _fill_missing_with_mean(self: Feature, default: float = 0.0):
    return self.transform_with(FillMissingWithMean(default=default))


def _z_normalize(self: Feature):
    return self.transform_with(ScalarNormalizer())


def _map_to(self: Feature, fn, output_type, operation_name: str = "map"):
    return self.transform_with(
        MapTransformer(fn, self.ftype, output_type, operation_name))


def _alias(self: Feature, name: str):
    return self.transform_with(AliasTransformer(name=name))


def _tokenize(self: Feature, **kw):
    from .ops.text import TextTokenizer
    return self.transform_with(TextTokenizer(**kw))


def _vectorize_collection(features: Sequence[Feature]):
    from .ops.transmogrifier import transmogrify as _tm
    return _tm(features)


def _to_email_prefix(self: Feature):
    from .ops.text_suite import EmailParser
    return self.transform_with(EmailParser(part="prefix"))


def _to_email_domain(self: Feature):
    from .ops.text_suite import EmailParser
    return self.transform_with(EmailParser(part="domain"))


def _to_url_protocol(self: Feature):
    from .ops.text_suite import UrlParser
    return self.transform_with(UrlParser(part="protocol"))


def _to_url_domain(self: Feature):
    from .ops.text_suite import UrlParser
    return self.transform_with(UrlParser(part="domain"))


def _is_valid_phone(self: Feature, default_region: str = "US"):
    from .ops.text_suite import PhoneNumberParser
    return self.transform_with(
        PhoneNumberParser(default_region=default_region, output="valid"))


def _detect_mime_types(self: Feature):
    from .ops.text_suite import MimeTypeDetector
    return self.transform_with(MimeTypeDetector())


def _ngram_similarity(self: Feature, other: Feature, n: int = 3):
    from .ops.text_suite import NGramSimilarity
    return self.transform_with(NGramSimilarity(n=n), other)


def _count_vectorize(self: Feature, *others: Feature, **kw):
    from .ops.text_suite import OpCountVectorizer
    return self.transform_with(OpCountVectorizer(**kw), *others)


def _bucketize(self: Feature, splits=None, **kw):
    from .ops.numeric import NumericBucketizer
    return self.transform_with(NumericBucketizer(
        splits=list(splits) if splits is not None else None, **kw))


def _to_unit_circle(self: Feature, **kw):
    from .ops.dates import DateToUnitCircleVectorizer
    return self.transform_with(DateToUnitCircleVectorizer(**kw))


def _combine(self: Feature, *others: Feature):
    from .ops.vectors import VectorsCombiner
    return self.transform_with(VectorsCombiner(), *others)


def _to_percentile(self: Feature, **kw):
    from .ops.calibrators import PercentileCalibrator
    return self.transform_with(PercentileCalibrator(**kw))


def _lda(self: Feature, n_topics: int = 10, **kw):
    from .ops.topics import OpLDA
    return self.transform_with(OpLDA(n_topics=n_topics, **kw))


def _word2vec(self: Feature, **kw):
    """Estimator defaults (dim=100, window=5 — Spark ml Word2Vec parity,
    ``ops/topics.py``): the DSL entry forwards kwargs untouched so the
    two surfaces cannot drift (a round-3 ``dim=32`` default here silently
    gave DSL users a non-parity model)."""
    from .ops.topics import OpWord2Vec
    return self.transform_with(OpWord2Vec(**kw))


def _tf(self: Feature, num_terms: int = 512, binary: bool = False):
    """TextList → hashed term-frequency OPVector
    (RichListFeature.tf :59)."""
    from .ops.list_ops import OpHashingTF
    return self.transform_with(OpHashingTF(num_terms=num_terms,
                                           binary=binary))


def _idf(self: Feature, min_doc_freq: int = 0):
    """OPVector → IDF-scaled OPVector (Spark IDF wrap)."""
    from .ops.list_ops import OpIDF
    return self.transform_with(OpIDF(min_doc_freq=min_doc_freq))


def _tfidf(self: Feature, num_terms: int = 512, binary: bool = False,
           min_doc_freq: int = 0):
    """TextList → TF-IDF OPVector (RichListFeature.tfidf :76)."""
    return _idf(_tf(self, num_terms=num_terms, binary=binary),
                min_doc_freq=min_doc_freq)


def _ngram(self: Feature, n: int = 2):
    """TextList → TextList of space-joined n-grams
    (RichListFeature.ngram :153)."""
    from .ops.list_ops import OpNGram
    return self.transform_with(OpNGram(n=n))


def _remove_stop_words(self: Feature, stop_words=None,
                       case_sensitive: bool = False):
    """TextList → TextList without stop words
    (RichListFeature.removeStopWords :168)."""
    from .ops.list_ops import OpStopWordsRemover
    return self.transform_with(OpStopWordsRemover(
        stop_words=stop_words, case_sensitive=case_sensitive))


def _jaccard_similarity(self: Feature, other: Feature):
    """(MultiPickList, MultiPickList) → RealNN Jaccard overlap
    (RichSetFeature.jaccardSimilarity :124)."""
    from .ops.list_ops import JaccardSimilarity
    return self.transform_with(JaccardSimilarity(), other)


def _unary_math(op):
    def method(self: Feature, arg: float = 0.0):
        return self.transform_with(MathUnaryTransformer(op=op, arg=arg))
    method.__name__ = f"_{op}"
    method.__doc__ = (f"Numeric → Real {op} "
                      "(RichNumericFeature unary math surface).")
    return method


def _scaled(self: Feature, scaling_type: str = "linear", **kw):
    """Real → Real via ScalerTransformer (ScalerTransformer.scala);
    ``descaled`` inverts using the recorded scaler metadata."""
    from .ops.scalers import ScalerTransformer
    return self.transform_with(ScalerTransformer(
        scaling_type=scaling_type, **kw))


def _descaled(self: Feature, scaled: "Feature", **kw):
    from .ops.scalers import DescalerTransformer
    return self.transform_with(DescalerTransformer(**kw), scaled)


def _to_isotonic_calibrated(self: Feature, label: "Feature",
                            isotonic: bool = True):
    """RealNN score → isotonic-calibrated score
    (RichNumericFeature.toIsotonicCalibrated →
    IsotonicRegressionCalibrator.scala)."""
    from .ops.calibrators import IsotonicRegressionCalibrator
    return label.transform_with(
        IsotonicRegressionCalibrator(isotonic=isotonic), self)


def _indexed(self: Feature, **kw):
    from .ops.indexers import OpStringIndexerNoFilter
    return self.transform_with(OpStringIndexerNoFilter(**kw))


def _deindexed(self: Feature, prediction: Feature, **kw):
    from .ops.indexers import PredictionDeIndexer
    return self.transform_with(PredictionDeIndexer(**kw), prediction)


def _filter_keys(self: Feature, allow=None, block=(), **kw):
    from .ops.maps import FilterMapKeys
    return self.transform_with(FilterMapKeys(allow=allow, block=block, **kw))


def _extract_key(self: Feature, key: str, **kw):
    from .ops.maps import ExtractMapKey
    return self.transform_with(ExtractMapKey(key=key, **kw))


def _sanity_check(self: Feature, features: Feature,
                  remove_bad_features: bool = True, **kw):
    from .ops.sanity_checker import SanityChecker
    checker = SanityChecker(remove_bad_features=remove_bad_features, **kw)
    checker.set_input(self, features)
    return checker.get_output()


# ---------------------------------------------------------------------------
# Rich* long tail (RichMapFeature.scala:1-1118, RichTextFeature.scala:75-822)
# ---------------------------------------------------------------------------

def _apply_key_filters(feats, allow_keys, block_keys, ColumnKind):
    """Key white/blacklists apply to every MAP-kind feature in the group;
    passing them with no map feature present is a silent no-op the caller
    almost certainly didn't intend (a dropped blacklist = a leaked key),
    so it raises instead."""
    if allow_keys is None and not block_keys:
        return feats
    is_map = [f.ftype.column_kind is ColumnKind.MAP for f in feats]
    if not any(is_map):
        raise ValueError(
            "allow_keys/block_keys were given but none of the features "
            "is map-typed — the key filter would be silently dropped")
    return [f.filter_keys(allow=allow_keys, block=block_keys) if m else f
            for f, m in zip(feats, is_map)]

def _vectorize(self: Feature, *others: Feature,
               top_k: Optional[int] = None,
               min_support: Optional[int] = None,
               track_nulls: Optional[bool] = None,
               track_invalid: Optional[bool] = None,
               num_features: Optional[int] = None,
               fill_with_mean: Optional[bool] = None,
               fill_with_mode: Optional[bool] = None,
               default_value: Optional[float] = None,
               allow_keys: Optional[Sequence[str]] = None,
               block_keys: Sequence[str] = ()):
    """One-call vectorization of this feature (+ same-typed ``others``)
    with per-call Transmogrifier overrides — the reference's per-type
    ``vectorize(...)`` surface collapsed onto one method: the stage each
    type gets is decided by the same dispatch table transmogrify uses.
    Map features additionally honor ``allow_keys``/``block_keys``
    (RichMapFeature's whiteListKeys/blackListKeys)."""
    from .ops.transmogrifier import Transmogrifier
    from .ops.vectorizer_base import TransmogrifierDefaults
    from .types.feature_types import ColumnKind

    feats = _apply_key_filters([self, *others], allow_keys, block_keys,
                               ColumnKind)

    class _Defaults(TransmogrifierDefaults):
        pass
    for attr, v in (("TOP_K", top_k), ("MIN_SUPPORT", min_support),
                    ("TRACK_NULLS", track_nulls),
                    ("TRACK_INVALID", track_invalid),
                    ("HASH_SIZE", num_features),
                    ("FILL_WITH_MEAN", fill_with_mean),
                    ("FILL_WITH_MODE", fill_with_mode),
                    ("FILL_VALUE", default_value)):
        if v is not None:
            setattr(_Defaults, attr, v)
    return Transmogrifier.vectorize(feats, _Defaults)


def _smart_vectorize(self: Feature, *others: Feature,
                     max_cardinality: int = 100,
                     top_k: Optional[int] = None,
                     min_support: Optional[int] = None,
                     num_features: Optional[int] = None,
                     track_nulls: bool = True,
                     track_text_len: bool = False,
                     allow_keys: Optional[Sequence[str]] = None,
                     block_keys: Sequence[str] = ()):
    """Cardinality-probing text vectorization (RichTextFeature
    ``smartVectorize`` :223-281 / RichMapFeature ``smartVectorize``
    :280-350): low-cardinality values pivot, high-cardinality values
    hash. Works on Text-ish features and on text-valued maps."""
    from .ops.smart_text import SmartTextVectorizer
    from .ops.maps import SmartTextMapVectorizer
    from .ops.vectorizer_base import TransmogrifierDefaults as TD
    from .types.feature_types import ColumnKind

    kw = dict(max_cardinality=max_cardinality,
              top_k=TD.TOP_K if top_k is None else top_k,
              min_support=TD.MIN_SUPPORT if min_support is None
              else min_support,
              num_features=TD.HASH_SIZE if num_features is None
              else num_features,
              track_nulls=track_nulls, track_text_len=track_text_len)
    feats = _apply_key_filters([self, *others], allow_keys, block_keys,
                               ColumnKind)
    if self.ftype.column_kind is ColumnKind.MAP:
        stage = SmartTextMapVectorizer(**kw)
    else:
        stage = SmartTextVectorizer(**kw)
    return feats[0].transform_with(stage, *feats[1:])


def _auto_bucketize(self: Feature, label: Feature, **kw):
    """Label-aware decision-tree bucketing (RichNumericFeature/
    RichMapFeature ``autoBucketize`` :542-664): split points come from a
    single-feature decision tree against the label."""
    from .ops.dt_bucketizer import (DecisionTreeNumericBucketizer,
                                    DecisionTreeNumericMapBucketizer)
    from .types.feature_types import ColumnKind

    cls = (DecisionTreeNumericMapBucketizer
           if self.ftype.column_kind is ColumnKind.MAP
           else DecisionTreeNumericBucketizer)
    return label.transform_with(cls(**kw), self)


def _detect_languages(self: Feature):
    """Text → RealMap of language-confidence scores
    (RichTextFeature.detectLanguages :403)."""
    from .ops.text_suite import LanguageDetector
    return self.transform_with(LanguageDetector())


def _recognize_entities(self: Feature):
    """Text → MultiPickList of entity spans
    (RichTextFeature.recognizeEntities :420)."""
    from .ops.text_suite import NameEntityRecognizer
    return self.transform_with(NameEntityRecognizer())


def _is_substring(self: Feature, other: Feature):
    """Binary: is this text a (case-insensitive) substring of ``other``
    (RichTextFeature.isSubstring :445)."""
    import numpy as np

    from .columns import NumericColumn
    from .stages.base import LambdaTransformer
    from .types.feature_types import Binary, Text

    def fn(a_col, b_col):
        n = len(a_col)
        vals = np.zeros((n,), np.float64)
        mask = np.zeros((n,), bool)
        for i in range(n):
            a, b = a_col.get_raw(i), b_col.get_raw(i)
            if a is not None and b is not None:
                mask[i] = True
                vals[i] = float(str(a).lower() in str(b).lower())
        return NumericColumn(Binary, vals, mask)

    stage = LambdaTransformer("isSubstring", fn, [Text, Text], Binary)
    stage.set_input(self, other)
    return stage.get_output()


def _is_valid_email(self: Feature):
    """Email → Binary validity (RichTextFeature.isValidEmail :591).
    Same grammar as to_email_prefix/domain (``parse_email``), so a value
    can never be 'valid' yet unparseable."""
    from .ops.text_suite import parse_email
    return _map_to(
        self, lambda v: (None if v is None
                         else parse_email(v)[0] is not None),
        _ft().Binary, "isValidEmail")


def _is_valid_url(self: Feature):
    """URL → Binary validity (RichTextFeature.isValidUrl :642); same
    grammar as to_url_protocol/domain (``parse_url``)."""
    from .ops.text_suite import parse_url
    return _map_to(
        self, lambda v: (None if v is None
                         else parse_url(v)[0] is not None),
        _ft().Binary, "isValidUrl")


def _parse_phone(self: Feature, default_region: str = "US"):
    """Phone → Text national number (RichTextFeature.parsePhone :464)."""
    from .ops.text_suite import PhoneNumberParser
    return self.transform_with(PhoneNumberParser(
        default_region=default_region, output="national"))


def _to_multi_pick_list(self: Feature):
    """TextList → MultiPickList (RichTextFeature.toMultiPickList :58)."""
    return _map_to(self, lambda v: set(v or ()), _ft().MultiPickList,
                   "toMultiPickList")


def _vectorize_location(self: Feature, *others: Feature,
                        top_k: Optional[int] = None,
                        min_support: Optional[int] = None,
                        track_nulls: bool = True):
    """Location-text pivot (RichLocationFeature.vectorize :50-76):
    Country/State/City/PostalCode/Street (Text + Location marker types)
    pivot into top-K one-hot + OTHER (+ null) columns. The numeric
    Geolocation type instead routes through ``vectorize`` →
    GeolocationVectorizer ((lat, lon, accuracy) with geo-mean fill)."""
    from .ops.onehot import OneHotVectorizer
    from .ops.vectorizer_base import TransmogrifierDefaults as TD
    stage = OneHotVectorizer(
        top_k=TD.TOP_K if top_k is None else top_k,
        min_support=TD.MIN_SUPPORT if min_support is None else min_support,
        track_nulls=track_nulls)
    return self.transform_with(stage, *others)


def _to_email_domain_map(self: Feature):
    """EmailMap → PickListMap of email domains — the extraction half of
    RichEmailMapFeature.vectorize (:968-1004); feed the result to
    ``vectorize``/``smart_vectorize`` to finish the reference's chain."""
    from .ops.text_suite import parse_email

    def f(m):
        out = {}
        for k, v in (m or {}).items():
            d = parse_email(v)[1]
            if d is not None:
                out[k] = d
        return out
    return _map_to(self, f, _ft().PickListMap, "emailMapToPickListMap")


def _to_url_domain_map(self: Feature):
    """URLMap → PickListMap of domains of VALID urls — the extraction
    half of RichURLMapFeature.vectorize (:1040-1096)."""
    from .ops.text_suite import parse_url

    def f(m):
        out = {}
        for k, v in (m or {}).items():
            proto, domain = parse_url(v)[:2]
            if proto is not None and domain is not None:
                out[k] = domain
        return out
    return _map_to(self, f, _ft().PickListMap, "urlMapToPickListMap")


def _is_valid_phone_map(self: Feature, default_region: str = "US"):
    """PhoneMap → BinaryMap of per-key phone validity
    (RichPhoneMapFeature.isValidPhoneDefaultCountryMap :945-958)."""
    from .ops.text_suite import parse_phone

    def f(m):
        return {k: parse_phone(v, default_region)[0]
                for k, v in (m or {}).items()}
    return _map_to(self, f, _ft().BinaryMap, "isValidPhoneMapDefaultCountry")


@register_stage
class ValueOpTransformer(Transformer):
    """RichFeature value-surface ops (replaceWith / filter / filterNot /
    collect / exists / occurs, ``RichFeature.scala:61-205``) as ONE
    registered stage: the op's semantics live here, and only the USER's
    predicate/partial function is serialized (via utils.fn_io, exactly
    like MapTransformer) — wrapping the user fn in a closure would make
    every such model unpersistable (fn_io cannot marshal captured
    function objects)."""

    def __init__(self, op: str = "exists", fn: Callable[[Any], Any] = None,
                 default: Any = None, old_val: Any = None,
                 new_val: Any = None,
                 input_type: Type[ft.FeatureType] = ft.FeatureType,
                 output_type: Type[ft.FeatureType] = ft.FeatureType,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        if isinstance(fn, dict):        # decoded from model.json
            from .utils.fn_io import decode_fn
            fn = decode_fn(fn)
        self.op = op
        self.fn = fn
        self.default = default
        self.old_val = old_val
        self.new_val = new_val
        self._input_type = input_type
        self.output_type = output_type
        self.operation_name = op

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(self._input_type)

    def get_params(self):
        from .utils.fn_io import encode_fn
        p = super().get_params()
        p["fn"] = encode_fn(self.fn) if self.fn is not None else None
        p["input_type"] = self._input_type
        return p

    @staticmethod
    def _present(v) -> bool:
        if v is None:
            return False
        if isinstance(v, (list, set, dict, str)):
            return len(v) > 0
        return True

    def _apply(self, v):
        op, fn = self.op, self.fn
        if op == "replaceWith":
            return self.new_val if v == self.old_val else v
        if op == "filter":
            return v if fn(v) else self.default
        if op == "filterNot":
            return self.default if fn(v) else v
        if op == "collect":
            out = fn(v)
            return self.default if out is None else out
        if op == "exists":
            return bool(v is not None and fn(v))
        if op == "occurs":
            if fn is None:
                return 1.0 if self._present(v) else 0.0
            return 1.0 if (v is not None and fn(v)) else 0.0
        raise ValueError(f"unknown value op {op!r}")

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        return column_from_values(
            self.output_type,
            [self._apply(col.get_raw(i)) for i in range(len(col))])


def _value_op(self: Feature, output_type, **kw):
    stage = ValueOpTransformer(input_type=self.ftype,
                               output_type=output_type, **kw)
    stage.set_input(self)
    return stage.get_output()


def _to_date_list(self: Feature):
    """Date → DateList of the single timestamp (RichDateFeature.toDateList
    :54-60); empty date → empty list."""
    return _map_to(self, lambda v: [] if v is None else [int(v)],
                   _ft().DateList, "toDateList")


def _to_date_time_list(self: Feature):
    """DateTime → DateTimeList (RichDateFeature.toDateTimeList :124-130)."""
    return _map_to(self, lambda v: [] if v is None else [int(v)],
                   _ft().DateTimeList, "toDateTimeList")


def _replace_with(self: Feature, old_val, new_val):
    """Swap one value for another, same type (RichFeature.replaceWith
    :75-77)."""
    return _value_op(self, self.ftype, op="replaceWith",
                     old_val=old_val, new_val=new_val)


def _filter_values(self: Feature, predicate, default):
    """Keep values passing ``predicate``; others become ``default``
    (RichFeature.filter :134-140)."""
    return _value_op(self, self.ftype, op="filter", fn=predicate,
                     default=default)


def _filter_not(self: Feature, predicate, default):
    """RichFeature.filterNot (:148-150)."""
    return _value_op(self, self.ftype, op="filterNot", fn=predicate,
                     default=default)


def _collect(self: Feature, fn, default, output_type=None):
    """Partial transform: ``fn(value)`` where it returns non-None, else
    ``default`` (RichFeature.collect :160-168 — Python spells a partial
    function as an fn returning None off-domain)."""
    return _value_op(self, output_type or self.ftype, op="collect",
                     fn=fn, default=default)


def _exists(self: Feature, predicate):
    """Binary: does the (non-null) value satisfy ``predicate``
    (RichFeature.exists :176-182)."""
    return _value_op(self, _ft().Binary, op="exists", fn=predicate)


def _occurs(self: Feature, match_fn=None):
    """RealNN 1.0/0.0 occurrence indicator (RichFeature.occurs
    :190-205): default = value is present/non-empty."""
    return _value_op(self, _ft().RealNN, op="occurs", fn=match_fn)


def _drop_indices_by(self: Feature, match_fn):
    """OPVector → OPVector with the metadata-matched columns dropped
    (RichVectorFeature.dropIndicesBy :139 → DropIndicesByTransformer):
    ``match_fn(VectorColumnMetadata) -> bool`` selects columns to DROP.
    Requires vector metadata (vectorizer outputs always carry it)."""
    from .columns import VectorColumn
    from .stages.base import LambdaTransformer
    ftx = _ft()

    def fn(col):
        # explicit ValueErrors, not asserts: input validation must
        # survive ``python -O`` (asserts are stripped under -O)
        if not isinstance(col, VectorColumn):
            raise ValueError(
                f"dropIndicesBy needs an OPVector column, got "
                f"{type(col).__name__}")
        if col.metadata is None:
            raise ValueError(
                "dropIndicesBy needs a metadata-carrying OPVector "
                "(vectorizer outputs always carry metadata)")
        keep = [i for i, cm in enumerate(col.metadata.columns)
                if not match_fn(cm)]
        meta = col.metadata.select(keep)
        return VectorColumn(ftx.OPVector, col.values[:, keep], meta)

    stage = LambdaTransformer("dropIndicesBy", fn, [ftx.OPVector],
                              ftx.OPVector)
    stage.set_input(self)
    return stage.get_output()


def _tupled(self: Feature):
    """Prediction → (prediction RealNN, rawPrediction OPVector,
    probability OPVector) (RichPredictionFeature.tupled :1098-1111)."""
    from .columns import PredictionColumn, VectorColumn
    from .stages.base import LambdaTransformer
    ftx = _ft()

    def mk(name, fn, otype):
        st = LambdaTransformer(name, fn, [ftx.Prediction], otype)
        st.set_input(self)
        return st.get_output()

    def _pred(c: PredictionColumn):
        return NumericColumn(ftx.RealNN, np.asarray(c.prediction),
                             np.ones(len(c), bool))
    return (
        mk("predictionValue", _pred, ftx.RealNN),
        mk("rawPrediction",
           lambda c: VectorColumn(ftx.OPVector,
                                  np.asarray(c.raw_prediction)),
           ftx.OPVector),
        mk("probability",
           lambda c: VectorColumn(ftx.OPVector,
                                  np.asarray(c.probability)),
           ftx.OPVector),
    )


def _ft():
    from .types import feature_types
    return feature_types


Feature.__add__ = _binary_math("add")
Feature.__sub__ = _binary_math("subtract")
Feature.__mul__ = _binary_math("multiply")
Feature.__truediv__ = _binary_math("divide")
Feature.__radd__ = _binary_math("add")
Feature.__rmul__ = _binary_math("multiply")
Feature.__rsub__ = _rbinary_math("subtract", "rsubtract")
Feature.__rtruediv__ = _rbinary_math("divide", "rdivide")
Feature.pivot = _pivot
Feature.fill_missing_with_mean = _fill_missing_with_mean
Feature.z_normalize = _z_normalize
Feature.map_to = _map_to
Feature.alias = _alias
Feature.tokenize = _tokenize
Feature.sanity_check = _sanity_check
Feature.to_email_prefix = _to_email_prefix
Feature.to_email_domain = _to_email_domain
Feature.to_url_protocol = _to_url_protocol
Feature.to_url_domain = _to_url_domain
Feature.is_valid_phone = _is_valid_phone
Feature.detect_mime_types = _detect_mime_types
Feature.ngram_similarity = _ngram_similarity
Feature.count_vectorize = _count_vectorize
Feature.indexed = _indexed
Feature.deindexed = _deindexed
Feature.bucketize = _bucketize
Feature.to_unit_circle = _to_unit_circle
Feature.combine = _combine
Feature.to_percentile = _to_percentile
Feature.lda = _lda
Feature.word2vec = _word2vec
Feature.tf = _tf
Feature.idf = _idf
Feature.tfidf = _tfidf
Feature.ngram = _ngram
Feature.remove_stop_words = _remove_stop_words
Feature.jaccard_similarity = _jaccard_similarity
Feature.abs = _unary_math("abs")
Feature.ceil = _unary_math("ceil")
Feature.floor = _unary_math("floor")
Feature.round_to = _unary_math("round")
Feature.exp = _unary_math("exp")
Feature.log = _unary_math("log")
Feature.sqrt = _unary_math("sqrt")
Feature.power = _unary_math("power")
Feature.scaled = _scaled
Feature.descaled = _descaled
Feature.to_isotonic_calibrated = _to_isotonic_calibrated
Feature.filter_keys = _filter_keys
Feature.extract_key = _extract_key
Feature.vectorize = _vectorize
Feature.smart_vectorize = _smart_vectorize
Feature.auto_bucketize = _auto_bucketize
Feature.detect_languages = _detect_languages
Feature.recognize_entities = _recognize_entities
Feature.is_substring = _is_substring
Feature.is_valid_email = _is_valid_email
Feature.is_valid_url = _is_valid_url
Feature.parse_phone = _parse_phone
Feature.to_multi_pick_list = _to_multi_pick_list
Feature.to_date_list = _to_date_list
Feature.to_date_time_list = _to_date_time_list
Feature.replace_with = _replace_with
Feature.filter_values = _filter_values
Feature.filter_not = _filter_not
Feature.collect = _collect
Feature.exists = _exists
Feature.occurs = _occurs
Feature.drop_indices_by = _drop_indices_by
Feature.vectorize_location = _vectorize_location
Feature.to_email_domain_map = _to_email_domain_map
Feature.to_url_domain_map = _to_url_domain_map
Feature.is_valid_phone_map = _is_valid_phone_map
Feature.tupled = _tupled

transmogrify = _vectorize_collection
