"""Device mesh + sharding helpers — the framework's distributed substrate.

The reference's distribution substrate is Spark's executor fleet (netty
shuffle, driver-coordinated jobs). The TPU-native substrate is a
``jax.sharding.Mesh`` with named axes and GSPMD: inputs carry
``NamedSharding`` annotations, ``jit`` partitions the computation, and XLA
inserts the collectives (psum for fit reductions) over ICI — no explicit
communication layer to maintain (SURVEY §2.10).

Axes:
* ``data``  — rows (batch). Fit reductions (gram matrices, gradient sums)
  become per-shard partials + psum, riding ICI.
* ``grid``  — (fold × hyperparameter) batch of the CV sweep. Embarrassingly
  parallel; sharding it multiplies model-selection throughput.

``make_mesh`` splits available devices between the two axes; for CV the grid
axis gets as many devices as it can fill, the data axis the rest.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "data_sharding", "shard_cv_inputs"]


def make_mesh(n_devices: Optional[int] = None, grid_size: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """2-D ``(data, grid)`` mesh over the available devices.

    ``grid_size`` is the total (fold × hyperparam) batch the caller wants to
    parallelize; the grid axis is sized to the largest power-of-two divisor
    of the device count that does not exceed it.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    grid_axis = 1
    while (n % (grid_axis * 2) == 0 and grid_axis * 2 <= max(grid_size, 1)
           and grid_axis * 2 <= n):
        grid_axis *= 2
    data_axis = n // grid_axis
    mesh_devs = np.asarray(devs).reshape(data_axis, grid_axis)
    return Mesh(mesh_devs, axis_names=("data", "grid"))


def data_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def pad_rows(X, y, w_folds, multiple: int):
    """Pad the row dimension to a multiple with ZERO-WEIGHT rows.

    Every fit reduction is sample-weighted, so w=0 padding rows are inert —
    this is how ragged row counts meet GSPMD's even-sharding requirement
    without changing any result. Returns (X, y, w_folds, n_original).
    """
    n = X.shape[0]
    pad = (-n) % multiple
    if pad == 0:
        return X, y, w_folds, n
    X = np.concatenate([X, np.zeros((pad, X.shape[1]), dtype=X.dtype)])
    y = np.concatenate([y, np.zeros((pad,), dtype=y.dtype)])
    w_folds = np.concatenate(
        [w_folds, np.zeros((w_folds.shape[0], pad), dtype=w_folds.dtype)],
        axis=1)
    return X, y, w_folds, n


def shard_cv_inputs(mesh: Mesh, X, y, w_folds, extra=None):
    """Place CV inputs: rows over ``data``, fold/grid batches over ``grid``.

    X: [n, d] → P('data', None); y: [n] → P('data');
    w_folds: [K, n] → P('grid', 'data') so each grid-axis shard owns a
    subset of folds and each data-axis shard a subset of rows.
    Rows are zero-weight padded to the data-axis size; the returned
    ``n_orig`` tells callers where to slice device outputs.

    ``extra`` — optional additional [K, n] per-fold mask/weight array
    (e.g. validation-row weights) padded with zeros and sharded like
    ``w_folds``; when given the return is (X, y, w, extra, n_orig).
    """
    import jax.numpy as jnp
    X = np.asarray(X)
    y = np.asarray(y)
    w_folds = np.asarray(w_folds)
    n = X.shape[0]
    X, y, w_folds, n_orig = pad_rows(X, y, w_folds, mesh.shape["data"])
    Xs = jax.device_put(jnp.asarray(X), NamedSharding(mesh, P("data", None)))
    ys = jax.device_put(jnp.asarray(y), NamedSharding(mesh, P("data")))
    k = w_folds.shape[0]
    grid_n = mesh.shape["grid"]
    spec = P("grid", "data") if k % grid_n == 0 else P(None, "data")
    ws = jax.device_put(jnp.asarray(w_folds), NamedSharding(mesh, spec))
    if extra is None:
        return Xs, ys, ws, n_orig
    extra = np.asarray(extra)
    pad = w_folds.shape[1] - n
    if pad:
        extra = np.concatenate(
            [extra, np.zeros((extra.shape[0], pad), dtype=extra.dtype)],
            axis=1)
    es = jax.device_put(jnp.asarray(extra), NamedSharding(mesh, spec))
    return Xs, ys, ws, es, n_orig
