"""Device mesh + sharding helpers — the framework's distributed substrate.

The reference's distribution substrate is Spark's executor fleet (netty
shuffle, driver-coordinated jobs). The TPU-native substrate is a
``jax.sharding.Mesh`` with named axes and GSPMD: inputs carry
``NamedSharding`` annotations, ``jit`` partitions the computation, and XLA
inserts the collectives (psum for fit reductions) over ICI — no explicit
communication layer to maintain (SURVEY §2.10).

Axes:
* ``data``  — rows (batch). Fit reductions (gram matrices, gradient sums)
  become per-shard partials + psum, riding ICI.
* ``grid``  — (fold × hyperparameter) batch of the CV sweep. Embarrassingly
  parallel; sharding it multiplies model-selection throughput.

``make_mesh`` splits available devices between the two axes; for CV the grid
axis gets as many devices as it can fill, the data axis the rest.

Since PR 6 the mesh is the MAINLINE substrate, not a dry-run opt-in: the
workflow/runner resolve one **process-default mesh** over all visible
devices at the first train/score and thread it to every heavy phase
(CV sweep, fused fit-statistics pass, scoring engine). On a single
device the default mesh is the degenerate ``1×1`` and every consumer
takes exactly the pre-mesh code path (``mesh_if_multi`` returns None),
so the single-device behavior is the special case of the mesh, not a
fork. ``TMOG_MESH=0`` disables the promotion entirely.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "data_sharding", "shard_cv_inputs", "pad_rows",
           "process_default_mesh", "set_process_mesh", "mesh_if_multi",
           "mesh_topology", "mesh_constructions", "mesh_enabled",
           "feature_shard_mesh"]

#: master switch for the mainline mesh promotion (``TMOG_MESH=0`` keeps
#: every consumer on the pre-mesh single-device path)
MESH_ENABLED = os.environ.get("TMOG_MESH", "1") != "0"

#: process-wide mesh constructions — cheap evidence that nothing builds a
#: throwaway mesh per pass (fitstats_stats()/bench docs surface it; the
#: steady state is ONE construction per process)
_CONSTRUCTIONS = [0]

_PROCESS_MESH: Optional[Mesh] = None
_PROCESS_MESH_LOCK = threading.Lock()


def mesh_enabled() -> bool:
    """True when the mainline mesh promotion is on (``TMOG_MESH``)."""
    return MESH_ENABLED


def mesh_constructions() -> int:
    """How many meshes this process has built (``make_mesh`` calls)."""
    return _CONSTRUCTIONS[0]


def make_mesh(n_devices: Optional[int] = None, grid_size: int = 1,
              devices: Optional[Sequence] = None,
              grid_axis: Optional[int] = None) -> Mesh:
    """2-D ``(data, grid)`` mesh over the available devices.

    ``grid_size`` is the total (fold × hyperparam) batch the caller wants to
    parallelize; the grid axis is sized to the largest power-of-two divisor
    of the device count that does not exceed it. An explicit ``grid_axis``
    overrides the sizing and must divide the device count evenly.

    Impossible splits raise a descriptive ``ValueError`` instead of
    silently truncating or crashing inside ``reshape``: asking for more
    devices than exist, a non-positive count, or a ``grid_axis`` that
    does not divide the device count.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if n_devices < 1:
            raise ValueError(
                f"make_mesh: n_devices must be >= 1, got {n_devices}")
        if n_devices > len(devs):
            raise ValueError(
                f"make_mesh: n_devices={n_devices} exceeds the "
                f"{len(devs)} visible device(s) — an oversized request "
                "must not silently shrink to what exists")
        devs = devs[:n_devices]
    n = len(devs)
    if n == 0:
        raise ValueError("make_mesh: no devices to build a mesh over")
    if grid_axis is not None:
        if grid_axis < 1 or n % grid_axis != 0:
            raise ValueError(
                f"make_mesh: impossible (data, grid) split — grid_axis="
                f"{grid_axis} does not divide the {n} device(s) evenly "
                f"(data axis would be {n}/{grid_axis})")
    else:
        grid_axis = 1
        while (n % (grid_axis * 2) == 0 and grid_axis * 2 <= max(grid_size, 1)
               and grid_axis * 2 <= n):
            grid_axis *= 2
    data_axis = n // grid_axis
    mesh_devs = np.asarray(devs).reshape(data_axis, grid_axis)
    _CONSTRUCTIONS[0] += 1
    return Mesh(mesh_devs, axis_names=("data", "grid"))


def feature_shard_mesh(n_shards: int,
                       devices: Optional[Sequence] = None) -> Mesh:
    """(data × grid) mesh with a ``grid`` axis of EXACTLY ``n_shards`` —
    the substrate the tree engine's feature-axis sharding requires (the
    ``featureShards`` knob only engages when the active tree mesh's grid
    axis matches the request, see ``models._treefit``). Rows keep
    whatever devices remain on the ``data`` axis, so the histogram psum
    and the column sharding compose on one mesh. Raises like
    :func:`make_mesh` when ``n_shards`` does not divide the device
    count — a silent fallback here would quietly train unsharded."""
    return make_mesh(devices=devices, grid_axis=int(n_shards))


def process_default_mesh() -> Mesh:
    """The process-wide ``(data, grid)`` mesh over ALL visible devices,
    built once and cached — the mainline substrate every heavy phase
    (workflow train, CV sweep, fitstats fold, scoring engine) shares.

    The default split is data-heavy (``grid_axis=1``): row sharding
    scales every phase's throughput with device count, and the row
    dimensions all pad to powers of two (``pad_rows``, the scoring
    bucket ladder, the fitstats chunk) so the power-of-two data axis
    always divides. A grid axis is opt-in via ``set_process_mesh`` /
    the runner's ``customParams.meshGridSize``. On one device this is
    the degenerate ``1×1`` mesh."""
    global _PROCESS_MESH
    if _PROCESS_MESH is None:
        with _PROCESS_MESH_LOCK:
            if _PROCESS_MESH is None:
                _PROCESS_MESH = make_mesh(grid_size=1)
    return _PROCESS_MESH


def set_process_mesh(mesh: Optional[Mesh]) -> Optional[Mesh]:
    """Install ``mesh`` as the process default (``None`` resets so the
    next :func:`process_default_mesh` rebuilds over all devices).
    Returns the previously installed mesh — the runner's run-scoped
    ``meshDevices``/``meshGridSize`` knobs restore it on exit."""
    global _PROCESS_MESH
    with _PROCESS_MESH_LOCK:
        prev = _PROCESS_MESH
        _PROCESS_MESH = mesh
    return prev


def mesh_if_multi(mesh: Optional[Mesh]) -> Optional[Mesh]:
    """``mesh`` when it actually spans more than one device, else None —
    the degenerate ``1×1`` mesh routes consumers onto the exact
    single-device code path (bit-identical, content-cached uploads),
    making the unsharded path the mesh's special case rather than a
    separately maintained fork. ``False`` (the explicit force-unsharded
    sentinel some callers accept) resolves to None too."""
    if mesh is None or mesh is False or not MESH_ENABLED:
        return None
    return mesh if mesh.devices.size > 1 else None


def mesh_topology(mesh: Optional[Mesh] = None) -> Dict[str, Any]:
    """JSON-ready topology of ``mesh`` (default: the process mesh) for
    metrics docs: device count, per-axis sizes, platform."""
    if mesh is None:
        mesh = process_default_mesh()
    devs = mesh.devices.reshape(-1)
    return {"devices": int(devs.size),
            "data": int(mesh.shape.get("data", 1)),
            "grid": int(mesh.shape.get("grid", 1)),
            "platform": getattr(devs[0], "platform", "unknown"),
            "enabled": MESH_ENABLED}


def data_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def pad_rows(X, y, w_folds, multiple: int):
    """Pad the row dimension to a multiple with ZERO-WEIGHT rows.

    Every fit reduction is sample-weighted, so w=0 padding rows are inert —
    this is how ragged row counts meet GSPMD's even-sharding requirement
    without changing any result. Returns (X, y, w_folds, n_original).
    """
    n = X.shape[0]
    pad = (-n) % multiple
    if pad == 0:
        return X, y, w_folds, n
    X = np.concatenate([X, np.zeros((pad, X.shape[1]), dtype=X.dtype)])
    y = np.concatenate([y, np.zeros((pad,), dtype=y.dtype)])
    w_folds = np.concatenate(
        [w_folds, np.zeros((w_folds.shape[0], pad), dtype=w_folds.dtype)],
        axis=1)
    return X, y, w_folds, n


def shard_cv_inputs(mesh: Mesh, X, y, w_folds, extra=None):
    """Place CV inputs: rows over ``data``, fold/grid batches over ``grid``.

    X: [n, d] → P('data', None); y: [n] → P('data');
    w_folds: [K, n] → P('grid', 'data') so each grid-axis shard owns a
    subset of folds and each data-axis shard a subset of rows.
    Rows are zero-weight padded to the data-axis size; the returned
    ``n_orig`` tells callers where to slice device outputs.

    ``extra`` — optional additional [K, n] per-fold mask/weight array
    (e.g. validation-row weights) padded with zeros and sharded like
    ``w_folds``; when given the return is (X, y, w, extra, n_orig).
    """
    import jax.numpy as jnp
    X = np.asarray(X)
    y = np.asarray(y)
    w_folds = np.asarray(w_folds)
    n = X.shape[0]
    X, y, w_folds, n_orig = pad_rows(X, y, w_folds, mesh.shape["data"])
    Xs = jax.device_put(jnp.asarray(X), NamedSharding(mesh, P("data", None)))
    ys = jax.device_put(jnp.asarray(y), NamedSharding(mesh, P("data")))
    k = w_folds.shape[0]
    grid_n = mesh.shape["grid"]
    spec = P("grid", "data") if k % grid_n == 0 else P(None, "data")
    ws = jax.device_put(jnp.asarray(w_folds), NamedSharding(mesh, spec))
    if extra is None:
        return Xs, ys, ws, n_orig
    extra = np.asarray(extra)
    pad = w_folds.shape[1] - n
    if pad:
        extra = np.concatenate(
            [extra, np.zeros((extra.shape[0], pad), dtype=extra.dtype)],
            axis=1)
    es = jax.device_put(jnp.asarray(extra), NamedSharding(mesh, spec))
    return Xs, ys, ws, es, n_orig
