from .mesh import (make_mesh, shard_cv_inputs, data_sharding,  # noqa: F401
                   process_default_mesh, set_process_mesh, mesh_if_multi,
                   mesh_topology)
