from .mesh import make_mesh, shard_cv_inputs, data_sharding  # noqa: F401
