"""Multi-host initialization — DCN-scale runs (v5e pods / multi-slice).

The reference scales out by adding Spark executors over the network; its
communication backend is Spark's netty RPC + shuffle (SURVEY §2.10, §5).
The TPU-native equivalent needs no custom backend at all: once every host
process joins the same JAX runtime, the SAME ``Mesh``/``NamedSharding``
program runs globally — XLA routes collectives over ICI within a slice
and DCN across slices. This module is the (thin) piece that joins the
processes, mirroring ``OpSparkListener``-era cluster bootstrap without a
driver/executor split.

Recipe (each host runs the identical program):

    from transmogrifai_tpu.parallel import multihost, mesh
    multihost.initialize()              # env-driven (TPU pods: automatic)
    m = mesh.make_mesh()                # sees GLOBAL devices
    ... Workflow(...).train() with mesh=m ...

Axis placement for DCN efficiency: put ``data`` (row sharding — fit
reductions are one psum of [d, d] gram / histogram partials, latency
tolerant) across slices, and ``grid`` (the fold × hyperparameter batch,
which communicates nothing until the final argmax) anywhere;
``make_mesh`` already orders axes so data is outermost, which maps
contiguous device blocks (slices) to data shards.
"""
from __future__ import annotations

import os
from typing import Optional

__all__ = ["initialize", "is_distributed", "is_coordinator",
           "process_summary"]

_INITIALIZED = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[list] = None) -> bool:
    """Join this process to the global JAX runtime.

    On Cloud TPU pods all arguments are discovered from the metadata/env
    (``jax.distributed.initialize()`` with no args); elsewhere pass the
    coordinator's ``host:port`` plus this process's rank and the world
    size, or set ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID``. Returns True if a multi-process runtime was
    initialized, False for the single-process (no-op) case. Idempotent.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    on_tpu_pod = os.environ.get("TPU_WORKER_HOSTNAMES") is not None
    if coordinator_address is None and not on_tpu_pod:
        return False                      # single host — nothing to join
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _INITIALIZED = True
    return True


def is_distributed() -> bool:
    import jax
    return jax.process_count() > 1


def is_coordinator() -> bool:
    """True on the single process that should perform shared-filesystem
    writes (model save, metrics sink, checkpoints). Every host runs the
    identical program and computes identical results (GSPMD), so exactly
    one writer suffices — and the crash-consistent checkpoint swap
    explicitly does not support concurrent writers. Always True
    single-process."""
    import jax
    return jax.process_index() == 0


def process_summary() -> dict:
    """Per-process view for logs/metrics sinks (runner observability)."""
    import jax
    return {
        "process_id": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
