"""Compiled batched scoring engine — one device program per model.

Training got five rounds of perf work; scoring still crossed the
host↔device link once per DAG layer (``apply_layer_vectorized`` called
per layer from ``WorkflowModel.transform``) and re-ran ``host_prepare``
bookkeeping every call. This module compiles a fitted model's
transform→predict chain into **one jitted XLA computation**: every
vectorizer's ``device_compute`` across every layer, the vector combiner's
concat, the sanity checker's column gather, and the predictor's
``predict_device`` fuse into a single program, so a scoring batch crosses
the link once — prepared host blocks in, result columns out.

KeystoneML (PAPERS.md) makes the case for whole-pipeline compilation over
per-stage execution for exactly this pipeline shape; tf.data makes the
case for overlapping host-side input preparation with accelerator compute.
Both live here:

* **Bucketed batch shapes** — incoming batches are zero-padded up to a
  small power-of-two ladder (``bucket_ladder``), so arbitrary request
  sizes hit at most O(log(cap)) compiled programs instead of one per
  shape. Batches beyond the cap are chunked through the largest bucket.
  Padding is safe because every fused stage is row-independent (the
  vectorizer/predictor contract); padded rows are sliced off after the
  single device pull.
* **Per-model program cache** — compiled executables live in a bounded
  LRU keyed by (bucket, block signature, outputs), the same discipline as
  ``workflow._LAYER_JIT_CACHE``. Model weights are closed over, so they
  upload once per program, not once per call; the DAG classification
  (host/device split, output metadata wiring) happens once per engine.
* **Overlapped streaming** — :func:`stream_score_overlapped` runs host
  feature extraction of micro-batch k+1 in a worker thread while batch k
  computes on device (tf.data-style software pipelining).

The engine honors the same bandwidth gate as layer fusion
(``workflow.FUSE_MIN_BANDWIDTH_MBPS``): on a slow tunnelled link the
numpy host path stays the right answer, and ``enabled()`` says so.
Since PR 7 that gate is only the *cold-start prior*: an attached
:class:`~transmogrifai_tpu.planner.ExecutionPlan` carries the measured
tier decision (``enabled()`` follows it either way) plus two
bit-identical device-program rewrites — verified CSE merges (a
structurally identical twin's output fans out from ONE computation;
its ``host_prepare``/``device_compute`` never run) and dead-column
pruning (columns the sanity checker drops before any sink are gathered
away right after their producing ``device_compute``, with the select
indices remapped into pruned coordinates).

On a multi-device host each bucket's row-leading blocks are sharded
over the process mesh's ``data`` axis before dispatch (PR 6 — see
docs/performance.md "Multichip execution"), so streaming/batch score
throughput scales with device count; the program cache keys on the mesh
shape so single- and multi-device executables never collide, and the
degenerate single-device mesh takes the unsharded path untouched.

Host/device split rules
-----------------------

A fitted stage is *device-capable* when the engine knows its pure-array
form: ``VectorizerModel`` (``host_prepare`` → ``device_compute``),
``VectorsCombiner`` (concat), ``SanityCheckerModel`` (static column
gather), ``StandardScalerModel`` (affine), and any ``PredictorModel``
implementing ``predict_device``. The fused set is the largest
consumer-closed subset of device-capable stages — a device stage whose
output any host stage consumes is demoted to host, so device values never
have to cross back mid-program. Everything else (row transformers,
lambda stages, text taggers) runs on host first; their columns feed
``host_prepare`` and any direct vector uploads.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import resilience, telemetry

logger = logging.getLogger(__name__)

__all__ = ["ScoringEngine", "bucket_for", "bucket_ladder",
           "stream_score_overlapped", "SCORING_MIN_ROWS",
           "DEFAULT_BUCKET_CAP", "BUCKET_MIN", "engine_cache_stats"]

#: smallest padded batch — below it, padding overhead is noise anyway
BUCKET_MIN = 8

#: default largest compiled batch shape; bigger batches chunk through it
DEFAULT_BUCKET_CAP = 8192

#: ``WorkflowModel.score/transform`` route through the engine only from
#: this many rows (same reasoning as ``workflow.FUSE_MIN_ROWS``: below
#: it, numpy beats compile+pad for one-shot calls). Explicit
#: ``engine=True`` or direct engine use ignores it — a serving loop
#: scoring small batches repeatedly amortizes the compile immediately.
SCORING_MIN_ROWS = 2048

#: compiled programs kept per engine (LRU) — ladder size bounds live
#: entries in practice; the cap guards pathological bucket_cap choices
PROGRAM_CACHE_CAP = 32

#: process-wide program-cache tallies across every engine. Always on
#: (cost is noise next to a device dispatch) so the bench can stamp
#: cache hit/miss evidence on every emitted doc without forcing full
#: telemetry on; the telemetry registry mirrors them when enabled. The
#: module lock keeps concurrent engines' read-modify-writes exact.
#: ``preloads`` counts programs seeded via :meth:`ScoringEngine.preload`
#: (the AOT bank path — they are neither hits nor compiles);
#: ``evictions`` counts LRU drops, so a bank whose ladder outruns
#: PROGRAM_CACHE_CAP shows up in bench docs instead of silently
#: re-JIT-ing.
_CACHE_STATS = {"hits": 0, "misses": 0, "evictions": 0, "preloads": 0}
_CACHE_STATS_LOCK = threading.Lock()


def engine_cache_stats() -> Dict[str, int]:
    """Cumulative scoring-engine program-cache hits/misses (and compiles
    == misses), LRU evictions and AOT-bank preloads across all engines
    in this process."""
    return {"hits": _CACHE_STATS["hits"], "misses": _CACHE_STATS["misses"],
            "compiles": _CACHE_STATS["misses"],
            "evictions": _CACHE_STATS["evictions"],
            "preloads": _CACHE_STATS["preloads"]}


def bucket_for(n: int, cap: int = DEFAULT_BUCKET_CAP) -> int:
    """Smallest ladder bucket holding ``n`` rows (cap-clamped; a
    non-power-of-two cap is itself the top rung, so the result never
    exceeds it)."""
    if n <= BUCKET_MIN:
        return BUCKET_MIN
    if n >= cap:
        return cap
    return min(cap, 1 << (n - 1).bit_length())


def bucket_ladder(cap: int = DEFAULT_BUCKET_CAP) -> List[int]:
    """The full bucket ladder: powers of two from BUCKET_MIN to cap."""
    out = [BUCKET_MIN]
    while out[-1] < cap:
        out.append(min(out[-1] * 2, cap))
    return out


class _FusedStage:
    """One device-resident step of the compiled program."""

    __slots__ = ("model", "kind", "out", "ins")

    def __init__(self, model, kind: str, out: str, ins: List[str]):
        self.model = model
        self.kind = kind      # vec | combine | select | scale | predict
        self.out = out
        self.ins = ins        # env/upload names consumed (no label slots)


def _has_predict_device(m) -> bool:
    """True when ``m.predict_device`` is a real implementation (not the
    PredictorModel stub), following SelectedModel delegation."""
    from .models.base import PredictorModel
    from .models.selector import SelectedModel
    if isinstance(m, SelectedModel):
        return m.inner is not None and _has_predict_device(m.inner)
    fn = type(m).predict_device
    return fn is not PredictorModel.predict_device


def _classify(m) -> Optional[str]:
    """Device-capable kind of a fitted stage, or None (host)."""
    from .models.base import PredictorModel
    from .ops.sanity_checker import SanityCheckerModel
    from .ops.vectorizer_base import VectorizerModel
    from .ops.vectors import StandardScalerModel, VectorsCombiner
    if isinstance(m, VectorizerModel):
        return "vec"
    if isinstance(m, VectorsCombiner):
        return "combine"
    if isinstance(m, SanityCheckerModel):
        return "select"
    if isinstance(m, StandardScalerModel):
        return "scale"
    if isinstance(m, PredictorModel) and _has_predict_device(m):
        return "predict"
    return None


def build_fused_plan(layers) -> Tuple[List["_FusedStage"], List[List[Any]]]:
    """Classify a resolved DAG's fitted stages and compute the largest
    consumer-closed fused set. Returns ``(plan_items, host_layers)`` —
    shared by the engine's program builder and the whole-DAG planner
    (planner.py), so the two can never disagree about what fuses."""
    flat = [m for layer in layers for m in layer]
    kinds = {m.uid: _classify(m) for m in flat}

    # consumer map over output names (host stages read via the store,
    # fused stages via the device env — both count as consumption)
    consumers: Dict[str, List[Any]] = {}
    for m in flat:
        for f in m.input_features:
            consumers.setdefault(f.name, []).append(m)

    # largest consumer-closed fused set: walk shallow→deep demoting
    # device-capable stages any of whose consumers stayed on host
    fused: Dict[str, bool] = {}
    for m in reversed(flat):
        ok = kinds[m.uid] is not None
        if ok:
            for c in consumers.get(m.output_name, []):
                if not fused.get(c.uid, False):
                    ok = False
                    break
        fused[m.uid] = ok

    plan: List[_FusedStage] = []
    host_layers: List[List[Any]] = []
    for layer in layers:
        host_row = []
        for m in layer:
            if not fused[m.uid]:
                host_row.append(m)
                continue
            kind = kinds[m.uid]
            if kind == "vec":
                ins: List[str] = []
            elif kind in ("select", "predict"):
                # (label, vector) arity: only the vector crosses
                ins = [m.input_features[1].name]
            else:
                ins = [f.name for f in m.input_features]
            plan.append(_FusedStage(m, kind, m.output_name, ins))
        host_layers.append(host_row)
    return plan, host_layers


class _PreparedBatch:
    """Host-side output of :meth:`ScoringEngine.prepare_batch`: everything
    the device program needs, already padded to its bucket. Chunked when
    the batch exceeds the bucket cap.

    When a pipeline :class:`~transmogrifai_tpu.pipeline.BufferPool` was
    used for the pad-to-bucket staging, ``buffers`` holds the pooled
    arrays so :meth:`release` can recycle them once the batch has been
    consumed (after the device pull — by then every transfer that read
    them has completed). ``release`` is idempotent."""

    __slots__ = ("chunks", "n_rows", "pool", "buffers")

    def __init__(self, chunks, n_rows: int, pool=None, buffers=None):
        self.chunks = chunks      # [(host_store, prepared, uploads, n, bucket)]
        self.n_rows = n_rows
        self.pool = pool
        self.buffers = list(buffers) if buffers else []

    def release(self) -> None:
        if self.pool is None:
            return
        bufs, self.buffers = self.buffers, []
        for b in bufs:
            self.pool.give(b)


class _StagedChunk:
    """One chunk of a :meth:`ScoringEngine.stage_batch` result: program
    resolved, row-leading blocks already ``device_put`` (sharded over
    the mesh when one applies) — the double-buffered upload stage's
    in-flight unit."""

    __slots__ = ("host_store", "prepared", "uploads", "n", "bucket",
                 "fn", "out_names", "shards", "was_compile")

    def __init__(self, host_store, prepared, uploads, n, bucket, fn,
                 out_names, shards, was_compile):
        self.host_store = host_store
        self.prepared = prepared
        self.uploads = uploads
        self.n = n
        self.bucket = bucket
        self.fn = fn
        self.out_names = out_names      # tuple — must match run_batch's
        self.shards = shards
        self.was_compile = was_compile


class ScoringEngine:
    """Compiled batched scorer for one fitted :class:`WorkflowModel`.

    Build once per model (``model.scoring_engine()`` memoizes); every
    ``score_store``/``transform_store`` call reuses the plan and the
    per-bucket compiled programs.
    """

    def __init__(self, model, bucket_cap: int = DEFAULT_BUCKET_CAP,
                 gate_bandwidth: bool = True, mesh=None, plan=None):
        self.model = model
        self.bucket_cap = int(bucket_cap)
        self.gate_bandwidth = gate_bandwidth
        #: (data, grid) mesh for batch sharding: None resolves to the
        #: process default per dispatch, False forces unsharded
        self._mesh = mesh
        #: optional planner.ExecutionPlan this engine follows: CSE
        #: aliases, dead-column pruning and the measured tier decision
        #: (None = legacy behavior, bandwidth gate only)
        self._exec_plan = plan
        self._programs: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._compile_count = 0
        self._lock = threading.Lock()
        #: id(program) -> executed FLOPs per dispatch, for the MFU
        #: block (telemetry.record_device_work): XLA cost analysis when
        #: the program exposes it (AOT-banked executables), else the
        #: analytic lower bound from the fused plan. id() reuse is
        #: harmless — a new program re-registers before any dispatch
        #: (the models/tuning.DEVICE_FLOPS discipline, generalized).
        self._prog_flops: Dict[int, float] = {}
        #: host_prepare amortization: repeat calls on the SAME ColumnStore
        #: (score → evaluate, warm benchmark reps) skip the whole host
        #: half. Weakref-validated identity keys — a dead or different
        #: store at the same address can never serve stale blocks.
        self._prep_cache: "OrderedDict[Tuple, Tuple[Any, _PreparedBatch]]" \
            = OrderedDict()
        self._build_plan()
        self._apply_exec_plan()

    # -- plan --------------------------------------------------------------
    def _build_plan(self) -> None:
        from .workflow import _raw_features_of
        layers = self.model._resolved_dag()
        plan, host_layers = build_fused_plan(layers)

        produced = {it.out for it in plan}
        upload_names: List[str] = []
        for it in plan:
            for nm in it.ins:
                if nm not in produced and nm not in upload_names:
                    upload_names.append(nm)

        self._host_layers = host_layers
        self._plan = plan
        self._by_out = {it.out: it for it in plan}
        self._fused_out = produced
        self._upload_names = upload_names
        self._result_names = [f.name for f in self.model.result_features]
        self._raw_features = _raw_features_of(self.model.result_features)

    # -- execution-plan application (planner.py) ---------------------------
    def _apply_exec_plan(self) -> None:
        """Translate the attached ExecutionPlan into program-level
        rewrites: CSE output aliases (the dropped stage's host_prepare
        and device_compute never run — its env entry is a fan-out of the
        kept computation), per-vec live-column gathers with the select
        indices remapped into pruned coordinates, and the measured tier
        hint ``enabled()`` consults. Both rewrites are bit-identical by
        construction (verified-identical state; gather-of-concat equals
        concat-of-gathers), and pruning self-disables for any program
        whose requested outputs a prune would visibly narrow."""
        self._cse_alias: Dict[str, str] = {}
        self._prune: Dict[str, np.ndarray] = {}
        self._prune_affected: set = set()
        self._select_keep_remap: Dict[str, np.ndarray] = {}
        self._scale_slice: Dict[str, np.ndarray] = {}
        plan = self._exec_plan
        self._plan_tier = getattr(plan, "engine_tier", None) \
            if plan is not None else None
        if plan is None:
            return
        by_uid = {it.model.uid: it for it in self._plan}
        cse_groups: List[List[str]] = []
        for m in getattr(plan, "cse", ()):
            kept = by_uid.get(m.get("kept"))
            if kept is None or kept.kind != "vec":
                continue
            members = [kept.model.uid]
            for uid in m.get("dropped", ()):
                it = by_uid.get(uid)
                if it is not None and it.kind == "vec" \
                        and it.out != kept.out:
                    self._cse_alias[it.out] = kept.out
                    members.append(uid)
            if len(members) > 1:
                cse_groups.append(members)
        for uid, live in sorted(getattr(plan, "prune", {}).items()):
            it = by_uid.get(uid)
            w = getattr(plan, "widths", {}).get(uid)
            if it is None or it.kind != "vec" or not w:
                continue
            live = np.asarray(live, dtype=np.int64)
            if live.size and live.size < int(w) \
                    and int(live.max()) < int(w) and int(live.min()) >= 0:
                self._prune[uid] = live
        # CSE × pruning: an aliased output IS the kept computation, so
        # every member of a merge group must carry one live set — the
        # union (a fully-live member means no pruning for the group)
        for members in cse_groups:
            lives = [self._prune.get(u) for u in members]
            if all(lv is None for lv in lives):
                continue
            w = by_uid[members[0]].model.vector_metadata().size
            if any(lv is None for lv in lives):
                union: Optional[np.ndarray] = None
            else:
                union = np.asarray(
                    sorted(set(int(j) for lv in lives for j in lv)),
                    dtype=np.int64)
                if union.size >= w:
                    union = None
            for u in members:
                if union is None:
                    self._prune.pop(u, None)
                else:
                    self._prune[u] = union
        if not self._prune:
            return
        pruned_outs = {by_uid[uid].out for uid in self._prune}
        affected = set(pruned_outs)
        for it in self._plan:
            if it.kind in ("combine", "scale") \
                    and any(nm in affected for nm in it.ins):
                affected.add(it.out)
        self._prune_affected = affected

        def _disable(reason: str, uid: str) -> None:
            logger.warning("planner pruning disabled: %s (%s)", reason,
                           uid)
            self._prune = {}
            self._prune_affected = set()
            self._select_keep_remap = {}
            self._scale_slice = {}

        for it in self._plan:
            # only select/scale/combine consumers understand a narrowed
            # input; anything else reading one would see wrong columns
            if it.kind not in ("select", "scale", "combine") \
                    and any(nm in affected for nm in it.ins):
                return _disable("a non-remappable stage consumes a "
                                "pruned value", it.model.uid)
        for it in self._plan:
            if it.kind == "scale" and it.ins[0] in affected:
                # the scaler's fitted mean/std are full-width: slice
                # them to the input's surviving (old) columns so the
                # per-column math is unchanged on what remains
                o2n = self._old_to_new(it.ins[0])
                if o2n is None:
                    return _disable("unresolvable width under a "
                                    "scaler", it.model.uid)
                self._scale_slice[it.model.uid] = \
                    np.nonzero(o2n >= 0)[0]
            if it.kind != "select" or it.ins[0] not in affected:
                continue
            o2n = self._old_to_new(it.ins[0])
            keep = np.asarray(it.model.keep_indices, dtype=np.int64)
            if o2n is None or keep.size and int(keep.max()) >= o2n.size:
                remap = None
            else:
                remap = o2n[keep]
            if remap is None or (remap < 0).any():
                # a kept column the liveness pass missed (or an
                # unresolvable width): pruning must not mis-select —
                # drop it entirely rather than risk a wrong gather
                return _disable("select keeps a column the liveness "
                                "pass marked dead", it.model.uid)
            self._select_keep_remap[it.model.uid] = remap

    def _in_width(self, name: str) -> Optional[int]:
        it = self._by_out.get(name)
        if it is None:
            return None                      # upload: width unknown here
        if it.kind == "vec":
            return it.model.vector_metadata().size
        if it.kind == "combine":
            ws = [self._in_width(nm) for nm in it.ins]
            return sum(ws) if all(w is not None for w in ws) else None
        if it.kind == "select":
            return len(it.model.keep_indices)
        if it.kind == "scale":
            return self._in_width(it.ins[0])
        return None

    def _old_to_new(self, name: str) -> Optional[np.ndarray]:
        """Old→pruned column index map for a fused env value (−1 =
        dead), or None when the value is not narrowed by pruning."""
        it = self._by_out.get(name)
        if it is None:
            return None
        if it.kind == "vec":
            live = self._prune.get(it.model.uid)
            if live is None:
                return None
            w = it.model.vector_metadata().size
            o2n = np.full(w, -1, dtype=np.int64)
            o2n[live] = np.arange(live.size, dtype=np.int64)
            return o2n
        if it.kind == "combine":
            parts = []
            any_pruned = False
            new_off = 0
            for nm in it.ins:
                sub = self._old_to_new(nm)
                w = self._in_width(nm)
                if w is None:
                    return None              # unresolvable width: bail
                if sub is None:
                    sub = np.arange(w, dtype=np.int64)
                else:
                    any_pruned = True
                parts.append(np.where(sub >= 0, sub + new_off, -1))
                new_off += int((sub >= 0).sum())
            return np.concatenate(parts) if any_pruned else None
        if it.kind == "scale":
            # a scaler narrows exactly as its input does (mean/std are
            # sliced to match in the program body)
            return self._old_to_new(it.ins[0])
        return None                # select outputs are never pruned

    def _active_prune(self, out_names) -> Optional[Dict[str, np.ndarray]]:
        """The prune map for a program pulling ``out_names`` — None when
        any requested output would be visibly narrowed (the transform
        path materializes every column; score paths prune freely)."""
        if not self._prune:
            return None
        if any(nm in self._prune_affected for nm in out_names):
            return None
        return self._prune

    # -- introspection -----------------------------------------------------
    @property
    def fused_stage_count(self) -> int:
        return len(self._plan)

    @property
    def covers_prediction(self) -> bool:
        """True when a predictor is inside the fused program (the full
        transform→predict chain runs as one device computation)."""
        return any(it.kind == "predict" for it in self._plan)

    @property
    def compile_count(self) -> int:
        """Programs compiled so far — the bucket-ladder guard metric."""
        return self._compile_count

    def program_budget(self, modes: int = 1) -> int:
        """Max distinct programs the ladder permits per output mode."""
        return len(bucket_ladder(self.bucket_cap)) * modes

    def enabled(self) -> bool:
        """Engine pays off: something fused AND the tier decision says
        device. Precedence: an explicit ``gate_bandwidth=False`` build
        (the caller's force knob) first, then an attached
        ExecutionPlan's measured tier (``device`` overrides a slow-link
        prior, ``host`` wins even on a fast link), then — when the plan
        defers (None) or none is attached — the legacy bandwidth gate
        as the cold-start prior."""
        if not self._plan:
            return False
        if not self.gate_bandwidth:
            # the explicit force knob outranks everything: a caller who
            # built the engine with gate_bandwidth=False owns the tier
            # decision (parity tests, serving export)
            return True
        tier = getattr(self, "_plan_tier", None)
        if tier == "host":
            return False
        if tier == "device":
            return True
        from .workflow import FUSE_MIN_BANDWIDTH_MBPS, device_roundtrip_mbps
        return device_roundtrip_mbps() >= FUSE_MIN_BANDWIDTH_MBPS

    # -- host half ---------------------------------------------------------
    def host_blocks(self, store) -> Tuple[Any, Dict[str, Dict[str, np.ndarray]],
                                          Dict[str, np.ndarray]]:
        """Run every host stage, then every fused vectorizer's
        ``host_prepare`` (canonicalized) + direct vector uploads.
        Returns (host_store, prepared, uploads) — unpadded."""
        from .ops.vectorizer_base import canonicalize_prepared
        for layer in self._host_layers:
            for m in layer:
                store = m.transform(store)
        prepared = {}
        for it in self._plan:
            # a CSE-aliased vectorizer contributes no blocks: its env
            # entry fans out from the kept twin's computation
            if it.kind == "vec" and it.out not in self._cse_alias:
                prepared[it.model.uid] = canonicalize_prepared(
                    it.model.host_prepare(store))
        uploads = {}
        for nm in self._upload_names:
            uploads[nm] = np.asarray(store[nm].values)
        return store, prepared, uploads

    def _raw_store(self, data):
        from .workflow import _generate_raw_store
        from .columns import ColumnStore
        if isinstance(data, ColumnStore):
            # tolerate stores that already carry engineered columns
            missing = [f for f in self._raw_features if f.name not in data]
            if not missing:
                return _generate_raw_store(data, self._raw_features)
            return data
        return _generate_raw_store(data, self._raw_features)

    # -- padding -----------------------------------------------------------
    @staticmethod
    def _pad_rows(a: np.ndarray, n: int, bucket: int) -> np.ndarray:
        """Zero-pad the leading (row) axis from n to bucket. Blocks whose
        leading dim is not the row count (fitted constants riding in
        prepared dicts) pass through untouched."""
        a = np.asarray(a)
        if a.ndim == 0 or a.shape[0] != n or n == bucket:
            return a
        pad = np.zeros((bucket - n,) + a.shape[1:], dtype=a.dtype)
        return np.concatenate([a, pad], axis=0)

    def prepare_batch(self, data, use_cache: bool = True,
                      bucket_min: Optional[int] = None,
                      pool=None) -> _PreparedBatch:
        """Host half of a scoring call, padded to the bucket ladder —
        safe to run in a worker thread (numpy/python only).

        ColumnStore inputs are amortized: re-scoring the same store
        object (score → evaluate, repeated warm calls) reuses the
        prepared blocks instead of re-running host transforms +
        host_prepare. Stores are treated as immutable (the ColumnStore
        API is copy-on-write); ``use_cache=False`` opts out.

        ``bucket_min`` pins the padded bucket to at least that rung
        (cap-clamped): the model server's per-request parity oracle
        scores a lone request through the SAME program its coalesced
        dispatch used, so co-batching is bit-identical by construction,
        not by accident of XLA's per-shape compilation.

        ``pool`` (a ``pipeline.BufferPool``) routes the pad-to-bucket
        staging through reusable pinned buffers instead of fresh
        allocations — the streaming pipeline's churn fix. Pooled
        batches are never prep-cached (their buffers recycle after
        consumption; a cache entry would alias recycled memory), and
        the values written are bit-identical to the allocating path."""
        import weakref

        from .columns import ColumnStore
        cache_key = None
        if pool is not None:
            use_cache = False
        if use_cache and isinstance(data, ColumnStore):
            cache_key = (id(data), data.n_rows, bucket_min)
            with self._lock:
                hit = self._prep_cache.get(cache_key)
            if hit is not None and hit[0]() is data:
                telemetry.counter("scoring.prep_cache_hits").inc()
                return hit[1]
            telemetry.counter("scoring.prep_cache_misses").inc()
        store = self._raw_store(data)
        n_total = store.n_rows
        chunks = []
        taken: List[np.ndarray] = []
        with telemetry.span("score:prepare", rows=n_total):
            for lo in range(0, max(n_total, 1), self.bucket_cap):
                sub = store
                if n_total > self.bucket_cap:
                    hi = min(lo + self.bucket_cap, n_total)
                    sub = store.take(np.arange(lo, hi))
                n = sub.n_rows
                bucket = bucket_for(n, self.bucket_cap)
                if bucket_min is not None:
                    bucket = min(self.bucket_cap,
                                 max(bucket, int(bucket_min)))
                host_store, prepared, uploads = self.host_blocks(sub)
                if pool is not None:
                    def pad(v):
                        return pool.pad_rows(v, n, bucket, taken)
                else:
                    def pad(v):
                        return self._pad_rows(v, n, bucket)
                prepared = {uid: {k: pad(v)
                                  for k, v in blocks.items()}
                            for uid, blocks in prepared.items()}
                uploads = {k: pad(v)
                           for k, v in uploads.items()}
                if telemetry.enabled():
                    # padded bytes about to cross the host→device link
                    nbytes = sum(int(np.asarray(v).nbytes)
                                 for blocks in prepared.values()
                                 for v in blocks.values())
                    nbytes += sum(int(np.asarray(v).nbytes)
                                  for v in uploads.values())
                    telemetry.counter("device.bytes_h2d").inc(nbytes)
                chunks.append((host_store, prepared, uploads, n, bucket))
                if n_total <= self.bucket_cap:
                    break
        pb = _PreparedBatch(chunks, n_total, pool=pool, buffers=taken)
        if cache_key is not None:
            with self._lock:
                self._prep_cache[cache_key] = (weakref.ref(data), pb)
                while len(self._prep_cache) > 4:
                    self._prep_cache.popitem(last=False)
        return pb

    # -- device program ----------------------------------------------------
    def _chunk_mesh(self, bucket: int):
        """The (data, grid) mesh this bucket's dispatch shards over, or
        None. Resolution order: the engine's pinned mesh (``False``
        forces unsharded), else the cached process default; the
        degenerate 1×1 mesh and any bucket the data axis does not divide
        evenly stay unsharded. Power-of-two buckets over a power-of-two
        data axis always divide, so streaming/batch score throughput
        scales with device count on multi-chip hosts."""
        if self._mesh is False:
            return None
        from .parallel.mesh import mesh_if_multi, process_default_mesh
        mesh = mesh_if_multi(self._mesh if self._mesh is not None
                             else process_default_mesh())
        if mesh is None or bucket % mesh.shape["data"] != 0:
            return None
        return mesh

    @staticmethod
    def _mesh_key(mesh) -> Optional[Tuple]:
        return tuple(sorted(mesh.shape.items())) if mesh is not None \
            else None

    def _shard_inputs(self, mesh, prepared, uploads, bucket: int):
        """Row-shard every bucket-leading block over the mesh's ``data``
        axis (fitted constants riding in prepared dicts stay replicated
        — jit broadcasts them). Zero-padded rows are inert by the
        row-independence contract, so sharding them is free."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def place(a):
            a = np.asarray(a)
            if a.ndim == 0 or a.shape[0] != bucket:
                return a
            spec = P("data", *([None] * (a.ndim - 1)))
            return jax.device_put(a, NamedSharding(mesh, spec))
        prepared = {uid: {k: place(v) for k, v in blocks.items()}
                    for uid, blocks in prepared.items()}
        uploads = {k: place(v) for k, v in uploads.items()}
        return prepared, uploads

    def _signature(self, prepared, uploads, out_names,
                   mesh_key: Optional[Tuple] = None) -> Tuple:
        sig = []
        for uid in sorted(prepared):
            for k in sorted(prepared[uid]):
                a = prepared[uid][k]
                sig.append((uid, k, tuple(np.shape(a)), str(np.asarray(a).dtype)))
        for k in sorted(uploads):
            a = uploads[k]
            sig.append(("", k, tuple(np.shape(a)), str(np.asarray(a).dtype)))
        # the mesh shape keys the program: a single-device executable and
        # a data-sharded one must never collide in the cache
        return (tuple(sig), tuple(out_names), mesh_key)

    def _program_body(self, jnp, prepared, uploads, out_names,
                      prune: Optional[Dict[str, np.ndarray]] = None):
        env: Dict[str, Any] = dict(uploads)
        for it in self._plan:
            alias = self._cse_alias.get(it.out)
            if alias is not None:
                # CSE fan-out: the dropped twin's output IS the kept
                # computation (bit-identical state, planner-verified)
                env[it.out] = env[alias]
            elif it.kind == "vec":
                v = it.model.device_compute(jnp, prepared[it.model.uid])
                if prune is not None and it.model.uid in prune:
                    # dead-column prune right at the producer: the
                    # select's remapped indices pick the same survivors
                    v = v[:, np.asarray(prune[it.model.uid],
                                        dtype=np.int32)]
                env[it.out] = v
            elif it.kind == "combine":
                mats = [env[nm] for nm in it.ins]
                env[it.out] = jnp.concatenate(mats, axis=1)
            elif it.kind == "select":
                keep = it.model.keep_indices
                if prune is not None \
                        and it.model.uid in self._select_keep_remap:
                    keep = self._select_keep_remap[it.model.uid].tolist()
                x = env[it.ins[0]]
                if keep == list(range(x.shape[1])):
                    env[it.out] = x
                else:
                    env[it.out] = x[:, np.asarray(keep, dtype=np.int32)]
            elif it.kind == "scale":
                m = it.model
                mean, std = m.mean, m.std
                if prune is not None \
                        and it.model.uid in self._scale_slice:
                    # pruned input: slice the fitted constants to the
                    # surviving columns — per-column math unchanged
                    sl = self._scale_slice[it.model.uid]
                    mean, std = mean[sl], std[sl]
                env[it.out] = ((env[it.ins[0]] - mean[None, :])
                               / std[None, :])
            elif it.kind == "predict":
                env[it.out] = it.model.predict_device(env[it.ins[0]])
        return {nm: env[nm] for nm in out_names}

    def program_callable(self, out_names: List[str]):
        """The pure pytree→pytree program body for ``out_names`` —
        ``run(prepared, uploads) -> {name: array-or-triple}`` with this
        engine's plan rewrites (CSE fan-out, dead-column pruning) baked
        in. Shared by the JIT path (:meth:`_program`) and the AOT bank's
        ahead-of-time ``lower().compile()`` (aot.py), so a banked
        executable and a JIT-on-miss compile can never disagree."""
        prune = self._active_prune(out_names)

        def run(prepared_, uploads_):
            import jax.numpy as jnp
            return self._program_body(jnp, prepared_, uploads_, out_names,
                                      prune=prune)

        return run

    def program_key(self, prepared, uploads, out_names: List[str],
                    mesh_key: Optional[Tuple] = None) -> Tuple:
        """The exact program-cache key :meth:`_program` would use for
        these blocks — the public half of the AOT preload seam: the bank
        computes keys through the engine itself (shapes, dtypes, output
        set, mesh shape AND the plan-rewrite bits), so a preloaded
        program can only ever be served where a JIT compile would have
        produced the identical computation."""
        prune = self._active_prune(out_names)
        return self._signature(prepared, uploads, out_names, mesh_key) \
            + (("plan", bool(self._cse_alias), prune is not None),)

    def preload(self, key: Tuple, fn) -> None:
        """Seed the program cache with an ahead-of-time compiled
        executable under ``key`` (from :meth:`program_key`). Counted as
        a preload — NOT a compile: ``compile_count`` stays untouched, so
        the cold-start guarantee (`compile_count == 0` after a full bank
        load) is assertable. Subject to the same LRU cap as JIT
        programs."""
        with self._lock:
            old = self._programs.pop(key, None)
            if old is not None:
                self._prog_flops.pop(id(old), None)
            self._programs[key] = fn
            with _CACHE_STATS_LOCK:
                _CACHE_STATS["preloads"] += 1
            telemetry.counter("scoring.cache_preloads").inc()
            self._evict_over_cap_locked()

    def programs(self) -> List[Tuple]:
        """Snapshot of the live program-cache keys, LRU-oldest first
        (introspection for the bank and the bench)."""
        with self._lock:
            return list(self._programs.keys())

    def _evict_over_cap_locked(self) -> None:
        """LRU trim (caller holds ``self._lock``); evictions are tallied
        so a bank-evicted program is visible in bench docs. The evicted
        program's FLOP-cache entry goes with it — a GC'd program's id()
        can be reused by a NEW program, which would otherwise inherit
        the dead program's per-dispatch FLOPs into the mfu block."""
        while len(self._programs) > PROGRAM_CACHE_CAP:
            _key, fn = self._programs.popitem(last=False)
            self._prog_flops.pop(id(fn), None)
            with _CACHE_STATS_LOCK:
                _CACHE_STATS["evictions"] += 1
            telemetry.counter("scoring.cache_evictions").inc()

    def _program(self, prepared, uploads, out_names,
                 mesh_key: Optional[Tuple] = None):
        import jax

        key = self.program_key(prepared, uploads, out_names, mesh_key)
        with self._lock:
            fn = self._programs.pop(key, None)
            if fn is not None:
                self._programs[key] = fn      # LRU re-insert
                with _CACHE_STATS_LOCK:
                    _CACHE_STATS["hits"] += 1
                telemetry.counter("scoring.cache_hits").inc()
                return fn

        fn = jax.jit(self.program_callable(out_names))
        with self._lock:
            self._programs[key] = fn
            self._compile_count += 1
            with _CACHE_STATS_LOCK:
                _CACHE_STATS["misses"] += 1
            telemetry.counter("scoring.cache_misses").inc()
            telemetry.counter("scoring.compile_count").inc()
            self._evict_over_cap_locked()
        return fn

    # -- executed-FLOP attribution (the MFU block) -------------------------
    def _analytic_flops(self, bucket: int) -> float:
        """Documented LOWER BOUND on one dispatch's FLOPs from the
        fused plan's static widths: the scale and predict arithmetic is
        counted (2 flops per element for (x−mean)/std, a ×2-output
        matvec for the head), vectorizer internals and nonlinearities
        are not — erring low is the same stance as the Pallas analytic
        estimate (docs/performance.md "MFU")."""
        w: Dict[str, Optional[int]] = {}
        per_row = 0.0
        for it in self._plan:
            if it.kind == "vec":
                w[it.out] = it.model.vector_metadata().size
                per_row += 2.0 * (w[it.out] or 0)
            elif it.kind == "combine":
                w[it.out] = sum(w.get(nm) or 0 for nm in it.ins)
            elif it.kind == "select":
                w[it.out] = len(it.model.keep_indices)
            elif it.kind == "scale":
                w[it.out] = w.get(it.ins[0]) or 0
                per_row += 2.0 * (w[it.out] or 0)
            elif it.kind == "predict":
                per_row += 4.0 * (w.get(it.ins[0]) or 0)
        return per_row * max(int(bucket), 1)

    def _program_flops(self, fn, bucket: int) -> float:
        """Per-dispatch FLOPs for one cached program: XLA cost analysis
        when the program exposes it (deserialized AOT executables),
        else the analytic plan bound — cached by id(fn), the
        models/tuning._register_exe_flops discipline."""
        f = self._prog_flops.get(id(fn))
        if f is None:
            f = 0.0
            try:
                ca = fn.cost_analysis()
                d = ca[0] if isinstance(ca, (list, tuple)) else ca
                f = float(d.get("flops", 0.0))
            except Exception:  # lint: broad-except — cost analysis is best-effort (backend/program-kind dependent)
                f = 0.0
            if f <= 0.0:
                f = self._analytic_flops(bucket)
            if len(self._prog_flops) > 4 * PROGRAM_CACHE_CAP:
                # stale id()s of LRU-evicted programs: a few floats,
                # but never unbounded in a long-lived server
                self._prog_flops.clear()
            self._prog_flops[id(fn)] = f
        return f

    # -- output wiring -----------------------------------------------------
    def _out_names(self, results_only: bool) -> List[str]:
        if results_only:
            return [nm for nm in self._result_names if nm in self._fused_out]
        return [it.out for it in self._plan]

    def _meta_for(self, it: _FusedStage, store, meta_env: Dict[str, Any],
                  width_env: Dict[str, Optional[int]]):
        """Mirror the host stages' vector-metadata wiring (plan shapes are
        model state, so this is pure bookkeeping — no data touched).
        ``width_env`` carries each env value's column count so the
        combiner's provenance-lost guard (metadata size != matrix width →
        metadata None, data kept correct) holds here too."""
        from .vector_metadata import VectorMetadata

        def in_meta(nm):
            if nm in meta_env:
                return meta_env[nm]
            col = store[nm] if nm in store else None
            return getattr(col, "metadata", None)

        if it.kind == "vec":
            return it.model.vector_metadata()
        if it.kind == "combine":
            metas = []
            for f, nm in zip(it.model.input_features, it.ins):
                metas.append(in_meta(nm) or VectorMetadata(f.name, []))
            meta = VectorMetadata.flatten(it.out, metas)
            width = width_env.get(it.out)
            if width is not None and meta.size != width:
                return None      # provenance lost for some inputs
            return meta
        if it.kind == "select":
            meta = in_meta(it.ins[0])
            if meta is None:
                return None
            meta = meta.select(it.model.keep_indices)
            meta.name = it.out
            return meta
        if it.kind == "scale":
            return in_meta(it.ins[0])
        return None

    def _width_env(self, store) -> Dict[str, Optional[int]]:
        """Column count of every fused env value, derived from model
        state + upload shapes (None = unknown)."""
        w: Dict[str, Optional[int]] = {}
        for nm in self._upload_names:
            vals = getattr(store[nm], "values", None) if nm in store else None
            w[nm] = (int(vals.shape[1])
                     if vals is not None and np.ndim(vals) == 2 else None)
        for it in self._plan:
            if it.kind == "vec":
                w[it.out] = it.model.vector_metadata().size
            elif it.kind == "combine":
                ins = [w.get(nm) for nm in it.ins]
                w[it.out] = (sum(ins) if all(x is not None for x in ins)
                             else None)
            elif it.kind == "select":
                w[it.out] = len(it.model.keep_indices)
            elif it.kind == "scale":
                w[it.out] = w.get(it.ins[0])
            else:
                w[it.out] = None
        return w

    def stage_batch(self, prep: _PreparedBatch,
                    results_only: bool = True) -> _PreparedBatch:
        """The double-buffered upload stage: resolve each chunk's
        program and issue its row-leading blocks' ``device_put`` NOW —
        ``jax.device_put`` is asynchronous, so the transfers drain in
        the background while the consumer is still computing the
        previous batch. ``run_batch`` on the returned batch skips
        resolution/sharding and dispatches the staged program directly
        (``results_only`` must match — asserted there).

        Pool buffers (the pinned staging arrays) move to the staged
        batch; they recycle only after ITS device pull, by which point
        every transfer that read them has completed."""
        import jax

        out_names = tuple(self._out_names(results_only))
        staged = []
        for host_store, prepared, uploads, n, bucket in prep.chunks:
            if not out_names:
                staged.append((host_store, prepared, uploads, n, bucket))
                continue
            resilience.inject("pipeline.upload", rows=n, bucket=bucket)
            mesh = self._chunk_mesh(bucket)
            before = self._compile_count
            # key/resolve off the HOST blocks before any placement
            fn = self._program(prepared, uploads, list(out_names),
                               self._mesh_key(mesh))
            was_compile = self._compile_count > before
            with telemetry.span("pipeline:upload", rows=n, bucket=bucket,
                                sharded=mesh is not None):
                if mesh is not None:
                    prepared, uploads = self._shard_inputs(
                        mesh, prepared, uploads, bucket)
                    shards = mesh.shape["data"]
                else:
                    def place(a):
                        arr = np.asarray(a)
                        if arr.ndim == 0 or arr.shape[0] != bucket:
                            return a          # fitted constant: replicated by jit
                        return jax.device_put(arr)
                    prepared = {uid: {k: place(v)
                                      for k, v in blocks.items()}
                                for uid, blocks in prepared.items()}
                    uploads = {k: place(v) for k, v in uploads.items()}
                    shards = 1
            staged.append(_StagedChunk(host_store, prepared, uploads, n,
                                       bucket, fn, out_names, shards,
                                       was_compile))
        from . import pipeline as _pl
        # only chunks whose device_put was actually issued count — with
        # no engine outputs the chunks ride through as plain tuples
        n_uploads = len(staged) if out_names else 0
        _pl._tally("staged_uploads", n_uploads)
        telemetry.counter("pipeline.staged_uploads").inc(n_uploads)
        out = _PreparedBatch(staged, prep.n_rows, pool=prep.pool,
                             buffers=prep.buffers)
        prep.buffers = []          # ownership moved: no double-recycle
        return out

    def run_batch(self, prep: _PreparedBatch, results_only: bool = True):
        """Device half: one jitted dispatch + one pull per chunk, then
        column wrapping. Returns a ColumnStore. Accepts both plain
        prepared batches (program resolved + uploaded here) and
        :meth:`stage_batch` output (uploads already in flight); pooled
        staging buffers are recycled on the way out either way."""
        out_names = self._out_names(results_only)
        try:
            stores = self._run_chunks(prep, out_names, results_only)
        finally:
            prep.release()
        if len(stores) == 1:
            return stores[0]
        return _concat_stores(stores)

    def _run_chunks(self, prep: _PreparedBatch, out_names, results_only):
        import jax

        from .columns import ColumnStore, PredictionColumn, VectorColumn
        from .types.feature_types import OPVector

        stores = []
        for chunk in prep.chunks:
            is_staged = isinstance(chunk, _StagedChunk)
            if is_staged:
                host_store, prepared, uploads = (chunk.host_store,
                                                 chunk.prepared,
                                                 chunk.uploads)
                n, bucket = chunk.n, chunk.bucket
                if chunk.out_names != tuple(out_names):
                    raise ValueError(
                        "stage_batch/run_batch results_only mismatch: "
                        f"staged for {chunk.out_names}, running "
                        f"{tuple(out_names)}")
            else:
                host_store, prepared, uploads, n, bucket = chunk
            t0 = time.perf_counter()
            was_compile = False
            resilience.inject("scoring.device_dispatch", rows=n,
                              bucket=bucket)
            if out_names and is_staged:
                was_compile = chunk.was_compile
                with telemetry.span("score:bucket", rows=n, bucket=bucket,
                                    compiled=was_compile, staged=True,
                                    data_shards=chunk.shards):
                    t_d0 = time.perf_counter()
                    outs = jax.device_get(chunk.fn(prepared, uploads))
                    if not was_compile:
                        # warm dispatches only: a compile riding the
                        # first call must not pollute the MFU
                        # denominator (docs/observability.md "MFU")
                        telemetry.record_device_work(
                            "scoring",
                            flops=self._program_flops(chunk.fn, bucket),
                            seconds=time.perf_counter() - t_d0)
            elif out_names:
                mesh = self._chunk_mesh(bucket)
                before = self._compile_count
                # key the program off the HOST blocks (shapes/dtypes are
                # sharding-invariant) — hashing sharded device arrays
                # would pull them back across the link
                fn = self._program(prepared, uploads, out_names,
                                   self._mesh_key(mesh))
                was_compile = self._compile_count > before
                if mesh is not None:
                    prepared, uploads = self._shard_inputs(
                        mesh, prepared, uploads, bucket)
                with telemetry.span("score:bucket", rows=n, bucket=bucket,
                                    compiled=was_compile,
                                    data_shards=(mesh.shape["data"]
                                                 if mesh is not None else 1)):
                    t_d0 = time.perf_counter()
                    outs = jax.device_get(fn(prepared, uploads))  # one pull
                    if not was_compile:
                        # warm dispatches only (see the staged branch)
                        telemetry.record_device_work(
                            "scoring",
                            flops=self._program_flops(fn, bucket),
                            seconds=time.perf_counter() - t_d0)
            else:
                outs = {}
            store = host_store
            meta_env: Dict[str, Any] = {}
            width_env = self._width_env(host_store)
            by_out = {it.out: it for it in self._plan}
            for it in self._plan:
                if it.out in out_names or it.kind in ("vec", "combine",
                                                      "select", "scale"):
                    meta_env[it.out] = self._meta_for(it, host_store,
                                                      meta_env, width_env)
            for nm in out_names:
                it = by_out[nm]
                val = outs[nm]
                if it.kind == "predict":
                    pred, raw, prob = (np.asarray(v, dtype=np.float64)[:n]
                                       for v in val)
                    store = store.with_column(
                        nm, PredictionColumn(pred, raw, prob))
                else:
                    mat = np.asarray(val)[:n]
                    store = store.with_column(
                        nm, VectorColumn(OPVector, mat, meta_env.get(nm)))
            chunk_s = time.perf_counter() - t0
            if telemetry.enabled():
                telemetry.counter("scoring.rows_scored").inc(n)
                telemetry.histogram("scoring.batch_seconds").observe(chunk_s)
                telemetry.emit("score_batch", n_rows=n, bucket=bucket,
                               seconds=chunk_s, compiled=was_compile)
            logger.debug("scoring engine: %d rows (bucket %d) in %.1fms",
                         n, bucket, 1e3 * chunk_s)
            if results_only and len(prep.chunks) > 1:
                # chunk-stitching only needs the result columns — raw
                # host columns (maps, ragged lists) never concatenate
                store = store.select([nm for nm in self._result_names
                                      if nm in store])
            stores.append(store)
        return stores

    # -- public scoring ----------------------------------------------------
    def transform_store(self, data, use_cache: bool = True):
        """Engine analog of ``WorkflowModel.transform``: every DAG column
        materialized (host columns + all fused outputs), one crossing."""
        return self.run_batch(self.prepare_batch(data, use_cache=use_cache),
                              results_only=False)

    def score_store(self, data, keep_intermediate: bool = False,
                    use_cache: bool = True,
                    bucket_min: Optional[int] = None):
        """Engine analog of ``WorkflowModel.score``: only result columns
        are pulled off the device. ``bucket_min`` pins the padded bucket
        (see :meth:`prepare_batch`)."""
        if keep_intermediate:
            return self.transform_store(data, use_cache=use_cache)
        store = self.run_batch(self.prepare_batch(data, use_cache=use_cache,
                                                  bucket_min=bucket_min),
                               results_only=True)
        return store.select([nm for nm in self._result_names
                             if nm in store])

    # -- export ------------------------------------------------------------
    def export_manifest(self, sample_data):
        """Flat input manifest for StableHLO export: per-block tail
        shapes/dtypes in a fixed order, from one sample host pass. All
        blocks must be row-leading (batch-polymorphic export pads
        nothing)."""
        store = self._raw_store(sample_data)
        n = store.n_rows
        _, prepared, uploads = self.host_blocks(store)
        manifest = []
        for uid in sorted(prepared):
            for k in sorted(prepared[uid]):
                a = np.asarray(prepared[uid][k])
                if a.ndim == 0 or a.shape[0] != n:
                    raise ValueError(
                        f"prepared block {uid}/{k} is not row-leading "
                        f"(shape {a.shape}); full-chain export needs every "
                        "input batch-polymorphic")
                manifest.append({"kind": "prepared", "uid": uid, "name": k,
                                 "tail": list(a.shape[1:]),
                                 "dtype": str(a.dtype)})
        for k in sorted(uploads):
            a = np.asarray(uploads[k])
            if a.ndim == 0 or a.shape[0] != n:
                raise ValueError(f"upload {k} is not row-leading")
            manifest.append({"kind": "upload", "uid": "", "name": k,
                             "tail": list(a.shape[1:]),
                             "dtype": str(a.dtype)})
        return manifest

    def rewrite_digest(self) -> str:
        """blake2b-128 over the plan rewrites baked into this engine's
        programs (CSE aliases, per-vec live sets, remapped select
        indices, sliced scaler constants) plus the fused-plan structure.
        An AOT bank records it at export; a serve-time engine whose
        rewrites differ (different attached ExecutionPlan) must NOT
        serve the banked executables — the baked gathers would produce
        different columns — so the loader compares digests and falls
        back to JIT on mismatch."""
        import hashlib
        h = hashlib.blake2b(digest_size=16)
        for it in self._plan:
            h.update(f"{it.kind}|{it.model.uid}|{it.out}|"
                     f"{','.join(it.ins)};".encode())
        for k in sorted(self._cse_alias):
            h.update(f"cse:{k}->{self._cse_alias[k]};".encode())
        for uid in sorted(self._prune):
            h.update(f"prune:{uid}:".encode())
            h.update(np.asarray(self._prune[uid], np.int64).tobytes())
        for uid in sorted(self._select_keep_remap):
            h.update(f"remap:{uid}:".encode())
            h.update(np.asarray(self._select_keep_remap[uid],
                                np.int64).tobytes())
        for uid in sorted(self._scale_slice):
            h.update(f"slice:{uid}:".encode())
            h.update(np.asarray(self._scale_slice[uid],
                                np.int64).tobytes())
        return h.hexdigest()

    def state_digest(self) -> str:
        """blake2b-128 over the fused stages' fitted ARRAY state: every
        numpy/jax array leaf reachable through public attributes
        (sorted by path, shallow object recursion). The banked
        executables close over these weights, so the bank manifest
        records this digest and the loader refuses (advisory, JIT
        fallback) when the serve-time model's arrays differ — a
        retrained model with coincidentally identical uids/shapes must
        never be served stale weights. Only array LEAVES are hashed:
        bookkeeping that legitimately differs across a save/load
        roundtrip (ctor params, selector summaries, private caches)
        must not poison the digest, so non-array values and
        underscore-private attributes are skipped."""
        import hashlib
        h = hashlib.blake2b(digest_size=16)

        def leaves(obj, path: str, depth: int, out) -> None:
            if isinstance(obj, np.ndarray):
                if obj.dtype != object:
                    out.append((path, obj))
                return
            if hasattr(obj, "__array__") and hasattr(obj, "dtype"):
                leaves(np.asarray(obj), path, depth, out)  # jax arrays
                return
            if depth <= 0:
                return
            if isinstance(obj, (list, tuple)):
                for i, v in enumerate(obj):
                    leaves(v, f"{path}[{i}]", depth - 1, out)
                return
            if isinstance(obj, dict):
                for k in sorted(obj, key=str):
                    leaves(obj[k], f"{path}.{k}", depth - 1, out)
                return
            d = getattr(obj, "__dict__", None)
            if isinstance(d, dict):
                for k in sorted(d):
                    if not k.startswith("_"):
                        leaves(d[k], f"{path}.{k}", depth - 1, out)

        for it in self._plan:
            out: List[Tuple[str, np.ndarray]] = []
            leaves(it.model, "", 3, out)
            h.update(f"{it.kind}|{it.model.uid}|".encode())
            for path, a in sorted(out, key=lambda kv: kv[0]):
                h.update(path.encode())
                h.update(str(a.dtype).encode())
                h.update(str(a.shape).encode())
                h.update(np.ascontiguousarray(a).tobytes())
        return h.hexdigest()

    def export_callable(self, manifest, out_names):
        """Flat-arg callable over ``manifest`` order, for jax.export."""
        def flat_fn(*blocks):
            import jax.numpy as jnp
            prepared: Dict[str, Dict[str, Any]] = {}
            uploads: Dict[str, Any] = {}
            for spec, a in zip(manifest, blocks):
                if spec["kind"] == "prepared":
                    prepared.setdefault(spec["uid"], {})[spec["name"]] = a
                else:
                    uploads[spec["name"]] = a
            return self._program_body(jnp, prepared, uploads, out_names)
        return flat_fn


def _concat_stores(stores):
    """Row-concatenate per-chunk stores. Covers the column kinds the
    engine emits (prediction/vector) plus the dense host kinds; exotic
    host columns (maps) raise — the workflow's transform routing catches
    that and replays the per-layer path."""
    from .columns import (ColumnStore, GeoColumn, NumericColumn,
                          PredictionColumn, RaggedColumn, TextColumn,
                          TextListColumn, TextSetColumn, VectorColumn)
    first = stores[0]
    cols = {}
    for nm in first.names():
        parts = [s[nm] for s in stores]
        c0 = parts[0]
        if isinstance(c0, PredictionColumn):
            cols[nm] = PredictionColumn(
                np.concatenate([p.prediction for p in parts]),
                np.concatenate([p.raw_prediction for p in parts]),
                np.concatenate([p.probability for p in parts]))
        elif isinstance(c0, VectorColumn):
            cols[nm] = VectorColumn(
                c0.ftype, np.concatenate([p.values for p in parts]),
                c0.metadata)
        elif isinstance(c0, NumericColumn):
            cols[nm] = NumericColumn(
                c0.ftype, np.concatenate([p.values for p in parts]),
                np.concatenate([p.mask for p in parts]), c0.labels)
        elif isinstance(c0, TextColumn):
            cols[nm] = TextColumn(
                c0.ftype, np.concatenate([p.values for p in parts]))
        elif isinstance(c0, (TextListColumn, TextSetColumn)):
            vals = [v for p in parts for v in p.values]
            cols[nm] = type(c0)(c0.ftype, vals)
        elif isinstance(c0, GeoColumn):
            cols[nm] = GeoColumn(
                c0.ftype, np.concatenate([p.values for p in parts]),
                np.concatenate([p.mask for p in parts]))
        elif isinstance(c0, RaggedColumn):
            flat = np.concatenate([p.flat for p in parts])
            lengths = np.concatenate(
                [np.diff(p.offsets) for p in parts])
            offsets = np.concatenate([[0], np.cumsum(lengths)])
            cols[nm] = RaggedColumn(c0.ftype, flat,
                                    offsets.astype(np.int64))
        else:
            raise TypeError(
                f"cannot row-concatenate column {nm!r} "
                f"({type(c0).__name__}) across scoring chunks")
    return ColumnStore(cols, sum(s.n_rows for s in stores))


def stream_score_overlapped(model, batches, keep_intermediate: bool = False,
                            engine: Optional[ScoringEngine] = None,
                            on_error: Optional[str] = None,
                            workers: Optional[int] = None,
                            prefetch: Optional[int] = None):
    """Pipelined streaming score — the tf.data-staged serving path.

    Three stages run concurrently (pipeline.py):

    1. **parallel host prep** — record→columns, host transforms,
       ``host_prepare`` and pad-to-bucket (through a reusable pinned
       :class:`~transmogrifai_tpu.pipeline.BufferPool`) run on a named
       worker pool (``workers``, default ``pipeline.DEFAULT_WORKERS``)
       with DETERMINISTIC output order — N-worker output is
       bit-identical to the serial loop, in content and order;
    2. **autotuned prefetch** — the in-flight depth starts at 2, grows
       while the consumer starves and shrinks when it never does
       (``prefetch`` caps it; ``pipeline.PrefetchAutotuner``);
    3. **double-buffered upload** — batch k+1's ``device_put`` is
       issued (:meth:`ScoringEngine.stage_batch`) BEFORE batch k's
       result is pulled, so the host→device transfer overlaps device
       compute.

    Yields one scored ColumnStore per batch, same contract as
    ``readers.stream_score``. Falls back to the plain per-batch path
    when the engine is missing or gated off (slow link).

    ``on_error="quarantine"`` routes a batch whose prep raises to the
    resilience dead-letter sink and keeps the pipeline flowing (same
    contract as ``readers.stream_score``, including the sink-aware
    ``None`` default and the first-batch-always-raises rule — batches
    are consumed in order, so index 0 still fails loudly whatever the
    worker count). A DEVICE compute (or staged upload) failure is
    handled as a tier failure, not data poison: it reports to the
    model's scoring-engine circuit breaker and the batch retries on the
    per-layer host path — only a batch that BOTH tiers reject is
    quarantined. With the breaker open, remaining batches route
    straight to the host path (the stream keeps scoring, without
    re-paying a failing dispatch per batch).

    Telemetry (when enabled): each prep worker and the consumer land on
    their own trace tracks (``pipeline:host_prep`` spans vs
    ``stream:device_compute``/``pipeline:upload`` — the overlap is
    visible in Perfetto), ``pipeline.queue_depth`` /
    ``pipeline.prefetch_depth`` gauges track the pipeline's state live,
    and the run records the occupancy gauges —
    ``stream.host_occupancy`` / ``stream.device_occupancy`` (busy
    fraction of the stream's wall-clock per side) and
    ``stream.overlap_efficiency`` (achieved fraction of the ideal
    overlap: ``(host_s + device_s - wall) / min(host_s, device_s)``).
    The always-on ``pipeline.pipeline_stats()`` tallies record the
    converged prefetch depth and buffer reuse either way."""
    import itertools
    import threading

    from . import pipeline as pl

    on_error = resilience.resolve_on_error(on_error)
    eng = engine if engine is not None else model.scoring_engine()
    if eng is None or not eng.enabled():
        for i, batch in enumerate(batches):
            try:
                yield model.score(pl.concrete_batch(batch),
                                  keep_intermediate=keep_intermediate)
            except Exception as e:  # lint: broad-except — poison batch quarantines (no-engine path)
                resilience.quarantine_batch_or_raise(on_error, i, e,
                                                     batch)
        return

    it = iter(batches)
    first = next(it, None)
    if first is None:
        return
    chained = itertools.chain([first], it)
    tel = telemetry.enabled()
    n_workers = pl.resolve_workers(workers)
    tuner = pl.PrefetchAutotuner(
        max_depth=(int(prefetch) if prefetch is not None
                   else pl.DEFAULT_MAX_PREFETCH))
    pool = pl.BufferPool()
    # host prep busy-span: the UNION of worker-active intervals, not
    # the per-worker sum — with N workers summed seconds exceed wall
    # and would saturate the occupancy/overlap gauges at any worker
    # count, making the headline overlap_efficiency trivially 1.0
    host_busy = [0.0]
    host_active = [0]
    host_t0 = [0.0]
    host_lock = threading.Lock()
    device_s = 0.0
    n_batches = 0
    results_only = not keep_intermediate
    t_start = time.perf_counter()

    def _prep(item):
        _i, batch = item
        resilience.inject("stream.score_batch", rows=len(batch))
        if not tel:
            return eng.prepare_batch(batch, use_cache=False, pool=pool)
        with host_lock:
            if host_active[0] == 0:
                host_t0[0] = time.perf_counter()
            host_active[0] += 1
        with telemetry.span("pipeline:host_prep", rows=len(batch)):
            try:
                return eng.prepare_batch(batch, use_cache=False,
                                         pool=pool)
            finally:
                with host_lock:
                    host_active[0] -= 1
                    if host_active[0] == 0:
                        host_busy[0] += (time.perf_counter()
                                         - host_t0[0])

    brk_fn = getattr(model, "_engine_breaker", None)
    brk = brk_fn() if callable(brk_fn) else None

    def _staged_stream():
        """Order-preserving prep results, each batch's uploads issued
        one step AHEAD of its consumption: when the consumer computes
        batch k, batch k+1's device transfers are already in flight.
        The breaker is consulted HERE, before the upload — with it open
        a batch skips ``stage_batch`` entirely and rides straight to
        the host fallback; the single ``allow()`` call per batch also
        keeps half-open probe accounting honest (one probe handed out,
        reported once by the consumer's success/failure record). Note
        the one-batch skew inherent to staging ahead: batch k+1's
        upload is issued before the consumer records batch k's outcome,
        so the trip that opens the breaker can land AFTER one more
        upload has already gone out — open means at most one straggler,
        then no further device_put until the reset timeout."""
        pending = None
        items = ((i, pl.concrete_batch(b)) for i, b in enumerate(chained))
        results = pl.map_ordered(_prep, items, workers=n_workers,
                                 tuner=tuner, name="score-prep")
        while True:
            try:
                (i, batch), prep, exc = next(results)
            except StopIteration:
                break
            except Exception:  # lint: broad-except — flushed and re-raised, nothing swallowed
                # the batch SOURCE raised (per-item decode faults ride
                # in order as `exc` instead): flush the already-prepped
                # pending batch first so every batch produced before
                # the failure is scored, like the serial path, then
                # surface the error
                if pending is not None:
                    yield pending
                    pending = None
                raise
            staged, stage_exc = None, None
            if exc is None and (brk is None or brk.allow()):
                try:
                    staged = eng.stage_batch(prep,
                                             results_only=results_only)
                except Exception as e:  # lint: broad-except — upload failure is a tier failure (handled by the consumer)
                    stage_exc = e
            if pending is not None:
                yield pending
            pending = (i, batch, prep, staged, exc, stage_exc)
        if pending is not None:
            yield pending

    try:
        for i, batch, prep, staged, exc, stage_exc in _staged_stream():
            if exc is not None:
                resilience.quarantine_batch_or_raise(on_error, i, exc,
                                                     batch)
                continue
            # a device/upload failure is a TIER failure, not data
            # poison: report it to the model's engine breaker and retry
            # the batch on the per-layer host path; a batch the breaker
            # refused arrives with staged=None (the upload was never
            # issued) and falls straight through to the host path
            store = None
            if stage_exc is not None:
                if brk is not None:
                    brk.record_failure()
                logger.warning(
                    "staged upload failed (%r); batch %d retries on "
                    "the host path", stage_exc, i)
            elif staged is not None:
                t0 = time.perf_counter()
                try:
                    with telemetry.span("stream:device_compute",
                                        rows=prep.n_rows):
                        store = eng.run_batch(staged,
                                              results_only=results_only)
                    if brk is not None:
                        brk.record_success()
                except Exception:  # lint: broad-except — breaker-governed device-tier fallback
                    if brk is not None:
                        brk.record_failure()
                    logger.exception(
                        "overlapped device compute failed; batch "
                        "%d retries on the host path", i)
                finally:
                    device_s += time.perf_counter() - t0
            if store is None:
                (staged if staged is not None else prep).release()
                try:
                    store = model.score(
                        batch,
                        keep_intermediate=keep_intermediate,
                        engine=False)
                except Exception as e:  # lint: broad-except — both tiers rejected: batch quarantines
                    # both tiers rejected it: now it is poison
                    resilience.quarantine_batch_or_raise(
                        on_error, i, e, batch, rows=prep.n_rows)
                    continue
            n_batches += 1
            if results_only:
                store = store.select([nm for nm in eng._result_names
                                      if nm in store])
            yield store
    finally:
        pl.record_stream(n_batches, n_workers, tuner=tuner, pool=pool)
        if tel:
            wall = max(time.perf_counter() - t_start, 1e-9)
            telemetry.counter("stream.batches").inc(n_batches)
            telemetry.gauge("pipeline.queue_depth").set(0)
            telemetry.gauge("stream.host_occupancy").set(
                min(host_busy[0] / wall, 1.0))
            telemetry.gauge("stream.device_occupancy").set(
                min(device_s / wall, 1.0))
            ideal = min(host_busy[0], device_s)
            eff = ((host_busy[0] + device_s - wall) / ideal
                   if ideal > 0 else 0.0)
            telemetry.gauge("stream.overlap_efficiency").set(
                max(0.0, min(eff, 1.0)))
