"""big_text quality anchor (VERDICT r2 #8): deterministic text-dependent
labels give AuPR a real target, and the Transmogrifier defaults match the
reference's constants (Transmogrifier.scala:52-88)."""
import os
import sys

import pytest


def test_transmogrifier_defaults_match_reference():
    """Pin our defaults to Transmogrifier.scala:52-88 — a silent drift in
    TopK/MinSupport/hash dims changes every AutoML vector."""
    from transmogrifai_tpu.ops.vectorizer_base import TransmogrifierDefaults as D

    assert D.TOP_K == 20                      # TopK
    assert D.MIN_SUPPORT == 10                # MinSupport
    assert D.HASH_SIZE == 512                 # DefaultNumOfFeatures
    assert D.MAX_NUM_FEATURES == 16384        # MaxNumOfFeatures
    assert D.FILL_VALUE == 0                  # FillValue
    assert D.BINARY_FILL_VALUE == 0.0         # BinaryFillValue (false)
    assert D.FILL_WITH_MEAN is True           # FillWithMean
    assert D.FILL_WITH_MODE is True           # FillWithMode
    assert D.TRACK_NULLS is True              # TrackNulls
    assert D.TRACK_INVALID is False           # TrackInvalid
    assert D.MIN_DOC_FREQUENCY == 0           # MinDocFrequency
    assert D.OTHER_STRING == "OTHER"          # OtherString
    assert D.NULL_STRING == "NullIndicatorValue"  # OpVectorColumnMetadata
    assert D.CIRCULAR_DATE_REPRESENTATIONS == [
        "HourOfDay", "DayOfWeek", "DayOfMonth", "DayOfYear"]


def test_big_text_deterministic_quality():
    """The BigPassenger-schema config trains against a deterministic
    text-dependent rule: AuPR must clear TARGET_AUPR (a pipeline that
    drops or mangles the hashed text path fails this hard)."""
    examples = os.path.join(os.path.dirname(__file__), os.pardir, "examples")
    sys.path.insert(0, examples)
    try:
        from big_passenger import TARGET_AUPR, run
    finally:
        sys.path.remove(examples)
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily

    out = run(n_rows=6000, num_folds=2,
              families=[LogisticRegressionFamily()], mesh=False, seed=11)
    aupr = float(out["metrics"]["AuPR"])
    assert aupr >= TARGET_AUPR, f"big_text AuPR {aupr} below {TARGET_AUPR}"
