"""Model lifecycle tier tests (lifecycle.py + server.py rollouts).

The lifecycle correctness contract: a version NAMES fitted weights (the
AOT state digest), the ``current`` pointer swap is atomic under any
crash (fresh-interpreter verified), the serving-time drift sentinel
flags a shifted stream within one sliding window without ever touching
the score path's results, shadow/canary rollouts keep non-canaried
traffic bit-identical to solo scoring, automated promotion moves the
pointer only after clean windows, and automated rollback under an
injected ``lifecycle.promote`` fault drops zero requests.
"""
import json
import subprocess
import sys
import textwrap
from types import SimpleNamespace

import numpy as np
import pytest

from transmogrifai_tpu import (FeatureBuilder, Workflow, lifecycle, lint,
                               resilience, serving, telemetry)
from transmogrifai_tpu import server as server_mod
from transmogrifai_tpu.features import Feature
from transmogrifai_tpu.filters.distribution import (FeatureDistribution,
                                                    Summary,
                                                    distributions_of_column)
from transmogrifai_tpu.filters.raw_feature_filter import RawFeatureFilter
from transmogrifai_tpu.lifecycle import (DriftSentinel, ModelRegistry,
                                         RegistryError, version_of_export)
from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                      LogisticRegressionFamily)
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.server import (ModelNotFound, ModelServer,
                                      RolloutError, serve_http)
from transmogrifai_tpu.workflow import WorkflowModel, _generate_raw_store

BUCKET_CAP = 64


def _train(seed, n=200):
    rng = np.random.default_rng(seed)
    y = np.asarray([i % 2 for i in range(n)], float)
    rng.shuffle(y)
    records = [{"label": float(y[i]),
                "x1": float(rng.normal() + y[i]),
                "x2": float(rng.normal())} for i in range(n)]
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
    f2 = FeatureBuilder.Real("x2").from_column().as_predictor()
    vec = transmogrify([f1, f2])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=seed)
    pred = label.transform_with(sel, vec)
    model = (Workflow().set_input_records(records)
             .with_raw_feature_filter(RawFeatureFilter(bins=20))
             .set_result_features(pred).train())
    return model, records, pred


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    """Two trained versions of ONE model (RawFeatureFilter attached so
    train-time distributions persist), saved + AOT-exported + registered
    with v1 promoted."""
    reg_dir = str(tmp_path_factory.mktemp("registry"))
    reg = ModelRegistry(reg_dir)
    out = {"registry": reg, "registry_dir": reg_dir, "versions": {}}
    for tag, seed in (("v1", 11), ("v2", 12)):
        model, records, pred = _train(seed)
        mdir = str(tmp_path_factory.mktemp(f"model_{tag}"))
        edir = str(tmp_path_factory.mktemp(f"export_{tag}"))
        model.save(mdir, overwrite=True)
        serving.export_scoring_fn(model, edir, records[:8],
                                  bucket_cap=BUCKET_CAP)
        vid = reg.register("churn", mdir, bank_dir=edir,
                           train_metrics={"seed": seed},
                           promote=(tag == "v1"))
        out[tag] = {"model": model, "records": records, "pred": pred,
                    "model_dir": mdir, "export_dir": edir, "vid": vid}
        out["versions"][tag] = vid
    yield out
    for tag in ("v1", "v2"):
        out[tag]["model"]._engine_breaker().reset()


@pytest.fixture()
def fresh_pointer(fleet):
    """Tests mutate the shared registry's pointer; restore v1-current."""
    reg = fleet["registry"]
    yield reg
    reg.promote("churn", fleet["versions"]["v1"])


def _server(fleet, **kw):
    kw.setdefault("bucket_cap", BUCKET_CAP)
    kw.setdefault("batch_deadline_s", 0.0)
    kw.setdefault("registry", fleet["registry"])
    srv = ModelServer(**kw)
    srv.register_from_registry("churn")
    return srv


def _assert_bitwise(a, b):
    for fld in ("prediction", "raw_prediction", "probability"):
        assert np.array_equal(getattr(a, fld), getattr(b, fld)), fld


# ---------------------------------------------------------------------------
# ModelRegistry
# ---------------------------------------------------------------------------


def test_version_id_is_the_aot_state_digest(fleet):
    """A registry version NAMES the fitted weights: the id equals the
    exported AOT manifest's state digest, so the bank loader's
    weights-vs-manifest verification transitively pins version->weights."""
    from transmogrifai_tpu import aot
    t = fleet["v1"]
    manifest, _ = aot.read_manifest(t["export_dir"])
    assert manifest is not None
    assert t["vid"] == manifest["stateDigest"]
    assert version_of_export(t["model_dir"], t["export_dir"]) == t["vid"]
    # bankless fallback digests the artifact bytes instead — stable
    # across calls, different across different models
    a = version_of_export(t["model_dir"])
    assert a == version_of_export(t["model_dir"])
    assert a != version_of_export(fleet["v2"]["model_dir"])


def test_register_promote_rollback_roundtrip(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    # registration does not need real artifacts when the version id is
    # explicit (the registry is a routing table, not a blob store)
    reg.register("m", str(tmp_path / "a"), version="va")
    reg.register("m", str(tmp_path / "b"), version="vb")
    assert reg.current("m") is None
    with pytest.raises(RegistryError):
        reg.resolve("m")                    # nothing promoted yet
    reg.promote("m", "va")
    assert reg.current("m") == "va" and reg.previous("m") is None
    reg.promote("m", "vb")
    assert (reg.current("m"), reg.previous("m")) == ("vb", "va")
    assert reg.resolve("m")["modelDir"].endswith("b")
    # rollback swings back; rollback is its own undo
    assert reg.rollback("m") == "va"
    assert (reg.current("m"), reg.previous("m")) == ("va", "vb")
    assert reg.rollback("m") == "vb"
    # idempotent re-register updates in place: still two versions
    reg.register("m", str(tmp_path / "b2"), version="vb")
    assert [r["version"] for r in reg.versions("m")] == ["va", "vb"]
    assert reg.record("m", "vb")["modelDir"].endswith("b2")
    assert reg.models() == ["m"]


def test_concurrent_registers_from_separate_handles_never_lose_records(
        tmp_path):
    """One atomic file per version: two registry handles (standing in
    for two PROCESSES — CLI + training runner) interleaving registers
    of the same model both land; there is no shared versions document
    to lose a read-modify-write race on."""
    a = ModelRegistry(str(tmp_path / "reg"))
    b = ModelRegistry(str(tmp_path / "reg"))
    a.register("m", "/tmp/a", version="va")
    b.register("m", "/tmp/b", version="vb")
    a.register("m", "/tmp/c", version="vc")
    for reg in (a, b):
        assert [r["version"] for r in reg.versions("m")] == \
            ["va", "vb", "vc"]
    with pytest.raises(RegistryError):
        a.register("m", "/tmp/x", version="../escape")


def test_registry_misuse_errors(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    with pytest.raises(RegistryError):
        reg.promote("ghost", "v0")          # never registered
    reg.register("m", str(tmp_path / "a"), version="va")
    with pytest.raises(RegistryError):
        reg.promote("m", "nope")            # unknown version
    with pytest.raises(RegistryError):
        reg.rollback("m")                   # no previous
    with pytest.raises(RegistryError):
        reg.register("bad/name", str(tmp_path / "a"), version="v")


def test_promote_fault_site_is_cataloged():
    assert "lifecycle.promote" in resilience.FAULT_SITES


def test_crash_mid_promote_leaves_pointer_intact_fresh_interpreter(
        tmp_path):
    """The atomic-pointer guarantee, verified across interpreters: a
    promote killed by an injected fault leaves the OLD pointer readable
    by a FRESH process — never a torn or half-switched state."""
    reg_dir = str(tmp_path / "reg")
    crash = textwrap.dedent(f"""
        import sys
        from transmogrifai_tpu import resilience
        from transmogrifai_tpu.lifecycle import ModelRegistry
        reg = ModelRegistry({reg_dir!r})
        reg.register("m", "/tmp/a", version="va", promote=True)
        reg.register("m", "/tmp/b", version="vb")
        plan = resilience.FaultPlan(seed=7).on("lifecycle.promote",
                                               error=OSError)
        with resilience.fault_plan(plan):
            try:
                reg.promote("m", "vb")
            except OSError:
                sys.exit(41)        # the "crash": process dies mid-promote
        sys.exit(1)
    """)
    proc = subprocess.run([sys.executable, "-c", crash],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 41, proc.stderr[-800:]
    probe = textwrap.dedent(f"""
        import sys
        from transmogrifai_tpu.lifecycle import ModelRegistry
        reg = ModelRegistry({reg_dir!r})
        assert reg.current("m") == "va", reg.current("m")
        assert reg.resolve("m")["modelDir"] == "/tmp/a"
        reg.promote("m", "vb")      # the registry is not wedged
        assert reg.current("m") == "vb"
        sys.exit(0)
    """)
    proc = subprocess.run([sys.executable, "-c", probe],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-800:]


# ---------------------------------------------------------------------------
# DriftSentinel
# ---------------------------------------------------------------------------


def _synthetic_sentinel(rng, n=256, bins=20, **kw):
    feats = [FeatureBuilder.Real("x1").from_column().as_predictor(),
             FeatureBuilder.Real("x2").from_column().as_predictor()]
    recs = [{"x1": float(rng.normal()), "x2": float(rng.normal())}
            for _ in range(n)]
    store = _generate_raw_store(recs, feats)
    summaries, baseline = {}, []
    for f in feats:
        summaries[(f.name, None)] = Summary.of_values(
            np.asarray([r[f.name] for r in recs]))
        baseline += distributions_of_column(f.name, store[f.name], bins,
                                            summaries)
    return DriftSentinel(baseline, feats, **kw), recs


def test_sentinel_sliding_window_and_in_distribution_silence():
    rng = np.random.default_rng(3)
    s, recs = _synthetic_sentinel(rng, window_rows=64, subwindows=4)
    assert s.subwindow_rows == 16
    # in-distribution traffic: windows compare, nothing fires
    for lo in range(0, 128, 8):
        out = s.observe([{"x1": float(rng.normal()),
                          "x2": float(rng.normal())}
                         for _ in range(8)])
        assert out == []
    st = s.stats()
    # ring filled at 64 rows, then slid every 16-row sub-window
    assert st["windowsCompared"] == 5
    assert st["advisories"] == 0
    assert st["trackedFeatures"] == 2
    assert st["lastWindow"]["rows"] == 64


def test_sentinel_flags_shift_within_one_window():
    rng = np.random.default_rng(4)
    s, _ = _synthetic_sentinel(rng, window_rows=64, subwindows=4)
    fired = []
    rows = 0
    while rows < 64 and not fired:
        fired = s.observe([{"x1": float(rng.normal() + 0.0),
                            "x2": float(rng.normal() * 0.05 + 2.5)}
                           for _ in range(8)])
        rows += 8
    assert rows <= 64, "advisory must fire within one window of shift"
    assert "TMG602" not in {f.rule for f in fired}
    assert {f.rule for f in fired} == {"TMG601"}
    (f,) = [f for f in fired if f.feature == "x2"]
    assert "JS divergence" in f.message


def test_sentinel_out_of_support_shift_is_maximal_divergence():
    """Live values entirely OUTSIDE the train bin range would be
    invisible to the in-range histogram (empty -> JS 0.0); the
    out-of-range mass guard reads them as what they are: maximal."""
    rng = np.random.default_rng(5)
    s, _ = _synthetic_sentinel(rng, window_rows=32, subwindows=4)
    s.observe([{"x1": float(1000.0 + i), "x2": float(rng.normal())}
               for i in range(32)])
    assert s.last_report["features"]["x1"]["js"] == 1.0
    assert any(f.rule == "TMG601" and f.feature == "x1"
               for f in s.last_findings)


def test_sentinel_fill_rate_shift_fires_tmg602():
    rng = np.random.default_rng(6)
    s, _ = _synthetic_sentinel(rng, window_rows=32, subwindows=4)
    # x2 vanishes from live traffic: fill 1.0 (train) -> 0.0 (live)
    findings = s.observe([{"x1": float(rng.normal())} for _ in range(32)])
    assert any(f.rule == "TMG602" and f.feature == "x2"
               for f in findings)
    info = s.last_report["features"]["x2"]
    assert info["liveFill"] == 0.0 and info["trainFill"] == 1.0


def test_sentinel_suppress_and_telemetry_hooks():
    rng = np.random.default_rng(7)
    # suppressed rules are muted but the window math still runs
    s, _ = _synthetic_sentinel(rng, window_rows=32, subwindows=4,
                               suppress=("TMG601", "TMG602"))
    out = s.observe([{"x1": 1000.0} for _ in range(32)])
    assert out == [] and s.stats()["windowsCompared"] == 1
    assert s.stats()["advisories"] == 0
    # unsuppressed: the on_drift listener hook + drift.* gauges fire
    telemetry.enable()
    try:
        listener = telemetry.add_listener(telemetry.CollectingRunListener())
        s2, _ = _synthetic_sentinel(rng, window_rows=32, subwindows=4,
                                    model_name="churn")
        s2.observe([{"x1": 1000.0, "x2": float(rng.normal())}
                    for _ in range(32)])
        assert listener.drift_advisories.get("TMG601", 0) >= 1
        assert "drift" in listener.events
        doc = telemetry.metrics_json()
        assert doc.get("drift.js_divergence.x1") == 1.0
        assert "lifecycle.drift_advisories" in doc
        summary = listener.summary()
        assert summary["driftAdvisories"].get("TMG601", 0) >= 1
    finally:
        telemetry.disable()
        telemetry.reset()


def test_sentinel_for_model_without_baseline_emits_tmg603():
    telemetry.enable()
    try:
        listener = telemetry.add_listener(telemetry.CollectingRunListener())
        bare = SimpleNamespace(rff_results=None, result_features=[])
        assert DriftSentinel.for_model(bare, model_name="bare") is None
        # TMG603 is INFO severity; the lint mirror carries it
        assert listener.lint_findings.get("info", 0) == 1
        assert "lint" in listener.events
    finally:
        telemetry.disable()
        telemetry.reset()


def test_drift_rules_are_cataloged():
    for rule in ("TMG601", "TMG602", "TMG603"):
        assert rule in lint.RULES


# ---------------------------------------------------------------------------
# RawFeatureFilterResults persistence (the sentinel's baseline)
# ---------------------------------------------------------------------------


def test_rff_results_roundtrip_through_saved_model(fleet):
    t = fleet["v1"]
    assert t["model"].rff_results is not None
    loaded = WorkflowModel.load(t["model_dir"])
    rff = loaded.rff_results
    assert rff is not None
    assert {d.name for d in rff.training_distributions} == {"x1", "x2"}
    orig = {d.name: d for d in
            t["model"].rff_results.training_distributions}
    for d in rff.training_distributions:
        assert np.array_equal(d.distribution, orig[d.name].distribution)
        assert d.summary_info == orig[d.name].summary_info
    assert rff.config.get("bins") == 20
    summ = rff.summary()
    assert summ["trainingDistributions"] == 2
    assert summ["excludedCount"] == len(summ["excluded"])


def test_runner_stamps_lifecycle_and_rff_summary(fleet, tmp_path):
    from transmogrifai_tpu.runner import OpParams, OpWorkflowRunner, RunType

    class _Reader:
        def read_records(self):
            return list(fleet["v1"]["records"])

    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
    f2 = FeatureBuilder.Real("x2").from_column().as_predictor()
    vec = transmogrify([f1, f2])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=3)
    pred = label.transform_with(sel, vec)
    wf = (Workflow().set_result_features(pred)
          .with_raw_feature_filter(RawFeatureFilter(bins=10)))
    params = OpParams(model_location=str(tmp_path / "model"),
                      metrics_location=str(tmp_path / "metrics.json"))
    out = OpWorkflowRunner(wf, training_reader=_Reader()).run(
        RunType.TRAIN, params)
    rffs = out.metrics["rawFeatureFilter"]
    assert rffs is not None and rffs["trainingDistributions"] == 2
    sunk = json.load(open(params.metrics_location))
    assert sunk["rawFeatureFilter"]["featuresChecked"] >= 2
    assert set(lifecycle.lifecycle_stats()) == set(sunk["lifecycle"])


# ---------------------------------------------------------------------------
# server: registry tenants, shadow, canary, automated promote/rollback
# ---------------------------------------------------------------------------


def test_registry_tenant_serves_current_and_reresolves_on_reload(
        fleet, fresh_pointer):
    reg = fresh_pointer
    srv = _server(fleet)
    try:
        t1, t2 = fleet["v1"], fleet["v2"]
        res = srv.score("churn", t1["records"][:4], timeout_s=120)
        solo = t1["model"].scoring_engine(
            gate_bandwidth=False, mesh=False,
            bucket_cap=BUCKET_CAP).score_store(t1["records"][:4],
                                               bucket_min=res.bucket)
        _assert_bitwise(res.store[t1["pred"].name], solo[t1["pred"].name])
        # promote v2 out-of-band, then evict: the reload re-resolves the
        # CURRENT pointer and serves the new version
        reg.promote("churn", t2["vid"])
        entry = srv._entries["churn"]
        with entry.lock:
            entry.model = None
            entry.engine = None
            entry.bank_buckets = []
            entry.sentinel = None
        res2 = srv.score("churn", t1["records"][:4], timeout_s=120)
        solo2 = t2["model"].scoring_engine(
            gate_bandwidth=False, mesh=False,
            bucket_cap=BUCKET_CAP).score_store(t1["records"][:4],
                                               bucket_min=res2.bucket)
        _assert_bitwise(res2.store[t2["pred"].name],
                        solo2[t2["pred"].name])
        assert srv.stats()["models"]["churn"]["viaRegistry"] is True
    finally:
        srv.shutdown(drain=True)


def test_register_via_registry_needs_registry():
    srv = ModelServer(registry=None)
    try:
        with pytest.raises(RolloutError):
            srv.register_from_registry("churn")
    finally:
        srv.shutdown(drain=True)


def test_shadow_rollout_parity_latency_and_solo_bit_identity(
        fleet, fresh_pointer):
    """Shadow of the SAME artifacts: every mirrored request records
    parity ok, latency delta is measured, responses stay bit-identical
    to solo scoring, and clean windows auto-promote (pointer unchanged
    for a same-version refresh)."""
    t1 = fleet["v1"]
    srv = _server(fleet)
    before = lifecycle.lifecycle_stats()
    try:
        srv.deploy("churn", t1["vid"], mode="shadow",
                   window_requests=4, promote_windows=2)
        with pytest.raises(RolloutError):        # one rollout at a time
            srv.deploy("churn", t1["vid"], mode="shadow")
        for i in range(6):
            res = srv.score("churn", t1["records"][i * 3:(i + 1) * 3],
                            timeout_s=120)
            assert res.canary is False
            solo = t1["model"].scoring_engine(
                gate_bandwidth=False, mesh=False,
                bucket_cap=BUCKET_CAP).score_store(
                    t1["records"][i * 3:(i + 1) * 3],
                    bucket_min=res.bucket)
            _assert_bitwise(res.store[t1["pred"].name],
                            solo[t1["pred"].name])
        # 6 requests x window 4 -> 1+ windows; finish to auto-promote
        for i in range(4):
            srv.score("churn", t1["records"][:2], timeout_s=120)
        after = lifecycle.lifecycle_stats()
        assert after["deploys"] - before["deploys"] == 1
        assert after["auto_promotions"] - before["auto_promotions"] == 1
        assert after["shadow_requests"] - before["shadow_requests"] >= 8
        assert after["shadow_parity_ok"] - before["shadow_parity_ok"] >= 8
        assert (after["shadow_parity_mismatch"]
                == before["shadow_parity_mismatch"])
        assert srv._entries["churn"].rollout is None
        assert fresh_pointer.current("churn") == t1["vid"]
    finally:
        srv.shutdown(drain=True)


def test_shadow_mismatch_blocks_promotion(fleet, fresh_pointer):
    """A candidate whose predictions DIFFER never reaches a clean
    window: parity mismatches are recorded and block auto-promote."""
    t1, t2 = fleet["v1"], fleet["v2"]
    srv = _server(fleet)
    try:
        srv.deploy("churn", t2["vid"], mode="shadow",
                   window_requests=2, promote_windows=1)
        for i in range(8):
            srv.score("churn", t1["records"][i:i + 2], timeout_s=120)
        status = srv.lifecycle_status("churn")
        assert status["rollout"] is not None, "must NOT have promoted"
        assert status["rollout"]["parityMismatch"] >= 1
        assert status["rollout"]["cleanWindows"] == 0
        assert status["rollout"]["shadowLatencyDeltaMs"] is not None
        assert fresh_pointer.current("churn") == t1["vid"]
        out = srv.rollback("churn")              # manual abort
        assert out["aborted"] == t2["vid"]
        assert srv._entries["churn"].rollout is None
    finally:
        srv.shutdown(drain=True)


def test_canary_routing_deterministic_and_noncanaried_bit_identical(
        fleet, fresh_pointer):
    t1, t2 = fleet["v1"], fleet["v2"]
    srv = _server(fleet)
    try:
        srv.deploy("churn", t2["vid"], mode="canary", fraction=0.5,
                   window_requests=10_000, promote_windows=100)
        flags = {}
        for i in range(24):
            res = srv.score("churn", [t1["records"][i]], timeout_s=120)
            flags[i] = res.canary
            if not res.canary:
                # the solo-path contract: non-canaried rows bit-identical
                solo = t1["model"].scoring_engine(
                    gate_bandwidth=False, mesh=False,
                    bucket_cap=BUCKET_CAP).score_store(
                        [t1["records"][i]], bucket_min=res.bucket)
                _assert_bitwise(res.store[t1["pred"].name],
                                solo[t1["pred"].name])
        assert any(flags.values()) and not all(flags.values())
        # deterministic: the SAME record routes the SAME way, always
        for i in (0, 5, 11):
            res = srv.score("churn", [t1["records"][i]], timeout_s=120)
            assert res.canary == flags[i]
    finally:
        srv.shutdown(drain=True)


def test_canary_auto_promotes_after_clean_windows(fleet, fresh_pointer):
    t1, t2 = fleet["v1"], fleet["v2"]
    srv = _server(fleet)
    before = lifecycle.lifecycle_stats()
    try:
        srv.deploy("churn", t2["vid"], mode="canary", fraction=0.5,
                   window_requests=4, promote_windows=2)
        n = 0
        while fresh_pointer.current("churn") != t2["vid"] and n < 64:
            res = srv.score("churn", [t1["records"][n % 100]],
                            timeout_s=120)
            assert res.rows == 1
            n += 1
        assert fresh_pointer.current("churn") == t2["vid"]
        assert srv._entries["churn"].rollout is None
        after = lifecycle.lifecycle_stats()
        assert after["auto_promotions"] - before["auto_promotions"] == 1
        assert after["canary_requests"] > before["canary_requests"]
        # the promoted model serves: bit-identical to v2 solo
        res = srv.score("churn", t1["records"][:4], timeout_s=120)
        solo = t2["model"].scoring_engine(
            gate_bandwidth=False, mesh=False,
            bucket_cap=BUCKET_CAP).score_store(t1["records"][:4],
                                               bucket_min=res.bucket)
        _assert_bitwise(res.store[t2["pred"].name], solo[t2["pred"].name])
    finally:
        srv.shutdown(drain=True)


def test_canary_promote_fault_rolls_back_with_zero_drops(
        fleet, fresh_pointer):
    """The acceptance chaos test: a seeded fault on ``lifecycle.promote``
    during a canary rollout. The automated promotion fails, automated
    rollback fires, EVERY request across the switch is answered (zero
    drops, nothing quarantined), the registry pointer never moves, and
    post-rollback traffic is bit-identical to the stable version."""
    t1, t2 = fleet["v1"], fleet["v2"]
    srv = _server(fleet)
    before = lifecycle.lifecycle_stats()
    q_before = resilience.resilience_stats()
    plan = resilience.FaultPlan(seed=9).on("lifecycle.promote",
                                           error=RuntimeError)
    try:
        srv.deploy("churn", t2["vid"], mode="canary", fraction=1.0,
                   window_requests=2, promote_windows=1)
        answered = 0
        with resilience.fault_plan(plan):
            for i in range(12):
                res = srv.score("churn", [t1["records"][i]], timeout_s=120)
                answered += int(res.rows == 1)
        assert answered == 12, "a rollout switch must drop zero requests"
        assert plan.fired("lifecycle.promote") == 1
        after = lifecycle.lifecycle_stats()
        assert after["auto_rollbacks"] - before["auto_rollbacks"] == 1
        assert after["auto_promotions"] == before["auto_promotions"]
        assert srv._entries["churn"].rollout is None
        assert fresh_pointer.current("churn") == t1["vid"]
        q_after = resilience.resilience_stats()
        for k in ("quarantined_batches", "quarantined_records"):
            assert q_after[k] == q_before[k]
        # the stable version still serves, bit-identically
        res = srv.score("churn", t1["records"][:4], timeout_s=120)
        solo = t1["model"].scoring_engine(
            gate_bandwidth=False, mesh=False,
            bucket_cap=BUCKET_CAP).score_store(t1["records"][:4],
                                               bucket_min=res.bucket)
        _assert_bitwise(res.store[t1["pred"].name], solo[t1["pred"].name])
    finally:
        srv.shutdown(drain=True)


def test_window_without_candidate_evidence_neither_promotes_nor_resets(
        fleet, fresh_pointer):
    """A window in which no request touched the candidate (host-tier
    primaries under shadow, zero canaried requests) proves nothing:
    it must not advance the promotion count — and must not reset it."""
    t1 = fleet["v1"]
    srv = _server(fleet)
    try:
        srv.deploy("churn", t1["vid"], mode="shadow",
                   window_requests=1, promote_windows=1)
        entry = srv._entries["churn"]
        rollout = entry.rollout
        srv._rollout_tick(entry, rollout, 1)     # evidence-free window
        assert entry.rollout is rollout, \
            "must NOT promote on zero parity evidence"
        assert rollout.windows == 1 and rollout.clean_windows == 0
        rollout.win_evidence = 2                 # now the window proves
        srv._rollout_tick(entry, rollout, 1)
        assert entry.rollout is None             # promoted
    finally:
        srv.shutdown(drain=True)


def test_manual_rollback_wins_over_racing_auto_promote(
        fleet, fresh_pointer):
    """An operator's rollback() landing between the worker's
    clean-window check and its promote must stick: the promote
    re-checks the rollout's identity under the entry lock and gives
    up."""
    t1, t2 = fleet["v1"], fleet["v2"]
    srv = _server(fleet)
    try:
        srv.deploy("churn", t2["vid"], mode="shadow",
                   window_requests=10 ** 6)
        entry = srv._entries["churn"]
        rollout = entry.rollout
        assert srv.rollback("churn")["aborted"] == t2["vid"]
        before = lifecycle.lifecycle_stats()
        srv._promote_rollout(entry, rollout)     # the racing worker
        after = lifecycle.lifecycle_stats()
        assert after["auto_promotions"] == before["auto_promotions"]
        assert fresh_pointer.current("churn") == t1["vid"]
        assert entry.rollout is None
    finally:
        srv.shutdown(drain=True)


def test_poison_request_during_rollout_never_kills_the_worker(
        fleet, fresh_pointer):
    """A record whose dict KEY is not JSON-serializable (tuple key —
    ``json.dumps`` raises even with ``default=str``) must not kill the
    tenant's worker thread mid-rollout: canary routing falls back to
    the stable path (which scores the absent features as nulls) and
    the next request is answered normally."""
    t1, t2 = fleet["v1"], fleet["v2"]
    srv = _server(fleet)
    try:
        srv.deploy("churn", t2["vid"], mode="canary", fraction=1.0,
                   window_requests=10 ** 6)
        res = srv.score("churn", [{(1, 2): "unroutable"}], timeout_s=120)
        assert res.rows == 1 and res.canary is False
        res = srv.score("churn", t1["records"][:2], timeout_s=120)
        assert res.rows == 2
    finally:
        srv.shutdown(drain=True)


def test_deploy_misuse_errors(fleet):
    srv = _server(fleet)
    try:
        with pytest.raises(RolloutError):
            srv.deploy("churn", fleet["v1"]["vid"], mode="blue-green")
        with pytest.raises(RegistryError):
            srv.deploy("churn", "no-such-version")
        with pytest.raises(RolloutError):
            srv.deploy("churn", fleet["v2"]["vid"], mode="canary",
                       fraction=1.5)
        with pytest.raises(ModelNotFound):
            srv.deploy("ghost", fleet["v1"]["vid"])
        no_reg = ModelServer()
        try:
            no_reg.register("m", model_dir=fleet["v1"]["model_dir"])
            with pytest.raises(RolloutError):
                no_reg.deploy("m", "v")
            with pytest.raises(RolloutError):
                no_reg.rollback("m")     # no rollout and no registry
        finally:
            no_reg.shutdown(drain=True)
    finally:
        srv.shutdown(drain=True)


def test_server_drift_sentinel_flags_shifted_traffic(fleet):
    srv = _server(fleet, drift_window=64)
    try:
        t1 = fleet["v1"]
        for lo in range(0, 64, 8):
            srv.score("churn", t1["records"][lo:lo + 8], timeout_s=120)
        srv.drain_drift()
        st = srv.stats()["models"]["churn"]["drift"]
        assert st["windowsCompared"] >= 1 and st["advisories"] == 0
        shifted = [{"label": 0.0, "x1": 500.0, "x2": 0.1}] * 8
        for _ in range(8):
            srv.score("churn", shifted, timeout_s=120)
        srv.drain_drift()
        st = srv.stats()["models"]["churn"]["drift"]
        assert st["advisories"] >= 1, "shifted stream must trip TMG6xx"
        assert srv.lifecycle_status("churn")["drift"] == st
    finally:
        srv.shutdown(drain=True)


def test_http_lifecycle_endpoints(fleet, fresh_pointer):
    import http.client
    t1, t2 = fleet["v1"], fleet["v2"]
    srv = _server(fleet)
    httpd = serve_http(srv, port=0)
    host, port = httpd.server_address
    try:
        conn = http.client.HTTPConnection(host, port, timeout=60)

        def call(method, path, body=None):
            conn.request(method, path,
                         None if body is None else json.dumps(body),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            return r.status, json.loads(r.read() or b"{}")

        status, doc = call("GET", "/v1/models/churn/versions")
        assert status == 200
        assert doc["current"] == t1["vid"] and doc["rollout"] is None
        assert {r["version"] for r in doc["versions"]} == \
            {t1["vid"], t2["vid"]}
        status, doc = call("POST", "/v1/models/churn:deploy",
                           {"version": t2["vid"], "mode": "shadow",
                            "windowRequests": 1000})
        assert status == 200 and doc["rollout"]["mode"] == "shadow"
        status, doc = call("GET", "/v1/models/churn/versions")
        assert doc["rollout"]["version"] == t2["vid"]
        status, doc = call("POST", "/v1/models/churn:score",
                           {"records": t1["records"][:2]})
        assert status == 200 and doc["rows"] == 2
        assert doc["canary"] is False
        status, doc = call("POST", "/v1/models/churn:rollback", {})
        assert status == 200 and doc["aborted"] == t2["vid"]
        status, _ = call("POST", "/v1/models/churn:deploy",
                         {"version": t2["vid"], "mode": "blue-green"})
        assert status == 400
        status, _ = call("GET", "/v1/models/ghost/versions")
        assert status == 404
    finally:
        httpd.shutdown()
        srv.shutdown(drain=True)


# ---------------------------------------------------------------------------
# CLI: registry subcommand + lifecycle knobs
# ---------------------------------------------------------------------------


def test_cli_registry_subcommand(fleet, tmp_path, capsys):
    from transmogrifai_tpu.cli import main
    reg_dir = str(tmp_path / "reg")
    t1, t2 = fleet["v1"], fleet["v2"]
    rc = main(["registry", "register", "--registry", reg_dir,
               "--model", "churn", "--model-dir", t1["model_dir"],
               "--bank", t1["export_dir"], "--promote"])
    assert rc == 0
    assert t1["vid"] in capsys.readouterr().out
    rc = main(["registry", "register", "--registry", reg_dir,
               "--model", "churn", "--model-dir", t2["model_dir"],
               "--bank", t2["export_dir"]])
    assert rc == 0 and t2["vid"] in capsys.readouterr().out
    rc = main(["registry", "list", "--registry", reg_dir, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["churn"]["current"] == t1["vid"]
    assert len(doc["churn"]["versions"]) == 2
    rc = main(["registry", "promote", "--registry", reg_dir,
               "--model", "churn", "--version", t2["vid"]])
    assert rc == 0
    capsys.readouterr()
    rc = main(["registry", "current", "--registry", reg_dir,
               "--model", "churn"])
    assert rc == 0
    assert capsys.readouterr().out.strip() == t2["vid"]
    rc = main(["registry", "rollback", "--registry", reg_dir,
               "--model", "churn"])
    assert rc == 0 and t1["vid"] in capsys.readouterr().out
    # misuse fails loudly, exit 1
    rc = main(["registry", "promote", "--registry", reg_dir,
               "--model", "churn", "--version", "nope"])
    assert rc == 1
    capsys.readouterr()


def test_cli_gen_emits_lifecycle_knobs_and_check_validates(tmp_path,
                                                           capsys):
    from transmogrifai_tpu.cli import generate_project, run_check
    csv = tmp_path / "data.csv"
    csv.write_text("label,x\n1,0.5\n0,0.1\n1,0.9\n0,0.2\n")
    files = generate_project(str(csv), "label", str(tmp_path / "proj"))
    params = json.load(open(files["params.json"]))
    cp = params["customParams"]
    for knob in ("registryDir", "driftWindow", "driftJsThreshold",
                 "canaryFraction"):
        assert knob in cp and cp[knob] is None
    # valid knobs pass the TMG001 numeric validation
    p = tmp_path / "params.json"
    p.write_text(json.dumps({"customParams": {
        "driftWindow": 2048, "driftJsThreshold": 0.2,
        "canaryFraction": 0.1, "registryDir": "./registry"}}))
    assert run_check(str(p)) == 0
    capsys.readouterr()
    for bad in ({"driftWindow": 2.5}, {"driftWindow": 0},
                {"driftJsThreshold": "hot"}, {"canaryFraction": 1.5},
                {"canaryFraction": 0}, {"registryDir": 42}):
        p.write_text(json.dumps({"customParams": bad}))
        assert run_check(str(p)) == 1, bad
        out = capsys.readouterr().out
        assert "TMG001" in out and next(iter(bad)) in out


def test_lifecycle_stats_reset_and_server_stamp(fleet):
    srv = _server(fleet)
    try:
        stats = srv.stats()
        assert set(stats["lifecycle"]) == set(lifecycle.lifecycle_stats())
    finally:
        srv.shutdown(drain=True)
