"""Map vectorizers, DateList vectorizer, fn serialization tests."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, Workflow
from transmogrifai_tpu.ops.maps import MapVectorizer
from transmogrifai_tpu.ops.date_list import DateListVectorizer
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.utils.fn_io import (FunctionSerializationError,
                                           decode_fn, encode_fn)

_MS_PER_DAY = 24 * 3600 * 1000


def test_real_map_vectorizer():
    m = FeatureBuilder.RealMap("m").from_column().as_predictor()
    store = ColumnStore.from_dict({
        "m": (ft.RealMap, [{"a": 1.0, "b": 10.0}, {"a": 3.0}, {}])})
    est = MapVectorizer()
    m.transform_with(est)
    model = est.fit(store)
    out = model.transform_columns(store)
    # keys a, b -> [a, a_null, b, b_null]
    np.testing.assert_allclose(out.values, [
        [1.0, 0, 10.0, 0], [3.0, 0, 10.0, 1], [2.0, 1, 10.0, 1]])
    assert out.metadata.columns[0].grouping == "a"
    assert out.metadata.columns[0].parent_feature_name == "m"


def test_text_map_pivot():
    m = FeatureBuilder.TextMap("m").from_column().as_predictor()
    store = ColumnStore.from_dict({
        "m": (ft.TextMap, [{"k": "x"}, {"k": "y"}, {"k": "x"}, {}])})
    est = MapVectorizer(top_k=5, min_support=1)
    m.transform_with(est)
    model = est.fit(store)
    out = model.transform_columns(store)
    # key k -> [x, y, OTHER, null]
    assert out.values.shape == (4, 4)
    np.testing.assert_allclose(out.values[0], [1, 0, 0, 0])
    np.testing.assert_allclose(out.values[3], [0, 0, 0, 1])


def test_multipicklist_map():
    m = FeatureBuilder.MultiPickListMap("m").from_column().as_predictor()
    store = ColumnStore.from_dict({
        "m": (ft.MultiPickListMap, [{"k": ["a", "b"]}, {"k": ["a"]}, {}])})
    est = MapVectorizer(min_support=1)
    m.transform_with(est)
    model = est.fit(store)
    out = model.transform_columns(store)
    assert out.values[0][:2].sum() == 2.0  # multi-hot


def test_binary_map_and_geo_map():
    b = FeatureBuilder.BinaryMap("b").from_column().as_predictor()
    g = FeatureBuilder.GeolocationMap("g").from_column().as_predictor()
    store = ColumnStore.from_dict({
        "b": (ft.BinaryMap, [{"x": True}, {"x": False}, {}]),
        "g": (ft.GeolocationMap, [{"home": [10.0, 20.0, 1.0]}, {}, {}]),
    })
    for feat, name in ((b, "b"), (g, "g")):
        est = MapVectorizer()
        feat.transform_with(est)
        model = est.fit(store)
        out = model.transform_columns(store)
        assert out.values.shape[0] == 3
        assert out.metadata.size == out.values.shape[1]


def test_transmogrify_with_maps_and_datelist():
    m = FeatureBuilder.RealMap("m").from_column().as_predictor()
    dl = FeatureBuilder.DateList("dl").from_column().as_predictor()
    age = FeatureBuilder.Real("age").from_column().as_predictor()
    vec = transmogrify([m, dl, age])
    store = ColumnStore.from_dict({
        "m": (ft.RealMap, [{"a": 1.0}, {}]),
        "dl": (ft.DateList, [[_MS_PER_DAY, 3 * _MS_PER_DAY], []]),
        "age": (ft.Real, [30.0, None]),
    })
    model = Workflow().set_input_store(store).set_result_features(vec).train()
    out = model.score(store, keep_intermediate=True)[vec.name]
    assert out.values.shape[0] == 2
    assert out.metadata is not None and out.metadata.size == out.values.shape[1]
    assert {"m", "dl", "age"} <= set(out.metadata.parent_features())


def test_date_list_vectorizer_since_last():
    dl = FeatureBuilder.DateList("dl").from_column().as_predictor()
    model = DateListVectorizer(reference_date_ms=10 * _MS_PER_DAY,
                               input_names=["dl"])
    dl.transform_with(model)
    store = ColumnStore.from_dict({
        "dl": (ft.DateList, [[2 * _MS_PER_DAY, 7 * _MS_PER_DAY], []])})
    out = model.transform_columns(store)
    np.testing.assert_allclose(out.values, [[3.0, 0.0], [0.0, 1.0]])


def test_fn_roundtrip_lambda():
    fn = decode_fn(encode_fn(lambda v: v * 2 if v is not None else None))
    assert fn(3) == 6 and fn(None) is None


def test_fn_roundtrip_with_math_module():
    fn = decode_fn(encode_fn(lambda v: math.floor(v)))  # noqa: F821
    assert fn(3.7) == 3


def test_fn_rejects_unknown_global_at_save():
    with pytest.raises(FunctionSerializationError):
        encode_fn(lambda v: some_unknown_helper(v))  # noqa: F821


def test_fn_named_function():
    spec = encode_fn(np.sqrt)
    assert spec["kind"] == "named"
    assert decode_fn(spec) is np.sqrt


def test_dsl_breadth(rng):
    """bucketize / to_unit_circle / combine / to_percentile DSL methods."""
    import numpy as np
    from transmogrifai_tpu import ColumnStore, FeatureBuilder, Workflow, column_from_values
    n = 50
    store = ColumnStore({
        "x": column_from_values(ft.Real, list(rng.normal(size=n))),
        "d": column_from_values(ft.Date, [1_500_000_000_000 + int(v)
                                          for v in rng.integers(0, 10**10, n)]),
    })
    x = FeatureBuilder.Real("x").from_column().as_predictor()
    d = FeatureBuilder.Date("d").from_column().as_predictor()
    b = x.bucketize([-1.0, 0.0, 1.0])
    circ = d.to_unit_circle()
    pct = x.to_percentile(num_buckets=10)
    both = b.combine(circ)
    model = (Workflow().set_input_store(store)
             .set_result_features(both, pct).train())
    out = model.transform(store)
    assert np.asarray(out[both.name].values).shape[0] == n
    p = np.asarray(out[pct.name].values)
    assert p.min() >= 0.0 and p.max() <= 99.0


def test_filter_map_keys_and_extract_key():
    """Map DSL: .filter_keys / .extract_key (RichMapFeature filter + the
    per-key access path)."""
    import transmogrifai_tpu.dsl  # noqa: F401 — attaches the methods

    m = FeatureBuilder.RealMap("m").from_column().as_predictor()
    store = ColumnStore.from_dict({
        "m": (ft.RealMap, [{"a": 1.0, "b": 2.0, "c": 3.0},
                           {"b": 5.0}, {}])})

    kept = m.filter_keys(block=["c"])
    out = kept.origin_stage.transform_columns(store)
    assert set(out.children.keys()) == {"a", "b"}
    assert out.ftype is ft.RealMap

    allowed = m.filter_keys(allow=["a"])
    out2 = allowed.origin_stage.transform_columns(store)
    assert set(out2.children.keys()) == {"a"}

    b = m.extract_key("b")
    assert b.ftype is ft.Real
    col = b.origin_stage.transform_columns(store)
    np.testing.assert_allclose(col.values[col.mask], [2.0, 5.0])
    # missing key -> all-null column of the element type
    missing = m.extract_key("zz").origin_stage.transform_columns(store)
    assert not missing.mask.any()


def test_extract_key_through_workflow(rng):
    """extract_key output feeds the normal scalar pipeline end-to-end."""
    import transmogrifai_tpu.dsl as dsl

    n = 40
    vals = rng.normal(size=n)
    m = FeatureBuilder.RealMap("m").from_column().as_predictor()
    rows = [{"x": float(v)} if i % 5 else {} for i, v in enumerate(vals)]
    store = ColumnStore.from_dict({"m": (ft.RealMap, rows)})
    filled = m.extract_key("x").fill_missing_with_mean()
    model = (Workflow().set_input_store(store)
             .set_result_features(filled).train())
    out = model.score(store)[filled.name]
    assert out.mask.all() or not np.isnan(
        np.asarray(out.values, dtype=float)).any()


def test_map_vectorize_fill_options():
    """RichMapFeature.vectorize's fill surface: default_value fills
    missing keys when fillWithMean/-Mode are off; per-key mean is the
    default (RichMapFeature.scala:497-540,665-696)."""
    import numpy as np
    from transmogrifai_tpu import FeatureBuilder, Workflow
    from transmogrifai_tpu.columns import ColumnStore
    from transmogrifai_tpu.ops.maps import MapVectorizer
    from transmogrifai_tpu.types import feature_types as ft

    rows = [{"a": 1.0, "b": 10.0}, {"a": 3.0}, {"b": 20.0}]
    store = ColumnStore.from_dict({"m": (ft.RealMap, rows)})

    def run(**kw):
        m = FeatureBuilder.RealMap("m").from_column().as_predictor()
        stage = MapVectorizer(track_nulls=False, **kw)
        stage.set_input(m)
        vec = stage.get_output()
        model = (Workflow().set_input_store(store)
                 .set_result_features(vec).train())
        out = model.transform(store)
        meta = out[vec.name].metadata
        cols = {c.grouping: i for i, c in enumerate(meta.columns)}
        return out[vec.name].values, cols

    vals, cols = run()                                 # mean fill default
    assert vals[2, cols["a"]] == 2.0                   # mean of 1, 3
    vals2, cols2 = run(fill_with_mean=False, default_value=-5.0)
    assert vals2[2, cols2["a"]] == -5.0
    assert vals2[1, cols2["b"]] == -5.0


def test_map_vectorize_integral_mode_fill():
    """fill_with_mode on IntegralMap: mode fill by default, fixed fill
    when disabled."""
    from transmogrifai_tpu import FeatureBuilder, Workflow
    from transmogrifai_tpu.columns import ColumnStore
    from transmogrifai_tpu.ops.maps import MapVectorizer
    from transmogrifai_tpu.types import feature_types as ft

    rows = [{"k": 7}, {"k": 7}, {"k": 2}, {}]
    store = ColumnStore.from_dict({"m": (ft.IntegralMap, rows)})

    def run(**kw):
        m = FeatureBuilder.IntegralMap("m").from_column().as_predictor()
        stage = MapVectorizer(track_nulls=False, **kw)
        stage.set_input(m)
        vec = stage.get_output()
        model = (Workflow().set_input_store(store)
                 .set_result_features(vec).train())
        return model.transform(store)[vec.name].values

    assert run()[3, 0] == 7.0                       # mode fill
    assert run(fill_with_mode=False,
               default_value=42.0)[3, 0] == 42.0    # fixed fill
