"""ModelInsights + RecordInsightsLOCO tests (ModelInsightsTest /
RecordInsightsLOCOTest analogs)."""
import json

import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, Workflow, column_from_values
from transmogrifai_tpu.columns import VectorColumn
from transmogrifai_tpu.insights import (ModelInsights, RecordInsightsLOCO,
                                        parse_insights)
from transmogrifai_tpu.models.linear import (LogisticRegressionFamily,
                                             LogisticRegressionModel)
from transmogrifai_tpu.models.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.ops.sanity_checker import SanityChecker
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (VectorColumnMetadata,
                                               VectorMetadata)


def _fitted_workflow(rng, n=300):
    y = rng.integers(0, 2, size=n).astype(float)
    strong = rng.normal(size=n) + 2.0 * y       # predictive
    weak = rng.normal(size=n)                   # noise
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "strong": column_from_values(ft.Real, list(strong)),
        "weak": column_from_values(ft.Real, list(weak)),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fs = FeatureBuilder.Real("strong").from_column().as_predictor()
    fw = FeatureBuilder.Real("weak").from_column().as_predictor()
    vec = transmogrify([fs, fw])
    checker = SanityChecker(remove_bad_features=False)
    checked = label.transform_with(checker, vec)
    pred = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()]) \
        .set_input(label, checked).get_output()
    wf = Workflow().set_result_features(pred).set_input_store(store)
    return wf.train(), store, pred


def test_model_insights_extraction(rng):
    model, store, pred = _fitted_workflow(rng)
    ins = model.model_insights(pred, store=store)
    assert ins.problem_type == "binary"
    assert ins.label.name == "label"
    assert ins.label.is_categorical and ins.label.sample_size == 300
    assert ins.selected_model_info.get("bestModelName")
    # derived columns grouped under raw parents, with corr + contribution
    parents = {f.feature_name for f in ins.features}
    assert {"strong", "weak"} <= parents
    strong_cols = next(f for f in ins.features if f.feature_name == "strong")
    d = strong_cols.derived[0]
    assert d.corr_with_label is not None and abs(d.corr_with_label) > 0.3
    assert d.contribution is not None and d.contribution > 0
    # json + pretty render
    j = ins.to_json()
    assert json.dumps(j)  # serializable
    text = ins.pretty()
    assert "Best model" in text and "strong" in text


def test_model_insights_without_store(rng):
    model, store, pred = _fitted_workflow(rng)
    ins = model.model_insights(pred)
    assert ins.selected_model_info.get("bestModelName")
    # stats harvested from the sanity checker even without data
    all_derived = [d for f in ins.features for d in f.derived]
    assert any(d.corr_with_label is not None for d in all_derived)


def test_loco_identifies_important_column(rng):
    n, d = 50, 4
    X = rng.normal(size=(n, d))
    coef = np.array([5.0, 0.0, 0.0, 0.1])
    model = LogisticRegressionModel(coef, 0.0, 2)
    meta = VectorMetadata("features", [
        VectorColumnMetadata(f"x{i}", "Real") for i in range(d)])
    store = ColumnStore({"features": VectorColumn(ft.OPVector, X, meta)})
    feat = FeatureBuilder.OPVector("features").from_column().as_predictor()

    loco = RecordInsightsLOCO(model=model, top_k=2)
    loco.set_input(feat)
    out = loco.transform_columns(store)
    for i in range(n):
        row = parse_insights(out.get_raw(i))
        assert len(row) <= 2
        top_name = max(row, key=lambda k: abs(row[k]))
        assert top_name.startswith("x0")   # dominant coefficient wins
        # sign consistency: diff = base - zeroed ⇒ matches x*coef sign
        assert np.sign(row[top_name]) == np.sign(X[i, 0] * 5.0) or X[i, 0] == 0


def test_loco_diffs_shape_and_zero_noop(rng):
    n, d = 8, 3
    X = np.zeros((n, d))
    model = LogisticRegressionModel(np.ones(d), 0.0, 2)
    loco = RecordInsightsLOCO(model=model)
    diffs = loco.loco_diffs(X)
    assert diffs.shape == (d, n)
    assert np.allclose(diffs, 0.0)  # zeroing a zero column changes nothing


def test_loco_end_to_end_on_workflow(rng):
    model, store, pred = _fitted_workflow(rng)
    selected = model.stage_of(pred)
    vec_feature = selected.input_features[1]
    scored = model.transform(store)
    loco = RecordInsightsLOCO(model=selected, top_k=3)
    loco.set_input(vec_feature)
    out = loco.transform_columns(scored)
    row = parse_insights(out.get_raw(0))
    assert 0 < len(row) <= 3


def test_insights_report_dropped_columns_with_meta(rng):
    """Columns removed by SanityChecker(remove_bad_features=True) must still
    appear in the report with their drop reasons."""
    n = 300
    y = rng.integers(0, 2, size=n).astype(float)
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "good": column_from_values(ft.Real, list(rng.normal(size=n) + y)),
        "const": column_from_values(ft.Real, [3.0] * n),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fg = FeatureBuilder.Real("good").from_column().as_predictor()
    fc = FeatureBuilder.Real("const").from_column().as_predictor()
    vec = transmogrify([fg, fc])
    checked = label.transform_with(
        SanityChecker(remove_bad_features=True, remove_feature_group=False), vec)
    pred = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()]) \
        .set_input(label, checked).get_output()
    model = Workflow().set_result_features(pred).set_input_store(store).train()
    ins = model.model_insights(pred, store=store)
    all_derived = [d for f in ins.features for d in f.derived]
    dropped = [d for d in all_derived if d.dropped]
    assert dropped, "dropped columns must appear in the report"
    assert any("variance" in r for d in dropped for r in d.drop_reasons)


def test_tree_contributions_use_real_splits(rng):
    """Tree importances must count only real splits (finite thr), not the
    feat=0 filler of non-split nodes."""
    from transmogrifai_tpu.models.trees import OpDecisionTreeClassifier
    n, d = 400, 4
    X = rng.normal(size=(n, d))
    y = (X[:, 3] > 0).astype(float)   # only feature 3 matters
    from transmogrifai_tpu.vector_metadata import VectorColumnMetadata, VectorMetadata
    meta = VectorMetadata("features", [
        VectorColumnMetadata(f"x{i}", "Real") for i in range(d)])
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "features": VectorColumn(ft.OPVector, X, meta),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    est = OpDecisionTreeClassifier(max_depth=4)
    est.set_input(label, feats)
    model = est.fit(store)
    imp = ModelInsights._contributions(model)
    assert imp is not None
    assert int(np.argmax(imp)) == 3


def test_loco_model_rebinds_after_save_load(rng, tmp_path):
    """get_params drops the live model object; save/load must re-attach it
    by uid so a loaded workflow's LOCO stage still scores (ADVICE r1)."""
    model, store, pred = _fitted_workflow(rng)
    selected = model.stage_of(pred)
    vec_feature = selected.input_features[1]
    loco = RecordInsightsLOCO(model=selected, top_k=3)
    loco.set_input(vec_feature)
    insights_f = loco.get_output()

    from transmogrifai_tpu.workflow import WorkflowModel
    wm = WorkflowModel(
        result_features=[pred, insights_f],
        fitted_stages={**model.fitted_stages, loco.uid: loco})
    path = str(tmp_path / "m")
    wm.save(path)

    from transmogrifai_tpu.model_io import load_workflow_model
    loaded = load_workflow_model(path)
    insights_loaded = next(f for f in loaded.result_features
                           if f.name == insights_f.name)
    loco2 = insights_loaded.origin_stage
    assert isinstance(loco2, RecordInsightsLOCO)
    assert loco2.model is not None and loco2.model.uid == selected.uid
    out = loaded.transform(store)
    row = parse_insights(out[insights_f.name].get_raw(0))
    assert 0 < len(row) <= 3


def test_loco_copy_carries_model(rng):
    model = LogisticRegressionModel(np.ones(3), 0.0, 2)
    loco = RecordInsightsLOCO(model=model, top_k=2)
    c = loco.copy()
    assert c.model is model

    unbound = RecordInsightsLOCO(model=None, model_uid="X_0")
    meta = VectorMetadata("features", [
        VectorColumnMetadata(f"x{i}", "Real") for i in range(3)])
    store = ColumnStore({"features": VectorColumn(
        ft.OPVector, np.zeros((2, 3)), meta)})
    feat = FeatureBuilder.OPVector("features").from_column().as_predictor()
    unbound.set_input(feat)
    with pytest.raises(RuntimeError, match="unbound"):
        unbound.transform_columns(store)


def test_tree_contributions_gain_weighted(rng):
    """A high-gain feature must outrank a correlated low-gain one even when
    both split equally often (gain weighting, reference featureImportances)."""
    from transmogrifai_tpu.models.trees import OpRandomForestClassifier
    n, d = 500, 3
    X = rng.normal(size=(n, d))
    y = (X[:, 1] + 0.2 * X[:, 2] > 0).astype(float)
    meta = VectorMetadata("features", [
        VectorColumnMetadata(f"x{i}", "Real") for i in range(d)])
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "features": VectorColumn(ft.OPVector, X, meta),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    est = OpRandomForestClassifier(num_trees=10, max_depth=4)
    est.set_input(label, feats)
    fitted = est.fit(store)
    assert "gain" in fitted.trees
    imp = ModelInsights._contributions(fitted)
    assert imp is not None and abs(imp.sum() - 1.0) < 1e-6
    assert int(np.argmax(imp)) == 1


def test_record_insights_corr(rng):
    """RecordInsightsCorr: correlation × min-max-normalized value, top-K per
    prediction column (RecordInsightsCorr.scala:95-165)."""
    from transmogrifai_tpu.columns import PredictionColumn
    from transmogrifai_tpu.insights import RecordInsightsCorr
    n, d = 200, 4
    X = rng.normal(size=(n, d))
    score = 1.0 / (1.0 + np.exp(-(3.0 * X[:, 2])))    # only x2 drives it
    probs = np.stack([1 - score, score], axis=1)
    meta = VectorMetadata("features", [
        VectorColumnMetadata(f"x{i}", "Real") for i in range(d)])
    store = ColumnStore({
        "pred": PredictionColumn(np.round(score), np.zeros((n, 0)), probs),
        "features": VectorColumn(ft.OPVector, X, meta),
    })
    pf = FeatureBuilder.Prediction("pred").from_column().as_predictor()
    xf = FeatureBuilder.OPVector("features").from_column().as_predictor()
    est = RecordInsightsCorr(top_k=2)
    est.set_input(pf, xf)
    model = est.fit(store)
    assert model.corr.shape == (2, d)
    assert abs(model.corr[1, 2]) > 0.8       # x2 ↔ P(1) strongly correlated

    out = model.transform_columns(store)
    row = json.loads(out.get_raw(0))
    assert any(k.startswith("x2") for k in row)
    # save/load round trip via contract machinery
    from tests.test_stage_contracts import _roundtrip
    m2 = _roundtrip(model)
    np.testing.assert_allclose(m2.corr, model.corr)
