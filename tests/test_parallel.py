"""Mesh sharding tests — distributed CV on the 8-device virtual mesh,
plus the PR 6 mainline-mesh promotion suite (process-default mesh,
degenerate single-device parity, sharded fitstats/scoring parity)."""
import jax
import numpy as np
import pytest

from transmogrifai_tpu.models import CrossValidation, LogisticRegressionFamily
from transmogrifai_tpu.parallel import mesh as pmesh
from transmogrifai_tpu.parallel.mesh import (make_mesh, mesh_if_multi,
                                             mesh_topology,
                                             process_default_mesh,
                                             set_process_mesh,
                                             shard_cv_inputs)


@pytest.fixture
def _restore_process_mesh():
    """Tests that pin the process mesh must not leak it to the suite."""
    prev = set_process_mesh(None)
    try:
        yield
    finally:
        set_process_mesh(prev)


def test_make_mesh_shapes():
    mesh = make_mesh(n_devices=8, grid_size=24)
    assert mesh.shape["data"] * mesh.shape["grid"] == 8
    assert mesh.shape["grid"] == 8  # grid-heavy split
    mesh2 = make_mesh(n_devices=8, grid_size=1)
    assert mesh2.shape == {"data": 8, "grid": 1}
    mesh3 = make_mesh(n_devices=8, grid_size=2)
    assert mesh3.shape == {"data": 4, "grid": 2}


def test_make_mesh_every_power_of_two_split():
    """The 1/2/4/8-device splits the conftest mesh supports, including
    the grid_size=1 degenerate (pure data) case per device count."""
    for d in (1, 2, 4, 8):
        m = make_mesh(n_devices=d, grid_size=1)
        assert m.shape == {"data": d, "grid": 1}
        assert m.devices.size == d
        m2 = make_mesh(n_devices=d, grid_size=8)
        assert m2.shape["data"] * m2.shape["grid"] == d


def test_make_mesh_rejects_impossible_splits():
    with pytest.raises(ValueError, match="n_devices must be >= 1"):
        make_mesh(n_devices=0)
    # oversubscription must raise, not silently shrink to what exists
    with pytest.raises(ValueError, match="exceeds the 8 visible"):
        make_mesh(n_devices=16)
    with pytest.raises(ValueError, match="impossible \\(data, grid\\)"):
        make_mesh(n_devices=8, grid_axis=3)
    with pytest.raises(ValueError, match="impossible"):
        make_mesh(n_devices=4, grid_axis=8)
    with pytest.raises(ValueError, match="no devices"):
        make_mesh(devices=[])
    # explicit valid split
    m = make_mesh(n_devices=8, grid_axis=4)
    assert m.shape == {"data": 2, "grid": 4}


def test_process_default_mesh_cached_and_counted(_restore_process_mesh):
    m1 = process_default_mesh()
    c0 = pmesh.mesh_constructions()
    m2 = process_default_mesh()
    assert m1 is m2, "the process mesh must be built once and cached"
    assert pmesh.mesh_constructions() == c0
    assert m1.devices.size == len(jax.devices())
    # set/restore roundtrip (the runner's run-scoped knob path)
    small = make_mesh(n_devices=2)
    prev = set_process_mesh(small)
    assert prev is m1 and process_default_mesh() is small
    set_process_mesh(prev)
    assert process_default_mesh() is m1


def test_mesh_if_multi_degenerate_resolves_to_none():
    assert mesh_if_multi(None) is None
    assert mesh_if_multi(make_mesh(n_devices=1)) is None
    m = make_mesh(n_devices=8)
    assert mesh_if_multi(m) is m


def test_mesh_topology_doc():
    topo = mesh_topology(make_mesh(n_devices=8, grid_axis=2))
    assert topo["devices"] == 8 and topo["data"] == 4 \
        and topo["grid"] == 2
    assert topo["platform"] == "cpu" and topo["enabled"] is True


def test_cv_with_mesh_matches_unsharded(rng):
    n, d = 128, 6
    X = rng.normal(size=(n, d))
    y = (X @ rng.normal(size=d) > 0).astype(float)
    fams = lambda: [LogisticRegressionFamily(
        grid=[{"regParam": r, "elasticNetParam": 0.0}
              for r in (0.0, 0.01, 0.1, 0.2)])]
    cv = CrossValidation(num_folds=4, metric_name="AuROC", task="binary")
    _, hp_plain, summ_plain = cv.validate(fams(), X, y)

    mesh = make_mesh(grid_size=16)
    cv2 = CrossValidation(num_folds=4, metric_name="AuROC", task="binary")
    _, hp_mesh, summ_mesh = cv2.validate(fams(), X, y, mesh=mesh)

    assert hp_plain == hp_mesh
    for a, b in zip(summ_plain.results, summ_mesh.results):
        np.testing.assert_allclose(a.mean_metric, b.mean_metric, atol=1e-6)


def test_shard_cv_inputs_places_rows():
    mesh = make_mesh(grid_size=2)
    X = np.ones((16, 4), dtype=np.float32)
    y = np.ones(16, dtype=np.float32)
    w = np.ones((2, 16), dtype=np.float32)
    Xs, ys, ws, n_orig = shard_cv_inputs(mesh, X, y, w)
    assert n_orig == 16
    assert Xs.sharding.is_equivalent_to(
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("data", None)),
        ndim=2)


def test_shard_cv_inputs_pads_ragged_rows():
    mesh = make_mesh(grid_size=1)  # data axis = 8
    n = 13  # not divisible by 8
    X = np.ones((n, 3), dtype=np.float32)
    y = np.ones(n, dtype=np.float32)
    w = np.ones((2, n), dtype=np.float32)
    Xs, ys, ws, n_orig = shard_cv_inputs(mesh, X, y, w)
    assert n_orig == 13 and Xs.shape[0] == 16
    assert np.asarray(ws)[:, 13:].sum() == 0  # padding rows carry no weight


def test_full_titanic_workflow_under_mesh(rng):
    """The FULL flagship workflow (feature engineering → sanity check →
    CV sweep → refit → holdout eval) must run under a multi-device mesh —
    the distributed substrate rides the product path, not just unit tests
    (VERDICT r1 #2). Runs on the 8-device virtual CPU mesh."""
    import os
    import sys
    examples = os.path.join(os.path.dirname(__file__), os.pardir,
                            "examples")
    sys.path.insert(0, examples)
    try:
        import jax
        from titanic import run
    finally:
        sys.path.remove(examples)

    assert len(jax.devices()) >= 8, "conftest must provide 8 CPU devices"
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.parallel.mesh import make_mesh
    mesh = make_mesh()
    out = run(num_folds=3, families=[LogisticRegressionFamily()],
              mesh=mesh, seed=42)
    s = out["summary"]
    assert s.best_model_name == "OpLogisticRegression"
    holdout = s.holdout_evaluation or {}
    assert holdout.get("AuPR", 0) > 0.6
    # and the unsharded run agrees on the winner + metric
    out2 = run(num_folds=3, families=[LogisticRegressionFamily()],
               mesh=False, seed=42)

    import numpy as np
    m1 = out["summary"].validator_summary.best.mean_metric
    m2 = out2["summary"].validator_summary.best.mean_metric
    np.testing.assert_allclose(m1, m2, rtol=1e-4)


def test_chunked_sweep_under_mesh_matches_unchunked(rng):
    """Host-level (fold × grid) chunk re-dispatch composes with GSPMD
    sharding: slicing the sharded fold-weight arrays per chunk reshards
    transparently. This is the 10M-row v5e-8 regime (big rows force
    chunking AND the data mesh) in miniature."""
    from transmogrifai_tpu.models import tuning
    from transmogrifai_tpu.models.trees import RandomForestFamily

    n, d = 96, 5
    X = rng.normal(size=(n, d))
    y = (X[:, 0] > 0).astype(float)

    def fams():
        return [RandomForestFamily(grid=[
            {"maxDepth": dep, "minInstancesPerNode": 2} for dep in (2, 3)])]

    cv = CrossValidation(num_folds=2, metric_name="AuROC", task="binary")
    mesh = make_mesh(grid_size=4)
    _, hp_plain, summ_plain = cv.validate(fams(), X, y, mesh=mesh)

    saved = tuning.CHUNK_MEM_BUDGET_BYTES
    try:
        tuning.CHUNK_MEM_BUDGET_BYTES = 1    # fold_chunk=1, grid_chunk=1
        _, hp_chunk, summ_chunk = cv.validate(fams(), X, y, mesh=mesh)
    finally:
        tuning.CHUNK_MEM_BUDGET_BYTES = saved

    assert hp_plain == hp_chunk
    plain = {(r.family_name, r.grid_index): r.mean_metric
             for r in summ_plain.results}
    chunk = {(r.family_name, r.grid_index): r.mean_metric
             for r in summ_chunk.results}
    assert plain.keys() == chunk.keys()
    for k in plain:
        np.testing.assert_allclose(plain[k], chunk[k], rtol=1e-6)


# ---------------------------------------------------------------------------
# PR 6: the mesh as the mainline substrate
# ---------------------------------------------------------------------------


def _records(rng, n=300):
    y = rng.integers(0, 2, n).astype(float)
    x = rng.normal(size=n) + y
    x2 = rng.normal(size=n) - 0.5 * y
    return [{"label": float(y[i]), "x": float(x[i]), "x2": float(x2[i])}
            for i in range(n)]


def _binary_flow(seed=5):
    from transmogrifai_tpu import FeatureBuilder, Workflow
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify

    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    fx2 = FeatureBuilder.Real("x2").from_column().as_predictor()
    vec = transmogrify([fx, fx2])
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=seed)
    pred = label.transform_with(selector, vec)
    return Workflow().set_result_features(pred), selector, pred


def test_workflow_train_threads_process_mesh_to_selector(rng):
    """The tentpole wiring: a plain train() on a multi-device host hands
    the process-default mesh to the CV sweep — no opt-in anywhere."""
    wf, selector, _pred = _binary_flow()
    assert selector.mesh is None
    wf.set_input_records(_records(rng)).train()
    assert selector.mesh is process_default_mesh()
    assert selector.mesh.devices.size == len(jax.devices())


def test_workflow_set_mesh_false_forces_unsharded(rng):
    wf, selector, _pred = _binary_flow()
    wf.set_mesh(False).set_input_records(_records(rng)).train()
    assert selector.mesh is None


def test_workflow_retrain_re_resolves_auto_assigned_mesh(rng):
    """A workflow-assigned selector mesh is not a permanent pin: a
    retrain after set_mesh(False) (or under a different process mesh)
    re-resolves it, while an explicitly constructed mesh= survives."""
    from transmogrifai_tpu import Workflow

    records = _records(rng)
    wf, selector, pred = _binary_flow()
    wf.set_input_records(records).train()
    assert selector.mesh is process_default_mesh()
    wf.set_mesh(False).train()
    assert selector.mesh is None            # re-resolved, not pinned
    wf.set_mesh(None).train()
    assert selector.mesh is process_default_mesh()
    # a DIFFERENT workflow over the same DAG also re-resolves an
    # auto-assigned mesh — the marker lives on the stage, so workflow
    # A's assignment never masquerades as an explicit pin to workflow B
    wf_b = (Workflow().set_result_features(pred).set_mesh(False)
            .set_input_records(records))
    wf_b.train()
    assert selector.mesh is None
    # explicit construction-time mesh is never overwritten
    pinned = make_mesh(n_devices=2)
    wf2, sel2, _p2 = _binary_flow()
    sel2.mesh = pinned
    wf2.set_input_records(records).train()
    assert sel2.mesh is pinned


def test_train_emits_on_mesh_listener_and_gauges(rng):
    from transmogrifai_tpu import telemetry
    telemetry.enable()
    try:
        telemetry.reset(keep_listeners=False)
        collector = telemetry.add_listener(
            telemetry.CollectingRunListener())
        wf, _sel, _pred = _binary_flow()
        wf.set_input_records(_records(rng)).train()
        topo = mesh_topology(process_default_mesh())
        assert collector.mesh == {
            "devices": topo["devices"], "data": topo["data"],
            "grid": topo["grid"], "platform": topo["platform"]}
        assert collector.summary()["mesh"]["devices"] == topo["devices"]
        assert telemetry.gauge("mesh.data_axis").value == topo["data"]
        assert telemetry.gauge("mesh.grid_axis").value == topo["grid"]
    finally:
        telemetry.disable()
        telemetry.reset()


def test_degenerate_mesh_parity_bit_identical(rng, monkeypatch,
                                              _restore_process_mesh):
    """The degenerate-mesh acceptance suite: with the process mesh
    pinned to ONE device, score/transform/fit results are bit-identical
    to the pre-promotion (mesh machinery disabled) path — the
    single-device path is the mesh's special case, not a fork."""
    records = _records(rng, n=300)

    def train_and_score(store_records):
        wf, selector, pred = _binary_flow()
        model = wf.set_input_records(store_records).train()
        store = model.transform(list(store_records))
        scores = model.score(list(store_records), engine=False)
        summ = model.fitted_stages[selector.uid].selector_summary
        return model, store, scores, summ

    # leg A: mesh promotion ON, degenerate 1-device process mesh
    set_process_mesh(make_mesh(n_devices=1))
    model_a, store_a, scores_a, summ_a = train_and_score(records)

    # leg B: mesh machinery disabled entirely (the pre-PR6 behavior)
    monkeypatch.setattr(pmesh, "MESH_ENABLED", False)
    model_b, store_b, scores_b, summ_b = train_and_score(records)

    assert summ_a.best_model_name == summ_b.best_model_name
    assert summ_a.validator_summary.best.mean_metric \
        == summ_b.validator_summary.best.mean_metric
    pa = scores_a[scores_a.names()[0]]
    pb = scores_b[scores_b.names()[0]]
    assert np.array_equal(pa.prediction, pb.prediction)
    assert np.array_equal(pa.probability, pb.probability)
    # column names embed per-flow uids — compare positionally
    for na, nb in zip(store_a.names(), store_b.names()):
        ca, cb = store_a[na], store_b[nb]
        va = getattr(ca, "values", None)
        if isinstance(va, np.ndarray):
            assert np.array_equal(
                np.asarray(va, dtype=np.float64),
                np.asarray(cb.values, dtype=np.float64),
            ), (na, nb)


def test_degenerate_mesh_fitstats_bit_identical(rng, _restore_process_mesh):
    """Fit-statistics device tier: a 1-device degenerate mesh computes
    the exact bytes the unsharded pass computes."""
    from transmogrifai_tpu import ColumnStore, column_from_values
    from transmogrifai_tpu.fitstats import LayerStatsPlan, StatRequest
    from transmogrifai_tpu.types import feature_types as ft

    n = 2048
    vals = [None if rng.random() < 0.1 else float(v)
            for v in rng.normal(size=n) * 100]
    store = ColumnStore({"x": column_from_values(ft.Real, vals)}, n)
    reqs = [StatRequest(k, "x") for k in
            ("count", "mean", "variance", "std", "min", "max")]
    plan = LayerStatsPlan(reqs, n_stages=2)
    set_process_mesh(make_mesh(n_devices=1))
    res_deg = plan.run(store, device=True)
    res_off = plan.run(store, device=True, mesh=False)
    for r in reqs:
        assert res_deg.for_request(r) == res_off.for_request(r), r.kind


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="sharded parity needs >= 2 devices")
def test_sharded_fitstats_merged_moments_parity(rng):
    """Device-count-gated: the data-axis-sharded stats fold (psum) must
    reproduce the unsharded merged moments — counts/extrema exactly,
    f-moments to reassociation tolerance."""
    from transmogrifai_tpu import ColumnStore, column_from_values
    from transmogrifai_tpu.fitstats import LayerStatsPlan, StatRequest
    from transmogrifai_tpu.types import feature_types as ft

    n = 4096
    cols = {}
    for j in range(3):
        vals = [None if rng.random() < 0.1 else float(v)
                for v in rng.normal(size=n) * 10 ** j]
        cols[f"x{j}"] = column_from_values(ft.Real, vals)
    store = ColumnStore(cols, n)
    reqs = [StatRequest(k, f"x{j}") for j in range(3)
            for k in ("count", "mean", "variance", "std", "min", "max")]
    plan = LayerStatsPlan(reqs, n_stages=3)
    sharded = plan.run(store, device=True,
                       mesh=process_default_mesh())
    plain = plan.run(store, device=True, mesh=False)
    for r in reqs:
        a, b = sharded.for_request(r), plain.for_request(r)
        if r.kind in ("count", "min", "max"):
            assert a == b, (r.kind, r.column, a, b)
        else:
            np.testing.assert_allclose(a, b, rtol=1e-9,
                                       err_msg=f"{r.kind}/{r.column}")


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="sharded scoring needs >= 2 devices")
def test_engine_sharded_scoring_parity_and_cache_keying(rng, monkeypatch):
    """The scoring engine's data-sharded bucket dispatch must score
    identically to the unsharded engine, and the program cache must key
    the two apart (a single-device executable and a sharded one never
    collide)."""
    import transmogrifai_tpu.workflow as wfmod
    from transmogrifai_tpu.scoring import ScoringEngine

    monkeypatch.setattr(wfmod, "_DEVICE_BW_MBPS", 1e9)  # gate open
    wf, _sel, _pred = _binary_flow()
    records = _records(rng, n=512)
    model = wf.set_input_records(records).train()

    eng_plain = ScoringEngine(model, mesh=False)
    eng_mesh = ScoringEngine(model, mesh=process_default_mesh())
    # score from raw records both ways
    sp = eng_plain.score_store(list(records))
    sm = eng_mesh.score_store(list(records))
    assert sp.names() == sm.names()
    pa, pb = sp[sp.names()[0]], sm[sm.names()[0]]
    assert np.array_equal(pa.prediction, pb.prediction)
    np.testing.assert_allclose(pa.probability, pb.probability,
                               rtol=1e-12, atol=0)
    # distinct cache keys: same block shapes, different mesh
    k_plain = eng_plain._signature({}, {}, ("p",), None)
    k_mesh = eng_plain._signature({}, {}, ("p",),
                                  (("data", 8), ("grid", 1)))
    assert k_plain != k_mesh
