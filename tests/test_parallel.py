"""Mesh sharding tests — distributed CV on the 8-device virtual mesh."""
import jax
import numpy as np
import pytest

from transmogrifai_tpu.models import CrossValidation, LogisticRegressionFamily
from transmogrifai_tpu.parallel.mesh import make_mesh, shard_cv_inputs


def test_make_mesh_shapes():
    mesh = make_mesh(n_devices=8, grid_size=24)
    assert mesh.shape["data"] * mesh.shape["grid"] == 8
    assert mesh.shape["grid"] == 8  # grid-heavy split
    mesh2 = make_mesh(n_devices=8, grid_size=1)
    assert mesh2.shape == {"data": 8, "grid": 1}
    mesh3 = make_mesh(n_devices=8, grid_size=2)
    assert mesh3.shape == {"data": 4, "grid": 2}


def test_cv_with_mesh_matches_unsharded(rng):
    n, d = 128, 6
    X = rng.normal(size=(n, d))
    y = (X @ rng.normal(size=d) > 0).astype(float)
    fams = lambda: [LogisticRegressionFamily(
        grid=[{"regParam": r, "elasticNetParam": 0.0}
              for r in (0.0, 0.01, 0.1, 0.2)])]
    cv = CrossValidation(num_folds=4, metric_name="AuROC", task="binary")
    _, hp_plain, summ_plain = cv.validate(fams(), X, y)

    mesh = make_mesh(grid_size=16)
    cv2 = CrossValidation(num_folds=4, metric_name="AuROC", task="binary")
    _, hp_mesh, summ_mesh = cv2.validate(fams(), X, y, mesh=mesh)

    assert hp_plain == hp_mesh
    for a, b in zip(summ_plain.results, summ_mesh.results):
        np.testing.assert_allclose(a.mean_metric, b.mean_metric, atol=1e-6)


def test_shard_cv_inputs_places_rows():
    mesh = make_mesh(grid_size=2)
    X = np.ones((16, 4), dtype=np.float32)
    y = np.ones(16, dtype=np.float32)
    w = np.ones((2, 16), dtype=np.float32)
    Xs, ys, ws, n_orig = shard_cv_inputs(mesh, X, y, w)
    assert n_orig == 16
    assert Xs.sharding.is_equivalent_to(
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("data", None)),
        ndim=2)


def test_shard_cv_inputs_pads_ragged_rows():
    mesh = make_mesh(grid_size=1)  # data axis = 8
    n = 13  # not divisible by 8
    X = np.ones((n, 3), dtype=np.float32)
    y = np.ones(n, dtype=np.float32)
    w = np.ones((2, n), dtype=np.float32)
    Xs, ys, ws, n_orig = shard_cv_inputs(mesh, X, y, w)
    assert n_orig == 13 and Xs.shape[0] == 16
    assert np.asarray(ws)[:, 13:].sum() == 0  # padding rows carry no weight


def test_full_titanic_workflow_under_mesh(rng):
    """The FULL flagship workflow (feature engineering → sanity check →
    CV sweep → refit → holdout eval) must run under a multi-device mesh —
    the distributed substrate rides the product path, not just unit tests
    (VERDICT r1 #2). Runs on the 8-device virtual CPU mesh."""
    import os
    import sys
    examples = os.path.join(os.path.dirname(__file__), os.pardir,
                            "examples")
    sys.path.insert(0, examples)
    try:
        import jax
        from titanic import run
    finally:
        sys.path.remove(examples)

    assert len(jax.devices()) >= 8, "conftest must provide 8 CPU devices"
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.parallel.mesh import make_mesh
    mesh = make_mesh()
    out = run(num_folds=3, families=[LogisticRegressionFamily()],
              mesh=mesh, seed=42)
    s = out["summary"]
    assert s.best_model_name == "OpLogisticRegression"
    holdout = s.holdout_evaluation or {}
    assert holdout.get("AuPR", 0) > 0.6
    # and the unsharded run agrees on the winner + metric
    out2 = run(num_folds=3, families=[LogisticRegressionFamily()],
               mesh=False, seed=42)

    import numpy as np
    m1 = out["summary"].validator_summary.best.mean_metric
    m2 = out2["summary"].validator_summary.best.mean_metric
    np.testing.assert_allclose(m1, m2, rtol=1e-4)


def test_chunked_sweep_under_mesh_matches_unchunked(rng):
    """Host-level (fold × grid) chunk re-dispatch composes with GSPMD
    sharding: slicing the sharded fold-weight arrays per chunk reshards
    transparently. This is the 10M-row v5e-8 regime (big rows force
    chunking AND the data mesh) in miniature."""
    from transmogrifai_tpu.models import tuning
    from transmogrifai_tpu.models.trees import RandomForestFamily

    n, d = 96, 5
    X = rng.normal(size=(n, d))
    y = (X[:, 0] > 0).astype(float)

    def fams():
        return [RandomForestFamily(grid=[
            {"maxDepth": dep, "minInstancesPerNode": 2} for dep in (2, 3)])]

    cv = CrossValidation(num_folds=2, metric_name="AuROC", task="binary")
    mesh = make_mesh(grid_size=4)
    _, hp_plain, summ_plain = cv.validate(fams(), X, y, mesh=mesh)

    saved = tuning.CHUNK_MEM_BUDGET_BYTES
    try:
        tuning.CHUNK_MEM_BUDGET_BYTES = 1    # fold_chunk=1, grid_chunk=1
        _, hp_chunk, summ_chunk = cv.validate(fams(), X, y, mesh=mesh)
    finally:
        tuning.CHUNK_MEM_BUDGET_BYTES = saved

    assert hp_plain == hp_chunk
    plain = {(r.family_name, r.grid_index): r.mean_metric
             for r in summ_plain.results}
    chunk = {(r.family_name, r.grid_index): r.mean_metric
             for r in summ_chunk.results}
    assert plain.keys() == chunk.keys()
    for k in plain:
        np.testing.assert_allclose(plain[k], chunk[k], rtol=1e-6)
