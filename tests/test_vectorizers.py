"""Vectorizer tests (parity with core/.../impl/feature tests)."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, column_from_values
from transmogrifai_tpu.ops import (BinaryVectorizer, IntegralVectorizer,
                                   OneHotVectorizer, RealVectorizer,
                                   SetVectorizer, SmartTextVectorizer,
                                   TextTokenizer, transmogrify)
from transmogrifai_tpu.ops.hashing import HashingVectorizerModel, murmur3_32
from transmogrifai_tpu.ops.dates import DateToUnitCircleVectorizer, TimePeriod
from transmogrifai_tpu.ops.geo import GeolocationVectorizer
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.workflow import Workflow


def _fit_transform(stage, store, *features):
    features[0].transform_with(stage, *features[1:])
    model = stage.fit(store) if hasattr(stage, "fit_columns") and not \
        hasattr(stage, "vocabs") else stage
    from transmogrifai_tpu.stages.base import Estimator
    if isinstance(stage, Estimator):
        model = stage.fit(store)
    else:
        model = stage
    return model, model.transform_columns(store)


def test_real_vectorizer_mean_impute():
    a = FeatureBuilder.Real("a").from_column().as_predictor()
    b = FeatureBuilder.Real("b").from_column().as_predictor()
    store = ColumnStore.from_dict({
        "a": (ft.Real, [1.0, None, 3.0]),
        "b": (ft.Real, [10.0, 20.0, None]),
    })
    est = RealVectorizer()
    model, out = _fit_transform(est, store, a, b)
    # layout: [a, a_null, b, b_null]
    np.testing.assert_allclose(out.values, [
        [1.0, 0.0, 10.0, 0.0],
        [2.0, 1.0, 20.0, 0.0],
        [3.0, 0.0, 15.0, 1.0],
    ])
    meta = out.metadata
    assert meta.size == 4
    assert meta.columns[1].is_null_indicator()
    assert meta.columns[2].parent_feature_name == "b"


def test_integral_mode_impute():
    a = FeatureBuilder.Integral("a").from_column().as_predictor()
    store = ColumnStore.from_dict({"a": (ft.Integral, [5, 5, 7, None])})
    model, out = _fit_transform(IntegralVectorizer(), store, a)
    np.testing.assert_allclose(out.values[:, 0], [5, 5, 7, 5])
    assert out.values[3, 1] == 1.0  # null tracked


def test_binary_vectorizer():
    a = FeatureBuilder.Binary("a").from_column().as_predictor()
    store = ColumnStore.from_dict({"a": (ft.Binary, [True, None, False])})
    model, out = _fit_transform(BinaryVectorizer(), store, a)
    np.testing.assert_allclose(out.values, [[1, 0], [0, 1], [0, 0]])


def test_onehot_topk_other_null():
    a = FeatureBuilder.PickList("color").from_column().as_predictor()
    values = ["red"] * 5 + ["blue"] * 3 + ["green"] * 1 + [None]
    store = ColumnStore.from_dict({"color": (ft.PickList, values)})
    est = OneHotVectorizer(top_k=2, min_support=2)
    model, out = _fit_transform(est, store, a)
    assert model.vocabs == [["red", "blue"]]  # green below min_support
    # columns: red, blue, OTHER, null
    assert out.values.shape == (10, 4)
    np.testing.assert_allclose(out.values[0], [1, 0, 0, 0])
    np.testing.assert_allclose(out.values[5], [0, 1, 0, 0])
    np.testing.assert_allclose(out.values[8], [0, 0, 1, 0])  # green -> OTHER
    np.testing.assert_allclose(out.values[9], [0, 0, 0, 1])  # null
    assert out.metadata.columns[2].is_other_indicator()


def test_set_vectorizer():
    a = FeatureBuilder.MultiPickList("tags").from_column().as_predictor()
    store = ColumnStore.from_dict({
        "tags": (ft.MultiPickList, [["a", "b"], ["a"], [], ["c"]])})
    est = SetVectorizer(top_k=2, min_support=1)
    model, out = _fit_transform(est, store, a)
    # vocab: a (2), b (1), c (1) -> ties by value: [a, b]
    assert model.vocabs == [["a", "b"]]
    np.testing.assert_allclose(out.values[0][:2], [1, 1])
    assert out.values[2][3] == 1.0  # null slot
    assert out.values[3][2] == 1.0  # c -> OTHER


def test_murmur3_known_values():
    # standard murmur3_x86_32 test vectors (public algorithm)
    assert murmur3_32(b"", 0) == 0
    assert murmur3_32(b"", 1) == 0x514E28B7
    assert murmur3_32(b"hello", 0) == 0x248BFA47
    assert murmur3_32(b"hello, world", 0) == 0x149BBB7F


def test_hashing_vectorizer():
    a = FeatureBuilder.TextList("toks").from_column().as_predictor()
    store = ColumnStore.from_dict({
        "toks": (ft.TextList, [["x", "y", "x"], [], ["z"]])})
    model = HashingVectorizerModel(num_features=16, input_names=["toks"])
    a.transform_with(model)
    out = model.transform_columns(store)
    assert out.values.shape == (3, 17)  # 16 + null
    assert out.values[0].sum() == 3.0  # token counts
    assert out.values[1, 16] == 1.0  # null tracked
    assert out.metadata.size == 17


def test_smart_text_routes_by_cardinality():
    cat = FeatureBuilder.Text("cat").from_column().as_predictor()
    free = FeatureBuilder.Text("free").from_column().as_predictor()
    n = 30
    store = ColumnStore.from_dict({
        "cat": (ft.Text, ["a" if i % 2 else "b" for i in range(n)]),
        "free": (ft.Text, [f"unique text number {i}" for i in range(n)]),
    })
    est = SmartTextVectorizer(max_cardinality=5, top_k=3, min_support=1,
                              num_features=32)
    model, out = _fit_transform(est, store, cat, free)
    assert model.is_categorical == [True, False]
    # cat: 3+1+1 = top3 is only 2 values -> 2+1+1=4 cols; free: 32 + null
    assert out.values.shape[1] == model.vector_metadata().size
    assert out.metadata.columns[0].indicator_value in ("a", "b")


def test_date_unit_circle():
    d = FeatureBuilder.Date("d").from_column().as_predictor()
    ms_noon = 12 * 3600 * 1000  # epoch day 0 at noon
    store = ColumnStore.from_dict({"d": (ft.Date, [ms_noon, None])})
    model = DateToUnitCircleVectorizer(periods=[TimePeriod.HOUR_OF_DAY],
                                       input_names=["d"])
    d.transform_with(model)
    out = model.transform_columns(store)
    # noon -> theta = pi -> sin=0, cos=-1 (f32-native pipeline: atol at
    # f32 eps — sin(float32(pi)) is ~-8.7e-8, not 0)
    np.testing.assert_allclose(out.values[0, :2], [0.0, -1.0], atol=1e-6)
    assert out.values[1, 2] == 1.0  # null


def test_geo_vectorizer_fill_geo_mean():
    g = FeatureBuilder.Geolocation("loc").from_column().as_predictor()
    store = ColumnStore.from_dict({
        "loc": (ft.Geolocation, [[10.0, 20.0, 1.0], [20.0, 30.0, 3.0], None])})
    est = GeolocationVectorizer()
    model, out = _fit_transform(est, store, g)
    assert out.values.shape == (3, 4)
    filled = out.values[2]
    assert 10.0 < filled[0] < 20.0 and 20.0 < filled[1] < 30.0
    assert filled[3] == 1.0


def test_text_tokenizer():
    t = FeatureBuilder.Text("t").from_column().as_predictor()
    tok = TextTokenizer()
    out_feat = t.transform_with(tok)
    assert out_feat.ftype is ft.TextList
    store = ColumnStore.from_dict({"t": (ft.Text, ["Hello, World!", None])})
    out = tok.transform_columns(store)
    assert out.values[0] == ["hello", "world"]
    assert out.values[1] == []


def test_transmogrify_end_to_end_workflow():
    age = FeatureBuilder.Real("age").from_column().as_predictor()
    cls = FeatureBuilder.Integral("cls").from_column().as_predictor()
    sex = FeatureBuilder.PickList("sex").from_column().as_predictor()
    vec = transmogrify([age, cls, sex])
    store = ColumnStore.from_dict({
        "age": (ft.Real, [22.0, None, 30.0, 41.0]),
        "cls": (ft.Integral, [1, 2, 3, None]),
        "sex": (ft.PickList, ["m", "f", "m", None]),
    })
    wf = Workflow().set_input_store(store).set_result_features(vec)
    model = wf.train()
    scored = model.score(store, keep_intermediate=True)
    out = scored[vec.name]
    assert out.values.shape[0] == 4
    assert out.metadata is not None
    assert out.values.shape[1] == out.metadata.size
    # every parent feature is represented in provenance
    assert set(out.metadata.parent_features()) >= {"age", "cls", "sex"}
    # score_fn row path agrees with columnar path
    fn = model.score_fn()
    row_out = fn({"age": 22.0, "cls": 1, "sex": "m"})
    np.testing.assert_allclose(np.asarray(row_out[vec.name]), out.values[0])


def test_string_indexer_roundtrip(rng):
    """OpStringIndexerNoFilter → PredictionDeIndexer label round-trip
    (OpStringIndexerNoFilter.scala:48-74, PredictionDeIndexer.scala:52-88)."""
    from transmogrifai_tpu.columns import PredictionColumn
    from transmogrifai_tpu.ops.indexers import (OpIndexToStringNoFilter,
                                                OpStringIndexerNoFilter,
                                                PredictionDeIndexer)

    vals = ["b", "a", "b", None, "c", "b", "a"]
    store = ColumnStore({"lbl": column_from_values(ft.Text, vals)})
    f = FeatureBuilder.Text("lbl").from_column().as_response()
    est = OpStringIndexerNoFilter()
    est.set_input(f)
    model = est.fit(store)
    # frequency desc: b(3), a(2), then c/null(1 each, label asc)
    assert model.labels == ["b", "a", "c", "null"]
    out = model.transform(store)
    col = out[model.output_name]
    assert col.values.tolist() == [0.0, 1.0, 0.0, 3.0, 2.0, 0.0, 1.0]
    assert col.labels[-1] == "UnseenLabel"

    # idx2str
    i2s = OpIndexToStringNoFilter(labels=model.labels)
    i2s.set_input(model.get_output())
    back = i2s.transform_columns(out)
    assert back.values.tolist() == ["b", "a", "b", "null", "c", "b", "a"]

    # deindex a Prediction column via the response metadata
    pred_col = PredictionColumn(np.array([1.0, 0.0, 9.0]),
                                np.zeros((3, 0)), np.zeros((3, 0)))
    st2 = ColumnStore({model.output_name: col.take(np.array([0, 1, 2])),
                       "pred": pred_col})
    pf = FeatureBuilder.Prediction("pred").from_column().as_predictor()
    de = PredictionDeIndexer()
    de.set_input(model.get_output(), pf)
    dm = de.fit(st2)
    got = dm.transform_columns(st2)
    assert got.values.tolist() == ["a", "b", "UnseenLabel"]


def test_native_hasher_matches_python():
    """The C++ batch murmur3 (native/fasthash.cc, lazily built at first
    use) must be bit-exact with the pure-Python reference implementation."""
    from transmogrifai_tpu.ops import hashing as H

    tokens = ["", "a", "hello", "héllo wörld", "x" * 100, "abc", "abcd",
              "abcde", "abcdef", "abcdefg"]
    expected = np.array([H.murmur3_32(t.encode("utf-8"), 42)
                         for t in tokens], dtype=np.uint32)
    got = H.hash_tokens(tokens, 42)
    np.testing.assert_array_equal(got, expected)
    if H._load_native():
        # force the native path explicitly and compare again
        got2 = H.hash_tokens(tokens, 7)
        exp2 = np.array([H.murmur3_32(t.encode("utf-8"), 7)
                         for t in tokens], dtype=np.uint32)
        np.testing.assert_array_equal(got2, exp2)
