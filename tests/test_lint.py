"""Pre-flight static analysis tests (lint.py + tools/tmoglint.py).

Covers every rule id in the catalog (one positive + one clean fixture
each), the eval_shape device pre-flight on a representative
binary-classification workflow, runner pre-flight gating (``--fail-on``
behavior, no reader I/O on rejection — the compile-time type-safety
acceptance), the CLI ``check`` subcommand, and the meta-test asserting
the repo itself is clean under the AST self-lint.
"""
import importlib.util
import json
import os

import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder, Workflow, lint, telemetry
from transmogrifai_tpu.features import Feature
from transmogrifai_tpu.graph import compute_dag
from transmogrifai_tpu.lint import Finding, LintError, Severity
from transmogrifai_tpu.models.linear import LogisticRegressionFamily
from transmogrifai_tpu.models.selector import (
    BinaryClassificationModelSelector)
from transmogrifai_tpu.ops.smart_text import SmartTextVectorizer
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.ops.vectorizer_base import VectorizerModel
from transmogrifai_tpu.runner import OpParams, OpWorkflowRunner, RunType
from transmogrifai_tpu.stages.base import (Estimator, LambdaTransformer,
                                           VarArity)
from transmogrifai_tpu.types.feature_types import (FeatureType, OPVector,
                                                   Prediction, Real)
from transmogrifai_tpu.vector_metadata import (VectorColumnMetadata,
                                               VectorMetadata)
from transmogrifai_tpu.workflow import WorkflowError, WorkflowModel

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tmoglint():
    spec = importlib.util.spec_from_file_location(
        "tmoglint", os.path.join(_REPO, "tools", "tmoglint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _records(rng, n=200):
    y = rng.integers(0, 2, n).astype(float)
    x = rng.normal(size=n) + y
    return [{"label": float(y[i]), "x": float(x[i])} for i in range(n)]


def _binary_flow():
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    vec = transmogrify([fx])
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=5)
    pred = label.transform_with(selector, vec)
    return Workflow().set_result_features(pred), label, fx, vec, pred


def _mistyped_workflow():
    """A text vectorizer fed an OPVector by direct wiring (bypassing
    set_input — the hole the static checker exists to close)."""
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    vec = transmogrify([fx])
    tv = SmartTextVectorizer()
    tv.input_features = (vec,)
    return Workflow().set_result_features(tv.get_output()), tv, vec


class _CountingReader:
    """Reader that records whether any I/O happened."""

    def __init__(self, records):
        self._records = records
        self.calls = 0

    def read_records(self):
        self.calls += 1
        return list(self._records)


# ---------------------------------------------------------------------------
# TMG1xx graph rules
# ---------------------------------------------------------------------------


def test_tmg101_mistyped_edge_names_both_sides():
    wf, tv, vec = _mistyped_workflow()
    findings = lint.check_workflow(wf)
    f = next(f for f in findings if f.rule == "TMG101")
    assert f.severity == Severity.ERROR
    assert f.stage == tv.uid
    assert vec.name in f.message          # the offending feature
    assert "OPVector" in f.message and "Text" in f.message
    assert "SmartTextVectorizer" in f.message


def test_tmg101_clean_binary_workflow():
    wf, *_ = _binary_flow()
    assert lint.check_workflow(wf) == []
    assert wf.validate() == []            # the method form


def test_tmg102_duplicate_uid_detected_and_dag_raises():
    a = FeatureBuilder.Real("a").from_column().as_predictor()
    b = FeatureBuilder.Real("b").from_column().as_predictor()
    from transmogrifai_tpu.ops.numeric import RealVectorizer
    dup = "RealVectorizer_00000000beef"
    f1 = RealVectorizer(uid=dup).set_input(a).get_output()
    f2 = RealVectorizer(uid=dup).set_input(b).get_output()
    findings = lint.check_workflow([f1, f2])
    f = next(f for f in findings if f.rule == "TMG102")
    assert dup in (f.stage or "") and f.severity == Severity.ERROR
    # the silent dict-overwrite collapse is gone: compute_dag raises,
    # naming both stages
    with pytest.raises(ValueError, match="distinct stages sharing"):
        compute_dag([f1, f2])
    with pytest.raises(WorkflowError, match="duplicate stage uid"):
        Workflow().set_result_features(f1, f2)
    # distinct uids stay clean
    g1 = RealVectorizer().set_input(a).get_output()
    g2 = RealVectorizer().set_input(b).get_output()
    assert not [x for x in lint.check_workflow([g1, g2])
                if x.rule == "TMG102"]


def test_tmg103_cycle_reported_not_crashed():
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    vec = transmogrify([fx])
    vec.parents = (vec,)                  # self-ancestry by force
    findings = lint.check_workflow([vec])
    assert any(f.rule == "TMG103" and f.severity == Severity.ERROR
               for f in findings)


def test_tmg104_dead_fitted_stage_in_model():
    rng = np.random.default_rng(0)
    wf, *_ = _binary_flow()
    model = wf.set_input_records(_records(rng)).train()
    model.fitted_stages["Ghost_00000000dead"] = object()
    findings = lint.check_model(model, device=False)
    f = next(f for f in findings if f.rule == "TMG104")
    assert "Ghost_00000000dead" in f.message
    assert f.severity == Severity.WARNING


def test_tmg105_response_leakage_via_laundered_feature():
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    # a plain transformer mixing label with predictors, wired directly
    # (set_input would reject the direct mix — the static check catches
    # graphs that bypassed it)
    leak = LambdaTransformer("leak", lambda a, b: a,
                             [Real, Real], Real)
    leak.input_features = (label, fx)
    leaked = leak.get_output()
    vec = transmogrify([leaked])
    findings = lint.check_workflow([vec])
    f = next(f for f in findings if f.rule == "TMG105")
    assert f.severity == Severity.ERROR and f.stage == leak.uid
    assert "label" in f.message


def test_tmg105_sanctioned_label_consumers_stay_clean():
    # SanityChecker / ModelSelector are AllowLabelAsInput — the whole
    # representative DAG (label feeds both) must produce zero findings
    from transmogrifai_tpu.ops.sanity_checker import SanityChecker
    wf, label, fx, vec, pred = _binary_flow()
    checked = label.transform_with(SanityChecker(), vec)
    assert not [f for f in lint.check_workflow([checked, pred])
                if f.rule == "TMG105"]


class _AnyInputEstimator(Estimator):
    operation_name = "dummyEst"
    output_type = OPVector

    @property
    def input_spec(self):
        return VarArity(FeatureType)

    def fit_columns(self, store):          # pragma: no cover
        raise NotImplementedError


def test_tmg106_estimator_consuming_prediction_warns():
    p = FeatureBuilder.of(Prediction, "p").from_column().as_predictor()
    est = _AnyInputEstimator().set_input(p)
    findings = lint.check_workflow([est.get_output()])
    f = next(f for f in findings if f.rule == "TMG106")
    assert f.severity == Severity.WARNING and f.stage == est.uid


def test_tmg106_unfitted_estimator_in_scored_dag_errors():
    wf, label, fx, vec, pred = _binary_flow()
    model = WorkflowModel(result_features=[pred], fitted_stages={})
    findings = lint.check_model(model, device=False)
    bad = [f for f in findings if f.rule == "TMG106"
           and f.severity == Severity.ERROR]
    assert bad and any("unfitted estimator" in f.message for f in bad)


# ---------------------------------------------------------------------------
# TMG2xx device pre-flight (eval_shape — no data, no device)
# ---------------------------------------------------------------------------


class _BadVec(VectorizerModel):
    """Deliberately broken vectorizer: wrong width (TMG201), f64
    promotion (TMG202), scalar prepared block + batch-size-dependent
    signature (TMG203)."""

    operation_name = "badVec"
    seq_type = Real

    def host_prepare(self, store):
        col = store[self.input_features[0].name]
        n = len(col)
        out = {"x": np.nan_to_num(col.astype_float()),
               "n": float(n)}                      # bare Python scalar
        if n % 2 == 0:                             # signature flaps with n
            out["pad"] = np.zeros(3, dtype=np.float32)
        return out

    def device_compute(self, xp, prepared):
        x = xp.asarray(prepared["x"], dtype=xp.float64)   # f64 promotion
        return xp.stack([x, x, x], axis=1)                # width 3 != 2

    def vector_metadata(self):
        return VectorMetadata("bad", [VectorColumnMetadata("x", "Real"),
                                      VectorColumnMetadata("x", "Real")])


def test_tmg201_202_203_seeded_violation_fixture():
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    out = _BadVec().set_input(fx).get_output()
    model = WorkflowModel(result_features=[out], fitted_stages={})
    findings = lint.preflight_device(model)
    rules = {f.rule for f in findings}
    assert {"TMG201", "TMG202", "TMG203"} <= rules
    shape = next(f for f in findings if f.rule == "TMG201")
    assert "(8, 3)" in shape.message and "(8, 2)" in shape.message
    scalar = [f for f in findings if f.rule == "TMG203"]
    assert any("'n'" in f.message for f in scalar)      # the scalar block
    assert any("batch size" in f.message for f in scalar)


def test_tmg202_fires_under_x32_production_config():
    """Under x32 (the production TPU config) jax silently truncates an
    f64 request before eval_shape can see the dtype — the rule must
    still fire, via the truncation warning itself."""
    import jax
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    out = _BadVec().set_input(fx).get_output()
    model = WorkflowModel(result_features=[out], fitted_stages={})
    jax.config.update("jax_enable_x64", False)
    try:
        findings = lint.preflight_device(model)
    finally:
        jax.config.update("jax_enable_x64", True)
    assert any(f.rule == "TMG202" for f in findings)


def test_suppressed_graph_error_does_not_skip_device_pass():
    """Suppressing a known/accepted graph error must re-enable the
    TMG2xx shape analysis, not silently return a clean verdict."""
    p = FeatureBuilder.of(Prediction, "p").from_column().as_predictor()
    est = _AnyInputEstimator().set_input(p)
    model = WorkflowModel(result_features=[est.get_output()],
                          fitted_stages={})
    # unsuppressed: the TMG106 error gates the device pass, with a
    # TMG204 note saying so rather than a silent skip
    findings = lint.check_model(model, device=True)
    assert any(f.rule == "TMG106" and f.severity == Severity.ERROR
               for f in findings)
    assert any(f.rule == "TMG204" and "skipped" in f.message
               for f in findings)
    # suppressed: the device pass runs (and reports the unresolvable
    # estimator as coverage info, not a crash)
    findings = lint.check_model(model, device=True, suppress=["TMG106"])
    assert not any(f.severity == Severity.ERROR for f in findings)
    assert any(f.rule == "TMG204" for f in findings)


def test_suppress_accepts_bare_string():
    wf, tv, _vec = _mistyped_workflow()
    # "TMG101" (the easy JSON mistake for ["TMG101"]) must not be
    # iterated character-by-character
    assert lint.check_workflow(wf, suppress="TMG101") == []


class _MeshUnsafeVec(VectorizerModel):
    """Row dimension baked into the program: device_compute statically
    slices to 8 rows, so a second probe size exposes that zero-weight
    pad_rows cannot pad it to the mesh's data axis (TMG205)."""

    operation_name = "meshUnsafeVec"
    seq_type = Real

    def host_prepare(self, store):
        col = store[self.input_features[0].name]
        return {"x": np.nan_to_num(col.astype_float())}

    def device_compute(self, xp, prepared):
        x = xp.asarray(prepared["x"], dtype=xp.float32)
        return xp.stack([x, x], axis=1)[:8]       # static row count

    def vector_metadata(self):
        return VectorMetadata("mu", [VectorColumnMetadata("x", "Real"),
                                     VectorColumnMetadata("x", "Real")])


class _MeshSafeVec(_MeshUnsafeVec):
    """The clean twin: rows track the batch."""

    operation_name = "meshSafeVec"

    def device_compute(self, xp, prepared):
        x = xp.asarray(prepared["x"], dtype=xp.float32)
        return xp.stack([x, x], axis=1)


def test_tmg205_mesh_unsafe_row_dimension():
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    out = _MeshUnsafeVec().set_input(fx).get_output()
    model = WorkflowModel(result_features=[out], fitted_stages={})
    findings = lint.preflight_device(model)
    f = next(f for f in findings if f.rule == "TMG205")
    assert f.severity == Severity.ERROR and f.stage is not None
    assert "mesh" in f.message and "data axis" in f.message
    # it fires at the FIRST probe size that passes TMG201, i.e. before
    # any data is read — and the clean twin stays silent
    fx2 = FeatureBuilder.Real("x").from_column().as_predictor()
    ok = _MeshSafeVec().set_input(fx2).get_output()
    clean = WorkflowModel(result_features=[ok], fitted_stages={})
    assert not [f for f in lint.preflight_device(clean)
                if f.rule == "TMG205"]


def test_tmg206_vmem_envelope_warning(monkeypatch):
    """A stage whose extrapolated device-resident working set exceeds
    the (shrunk, for the test) VMEM envelope warns — and names the
    featureShards knob — while feature sharding stays disengaged."""
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    out = _MeshSafeVec().set_input(fx).get_output()
    model = WorkflowModel(result_features=[out], fitted_stages={})
    monkeypatch.setattr(lint, "VMEM_ENVELOPE_BYTES", 64)
    findings = lint.preflight_device(model)
    f = next(f for f in findings if f.rule == "TMG206")
    assert f.severity == Severity.WARNING and f.stage is not None
    assert "featureShards" in f.message and "VMEM" in f.message


def test_tmg206_silent_when_sharding_engaged_or_under_envelope(
        monkeypatch):
    from transmogrifai_tpu.models import _treefit
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    out = _MeshSafeVec().set_input(fx).get_output()
    model = WorkflowModel(result_features=[out], fitted_stages={})
    # under the default 16 MiB envelope the tiny fixture is silent
    assert not [f for f in lint.preflight_device(model)
                if f.rule == "TMG206"]
    # over the envelope but with feature sharding requested: silent —
    # the per-chip working set shrinks 1/G, which is the remediation
    monkeypatch.setattr(lint, "VMEM_ENVELOPE_BYTES", 64)
    with _treefit.feature_shards_scope(2):
        assert not [f for f in lint.preflight_device(model)
                    if f.rule == "TMG206"]


def test_tmg204_host_stage_without_static_form_halts_with_info():
    fx = FeatureBuilder.Real("x").from_column().as_predictor()

    def boom(col):
        raise RuntimeError("no static form")

    t = LambdaTransformer("boom", boom, [Real], Real).set_input(fx)
    model = WorkflowModel(result_features=[t.get_output()],
                          fitted_stages={})
    findings = lint.preflight_device(model)
    f = next(f for f in findings if f.rule == "TMG204")
    assert f.severity == Severity.INFO and "no static form" in f.message


def test_preflight_clean_on_fitted_binary_workflow(rng):
    """Representative end-to-end: transmogrify → selector, trained, then
    shape-propagated through eval_shape with zero findings."""
    wf, *_ = _binary_flow()
    model = wf.set_input_records(_records(rng)).train()
    assert model.validate(device=True) == []


def test_suppress_and_enforce_semantics():
    wf, tv, _vec = _mistyped_workflow()
    assert lint.check_workflow(wf, suppress=["TMG101"]) == []
    with pytest.raises(ValueError, match="unknown lint rule"):
        lint.check_workflow(wf, suppress=["TMG999"])
    findings = [Finding("TMG203", "warn-only")]
    lint.enforce(findings, fail_on="error")          # warnings pass
    with pytest.raises(LintError):
        lint.enforce(findings, fail_on="warning")
    with pytest.raises(ValueError):
        lint.enforce(findings, fail_on="info")


# ---------------------------------------------------------------------------
# runner pre-flight gating (the acceptance criterion: no reader I/O)
# ---------------------------------------------------------------------------


def test_runner_rejects_mistyped_workflow_before_any_reader_io(rng):
    wf, tv, vec = _mistyped_workflow()
    reader = _CountingReader(_records(rng))
    runner = OpWorkflowRunner(wf, training_reader=reader)
    with pytest.raises(LintError) as ei:
        runner.run(RunType.TRAIN, OpParams())
    # the error names the rule, the stage and both features' types
    msg = str(ei.value)
    assert "TMG101" in msg and tv.uid in msg and "OPVector" in msg
    assert reader.calls == 0, "pre-flight must run before data loading"


def test_runner_fail_on_warning_gates_warnings():
    p = FeatureBuilder.of(Prediction, "p").from_column().as_predictor()
    est = _AnyInputEstimator().set_input(p)
    wf = Workflow().set_result_features(est.get_output())
    runner = OpWorkflowRunner(wf)
    # default gate (error): warnings log but pass
    summary = runner._preflight(OpParams(), workflow=wf)
    assert summary["warning"] == 1 and summary["failOn"] == "error"
    with pytest.raises(LintError):
        runner._preflight(
            OpParams(custom_params={"failOn": "warning"}), workflow=wf)
    # validate: false skips entirely
    assert runner._preflight(
        OpParams(custom_params={"validate": False}), workflow=wf) is None
    # lintSuppress mutes the rule
    summary = runner._preflight(
        OpParams(custom_params={"failOn": "warning",
                                "lintSuppress": ["TMG106"]}), workflow=wf)
    assert summary["findings"] == 0


def test_runner_train_stamps_preflight_in_metrics(rng, tmp_path):
    wf, *_ = _binary_flow()
    reader = _CountingReader(_records(rng))
    params = OpParams(model_location=str(tmp_path / "model"),
                      metrics_location=str(tmp_path / "metrics.json"))
    out = OpWorkflowRunner(wf, training_reader=reader).run(
        RunType.TRAIN, params)
    assert out.metrics["preflight"] == {"findings": 0, "failOn": "error"}
    sunk = json.load(open(params.metrics_location))
    assert sunk["preflight"]["findings"] == 0


def test_lint_findings_mirror_into_telemetry():
    telemetry.enable()
    try:
        telemetry.reset()
        collector = telemetry.add_listener(
            telemetry.CollectingRunListener())
        lint.emit_findings([Finding("TMG101", "boom"),
                            Finding("TMG203", "hazard")])
        assert telemetry.counter("lint.errors").value == 1
        assert telemetry.counter("lint.warnings").value == 1
        assert collector.lint_findings == {"error": 1, "warning": 1}
        assert collector.summary()["lintFindings"] == {"error": 1,
                                                       "warning": 1}
    finally:
        telemetry.disable()
        telemetry.reset()


# ---------------------------------------------------------------------------
# CLI: check subcommand + gen default
# ---------------------------------------------------------------------------


def test_cli_check_rejects_malformed_params(tmp_path, capsys):
    from transmogrifai_tpu.cli import run_check
    p = tmp_path / "params.json"
    p.write_text(json.dumps({"customParams": {"maxBatches": 2.5}}))
    assert run_check(str(p)) == 1
    out = capsys.readouterr().out
    assert "maxBatches" in out and "TMG001" in out
    p.write_text(json.dumps({"customParams": {"maxBatches": 3}}))
    assert run_check(str(p)) == 0


def test_cli_check_model_directory(rng, tmp_path, capsys):
    from transmogrifai_tpu.cli import run_check
    wf, *_ = _binary_flow()
    model = wf.set_input_records(_records(rng)).train()
    model.save(str(tmp_path / "model"), overwrite=True)
    assert run_check(model_location=str(tmp_path / "model")) == 0
    assert "0 error(s)" in capsys.readouterr().out


def test_cli_gen_emits_validate_by_default(tmp_path):
    from transmogrifai_tpu.cli import generate_project
    csv = tmp_path / "data.csv"
    csv.write_text("label,x\n1,0.5\n0,0.1\n1,0.9\n0,0.2\n")
    files = generate_project(str(csv), "label", str(tmp_path / "proj"))
    params = json.load(open(files["params.json"]))
    assert params["customParams"]["validate"] is True
    assert params["customParams"]["failOn"] == "error"
    # the mesh knobs are discoverable (null = all visible devices) and
    # their keys ride the validated-numeric path (PR 6)
    assert params["customParams"]["meshDevices"] is None
    assert params["customParams"]["meshGridSize"] is None


# ---------------------------------------------------------------------------
# TMG3xx repo self-lint (tools/tmoglint.py)
# ---------------------------------------------------------------------------


def test_tmg301_time_time_flagged_and_allowlisted():
    tm = _load_tmoglint()
    bad = "import time\nt0 = time.time()\n"
    assert [f.rule for f in tm.lint_source(bad)] == ["TMG301"]
    aliased = "import time as _time\nt0 = _time.time()\n"
    assert [f.rule for f in tm.lint_source(aliased)] == ["TMG301"]
    ok = "import time\nt0 = time.perf_counter()\n"
    assert tm.lint_source(ok) == []
    allowed = "import time\nnow = time.time()  # lint: wall-clock\n"
    assert tm.lint_source(allowed) == []


def test_tmg302_broad_except_flagged_and_allowlisted():
    tm = _load_tmoglint()
    bad = "try:\n    x = 1\nexcept Exception:\n    pass\n"
    assert [f.rule for f in tm.lint_source(bad)] == ["TMG302"]
    allowed = ("try:\n    x = 1\n"
               "except Exception:  # lint: broad-except — fallback site\n"
               "    pass\n")
    assert tm.lint_source(allowed) == []
    narrow = "try:\n    x = 1\nexcept ValueError:\n    pass\n"
    assert tm.lint_source(narrow) == []


def test_tmg303_unregistered_inject_site():
    tm = _load_tmoglint()
    bad = ("from transmogrifai_tpu import resilience\n"
           "resilience.inject('stream.raed_file')\n")       # typo'd site
    assert [f.rule for f in tm.lint_source(bad)] == ["TMG303"]
    ok = ("from transmogrifai_tpu import resilience\n"
          "resilience.inject('stream.read_file', path='x')\n")
    assert tm.lint_source(ok) == []


def test_tmg304_span_outside_with():
    tm = _load_tmoglint()
    bad = ("from transmogrifai_tpu import telemetry\n"
           "s = telemetry.span('fit:stage')\n")
    assert [f.rule for f in tm.lint_source(bad)] == ["TMG304"]
    ok = ("from transmogrifai_tpu import telemetry\n"
          "with telemetry.span('fit:stage'):\n    pass\n")
    assert tm.lint_source(ok) == []


def test_tmg306_direct_make_mesh_outside_parallel():
    tm = _load_tmoglint()
    bad = ("from transmogrifai_tpu.parallel.mesh import make_mesh\n"
           "m = make_mesh(n_devices=1)\n")
    assert [f.rule for f in tm.lint_source(
        bad, "transmogrifai_tpu/somewhere.py")] == ["TMG306"]
    # module-attribute form (the runner's import style) triggers too
    bad_attr = ("from transmogrifai_tpu.parallel import mesh as _mesh\n"
                "m = _mesh.make_mesh(grid_size=2)\n")
    assert [f.rule for f in tm.lint_source(
        bad_attr, "transmogrifai_tpu/somewhere.py")] == ["TMG306"]
    # the sanctioned path is clean
    ok = ("from transmogrifai_tpu.parallel.mesh import "
          "process_default_mesh\n"
          "m = process_default_mesh()\n")
    assert tm.lint_source(ok, "transmogrifai_tpu/somewhere.py") == []
    # the explicit-mesh marker allows a deliberate construction
    allowed = ("from transmogrifai_tpu.parallel.mesh import make_mesh\n"
               "m = make_mesh(n_devices=1)  "
               "# lint: explicit-mesh — scaling bench pins 1 device\n")
    assert tm.lint_source(allowed, "transmogrifai_tpu/somewhere.py") == []
    # parallel/ itself and tests are exempt by path
    assert tm.lint_source(
        bad, "transmogrifai_tpu/parallel/mesh.py") == []
    assert tm.lint_source(bad, "tests/test_whatever.py") == []


def test_tmg307_thread_name_daemon_explicit():
    """PR-8 rule: worker threads must declare name= and daemon= — the
    telemetry tracer keys trace tracks by thread name, and the model
    server's shutdown semantics hinge on daemonness being visible."""
    tm = _load_tmoglint()
    bad = ("import threading\n"
           "t = threading.Thread(target=f)\n")
    assert [f.rule for f in tm.lint_source(bad)] == ["TMG307"]
    # one missing keyword is still a finding (and names the gap)
    half = ("import threading\n"
            "t = threading.Thread(target=f, name='worker')\n")
    fs = tm.lint_source(half)
    assert [f.rule for f in fs] == ["TMG307"]
    assert "daemon=" in fs[0].message
    # the from-import and aliased-module forms trigger too
    from_import = ("from threading import Thread\n"
                   "t = Thread(target=f)\n")
    assert [f.rule for f in tm.lint_source(from_import)] == ["TMG307"]
    aliased = ("import threading as _threading\n"
               "t = _threading.Thread(target=f)\n")
    assert [f.rule for f in tm.lint_source(aliased)] == ["TMG307"]
    # fully explicit is clean
    ok = ("import threading\n"
          "t = threading.Thread(target=f, name='serve-x', daemon=True)\n")
    assert tm.lint_source(ok) == []
    # the thread marker allows a deliberate default
    allowed = ("import threading\n"
               "t = threading.Thread(target=f)  "
               "# lint: thread — interpreter-owned helper\n")
    assert tm.lint_source(allowed) == []


def test_tmg308_unbounded_queue():
    """Input-pipeline rule: a queue.Queue() without maxsize= hides
    backpressure — the staged pipeline's contract is bounded queues."""
    tm = _load_tmoglint()
    bad = ("import queue\n"
           "q = queue.Queue()\n")
    assert [f.rule for f in tm.lint_source(bad)] == ["TMG308"]
    # from-import and aliased-module forms trigger too
    from_import = ("from queue import Queue\n"
                   "q = Queue()\n")
    assert [f.rule for f in tm.lint_source(from_import)] == ["TMG308"]
    aliased = ("import queue as _q\n"
               "q = _q.Queue()\n")
    assert [f.rule for f in tm.lint_source(aliased)] == ["TMG308"]
    # an explicit bound is clean — keyword or positional
    ok = ("import queue\n"
          "q = queue.Queue(maxsize=64)\n")
    assert tm.lint_source(ok) == []
    ok_pos = ("import queue\n"
              "q = queue.Queue(64)\n")
    assert tm.lint_source(ok_pos) == []
    # maxsize<=0 is UNBOUNDED in queue semantics — flagged like omission
    zero_pos = ("import queue\n"
                "q = queue.Queue(0)\n")
    assert [f.rule for f in tm.lint_source(zero_pos)] == ["TMG308"]
    zero_kw = ("import queue\n"
               "q = queue.Queue(maxsize=0)\n")
    assert [f.rule for f in tm.lint_source(zero_kw)] == ["TMG308"]
    neg = ("import queue\n"
           "q = queue.Queue(maxsize=-1)\n")
    assert [f.rule for f in tm.lint_source(neg)] == ["TMG308"]
    # the marker allows a deliberate unbounded queue
    allowed = ("import queue\n"
               "q = queue.Queue()  "
               "# lint: unbounded-queue — drained synchronously in tests\n")
    assert tm.lint_source(allowed) == []
    # someone else's Queue (multiprocessing, a local class) is not ours
    other = ("import multiprocessing\n"
             "q = multiprocessing.Queue()\n")
    assert tm.lint_source(other) == []


def test_tmg309_popen_explicit_streams():
    """Fleet-supervisor rule: product-code subprocess.Popen must own
    its child's streams — an inherited stdout ties worker logs to the
    parent's terminal, an undrained PIPE deadlocks the child."""
    tm = _load_tmoglint()
    bad = ("import subprocess\n"
           "p = subprocess.Popen(['worker'])\n")
    assert [f.rule for f in tm.lint_source(bad)] == ["TMG309"]
    # one missing keyword is still a finding (and names the gap)
    half = ("import subprocess\n"
            "p = subprocess.Popen(['worker'], stdout=fh)\n")
    fs = tm.lint_source(half)
    assert [f.rule for f in fs] == ["TMG309"]
    assert "without explicit stderr=" in fs[0].message
    # the from-import and aliased-module forms trigger too
    from_import = ("from subprocess import Popen\n"
                   "p = Popen(['worker'])\n")
    assert [f.rule for f in tm.lint_source(from_import)] == ["TMG309"]
    aliased = ("import subprocess as sp\n"
               "p = sp.Popen(['worker'])\n")
    assert [f.rule for f in tm.lint_source(aliased)] == ["TMG309"]
    # fully explicit is clean
    ok = ("import subprocess\n"
          "p = subprocess.Popen(['worker'], stdout=fh, "
          "stderr=subprocess.STDOUT)\n")
    assert tm.lint_source(ok) == []
    # subprocess.run is the blocking convenience API, not supervision
    run_ok = ("import subprocess\n"
              "subprocess.run(['git', 'rev-parse'], capture_output=True)\n")
    assert tm.lint_source(run_ok) == []
    # a **kwargs splat may carry stdout/stderr — no false ERROR
    splat_ok = ("import subprocess\n"
                "p = subprocess.Popen(['worker'], **opts)\n")
    assert tm.lint_source(splat_ok) == []
    # the popen marker allows a deliberate inherit
    allowed = ("import subprocess\n"
               "p = subprocess.Popen(['worker'])  "
               "# lint: popen — interactive child owns the tty\n")
    assert tm.lint_source(allowed) == []


def test_tmg310_thread_loop_must_catch():
    """Continual-tier rule: a while loop inside a Thread target with no
    try anywhere in its body dies silently on the first exception —
    loop bodies must catch-and-tally."""
    tm = _load_tmoglint()
    bad = ("import threading\n"
           "def loop():\n"
           "    while True:\n"
           "        work()\n"
           "threading.Thread(target=loop, name='w', daemon=True)\n")
    assert [f.rule for f in tm.lint_source(bad)] == ["TMG310"]
    # method targets (target=self._loop) resolve by attribute name,
    # and definition order does not matter (post-pass resolution)
    method = ("import threading\n"
              "class S:\n"
              "    def start(self):\n"
              "        threading.Thread(target=self._loop, name='w',\n"
              "                         daemon=True).start()\n"
              "    def _loop(self):\n"
              "        while True:\n"
              "            step()\n")
    assert [f.rule for f in tm.lint_source(method)] == ["TMG310"]
    # a try ANYWHERE in the while body is the catch-and-tally shape
    ok = ("import threading\n"
          "def loop():\n"
          "    while True:\n"
          "        try:\n"
          "            work()\n"
          "        except ValueError:\n"
          "            tally()\n"
          "threading.Thread(target=loop, name='w', daemon=True)\n")
    assert tm.lint_source(ok) == []
    # a function never used as a thread target is out of scope
    plain = ("def loop():\n"
             "    while True:\n"
             "        work()\n")
    assert tm.lint_source(plain) == []
    # library targets the module does not define are out of scope
    lib = ("import threading\n"
           "threading.Thread(target=httpd.serve_forever, name='h',\n"
           "                 daemon=True)\n")
    assert tm.lint_source(lib) == []
    # the marker allows a deliberately bare loop — while or def line
    allowed = ("import threading\n"
               "def loop():\n"
               "    while True:  # lint: thread-loop — exits with the process\n"
               "        work()\n"
               "threading.Thread(target=loop, name='w', daemon=True)\n")
    assert tm.lint_source(allowed) == []
    allowed_def = ("import threading\n"
                   "def loop():  # lint: thread-loop — supervised elsewhere\n"
                   "    while True:\n"
                   "        work()\n"
                   "threading.Thread(target=loop, name='w', daemon=True)\n")
    assert tm.lint_source(allowed_def) == []


def test_tmg314_raw_custom_params_reads():
    tm = _load_tmoglint()
    # subscript read + .get() read both flagged, whatever the receiver
    bad_sub = "v = params.custom_params['batchSize']\n"
    assert [f.rule for f in tm.lint_source(
        bad_sub, "transmogrifai_tpu/mod.py")] == ["TMG314"]
    bad_get = "v = params.custom_params.get('batchSize', 1024)\n"
    assert [f.rule for f in tm.lint_source(
        bad_get, "transmogrifai_tpu/mod.py")] == ["TMG314"]
    bad_name = "v = customParams.get('plan')\n"
    assert [f.rule for f in tm.lint_source(
        bad_name, "transmogrifai_tpu/mod.py")] == ["TMG314"]
    # WRITES are legitimate assembly (the CLI builds params dicts)
    write = "params.custom_params['costDb'] = path\n"
    assert tm.lint_source(write, "transmogrifai_tpu/mod.py") == []
    delete = "del params.custom_params['costDb']\n"
    assert tm.lint_source(delete, "transmogrifai_tpu/mod.py") == []
    # the marker sanctions a deliberate passthrough — on the read's
    # first line or (wrapped call) its last
    marked = ("v = params.custom_params.get('costDb')"
              "  # lint: knob — path passthrough\n")
    assert tm.lint_source(marked, "transmogrifai_tpu/mod.py") == []
    wrapped = ("v = params.custom_params.get(  # lint: knob — wrapped\n"
               "    'costDb')\n")
    assert tm.lint_source(wrapped, "transmogrifai_tpu/mod.py") == []
    # config.py owns the surface; tests poke raw dicts freely
    home = "v = custom_params.get('plan')\n"
    assert tm.lint_source(home, "transmogrifai_tpu/config.py") == []
    assert tm.lint_source(bad_get, "tests/test_x.py") == []
    # an unrelated mapping is out of scope
    other = "v = options.get('batchSize')\n"
    assert tm.lint_source(other, "transmogrifai_tpu/mod.py") == []


def test_tmg314_in_rules_catalog():
    from transmogrifai_tpu import lint
    assert "TMG314" in lint.RULES
    assert lint.RULES["TMG314"][0] == lint.Severity.ERROR
    assert "TMG406" in lint.RULES
    assert lint.RULES["TMG406"][0] == lint.Severity.WARNING


def test_repo_is_clean_under_self_lint():
    """The meta-test: the package itself reports zero findings — the
    project invariants PRs 1-4 introduced by convention are now CI
    law. Regressions (a new time.time() duration, an unmarked broad
    except, a typo'd fault site, a bare span) fail HERE."""
    tm = _load_tmoglint()
    findings = tm.lint_paths(
        [os.path.join(_REPO, "transmogrifai_tpu")])
    assert findings == [], "\n".join(f.format() for f in findings)


def test_tmoglint_cli_exit_codes(tmp_path, capsys):
    tm = _load_tmoglint()
    bad = tmp_path / "bad.py"
    bad.write_text("import time\nt0 = time.time()\n")
    assert tm.main([str(bad)]) == 1
    assert "TMG301" in capsys.readouterr().out
    good = tmp_path / "good.py"
    good.write_text("import time\nt0 = time.perf_counter()\n")
    assert tm.main([str(good)]) == 0


# ---------------------------------------------------------------------------
# TMG305 — unparseable file (the rule every other rule depends on)
# ---------------------------------------------------------------------------
def test_tmg305_syntax_error_is_a_finding_not_a_crash():
    tm = _load_tmoglint()
    fs = tm.lint_source("def f(:\n    pass\n", "transmogrifai_tpu/x.py")
    assert [f.rule for f in fs] == ["TMG305"]
    assert fs[0].severity == "error"
    assert "parse" in fs[0].message


# ---------------------------------------------------------------------------
# TMG399 — stale suppression markers (satellite: suppressions must not
# outlive their findings)
# ---------------------------------------------------------------------------
def test_tmg399_stale_marker_flagged():
    tm = _load_tmoglint()
    stale = ("import time\n"
             "t0 = time.perf_counter()  "
             "# lint: wall-clock — no longer true\n")
    fs = tm.lint_source(stale)
    assert [f.rule for f in fs] == ["TMG399"]
    assert "wall-clock" in fs[0].message


def test_tmg399_live_marker_not_flagged():
    tm = _load_tmoglint()
    live = ("import time\n"
            "t0 = time.time()  # lint: wall-clock — epoch needed\n")
    assert tm.lint_source(live) == []


def test_tmg399_wrong_marker_fires_rule_and_stale():
    """A marker for the WRONG rule is double-wrong: the real rule still
    fires (the marker silences nothing) AND the marker is stale."""
    tm = _load_tmoglint()
    wrong = ("import time\n"
             "t0 = time.time()  # lint: broad-except — oops\n")
    assert sorted(f.rule for f in tm.lint_source(wrong)) == [
        "TMG301", "TMG399"]


def test_tmg399_string_literals_are_not_markers():
    tm = _load_tmoglint()
    doc = 's = "escape with # lint: wall-clock — reason"\n'
    assert tm.lint_source(doc) == []


def test_tmg399_path_exempt_marker_is_inert_not_stale():
    """A marker for a rule that is path-exempt in this file (e.g. the
    explicit-mesh rule inside parallel/) silences nothing but is NOT
    reported stale — deleting it would re-fire the rule if the file
    ever moves."""
    tm = _load_tmoglint()
    src = ("from transmogrifai_tpu.parallel.mesh import make_mesh\n"
           "m = make_mesh(n_devices=1)  # lint: explicit-mesh — bench\n")
    assert tm.lint_source(
        src, "transmogrifai_tpu/parallel/mesh.py") == []
    # ... and the same marker in unexempt code is live, not stale
    assert tm.lint_source(src, "transmogrifai_tpu/other.py") == []


def test_tmg399_can_be_disabled():
    tm = _load_tmoglint()
    stale = "x = 1  # lint: wall-clock — nope\n"
    assert [f.rule for f in tm.lint_source(stale)] == ["TMG399"]
    assert tm.lint_source(stale, stale_markers=False) == []


# ---------------------------------------------------------------------------
# TMG8xx — whole-program concurrency & crash-safety pass
# (tools/concurrency_lint.py)
# ---------------------------------------------------------------------------
def _load_conclint():
    spec = importlib.util.spec_from_file_location(
        "concurrency_lint",
        os.path.join(_REPO, "tools", "concurrency_lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_DEADLOCK_SRC = """\
import threading
A = threading.Lock()
B = threading.Lock()
def one():
    with A:
        with B:
            pass
def two():
    with B:
        with A:
            pass
"""


def test_tmg801_deadlock_cycle_quotes_both_paths():
    """The acceptance fixture: a seeded AB/BA deadlock is detected and
    the finding quotes BOTH acquisition paths (file:line + source for
    each edge), so the report is actionable without opening the file."""
    cl = _load_conclint()
    fs = cl.analyze_sources({"m.py": _DEADLOCK_SRC})
    assert [f.rule for f in fs] == ["TMG801"]
    msg = fs[0].message
    assert "m.A -> m.B" in msg and "m.B -> m.A" in msg
    # both paths quoted, line-accurately
    assert "m.py:5: with A:" in msg and "m.py:6: with B:" in msg
    assert "m.py:9: with B:" in msg and "m.py:10: with A:" in msg


def test_tmg801_cross_function_edge_one_call_deep():
    cl = _load_conclint()
    src = ("import threading\n"
           "A = threading.Lock()\n"
           "B = threading.Lock()\n"
           "def helper():\n"
           "    with B:\n"
           "        pass\n"
           "def one():\n"
           "    with A:\n"
           "        helper()\n"        # A -> B via the call
           "def two():\n"
           "    with B:\n"
           "        with A:\n"
           "            pass\n")
    fs = cl.analyze_sources({"m.py": src})
    assert [f.rule for f in fs] == ["TMG801"]


def test_tmg801_consistent_order_and_rlock_are_clean():
    cl = _load_conclint()
    ok = ("import threading\n"
          "A = threading.Lock()\n"
          "B = threading.Lock()\n"
          "R = threading.RLock()\n"
          "def one():\n"
          "    with A:\n"
          "        with B:\n"
          "            pass\n"
          "def two():\n"
          "    with A:\n"
          "        with B:\n"
          "            pass\n"
          "def reenter():\n"
          "    with R:\n"
          "        with R:\n"          # reentrant: not a self-deadlock
          "            pass\n")
    assert cl.analyze_sources({"m.py": ok}) == []


def test_tmg801_self_deadlock_on_plain_lock():
    cl = _load_conclint()
    bad = ("import threading\n"
           "A = threading.Lock()\n"
           "def f():\n"
           "    with A:\n"
           "        with A:\n"
           "            pass\n")
    fs = cl.analyze_sources({"m.py": bad})
    assert [f.rule for f in fs] == ["TMG801"]
    assert "itself" in fs[0].message or "m.A" in fs[0].message


def test_tmg801_escape_marker_clears():
    cl = _load_conclint()
    marked = _DEADLOCK_SRC.replace(
        "    with A:\n        with B:",
        "    with A:  # lint: lock-order — fixture-sanctioned\n"
        "        with B:")
    assert cl.analyze_sources({"m.py": marked}) == []


_ESCAPE_SRC = """\
import threading
_LOCK = threading.Lock()
_STATE = {}
def writer():
    while True:
        _STATE["k"] = 1
def safe():
    with _LOCK:
        _STATE["k"] = 2
threading.Thread(target=writer, name="w", daemon=True).start()
"""


def test_tmg802_unlocked_shared_mutation_quotes_both_sites():
    """The acceptance fixture: a thread-reachable lock-free mutation of
    state whose OTHER mutation sites hold a lock — finding quotes both
    the unlocked and the locked site plus the guarding lock."""
    cl = _load_conclint()
    fs = cl.analyze_sources({"m.py": _ESCAPE_SRC})
    assert [f.rule for f in fs] == ["TMG802"]
    msg = fs[0].message
    assert "m._LOCK" in msg
    assert 'm.py:6: _STATE["k"] = 1' in msg      # unlocked site
    assert 'm.py:9: _STATE["k"] = 2' in msg      # locked site


def test_tmg802_fully_locked_and_unreachable_are_clean():
    cl = _load_conclint()
    locked = _ESCAPE_SRC.replace(
        "    while True:\n        _STATE[\"k\"] = 1",
        "    while True:\n        with _LOCK:\n            _STATE[\"k\"] = 1")
    assert cl.analyze_sources({"m.py": locked}) == []
    # same mutation mix, but writer is never a Thread target
    no_thread = _ESCAPE_SRC.replace(
        "threading.Thread(target=writer, name=\"w\", daemon=True).start()\n",
        "")
    assert cl.analyze_sources({"m.py": no_thread}) == []


def test_tmg802_escape_marker_clears():
    cl = _load_conclint()
    marked = _ESCAPE_SRC.replace(
        '        _STATE["k"] = 1',
        '        _STATE["k"] = 1  # lint: thread-escape — benign counter')
    assert cl.analyze_sources({"m.py": marked}) == []


def test_tmg803_blocking_calls_under_lock():
    cl = _load_conclint()
    bad = ("import threading, time, queue\n"
           "_LOCK = threading.Lock()\n"
           "_Q = queue.Queue(maxsize=8)\n"
           "def f():\n"
           "    with _LOCK:\n"
           "        time.sleep(1)\n"
           "def g():\n"
           "    with _LOCK:\n"
           "        x = _Q.get()\n")
    fs = cl.analyze_sources({"m.py": bad})
    assert [f.rule for f in fs] == ["TMG803", "TMG803"]
    assert "time.sleep" in fs[0].message
    ok = ("import threading, time, queue\n"
          "_LOCK = threading.Lock()\n"
          "_Q = queue.Queue(maxsize=8)\n"
          "def f():\n"
          "    time.sleep(1)\n"              # not under the lock
          "    with _LOCK:\n"
          "        pass\n"
          "def g():\n"
          "    with _LOCK:\n"
          "        x = _Q.get(timeout=0.1)\n"   # bounded: fine
          "    with _LOCK:\n"
          "        y = _Q.get(block=False)\n")
    assert cl.analyze_sources({"m.py": ok}) == []


def test_tmg803_condition_wait_is_not_blocking():
    """``cv.wait()`` inside ``with cv:`` RELEASES the lock — the
    canonical condition-variable pattern must stay clean."""
    cl = _load_conclint()
    ok = ("import threading\n"
          "CV = threading.Condition()\n"
          "def f():\n"
          "    with CV:\n"
          "        CV.wait()\n")
    assert cl.analyze_sources({"m.py": ok}) == []


def test_tmg803_propagates_one_call_deep():
    cl = _load_conclint()
    bad = ("import threading, time\n"
           "_LOCK = threading.Lock()\n"
           "def slow():\n"
           "    time.sleep(1)\n"
           "def f():\n"
           "    with _LOCK:\n"
           "        slow()\n")
    fs = cl.analyze_sources({"m.py": bad})
    assert [f.rule for f in fs] == ["TMG803"]
    # escape at the CALL site clears it
    marked = bad.replace("        slow()",
                         "        slow()  # lint: lock-blocking — bounded")
    assert cl.analyze_sources({"m.py": marked}) == []


def test_tmg803_flock_counts_as_a_lock():
    cl = _load_conclint()
    bad = ("import fcntl, os, time\n"
           "def f(fd):\n"
           "    fcntl.flock(fd, fcntl.LOCK_EX)\n"
           "    time.sleep(1)\n"
           "    fcntl.flock(fd, fcntl.LOCK_UN)\n")
    fs = cl.analyze_sources({"m.py": bad})
    assert [f.rule for f in fs] == ["TMG803"]
    ok = ("import fcntl, os, time\n"
          "def f(fd):\n"
          "    fcntl.flock(fd, fcntl.LOCK_EX)\n"
          "    fcntl.flock(fd, fcntl.LOCK_UN)\n"
          "    time.sleep(1)\n")                 # after release
    assert cl.analyze_sources({"m.py": ok}) == []


def test_tmg804_atomic_write_discipline():
    cl = _load_conclint()
    torn = ("import json\n"
            "def save(doc, path):\n"
            "    with open(path + \"/registry.json\", \"w\") as fh:\n"
            "        json.dump(doc, fh)\n")
    fs = cl.analyze_sources({"m.py": torn})
    assert [f.rule for f in fs] == ["TMG804"]
    assert "os.replace" in fs[0].message
    ok = ("import json, os\n"
          "def save(doc, path):\n"
          "    tmp = path + \"/registry.json.tmp\"\n"
          "    with open(tmp, \"w\") as fh:\n"
          "        json.dump(doc, fh)\n"
          "    os.replace(tmp, path + \"/registry.json\")\n")
    assert cl.analyze_sources({"m.py": ok}) == []
    # a non-shared path family is not this rule's business
    private = ("def save(doc, path):\n"
               "    with open(path + \"/notes.txt\", \"w\") as fh:\n"
               "        fh.write(str(doc))\n")
    assert cl.analyze_sources({"m.py": private}) == []
    marked = torn.replace(
        "    with open(path + \"/registry.json\", \"w\") as fh:",
        "    with open(path + \"/registry.json\", \"w\") as fh:"
        "  # lint: atomic-write — single-writer bootstrap")
    assert cl.analyze_sources({"m.py": marked}) == []


def test_tmg805_fault_site_coverage(tmp_path):
    cl = _load_conclint()
    from transmogrifai_tpu import resilience
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    # no tests at all: every site is a gap
    fs = cl.fault_coverage_findings(str(tests_dir))
    assert len(fs) == len(resilience.FAULT_SITES)
    assert all(f.rule == "TMG805" for f in fs)
    # quoting every site (however the test uses it) clears the gaps
    body = "\n".join(f'plan.on("{s}")' for s in
                     sorted(resilience.FAULT_SITES))
    (tests_dir / "test_all_sites.py").write_text(body + "\n")
    assert cl.fault_coverage_findings(str(tests_dir)) == []


def test_tmg8xx_stale_markers_flagged():
    cl = _load_conclint()
    stale = ("import threading\n"
             "x = 1  # lint: lock-order — outdated\n")
    fs = cl.analyze_sources({"m.py": stale})
    assert [f.rule for f in fs] == ["TMG399"]
    assert cl.analyze_sources({"m.py": stale},
                              stale_markers=False) == []


def test_tmg8xx_in_rules_catalog():
    from transmogrifai_tpu import lint
    for rule in ("TMG399", "TMG801", "TMG802", "TMG803", "TMG804",
                 "TMG805"):
        assert rule in lint.RULES
    assert lint.RULES["TMG399"][0] == lint.Severity.WARNING
    for rule in ("TMG801", "TMG802", "TMG803", "TMG804", "TMG805"):
        assert lint.RULES[rule][0] == lint.Severity.ERROR


def test_repo_is_clean_under_concurrency_lint():
    """The TMG8xx meta-test: the whole package, analyzed as one
    program, reports zero findings — every lock nests in one global
    order, no thread-reachable lock-free shared mutation, no blocking
    call under a lock, no torn shared-artifact write, every fault site
    chaos-tested, and every escape marker still earns its keep."""
    cl = _load_conclint()
    findings = cl.lint_paths(
        [os.path.join(_REPO, "transmogrifai_tpu")],
        tests_dir=os.path.join(_REPO, "tests"))
    assert findings == [], "\n".join(f.format() for f in findings)


def test_cli_lint_subcommand(tmp_path, capsys):
    """``python -m transmogrifai_tpu lint`` wraps both passes with
    ``check``-style exit codes, no tools/ path knowledge needed."""
    from transmogrifai_tpu import cli
    bad = tmp_path / "bad.py"
    bad.write_text("import time\nt0 = time.time()\n")
    assert cli.main(["lint", "--no-tests-check", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "TMG301" in out and "lint:" in out
    good = tmp_path / "good.py"
    good.write_text("import time\nt0 = time.perf_counter()\n")
    assert cli.main(["lint", "--no-tests-check", str(good)]) == 0
