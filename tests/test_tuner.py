"""Self-tuning runtime tests (PR 18): the offline trace-replay
autotuner (tuner.py) and the online batch-deadline AIMD controller
(server.py `_adapt_deadline`), plus the score-run cost-db drain the
tuner's priors feed on.

The offline search is tested against a DETERMINISTIC fake replay leg
(monkeypatched `_boot_and_replay`) so the coordinate-descent
mechanics — parity gating, bounds clamping, incumbent replacement,
byte-stable reporting — are asserted exactly; the live
boot-replay-score loop is exercised end-to-end by the slow-marked
round-trip test and the `autotune` bench config.
"""
import json
import os

import numpy as np
import pytest

from transmogrifai_tpu import config
from transmogrifai_tpu import server as server_mod
from transmogrifai_tpu import tuner as tuner_mod
from transmogrifai_tpu.server import ModelServer, _ModelEntry


@pytest.fixture(autouse=True)
def _fresh_tuner_stats():
    tuner_mod.reset_tuner_stats()
    yield


# ---------------------------------------------------------------------------
# objective + probe mechanics
# ---------------------------------------------------------------------------


def _fake_replay(p99_ms, rows=64, duration_s=1.0, parity_failures=0,
                 failed=0):
    return {"sent": 8, "failed": failed, "lateSends": 0,
            "skippedNoPayload": 0, "truncated": 0, "requests": 8,
            "parityChecked": 8, "parityFailures": parity_failures,
            "parityMaxAbsDelta": 0.0, "durationS": duration_s,
            "client": {"e2e": {"n": 8, "p50Ms": p99_ms / 2,
                               "p95Ms": p99_ms, "p99Ms": p99_ms}},
            "models": {"m": {"rows": rows}}}


def test_objective_score_p99_and_throughput():
    r = _fake_replay(12.5, rows=100, duration_s=2.0)
    assert tuner_mod._objective_score(r, "p99") == 12.5
    # throughput negated so the search minimizes uniformly
    assert tuner_mod._objective_score(r, "throughput") == -50.0
    assert tuner_mod._objective_score({"client": {}, "models": {}},
                                      "p99") is None


def test_probe_values_stay_inside_declared_bounds():
    k = config.knob("serveBatchDeadlineMs")
    lo, hi = config.knob_bounds("serveBatchDeadlineMs")
    for cur in (0.0, 2.0, 49.0, hi):
        for v in tuner_mod._probe_values(k, cur):
            assert lo <= v <= hi, (cur, v)
    kw = config.knob("pipelineWorkers")
    wlo, whi = config.knob_bounds("pipelineWorkers")
    for v in tuner_mod._probe_values(kw, 2):
        assert isinstance(v, int) and wlo <= v <= whi, v


def _workload_file(tmp_path, n=4):
    doc = {"records": [
        {"tS": i * 0.01, "model": "m", "rows": 2,
         "payload": [{"x": 1.0}, {"x": 2.0}]} for i in range(n)]}
    p = tmp_path / "wl.json"
    p.write_text(json.dumps(doc))
    return str(p), doc


def _params_file(tmp_path, **custom):
    p = tmp_path / "params.json"
    p.write_text(json.dumps({"customParams": custom}))
    return str(p)


# ---------------------------------------------------------------------------
# the parity GATE: broken numerics are rejected, never ranked
# ---------------------------------------------------------------------------


def test_tune_refuses_parity_broken_baseline(tmp_path, monkeypatch):
    monkeypatch.setattr(
        tuner_mod, "_boot_and_replay",
        lambda *a, **kw: _fake_replay(5.0, parity_failures=1))
    _wl_path, doc = _workload_file(tmp_path)
    with pytest.raises(tuner_mod.TunerError, match="baseline"):
        tuner_mod.tune(_params_file(tmp_path), doc,
                       knobs=["serveBatchDeadlineMs"], budget_s=5.0)
    assert tuner_mod.tuner_stats()["candidates_rejected_parity"] == 1


def test_tune_rejects_parity_breaking_candidate_not_ranked(
        tmp_path, monkeypatch):
    def fake(params_doc, workload_doc, **kw):
        dl = (params_doc.get("customParams") or {}).get(
            "serveBatchDeadlineMs", 8.0)
        if dl is not None and float(dl) < 1.0:
            # "fastest" leg by far — but it broke the numerics
            return _fake_replay(0.1, parity_failures=3)
        return _fake_replay(10.0 + float(dl))
    monkeypatch.setattr(tuner_mod, "_boot_and_replay", fake)
    _wl, doc = _workload_file(tmp_path)
    out = tuner_mod.tune(
        _params_file(tmp_path, serveBatchDeadlineMs=8.0), doc,
        knobs=["serveBatchDeadlineMs"], budget_s=30.0)
    rep = out["report"]
    winner_dl = rep["winner"].get("serveBatchDeadlineMs")
    assert winner_dl is None or winner_dl >= 1.0
    rejected = [leg for leg in rep["legs"]
                if leg.get("rejected") == "score parity"]
    assert rejected, "the parity-breaking legs must be visible"
    # none of the rejected configs became the winner despite their
    # "fastest" measured score
    for leg in rejected:
        assert leg["values"] != rep["winner"]
    assert tuner_mod.tuner_stats()["candidates_rejected_parity"] >= 1


def test_tune_descends_to_better_deadline_and_report_is_byte_stable(
        tmp_path, monkeypatch):
    def fake(params_doc, workload_doc, **kw):
        dl = (params_doc.get("customParams") or {}).get(
            "serveBatchDeadlineMs", 8.0)
        # deterministic objective valley at the declared lower bound
        return _fake_replay(5.0 + float(dl))
    monkeypatch.setattr(tuner_mod, "_boot_and_replay", fake)
    _wl, doc = _workload_file(tmp_path)
    pf = _params_file(tmp_path, serveBatchDeadlineMs=8.0)
    out1 = tuner_mod.tune(pf, doc, knobs=["serveBatchDeadlineMs"],
                          budget_s=30.0)
    out2 = tuner_mod.tune(pf, doc, knobs=["serveBatchDeadlineMs"],
                          budget_s=30.0)
    rep = out1["report"]
    assert rep["winner"]["serveBatchDeadlineMs"] == 0.0
    assert rep["winnerScore"] < rep["baselineScore"]
    assert out1["tunedParams"]["customParams"][
        "serveBatchDeadlineMs"] == 0.0
    # the untouched knobs of the params file survive the overlay
    assert config.check_custom_params(
        out1["tunedParams"]["customParams"]) == []
    # byte-stable: identical measurements -> identical report bytes
    assert json.dumps(out1["report"], sort_keys=True) == \
        json.dumps(out2["report"], sort_keys=True)
    assert rep["digest"].startswith("blake2b:")
    st = tuner_mod.tuner_stats()
    assert st["searches"] == 2 and st["candidates_improved"] >= 2
    assert st["legs_replayed"] == rep["legsMeasured"] * 2


def test_tune_keeps_baseline_when_nothing_beats_it(tmp_path,
                                                   monkeypatch):
    monkeypatch.setattr(tuner_mod, "_boot_and_replay",
                        lambda *a, **kw: _fake_replay(10.0))
    _wl, doc = _workload_file(tmp_path)
    out = tuner_mod.tune(_params_file(tmp_path, serveBatchDeadlineMs=2),
                         doc, knobs=["serveBatchDeadlineMs"],
                         budget_s=30.0)
    assert out["report"]["winner"] == {}
    assert out["tunedParams"]["customParams"][
        "serveBatchDeadlineMs"] == 2


def test_tune_validates_inputs(tmp_path):
    _wl, doc = _workload_file(tmp_path)
    with pytest.raises(tuner_mod.TunerError, match="objective"):
        tuner_mod.tune(_params_file(tmp_path), doc, objective="p42")
    with pytest.raises(tuner_mod.TunerError, match="not tunable"):
        tuner_mod.tune(_params_file(tmp_path), doc,
                       knobs=["validate"])
    bad = _params_file(tmp_path, serveBatchDeadlineMs="soon")
    with pytest.raises(tuner_mod.TunerError, match="baseline params"):
        tuner_mod.tune(bad, doc)


def test_run_tune_writes_validated_tuned_params_and_report(
        tmp_path, monkeypatch, capsys):
    def fake(params_doc, workload_doc, **kw):
        dl = (params_doc.get("customParams") or {}).get(
            "serveBatchDeadlineMs", 4.0)
        return _fake_replay(5.0 + float(dl))
    monkeypatch.setattr(tuner_mod, "_boot_and_replay", fake)
    wl_path, _doc = _workload_file(tmp_path)
    pf = _params_file(tmp_path, serveBatchDeadlineMs=4.0)
    rc = tuner_mod.run_tune(pf, wl_path, budget_s=30.0,
                            knobs="serveBatchDeadlineMs")
    assert rc == 0
    out = capsys.readouterr().out
    assert "tuned params ->" in out and "report ->" in out
    tuned_path = os.path.splitext(pf)[0] + ".tuned.json"
    tuned = json.load(open(tuned_path))
    assert config.check_custom_params(tuned["customParams"]) == []
    rep = json.load(open(os.path.splitext(tuned_path)[0]
                         + ".tuning-report.json"))
    assert rep["legsMeasured"] == len(rep["legs"])
    assert rep["searchedKnobs"] == ["serveBatchDeadlineMs"]
    assert rep["bounds"]["serveBatchDeadlineMs"] == [0.0, 50.0]


def test_run_tune_missing_workload_is_exit_1(tmp_path, capsys):
    rc = tuner_mod.run_tune(_params_file(tmp_path),
                            str(tmp_path / "nope.json"))
    assert rc == 1
    assert "cannot load workload" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# online adaptation: the bounded AIMD controller
# ---------------------------------------------------------------------------


def _entry_with_window(srv, qw_s, ch_s, n=None):
    n = n or server_mod.ADAPT_WINDOW_REQUESTS
    entry = _ModelEntry("t", None, None, None, srv.max_queue)
    entry.requests = n
    for _ in range(n):
        entry.decomp["queueWait"].append(qw_s)
        entry.decomp["coalesceHold"].append(ch_s)
    return entry


def test_adapt_decreases_when_queue_wait_dominates():
    srv = ModelServer(batch_deadline_s=0.004, adapt_deadline=True)
    try:
        entry = _entry_with_window(srv, qw_s=0.010, ch_s=0.001)
        srv._adapt_deadline(entry)
        assert entry.deadline_s == pytest.approx(
            0.004 * server_mod.ADAPT_MD_FACTOR)
        assert entry.adapt_decreases == 1
        # hysteresis: the same window does not re-fire
        srv._adapt_deadline(entry)
        assert entry.adapt_decreases == 1
    finally:
        srv.shutdown(drain=True)


def test_adapt_increases_when_coalesce_hold_dominates():
    srv = ModelServer(batch_deadline_s=0.004, adapt_deadline=True)
    try:
        entry = _entry_with_window(srv, qw_s=0.0001, ch_s=0.004)
        srv._adapt_deadline(entry)
        assert entry.deadline_s == pytest.approx(
            0.004 + server_mod.ADAPT_STEP_S)
        assert entry.adapt_increases == 1
    finally:
        srv.shutdown(drain=True)


def test_adapt_never_leaves_registry_bounds():
    lo, hi = config.knob_bounds("serveBatchDeadlineMs")
    srv = ModelServer(batch_deadline_s=hi / 1e3, adapt_deadline=True)
    try:
        # increase pressure at the ceiling: clamped, no move
        entry = _entry_with_window(srv, qw_s=0.0001, ch_s=0.02)
        srv._adapt_deadline(entry)
        assert entry.deadline_s is None or entry.deadline_s <= hi / 1e3
        assert entry.adapt_clamped == 1
        # decrease pressure at the floor: clamped at lo, never below
        srv2 = ModelServer(batch_deadline_s=lo / 1e3 if lo else 0.0,
                           adapt_deadline=True)
        try:
            e2 = _entry_with_window(srv2, qw_s=0.02, ch_s=0.0001)
            srv2._adapt_deadline(e2)
            assert e2.deadline_s is None or e2.deadline_s >= lo / 1e3
        finally:
            srv2.shutdown(drain=True)
    finally:
        srv.shutdown(drain=True)


def test_adapt_holds_inside_deadband_and_below_window():
    srv = ModelServer(batch_deadline_s=0.004, adapt_deadline=True)
    try:
        # balanced medians: hold
        entry = _entry_with_window(srv, qw_s=0.002, ch_s=0.002)
        before = server_mod.server_stats()["deadline_holds"]
        srv._adapt_deadline(entry)
        assert entry.deadline_s is None
        assert server_mod.server_stats()["deadline_holds"] == before + 1
        # an incomplete window: no evaluation at all
        e2 = _entry_with_window(
            srv, 0.02, 0.0001,
            n=server_mod.ADAPT_WINDOW_REQUESTS - 1)
        srv._adapt_deadline(e2)
        assert e2.deadline_s is None
    finally:
        srv.shutdown(drain=True)


def test_adapt_advisory_tmg406_fires_once_on_contradiction():
    from transmogrifai_tpu import lint
    srv = ModelServer(batch_deadline_s=0.008, adapt_deadline=True)
    try:
        entry = _entry_with_window(srv, qw_s=0.05, ch_s=0.0001)
        before = server_mod.server_stats()["deadline_advisories"]
        # two MD windows: 8ms -> 4ms -> 2ms (<= 8/2 trips the advisory)
        srv._adapt_deadline(entry)
        entry.requests += server_mod.ADAPT_WINDOW_REQUESTS
        for _ in range(server_mod.ADAPT_WINDOW_REQUESTS):
            entry.decomp["queueWait"].append(0.05)
            entry.decomp["coalesceHold"].append(0.0001)
        srv._adapt_deadline(entry)
        assert entry.deadline_advised is True
        assert server_mod.server_stats()["deadline_advisories"] == \
            before + 1
        # converged far from config, advisory fired exactly once
        entry.requests += server_mod.ADAPT_WINDOW_REQUESTS
        for _ in range(server_mod.ADAPT_WINDOW_REQUESTS):
            entry.decomp["queueWait"].append(0.05)
            entry.decomp["coalesceHold"].append(0.0001)
        srv._adapt_deadline(entry)
        assert server_mod.server_stats()["deadline_advisories"] == \
            before + 1
    finally:
        srv.shutdown(drain=True)


def test_adapt_disabled_is_bit_inert(monkeypatch):
    srv = ModelServer(batch_deadline_s=0.004)   # default: off
    try:
        assert srv.adapt_deadline is False
        assert srv.stats()["adaptDeadline"] is False
        entry = _entry_with_window(srv, qw_s=0.05, ch_s=0.0001)
        # the worker loop only calls the controller when enabled; even
        # a direct call must leave per-entry state None-untouched only
        # via the enable flag — assert the OFF wiring:
        assert entry.deadline_s is None
        assert entry.stats()["adaptiveDeadlineMs"] is None
    finally:
        srv.shutdown(drain=True)
    # kill switch: TMOG_ADAPT=0 forces the constructor flag off
    monkeypatch.setenv("TMOG_ADAPT", "0")
    srv2 = ModelServer(batch_deadline_s=0.004, adapt_deadline=True)
    try:
        assert srv2.adapt_deadline is False
    finally:
        srv2.shutdown(drain=True)


def test_server_stats_expose_adaptation_counters():
    st = server_mod.server_stats()
    for key in ("deadline_adapt_windows", "deadline_increases",
                "deadline_decreases", "deadline_holds",
                "deadline_clamped", "deadline_advisories"):
        assert key in st, key
    from transmogrifai_tpu import fleet as fleet_mod
    fst = fleet_mod.fleet_stats()
    for key in ("worker_deadline_increases", "worker_deadline_decreases",
                "worker_deadline_clamped", "worker_deadline_advisories"):
        assert key in fst, key


# ---------------------------------------------------------------------------
# satellite: score-type runs drain phase observations into the cost db
# ---------------------------------------------------------------------------


def test_score_run_grows_cost_db(rng, tmp_path):
    from transmogrifai_tpu import FeatureBuilder, Workflow, planner
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import (
        BinaryClassificationModelSelector)
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.runner import (OpParams, OpWorkflowRunner,
                                          RunType)

    y = rng.integers(0, 2, 120).astype(float)
    x = rng.normal(size=120) + y
    records = [{"label": float(y[i]), "x": float(x[i])}
               for i in range(120)]

    class _R:
        def read_records(self):
            return list(records)

    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()],
        splitter=None, seed=3)
    pred = label.transform_with(sel, transmogrify([fx]))
    wf = Workflow().set_result_features(pred)
    runner = OpWorkflowRunner(wf, training_reader=_R(),
                              scoring_reader=_R())
    db_path = str(tmp_path / "cost.json")
    params = OpParams(model_location=str(tmp_path / "model"),
                      write_location=str(tmp_path / "scores.csv"),
                      custom_params={"costDb": db_path})
    runner.run(RunType.TRAIN, params)
    before = json.load(open(db_path))
    n_before = sum(
        slot.get("n", 0)
        for tiers in before.get("stages", {}).values()
        for slot in tiers.values() if isinstance(slot, dict))
    # a tiny score run sits below the fusion row floor, so seed the
    # observation buffer the way a production-sized transform would —
    # the satellite under test is the DRAIN on the score path
    planner.observe_phase("transform", "host", 0.5, 25_000)
    out = runner.run(RunType.SCORE, params)
    assert out.metrics["rowsScored"] == 120
    after = json.load(open(db_path))
    assert "phase:transform" in after.get("stages", {})
    n_after = sum(
        slot.get("n", 0)
        for tiers in after.get("stages", {}).values()
        for slot in tiers.values() if isinstance(slot, dict))
    assert n_after > n_before
    # and the run stamped its resolved config (tentpole a)
    assert "effectiveConfig" in out.metrics
    assert out.metrics["effectiveConfig"]["costDb"] == db_path


# ---------------------------------------------------------------------------
# live end-to-end (slow): record -> tune -> tuned beats/matches default
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_tune_live_round_trip(tmp_path):
    import http.client

    from transmogrifai_tpu import FeatureBuilder, Workflow
    from transmogrifai_tpu import workload as workload_mod
    from transmogrifai_tpu.cli import build_server_from_params
    from transmogrifai_tpu.models import (
        BinaryClassificationModelSelector, LogisticRegressionFamily)
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.runner import OpParams

    rng = np.random.default_rng(7)
    y = np.asarray([i % 2 for i in range(120)], float)
    rng.shuffle(y)
    records = [{"label": float(y[i]),
                "x1": float(rng.normal() + y[i]),
                "x2": float(rng.normal())} for i in range(120)]
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
    f2 = FeatureBuilder.Real("x2").from_column().as_predictor()
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()],
        splitter=None, seed=7)
    pred = label.transform_with(sel, transmogrify([f1, f2]))
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())
    mdir = str(tmp_path / "model")
    model.save(mdir, overwrite=True)
    pf = str(tmp_path / "params.json")
    with open(pf, "w") as fh:
        json.dump({"modelLocation": mdir,
                   "customParams": {"serveBatchDeadlineMs": 2,
                                    "serveBucketCap": 16}}, fh)
    params = OpParams.from_file(pf)
    srv = build_server_from_params(params)
    httpd = server_mod.serve_http(srv, port=0)
    port = httpd.server_address[1]
    wdir = str(tmp_path / "wl")
    workload_mod.start_recorder(wdir, role="tune-test")
    try:
        for lo in range(0, 24, 3):
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=60)
            conn.request("POST", "/v1/models/default:score",
                         json.dumps({"records": records[lo:lo + 3]}),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            assert r.status == 200
            r.read()
            conn.close()
    finally:
        workload_mod.stop_recorder()
        httpd.shutdown()
        srv.shutdown(drain=True)
        for e in srv._entries.values():
            if e.model is not None:
                e.model._engine_breaker().reset()
    rc = tuner_mod.run_tune(pf, wdir, budget_s=60.0,
                            knobs="serveBatchDeadlineMs", speed=50.0)
    assert rc == 0
    rep = json.load(open(str(tmp_path / "params.tuned.tuning-report"
                                        ".json")))
    # the gate the tuner enforces by construction: the emitted config
    # never loses to the baseline, and EVERY ranked leg held parity
    assert rep["winnerScore"] <= rep["baselineScore"]
    for leg in rep["legs"]:
        if leg.get("rejected") is None:
            assert leg["parityFailures"] == 0
    tuned = json.load(open(str(tmp_path / "params.tuned.json")))
    assert config.check_custom_params(tuned["customParams"]) == []
