"""Workload flight-recorder / replay / critical-path tests (PR 17,
workload.py + its server/fleet/CLI integration).

The tentpole contract: every request accepted by a serving process
appends ONE compact JSONL record — off the request path, through a
bounded queue into a single named writer thread, size-rotated,
torn-tolerant — and `workload merge` stitches the per-process shards
into one arrival-ordered workload that `workload replay` re-drives
open-loop against a live server with score parity asserted wherever
payloads were recorded. `trace analyze` reconstructs per-request
critical paths from merged traces (parent-child self-time plus
batch-span link donations) and `diff_analyses` is the thresholded
regression watchdog over two analyses. Chaos satellite: a fresh
interpreter SIGKILLed mid-write tears at most the final line, which
merge skips and tallies — never a crash.
"""
import http.client
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder, Workflow, telemetry
from transmogrifai_tpu import server as server_mod
from transmogrifai_tpu import workload as workload_mod
from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                      LogisticRegressionFamily)
from transmogrifai_tpu.ops.transmogrifier import transmogrify

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_workload():
    workload_mod.stop_recorder()
    workload_mod.reset_workload_stats()
    telemetry.disable()
    telemetry.reset()
    yield
    workload_mod.stop_recorder()
    workload_mod.reset_workload_stats()
    telemetry.disable()
    telemetry.reset()


def _read_lines(path):
    with open(path, "rb") as fh:
        return [json.loads(ln) for ln in fh.read().splitlines() if ln]


# ---------------------------------------------------------------------------
# recorder: shard format, zero-copy splice, caps, rotation, drops
# ---------------------------------------------------------------------------


def test_recorder_shard_header_and_record(tmp_path):
    d = str(tmp_path / "wl")
    rec = workload_mod.start_recorder(d, role="worker")
    assert workload_mod.recording_enabled()
    ok = workload_mod.record_request(
        "m", 2, records=[{"x": 1.0}, {"x": 2.0}],
        outputs=[{"p": 0.5}, {"p": 0.7}], trace_id="t1",
        outcome={"status": 200, "ok": True},
        phases={"e2e": 0.005, "queueWait": 0.001})
    assert ok
    shard = rec.shard_path
    workload_mod.stop_recorder()        # drains the queue — a barrier
    assert not workload_mod.recording_enabled()
    lines = _read_lines(shard)
    hdr, req = lines[0], lines[1]
    assert hdr["kind"] == "header"
    assert hdr["version"] == workload_mod.WORKLOAD_VERSION
    assert hdr["role"] == "worker" and hdr["pid"] == os.getpid()
    assert hdr["epochUnixS"] > 0
    assert req["kind"] == "request" and req["model"] == "m"
    assert req["rows"] == 2 and req["traceId"] == "t1"
    assert req["payload"] == [{"x": 1.0}, {"x": 2.0}]
    assert req["outputs"] == [{"p": 0.5}, {"p": 0.7}]
    assert req["phases"]["e2e"] == 0.005
    assert req["tOffsetS"] >= 0
    st = workload_mod.workload_stats()
    assert st["records_enqueued"] == 1 and st["records_written"] == 1
    assert st["payloads_recorded"] == 1 and st["records_dropped"] == 0
    assert st["recording"] is False and st["drop_rate"] == 0.0


def test_recorder_zero_copy_splice_and_merge_normalizes(tmp_path):
    # pre-serialized request/response bodies are spliced VERBATIM into
    # the line (the serving handler already paid the serialization);
    # merge unwraps them back into the payload/outputs/phases schema
    d = str(tmp_path / "wl")
    rec = workload_mod.start_recorder(d, role="worker")
    raw_req = b'{"records":[{"x":1.5}],"junk":true}'
    raw_resp = (b'{"model":"m","outputs":[{"p":0.25}],'
                b'"phases":{"e2e":0.002,"queueWait":0.0003}}')
    assert workload_mod.record_request(
        "m", 1, payload_json=raw_req, response_json=raw_resp,
        trace_id="tz", outcome={"status": 200, "ok": True})
    shard = rec.shard_path
    workload_mod.stop_recorder()
    with open(shard, "rb") as fh:
        blob = fh.read()
    assert raw_req in blob and raw_resp in blob   # byte-verbatim splice
    req = _read_lines(shard)[1]
    assert req["request"]["records"] == [{"x": 1.5}]
    merged = workload_mod.merge_workload_shards(d)
    r = merged["records"][0]
    assert "request" not in r and "response" not in r
    assert r["payload"] == [{"x": 1.5}]
    assert r["outputs"] == [{"p": 0.25}]
    assert r["phases"]["queueWait"] == 0.0003


def test_payload_cap_digests_and_payloads_off(tmp_path):
    d = str(tmp_path / "wl")
    rec = workload_mod.start_recorder(d, role="worker")
    big = [{"x": float(i)} for i in range(20_000)]   # > 64 KiB as JSON
    assert workload_mod.record_request("m", len(big), records=big)
    shard = rec.shard_path
    workload_mod.stop_recorder()
    req = _read_lines(shard)[1]
    assert "payload" not in req
    dig = req["payloadDigest"]
    assert dig["rows"] == len(big) and dig["bytes"] > 65536
    assert len(dig["sha256"]) == 16
    assert workload_mod.workload_stats()["payloads_digested"] == 1

    # payload capture disabled: even a tiny payload degrades to digest
    d2 = str(tmp_path / "wl2")
    rec2 = workload_mod.start_recorder(d2, role="worker",
                                       payloads=False)
    assert workload_mod.record_request("m", 1, records=[{"x": 1.0}])
    shard2 = rec2.shard_path
    workload_mod.stop_recorder()
    req2 = _read_lines(shard2)[1]
    assert "payload" not in req2 and "payloadDigest" in req2


def test_recorder_size_rotation(tmp_path):
    d = str(tmp_path / "wl")
    # max_mb below the 4 KiB floor: the floor keeps segments meaningful
    rec = workload_mod.start_recorder(d, role="worker", max_mb=0.001)
    assert rec.max_bytes == 4096
    payload = [{"x": 1.0, "y": 2.0}] * 4
    for i in range(100):
        assert workload_mod.record_request("m", 4, records=payload,
                                           trace_id=f"t{i:04d}")
    workload_mod.stop_recorder()
    shards = sorted(os.listdir(d))
    assert len(shards) >= 2                       # rotated segments
    assert any(".workload.000.jsonl" in s for s in shards)
    assert workload_mod.workload_stats()["rotations"] >= 1
    merged = workload_mod.merge_workload_shards(d)  # reads ALL segments
    assert merged["requests"] == 100
    assert merged["mergedShards"] == len(shards)


def test_recorder_queue_full_drops_never_blocks(tmp_path):
    rec = workload_mod.WorkloadRecorder(str(tmp_path / "wl"),
                                        role="worker", queue_depth=1)
    # stop the writer thread out-of-band so the queue genuinely fills
    rec._queue.put(None)
    rec._thread.join(timeout=10)
    assert not rec._thread.is_alive()
    assert rec.record({"kind": "request", "model": "m", "rows": 1})
    t0 = time.perf_counter()
    assert not rec.record({"kind": "request", "model": "m", "rows": 1})
    assert time.perf_counter() - t0 < 0.5         # dropped, not blocked
    st = workload_mod.workload_stats()
    assert st["records_dropped"] == 1 and st["drop_rate"] == 0.5
    rec._closed = True


# ---------------------------------------------------------------------------
# merge: clock alignment, router+worker combine, torn tolerance
# ---------------------------------------------------------------------------


def _write_shard(path, role, pid, epoch, records, torn_tail=None):
    with open(path, "wb") as fh:
        fh.write(json.dumps({"kind": "header", "version": 1,
                             "role": role, "pid": pid, "segment": 0,
                             "epochUnixS": epoch}).encode() + b"\n")
        for r in records:
            fh.write(json.dumps({"kind": "request", **r},
                                separators=(",", ":")).encode() + b"\n")
        if torn_tail is not None:
            fh.write(torn_tail)                   # no terminator


def test_merge_clock_alignment_and_router_worker_combine(tmp_path):
    d = str(tmp_path / "wl")
    os.makedirs(d)
    # worker anchored 100 s BEFORE the router: absolute arrival is
    # anchor + offset, so the worker's offsets are 100 s larger
    _write_shard(os.path.join(d, "shard-router-1.workload.jsonl"),
                 "router", 1, 1000.0, [
        {"tOffsetS": 4.0, "model": "m", "rows": 2, "traceId": "tt",
         "outcome": {"status": 200, "ok": True},
         "phases": {"e2e": 0.006},
         "route": {"worker": 0, "failovers": 0}}])
    _write_shard(os.path.join(d, "shard-worker-2.workload.jsonl"),
                 "worker", 2, 900.0, [
        {"tOffsetS": 90.0, "model": "m", "rows": 1},   # abs 990: first
        {"tOffsetS": 104.1, "model": "m", "rows": 2, "traceId": "tt",
         "payload": [{"x": 1.0}, {"x": 2.0}],
         "outputs": [{"p": 0.1}, {"p": 0.9}],
         "phases": {"e2e": 0.005, "queueWait": 0.001}}])
    merged = workload_mod.merge_workload_shards(d)
    assert merged["mergedShards"] == 2
    assert merged["tornRecordsSkipped"] == 0
    assert merged["requests"] == 2                # tt folded into one
    first, second = merged["records"]
    assert first["tS"] == 0.0 and "traceId" not in first
    assert second["traceId"] == "tt"
    # rebased on the earliest arrival, clock offsets aligned:
    # router 1000+4.0 vs worker 900+104.1 → the worker record is the
    # earlier instant of the SAME request and keeps the timeline
    assert second["tS"] == pytest.approx(14.0, abs=1e-6)
    assert second["sources"] == ["router", "worker"]
    assert second["route"]["worker"] == 0
    assert second["payload"] == [{"x": 1.0}, {"x": 2.0}]
    # the router's e2e (client-visible) wins; worker sub-phases ride
    assert second["phases"]["e2e"] == 0.006
    assert second["phases"]["queueWait"] == 0.001
    assert second["outcome"]["ok"] is True


def test_merge_torn_tail_and_unreadable_shard(tmp_path):
    d = str(tmp_path / "wl")
    os.makedirs(d)
    _write_shard(os.path.join(d, "shard-worker-1.workload.jsonl"),
                 "worker", 1, 100.0,
                 [{"tOffsetS": 1.0, "model": "m", "rows": 1}],
                 torn_tail=b'{"kind":"request","model":"m","ro')
    with open(os.path.join(d, "shard-worker-2.workload.jsonl"),
              "wb") as fh:
        fh.write(b"not json at all\n")            # header unreadable
    merged = workload_mod.merge_workload_shards(d)
    assert merged["requests"] == 1
    assert merged["tornRecordsSkipped"] == 1
    assert len(merged["mergeErrors"]) == 1
    assert "shard-worker-2" in merged["mergeErrors"][0]
    st = workload_mod.workload_stats()
    assert st["torn_records_skipped"] == 1 and st["merge_errors"] == 1
    with pytest.raises(ValueError):
        workload_mod.merge_workload_shards(str(tmp_path / "empty"))


def test_summarize_workload_percentiles_and_failures():
    doc = {"records": [
        {"tS": 0.0, "model": "m", "rows": 4,
         "phases": {"e2e": 0.010}},
        {"tS": 0.4, "model": "m", "rows": 4,
         "phases": {"e2e": 0.020}},
        {"tS": 0.5, "model": "m", "rows": 4,
         "phases": {"e2e": 0.030}},
        {"tS": 1.0, "model": "m", "rows": 2,
         "outcome": {"status": 503, "ok": False}}]}
    s = workload_mod.summarize_workload(doc)
    assert s["requests"] == 4 and s["durationS"] == 1.0
    m = s["models"]["m"]
    assert m["rows"] == 14 and m["failed"] == 1
    assert m["phases"]["e2e"]["n"] == 3
    assert m["phases"]["e2e"]["p50Ms"] == 20.0    # nearest-rank
    assert m["phases"]["e2e"]["p99Ms"] == 30.0


# ---------------------------------------------------------------------------
# replay: live round-trip with score parity, skips, speed
# ---------------------------------------------------------------------------


def _train_tiny(seed, n=160):
    rng = np.random.default_rng(seed)
    y = np.asarray([i % 2 for i in range(n)], float)
    rng.shuffle(y)
    records = [{"label": float(y[i]),
                "x1": float(rng.normal() + y[i]),
                "x2": float(rng.normal())} for i in range(n)]
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
    f2 = FeatureBuilder.Real("x2").from_column().as_predictor()
    vec = transmogrify([f1, f2])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()],
        splitter=None, seed=seed)
    pred = label.transform_with(sel, vec)
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())
    return model, records


@pytest.fixture(scope="module")
def tiny_server():
    model, records = _train_tiny(47)
    srv = server_mod.ModelServer(batch_deadline_s=0.0)
    srv.register("m", model=model)
    httpd = server_mod.serve_http(srv, port=0)
    yield srv, httpd.server_address[1], records
    httpd.shutdown()
    srv.shutdown(drain=True)
    model._engine_breaker().reset()


def _post_score(port, name, records):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    try:
        conn.request("POST", f"/v1/models/{name}:score",
                     json.dumps({"records": records}),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        return r.status, json.loads(r.read() or b"{}")
    finally:
        conn.close()


def test_record_and_replay_live_score_parity(tiny_server, tmp_path):
    srv, port, records = tiny_server
    d = str(tmp_path / "wl")
    workload_mod.start_recorder(d, role="worker")
    for lo in range(0, 12, 3):
        status, doc = _post_score(port, "m", records[lo:lo + 3])
        assert status == 200
        assert "phases" in doc and "e2e" in doc["phases"]
    workload_mod.stop_recorder()
    merged = workload_mod.merge_workload_shards(d)
    assert merged["requests"] == 4
    r0 = merged["records"][0]
    assert r0["payload"] == records[0:3]          # zero-copy unwrapped
    assert len(r0["outputs"]) == 3
    assert r0["outcome"] == {"status": 200, "ok": True}
    out = workload_mod.replay_workload(
        merged, f"127.0.0.1:{port}", speed=100.0, timeout_s=60.0)
    assert out["sent"] == 4 and out["failed"] == 0
    assert out["skippedNoPayload"] == 0
    assert out["parityChecked"] == 4 and out["parityFailures"] == 0
    assert out["parityMaxAbsDelta"] <= 1e-4
    ph = out["models"]["m"]["phases"]
    assert "e2e" in ph and "queueWait" in ph      # decomposed summary
    st = workload_mod.workload_stats()
    assert st["replayed_requests"] == 4 and st["parity_checked"] == 4


def test_replay_skips_digested_and_failed_records(tiny_server, tmp_path):
    srv, port, records = tiny_server
    doc = {"records": [
        {"tS": 0.0, "model": "m", "rows": 2, "payload": records[:2]},
        {"tS": 0.001, "model": "m", "rows": 2,
         "payloadDigest": {"rows": 2, "bytes": 99, "sha256": "ab"}},
        {"tS": 0.002, "model": "m", "rows": 2, "payload": records[2:4],
         "outcome": {"status": 504, "ok": False}}]}
    out = workload_mod.replay_workload(doc, f"http://127.0.0.1:{port}",
                                       speed=10.0, timeout_s=60.0)
    assert out["requests"] == 2                   # failed one filtered
    assert out["sent"] == 1                       # digest unreplayable
    assert out["skippedNoPayload"] == 1
    assert out["parityChecked"] == 0              # outputs not recorded
    stats = workload_mod.workload_stats()
    assert stats["replay_skipped_no_payload"] == 1
    with pytest.raises(ValueError):
        workload_mod.replay_workload(doc, f"127.0.0.1:{port}", speed=0)


def test_replay_truncation_by_duration_and_count(tiny_server):
    srv, port, records = tiny_server
    doc = {"records": [
        {"tS": 0.0, "model": "m", "rows": 2, "payload": records[:2]},
        {"tS": 0.01, "model": "m", "rows": 2, "payload": records[2:4]},
        {"tS": 60.0, "model": "m", "rows": 2, "payload": records[4:6]}]}
    # --duration-s: arrival offsets are scaled by speed BEFORE the cut,
    # so a 60 s tail at 100x lands at 0.6 s and a 0.5 s window drops it
    out = workload_mod.replay_workload(doc, f"127.0.0.1:{port}",
                                       speed=100.0, timeout_s=60.0,
                                       duration_s=0.5)
    assert out["sent"] == 2 and out["truncated"] == 1
    # --max-requests keeps the arrival-ordered head
    out = workload_mod.replay_workload(doc, f"127.0.0.1:{port}",
                                       speed=100.0, timeout_s=60.0,
                                       max_requests=1)
    assert out["sent"] == 1 and out["truncated"] == 2
    assert workload_mod.workload_stats()["replay_truncated"] == 3
    # both truncations compose; invalid values name themselves
    with pytest.raises(ValueError, match="duration_s"):
        workload_mod.replay_workload(doc, f"127.0.0.1:{port}",
                                     duration_s=0)
    with pytest.raises(ValueError, match="max_requests"):
        workload_mod.replay_workload(doc, f"127.0.0.1:{port}",
                                     max_requests=0)


# ---------------------------------------------------------------------------
# critical-path analyzer + regression watchdog
# ---------------------------------------------------------------------------


def _span(name, trace, sid, t0_us, dur_us, parent=None, links=()):
    return {"ph": "X", "name": name, "ts": t0_us, "dur": dur_us,
            "args": {"trace_id": trace, "span_id": sid,
                     "parent_span_id": parent, "links": list(links)}}


def _synthetic_trace():
    return {"traceEvents": [
        # T1: request root + child; a foreign-trace batch span links
        # the root and donates its overlap under its own name
        _span("server:request", "T1", "r1", 0, 10_000),
        _span("score:prepare", "T1", "c1", 1_000, 2_000, parent="r1"),
        _span("server:dispatch", "T2", "b1", 4_000, 4_000,
              links=["r1"]),
        # T3: the batch span is ALSO a same-trace child of the request
        # it links — ordinary parent-child accounting must apply ONCE
        _span("server:request", "T3", "r3", 0, 8_000),
        _span("server:dispatch", "T3", "b3", 2_000, 6_000,
              parent="r3", links=["r3"]),
    ]}


def test_analyze_trace_links_self_time_and_coverage():
    a = workload_mod.analyze_trace(_synthetic_trace(), top_k=5)
    assert a["requests"] == 2
    assert a["skippedTraces"] == 1                # T2 has no root
    assert a["coverage"]["min"] == 1.0 and a["coverage"]["mean"] == 1.0
    by_req = {r["traceId"]: r for r in a["slowest"]}
    t1 = by_req["T1"]["attributionMs"]
    # 10 ms e2e = 4 self + 2 child + 4 donated by the linked batch
    assert t1 == {"score:prepare": 2.0, "server:dispatch": 4.0,
                  "server:request": 4.0}
    t3 = by_req["T3"]["attributionMs"]
    # same-trace child link: NO double deduction — 2 self + 6 child
    assert t3 == {"server:dispatch": 6.0, "server:request": 2.0}
    assert a["e2e"]["p99Ms"] == 10.0
    assert a["phases"]["server:dispatch"]["n"] == 2
    # the slowest request's path crosses the coalescing boundary into
    # the linked batch span
    assert a["slowest"][0]["traceId"] == "T1"
    names = [p["name"] for p in a["slowest"][0]["path"]]
    assert names == ["server:request", "score:prepare",
                     "server:dispatch"]


def test_diff_analyses_regression_watchdog():
    cur = {"e2e": {"p99Ms": 10.0},
           "phases": {"a": {"p99Ms": 20.0}, "b": {"p99Ms": 0.2},
                      "new": {"p99Ms": 1.0}}}
    base = {"e2e": {"p99Ms": 10.0},
            "phases": {"a": {"p99Ms": 10.0}, "b": {"p99Ms": 0.1},
                       "gone": {"p99Ms": 5.0}}}
    diff = workload_mod.diff_analyses(cur, base)
    verdicts = {v["phase"]: v["verdict"] for v in diff["verdicts"]}
    assert verdicts["e2e"] == "ok"
    assert verdicts["a"] == "regressed"           # +100%, +10 ms
    assert verdicts["b"] == "ok"                  # +100% but < abs floor
    assert verdicts["new"] == "added"
    assert verdicts["gone"] == "removed"
    assert diff["regressions"] == 1 and diff["ok"] is False
    assert workload_mod.diff_analyses(cur, cur)["ok"] is True


# ---------------------------------------------------------------------------
# CLI: workload merge/replay, trace analyze, gen/check knobs
# ---------------------------------------------------------------------------


def test_cli_workload_merge_and_strict(tmp_path, capsys):
    from transmogrifai_tpu.cli import main as cli_main
    d = str(tmp_path / "wl")
    os.makedirs(d)
    _write_shard(os.path.join(d, "shard-worker-1.workload.jsonl"),
                 "worker", 1, 100.0,
                 [{"tOffsetS": 1.0, "model": "m", "rows": 1,
                   "payload": [{"x": 1.0}]}])
    with open(os.path.join(d, "shard-worker-2.workload.jsonl"),
              "wb") as fh:
        fh.write(b"garbage\n")
    assert cli_main(["workload", "merge", d]) == 0
    err = capsys.readouterr().err
    assert "skipped" in err and "shard-worker-2" in err
    assert os.path.exists(os.path.join(d, "merged.workload.json"))
    # --strict makes a merge that skipped shards a non-zero exit
    assert cli_main(["workload", "merge", d, "--strict"]) == 1
    assert cli_main(["workload", "merge",
                     str(tmp_path / "missing")]) == 1


def test_cli_workload_replay_live(tiny_server, tmp_path, capsys):
    from transmogrifai_tpu.cli import main as cli_main
    srv, port, records = tiny_server
    d = str(tmp_path / "wl")
    workload_mod.start_recorder(d, role="worker")
    for lo in (0, 4):
        status, _doc = _post_score(port, "m", records[lo:lo + 4])
        assert status == 200
    workload_mod.stop_recorder()
    merged_path = str(tmp_path / "merged.workload.json")
    assert cli_main(["workload", "merge", d, "-o", merged_path]) == 0
    summary_path = str(tmp_path / "replay.json")
    assert cli_main(["workload", "replay", merged_path,
                     "--url", f"http://127.0.0.1:{port}",
                     "--speed", "100", "-o", summary_path]) == 0
    out = capsys.readouterr().out
    assert "2/2 request(s) re-driven" in out
    assert "parity: 2 checked, 0 failure(s)" in out
    with open(summary_path) as fh:
        doc = json.load(fh)
    assert doc["replayed"]["parityChecked"] == 2
    assert doc["recorded"]["models"]["m"]["requests"] == 2
    # replay without --url is an argument error, not a crash
    assert cli_main(["workload", "replay", merged_path]) == 1


def test_cli_trace_merge_surfaces_torn_shards_and_strict(tmp_path,
                                                         capsys):
    from transmogrifai_tpu.cli import run_trace
    telemetry.enable()
    with telemetry.trace_scope(telemetry.mint_trace()):
        with telemetry.span("wl:span"):
            pass
    d = str(tmp_path / "shards")
    telemetry.write_trace_shard(d, role="worker")
    with open(os.path.join(d, "shard-worker-99999.trace.json"),
              "w") as fh:
        fh.write('{"torn": tr')                   # unreadable shard
    assert run_trace("merge", d) == 0             # non-strict: warns
    err = capsys.readouterr().err
    assert "skipped" in err and "shard-worker-99999" in err
    assert run_trace("merge", d, strict=True) == 1
    assert "failing (--strict)" in capsys.readouterr().err


def test_cli_trace_analyze_and_baseline_watchdog(tmp_path, capsys):
    from transmogrifai_tpu.cli import run_trace
    trace_path = str(tmp_path / "merged.trace.json")
    with open(trace_path, "w") as fh:
        json.dump(_synthetic_trace(), fh)
    analysis_path = str(tmp_path / "analysis.json")
    assert run_trace("analyze", trace_path, out=analysis_path,
                     top_k=2) == 0
    out = capsys.readouterr().out
    assert "2 request trace(s)" in out and "coverage min 1.0" in out
    with open(analysis_path) as fh:
        analysis = json.load(fh)
    # self-baseline: clean; halved baseline p99s: regressions, exit 1
    assert run_trace("analyze", trace_path,
                     baseline=analysis_path) == 0
    assert "no regressions" in capsys.readouterr().out
    for ph in analysis["phases"].values():
        ph["p99Ms"] = ph["p99Ms"] / 2.0
    analysis["e2e"]["p99Ms"] = analysis["e2e"]["p99Ms"] / 2.0
    perturbed_path = str(tmp_path / "baseline.json")
    with open(perturbed_path, "w") as fh:
        json.dump(analysis, fh)
    assert run_trace("analyze", trace_path,
                     baseline=perturbed_path) == 1
    assert "regression(s)" in capsys.readouterr().err
    assert run_trace("analyze", str(tmp_path / "nope.json")) == 1


def test_cli_gen_emits_and_check_validates_workload_knobs(tmp_path):
    from transmogrifai_tpu.cli import generate_project, run_check
    csv = tmp_path / "d.csv"
    csv.write_text("label,x\n1,0.5\n0,0.2\n1,0.9\n0,0.1\n")
    out = generate_project(str(csv), "label", str(tmp_path / "proj"))
    params = json.loads(open(out["params.json"]).read())
    for knob in ("workloadDir", "workloadMaxMb", "workloadPayloads"):
        assert knob in params["customParams"]
        assert params["customParams"][knob] is None
    for bad_knobs in ({"workloadDir": 7},
                      {"workloadMaxMb": "big"},
                      {"workloadMaxMb": -1.0},
                      {"workloadPayloads": "yes"}):
        bad = dict(params)
        bad["customParams"] = dict(params["customParams"], **bad_knobs)
        bad_path = tmp_path / "bad.json"
        bad_path.write_text(json.dumps(bad))
        assert run_check(str(bad_path)) == 1, bad_knobs


# ---------------------------------------------------------------------------
# chaos satellite: SIGKILL mid-write tears ONE line, merge survives
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_sigkill_mid_write_tears_one_line_merge_survives(tmp_path):
    d = str(tmp_path / "wl")
    child = textwrap.dedent("""
        import json, os, signal, sys, time
        from transmogrifai_tpu import workload
        d = sys.argv[1]
        rec = workload.start_recorder(d, role="worker")
        for i in range(5):
            workload.record_request("m", 1, records=[{"x": float(i)}],
                                    trace_id=f"t{i}")
        for _ in range(200):               # wait for the writer thread
            if workload.workload_stats()["records_written"] == 5:
                break
            time.sleep(0.05)
        else:
            sys.exit(3)
        # die mid-line: append a torn record with NO terminator, then
        # SIGKILL ourselves — no atexit, no flush, no drain
        with open(rec.shard_path, "ab") as fh:
            fh.write(b'{"kind":"request","model":"m","ro')
            fh.flush()
            os.kill(os.getpid(), signal.SIGKILL)
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", child, d],
                          cwd=_REPO, env=env, capture_output=True,
                          timeout=240)
    assert proc.returncode == -signal.SIGKILL, proc.stderr.decode()
    merged = workload_mod.merge_workload_shards(d)
    assert merged["requests"] == 5                # good lines survive
    assert merged["tornRecordsSkipped"] == 1      # torn tail tallied
    assert "mergeErrors" not in merged
    # and the CLI path reports it without failing (non-strict)
    from transmogrifai_tpu.cli import run_workload
    assert run_workload("merge", d) == 0
