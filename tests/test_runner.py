"""Runner / OpParams / observability / warm start / random search tests
(OpWorkflowRunnerTest / OpParamsTest analogs)."""
import json
import os

import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, Workflow, column_from_values
from transmogrifai_tpu.evaluators import Evaluators
from transmogrifai_tpu.models.linear import LogisticRegressionFamily
from transmogrifai_tpu.models.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.models.tuning import RandomParamBuilder
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.runner import (OpApp, OpParams, OpWorkflowRunner,
                                      RunType)
from transmogrifai_tpu.types import feature_types as ft


class _ListReader:
    def __init__(self, records):
        self._records = records

    def read_records(self):
        return list(self._records)


def _records(rng, n=200):
    y = rng.integers(0, 2, n).astype(float)
    x = rng.normal(size=n) + y
    return [{"label": float(y[i]), "x": float(x[i])} for i in range(n)]


def _flow(num_folds=2):
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    vec = transmogrify([fx])
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=num_folds, families=[LogisticRegressionFamily()],
        splitter=None, seed=5)
    pred = label.transform_with(selector, vec)
    wf = Workflow().set_result_features(pred)
    return wf, label, pred, selector


def test_runner_train_score_evaluate(rng, tmp_path):
    records = _records(rng)
    reader = _ListReader(records)
    wf, label, pred, _sel = _flow()
    evaluator = Evaluators.BinaryClassification.auPR().set_columns(label, pred)
    runner = OpWorkflowRunner(wf, training_reader=reader,
                              scoring_reader=reader, evaluator=evaluator)
    params = OpParams(model_location=str(tmp_path / "model"),
                      metrics_location=str(tmp_path / "metrics.json"),
                      write_location=str(tmp_path / "scores.csv"))

    out = runner.run(RunType.TRAIN, params)
    assert out.model_location and os.path.exists(
        os.path.join(out.model_location, "model.json"))
    assert os.path.exists(params.metrics_location)
    # per-stage timers rode into the metrics sink (OpSparkListener analog)
    sunk = json.load(open(params.metrics_location))
    assert any("fitSeconds" in m for m in sunk["stageMetrics"].values())

    out = runner.run(RunType.SCORE, params)
    assert out.metrics["rowsScored"] == len(records)
    assert os.path.exists(params.write_location)

    out = runner.run(RunType.EVALUATE, params)
    assert out.metrics["AuPR"] > 0.6


def test_opparams_stage_overrides(rng, tmp_path):
    p = tmp_path / "params.json"
    p.write_text(json.dumps({
        "stageParams": {"SanityChecker": {"min_variance": 0.123}},
        "customParams": {"tag": "run1"}}))
    params = OpParams.from_file(str(p))
    assert params.custom_params["tag"] == "run1"

    from transmogrifai_tpu.ops.sanity_checker import SanityChecker
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    vec = transmogrify([fx])
    checked = label.transform_with(SanityChecker(), vec)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None)
    pred = label.transform_with(selector, checked)
    wf = Workflow().set_result_features(pred)
    params.apply_to_workflow(wf)
    assert checked.origin_stage.min_variance == 0.123


def test_warm_start_skips_refit(rng):
    records = _records(rng)
    wf, label, pred, selector = _flow()
    model = wf.set_input_records(records).train()

    wf2, label2, pred2, selector2 = _flow()
    # same DAG object reuse: warm start matches by uid, so rebuild the SAME
    # features through with_model_stages on a fresh workflow over them
    wf3 = (Workflow().set_result_features(pred)
           .set_input_records(records).with_model_stages(model))
    model2 = wf3.train()
    m = model2.stage_metrics[selector.uid]
    assert m.get("warmStarted") is True and m["fitSeconds"] == 0.0
    # warm-started model scores identically AND the donor model's stage
    # wiring is untouched (no in-place mutation)
    s1 = model.score(records)
    s2 = model2.score(records)
    np.testing.assert_allclose(
        np.asarray(s1[pred.name].prediction),
        np.asarray(s2[pred.name].prediction))


def test_random_param_builder():
    grid = (RandomParamBuilder(seed=1)
            .exponential("regParam", 1e-4, 1e-1)
            .uniform("elasticNetParam", 0.0, 1.0)
            .choice("fitIntercept", [True, False])
            .build(25))
    assert len(grid) == 25
    regs = [g["regParam"] for g in grid]
    assert all(1e-4 <= r <= 1e-1 for r in regs)
    # log-uniform: spread over decades
    assert min(regs) < 1e-3 and max(regs) > 1e-2
    assert {g["fitIntercept"] for g in grid} == {True, False}


class _App(OpApp):
    def __init__(self, runner_obj):
        self._runner = runner_obj

    def runner(self, params):
        return self._runner


def test_op_app_cli(rng, tmp_path):
    records = _records(rng)
    reader = _ListReader(records)
    wf, label, pred, _sel = _flow()
    runner = OpWorkflowRunner(wf, training_reader=reader,
                              scoring_reader=reader)
    app = _App(runner)
    out = app.main(["--run-type", "Train",
                    "--model-location", str(tmp_path / "m"),
                    "--metrics-location", str(tmp_path / "met.json")])
    assert out.run_type == "Train"
    assert os.path.exists(str(tmp_path / "met.json"))


def test_summary_pretty_renders_stage_table(rng):
    records = _records(rng, 80)
    wf, label, pred, _sel = _flow()
    model = wf.set_input_records(records).train()
    text = model.summary_pretty()
    assert "Stage metrics" in text and "fit s" in text
    from transmogrifai_tpu.utils.table import Table
    t = Table(["a", "b"], [[1, 2.5], ["x", None]], name="T")
    s = t.render()
    assert "| a" in s and "2.5" in s


def test_streaming_score_run_type(rng, tmp_path):
    records = _records(rng, 150)
    reader = _ListReader(records)
    wf, label, pred, _sel = _flow()
    runner = OpWorkflowRunner(wf, training_reader=reader,
                              scoring_reader=reader)
    params = OpParams(model_location=str(tmp_path / "m"),
                      write_location=str(tmp_path / "s.csv"),
                      custom_params={"batchSize": 64})
    runner.run(RunType.TRAIN, params)
    out = runner.run(RunType.STREAMING_SCORE, params)
    assert out.metrics["rowsScored"] == 150
    assert out.metrics["batches"] == 3
    assert os.path.exists(params.write_location)
    assert sum(1 for _ in open(params.write_location)) == 151   # header + rows


def test_train_logs_and_compile_split(rng, caplog, tmp_path):
    """VERDICT r2 #9: a training run narrates itself at INFO and stage
    metrics split fit wall-clock into compile vs execute seconds."""
    import logging

    from transmogrifai_tpu import FeatureBuilder, Workflow
    from transmogrifai_tpu.columns import ColumnStore, column_from_values
    from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                          LogisticRegressionFamily)
    from transmogrifai_tpu.ops import transmogrify
    from transmogrifai_tpu.types import feature_types as ft

    n = 200
    x = rng.normal(size=n)
    y = (x > 0).astype(float)
    store = ColumnStore({
        "x": column_from_values(ft.Real, x.tolist()),
        "y": column_from_values(ft.RealNN, y.tolist()),
    }, n)
    yf = FeatureBuilder.RealNN("y").from_column().as_response()
    xf = FeatureBuilder.Real("x").from_column().as_predictor()
    vec = transmogrify([xf])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])])
    pred = yf.transform_with(sel, vec)

    with caplog.at_level(logging.INFO, logger="transmogrifai_tpu"):
        model = (Workflow().set_input_store(store)
                 .set_result_features(pred).train())

    text = "\n".join(r.getMessage() for r in caplog.records)
    assert "train:" in text and "fitting" in text and "fit in" in text
    assert "chunk plan" in text

    sel_metrics = model.stage_metrics[sel.uid]
    assert "compileSeconds" in sel_metrics and "executeSeconds" in sel_metrics
    assert sel_metrics["fitSeconds"] >= sel_metrics["executeSeconds"]
    pretty = model.summary_pretty()
    assert "compile s" in pretty and "execute s" in pretty


def test_score_avro_output_roundtrip(rng, tmp_path):
    """VERDICT r2 #10: the Score run type writes Avro (saveScores /
    RichDataset.saveAvro analog) when the sink path ends in .avro, with
    the store column-pruned to result features; the package's own decoder
    round-trips it."""
    from transmogrifai_tpu.readers.avro import read_avro_records

    records = _records(rng)
    reader = _ListReader(records)
    wf, label, pred, _sel = _flow()
    runner = OpWorkflowRunner(wf, training_reader=reader,
                              scoring_reader=reader)
    params = OpParams(model_location=str(tmp_path / "model"),
                      write_location=str(tmp_path / "scores.avro"))
    runner.run(RunType.TRAIN, params)
    out = runner.run(RunType.SCORE, params)

    back = read_avro_records(params.write_location)
    assert len(back) == len(records)
    # pruned to the result feature column (+ no intermediate vectors)
    assert set(back[0].keys()) == set(out.scores.names())
    row0 = back[0][pred.name]
    assert "prediction" in row0 and any(k.startswith("prob") for k in row0)
    preds = [r[pred.name]["prediction"] for r in back]
    np.testing.assert_allclose(
        preds, np.asarray(out.scores[pred.name].prediction), rtol=1e-12)

    # streaming scoring writes the same container incrementally
    params2 = OpParams(model_location=params.model_location,
                       write_location=str(tmp_path / "stream.avro"),
                       custom_params={"batchSize": 64})
    runner.run(RunType.STREAMING_SCORE, params2)
    back2 = read_avro_records(params2.write_location)
    assert len(back2) == len(records)
    np.testing.assert_allclose(
        [r[pred.name]["prediction"] for r in back2], preds, rtol=1e-12)


def test_runner_mesh_knobs_validated_and_stamped(rng, tmp_path):
    """PR 6 satellites: customParams.meshDevices/meshGridSize bound the
    run's mesh via the validated numeric path, the topology is stamped
    in the metrics doc, and the previous process mesh is restored."""
    from transmogrifai_tpu.parallel.mesh import process_default_mesh

    records = _records(rng)
    reader = _ListReader(records)
    wf, label, pred, _sel = _flow()
    runner = OpWorkflowRunner(wf, training_reader=reader)
    # malformed values name their key before any data is read
    with pytest.raises(ValueError, match="meshDevices"):
        runner.run(RunType.TRAIN, OpParams(
            custom_params={"meshDevices": 2.5}))
    with pytest.raises(ValueError, match="meshGridSize"):
        runner.run(RunType.TRAIN, OpParams(
            custom_params={"meshGridSize": 0}))
    # impossible splits fail descriptively up front — and a
    # meshGridSize the device count cannot divide must RAISE, never
    # silently round down to a nearby power of two
    with pytest.raises(ValueError, match="exceeds the 8 visible"):
        runner.run(RunType.TRAIN, OpParams(
            custom_params={"meshDevices": 64}))
    with pytest.raises(ValueError, match="impossible"):
        runner.run(RunType.TRAIN, OpParams(
            custom_params={"meshGridSize": 3}))

    before = process_default_mesh()
    out = runner.run(RunType.TRAIN, OpParams(
        model_location=str(tmp_path / "m"),
        custom_params={"meshDevices": 4, "meshGridSize": 2}))
    assert out.metrics["mesh"]["devices"] == 4
    assert out.metrics["mesh"]["data"] == 2
    assert out.metrics["mesh"]["grid"] == 2
    # run-scoped: the process mesh is back afterwards
    assert process_default_mesh() is before


def test_runner_metrics_doc_always_stamps_mesh(rng, tmp_path):
    records = _records(rng)
    reader = _ListReader(records)
    wf, label, pred, _sel = _flow()
    runner = OpWorkflowRunner(wf, training_reader=reader,
                              scoring_reader=reader)
    params = OpParams(model_location=str(tmp_path / "m"))
    out = runner.run(RunType.TRAIN, params)
    topo = out.metrics["mesh"]
    assert topo["devices"] == 8 and topo["platform"] == "cpu"
    # the always-on flight-recorder tallies ride the same doc
    wl = out.metrics["workload"]
    assert wl["recording"] is False and "records_written" in wl
    out2 = runner.run(RunType.SCORE, params)
    assert out2.metrics["mesh"]["devices"] == 8


def test_op_app_mesh_devices_flag(rng, tmp_path):
    records = _records(rng)
    reader = _ListReader(records)
    wf, label, pred, _sel = _flow()
    runner = OpWorkflowRunner(wf, training_reader=reader)
    captured = {}

    class _CapturingApp(OpApp):
        def runner(self, params):
            captured["params"] = params
            return runner

    out = _CapturingApp().main(
        ["--run-type", "Train", "--mesh-devices", "4",
         "--model-location", str(tmp_path / "m"), "--quiet"])
    assert captured["params"].custom_params["meshDevices"] == 4
    assert out.metrics["mesh"]["devices"] == 4


def test_runner_stream_fit_knobs_validated_and_scoped(rng, tmp_path):
    """PR 16 satellite: customParams.streamFit/streamFitPasses/rssCapMb/
    featureShards install run-scoped (the process knobs are restored
    after the run), malformed values name their key before any data is
    read, and a streamFit=true run off a directory reader takes the
    streamed ingest end to end."""
    from transmogrifai_tpu import workflow as wfmod
    from transmogrifai_tpu.models import _treefit
    from transmogrifai_tpu.readers import DirectoryStreamReader
    from transmogrifai_tpu.readers.avro import write_avro_records

    records = _records(rng)
    wf, label, pred, _sel = _flow()
    runner = OpWorkflowRunner(wf, training_reader=_ListReader(records))
    for key, bad in (("streamFitPasses", 0), ("rssCapMb", 0),
                     ("featureShards", 0), ("streamFit", "yes")):
        with pytest.raises(ValueError, match=key):
            runner.run(RunType.TRAIN, OpParams(custom_params={key: bad}))

    d = tmp_path / "train"
    d.mkdir()
    for i in range(2):
        write_avro_records(str(d / f"p{i}.avro"),
                           records[i * 100:(i + 1) * 100])
    wf2, label2, pred2, _sel2 = _flow()
    runner2 = OpWorkflowRunner(
        wf2, training_reader=DirectoryStreamReader(str(d),
                                                   settle_s=0.0))
    before = (wfmod.STREAM_FIT, wfmod.STREAM_FIT_PASSES,
              wfmod.STREAM_RSS_CAP_MB, wfmod._INGEST_TIER_HINT,
              _treefit.active_feature_shards())
    out = runner2.run(RunType.TRAIN, OpParams(
        model_location=str(tmp_path / "m"),
        custom_params={"streamFit": True, "streamFitPasses": 2,
                       "rssCapMb": 4096, "featureShards": 1}))
    assert os.path.exists(os.path.join(out.model_location, "model.json"))
    # run-scoped: every knob is back afterwards
    assert (wfmod.STREAM_FIT, wfmod.STREAM_FIT_PASSES,
            wfmod.STREAM_RSS_CAP_MB, wfmod._INGEST_TIER_HINT,
            _treefit.active_feature_shards()) == before
