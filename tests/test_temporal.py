"""Temporal workload tier tests (temporal.py + reader integration):
columnar aggregation bit-parity against the row-wise readers across
monoid families / cutoff shapes / join types, the parallel partial-
aggregation paths, the bounded streaming hash join (spill-to-quarantine,
fault-site retry, breaker fallback), the runner/CLI knob wiring, and the
TMG7xx cutoff-leakage rules (static, gated before reader I/O)."""
import json
import os

import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder, Workflow, lint, temporal
from transmogrifai_tpu import resilience
from transmogrifai_tpu.readers import (AggregateReader, ConditionalReader,
                                       CutOffTime, DataReaders,
                                       JoinedAggregateDataReader,
                                       JoinedDataReader, TemporalJoinReader)
from transmogrifai_tpu.readers.avro import write_avro_records
from transmogrifai_tpu.runner import (OpParams, OpWorkflowRunner, RunType)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.utils.aggregators import (ConcatTextAggregator,
                                                 FirstAggregator,
                                                 LastAggregator,
                                                 LogicalOrAggregator,
                                                 MaxAggregator,
                                                 MeanAggregator,
                                                 MinAggregator,
                                                 ModeAggregator,
                                                 SumAggregator)


class _TableSource:
    """Reader handing a prebuilt columnar batch to the temporal tier."""

    def __init__(self, table, key_fn):
        self._table = table
        self.key_fn = key_fn

    def read_records(self):
        return self._table


def _events(rng, n=4000, n_keys=37, text=False):
    recs = []
    for _ in range(n):
        r = {"user": float(rng.integers(0, n_keys)),
             "ts": float(rng.uniform(0, 1000.0)),
             "amount": float(rng.gamma(2.0, 10.0)),
             "flag": bool(rng.random() < 0.2)}
        if text:
            r["word"] = f"w{int(rng.integers(0, 5))}"
        recs.append(r)
    return recs


KEY = temporal.field("user")
TS = temporal.field("ts")


def _amount(name, agg, window=None, response=False):
    b = FeatureBuilder.Real(name).extract(temporal.field("amount"),
                                          "amount").aggregate(agg)
    if window is not None:
        b = b.window(window)
    return b.as_response() if response else b.as_predictor()


def _assert_store_equal(a, b, names):
    assert a.n_rows == b.n_rows
    for name in names:
        ca, cb = a[name], b[name]
        assert type(ca) is type(cb), name
        va = getattr(ca, "values", None)
        if va is not None:
            assert np.array_equal(ca.values, cb.values, equal_nan=True), name
        if hasattr(ca, "mask") and not callable(getattr(ca, "mask")):
            assert np.array_equal(ca.mask, cb.mask), name


# ---------------------------------------------------------------------------
# columnar aggregation parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cutoff", [CutOffTime.at(700),
                                    CutOffTime.no_cutoff()])
def test_columnar_aggregate_bit_identical_across_monoids(rng, cutoff):
    recs = _events(rng)
    tab = temporal.table_from_records(recs)
    feats = [
        _amount("s", SumAggregator()),
        _amount("m", MeanAggregator()),
        _amount("mx", MaxAggregator()),
        _amount("mn", MinAggregator()),
        _amount("first", FirstAggregator()),
        _amount("last", LastAggregator()),
        _amount("w", MeanAggregator(), window=150),
        FeatureBuilder.Binary("or").extract(temporal.field("flag"), "flag")
        .aggregate(LogicalOrAggregator()).as_response(),
    ]
    row = AggregateReader(DataReaders.simple.records(recs), TS, cutoff,
                          key_fn=KEY).generate_store(feats)
    before = temporal.temporal_stats()
    col = AggregateReader(_TableSource(tab, KEY), TS, cutoff,
                          key_fn=KEY).generate_store(feats)
    after = temporal.temporal_stats()
    assert after["columnar_aggregates"] == before["columnar_aggregates"] + 1
    _assert_store_equal(row, col, [f.name for f in feats])


def test_columnar_aggregate_text_and_mode_monoids(rng):
    recs = _events(rng, n=600, n_keys=9, text=True)
    tab = temporal.table_from_records(recs)
    feats = [
        FeatureBuilder.Text("cat").extract(temporal.field("word"), "word")
        .aggregate(ConcatTextAggregator()).as_predictor(),
        FeatureBuilder.PickList("pick").extract(temporal.field("word"),
                                                "word")
        .aggregate(ModeAggregator()).as_predictor(),
        # no explicit aggregator: the type default (concat) must resolve
        # identically on both paths
        FeatureBuilder.Text("word").from_column().as_predictor(),
    ]
    cutoff = CutOffTime.at(500)
    row = AggregateReader(DataReaders.simple.records(recs), TS, cutoff,
                          key_fn=KEY).generate_store(feats)
    col = AggregateReader(_TableSource(tab, KEY), TS, cutoff,
                          key_fn=KEY).generate_store(feats)
    assert row.n_rows == col.n_rows
    for f in feats:
        assert row[f.name].to_list() == col[f.name].to_list()


def test_columnar_boundary_ts_equal_cutoff():
    """The pinned boundary, columnar == row-wise: an event exactly AT
    the cutoff lands in NEITHER fold; ts just below folds into the
    predictor, just above into the response."""
    recs = [
        {"user": 1.0, "ts": 99.0, "amount": 2.0, "flag": False},
        {"user": 1.0, "ts": 100.0, "amount": 5.0, "flag": True},   # AT
        {"user": 1.0, "ts": 101.0, "amount": 11.0, "flag": False},
    ]
    feats = [_amount("spend", SumAggregator()),
             FeatureBuilder.Binary("out").extract(temporal.field("flag"),
                                                  "flag")
             .aggregate(LogicalOrAggregator()).as_response(),
             _amount("after", SumAggregator(), response=True)]
    cutoff = CutOffTime.at(100)
    row = AggregateReader(DataReaders.simple.records(recs), TS, cutoff,
                          key_fn=KEY).generate_store(feats)
    col = AggregateReader(
        _TableSource(temporal.table_from_records(recs), KEY), TS, cutoff,
        key_fn=KEY).generate_store(feats)
    for store in (row, col):
        assert store["spend"].get_raw(0) == 2.0       # ts=100 excluded
        assert store["out"].get_raw(0) is False       # flag@cutoff excluded
        assert store["after"].get_raw(0) == 11.0      # strictly after
    _assert_store_equal(row, col, [f.name for f in feats])


def test_conditional_columnar_parity_and_edge_cases(rng):
    recs = _events(rng, n=2500, n_keys=25)
    tab = temporal.table_from_records(recs)
    feats = [_amount("s", SumAggregator()),
             _amount("resp", SumAggregator(), response=True)]
    cond = temporal.field("flag")
    for drop in (True, False):
        row = ConditionalReader(DataReaders.simple.records(recs), TS,
                                lambda r: bool(r["flag"]),
                                drop_if_no_condition=drop,
                                key_fn=KEY).generate_store(feats)
        col = ConditionalReader(_TableSource(tab, KEY), TS,
                                lambda r: bool(r["flag"]),
                                drop_if_no_condition=drop,
                                key_fn=KEY).generate_store(feats)
        _assert_store_equal(row, col, [f.name for f in feats])
    assert cond is not None


def test_unroutable_extractor_falls_back_rowwise(rng):
    """A custom (non-column-keyed) extract_fn cannot vectorize: the
    columnar route declines and the row-wise fold serves, identical —
    and the breaker is NOT poisoned."""
    recs = _events(rng, n=400, n_keys=7)
    tab = temporal.table_from_records(recs)
    opaque = (FeatureBuilder.Real("double_amt")
              .extract(lambda r: (r.get("amount") or 0.0) * 2, "amount")
              .aggregate(SumAggregator()).as_predictor())
    cutoff = CutOffTime.at(600)
    row = AggregateReader(DataReaders.simple.records(recs), TS, cutoff,
                          key_fn=KEY).generate_store([opaque])
    before = temporal.temporal_stats()
    col = AggregateReader(_TableSource(tab, KEY), TS, cutoff,
                          key_fn=KEY).generate_store([opaque])
    after = temporal.temporal_stats()
    assert after["rowwise_aggregates"] == before["rowwise_aggregates"] + 1
    assert after["columnar_aggregates"] == before["columnar_aggregates"]
    assert resilience.breaker("temporal.columnar").state == "closed"
    _assert_store_equal(row, col, ["double_amt"])


def test_columnar_mode_knob_forces_off(rng):
    recs = _events(rng, n=300, n_keys=5)
    tab = temporal.table_from_records(recs)
    feats = [_amount("s", SumAggregator())]
    prev = temporal.set_run_defaults(columnar=False)
    try:
        before = temporal.temporal_stats()
        AggregateReader(_TableSource(tab, KEY), TS, CutOffTime.at(500),
                        key_fn=KEY).generate_store(feats)
        after = temporal.temporal_stats()
        assert after["columnar_aggregates"] == before["columnar_aggregates"]
        assert after["rowwise_aggregates"] == \
            before["rowwise_aggregates"] + 1
    finally:
        temporal.set_run_defaults(**prev)


def test_columnar_fault_trips_breaker_and_falls_back(rng):
    """A fault injected at temporal.aggregate degrades to the row-wise
    fold bit-identically, counts a fallback, and repeated failures trip
    the temporal.columnar breaker (later reads skip the failing tier
    without attempting)."""
    recs = _events(rng, n=500, n_keys=8)
    tab = temporal.table_from_records(recs)
    feats = [_amount("s", SumAggregator())]
    cutoff = CutOffTime.at(500)
    want = AggregateReader(DataReaders.simple.records(recs), TS, cutoff,
                           key_fn=KEY).generate_store(feats)
    resilience.reset_breakers()
    plan = resilience.FaultPlan(seed=3).on("temporal.aggregate",
                                           error=RuntimeError)
    before = temporal.temporal_stats()
    with resilience.fault_plan(plan):
        for _ in range(4):
            got = AggregateReader(_TableSource(tab, KEY), TS, cutoff,
                                  key_fn=KEY).generate_store(feats)
            _assert_store_equal(want, got, ["s"])
    after = temporal.temporal_stats()
    assert after["columnar_fallbacks"] >= before["columnar_fallbacks"] + 3
    br = resilience.breaker("temporal.columnar")
    assert br.state == "open"
    # breaker OPEN: the failing columnar pass is not even attempted
    fired_before = plan.fired("temporal.aggregate")
    with resilience.fault_plan(plan):
        got = AggregateReader(_TableSource(tab, KEY), TS, cutoff,
                              key_fn=KEY).generate_store(feats)
    _assert_store_equal(want, got, ["s"])
    assert plan.fired("temporal.aggregate") == fired_before
    resilience.reset_breakers()


# ---------------------------------------------------------------------------
# parallel partial aggregation
# ---------------------------------------------------------------------------


def test_aggregate_directory_parallel_bit_identical(rng, tmp_path):
    all_recs = []
    for i in range(5):
        recs = _events(rng, n=800, n_keys=30)
        all_recs.extend(recs)
        write_avro_records(str(tmp_path / f"b{i:03d}.avro"), recs)
    feats = [_amount("s", SumAggregator()),
             _amount("w", MeanAggregator(), window=250),
             FeatureBuilder.Binary("r").extract(temporal.field("flag"),
                                                "flag")
             .aggregate(LogicalOrAggregator()).as_response()]
    serial = AggregateReader(DataReaders.simple.records(all_recs), TS,
                             CutOffTime.at(650),
                             key_fn=KEY).generate_store(feats)
    for workers in (1, 3):
        par = temporal.aggregate_directory(str(tmp_path), feats, TS, KEY,
                                           cutoff_ms=650, workers=workers)
        _assert_store_equal(serial, par, [f.name for f in feats])


def test_aggregate_tables_matches_single_table(rng):
    tables = [temporal.table_from_records(_events(rng, n=700, n_keys=20))
              for _ in range(3)]
    feats = [_amount("s", SumAggregator())]
    whole = AggregateReader(
        _TableSource(temporal.concat_tables(tables), KEY), TS,
        CutOffTime.at(500), key_fn=KEY).generate_store(feats)
    split = temporal.aggregate_tables(tables, feats, TS, KEY,
                                      cutoff_ms=500, workers=2)
    _assert_store_equal(whole, split, ["s"])


# ---------------------------------------------------------------------------
# streaming hash join
# ---------------------------------------------------------------------------


def _join_fixture(rng, n=3000, n_keys=40, missing=6):
    left = _events(rng, n=n, n_keys=n_keys)
    right = [{"user": float(u), "seg": float(u % 7)}
             for u in range(n_keys - missing)]
    return left, right


@pytest.mark.parametrize("join_type", ["left_outer", "inner"])
def test_streaming_join_matches_joined_reader(rng, join_type):
    left, right = _join_fixture(rng)
    lr = DataReaders.simple.records(left, key_fn=KEY)
    rr = DataReaders.simple.records(right, key_fn=KEY)
    old = JoinedDataReader(lr, rr, join_type).read_records()
    new = TemporalJoinReader(lr, rr, join_type).read_records()
    assert len(old) == len(new)
    for a, b in zip(old, new):
        for k in set(a) | set(b):
            assert a.get(k) == b.get(k), (join_type, k)


@pytest.mark.parametrize("join_type", ["left_outer", "inner"])
def test_columnar_join_aggregate_composition_parity(rng, join_type):
    left, right = _join_fixture(rng)
    feats = [_amount("s", SumAggregator()),
             FeatureBuilder.Real("seg_f").extract(temporal.field("seg"),
                                                  "seg")
             .aggregate(MaxAggregator()).as_predictor(),
             FeatureBuilder.Binary("r").extract(temporal.field("flag"),
                                                "flag")
             .aggregate(LogicalOrAggregator()).as_response()]
    row = JoinedAggregateDataReader(
        DataReaders.simple.records(left, key_fn=KEY),
        DataReaders.simple.records(right, key_fn=KEY),
        TS, CutOffTime.at(700), join_type).generate_store(feats)
    col = JoinedAggregateDataReader(
        _TableSource(temporal.table_from_records(left), KEY),
        _TableSource(temporal.table_from_records(right), KEY),
        TS, CutOffTime.at(700), join_type).generate_store(feats)
    _assert_store_equal(row, col, [f.name for f in feats])


def test_join_aggregate_directory_workers_parity(rng, tmp_path):
    all_recs = []
    for i in range(4):
        recs = _events(rng, n=900, n_keys=35)
        all_recs.extend(recs)
        write_avro_records(str(tmp_path / f"e{i:02d}.avro"), recs)
    right = [{"user": float(u), "seg": float(u % 5)} for u in range(30)]
    feats = [_amount("s", SumAggregator()),
             FeatureBuilder.Real("seg_f").extract(temporal.field("seg"),
                                                  "seg")
             .aggregate(MaxAggregator()).as_predictor()]
    want = JoinedAggregateDataReader(
        DataReaders.simple.records(all_recs, key_fn=KEY),
        DataReaders.simple.records(right, key_fn=KEY),
        TS, CutOffTime.at(600)).generate_store(feats)
    for w in (1, 3):
        got = temporal.join_aggregate_directory(
            str(tmp_path), feats, temporal.table_from_records(right),
            TS, KEY, cutoff_ms=600, workers=w)
        _assert_store_equal(want, got, [f.name for f in feats])


def test_join_aggregate_directory_dict_right_lifts_and_bound_rejects(
        rng, tmp_path):
    """A plain list-of-dicts dimension table auto-lifts to a columnar
    build side; an un-vectorizable build (over the partition bound)
    is rejected LOUDLY up front instead of crashing inside a worker."""
    recs = _events(rng, n=400, n_keys=12)
    write_avro_records(str(tmp_path / "a.avro"), recs)
    right = [{"user": float(u), "seg": float(u)} for u in range(12)]
    feats = [_amount("s", SumAggregator()),
             FeatureBuilder.Real("seg_f").extract(temporal.field("seg"),
                                                  "seg")
             .aggregate(MaxAggregator()).as_predictor()]
    via_table = temporal.join_aggregate_directory(
        str(tmp_path), feats, temporal.table_from_records(right), TS, KEY,
        cutoff_ms=600)
    via_dicts = temporal.join_aggregate_directory(
        str(tmp_path), feats, right, TS, KEY, cutoff_ms=600)
    _assert_store_equal(via_table, via_dicts, [f.name for f in feats])
    prev = temporal.set_run_defaults(join_partitions=1,
                                     join_table_max_rows=3)
    try:
        with pytest.raises(temporal.TemporalError, match="bounded"):
            temporal.join_aggregate_directory(
                str(tmp_path), feats, right, TS, KEY, cutoff_ms=600)
    finally:
        temporal.set_run_defaults(**prev)


def test_unroutable_pass_does_not_reset_breaker_failures(rng):
    """An unroutable (TemporalError) aggregation records NEITHER
    success nor failure: interleaving one with a failing columnar
    reader must not keep resetting the consecutive-failure count."""
    recs = _events(rng, n=200, n_keys=5)
    tab = temporal.table_from_records(recs)
    opaque = (FeatureBuilder.Real("d")
              .extract(lambda r: r.get("amount"), "amount")
              .aggregate(SumAggregator()).as_predictor())
    good = [_amount("s", SumAggregator())]
    cutoff = CutOffTime.at(500)
    resilience.reset_breakers()
    # fault only the GOOD reads (calls 0/2/4) — the interleaved opaque
    # reads (calls 1/3) must reach the engine and raise TemporalError
    plan = resilience.FaultPlan(seed=8).on("temporal.aggregate",
                                           error=RuntimeError,
                                           at=[0, 2, 4])
    br = resilience.breaker("temporal.columnar")
    with resilience.fault_plan(plan):
        for i in range(3):
            AggregateReader(_TableSource(tab, KEY), TS, cutoff,
                            key_fn=KEY).generate_store(good)   # fails
            if i < 2:
                # unroutable pass between failures must not reset them
                AggregateReader(_TableSource(tab, KEY), TS, cutoff,
                                key_fn=KEY).generate_store([opaque])
                assert br.consecutive_failures == i + 1
    assert br.state == "open"
    resilience.reset_breakers()


def test_join_table_overflow_spills_to_quarantine(tmp_path, rng):
    """A build-side partition past joinTableMaxRows spills NEW keys'
    rows to the dead-letter sink (counted + replayable) instead of
    growing the heap; probe rows for spilled keys come back unmatched."""
    sink_path = str(tmp_path / "dead.jsonl")
    prev_sink = resilience.set_quarantine(sink_path)
    try:
        right = [{"user": float(u), "seg": float(u)} for u in range(10)]
        left = [{"user": float(u), "ts": 1.0, "amount": 1.0}
                for u in range(10)]
        before = temporal.temporal_stats()
        out = TemporalJoinReader(
            DataReaders.simple.records(left, key_fn=KEY),
            DataReaders.simple.records(right, key_fn=KEY),
            "left_outer", partitions=1,
            table_max_rows=4).read_records()
        after = temporal.temporal_stats()
        assert len(out) == 10                 # probe side never dropped
        spilled = after["join_spilled_rows"] - before["join_spilled_rows"]
        assert spilled == 6
        matched = [r for r in out if r.get("seg") is not None]
        assert len(matched) == 4
        entries = resilience.get_quarantine().entries()
        assert sum(1 for e in entries
                   if e["site"] == "temporal.join") == spilled
        assert all(e["records"] for e in entries
                   if e["site"] == "temporal.join")   # replayable
    finally:
        resilience.set_quarantine(prev_sink)


def test_join_mixed_int_float_keys_match_like_dict_join(rng):
    """Python-dict key equality is the join contract: int 1, float 1.0
    and True are ONE key, so the partitioned build tables must land
    them in one partition — a repr-based hash split an int-keyed build
    side from a float-keyed probe side and silently unmatched every
    row (regression test for the canonical-key fix)."""
    left = [{"user": float(u % 6), "ts": 1.0, "amount": 1.0}
            for u in range(24)]
    right = [{"user": int(u), "seg": float(u * 10)} for u in range(6)]
    lr = DataReaders.simple.records(left, key_fn=KEY)
    rr = DataReaders.simple.records(right, key_fn=KEY)
    want = JoinedDataReader(lr, rr).read_records()
    for partitions in (1, 4, 7):
        got = TemporalJoinReader(lr, rr,
                                 partitions=partitions).read_records()
        assert all(a.get("seg") == b.get("seg") and a.get("seg") is not None
                   for a, b in zip(want, got))
    assert temporal.partition_of(1, 7) == temporal.partition_of(1.0, 7) \
        == temporal.partition_of(True, 7) \
        == temporal.partition_of(np.float64(1.0), 7)


def test_nan_timestamp_folds_both_sides_row_and_columnar():
    """A NaN event time passes none of the row-wise cutoff guards, so
    the row folds into BOTH sides (and bypasses windows); the columnar
    masks must match bit-for-bit (regression test for the NaN-ts
    parity fix)."""
    recs = [{"user": 1.0, "ts": float("nan"), "amount": 5.0,
             "flag": True},
            {"user": 1.0, "ts": 50.0, "amount": 1.0, "flag": False},
            {"user": 1.0, "ts": 150.0, "amount": 2.0, "flag": False}]
    feats = [_amount("pred", SumAggregator()),
             _amount("win", SumAggregator(), window=200),
             _amount("resp", SumAggregator(), response=True)]
    cutoff = CutOffTime.at(100)
    row = AggregateReader(DataReaders.simple.records(recs), TS, cutoff,
                          key_fn=KEY).generate_store(feats)
    col = AggregateReader(
        _TableSource(temporal.table_from_records(recs), KEY), TS, cutoff,
        key_fn=KEY).generate_store(feats)
    assert row["pred"].get_raw(0) == 6.0      # nan-ts row + ts=50
    assert row["win"].get_raw(0) == 6.0       # window bypassed for nan
    assert row["resp"].get_raw(0) == 7.0      # nan-ts row + ts=150
    _assert_store_equal(row, col, [f.name for f in feats])
    # the parallel partial path matches too
    par = temporal.aggregate_tables(
        [temporal.table_from_records(recs)], feats, TS, KEY,
        cutoff_ms=100.0, workers=1)
    _assert_store_equal(row, par, [f.name for f in feats])


def test_join_aggregate_directory_retries_transient_fault(rng, tmp_path):
    recs = _events(rng, n=300, n_keys=10)
    write_avro_records(str(tmp_path / "a.avro"), recs)
    right = [{"user": float(u), "seg": float(u)} for u in range(10)]
    feats = [_amount("s", SumAggregator())]
    want = temporal.join_aggregate_directory(
        str(tmp_path), feats, temporal.table_from_records(right), TS, KEY,
        cutoff_ms=600)
    plan = resilience.FaultPlan(seed=5).on("temporal.join", error=OSError,
                                           times=1)
    before = resilience.resilience_stats()
    with resilience.fault_plan(plan):
        got = temporal.join_aggregate_directory(
            str(tmp_path), feats, temporal.table_from_records(right), TS,
            KEY, cutoff_ms=600)
    after = resilience.resilience_stats()
    assert plan.fired("temporal.join") == 1
    assert after["retries"] == before["retries"] + 1
    _assert_store_equal(want, got, ["s"])


def test_join_fault_site_rides_reader_retry(rng):
    """A transient OSError injected at temporal.join retries (the build
    is pure compute, safe to re-run) and the read succeeds."""
    left, right = _join_fixture(rng, n=200, n_keys=10)
    plan = resilience.FaultPlan(seed=1).on("temporal.join", error=OSError,
                                           times=1)
    before = resilience.resilience_stats()
    with resilience.fault_plan(plan):
        out = TemporalJoinReader(
            DataReaders.simple.records(left, key_fn=KEY),
            DataReaders.simple.records(right, key_fn=KEY)).read_records()
    after = resilience.resilience_stats()
    assert len(out) == len(left)
    assert plan.fired("temporal.join") == 1
    assert after["retries"] == before["retries"] + 1


# ---------------------------------------------------------------------------
# workflow / runner / CLI integration
# ---------------------------------------------------------------------------


def _temporal_workflow(rng, cutoff=CutOffTime.at(700)):
    recs = _events(rng, n=1200, n_keys=120)
    reader = AggregateReader(DataReaders.simple.records(recs), TS, cutoff,
                             key_fn=KEY)
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    label = (FeatureBuilder.RealNN("label")
             .extract(temporal.field("flag"), "flag")
             .aggregate(LogicalOrAggregator()).as_response())
    spend = _amount("spend", SumAggregator())
    recent = _amount("recent", MeanAggregator(), window=300)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=11)
    pred = label.transform_with(selector, transmogrify([spend, recent]))
    wf = Workflow().set_result_features(pred).set_reader(reader)
    return wf, reader, pred


def test_workflow_train_uses_aggregating_reader(rng):
    """Workflow.train hands raw-store generation to an aggregating
    reader: one row per KEY (not per event), trainable end to end."""
    wf, reader, pred = _temporal_workflow(rng)
    model = wf.train()
    assert model.train_rows == 120
    store = reader.generate_store(
        [f for f in pred.raw_features()])
    assert store.n_rows == 120


def test_runner_stamps_temporal_and_validates_knobs(rng, tmp_path):
    wf, reader, _pred = _temporal_workflow(rng)
    runner = OpWorkflowRunner(wf, training_reader=reader)
    params = OpParams(custom_params={"plan": False},
                      metrics_location=str(tmp_path / "m.json"))
    res = runner.run(RunType.TRAIN, params)
    assert "temporal" in res.metrics
    assert res.metrics["temporal"]["rowwise_aggregates"] >= 1
    doc = json.load(open(tmp_path / "m.json"))
    assert "temporal" in doc
    # malformed knobs name their key up front
    for key, val in (("joinPartitions", 0), ("joinTableMaxRows", 2.5),
                     ("aggregateColumnar", "yes")):
        bad = OpParams(custom_params={key: val})
        with pytest.raises(ValueError, match=key):
            runner.run(RunType.TRAIN, bad)


def test_runner_knob_installs_run_scoped_defaults(rng):
    wf, reader, _pred = _temporal_workflow(rng)
    runner = OpWorkflowRunner(wf, training_reader=reader)
    params = OpParams(custom_params={"plan": False,
                                     "joinPartitions": 3,
                                     "joinTableMaxRows": 123,
                                     "aggregateColumnar": False})
    seen = {}
    orig = wf.train

    def spy_train():
        seen["partitions"] = temporal.join_partitions()
        seen["cap"] = temporal.join_table_max_rows()
        seen["mode"] = temporal.columnar_mode()
        return orig()

    wf.train = spy_train
    try:
        runner.run(RunType.TRAIN, params)
    finally:
        wf.train = orig
    assert seen == {"partitions": 3, "cap": 123, "mode": False}
    # restored after the run
    assert temporal.join_partitions() == temporal.DEFAULT_JOIN_PARTITIONS
    assert temporal.columnar_mode() == "auto"


def test_cli_gen_emits_and_check_validates_temporal_knobs(tmp_path,
                                                          capsys):
    from transmogrifai_tpu.cli import generate_project, run_check
    csv = tmp_path / "d.csv"
    csv.write_text("id,x,label\n1,0.5,0\n2,1.5,1\n3,2.5,0\n4,3.5,1\n")
    files = generate_project(str(csv), "label", str(tmp_path / "proj"),
                             id_column="id")
    params = json.load(open(files["params.json"]))
    cp = params["customParams"]
    assert cp["aggregateColumnar"] is None
    assert cp["joinPartitions"] is None
    assert cp["joinTableMaxRows"] is None
    # clean params pass check
    assert run_check(files["params.json"]) == 0
    # malformed temporal knobs are TMG001 findings
    cp["joinPartitions"] = 0
    cp["aggregateColumnar"] = "maybe"
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(params))
    assert run_check(str(bad)) == 1
    out = capsys.readouterr().out
    assert "joinPartitions" in out and "aggregateColumnar" in out
    assert "TMG001" in out


# ---------------------------------------------------------------------------
# TMG7xx cutoff leakage rules
# ---------------------------------------------------------------------------


class _NoIOReader(AggregateReader):
    """Aggregating reader whose any I/O fails the test."""

    def read_records(self):
        raise AssertionError("reader I/O happened during static checks")


def _leaky_workflow(rng, cutoff=CutOffTime.no_cutoff()):
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    reader = _NoIOReader(DataReaders.simple.records([]), TS, cutoff,
                         key_fn=KEY)
    label = (FeatureBuilder.RealNN("label")
             .extract(temporal.field("flag"), "flag")
             .aggregate(LogicalOrAggregator()).as_response())
    spend = _amount("spend", SumAggregator())
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None)
    pred = label.transform_with(selector, transmogrify([spend]))
    wf = Workflow().set_result_features(pred)
    return wf, reader


def test_tmg701_no_cutoff_with_response_fires_and_repairs(rng):
    wf, reader = _leaky_workflow(rng)
    findings = lint.check_workflow(wf, reader=reader)
    f = next(x for x in findings if x.rule == "TMG701")
    assert f.severity == "error"
    assert "spend" in f.message and "label" in f.message
    # repaired: a cutoff (or a conditional reader) clears it
    wf2, reader2 = _leaky_workflow(rng, cutoff=CutOffTime.at(500))
    assert not [x for x in lint.check_workflow(wf2, reader=reader2)
                if x.rule == "TMG701"]
    cond = ConditionalReader(DataReaders.simple.records([]), TS,
                             lambda r: bool(r["flag"]), key_fn=KEY)
    assert not [x for x in lint.check_workflow(wf2, reader=cond)
                if x.rule == "TMG701"]


def test_tmg701_runner_blocks_before_reader_io(rng):
    wf, reader = _leaky_workflow(rng)
    runner = OpWorkflowRunner(wf, training_reader=reader)
    with pytest.raises(lint.LintError, match="TMG701"):
        runner.run(RunType.TRAIN, OpParams(custom_params={"plan": False}))
    # suppression flows through the normal machinery — and the reader
    # still does no I/O during the static phase (train then hits the
    # asserting reader, proving the gate ran first)
    params = OpParams(custom_params={"plan": False,
                                     "lintSuppress": ["TMG701"]})
    with pytest.raises(AssertionError, match="reader I/O"):
        runner.run(RunType.TRAIN, params)


def test_tmg702_response_window_is_error(rng):
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    reader = _NoIOReader(DataReaders.simple.records([]), TS,
                         CutOffTime.at(500), key_fn=KEY)
    label = (FeatureBuilder.RealNN("label")
             .extract(temporal.field("flag"), "flag")
             .aggregate(LogicalOrAggregator()).window(100).as_response())
    spend = _amount("spend", SumAggregator())
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None)
    pred = label.transform_with(selector, transmogrify([spend]))
    wf = Workflow().set_result_features(pred)
    findings = lint.check_workflow(wf, reader=reader)
    f = next(x for x in findings if x.rule == "TMG702")
    assert f.severity == "error" and f.feature == "label"
    # clean: window on the PREDICTOR side is the sanctioned shape
    wf2, reader2 = _leaky_workflow(rng, cutoff=CutOffTime.at(500))
    assert not [x for x in lint.check_workflow(wf2, reader=reader2)
                if x.rule == "TMG702"]


def test_tmg703_join_key_from_response_field_warns(rng):
    left = DataReaders.simple.records([], key_fn=temporal.field("flag"))
    right = DataReaders.simple.records([], key_fn=temporal.field("flag"))
    join = TemporalJoinReader(left, right, key_field="flag")
    reader = AggregateReader(join, TS, CutOffTime.at(500),
                             key_fn=temporal.field("flag"))
    label = (FeatureBuilder.RealNN("label")
             .extract(temporal.field("flag"), "flag")
             .aggregate(LogicalOrAggregator()).as_response())
    spend = _amount("spend", SumAggregator())
    findings = temporal.check_temporal(reader, [label, spend])
    f = next(x for x in findings if x.rule == "TMG703")
    assert f.severity == "warning" and "flag" in f.message
    # clean: joining on a non-response key
    left2 = DataReaders.simple.records([], key_fn=KEY)
    right2 = DataReaders.simple.records([], key_fn=KEY)
    join2 = TemporalJoinReader(left2, right2, key_field="user")
    reader2 = AggregateReader(join2, TS, CutOffTime.at(500), key_fn=KEY)
    assert not [x for x in temporal.check_temporal(reader2, [label, spend])
                if x.rule == "TMG703"]


# ---------------------------------------------------------------------------
# TMG311 self-lint fixtures
# ---------------------------------------------------------------------------


def _load_tmoglint():
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "tmoglint", os.path.join(repo, "tools", "tmoglint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tmg311_unstable_sort_flagged_and_allowlisted():
    tm = _load_tmoglint()
    bad = "import numpy as np\norder = np.argsort(ts)\n"
    assert [f.rule for f in tm.lint_source(bad)] == ["TMG311"]
    bad2 = "import numpy as np\ni = np.searchsorted(edges, ts)\n"
    assert [f.rule for f in tm.lint_source(bad2)] == ["TMG311"]
    from_import = "from numpy import argsort\no = argsort(ts)\n"
    assert [f.rule for f in tm.lint_source(from_import)] == ["TMG311"]
    ok = ("import numpy as np\n"
          "o = np.argsort(ts, kind='stable')\n"
          "i = np.searchsorted(edges, ts, side='left')\n")
    assert tm.lint_source(ok) == []
    allowed = ("import numpy as np\n"
               "o = np.argsort(x)  # lint: sort — rank only, ties ok\n")
    assert tm.lint_source(allowed) == []
    jnp_ok = "import jax.numpy as jnp\no = jnp.argsort(x)\n"
    assert tm.lint_source(jnp_ok) == []
    method_ok = "o = x.argsort()\n"          # not attributable to numpy
    assert tm.lint_source(method_ok) == []


def test_tmg7xx_and_tmg311_in_rules_catalog():
    for rule in ("TMG701", "TMG702", "TMG703", "TMG311"):
        assert rule in lint.RULES
    assert lint.RULES["TMG701"][0] == "error"
    assert lint.RULES["TMG702"][0] == "error"
    assert lint.RULES["TMG703"][0] == "warning"


def test_temporal_findings_mirror_to_telemetry(rng):
    from transmogrifai_tpu import telemetry
    wf, reader = _leaky_workflow(rng)
    telemetry.enable()
    try:
        telemetry.reset(keep_listeners=True)
        findings = lint.check_workflow(wf, reader=reader)
        lint.emit_findings(findings)
        assert telemetry.counter("lint.errors").value >= 1
    finally:
        telemetry.disable()
        telemetry.reset(keep_listeners=True)
