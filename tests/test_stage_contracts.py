"""Stage contract spec — the reference's single best testing idea.

For EVERY registered stage reachable through a case below, assert that

1. fit (estimators) produces a model whose columnar ``transform_columns``,
2. per-row ``transform_row`` (the serving path), and
3. serialize → reconstruct → ``transform_columns``

all agree (``OpTransformerSpec.scala:59-84``, ``OpEstimatorSpec.scala:55-120``).
A completeness check asserts no registered stage silently escapes the
contract: each class is either exercised by a case, produced as a fitted
model by one, or explicitly exempted with a reason.
"""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, column_from_values
from transmogrifai_tpu import model_io
from transmogrifai_tpu.columns import VectorColumn
from transmogrifai_tpu.stages.base import Estimator, STAGE_REGISTRY
from transmogrifai_tpu.testkit import RandomData
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (VectorColumnMetadata,
                                               VectorMetadata)

N = 60


def _f(name, ftype, response=False):
    b = getattr(FeatureBuilder, ftype.__name__)(name).from_column()
    return b.as_response() if response else b.as_predictor()


def _label_store(seed=3):
    y = RandomData.binaries().take(N, seed)
    return column_from_values(ft.RealNN, [1.0 if v else 0.0 for v in y])


def _vec_store(seed=5, dim=4):
    X = np.stack(RandomData.vectors(dim).take(N, seed))
    meta = VectorMetadata("features", [
        VectorColumnMetadata(f"x{i}", "Real") for i in range(dim)])
    return VectorColumn(ft.OPVector, X, meta)


# --------------------------------------------------------------------------
# Case table: class name → () → (stage, [input features], store)
# --------------------------------------------------------------------------

def _numeric_case(cls, **kw):
    def build():
        stage = cls(**kw)
        feats = [_f("a", ft.Real), _f("b", ft.Real)]
        store = ColumnStore({
            "a": RandomData.reals().with_prob_empty(0.2).column(ft.Real, N),
            "b": RandomData.reals(2.0).column(ft.Real, N)})
        return stage, feats, store
    return build


def _unary_real(cls, **kw):
    def build():
        stage = cls(**kw)
        feats = [_f("a", ft.Real)]
        store = ColumnStore({
            "a": RandomData.reals().with_prob_empty(0.1).column(ft.Real, N)})
        return stage, feats, store
    return build


def _labelled(cls, xtype=ft.Real, xgen=None, **kw):
    def build():
        stage = cls(**kw)
        feats = [_f("label", ft.RealNN, response=True), _f("x", xtype)]
        xcol = (xgen or RandomData.reals()).column(xtype, N)
        store = ColumnStore({"label": _label_store(), "x": xcol})
        return stage, feats, store
    return build


def _predictor(cls, **kw):
    def build():
        stage = cls(**kw)
        feats = [_f("label", ft.RealNN, response=True),
                 _f("features", ft.OPVector)]
        store = ColumnStore({"label": _label_store(),
                             "features": _vec_store()})
        return stage, feats, store
    return build


def _cases():
    from transmogrifai_tpu.dsl import (AliasTransformer, FillMissingWithMean,
                                       MathBinaryTransformer,
                                       MathScalarTransformer, ScalarNormalizer)
    from transmogrifai_tpu.models.linear import (OpLinearRegression,
                                                 OpLogisticRegression,
                                                 OpNaiveBayes)
    from transmogrifai_tpu.models.svm import (OpLinearSVC,
                                              OpMultilayerPerceptronClassifier)
    from transmogrifai_tpu.models.trees import (OpDecisionTreeClassifier,
                                                OpDecisionTreeRegressor,
                                                OpGBTClassifier,
                                                OpGBTRegressor,
                                                OpRandomForestClassifier,
                                                OpRandomForestRegressor,
                                                OpXGBoostClassifier,
                                                OpXGBoostRegressor)
    from transmogrifai_tpu.ops import (BinaryVectorizer, IntegralVectorizer,
                                       OneHotVectorizer, RealVectorizer,
                                       SetVectorizer, SmartTextVectorizer,
                                       TextTokenizer, VectorsCombiner,
                                       StandardScalerEstimator)
    from transmogrifai_tpu.ops.calibrators import (IsotonicRegressionCalibrator,
                                                   PercentileCalibrator)
    from transmogrifai_tpu.ops.date_list import DateListVectorizer
    from transmogrifai_tpu.ops.dates import DateToUnitCircleVectorizer
    from transmogrifai_tpu.ops.dt_bucketizer import (
        DecisionTreeNumericBucketizer, DecisionTreeNumericMapBucketizer)
    from transmogrifai_tpu.ops.geo import GeolocationVectorizer
    from transmogrifai_tpu.ops.hashing import HashingVectorizerModel
    from transmogrifai_tpu.ops.indexers import (OpIndexToStringNoFilter,
                                                OpStringIndexerNoFilter)
    from transmogrifai_tpu.ops.maps import MapVectorizer
    from transmogrifai_tpu.ops.numeric import NumericBucketizer
    from transmogrifai_tpu.ops.scalers import (DescalerTransformer,
                                               OpScalarStandardScaler,
                                               ScalerTransformer)

    cases = {}

    # vectorizers -----------------------------------------------------------
    cases["RealVectorizer"] = _numeric_case(RealVectorizer)

    def integral_case():
        stage = IntegralVectorizer()
        feats = [_f("a", ft.Integral)]
        store = ColumnStore({"a": RandomData.integrals().with_prob_empty(0.2)
                             .column(ft.Integral, N)})
        return stage, feats, store
    cases["IntegralVectorizer"] = integral_case

    def binary_case():
        stage = BinaryVectorizer()
        feats = [_f("a", ft.Binary)]
        store = ColumnStore({"a": RandomData.binaries().with_prob_empty(0.2)
                             .column(ft.Binary, N)})
        return stage, feats, store
    cases["BinaryVectorizer"] = binary_case

    def onehot_case():
        stage = OneHotVectorizer(top_k=3, min_support=1)
        feats = [_f("a", ft.PickList)]
        store = ColumnStore({"a": RandomData.picklists().with_prob_empty(0.1)
                             .column(ft.PickList, N)})
        return stage, feats, store
    cases["OneHotVectorizer"] = onehot_case

    def set_case():
        stage = SetVectorizer(top_k=3, min_support=1)
        feats = [_f("a", ft.MultiPickList)]
        store = ColumnStore({"a": RandomData.multi_picklists()
                             .column(ft.MultiPickList, N)})
        return stage, feats, store
    cases["SetVectorizer"] = set_case

    def smart_text_case():
        stage = SmartTextVectorizer(max_cardinality=10, num_features=32,
                                    min_support=1)
        feats = [_f("a", ft.Text), _f("b", ft.Text)]
        store = ColumnStore({
            "a": RandomData.unique_texts().with_prob_empty(0.1)
            .column(ft.Text, N),                       # high card → hashed
            "b": RandomData.picklists().column(ft.Text, N)})  # low → pivot
        return stage, feats, store
    cases["SmartTextVectorizer"] = smart_text_case

    def hashing_case():
        stage = HashingVectorizerModel(num_features=16,
                                       input_names=["a"])
        feats = [_f("a", ft.TextList)]
        store = ColumnStore({"a": RandomData.text_lists()
                             .column(ft.TextList, N)})
        return stage, feats, store
    cases["HashingVectorizerModel"] = hashing_case

    def date_case():
        stage = DateToUnitCircleVectorizer()
        feats = [_f("a", ft.Date)]
        store = ColumnStore({"a": RandomData.dates().with_prob_empty(0.1)
                             .column(ft.Date, N)})
        return stage, feats, store
    cases["DateToUnitCircleVectorizer"] = date_case

    def date_list_case():
        stage = DateListVectorizer(reference_date_ms=1_500_000_000_000)
        feats = [_f("a", ft.DateList)]
        store = ColumnStore({"a": RandomData.date_lists()
                             .column(ft.DateList, N)})
        return stage, feats, store
    cases["DateListVectorizer"] = date_list_case

    # collection lifts (OPCollectionTransformer family) --------------------
    from transmogrifai_tpu.ops.collections import (OPListTransformer,
                                                   OPMapTransformer,
                                                   OPSetTransformer)
    from transmogrifai_tpu.ops.text_suite import EmailParser

    def map_lift_case():
        stage = OPMapTransformer(ScalerTransformer(slope=2.0, intercept=1.0))
        feats = [_f("a", ft.RealMap)]
        store = ColumnStore({"a": RandomData.real_maps()
                             .column(ft.RealMap, N)})
        return stage, feats, store
    cases["OPMapTransformer"] = map_lift_case

    def list_lift_case():
        stage = OPListTransformer(EmailParser(part="domain"))
        feats = [_f("a", ft.TextList)]
        store = ColumnStore({"a": RandomData.text_lists()
                             .column(ft.TextList, N)})
        return stage, feats, store
    cases["OPListTransformer"] = list_lift_case

    def set_lift_case():
        stage = OPSetTransformer(EmailParser(part="domain"))
        feats = [_f("a", ft.MultiPickList)]
        store = ColumnStore({"a": RandomData.multi_picklists()
                             .column(ft.MultiPickList, N)})
        return stage, feats, store
    cases["OPSetTransformer"] = set_lift_case

    from transmogrifai_tpu.ops.maps import SmartTextMapVectorizer
    from transmogrifai_tpu.ops.text_suite import LanguageDetector

    def smart_text_map_case():
        stage = SmartTextMapVectorizer(max_cardinality=4, num_features=16,
                                       min_support=1, top_k=5)
        feats = [_f("a", ft.TextMap)]
        store = ColumnStore({"a": RandomData.text_maps()
                             .column(ft.TextMap, N)})
        return stage, feats, store
    cases["SmartTextMapVectorizer"] = smart_text_map_case

    def language_detector_case():
        stage = LanguageDetector()
        feats = [_f("a", ft.Text)]
        store = ColumnStore({"a": RandomData.texts().with_prob_empty(0.2)
                             .column(ft.Text, N)})
        return stage, feats, store
    cases["LanguageDetector"] = language_detector_case

    def geo_case():
        stage = GeolocationVectorizer()
        feats = [_f("a", ft.Geolocation)]
        store = ColumnStore({"a": RandomData.geolocations()
                             .with_prob_empty(0.1)
                             .column(ft.Geolocation, N)})
        return stage, feats, store
    cases["GeolocationVectorizer"] = geo_case

    def map_case():
        stage = MapVectorizer(top_k=3, min_support=1)
        feats = [_f("a", ft.RealMap)]
        store = ColumnStore({"a": RandomData.real_maps()
                             .column(ft.RealMap, N)})
        return stage, feats, store
    cases["MapVectorizer"] = map_case

    def filter_keys_case():
        from transmogrifai_tpu.ops.maps import FilterMapKeys
        stage = FilterMapKeys(block=["k1"])
        feats = [_f("a", ft.RealMap)]
        store = ColumnStore({"a": RandomData.real_maps()
                             .column(ft.RealMap, N)})
        return stage, feats, store
    cases["FilterMapKeys"] = filter_keys_case

    def extract_key_case():
        from transmogrifai_tpu.ops.maps import ExtractMapKey
        stage = ExtractMapKey(key="k1")
        feats = [_f("a", ft.RealMap)]
        store = ColumnStore({"a": RandomData.real_maps()
                             .column(ft.RealMap, N)})
        return stage, feats, store
    cases["ExtractMapKey"] = extract_key_case

    def bucketizer_case():
        stage = NumericBucketizer(splits=[-1.0, 0.0, 1.0],
                                  track_invalid=True)
        feats = [_f("a", ft.Real)]
        store = ColumnStore({"a": RandomData.reals().with_prob_empty(0.1)
                             .column(ft.Real, N)})
        return stage, feats, store
    cases["NumericBucketizer"] = bucketizer_case

    cases["DecisionTreeNumericBucketizer"] = _labelled(
        DecisionTreeNumericBucketizer, min_info_gain=1e-6)
    cases["DecisionTreeNumericMapBucketizer"] = _labelled(
        DecisionTreeNumericMapBucketizer, xtype=ft.RealMap,
        xgen=RandomData.real_maps(), min_info_gain=1e-6)

    # scalers / calibrators / DSL ------------------------------------------
    cases["OpScalarStandardScaler"] = _unary_real(OpScalarStandardScaler)
    cases["ScalerTransformer"] = _unary_real(
        ScalerTransformer, scaling_type="logarithmic")

    def descaler_case():
        stage = DescalerTransformer()
        scaled = ScalerTransformer(scaling_type="linear", slope=2.0,
                                   intercept=1.0)
        f = _f("a", ft.Real)
        scaled.set_input(f)
        # input 0: value to descale; input 1: feature with a
        # ScalerTransformer ancestor whose scaling gets inverted
        feats = [scaled.get_output(), scaled.get_output()]
        base = ColumnStore({"a": RandomData.reals().column(ft.Real, N)})
        store = scaled.transform(base)
        return stage, feats, store
    cases["DescalerTransformer"] = descaler_case

    cases["FillMissingWithMean"] = _unary_real(FillMissingWithMean)
    cases["ScalarNormalizer"] = _unary_real(ScalarNormalizer)
    cases["PercentileCalibrator"] = _unary_real(PercentileCalibrator,
                                                num_buckets=10)
    cases["IsotonicRegressionCalibrator"] = _labelled(
        IsotonicRegressionCalibrator)
    cases["MathBinaryTransformer"] = _numeric_case(
        MathBinaryTransformer, op="multiply")
    cases["MathScalarTransformer"] = _unary_real(
        MathScalarTransformer, op="add", scalar=3.0)
    cases["AliasTransformer"] = _unary_real(AliasTransformer, name="renamed")

    def tokenizer_case():
        stage = TextTokenizer()
        feats = [_f("a", ft.Text)]
        store = ColumnStore({"a": RandomData.texts().with_prob_empty(0.1)
                             .column(ft.Text, N)})
        return stage, feats, store
    cases["TextTokenizer"] = tokenizer_case

    def combine_case():
        stage = VectorsCombiner()
        feats = [_f("u", ft.OPVector), _f("v", ft.OPVector)]
        store = ColumnStore({"u": _vec_store(seed=1, dim=2),
                             "v": _vec_store(seed=2, dim=3)})
        return stage, feats, store
    cases["VectorsCombiner"] = combine_case

    def std_scaler_case():
        stage = StandardScalerEstimator()
        feats = [_f("u", ft.OPVector)]
        store = ColumnStore({"u": _vec_store(seed=1, dim=3)})
        return stage, feats, store
    cases["StandardScalerEstimator"] = std_scaler_case

    # text suite ------------------------------------------------------------
    from transmogrifai_tpu.ops.text_suite import (EmailParser,
                                                  MimeTypeDetector,
                                                  NGramSimilarity,
                                                  OpCountVectorizer,
                                                  PhoneNumberParser,
                                                  UrlParser)

    def email_case():
        stage = EmailParser(part="domain")
        feats = [_f("a", ft.Email)]
        vals = ["u@d.com", "bad", None, "x@y.org"] * (N // 4)
        store = ColumnStore({"a": column_from_values(ft.Email, vals)})
        return stage, feats, store
    cases["EmailParser"] = email_case

    def url_case():
        stage = UrlParser(part="protocol")
        feats = [_f("a", ft.URL)]
        vals = ["https://a.com", "junk", None, "ftp://f.org"] * (N // 4)
        store = ColumnStore({"a": column_from_values(ft.URL, vals)})
        return stage, feats, store
    cases["UrlParser"] = url_case

    def phone_case():
        stage = PhoneNumberParser(output="valid")
        feats = [_f("a", ft.Phone)]
        vals = ["+16505551234", "123", None, "6505551234"] * (N // 4)
        store = ColumnStore({"a": column_from_values(ft.Phone, vals)})
        return stage, feats, store
    cases["PhoneNumberParser"] = phone_case

    def mime_case():
        import base64 as b64
        stage = MimeTypeDetector()
        feats = [_f("a", ft.Base64)]
        vals = [b64.b64encode(b"%PDF-1.4").decode(),
                b64.b64encode(b"plain text").decode(), None,
                b64.b64encode(b"\x89PNG1234").decode()] * (N // 4)
        store = ColumnStore({"a": column_from_values(ft.Base64, vals)})
        return stage, feats, store
    cases["MimeTypeDetector"] = mime_case

    def ngram_case():
        stage = NGramSimilarity(n=3)
        feats = [_f("a", ft.Text), _f("b", ft.Text)]
        store = ColumnStore({
            "a": RandomData.texts().with_prob_empty(0.1).column(ft.Text, N),
            "b": RandomData.texts().column(ft.Text, N)})
        return stage, feats, store
    cases["NGramSimilarity"] = ngram_case

    def countvec_case():
        stage = OpCountVectorizer(vocab_size=8, min_df=1)
        feats = [_f("a", ft.TextList)]
        store = ColumnStore({"a": RandomData.text_lists()
                             .column(ft.TextList, N)})
        return stage, feats, store
    cases["OpCountVectorizer"] = countvec_case

    from transmogrifai_tpu.ops.text_suite import NameEntityRecognizer
    from transmogrifai_tpu.ops.topics import OpLDA, OpWord2Vec

    def ner_case():
        stage = NameEntityRecognizer()
        feats = [_f("a", ft.Text)]
        vals = ["Alice Smith went to Paris", "the dog barked", None,
                "Bob Jones"] * (N // 4)
        store = ColumnStore({"a": column_from_values(ft.Text, vals)})
        return stage, feats, store
    cases["NameEntityRecognizer"] = ner_case

    def lda_case():
        stage = OpLDA(n_topics=2, n_iter=15)
        feats = [_f("a", ft.TextList)]
        store = ColumnStore({"a": RandomData.text_lists(max_len=6)
                             .column(ft.TextList, N)})
        return stage, feats, store
    cases["OpLDA"] = lda_case

    def w2v_case():
        stage = OpWord2Vec(dim=8, epochs=10, min_count=1)
        feats = [_f("a", ft.TextList)]
        store = ColumnStore({"a": RandomData.text_lists(max_len=6)
                             .column(ft.TextList, N)})
        return stage, feats, store
    cases["OpWord2Vec"] = w2v_case

    from transmogrifai_tpu.ops.list_ops import (JaccardSimilarity,
                                                OpHashingTF, OpIDF,
                                                OpNGram, OpStopWordsRemover)

    def _textlist_case(mk):
        def case():
            stage = mk()
            feats = [_f("a", ft.TextList)]
            store = ColumnStore({"a": RandomData.text_lists(max_len=6)
                                 .column(ft.TextList, N)})
            return stage, feats, store
        return case
    cases["OpHashingTF"] = _textlist_case(
        lambda: OpHashingTF(num_terms=16))
    cases["OpNGram"] = _textlist_case(lambda: OpNGram(n=2))
    cases["OpStopWordsRemover"] = _textlist_case(OpStopWordsRemover)

    def idf_case():
        stage = OpIDF(min_doc_freq=1)
        feats = [_f("a", ft.OPVector)]
        store = ColumnStore({"a": RandomData.vectors(dim=6)
                             .column(ft.OPVector, N)})
        return stage, feats, store
    cases["OpIDF"] = idf_case

    def jaccard_case():
        stage = JaccardSimilarity()
        feats = [_f("a", ft.MultiPickList), _f("b", ft.MultiPickList)]
        store = ColumnStore({
            "a": RandomData.multi_picklists().column(ft.MultiPickList, N),
            "b": RandomData.multi_picklists().column(ft.MultiPickList, N)})
        return stage, feats, store
    cases["JaccardSimilarity"] = jaccard_case

    from transmogrifai_tpu.dsl import MathUnaryTransformer
    from transmogrifai_tpu.ops.text_suite import (OpPOSTagger,
                                                  OpSentenceSplitter)

    def unary_math_case():
        stage = MathUnaryTransformer(op="abs")
        feats = [_f("a", ft.Real)]
        store = ColumnStore({"a": RandomData.reals().with_prob_empty(0.2)
                             .column(ft.Real, N)})
        return stage, feats, store
    cases["MathUnaryTransformer"] = unary_math_case

    def _text_case(mk):
        def case():
            stage = mk()
            feats = [_f("a", ft.Text)]
            vals = ["Dr. Lee met Anna Cole in Paris. They left early.",
                    "the quick brown fox", None, "Acme Corp shipped it."
                    ] * (N // 4)
            store = ColumnStore({"a": column_from_values(ft.Text, vals)})
            return stage, feats, store
        return case
    cases["OpSentenceSplitter"] = _text_case(OpSentenceSplitter)
    cases["OpPOSTagger"] = _text_case(OpPOSTagger)

    # indexers --------------------------------------------------------------
    def indexer_case():
        stage = OpStringIndexerNoFilter()
        feats = [_f("a", ft.Text, response=True)]
        store = ColumnStore({"a": RandomData.picklists()
                             .with_prob_empty(0.1).column(ft.Text, N)})
        return stage, feats, store
    cases["OpStringIndexerNoFilter"] = indexer_case

    def idx2str_case():
        stage = OpIndexToStringNoFilter(labels=["x", "y", "z"])
        feats = [_f("a", ft.RealNN)]
        store = ColumnStore({"a": column_from_values(
            ft.RealNN, [float(i % 4) for i in range(N)])})
        return stage, feats, store
    cases["OpIndexToStringNoFilter"] = idx2str_case

    # model wrappers --------------------------------------------------------
    cases["OpLogisticRegression"] = _predictor(OpLogisticRegression)
    cases["OpLinearRegression"] = _predictor(OpLinearRegression)
    cases["OpNaiveBayes"] = _predictor(OpNaiveBayes)
    cases["OpLinearSVC"] = _predictor(OpLinearSVC, max_iter=8)
    cases["OpMultilayerPerceptronClassifier"] = _predictor(
        OpMultilayerPerceptronClassifier, max_iter=8)
    cases["OpDecisionTreeClassifier"] = _predictor(
        OpDecisionTreeClassifier, max_depth=3)
    cases["OpDecisionTreeRegressor"] = _predictor(
        OpDecisionTreeRegressor, max_depth=3)
    cases["OpRandomForestClassifier"] = _predictor(
        OpRandomForestClassifier, num_trees=4, max_depth=3)
    cases["OpRandomForestRegressor"] = _predictor(
        OpRandomForestRegressor, num_trees=4, max_depth=3)
    cases["OpGBTClassifier"] = _predictor(OpGBTClassifier, max_iter=4,
                                          max_depth=3)
    cases["OpGBTRegressor"] = _predictor(OpGBTRegressor, max_iter=4,
                                         max_depth=3)
    cases["OpXGBoostClassifier"] = _predictor(OpXGBoostClassifier,
                                              num_round=4, max_depth=3)
    cases["OpXGBoostRegressor"] = _predictor(OpXGBoostRegressor,
                                             num_round=4, max_depth=3)
    from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression
    cases["OpGeneralizedLinearRegression"] = _predictor(
        OpGeneralizedLinearRegression)
    return cases


CASES = _cases()

#: registered classes NOT exercised directly, with the reason
EXEMPT = {
    "FeatureGeneratorStage": "origin stage; exercised by reader tests",
    "ModelSelector": "exercised end-to-end in test_selector/test_workflow_cv",
    "SelectedModel": "fitted product of ModelSelector (test_selector)",
    "RecordInsightsLOCO": "needs a live model ref; tested in test_insights",
    "PredictionDeIndexer": "needs labelled metadata; test_vectorizers",
    "PredictionDeIndexerModel": "fitted product of PredictionDeIndexer",
    "MapTransformer": "lambda-carrying; covered in test_workflow_io",
    "ValueOpTransformer": "lambda-carrying; covered in test_dsl_rich "
                          "(value surface + save/load round-trip)",
    "SanityChecker": "label-aware column selection; test_sanity_checker",
    "SanityCheckerModel": "fitted product of SanityChecker",
    "RecordInsightsCorr": "needs a PredictionColumn input; test_insights",
    "RecordInsightsCorrModel": "fitted product of RecordInsightsCorr",
}

#: fitted-model classes produced by a covered estimator (contract reaches
#: them through fit)
_PRODUCED = {
    "NumericVectorizerModel", "OneHotModel", "SmartTextVectorizerModel",
    "MapVectorizerModel", "NumericBucketizerModel", "_MapBucketizerModel",
    "GeolocationVectorizerModel", "ScalarStandardScalerModel",
    "PercentileCalibratorModel", "IsotonicRegressionModel",
    "FillMissingWithMeanModel", "ScalarNormalizerModel",
    "StandardScalerModel", "LogisticRegressionModel", "LinearRegressionModel",
    "NaiveBayesModel", "LinearSVCModel", "MLPModel", "TreeEnsembleModel",
    "OpStringIndexerModel", "CountVectorizerModel", "GLMRegressionModel",
    "LDAModel", "Word2VecModel", "OpIDFModel",
}


def test_registry_is_fully_covered():
    missing = [name for name in STAGE_REGISTRY
               if name not in CASES and name not in EXEMPT
               and name not in _PRODUCED]
    assert not missing, (
        f"Stages without a contract case or exemption: {missing} — add a "
        "case to tests/test_stage_contracts.py")


def _roundtrip(stage):
    """Serialize a stage exactly as model_io does and reconstruct it."""
    arrays = {}
    rec = model_io._stage_record(stage, arrays)
    cls = STAGE_REGISTRY[rec["className"]]
    params = model_io._decode_param(rec["params"], arrays)
    params.pop("uid", None)
    s2 = cls(uid=rec["uid"], **params)
    if rec.get("isModel"):
        state = model_io._decode_param(rec.get("modelState", {}), arrays)
        if hasattr(s2, "apply_model_state"):
            s2.apply_model_state(state)
        else:
            for k, v in state.items():
                setattr(s2, k, v)
    s2.input_features = stage.input_features
    s2._output_feature = stage._output_feature
    return s2


def _assert_values_equal(a, b, context):
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float64), np.asarray(b, dtype=np.float64),
            rtol=1e-6, atol=1e-9, err_msg=context)
    elif isinstance(a, float) and isinstance(b, float):
        assert a == pytest.approx(b, rel=1e-6, abs=1e-9), context
    elif isinstance(a, dict) and isinstance(b, dict):
        assert set(a) == set(b), context
        for k in a:
            _assert_values_equal(a[k], b[k], f"{context}[{k}]")
    else:
        assert a == b, context


@pytest.mark.parametrize("name", sorted(CASES))
def test_stage_contract(name):
    stage, feats, store = CASES[name]()
    stage.set_input(*feats)
    model = stage.fit(store) if isinstance(stage, Estimator) else stage

    out = model.transform(store)
    col = out[model.output_name]

    # columnar vs row path on a sample of rows
    for i in (0, 1, N // 2, N - 1):
        row = {f.name: store[f.name].get_raw(i)
               for f in model.input_features}
        got = model.transform_row(row)
        _assert_values_equal(got, col.get_raw(i),
                             f"{name}: row {i} transform_row mismatch")

    # save → load → transform equality
    loaded = _roundtrip(model)
    col2 = loaded.transform(store)[model.output_name]
    for i in (0, N // 2, N - 1):
        _assert_values_equal(col2.get_raw(i), col.get_raw(i),
                             f"{name}: row {i} save/load mismatch")
