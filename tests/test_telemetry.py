"""Run telemetry tests (telemetry.py) — the OpSparkListener analog.

Covers the tentpole contract: span nesting + thread safety, Chrome
trace-event JSON validity, counter/gauge/histogram math, Prometheus
text exposition, RunListener event ordering over a tiny fit+score run,
and the disabled-path guard (zero spans, zero listeners, no extra
jax.monitoring registrations when telemetry is off). Satellites: the
runner's atomic metrics sink and the CLI --trace-out/--metrics-format
surface.
"""
import json
import os
import threading

import numpy as np
import pytest

from transmogrifai_tpu import (FeatureBuilder, Workflow, telemetry)
from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                      LogisticRegressionFamily)
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.runner import (OpApp, OpParams, OpWorkflowRunner,
                                      RunType)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _records(rng, n=200):
    y = rng.integers(0, 2, n).astype(float)
    x = rng.normal(size=n) + y
    return [{"label": float(y[i]), "x": float(x[i])} for i in range(n)]


def _flow():
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    vec = transmogrify([fx])
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])],
        splitter=None, seed=5)
    pred = label.transform_with(selector, vec)
    return Workflow().set_result_features(pred), pred


# -- span tracer -----------------------------------------------------------

def test_span_nesting_and_chrome_trace_validity(tmp_path):
    telemetry.enable()
    with telemetry.span("outer", kind="test"):
        assert telemetry.current_span_stack() == ("outer",)
        with telemetry.span("inner", depth=2):
            assert telemetry.current_span_stack() == ("outer", "inner")
    assert telemetry.current_span_stack() == ()

    events = [e for e in telemetry.trace_events() if e["ph"] == "X"]
    by_name = {e["name"]: e for e in events}
    assert set(by_name) == {"outer", "inner"}
    inner, outer = by_name["inner"], by_name["outer"]
    # the child span nests inside the parent on the same track
    assert inner["tid"] == outer["tid"]
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert inner["args"] == {"depth": 2}

    p = tmp_path / "trace.json"
    assert telemetry.write_trace(str(p))
    doc = json.load(open(p))            # valid JSON, Perfetto-loadable keys
    assert doc["displayTimeUnit"] == "ms"
    for e in doc["traceEvents"]:
        assert {"name", "ph", "pid", "tid"} <= set(e)
        if e["ph"] == "X":
            assert e["dur"] >= 0 and e["ts"] >= 0


def test_span_thread_safety_and_per_thread_tracks():
    telemetry.enable()
    n_threads, n_spans = 4, 50
    barrier = threading.Barrier(n_threads)

    def work(k):
        barrier.wait()
        for i in range(n_spans):
            with telemetry.span("worker", thread=k, i=i):
                pass

    threads = [threading.Thread(target=work, args=(k,), name=f"w{k}")
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = [e for e in telemetry.trace_events()
             if e["ph"] == "X" and e["name"] == "worker"]
    assert len(spans) == n_threads * n_spans      # none lost to races
    assert len({e["tid"] for e in spans}) == n_threads
    # each worker thread announced its name on its own track
    metas = [e for e in telemetry.trace_events() if e["ph"] == "M"]
    names = {e["args"]["name"] for e in metas}
    assert {f"w{k}" for k in range(n_threads)} <= names


def test_disabled_path_records_nothing():
    """The guard the tentpole demands: telemetry off ⇒ shared no-op
    singletons, zero spans, zero listeners, no metrics registered."""
    assert not telemetry.enabled()
    s = telemetry.span("x", big=list(range(3)))
    assert s is telemetry.span("y")               # shared null span
    with s:
        pass
    c = telemetry.counter("scoring.cache_hits")
    assert c is telemetry.gauge("g") is telemetry.histogram("h")
    c.inc()
    telemetry.gauge("g").set(5)
    telemetry.emit("run_start", run_type="Train")
    assert telemetry.trace_events() == []
    assert telemetry.metrics_json() == {}
    assert telemetry.listeners() == []


# -- metrics registry ------------------------------------------------------

def test_counter_gauge_histogram_math():
    telemetry.enable()
    c = telemetry.counter("scoring.cache_hits")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert telemetry.counter("scoring.cache_hits") is c   # get-or-create

    g = telemetry.gauge("stream.queue_depth")
    g.set(3)
    g.inc()
    g.dec(2)
    assert g.value == 2

    h = telemetry.histogram("lat", buckets=(0.001, 0.01, 1.0))
    for v in (0.0004, 0.005, 0.5, 30.0):
        h.observe(v)
    assert h.count == 4
    assert h.sum == pytest.approx(30.5054)
    assert h.bucket_counts() == {0.001: 1, 0.01: 2, 1.0: 3}   # cumulative

    doc = telemetry.metrics_json()
    assert doc["scoring.cache_hits"] == 5
    assert doc["stream.queue_depth"] == 2
    assert doc["lat"]["count"] == 4
    assert doc["lat"]["buckets"]["0.01"] == 2

    with pytest.raises(TypeError):
        telemetry.gauge("scoring.cache_hits")     # kind mismatch caught


def test_prometheus_exposition_format():
    telemetry.enable()
    telemetry.counter("scoring.cache_hits").inc(3)
    telemetry.gauge("stream.overlap_efficiency").set(0.75)
    h = telemetry.histogram("scoring.batch_seconds", buckets=(0.01, 1.0))
    h.observe(0.005)
    h.observe(2.0)
    text = telemetry.render_prometheus(extra={"run_appSeconds": 1.5})
    lines = text.splitlines()
    assert "# TYPE scoring_cache_hits counter" in lines
    assert "scoring_cache_hits 3" in lines
    assert "# TYPE stream_overlap_efficiency gauge" in lines
    assert "stream_overlap_efficiency 0.75" in lines
    assert "# TYPE scoring_batch_seconds histogram" in lines
    assert 'scoring_batch_seconds_bucket{le="0.01"} 1' in lines
    assert 'scoring_batch_seconds_bucket{le="+Inf"} 2' in lines
    assert "scoring_batch_seconds_count 2" in lines
    assert any(l.startswith("scoring_batch_seconds_sum") for l in lines)
    assert "run_appSeconds 1.5" in lines
    assert text.endswith("\n")


# -- listeners over a real run ---------------------------------------------

class _Recorder(telemetry.RunListener):
    def __init__(self):
        self.events = []

    def on_run_start(self, run_type, **_):
        self.events.append(("run_start", run_type))

    def on_run_end(self, run_type, seconds=0.0, **_):
        self.events.append(("run_end", run_type))

    def on_layer_start(self, index, n_stages, **_):
        self.events.append(("layer_start", index))

    def on_stage_fit(self, uid, stage_name, fit_s, **_):
        self.events.append(("stage_fit", uid))

    def on_score_batch(self, n_rows, bucket, seconds, **_):
        self.events.append(("score_batch", n_rows))


def test_listener_event_ordering_over_fit_and_score(rng, tmp_path):
    telemetry.enable()
    rec = telemetry.add_listener(_Recorder())
    records = _records(rng)
    wf, pred = _flow()

    class _Reader:
        def read_records(self):
            return list(records)

    runner = OpWorkflowRunner(wf, training_reader=_Reader(),
                              scoring_reader=_Reader())
    params = OpParams(model_location=str(tmp_path / "m"))
    result = runner.run(RunType.TRAIN, params)
    names = [e[0] for e in rec.events]
    assert names[0] == "run_start" and rec.events[0][1] == "Train"
    assert names[-1] == "run_end"
    layer_idx = [e[1] for e in rec.events if e[0] == "layer_start"]
    assert layer_idx == sorted(layer_idx) and layer_idx[0] == 0
    # stage fits happen after their layer opened, before run_end
    assert names.index("layer_start") < names.index("stage_fit") \
        < names.index("run_end")
    assert names.count("stage_fit") >= 2        # vectorizer + selector

    # engine-scored batches land as score_batch events after the train run
    from transmogrifai_tpu.workflow import WorkflowModel
    model = WorkflowModel.load(str(tmp_path / "m"))
    eng = model.scoring_engine(gate_bandwidth=False)
    eng.score_store(records)
    assert ("score_batch", len(records)) in rec.events
    assert names.index("run_end") < rec.events.index(
        ("score_batch", len(records)))

    # the runner's own collecting listener rode into the metrics doc
    tel = result.metrics["telemetry"]
    assert tel["runType"] == "Train"
    assert tel["layers"] >= 2 and tel["fittedStages"] >= 2
    assert tel["appSeconds"] > 0


def test_listener_exceptions_do_not_break_the_run():
    telemetry.enable()

    class _Bomb(telemetry.RunListener):
        def on_layer_start(self, index, n_stages, **_):
            raise RuntimeError("boom")

    rec = _Recorder()
    telemetry.add_listener(_Bomb())
    telemetry.add_listener(rec)
    telemetry.emit("layer_start", index=0, n_stages=1)   # must not raise
    assert rec.events == [("layer_start", 0)]


# -- acceptance: fit + engine-scored run -----------------------------------

def test_enabled_run_traces_layers_stages_and_buckets(rng, tmp_path):
    """Acceptance: a fit + engine-scored run with telemetry on writes a
    valid Chrome trace with spans for every DAG layer, every fitted
    stage, and every scoring bucket execution, plus nonzero compile and
    cache-hit counters in the metrics doc."""
    telemetry.enable()
    records = _records(rng)
    wf, pred = _flow()
    model = wf.set_input_records(records).train()
    eng = model.scoring_engine(gate_bandwidth=False)
    eng.score_store(records)
    eng.score_store(list(records))      # same shapes → program cache hit

    spans = [e for e in telemetry.trace_events() if e["ph"] == "X"]
    layers = [e for e in spans if e["name"] == "fit:layer"]
    assert len(layers) == len(model.dag)
    assert {e["args"]["layer"] for e in layers} == set(range(len(model.dag)))
    stage_uids = {e["args"]["uid"] for e in spans
                  if e["name"] == "fit:stage"}
    assert stage_uids == set(model.fitted_stages)
    buckets = [e for e in spans if e["name"] == "score:bucket"]
    assert len(buckets) == 2
    assert buckets[0]["args"]["compiled"] is True
    assert buckets[1]["args"]["compiled"] is False

    metrics = telemetry.metrics_json()
    assert metrics["scoring.compile_count"] >= 1
    assert metrics["scoring.cache_hits"] >= 1
    assert metrics["device.bytes_h2d"] > 0

    p = tmp_path / "trace.json"
    telemetry.write_trace(str(p))
    assert len(json.load(open(p))["traceEvents"]) == len(
        telemetry.trace_events())


def test_disabled_run_registers_nothing(rng):
    """Acceptance flip side: the same run with telemetry off records zero
    spans, keeps the listener registry empty, and registers no extra
    jax.monitoring listeners (only the single shared compile-clock one,
    installed once per process whether telemetry is on or off)."""
    assert not telemetry.enabled()
    records = _records(rng, n=120)
    wf, pred = _flow()
    model = wf.set_input_records(records).train()
    eng = model.scoring_engine(gate_bandwidth=False)
    eng.score_store(records)
    assert telemetry.trace_events() == []
    assert telemetry.metrics_json() == {}
    assert telemetry.listeners() == []
    assert telemetry._COMPILE_LISTENER_REGISTRATIONS[0] <= 1
    # the compile clock itself still works when telemetry is off (bench
    # and the stage compile/execute split depend on it)
    assert telemetry.compile_clock_s() >= 0.0
    # enabling+disabling telemetry must not add monitoring listeners
    telemetry.enable()
    telemetry.disable()
    assert telemetry._COMPILE_LISTENER_REGISTRATIONS[0] <= 1


def test_workflow_reexports_share_state():
    """Satellite: workflow keeps the public compile-clock names as thin
    re-exports over telemetry's single implementation."""
    from transmogrifai_tpu import workflow as wf
    assert wf._COMPILE_CLOCK is telemetry._COMPILE_CLOCK
    assert wf.compile_clock_s is telemetry.compile_clock_s
    assert wf._ensure_compile_listener is telemetry._ensure_compile_listener


# -- runner satellites -----------------------------------------------------

def test_write_metrics_atomic(tmp_path, monkeypatch):
    p = tmp_path / "metrics.json"
    OpWorkflowRunner._write_metrics(str(p), {"a": 1})
    assert json.load(open(p)) == {"a": 1}
    assert not os.path.exists(str(p) + ".tmp")

    # a crash mid-write must leave the previous good file intact
    def boom(*a, **kw):
        raise RuntimeError("disk full")
    monkeypatch.setattr(json, "dump", boom)
    with pytest.raises(RuntimeError):
        OpWorkflowRunner._write_metrics(str(p), {"a": 2})
    monkeypatch.undo()
    assert json.load(open(p)) == {"a": 1}


def test_write_metrics_prometheus_format(tmp_path):
    telemetry.enable()
    telemetry.counter("scoring.cache_hits").inc(7)
    p = tmp_path / "metrics.prom"
    OpWorkflowRunner._write_metrics(
        str(p), {"appSeconds": 1.25, "rowsScored": 10, "tag": "x"},
        fmt="prometheus")
    text = open(p).read()
    assert "# TYPE scoring_cache_hits counter" in text
    assert "scoring_cache_hits 7" in text
    assert "run_appSeconds 1.25" in text
    assert "run_rowsScored 10" in text
    assert "tag" not in text            # non-numeric doc fields dropped


def test_cli_trace_out_and_metrics_format(rng, tmp_path):
    records = _records(rng)
    wf, pred = _flow()

    class _Reader:
        def read_records(self):
            return list(records)

    class _App(OpApp):
        def runner(self, params):
            return OpWorkflowRunner(wf, training_reader=_Reader(),
                                    scoring_reader=_Reader())

    trace = tmp_path / "trace.json"
    metrics = tmp_path / "metrics.prom"
    out = _App().main([
        "--run-type", "Train", "--quiet",
        "--model-location", str(tmp_path / "m"),
        "--metrics-location", str(metrics),
        "--trace-out", str(trace),
        "--metrics-format", "prometheus"])
    assert out.run_type == "Train"
    doc = json.load(open(trace))
    names = {e["name"] for e in doc["traceEvents"]}
    assert "run:Train" in names and "fit:stage" in names
    text = open(metrics).read()
    assert "# TYPE" in text and "run_appSeconds" in text
    # the collecting listener's AppMetrics summary rode in the result
    assert out.metrics["telemetry"]["fittedStages"] >= 2


def test_runner_telemetry_is_run_scoped(rng, tmp_path):
    """OpParams-driven telemetry must not stay sticky: later runs of a
    long-lived process that never asked for it record nothing."""
    records = _records(rng, n=120)
    wf, pred = _flow()

    class _Reader:
        def read_records(self):
            return list(records)

    runner = OpWorkflowRunner(wf, training_reader=_Reader(),
                              scoring_reader=_Reader())
    trace = tmp_path / "trace.json"
    params = OpParams(model_location=str(tmp_path / "m"),
                      trace_location=str(trace))
    assert not telemetry.enabled()
    out = runner.run(RunType.TRAIN, params)
    assert trace.exists() and "telemetry" in out.metrics
    assert not telemetry.enabled()        # switched back off after the run
    # a later run WITHOUT telemetry params records nothing new
    n_before = len(telemetry.trace_events())
    runner.run(RunType.SCORE, OpParams(model_location=str(tmp_path / "m")))
    assert len(telemetry.trace_events()) == n_before
    # and a later telemetry-enabled run gets a CLEAN per-run trace
    trace2 = tmp_path / "trace2.json"
    runner.run(RunType.SCORE, OpParams(model_location=str(tmp_path / "m"),
                                       trace_location=str(trace2)))
    names2 = {e["name"] for e in json.load(open(trace2))["traceEvents"]}
    assert "run:Score" in names2 and "fit:stage" not in names2


def test_crashed_run_still_writes_partial_trace(tmp_path):
    """The failing run is the one you most want a trace of: spans up to
    the failure are flushed, and run-scoped telemetry is still torn
    down."""
    wf, pred = _flow()
    runner = OpWorkflowRunner(wf)
    trace = tmp_path / "trace.json"
    params = OpParams(trace_location=str(trace))   # no modelLocation
    with pytest.raises(ValueError, match="requires modelLocation"):
        runner.run(RunType.SCORE, params)
    doc = json.load(open(trace))
    assert any(e["name"] == "run:Score" for e in doc["traceEvents"])
    assert not telemetry.enabled()


def test_opparams_telemetry_roundtrip(tmp_path):
    p = tmp_path / "params.json"
    p.write_text(json.dumps({
        "traceLocation": "/tmp/trace.json",
        "metricsFormat": "prometheus",
        "customParams": {"telemetry": True}}))
    params = OpParams.from_file(str(p))
    assert params.trace_location == "/tmp/trace.json"
    assert params.metrics_format == "prometheus"
    assert params.telemetry_requested()
    doc = params.to_json()
    assert doc["traceLocation"] == "/tmp/trace.json"
    assert doc["metricsFormat"] == "prometheus"
    assert not OpParams().telemetry_requested()
