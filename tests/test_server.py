"""Multi-tenant model server tests (server.py).

The serving correctness contract: dynamic micro-batching is
bit-identical to solo scoring (co-batching never perturbs a tenant's
rows), admission control rejects loudly, the LRU evicts and reloads
transparently, faults quarantine requests without killing the server,
and graceful shutdown drains every accepted request."""
import json
import threading
import time

import numpy as np
import pytest

from transmogrifai_tpu import (FeatureBuilder, Workflow, resilience,
                               serving, telemetry)
from transmogrifai_tpu import server as server_mod
from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                      LogisticRegressionFamily)
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.server import (ModelNotFound, ModelServer,
                                      ServerBusy, ServerClosed,
                                      serve_http, server_stats)

BUCKET_CAP = 64


def _train(seed, n=200):
    rng = np.random.default_rng(seed)
    y = np.asarray([i % 2 for i in range(n)], float)
    rng.shuffle(y)
    records = [{"label": float(y[i]),
                "x1": float(rng.normal() + y[i]),
                "x2": float(rng.normal())} for i in range(n)]
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
    f2 = FeatureBuilder.Real("x2").from_column().as_predictor()
    vec = transmogrify([f1, f2])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=seed)
    pred = label.transform_with(sel, vec)
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())
    return model, records, pred


@pytest.fixture(scope="module")
def tenants(tmp_path_factory):
    """Two trained models saved + AOT-exported — the mixed-model
    serving roster."""
    out = {}
    for name, seed in (("A", 11), ("B", 12)):
        model, records, pred = _train(seed)
        mdir = str(tmp_path_factory.mktemp(f"model{name}"))
        edir = str(tmp_path_factory.mktemp(f"export{name}"))
        model.save(mdir, overwrite=True)
        serving.export_scoring_fn(model, edir, records[:8],
                                  bucket_cap=BUCKET_CAP)
        out[name] = {"model": model, "records": records, "pred": pred,
                     "model_dir": mdir, "export_dir": edir}
    yield out
    # chaos/breaker state must not leak across modules
    for t in out.values():
        t["model"]._engine_breaker().reset()


def _server(tenants, **kw):
    kw.setdefault("bucket_cap", BUCKET_CAP)
    kw.setdefault("batch_deadline_s", 0.02)
    srv = ModelServer(**kw)
    for name, t in tenants.items():
        srv.register(name, model_dir=t["model_dir"],
                     bank_dir=t["export_dir"])
    return srv


def _assert_bitwise(a, b):
    for fld in ("prediction", "raw_prediction", "probability"):
        assert np.array_equal(getattr(a, fld), getattr(b, fld)), fld


def _reset_breakers(srv):
    for e in srv._entries.values():
        if e.model is not None:
            e.model._engine_breaker().reset()


# ---------------------------------------------------------------------------
# basic serving + coalescing
# ---------------------------------------------------------------------------


def test_roundtrip_coalescing_and_bank_cold_start(tenants):
    srv = _server(tenants, slo_ms=2000)
    try:
        before = server_stats()
        futs = [(nm, t["records"][i * 3:(i + 1) * 3],
                 srv.submit(nm, t["records"][i * 3:(i + 1) * 3]))
                for i in range(5) for nm, t in tenants.items()]
        for nm, recs, f in futs:
            res = f.result(timeout=60)
            assert res.rows == len(recs)
            entry = srv._entries[nm]
            # bit-identical to solo scoring through the same program
            # (the dispatch's bucket pinned — co-batching is inert)
            solo = entry.engine.score_store(recs, bucket_min=res.bucket)
            _assert_bitwise(res.store[tenants[nm]["pred"].name],
                            solo[tenants[nm]["pred"].name])
        after = server_stats()
        d = {k: after[k] - before[k] for k in
             ("requests", "batches", "rows", "model_loads", "bank_loads")}
        assert d["requests"] == 10
        assert d["rows"] == 30
        assert 0 < d["batches"] <= 10
        assert d["model_loads"] == 2 and d["bank_loads"] == 2
        # the AOT bank answered the cold start: zero compiles anywhere
        assert all(e.engine.compile_count == 0
                   for e in srv._entries.values())
        # the sync convenience wrapper
        res = srv.score("A", tenants["A"]["records"][:4], timeout_s=60)
        assert res.store.n_rows == 4
    finally:
        srv.shutdown(drain=True)


def test_stats_shapes(tenants):
    srv = _server(tenants, slo_ms=5000)
    try:
        srv.score("A", tenants["A"]["records"][:4], timeout_s=60)
        doc = srv.stats()
        assert doc["sloMs"] == 5000
        a = doc["models"]["A"]
        assert a["loaded"] and a["requests"] >= 1
        assert "p50_ms" in a and "p99_ms" in a
        assert a["bankBuckets"] == [8, 16, 32, 64]
        glob = doc["server"]
        assert glob["batch_coalescing_factor"] is not None
        assert glob["slo_attainment"] is not None
    finally:
        srv.shutdown(drain=True)


def test_unknown_model_and_closed_server(tenants):
    srv = _server(tenants)
    with pytest.raises(ModelNotFound):
        srv.submit("nope", [{"x": 1}])
    srv.shutdown(drain=True)
    with pytest.raises(ServerClosed):
        srv.submit("A", tenants["A"]["records"][:1])
    srv.shutdown(drain=True)      # idempotent


def test_backpressure_rejects_when_queue_full(tenants):
    """Admission control: a full bounded queue rejects synchronously
    with ServerBusy — no silent unbounded buffering. The first dispatch
    is held on an event so the fill is deterministic."""
    gate = threading.Event()
    released = threading.Event()

    class Held(ModelServer):
        def _dispatch(self, entry, batch):
            released.set()
            gate.wait(timeout=30)
            super()._dispatch(entry, batch)

    srv = Held(max_models=2, max_queue=2, batch_deadline_s=0.0,
               bucket_cap=BUCKET_CAP)
    srv.register("A", model_dir=tenants["A"]["model_dir"])
    try:
        recs = tenants["A"]["records"]
        before = server_stats()["rejected"]
        f0 = srv.submit("A", recs[:2])      # worker picks this up
        released.wait(timeout=30)           # dispatch is now held
        f1 = srv.submit("A", recs[2:4])     # queued (1/2)
        f2 = srv.submit("A", recs[4:6])     # queued (2/2)
        with pytest.raises(ServerBusy):
            srv.submit("A", recs[6:8])      # bounced
        assert server_stats()["rejected"] - before == 1
        gate.set()
        for f in (f0, f1, f2):
            assert f.result(timeout=60).rows == 2
    finally:
        gate.set()
        srv.shutdown(drain=True)


# ---------------------------------------------------------------------------
# LRU eviction / reload
# ---------------------------------------------------------------------------


def test_lru_evicts_and_reloads(tenants):
    srv = _server(tenants, max_models=1)
    try:
        before = server_stats()
        srv.score("A", tenants["A"]["records"][:3], timeout_s=60)
        assert srv._entries["A"].model is not None
        srv.score("B", tenants["B"]["records"][:3], timeout_s=60)
        # loading B crossed max_models=1: A (the LRU victim) unloaded
        assert srv._entries["A"].model is None
        assert srv._entries["B"].model is not None
        # A transparently reloads on its next request — correct results
        res = srv.score("A", tenants["A"]["records"][:3], timeout_s=60)
        solo = srv._entries["A"].engine.score_store(
            tenants["A"]["records"][:3], bucket_min=res.bucket)
        _assert_bitwise(res.store[tenants["A"]["pred"].name],
                        solo[tenants["A"]["pred"].name])
        d = server_stats()
        assert d["model_evictions"] - before["model_evictions"] >= 2
        assert d["model_loads"] - before["model_loads"] >= 3
        # the bank re-attaches on reload: still zero compiles
        assert srv._entries["A"].engine.compile_count == 0
        # LRU weight: bank bytes with a 1 MiB floor (tiny test banks
        # sit under the floor)
        from transmogrifai_tpu import aot
        manifest, _ = aot.read_manifest(tenants["A"]["export_dir"])
        assert aot.bank_bytes(manifest) > 0
        assert srv._entries["A"].weight_bytes \
            == max(aot.bank_bytes(manifest), 1 << 20)
    finally:
        srv.shutdown(drain=True)


def test_eviction_mid_dispatch_does_not_kill_worker(tenants):
    """Regression: an LRU eviction landing while a dispatch is in
    flight must not null the model out from under it — the dispatch
    scores through references captured under the entry lock, the
    future resolves, and the worker survives for the next request."""
    gate = threading.Event()
    released = threading.Event()

    class Held(ModelServer):
        def _dispatch(self, entry, batch):
            if entry.name == "A":
                released.set()
                gate.wait(timeout=60)
            super()._dispatch(entry, batch)

    srv = Held(max_models=1, batch_deadline_s=0.0, bucket_cap=BUCKET_CAP)
    srv.register("A", model_dir=tenants["A"]["model_dir"],
                 bank_dir=tenants["A"]["export_dir"])
    srv.register("B", model_dir=tenants["B"]["model_dir"])
    try:
        fa = srv.submit("A", tenants["A"]["records"][:3])
        released.wait(timeout=60)          # A's dispatch is in flight
        # B's load crosses max_models=1 and evicts A mid-dispatch
        srv.score("B", tenants["B"]["records"][:3], timeout_s=60)
        gate.set()
        assert fa.result(timeout=60).rows == 3      # batch unharmed
        # the worker survived: a fresh A request reloads and scores
        assert srv.score("A", tenants["A"]["records"][:2],
                         timeout_s=60).rows == 2
    finally:
        gate.set()
        srv.shutdown(drain=True)
        _reset_breakers(srv)


def test_pinned_live_model_never_evicted(tenants):
    srv = ModelServer(max_models=1, batch_deadline_s=0.0,
                      bucket_cap=BUCKET_CAP)
    srv.register("live", model=tenants["A"]["model"])
    srv.register("B", model_dir=tenants["B"]["model_dir"])
    try:
        srv.score("B", tenants["B"]["records"][:3], timeout_s=60)
        assert srv._entries["live"].model is not None   # pinned
        res = srv.score("live", tenants["A"]["records"][:3],
                        timeout_s=60)
        assert res.rows == 3
    finally:
        srv.shutdown(drain=True)
        tenants["A"]["model"]._engine_breaker().reset()


# ---------------------------------------------------------------------------
# graceful shutdown drains
# ---------------------------------------------------------------------------


def test_graceful_shutdown_drains_all_queued(tenants):
    """A long batching deadline leaves requests queued/coalescing when
    shutdown lands; drain=True scores every accepted request anyway."""
    srv = _server(tenants, batch_deadline_s=30.0)
    futs = [srv.submit(nm, tenants[nm]["records"][i * 2:(i + 1) * 2])
            for i in range(4) for nm in ("A", "B")]
    t0 = time.perf_counter()
    srv.shutdown(drain=True, timeout_s=120)
    assert time.perf_counter() - t0 < 60       # sentinel cut the hold
    for f in futs:
        res = f.result(timeout=1)              # already resolved
        assert res.rows == 2
    _reset_breakers(srv)


def test_no_drain_fails_pending(tenants):
    """drain=False: in-flight work completes, but requests still QUEUED
    fail loudly with ServerClosed instead of being silently dropped.
    The first dispatch is held on an event so 'queued' is
    deterministic."""
    gate = threading.Event()
    released = threading.Event()

    class Held(ModelServer):
        def _dispatch(self, entry, batch):
            released.set()
            gate.wait(timeout=60)
            super()._dispatch(entry, batch)

    srv = Held(max_models=2, batch_deadline_s=0.0,
               bucket_cap=BUCKET_CAP)
    srv.register("A", model_dir=tenants["A"]["model_dir"])
    try:
        f0 = srv.submit("A", tenants["A"]["records"][:2])  # in flight
        released.wait(timeout=60)
        queued = [srv.submit("A", tenants["A"]["records"][:2])
                  for _ in range(2)]
        stopper = threading.Thread(
            target=lambda: srv.shutdown(drain=False, timeout_s=120),
            name="test-stopper", daemon=True)
        stopper.start()
        for f in queued:       # failed synchronously by the no-drain path
            with pytest.raises(ServerClosed):
                f.result(timeout=60)
        gate.set()
        stopper.join(timeout=120)
        assert f0.result(timeout=60).rows == 2   # in-flight completed
    finally:
        gate.set()
        srv.shutdown(drain=False)
        _reset_breakers(srv)


# ---------------------------------------------------------------------------
# chaos: faults injected, bit-identity held, quarantine counted, no drops
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_concurrent_mixed_model_chaos_bit_identity(tenants):
    """The acceptance chaos test: K threads of mixed-model traffic with
    a seeded fault plan on ``server.dispatch``. Every request either
    succeeds BIT-IDENTICALLY to solo scoring or fails with the injected
    fault and is quarantined; the quarantine tally matches the failures
    exactly; graceful shutdown drops nothing."""
    srv = _server(tenants, batch_deadline_s=0.005)
    results = []
    res_lock = threading.Lock()
    plan = resilience.FaultPlan(seed=1234).on(
        "server.dispatch", error=RuntimeError, probability=0.35)
    q_before = resilience.resilience_stats()["quarantined_batches"]
    s_before = server_stats()

    def client(k):
        rng = np.random.default_rng(1000 + k)
        for i in range(8):
            nm = "A" if (k + i) % 2 == 0 else "B"
            recs = tenants[nm]["records"]
            lo = int(rng.integers(0, 150))
            n = int(rng.integers(1, 7))
            reqs = recs[lo:lo + n]
            try:
                fut = srv.submit(nm, reqs)
            except ServerBusy:
                continue
            with res_lock:
                results.append((nm, reqs, fut))

    try:
        with resilience.fault_plan(plan):
            threads = [threading.Thread(target=client, args=(k,),
                                        name=f"chaos-client-{k}",
                                        daemon=True)
                       for k in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            srv.shutdown(drain=True, timeout_s=120)
    finally:
        _reset_breakers(srv)

    assert results
    failed = 0
    for nm, reqs, fut in results:
        assert fut.done()          # graceful shutdown dropped nothing
        try:
            res = fut.result(timeout=1)
        except RuntimeError:
            failed += 1            # the injected fault, surfaced loudly
            continue
        assert res.rows == len(reqs)
        entry = srv._entries[nm]
        pred = tenants[nm]["pred"]
        if res.engine_tier:
            solo = entry.engine.score_store(reqs, bucket_min=res.bucket)
        else:
            solo = entry.model.score(reqs, engine=False)
        _assert_bitwise(res.store[pred.name], solo[pred.name])
    # quarantine accounting: every failed request was quarantined, and
    # nothing else was
    q_delta = (resilience.resilience_stats()["quarantined_batches"]
               - q_before)
    assert q_delta == failed
    s_after = server_stats()
    assert s_after["quarantined_requests"] \
        - s_before["quarantined_requests"] == failed
    assert s_after["requests"] - s_before["requests"] \
        == len(results) - failed
    assert plan.fired("server.dispatch") >= failed


# ---------------------------------------------------------------------------
# telemetry + HTTP front end
# ---------------------------------------------------------------------------


def test_on_request_listener_and_instruments(tenants):
    telemetry.enable()
    try:
        collector = telemetry.add_listener(
            telemetry.CollectingRunListener())
        srv = _server(tenants, slo_ms=5000)
        try:
            srv.score("A", tenants["A"]["records"][:4], timeout_s=60)
        finally:
            srv.shutdown(drain=True)
        summary = collector.summary()
        assert summary["requests"] == 1
        assert summary["requestRows"] == 4
        assert summary["requestsFailed"] == 0
        doc = telemetry.metrics_json()
        assert doc["server.requests"] >= 1
        assert "server.request_seconds.A" in doc
    finally:
        telemetry.disable()
        telemetry.reset()


def test_http_front_end(tenants):
    import http.client
    srv = _server(tenants, slo_ms=5000)
    httpd = serve_http(srv, port=0)
    host, port = httpd.server_address
    try:
        conn = http.client.HTTPConnection(host, port, timeout=30)

        def call(method, path, body=None):
            conn.request(method, path,
                         None if body is None else json.dumps(body),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            return r.status, json.loads(r.read() or b"{}")

        status, doc = call("GET", "/healthz")
        assert status == 200 and sorted(doc["models"]) == ["A", "B"]
        status, doc = call("POST", "/v1/models/A:score",
                           {"records": tenants["A"]["records"][:3]})
        assert status == 200
        assert doc["rows"] == 3 and doc["bucket"] >= 3
        pred_name = tenants["A"]["pred"].name
        assert pred_name in doc["outputs"][0]
        assert "prediction" in doc["outputs"][0][pred_name]
        status, _ = call("POST", "/v1/models/nope:score",
                         {"records": [{"x": 1}]})
        assert status == 404
        status, _ = call("POST", "/v1/models/A:score", {"records": []})
        assert status == 400
        status, doc = call("GET", "/stats")
        assert status == 200 and "A" in doc["models"]
        status, _ = call("GET", "/nothing")
        assert status == 404
    finally:
        httpd.shutdown()
        srv.shutdown(drain=True)


def test_http_score_timeout_answers_504_and_is_tallied(tenants):
    """A request that outlives request_timeout_s answers 504 with a
    structured body (it used to fall into the broad-except and answer
    500), is tallied, and the still-running future is accounted for —
    its eventual completion lands in ``timed_out_completions`` instead
    of vanishing."""
    import http.client
    gate = threading.Event()
    released = threading.Event()

    class Held(ModelServer):
        def _dispatch(self, entry, batch):
            released.set()
            gate.wait(timeout=60)
            super()._dispatch(entry, batch)

    srv = Held(max_models=2, batch_deadline_s=0.0, bucket_cap=BUCKET_CAP)
    srv.register("A", model_dir=tenants["A"]["model_dir"])
    httpd = serve_http(srv, port=0, request_timeout_s=0.3)
    host, port = httpd.server_address
    before = server_stats()
    try:
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("POST", "/v1/models/A:score",
                     json.dumps({"records": tenants["A"]["records"][:2]}),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        doc = json.loads(r.read())
        assert r.status == 504
        assert "timed out after 0.3s" in doc["error"]
        conn.close()
        d = server_stats()
        assert d["requests_timed_out"] - before["requests_timed_out"] == 1
        gate.set()                # let the held dispatch complete late
        srv.shutdown(drain=True, timeout_s=120)
        d = server_stats()
        # the future was NOT silently dropped: either the cancel won
        # (worker skipped it) or its late completion was tallied
        assert (d["timed_out_completions"]
                - before["timed_out_completions"]) in (0, 1)
    finally:
        gate.set()
        httpd.shutdown()
        srv.shutdown(drain=True)
        _reset_breakers(srv)


def test_healthz_draining_and_readyz_split(tenants):
    """Liveness vs readiness: /healthz flips 503 the instant shutdown
    begins (a router must stop sending to a draining worker); /readyz
    reports loadable tenants + queue headroom as its own document."""
    import http.client
    srv = _server(tenants)
    httpd = serve_http(srv, port=0)
    host, port = httpd.server_address

    def call(path):
        conn = http.client.HTTPConnection(host, port, timeout=30)
        try:
            conn.request("GET", path)
            r = conn.getresponse()
            return r.status, json.loads(r.read() or b"{}")
        finally:
            conn.close()

    try:
        status, doc = call("/healthz")
        assert status == 200 and doc["status"] == "ok"
        status, doc = call("/readyz")
        assert status == 200 and doc["ready"] is True
        assert doc["models"] == 2 and doc["queueHeadroom"] == 1.0
        assert doc["reasons"] == []
        srv.shutdown(drain=True)
        status, doc = call("/healthz")
        assert status == 503 and doc["status"] == "draining"
        status, doc = call("/readyz")
        assert status == 503 and doc["ready"] is False
        assert "closing" in doc["reasons"]
    finally:
        httpd.shutdown()
        srv.shutdown(drain=True)


def test_readiness_reports_queue_saturation(tenants):
    """A server whose queues are nearly full stops being READY while
    staying LIVE — the router keeps the worker but stops sending."""
    gate = threading.Event()
    released = threading.Event()

    class Held(ModelServer):
        def _dispatch(self, entry, batch):
            released.set()
            gate.wait(timeout=60)
            super()._dispatch(entry, batch)

    srv = Held(max_models=2, max_queue=2, batch_deadline_s=0.0,
               bucket_cap=BUCKET_CAP)
    srv.register("A", model_dir=tenants["A"]["model_dir"])
    try:
        recs = tenants["A"]["records"]
        futs = [srv.submit("A", recs[:2])]
        released.wait(timeout=60)
        futs += [srv.submit("A", recs[2:4]), srv.submit("A", recs[4:6])]
        doc = srv.readiness()
        assert doc["ready"] is False
        assert any("headroom" in r for r in doc["reasons"])
        gate.set()
        for f in futs:
            assert f.result(timeout=60).rows == 2
        assert srv.readiness()["ready"] is True
    finally:
        gate.set()
        srv.shutdown(drain=True)
        _reset_breakers(srv)


# ---------------------------------------------------------------------------
# params-file construction + knob validation (runner/cli satellite)
# ---------------------------------------------------------------------------


def test_build_server_from_params(tenants, tmp_path):
    from transmogrifai_tpu.cli import build_server_from_params
    from transmogrifai_tpu.runner import OpParams
    params = OpParams(
        model_location=tenants["A"]["model_dir"],
        custom_params={
            "serveModels": {"B": {"model": tenants["B"]["model_dir"],
                                  "bank": tenants["B"]["export_dir"]}},
            "serveBank": tenants["A"]["export_dir"],
            "serveBatchDeadlineMs": 1, "serveMaxQueue": 16,
            "serveMaxModels": 2, "serveSloMs": 5000,
            "serveBucketCap": BUCKET_CAP})
    srv = build_server_from_params(params)
    try:
        assert sorted(srv.models()) == ["B", "default"]
        assert srv.slo_ms == 5000 and srv.max_queue == 16
        res = srv.score("default", tenants["A"]["records"][:3],
                        timeout_s=60)
        assert res.rows == 3
        assert srv._entries["default"].engine.compile_count == 0  # bank
    finally:
        srv.shutdown(drain=True)


@pytest.mark.parametrize("key,val", [
    ("serveBatchDeadlineMs", "soon"), ("serveMaxQueue", 2.5),
    ("serveMaxModels", 0), ("serveSloMs", float("nan")),
    ("serveBucketCap", 4),
])
def test_serve_knob_validation_names_the_key(tenants, key, val):
    from transmogrifai_tpu.cli import build_server_from_params
    from transmogrifai_tpu.runner import OpParams
    params = OpParams(model_location=tenants["A"]["model_dir"],
                      custom_params={key: val})
    with pytest.raises(ValueError, match=key):
        build_server_from_params(params)


def test_cli_check_validates_serve_knobs(tmp_path, capsys):
    from transmogrifai_tpu.cli import run_check
    p = tmp_path / "params.json"
    p.write_text(json.dumps({
        "customParams": {"serveBatchDeadlineMs": "abc",
                         "serveMaxModels": 1.5}}))
    assert run_check(str(p)) == 1
    out = capsys.readouterr().out
    assert "TMG001" in out
    assert "serveBatchDeadlineMs" in out and "serveMaxModels" in out


def test_cli_serve_bad_params_exits_nonzero(tmp_path, capsys):
    from transmogrifai_tpu.cli import run_serve
    p = tmp_path / "params.json"
    p.write_text(json.dumps({"customParams": {"serveMaxQueue": "lots"}}))
    assert run_serve(str(p)) == 1
    assert "serveMaxQueue" in capsys.readouterr().out
    # no models configured at all
    p.write_text(json.dumps({}))
    assert run_serve(str(p)) == 1
    assert "no models" in capsys.readouterr().out
