"""Whole-DAG planner tests (planner.py + its engine/workflow/runner/CLI
integration).

Covers: plan determinism (same DAG + same cost db ⇒ byte-identical
report and JSON), dead-column liveness + TMG402, verified CSE merges +
bit-identical planned scores on a duplicated-vectorizer workflow,
dead-column pruning parity on the titanic example, tier hints (engine,
fitstats, transform-layer), the cost database's atomic writes and
corrupt-file tolerance (TMG404, never a crash), the TMG401 measured-
tier contradiction, runner stamping + failOn/suppress flow for TMG4xx,
and the ``plan`` CLI's no-reader-I/O / no-device-dispatch contract.
"""
import json
import os
import sys

import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder, Workflow, lint, planner
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.models.linear import LogisticRegressionFamily
from transmogrifai_tpu.models.selector import (
    BinaryClassificationModelSelector)
from transmogrifai_tpu.planner import CostDatabase, ExecutionPlan
from transmogrifai_tpu.runner import OpParams, OpWorkflowRunner, RunType

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _records(rng, n=300):
    y = rng.integers(0, 2, n).astype(float)
    cats = ["a", "b", "c"]
    return [{"label": float(y[i]),
             "x": float(rng.normal() + 2 * y[i]),
             "junk": 0.0,
             "c": cats[int(rng.integers(0, 3))]} for i in range(n)]


def _pruning_cse_model(rng, dup_pivot=True):
    """A fitted workflow with a constant 'junk' feature (the sanity
    checker drops its columns → dead columns) and, optionally, two
    structurally identical pivots over one feature (CSE bait)."""
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    fj = FeatureBuilder.Real("junk").from_column().as_predictor()
    fc = FeatureBuilder.PickList("c").from_column().as_predictor()
    feats = [fx, fj]
    if dup_pivot:
        feats += [fc.pivot(), fc.pivot()]
    else:
        feats += [fc]
    vec = transmogrify(feats)
    checked = label.sanity_check(vec, remove_bad_features=True)
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=5)
    pred = label.transform_with(sel, checked)
    recs = _records(rng)
    model = (Workflow().set_input_records(recs)
             .set_result_features(pred).train())
    return model, recs


@pytest.fixture
def fast_link(monkeypatch):
    """Pin the bandwidth gate OPEN so engine paths run in CI."""
    from transmogrifai_tpu import workflow as wf
    monkeypatch.setattr(wf, "_DEVICE_BW_MBPS", 1e9)


# ---------------------------------------------------------------------------
# determinism + report schema
# ---------------------------------------------------------------------------


def test_plan_determinism_byte_identical(rng, tmp_path):
    model, _ = _pruning_cse_model(rng)
    db = CostDatabase.load(str(tmp_path / "db.json"))
    planner.record_fit_costs(model, db)
    db.save()
    db2 = CostDatabase.load(str(tmp_path / "db.json"))
    p1 = planner.plan_model(model, cost_db=db)
    p2 = planner.plan_model(model, cost_db=db2)
    assert p1.report() == p2.report()
    assert (json.dumps(p1.to_json(), sort_keys=True)
            == json.dumps(p2.to_json(), sort_keys=True))
    # the report is the documented explainable artifact: every stage
    # row names its tier + reason, the header names the link source
    rep = p1.report()
    assert "ExecutionPlan" in rep and "Stage tiers" in rep
    assert "measured" in rep or "static" in rep


def test_plan_json_schema(rng):
    model, _ = _pruning_cse_model(rng)
    doc = planner.plan_model(model).to_json()
    assert doc["version"] == 1
    assert set(doc["tiers"]) == {"engine", "fitstats", "transform",
                                 "aggregate"}
    assert doc["counts"]["stages"] == len(doc["stages"])
    for row in doc["stages"]:
        assert {"uid", "stage", "kind", "tier", "reason",
                "source"} <= set(row)


# ---------------------------------------------------------------------------
# dead-column liveness + CSE analyses
# ---------------------------------------------------------------------------


def test_dead_columns_found_and_reported(rng):
    model, _ = _pruning_cse_model(rng)
    plan = planner.plan_model(model)
    assert plan.counts()["prunedColumns"] > 0
    rules = [f.rule for f in plan.findings()]
    assert "TMG402" in rules
    # liveness must cover every column the sanity checker keeps: the
    # per-stage live sets union to at least the kept width
    from transmogrifai_tpu.ops.sanity_checker import SanityCheckerModel
    sc = next(m for m in model.fitted_stages.values()
              if isinstance(m, SanityCheckerModel))
    live_total = sum(len(v) for v in plan.prune.values())
    full_widths = sum(plan.widths.values())
    assert full_widths - live_total == plan.counts()["prunedColumns"]
    assert live_total >= 1 and len(sc.keep_indices) >= 1


def test_cse_merge_is_verified(rng):
    model, _ = _pruning_cse_model(rng, dup_pivot=True)
    plan = planner.plan_model(model)
    assert len(plan.cse) == 1
    m = plan.cse[0]
    assert m["stage"] == "OneHotModel" and len(m["dropped"]) == 1
    # no duplicate: no merge
    model2, _ = _pruning_cse_model(rng, dup_pivot=False)
    assert planner.plan_model(model2).cse == []


def test_tmg403_state_mismatch_suppresses_merge(rng):
    model, _ = _pruning_cse_model(rng, dup_pivot=True)
    from transmogrifai_tpu.ops.onehot import OneHotModel
    pivots = [m for m in model.fitted_stages.values()
              if isinstance(m, OneHotModel)]
    assert len(pivots) == 2
    # perturb one twin's fitted state: still structurally identical
    # (same class/inputs/params) but no longer bit-identical — the
    # merge must be SUPPRESSED, not applied
    pivots[1].vocabs = [list(reversed(v)) for v in pivots[1].vocabs]
    plan = planner.plan_model(model)
    assert plan.cse == []
    f = next(f for f in plan.findings() if f.rule == "TMG403")
    assert "fitted state differs" in f.message
    assert f.severity == lint.Severity.INFO


def test_planned_scores_bit_identical_with_cse_and_pruning(rng, fast_link):
    model, recs = _pruning_cse_model(rng)
    plan = model.plan()                        # builds + attaches
    assert plan.counts()["prunedColumns"] > 0
    assert plan.counts()["cseMerges"] == 1
    base = model.score(recs, engine=False)
    planned_eng = model.scoring_engine(gate_bandwidth=False)
    unplanned_eng = model.scoring_engine(plan=None, gate_bandwidth=False)
    # the aliased twin contributes no prepared blocks (host_prepare
    # skipped) and the pruning actually rewrote the select indices
    assert planned_eng._cse_alias and planned_eng._prune
    assert not unplanned_eng._cse_alias and not unplanned_eng._prune
    planned = planned_eng.score_store(recs)
    unplanned = unplanned_eng.score_store(recs)
    nm = [f.name for f in model.result_features][0]
    for other in (planned, unplanned):
        assert np.array_equal(base[nm].prediction, other[nm].prediction)
        assert np.array_equal(base[nm].probability, other[nm].probability)
        assert np.array_equal(base[nm].raw_prediction,
                              other[nm].raw_prediction)
    # transform path materializes every column: pruning must self-
    # disable there and stay bit-identical too
    tb = model.transform(recs, engine=False)
    tp = planned_eng.transform_store(recs)
    for cn in tb.names():
        vb = getattr(tb[cn], "values", None)
        if isinstance(vb, np.ndarray) and vb.dtype != object:
            assert np.array_equal(vb, np.asarray(tp[cn].values)), cn


def _titanic_pruning_parity(families=None):
    sys.path.insert(0, os.path.join(_REPO, "examples"))
    try:
        from titanic import run as run_titanic
    finally:
        sys.path.pop(0)
    out = run_titanic(num_folds=2, families=families, seed=42)
    model = out["model"]
    plan = planner.plan_model(model)
    # the sanity checker prunes bad features on titanic → dead columns
    assert plan.counts()["prunedColumns"] > 0
    raws = [f for f in model.result_features[0].raw_features()]
    from titanic import DEFAULT_CSV, TITANIC_SCHEMA
    from transmogrifai_tpu.readers import DataReaders
    store = DataReaders.simple.csv(
        DEFAULT_CSV, TITANIC_SCHEMA,
        key_fn=lambda r: r["id"]).generate_store(raws)
    base = model.score(store, engine=False)
    model.attach_plan(plan)
    planned = model.scoring_engine(gate_bandwidth=False).score_store(store)
    nm = [f.name for f in model.result_features][0]
    assert np.array_equal(base[nm].prediction, planned[nm].prediction)
    assert np.array_equal(base[nm].probability, planned[nm].probability)


def test_pruning_parity_on_titanic_small_grid(fast_link):
    # tier-1 variant: ONE logistic-regression grid point keeps the CV
    # sweep tiny while still exercising sanity-check pruning + planned
    # scoring parity on the real example end to end
    _titanic_pruning_parity(families=[LogisticRegressionFamily(
        grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])])


@pytest.mark.slow
def test_pruning_parity_on_titanic_example(fast_link):
    # full default model-selector sweep (every family, full grids)
    _titanic_pruning_parity()


# ---------------------------------------------------------------------------
# tier assignment: hints, measured costs, TMG401
# ---------------------------------------------------------------------------


def test_engine_tier_hint_overrides_gate(rng, monkeypatch):
    model, _ = _pruning_cse_model(rng)
    from transmogrifai_tpu import workflow as wf
    monkeypatch.setattr(wf, "_DEVICE_BW_MBPS", 1.0)   # link below gate
    plan = planner.plan_model(model)
    plan.engine_tier = "device"
    eng = model.attach_plan(plan).scoring_engine()
    assert eng.enabled()          # measured tier beats the slow prior
    plan2 = planner.plan_model(model)
    plan2.engine_tier = "host"
    monkeypatch.setattr(wf, "_DEVICE_BW_MBPS", 1e9)
    eng = model.attach_plan(plan2).scoring_engine()
    assert not eng.enabled()      # measured host tier beats a fast link
    # the explicit force knob outranks the plan tier: a caller who
    # builds with gate_bandwidth=False owns the decision
    eng = model.attach_plan(plan2).scoring_engine(gate_bandwidth=False)
    assert eng.enabled()
    eng = model.attach_plan(None).scoring_engine()
    assert eng.enabled()          # no plan: the gate (prior) rules


def test_measured_chain_costs_decide_engine_tier(rng, tmp_path):
    model, _ = _pruning_cse_model(rng)
    db = CostDatabase.load(str(tmp_path / "db.json"))
    db.record_chain(host_rows_per_s=1000.0, engine_rows_per_s=10000.0)
    assert planner.plan_model(model, cost_db=db).engine_tier == "device"
    db.record_chain(host_rows_per_s=10000.0, engine_rows_per_s=1000.0)
    assert planner.plan_model(model, cost_db=db).engine_tier == "host"


def test_tmg401_measured_slower_on_device(rng, tmp_path):
    model, _ = _pruning_cse_model(rng)
    db = CostDatabase.load(str(tmp_path / "db.json"))
    # the vectorizer class measured 10× slower on device than host but
    # its consumers pin it into the fused program → TMG401 warning
    db.record_stage("NumericVectorizerModel", "host", 0.001, 1000)
    db.record_stage("NumericVectorizerModel", "device", 0.01, 1000)
    plan = planner.plan_model(model, cost_db=db)
    f = next(f for f in plan.findings() if f.rule == "TMG401")
    assert f.severity == lint.Severity.WARNING
    assert "slower on device" in f.message
    entry = next(e for e in plan.entries
                 if e.stage == "NumericVectorizerModel")
    assert entry.source == "measured"


def test_fitstats_tier_hint_overrides_bandwidth_only(monkeypatch):
    from transmogrifai_tpu import workflow as wf
    from transmogrifai_tpu.columns import ColumnStore, column_from_values
    from transmogrifai_tpu.fitstats import LayerStatsPlan, StatRequest
    from transmogrifai_tpu.types import feature_types as ft
    rng = np.random.default_rng(7)
    n = wf.FUSE_MIN_ROWS
    store = ColumnStore(
        {"x": column_from_values(ft.Real, rng.normal(size=n))}, n)
    plan = LayerStatsPlan([StatRequest("mean", "x")], n_stages=2)
    monkeypatch.setattr(wf, "_DEVICE_BW_MBPS", 1.0)   # slow link
    assert plan._gate_device(store) is False
    assert plan._gate_device(store, "device") is True   # hint overrides
    monkeypatch.setattr(wf, "_DEVICE_BW_MBPS", 1e9)   # fast link
    assert plan._gate_device(store, "host") is False    # hint overrides
    # the row floor holds whatever the hint says
    small = ColumnStore(
        {"x": column_from_values(ft.Real, rng.normal(size=8))}, 8)
    assert plan._gate_device(small, "device") is False
    # results parity: hinted tiers compute the same stats
    r_host = plan.run(store, device=False)
    r_hint = plan.run(store, tier_hint="host", mesh=False)
    assert r_host.value("mean", "x") == r_hint.value("mean", "x")


def test_transform_layer_fuse_override(rng, monkeypatch):
    from transmogrifai_tpu import workflow as wf
    model, recs = _pruning_cse_model(rng)
    layer = [m for m in model._resolved_dag()[0]]
    from transmogrifai_tpu.workflow import (_generate_raw_store,
                                            _raw_features_of,
                                            apply_layer_vectorized)
    store = _generate_raw_store(recs,
                                _raw_features_of(model.result_features))
    monkeypatch.setattr(wf, "_DEVICE_BW_MBPS", 1.0)   # gate says host
    host = apply_layer_vectorized(layer, store, fuse_min_rows=1)
    fused = apply_layer_vectorized(layer, store, fuse_min_rows=1,
                                   fuse=True)
    for m in layer:
        nm = m.output_name
        assert np.array_equal(np.asarray(host[nm].values),
                              np.asarray(fused[nm].values)), nm


def test_pruning_parity_with_scaler_between_combine_and_select(
        rng, fast_link):
    """A StandardScaler between the (pruned) combiner and the sanity
    select: the engine must slice the scaler's full-width mean/std to
    the surviving columns (or the program would fail to broadcast) and
    stay bit-identical."""
    from transmogrifai_tpu.ops.vectors import StandardScalerEstimator
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    fj = FeatureBuilder.Real("junk").from_column().as_predictor()
    fc = FeatureBuilder.PickList("c").from_column().as_predictor()
    vec = transmogrify([fx, fj, fc])
    scaled = StandardScalerEstimator().set_input(vec).get_output()
    checked = label.sanity_check(scaled, remove_bad_features=True)
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=5)
    pred = label.transform_with(sel, checked)
    recs = _records(rng)
    model = (Workflow().set_input_records(recs)
             .set_result_features(pred).train())
    # the parity oracle here is the UNPLANNED engine: host numpy runs
    # the scaler in f64 while the device program runs the pipeline f32,
    # a pre-existing engine-wide difference independent of planning —
    # the planner's contract is planned ≡ unplanned, bit for bit
    unplanned = model.scoring_engine(
        plan=None, gate_bandwidth=False).score_store(recs)
    plan = model.plan()
    assert plan.counts()["prunedColumns"] > 0
    eng = model.scoring_engine(gate_bandwidth=False)
    assert eng._prune and eng._scale_slice, \
        "the scaler under pruning must carry a constants slice"
    planned = eng.score_store(recs)
    nm = [f.name for f in model.result_features][0]
    assert np.array_equal(unplanned[nm].prediction,
                          planned[nm].prediction)
    assert np.array_equal(unplanned[nm].probability,
                          planned[nm].probability)
    assert np.array_equal(unplanned[nm].raw_prediction,
                          planned[nm].raw_prediction)


def test_liveness_unknown_width_disables_pruning_through_combine():
    """A combine input of unknown width (an upload) poisons the column
    offsets of everything after it — no input of that combine may be
    reported prunable."""
    from types import SimpleNamespace

    from transmogrifai_tpu.planner import _ALL, _device_liveness
    from transmogrifai_tpu.scoring import _FusedStage

    vec = SimpleNamespace(uid="v1",
                          vector_metadata=lambda: SimpleNamespace(size=3))
    sel = SimpleNamespace(uid="s1", keep_indices=[3])
    items = [
        _FusedStage(vec, "vec", "v1o", []),
        _FusedStage(SimpleNamespace(uid="c1"), "combine", "co",
                    ["upload", "v1o"]),
        _FusedStage(sel, "select", "so", ["co"]),
        _FusedStage(SimpleNamespace(uid="p1"), "predict", "po", ["so"]),
    ]
    live, _widths = _device_liveness(items, ["po"])
    assert live["v1o"] is _ALL
    # with the width known, the same shape DOES prune correctly
    vec0 = SimpleNamespace(uid="v0",
                           vector_metadata=lambda: SimpleNamespace(size=3))
    items[1] = _FusedStage(SimpleNamespace(uid="c1"), "combine", "co",
                           ["v0o", "v1o"])
    live, _ = _device_liveness([_FusedStage(vec0, "vec", "v0o", [])]
                               + items, ["po"])
    assert live["v1o"] == {0}          # global col 3 → v1o's col 0
    assert live["v0o"] == set()        # v0 is entirely dead


def test_cse_pass_tolerates_unparamable_stages():
    from types import SimpleNamespace

    from transmogrifai_tpu.planner import _cse_pass
    from transmogrifai_tpu.scoring import _FusedStage

    class _NoParams:
        def __init__(self, uid):
            self.uid = uid
            self.input_features = (SimpleNamespace(name="x"),)

        def get_params(self):
            raise RuntimeError("no ctor capture")

    items = [_FusedStage(_NoParams("a"), "vec", "ao", []),
             _FusedStage(_NoParams("b"), "vec", "bo", [])]
    merges, suppressed = _cse_pass(items)     # must not raise
    assert merges == []


def test_phase_observations_feed_measured_phase_tiers(rng, monkeypatch):
    """The fused stats pass / transform fusion report their measured
    (phase, tier) costs; drained into a db they activate the planner's
    per-phase tier decisions — the path that retires the global gate."""
    from transmogrifai_tpu import workflow as wf
    from transmogrifai_tpu.columns import ColumnStore, column_from_values
    from transmogrifai_tpu.fitstats import LayerStatsPlan, StatRequest
    from transmogrifai_tpu.types import feature_types as ft
    db = CostDatabase()
    planner.drain_phase_observations(db)          # clear any pending
    n = wf.FUSE_MIN_ROWS
    store = ColumnStore(
        {"x": column_from_values(ft.Real,
                                 np.random.default_rng(3).normal(size=n))},
        n)
    plan = LayerStatsPlan([StatRequest("mean", "x")], n_stages=2)
    plan.run(store, device=False)                  # host-tier pass
    db2 = CostDatabase()
    assert planner.drain_phase_observations(db2) >= 1
    assert db2.stage_cost("phase:fitstats", "host") is not None
    # both tiers measured → the phase tier activates
    db2.record_stage("phase:fitstats", "device", 10.0, 1000)
    model, _ = _pruning_cse_model(rng)
    assert planner.plan_model(model, cost_db=db2).fitstats_tier == "host"
    db2.record_stage("phase:fitstats", "device", 0.000001, 1000000000)
    p = planner.plan_model(model, cost_db=db2)
    assert p.fitstats_tier in ("host", "device")   # decided, not None


# ---------------------------------------------------------------------------
# cost database: atomicity + corruption tolerance (satellite)
# ---------------------------------------------------------------------------


def test_cost_db_atomic_write_and_roundtrip(tmp_path):
    path = str(tmp_path / "cache" / "tmog_cost_db.json")
    db = CostDatabase.load(path)
    db.record_stage("Foo", "fit", 0.5, 1000)
    db.record_stage("Foo", "fit", 1.5, 1000)      # running mean
    db.record_bandwidth(1234.56)
    assert db.save()
    assert os.path.exists(path)
    assert not os.path.exists(path + ".tmp"), \
        "atomic write must leave no temp file behind"
    back = CostDatabase.load(path)
    assert back.stage_cost("Foo", "fit") == pytest.approx(1.0)
    assert back.bandwidth_mbps() == pytest.approx(1234.6)
    assert back.corrupt is False and back.finding() is None


@pytest.mark.parametrize("payload", [
    '{"version": 1, "stages": {',            # truncated mid-object
    "not json at all",
    '{"version": 99, "stages": {}}',         # wrong version
    '[1, 2, 3]',                             # wrong shape
])
def test_cost_db_corruption_never_crashes(tmp_path, payload):
    path = str(tmp_path / "db.json")
    with open(path, "w") as fh:
        fh.write(payload)
    db = CostDatabase.load(path)
    assert db.corrupt is True
    f = db.finding()
    assert f.rule == "TMG404" and f.severity == lint.Severity.WARNING
    assert db.stage_cost("Foo", "fit") is None
    db.record_stage("Foo", "fit", 1.0, 1000)  # still usable
    assert db.save()                          # and repairable


def test_cost_db_merge_window_keeps_means_refreshable():
    db = CostDatabase()
    for _ in range(100):
        db.record_stage("Foo", "device", 0.001, 1000)     # 0.001 s/krow
    slot = db.doc["stages"]["Foo"]["device"]
    assert slot["n"] == 100                # observation count is honest
    db.record_stage("Foo", "device", 0.001 + 0.032, 1000)
    # bounded window: the new observation carries >= 1/MERGE_WINDOW
    # weight (an unbounded mean would move by only 1/101)
    moved = db.stage_cost("Foo", "device") - 0.001
    assert moved >= 0.032 / CostDatabase.MERGE_WINDOW - 1e-9


def test_runner_disabled_plan_clears_stale_workflow_plan(rng, tmp_path):
    """A reused runner: run A plans, run B sets plan:false — run B must
    not silently follow run A's plan while stamping plan: null."""
    wf = _flow_for_runner(rng)
    reader = _CountingReader(_records(rng))
    runner = OpWorkflowRunner(wf, training_reader=reader)
    runner.run(RunType.TRAIN,
               OpParams(model_location=str(tmp_path / "m1")))
    assert wf._exec_plan is not None
    out = runner.run(RunType.TRAIN,
                     OpParams(model_location=str(tmp_path / "m2"),
                              custom_params={"plan": False}))
    assert wf._exec_plan is None
    assert out.metrics["plan"] is None


def test_record_fit_costs_from_trained_model(rng):
    model, _ = _pruning_cse_model(rng)
    assert model.train_rows > 0
    db = CostDatabase(path=None)
    n = planner.record_fit_costs(model, db)
    assert n > 0
    assert db.stage_cost("ModelSelector_modelSelector", "fit") is not None
    # loaded models (train_rows 0) record nothing
    model.train_rows = 0
    assert planner.record_fit_costs(model, CostDatabase()) == 0


# ---------------------------------------------------------------------------
# runner + CLI integration
# ---------------------------------------------------------------------------


class _CountingReader:
    def __init__(self, records):
        self._records = records
        self.calls = 0

    def read_records(self):
        self.calls += 1
        return list(self._records)


def _flow_for_runner(rng):
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    vec = transmogrify([fx])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=5)
    pred = label.transform_with(sel, vec)
    return Workflow().set_result_features(pred)


def test_runner_train_stamps_plan_and_persists_cost_db(rng, tmp_path):
    wf = _flow_for_runner(rng)
    reader = _CountingReader(_records(rng))
    db_path = str(tmp_path / "cost.json")
    params = OpParams(model_location=str(tmp_path / "model"),
                      metrics_location=str(tmp_path / "metrics.json"),
                      custom_params={"costDb": db_path})
    out = OpWorkflowRunner(wf, training_reader=reader).run(
        RunType.TRAIN, params)
    plan = out.metrics["plan"]
    assert plan["version"] == 1 and plan["counts"]["stages"] >= 2
    # the post-train stamp is the FULL model plan (kinds classified)
    assert any(e["kind"] == "predict" for e in plan["stages"])
    db = json.load(open(db_path))
    assert db["stages"], "measured fit costs must persist"
    sunk = json.load(open(params.metrics_location))
    assert sunk["plan"]["counts"] == plan["counts"]
    # and the plan rides into score runs, attached to the engine
    params2 = OpParams(model_location=str(tmp_path / "model"),
                       custom_params={"costDb": db_path})
    runner2 = OpWorkflowRunner(wf, scoring_reader=_CountingReader(
        _records(rng)))
    out2 = runner2.run(RunType.SCORE, params2)
    assert out2.metrics["plan"]["counts"]["stages"] >= 2
    # plan: false disables and stamps None
    params3 = OpParams(model_location=str(tmp_path / "model"),
                       custom_params={"plan": False})
    out3 = runner2.run(RunType.SCORE, params3)
    assert out3.metrics["plan"] is None


def test_runner_plan_findings_ride_failon_and_suppress(rng, tmp_path):
    wf = _flow_for_runner(rng)
    db_path = str(tmp_path / "cost.json")
    with open(db_path, "w") as fh:
        fh.write('{"version": 1, "stages": {')       # corrupt → TMG404
    model_dir = str(tmp_path / "model")
    reader = _CountingReader(_records(rng))
    OpWorkflowRunner(wf, training_reader=reader).run(
        RunType.TRAIN,
        OpParams(model_location=model_dir,
                 custom_params={"plan": False}))
    runner = OpWorkflowRunner(wf, scoring_reader=reader)
    # default failOn=error: the TMG404 warning logs but passes
    out = runner.run(RunType.SCORE, OpParams(
        model_location=model_dir, custom_params={"costDb": db_path}))
    assert out.metrics["rowsScored"] > 0
    # failOn=warning gates it — BEFORE any reader I/O
    reader.calls = 0
    with pytest.raises(lint.LintError) as ei:
        runner.run(RunType.SCORE, OpParams(
            model_location=model_dir,
            custom_params={"costDb": db_path, "failOn": "warning"}))
    assert "TMG404" in str(ei.value)
    assert reader.calls == 0
    # lintSuppress mutes the rule and the run proceeds
    out = runner.run(RunType.SCORE, OpParams(
        model_location=model_dir,
        custom_params={"costDb": db_path, "failOn": "warning",
                       "lintSuppress": ["TMG404"]}))
    assert out.metrics["rowsScored"] > 0


def test_plan_cli_no_reader_io_no_device_dispatch(rng, tmp_path,
                                                  capsys, monkeypatch):
    from transmogrifai_tpu.cli import run_plan
    model, _ = _pruning_cse_model(rng)
    model.save(str(tmp_path / "model"), overwrite=True)
    # the acceptance gate: planning must never probe the link, dispatch
    # to a device, or read a dataset (same discipline as PR 5's check)
    import jax

    from transmogrifai_tpu import telemetry, workflow as wfmod

    def _boom(*a, **k):
        raise AssertionError("plan must not touch the device/link")
    monkeypatch.setattr(wfmod, "device_roundtrip_mbps", _boom)
    monkeypatch.setattr(telemetry, "probe_device_roundtrip_mbps", _boom)
    monkeypatch.setattr(jax, "device_put", _boom)
    assert run_plan(model_location=str(tmp_path / "model")) == 0
    out = capsys.readouterr().out
    assert "ExecutionPlan" in out and "Stage tiers" in out
    assert "TMG402" in out            # the dead columns are reported
    # --json renders the same stable document
    assert run_plan(model_location=str(tmp_path / "model"),
                    as_json=True) == 0
    doc = json.loads(capsys.readouterr().out.split("\nTMG")[0])
    assert doc["version"] == 1
    # --suppress (and a params file's lintSuppress) mutes advisories,
    # same machinery as check/the runner
    assert run_plan(model_location=str(tmp_path / "model"),
                    suppress=["TMG402"]) == 0
    assert "TMG402" not in capsys.readouterr().out
    p = tmp_path / "params.json"
    p.write_text(json.dumps({
        "modelLocation": str(tmp_path / "model"),
        "customParams": {"lintSuppress": ["TMG402"]}}))
    assert run_plan(str(p)) == 0
    assert "TMG402" not in capsys.readouterr().out
    # a missing model is a clean exit-1, not a traceback
    assert run_plan(model_location=str(tmp_path / "nope")) == 1


def test_cli_check_validates_planner_knobs(tmp_path, capsys):
    from transmogrifai_tpu.cli import run_check
    p = tmp_path / "params.json"
    p.write_text(json.dumps({"customParams": {"plan": "yes"}}))
    assert run_check(str(p)) == 1
    assert "customParams.plan" in capsys.readouterr().out
    p.write_text(json.dumps({"customParams": {"costDb": 5}}))
    assert run_check(str(p)) == 1
    assert "customParams.costDb" in capsys.readouterr().out
    p.write_text(json.dumps({"customParams": {
        "plan": True, "costDb": "/tmp/db.json"}}))
    assert run_check(str(p)) == 0


def test_cli_gen_emits_plan_knobs(tmp_path):
    from transmogrifai_tpu.cli import generate_project
    csv = tmp_path / "data.csv"
    csv.write_text("label,x\n1,0.5\n0,0.1\n1,0.9\n0,0.2\n")
    files = generate_project(str(csv), "label", str(tmp_path / "proj"))
    params = json.load(open(files["params.json"]))
    assert params["customParams"]["plan"] is True
    assert params["customParams"]["costDb"] is None


# ---------------------------------------------------------------------------
# telemetry mirroring + always-on tallies
# ---------------------------------------------------------------------------


def test_plan_emits_telemetry_and_tallies(rng):
    from transmogrifai_tpu import telemetry
    model, _ = _pruning_cse_model(rng)
    before = planner.planner_stats()
    telemetry.enable()
    try:
        telemetry.reset()
        collector = telemetry.add_listener(
            telemetry.CollectingRunListener())
        plan = planner.plan_model(model)
        assert collector.plan is not None
        assert collector.plan["stages"] == plan.counts()["stages"]
        assert collector.plan["cseMerges"] == 1
        assert collector.summary()["plan"]["prunedColumns"] > 0
    finally:
        telemetry.disable()
        telemetry.reset()
    after = planner.planner_stats()
    assert after["plans_built"] == before["plans_built"] + 1
    assert after["pruned_columns"] > before["pruned_columns"]


def test_plan_workflow_pre_fit(rng):
    wf = _flow_for_runner(rng)
    plan = planner.plan_workflow(wf)
    assert plan.counts()["stages"] >= 2
    assert plan.engine_tier is None and plan.prune == {}
    wf.set_plan(plan)
    assert wf._exec_plan is plan
