"""CI drift guards: the docs and the code surface they describe must
not diverge silently (PR 17 satellite).

Two invariants, both checked against SOURCE TEXT so they hold without
importing heavy modules:

1. every `python -m transmogrifai_tpu <subcommand>` the docs (and the
   README) mention exists as an argparse subparser in `cli.py` — a
   renamed or removed subcommand must fail CI, not a reader;
2. every always-on `*_stats()` family that `bench.py` stamps onto its
   result docs has a catalog row in docs/observability.md — bench
   evidence nobody can look up is not evidence;
3. the declared knob registry (config.py) and the consolidated knob
   table in docs/tuning.md name exactly the same knobs — a knob
   declared but undocumented (or documented but undeclared) fails CI
   (PR 18);
4. every rule id in the lint RULES catalog has a docs row in
   docs/static-analysis.md AND at least one positive test fixture
   somewhere under tests/ — the TMG308-was-missing bug (PR 11, a rule
   shipped with no fixture proving it fires) is structurally
   impossible (PR 20).
"""
import glob
import os
import re

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _read(path):
    with open(path, encoding="utf-8") as fh:
        return fh.read()


def _doc_files():
    paths = sorted(glob.glob(os.path.join(_REPO, "docs", "*.md")))
    paths.append(os.path.join(_REPO, "README.md"))
    return paths


def test_documented_cli_subcommands_exist():
    cli_src = _read(os.path.join(_REPO, "transmogrifai_tpu", "cli.py"))
    parsers = set(re.findall(r'add_parser\(\s*"(\w+)"', cli_src))
    assert parsers, "no argparse subparsers found in cli.py"
    mentioned = {}
    for path in _doc_files():
        for m in re.finditer(r"python -m transmogrifai_tpu\s+(\w+)",
                             _read(path)):
            mentioned.setdefault(m.group(1), []).append(
                os.path.relpath(path, _REPO))
    unknown = {cmd: files for cmd, files in mentioned.items()
               if cmd not in parsers}
    assert not unknown, (
        f"docs reference CLI subcommands missing from cli.py "
        f"(available: {sorted(parsers)}): {unknown}")
    # the observability tooling must actually be documented somewhere
    for cmd in ("trace", "workload"):
        assert cmd in mentioned, f"no doc shows `python -m "\
                                 f"transmogrifai_tpu {cmd} ...`"


def test_bench_stamped_stats_families_have_catalog_rows():
    bench_src = _read(os.path.join(_REPO, "bench.py"))
    families = set(re.findall(
        r'self\.doc\["\w+"\]\s*=\s*(?:[\w.]+\.)?(\w+_stats)\(\)',
        bench_src))
    assert len(families) >= 10, (
        f"bench.py stats stamps not found by the pattern — did the "
        f"stamping idiom change? matched: {sorted(families)}")
    # the families this PR sequence promised are stamped
    assert {"workload_stats", "telemetry_stats",
            "device_cost_stats"} <= families
    catalog = _read(os.path.join(_REPO, "docs", "observability.md"))
    missing = sorted(f for f in families if f not in catalog)
    assert not missing, (
        f"bench.py stamps these always-on stats families but "
        f"docs/observability.md has no catalog row naming them: "
        f"{missing}")


def test_registry_knobs_match_docs_knob_table():
    cfg_src = _read(os.path.join(_REPO, "transmogrifai_tpu",
                                 "config.py"))
    declared = set(re.findall(r'_declare\(\s*\n?\s*"(\w+)"', cfg_src))
    assert len(declared) >= 40, (
        f"knob declarations not found by the pattern — did the "
        f"_declare idiom change? matched: {sorted(declared)}")
    doc = _read(os.path.join(_REPO, "docs", "tuning.md"))
    m = re.search(r"<!-- KNOB TABLE START -->(.*?)<!-- KNOB TABLE"
                  r" END -->", doc, re.S)
    assert m, "docs/tuning.md lost its KNOB TABLE markers"
    documented = set(re.findall(r"^\|\s*`(\w+)`", m.group(1), re.M))
    undocumented = sorted(declared - documented)
    undeclared = sorted(documented - declared)
    assert not undocumented, (
        f"config.py declares knobs missing from the docs/tuning.md "
        f"table: {undocumented}")
    assert not undeclared, (
        f"docs/tuning.md documents knobs config.py does not declare: "
        f"{undeclared}")


def test_every_lint_rule_has_docs_row_and_test_fixture():
    """Rule-catalog drift guard: a rule with no docs row is
    undiscoverable; a rule with no positive fixture is unproven (it
    may never have fired even once). Checked against the RULES source
    so a rule added to lint.py cannot merge without both."""
    lint_src = _read(os.path.join(_REPO, "transmogrifai_tpu",
                                  "lint.py"))
    m = re.search(r"RULES\s*:[^=]*=\s*\{(.*?)\n\}", lint_src, re.S)
    assert m, "lint.py lost its RULES catalog literal"
    rules = set(re.findall(r'"(TMG\d{3})":', m.group(1)))
    assert len(rules) >= 50, (
        f"RULES ids not found by the pattern — did the catalog idiom "
        f"change? matched {len(rules)}")
    docs = _read(os.path.join(_REPO, "docs", "static-analysis.md"))
    undocumented = sorted(r for r in rules if r not in docs)
    assert not undocumented, (
        f"lint.py declares rules with no docs/static-analysis.md row: "
        f"{undocumented}")
    tested = set()
    for path in sorted(glob.glob(os.path.join(_REPO, "tests",
                                              "*.py"))):
        tested |= set(re.findall(r"TMG\d{3}", _read(path)))
    unproven = sorted(rules - tested)
    assert not unproven, (
        f"rules with no test fixture anywhere under tests/ (a rule "
        f"that has never demonstrably fired): {unproven}")
    phantom = sorted(r for r in tested - rules
                     if not r.startswith("TMG9"))
    assert not phantom, (
        f"tests reference rule ids the RULES catalog does not "
        f"declare: {phantom}")
