"""Mesh-sharded tree training (the PR 14 tentpole) + satellites.

The histogram build is a monoid fold, so the data-parallel shard_map
(+psum) path must be BIT-IDENTICAL to the single-device pass — asserted
here on exact-integer statistics (classification stats are weighted
counts: every float op is exact, so accumulation order cannot hide a
sharding bug). The degenerate 1-device mesh must resolve to the exact
pre-shard trace (the PR 6 discipline). Satellites: order-robust quantile
sketch, the Workflow warm probe, the planner's columnar-vs-rowwise
aggregation hint, and the TMG312 kernel-gating self-lint rule.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transmogrifai_tpu.models import _pallas_hist as ph
from transmogrifai_tpu.models import _treefit as TF
from transmogrifai_tpu.parallel.mesh import make_mesh, process_default_mesh

multi_device = pytest.mark.skipif(
    jax.device_count() < 2,
    reason="sharded-vs-single parity needs >1 device")


def _tree_data(rng, n=160, F=5, n_bin_cols=2):
    Xc = rng.normal(size=(n, F - n_bin_cols))
    Xb01 = rng.integers(0, 2, size=(n, n_bin_cols)).astype(np.float64)
    X = jnp.asarray(np.concatenate([Xc, Xb01], axis=1))
    bmask = np.array([False] * (F - n_bin_cols) + [True] * n_bin_cols)
    y = jnp.asarray((rng.normal(size=(n,)) + np.asarray(X)[:, 0] > 0)
                    .astype(np.float64))
    return X, y, jnp.ones((n,)), bmask


_FIT_KW = dict(task="classification", n_classes=2, n_trees=3, max_depth=4,
               n_bins=8)


def _fit(X, y, w, bmask, **over):
    kw = dict(_FIT_KW, min_instances=jnp.asarray(1.0),
              min_info_gain=jnp.asarray(0.0),
              num_trees_used=jnp.asarray(3),
              subsample_rate=jnp.asarray(1.0), binary_mask=bmask)
    kw.update(over)
    return TF.fit_forest(X, y, w, **kw)


# ---------------------------------------------------------------------------
# tentpole: sharded histogram build + trained-tree parity
# ---------------------------------------------------------------------------


@multi_device
def test_sharded_cumhist_bit_identical(rng):
    """shard_map partial histograms + psum == single-device kernel, bit
    for bit (exact-integer stats), for the generic, precomputed-bc and
    sparse01 kernel variants."""
    mesh = make_mesh()
    n, F, A, B, C = 128, 6, 4, 8, 3
    stats = jnp.asarray(rng.integers(0, 3, size=(n, C)).astype(np.float64))
    node = jnp.asarray(rng.integers(0, A + 1, size=(n,)), jnp.int32)
    XbT = jnp.asarray(rng.integers(0, B, size=(F, n)), jnp.int32)
    Xb01T = jnp.asarray(rng.integers(0, 2, size=(F, n)), jnp.int32)
    bc = ph.make_bc(XbT, B, jnp.float64)
    cases = [
        (XbT, B, dict()),
        (XbT, B, dict(bc=bc)),
        (Xb01T, 2, dict(sparse01=True)),
    ]
    for mat, nb, kw in cases:
        single = ph.cumhist(stats, node, mat, A, nb, **kw)
        sharded = TF._sharded_cumhist(mesh, stats, node, mat, A, nb, **kw)
        np.testing.assert_array_equal(np.asarray(single),
                                      np.asarray(sharded))


@multi_device
def test_sharded_tree_fit_bit_identical(rng, monkeypatch):
    """Trees grown under a multi-device tree-mesh scope (kernel forced,
    interpret) == trees grown unscoped == the XLA path — the acceptance
    bit-parity, covering both level drivers (scan + unrolled/sibling)."""
    monkeypatch.setenv("TMOG_PALLAS", "0")
    X, y, w, bmask = _tree_data(rng)
    base = _fit(X, y, w, bmask)

    monkeypatch.setenv("TMOG_PALLAS", "1")
    solo = _fit(X, y, w, bmask)
    before = ph.tree_kernel_stats()
    with TF.tree_mesh_scope(make_mesh()):
        sharded = _fit(X, y, w, bmask)
    after = ph.tree_kernel_stats()
    assert after["sharded_hist_traces"] > before["sharded_hist_traces"]
    assert after["sharded_route_traces"] > before["sharded_route_traces"]
    for k in ("feat", "thr", "leaf", "train_node"):
        np.testing.assert_array_equal(np.asarray(base[k]),
                                      np.asarray(solo[k]))
        np.testing.assert_array_equal(np.asarray(solo[k]),
                                      np.asarray(sharded[k]))

    # unrolled driver (static depth, sibling subtraction) under the mesh
    pre = TF.prepare_bins(X, 8, bmask)
    prebinned = (pre[0], pre[1], pre[2], False)
    solo_u = _fit(None, y, w, bmask, prebinned=prebinned, unroll=True)
    with TF.tree_mesh_scope(make_mesh()):
        shard_u = _fit(None, y, w, bmask, prebinned=prebinned,
                       unroll=True)
    for k in ("feat", "thr", "leaf"):
        np.testing.assert_array_equal(np.asarray(solo_u[k]),
                                      np.asarray(shard_u[k]))


@multi_device
def test_cv_sweep_sharded_matches_unsharded(rng, monkeypatch):
    """The whole fused CV path (shard_cv_inputs row sharding + the
    tree-mesh scope inside validate): winner, params and the per-fold
    metric matrix must match the unsharded sweep exactly."""
    from transmogrifai_tpu.models.trees import RandomForestFamily
    from transmogrifai_tpu.models.tuning import CrossValidation

    monkeypatch.setenv("TMOG_PALLAS", "1")
    n = 256
    X, y, _w, bmask = _tree_data(rng, n=n)
    X, y = np.asarray(X), np.asarray(y)

    def families():
        fam = RandomForestFamily(
            grid=[{"maxDepth": 3, "minInstancesPerNode": 1,
                   "minInfoGain": 0.0},
                  {"maxDepth": 3, "minInstancesPerNode": 8,
                   "minInfoGain": 0.01}],
            num_trees=3)
        fam.binary_mask = bmask
        return [fam]

    cv = CrossValidation(num_folds=2, metric_name="AuROC", task="binary",
                         seed=3)
    _f0, hp0, sum0 = cv.validate(families(), X, y, mesh=None)
    _f1, hp1, sum1 = cv.validate(families(), X, y, mesh=make_mesh())
    assert hp0 == hp1
    assert sum0.best.family_name == sum1.best.family_name
    m0 = {(r.grid_index): r.metric_values for r in sum0.results}
    m1 = {(r.grid_index): r.metric_values for r in sum1.results}
    assert m0 == m1


def test_degenerate_mesh_resolves_to_exact_path():
    """1-device mesh / None / False under the scope → no active tree
    mesh → the exact pre-shard trace (no shard_map anywhere)."""
    one = make_mesh(n_devices=1)
    with TF.tree_mesh_scope(one):
        assert TF.active_tree_mesh() is None
    with TF.tree_mesh_scope(None):
        assert TF.active_tree_mesh() is None
    with TF.tree_mesh_scope(False):
        assert TF.active_tree_mesh() is None
    if jax.device_count() > 1:
        with TF.tree_mesh_scope(make_mesh()):
            assert TF.active_tree_mesh() is not None
        assert TF.active_tree_mesh() is None      # restored


@multi_device
def test_device_prep_pads_rows_to_mesh_multiple(monkeypatch, rng):
    """Under a tree-mesh scope the kernel-path binned matrix must pad to
    a row count the data axis divides evenly (shard_map's even-sharding
    requirement), with zero-weight pad rows (the pad_rows discipline)."""
    from transmogrifai_tpu.models.trees import RandomForestFamily

    monkeypatch.setenv("TMOG_PALLAS", "1")
    mesh = make_mesh()
    d = int(mesh.shape["data"])
    fam = RandomForestFamily(num_trees=2)
    Xd = jnp.asarray(rng.normal(size=(300, 4)), jnp.float32)
    with TF.tree_mesh_scope(mesh):
        prep = fam.device_prep(Xd)
    n_pad = prep["XbT"].shape[1]
    assert n_pad % ph.ROW_ALIGN == 0 and n_pad % d == 0


def test_tree_estimator_fit_enters_mesh_scope(rng, monkeypatch):
    """Standalone tree estimator stages fit inside a tree-mesh scope on
    the workflow-resolved (process-default) mesh — tree fits scale with
    devices, not just the CV fold grid."""
    from transmogrifai_tpu import FeatureBuilder, Workflow
    from transmogrifai_tpu.models.trees import OpRandomForestClassifier
    from transmogrifai_tpu.ops.transmogrifier import transmogrify

    seen = []
    real = TF.tree_mesh_scope

    def spy(mesh):
        seen.append(mesh)
        return real(mesh)
    # fit_columns imports tree_mesh_scope from ._treefit at call time
    monkeypatch.setattr(TF, "tree_mesh_scope", spy)

    recs = [{"label": float(rng.integers(0, 2)),
             "x": float(rng.normal()), "z": float(rng.normal())}
            for _ in range(64)]
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    fz = FeatureBuilder.Real("z").from_column().as_predictor()
    vec = transmogrify([fx, fz])
    pred = label.transform_with(
        OpRandomForestClassifier(num_trees=2, max_depth=2), vec)
    (Workflow().set_input_records(recs)
     .set_result_features(pred).train())
    assert seen
    if jax.device_count() > 1:
        assert seen[-1] is process_default_mesh()


# ---------------------------------------------------------------------------
# PR 16 tentpole (b): feature-axis sharding over the mesh grid axis
# ---------------------------------------------------------------------------


@multi_device
def test_feature_sharded_fit_bit_identical(rng, monkeypatch):
    """Columns sharded over the mesh ``grid`` axis — each shard runs the
    kernel histogram + fused split-scan over its own feature block, the
    cross-shard winner merges by the kernel's own (score desc, idx asc)
    rule — must reproduce the single-shard forest BIT for bit, under
    both level drivers. The scan driver doubles as the regression test
    for the RNG shield (``_rng_replicated``): without it, GSPMD's
    backward sharding propagation into the non-partitionable threefry
    changes the bootstrap/feature-mask draws under a grid>1 mesh and
    the trees silently diverge."""
    from transmogrifai_tpu.parallel.mesh import feature_shard_mesh

    monkeypatch.setenv("TMOG_PALLAS", "1")
    X, y, w, bmask = _tree_data(rng, n=256, F=24, n_bin_cols=6)
    mesh = feature_shard_mesh(2)
    assert int(mesh.shape["grid"]) == 2

    solo = _fit(X, y, w, bmask)
    before = ph.tree_kernel_stats()
    with TF.tree_mesh_scope(mesh), TF.feature_shards_scope(2):
        sharded = _fit(X, y, w, bmask)
    after = ph.tree_kernel_stats()
    assert after["feature_shard_traces"] > before["feature_shard_traces"]
    for k in ("feat", "thr", "leaf", "train_node"):
        np.testing.assert_array_equal(np.asarray(solo[k]),
                                      np.asarray(sharded[k]))

    # unrolled driver (static depth, sibling subtraction) sharded too
    pre = TF.prepare_bins(X, 8, bmask)
    prebinned = (pre[0], pre[1], pre[2], False)
    solo_u = _fit(None, y, w, bmask, prebinned=prebinned, unroll=True)
    with TF.tree_mesh_scope(mesh), TF.feature_shards_scope(2):
        shard_u = _fit(None, y, w, bmask, prebinned=prebinned,
                       unroll=True)
    for k in ("feat", "thr", "leaf"):
        np.testing.assert_array_equal(np.asarray(solo_u[k]),
                                      np.asarray(shard_u[k]))


@multi_device
def test_feature_shards_degenerate_paths(rng, monkeypatch):
    """featureShards=1 (the default) under a grid mesh, and
    featureShards>1 WITHOUT a grid mesh, must both resolve to the exact
    current code path — zero feature-shard traces, identical trees."""
    from transmogrifai_tpu.parallel.mesh import feature_shard_mesh

    monkeypatch.setenv("TMOG_PALLAS", "1")
    X, y, w, bmask = _tree_data(rng)
    solo = _fit(X, y, w, bmask)

    t0 = ph.tree_kernel_stats()["feature_shard_traces"]
    # grid mesh but shards off (the default _FEATURE_SHARDS == 1)
    with TF.tree_mesh_scope(feature_shard_mesh(2)):
        a = _fit(X, y, w, bmask)
    # shards requested but the mesh has no grid axis to carry them
    with TF.tree_mesh_scope(make_mesh()), TF.feature_shards_scope(2):
        b = _fit(X, y, w, bmask)
    assert ph.tree_kernel_stats()["feature_shard_traces"] == t0
    for k in ("feat", "thr", "leaf", "train_node"):
        np.testing.assert_array_equal(np.asarray(solo[k]),
                                      np.asarray(a[k]))
        np.testing.assert_array_equal(np.asarray(solo[k]),
                                      np.asarray(b[k]))


def test_feature_shard_knob_validation():
    with pytest.raises(ValueError):
        TF.set_feature_shards(0)
    prev = TF.set_feature_shards(3)
    try:
        assert TF.active_feature_shards() == 3
    finally:
        TF.set_feature_shards(prev)
    assert TF.active_feature_shards() == prev


@multi_device
def test_feature_shard_mesh_shape():
    """feature_shard_mesh(G) slices the SAME device pool into
    data × grid — total devices unchanged, grid axis exactly G."""
    from transmogrifai_tpu.parallel.mesh import feature_shard_mesh
    mesh = feature_shard_mesh(2)
    assert int(mesh.shape["grid"]) == 2
    assert (int(mesh.shape["data"]) * int(mesh.shape["grid"])
            == jax.device_count())


# ---------------------------------------------------------------------------
# satellite: order-robust quantile sketch
# ---------------------------------------------------------------------------


def test_quantile_sketch_order_robust(monkeypatch, rng):
    """Sorted vs shuffled copies of the same column must sketch to the
    same edges (both are now uniform samples of the same values — the
    raw ``X[::stride]`` slice was a function of row order), and the
    sketch stays deterministic call to call."""
    monkeypatch.setattr(TF, "QUANTILE_SAMPLE_ROWS", 512)
    n = 4096
    vals = rng.gamma(2.0, 10.0, size=n)
    shuffled = jnp.asarray(vals[:, None])
    sorted_ = jnp.asarray(np.sort(vals)[:, None])
    e_shuf = np.asarray(TF.quantile_bin_edges(shuffled, 16))
    e_sort = np.asarray(TF.quantile_bin_edges(sorted_, 16))
    e_true = np.quantile(vals, np.linspace(0, 1, 17)[1:-1])
    # both are (different) uniform random samples → close to each other
    # and to the exact quantiles, with sampling noise only
    scale = float(np.std(vals))
    np.testing.assert_allclose(e_shuf[0], e_sort[0], atol=0.2 * scale)
    np.testing.assert_allclose(e_shuf[0], e_true, atol=0.2 * scale)
    # deterministic: same input → identical edges
    np.testing.assert_array_equal(
        e_shuf, np.asarray(TF.quantile_bin_edges(shuffled, 16)))
    # below the sampling threshold the exact path is untouched
    small = jnp.asarray(vals[:256][:, None])
    np.testing.assert_allclose(
        np.asarray(TF.quantile_bin_edges(small, 8))[0],
        np.quantile(vals[:256], np.linspace(0, 1, 9)[1:-1]), rtol=1e-12)


# ---------------------------------------------------------------------------
# satellite: Workflow warm probe
# ---------------------------------------------------------------------------


def test_workflow_train_warms_tree_kernel_probe(monkeypatch):
    """A DAG containing a tree family (selector) or a tree estimator
    must kick the async Pallas probe; a tree-free DAG must not."""
    from transmogrifai_tpu.models.selector import ModelSelector
    from transmogrifai_tpu.models.trees import (OpRandomForestClassifier,
                                                RandomForestFamily)
    from transmogrifai_tpu.workflow import Workflow

    calls = []
    monkeypatch.setattr(ph, "warm_probe_async",
                        lambda: calls.append(True))

    sel = ModelSelector(families=[RandomForestFamily(num_trees=2)])
    Workflow._warm_tree_probe([[sel]])
    assert calls == [True]

    est = OpRandomForestClassifier()
    Workflow._warm_tree_probe([[est]])
    assert calls == [True, True]

    Workflow._warm_tree_probe([[ModelSelector(families=[])]])
    assert calls == [True, True]               # no tree family → no probe


def test_resolve_mesh_assigns_tree_estimators():
    """Workflow._resolve_mesh threads the active mesh to tree estimator
    stages exactly like ModelSelector stages (auto-marked, re-resolved
    on retrain)."""
    from transmogrifai_tpu.models.trees import OpRandomForestClassifier
    from transmogrifai_tpu.workflow import Workflow

    wf = Workflow()
    est = OpRandomForestClassifier()
    wf._resolve_mesh([[est]])
    if jax.device_count() > 1:
        assert est.mesh is process_default_mesh()
        assert est._mesh_auto
    else:
        assert est.mesh is None
    wf.mesh = False
    wf._resolve_mesh([[est]])
    assert est.mesh is None                    # forced unsharded wins


# ---------------------------------------------------------------------------
# satellite: cost-db columnar-vs-rowwise aggregation hint
# ---------------------------------------------------------------------------


def test_aggregate_route_tier_needs_both_measurements(tmp_path):
    from transmogrifai_tpu import planner

    db = planner.CostDatabase(path=str(tmp_path / "db.json"))
    assert planner.aggregate_route_tier(db) is None
    db.record_stage("phase:temporal.route_aggregate", "columnar", 0.1,
                    10_000)
    assert planner.aggregate_route_tier(db) is None     # one-sided
    db.record_stage("phase:temporal.route_aggregate", "rowwise", 1.0,
                    10_000)
    assert planner.aggregate_route_tier(db) == "columnar"
    # flip the evidence hard enough to move the running mean
    for _ in range(64):
        db.record_stage("phase:temporal.route_aggregate", "columnar",
                        5.0, 1_000)
    assert planner.aggregate_route_tier(db) == "rowwise"


def test_route_aggregate_consults_hint_and_feeds_cost_db(rng):
    """auto + hint "rowwise" → the columnar engine stands down (tallied
    hint_fallbacks); auto + hint "columnar"/None → columnar serves and
    reports a phase observation the cost db drains."""
    from transmogrifai_tpu import FeatureBuilder, planner, temporal
    from transmogrifai_tpu.readers import (AggregateReader, CutOffTime,
                                           DataReaders)

    recs = [{"user": float(rng.integers(0, 5)),
             "ts": float(rng.uniform(0, 100)),
             "amount": float(rng.uniform(0, 10))} for _ in range(400)]
    tab = temporal.table_from_records(recs)
    key = temporal.field("user")
    ts = temporal.field("ts")
    feats = [FeatureBuilder.Real("s")
             .extract(temporal.field("amount"), "amount")
             .aggregate(None).as_predictor()]

    class _Src:
        def __init__(self):
            self.key_fn = key

        def read_records(self):
            return tab

    reader = AggregateReader(_Src(), ts, CutOffTime.no_cutoff(),
                             key_fn=key)
    prev = temporal.set_aggregate_tier_hint("rowwise")
    try:
        temporal._HINT_COUNT[0] = 0
        before = temporal.temporal_stats()
        out = temporal.route_aggregate(reader, tab, feats)
        after = temporal.temporal_stats()
        assert out is None
        assert after["hint_fallbacks"] == before["hint_fallbacks"] + 1
        # the hint is NOT a one-way ratchet: every HINT_PROBE_EVERY-th
        # pass still runs columnar so the measurement can flip back
        probed = [temporal.route_aggregate(reader, tab, feats)
                  for _ in range(temporal.HINT_PROBE_EVERY)]
        assert any(p is not None for p in probed)

        temporal.set_aggregate_tier_hint("columnar")
        out = temporal.route_aggregate(reader, tab, feats)
        assert out is not None
        # the timed columnar pass fed observe_phase → a drain lands it
        # in the db under phase:temporal.route_aggregate / columnar
        db = planner.CostDatabase()
        planner.drain_phase_observations(db)
        assert db.stage_cost("phase:temporal.route_aggregate",
                             "columnar") is not None
    finally:
        temporal.set_aggregate_tier_hint(prev)


def test_rowwise_fold_reports_phase_observation(rng):
    from transmogrifai_tpu import planner, temporal

    db = planner.CostDatabase()
    planner.drain_phase_observations(db)       # clear the buffer
    temporal.tally_rowwise(5_000, seconds=0.25)
    db2 = planner.CostDatabase()
    planner.drain_phase_observations(db2)
    assert db2.stage_cost("phase:temporal.route_aggregate",
                          "rowwise") == pytest.approx(0.05)


def test_uncontested_rowwise_passes_stay_out_of_cost_db(rng):
    """Rowwise timings feed the cost db ONLY when the columnar tier was
    a real option: row-list sources and structurally unroutable (opaque
    extractor) readers must not poison the pooled rowwise s/krow."""
    from transmogrifai_tpu import FeatureBuilder, planner, temporal
    from transmogrifai_tpu.readers import (AggregateReader, CutOffTime,
                                           DataReaders)

    key = temporal.field("user")
    ts = temporal.field("ts")
    recs = [{"user": float(i % 3), "ts": float(i), "amount": 1.0}
            for i in range(60)]
    planner.drain_phase_observations(planner.CostDatabase())   # clear

    # row-list source: columnar never an option → no observation
    feats = [FeatureBuilder.Real("s")
             .extract(temporal.field("amount"), "amount")
             .aggregate(None).as_predictor()]
    AggregateReader(DataReaders.simple.records(recs), ts,
                    CutOffTime.no_cutoff(),
                    key_fn=key).generate_store(feats)
    db = planner.CostDatabase()
    planner.drain_phase_observations(db)
    assert db.stage_cost("phase:temporal.route_aggregate",
                         "rowwise") is None

    # columnar TABLE source but opaque (callable) extractor: the route
    # raises TemporalError — structurally unroutable, NOT contested
    tab = temporal.table_from_records(recs)

    class _Src:
        def __init__(self):
            self.key_fn = key

        def read_records(self):
            return tab

    opaque = [FeatureBuilder.Real("o")
              .extract(lambda r: r["amount"], "amount")
              .aggregate(None).as_predictor()]
    AggregateReader(_Src(), ts, CutOffTime.no_cutoff(),
                    key_fn=key).generate_store(opaque)
    assert not temporal.last_route_contested()
    db = planner.CostDatabase()
    planner.drain_phase_observations(db)
    assert db.stage_cost("phase:temporal.route_aggregate",
                         "rowwise") is None


def test_tmg405_contradiction_advisory(tmp_path, monkeypatch):
    """An explicit aggregateColumnar knob that contradicts the measured
    tier surfaces as a TMG405 warning from the runner's plan step, and
    the measured hint is installed for the run."""
    from transmogrifai_tpu import lint, planner, temporal
    from transmogrifai_tpu.runner import OpParams, OpWorkflowRunner

    db_path = tmp_path / "cache" / "tmog_cost_db.json"
    db = planner.CostDatabase(path=str(db_path))
    db.record_stage("phase:temporal.route_aggregate", "columnar", 2.0,
                    1_000)
    db.record_stage("phase:temporal.route_aggregate", "rowwise", 0.2,
                    1_000)
    db.save()
    assert planner.aggregate_route_tier(db) == "rowwise"

    from transmogrifai_tpu import FeatureBuilder, Workflow
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import (
        BinaryClassificationModelSelector)
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    vec = transmogrify([fx])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()],
        splitter=None, seed=5)
    pred = label.transform_with(sel, vec)
    wf = Workflow().set_result_features(pred)
    runner = OpWorkflowRunner(workflow=wf)
    params = OpParams(custom_params={
        "compileCacheDir": str(tmp_path / "cache"),
        "aggregateColumnar": True})
    emitted = []
    monkeypatch.setattr(lint, "emit_findings",
                        lambda fs: emitted.extend(fs))
    prev_hint = temporal.aggregate_tier_hint()
    try:
        plan = runner._plan_step(params, workflow=wf)
        assert plan is not None
        assert temporal.aggregate_tier_hint() == "rowwise"
        assert any(f.rule == "TMG405" for f in emitted)
        assert plan.to_json()["tiers"]["aggregate"] == "rowwise"
    finally:
        temporal.set_aggregate_tier_hint(prev_hint)


# ---------------------------------------------------------------------------
# satellite: TMG312 self-lint fixtures
# ---------------------------------------------------------------------------


def _load_tmoglint():
    import importlib.util
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "tmoglint", os.path.join(repo, "tools", "tmoglint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tmg312_ungated_pallas_call_flagged_and_allowlisted():
    tm = _load_tmoglint()
    bad = ("from jax.experimental import pallas as pl\n"
           "out = pl.pallas_call(kern, out_shape=s)(x)\n")
    assert [f.rule for f in tm.lint_source(bad, "models/foo.py")] \
        == ["TMG312"]
    bad2 = ("import jax.experimental.pallas as pl\n"
            "out = pl.pallas_call(kern, out_shape=s)(x)\n")
    assert [f.rule for f in tm.lint_source(bad2, "scoring.py")] \
        == ["TMG312"]
    from_import = ("from jax.experimental.pallas import pallas_call\n"
                   "out = pallas_call(kern, out_shape=s)(x)\n")
    assert [f.rule for f in tm.lint_source(from_import, "x.py")] \
        == ["TMG312"]
    dotted = ("import jax.experimental.pallas\n"
              "out = jax.experimental.pallas.pallas_call(k, out_shape=s)"
              "(x)\n")
    assert [f.rule for f in tm.lint_source(dotted, "x.py")] == ["TMG312"]
    home = ("from jax.experimental import pallas as pl\n"
            "out = pl.pallas_call(kern, out_shape=s)(x)\n")
    assert tm.lint_source(home, "models/_pallas_hist.py") == []
    allowed = ("from jax.experimental import pallas as pl\n"
               "out = pl.pallas_call(k, out_shape=s)(x)"
               "  # lint: pallas — probe-gated at the callsite\n")
    assert tm.lint_source(allowed, "models/foo.py") == []
    tests_ok = ("from jax.experimental import pallas as pl\n"
                "out = pl.pallas_call(kern, out_shape=s)(x)\n")
    assert tm.lint_source(tests_ok, "tests/test_foo.py") == []


def test_tmg312_and_tmg405_in_rules_catalog():
    from transmogrifai_tpu import lint
    assert lint.RULES["TMG312"][0] == "error"
    assert lint.RULES["TMG405"][0] == "warning"


def test_tree_kernel_stats_shape():
    st = ph.tree_kernel_stats()
    for k in ("cumhist_traces", "sparse01_traces", "split_scan_traces",
              "sharded_hist_traces", "kernel_disables", "gate",
              "sparse01", "split_scan"):
        assert k in st
