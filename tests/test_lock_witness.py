"""Runtime lock-order witness tests (utils/locks.py — the dynamic half
of the TMG8xx concurrency pass).

The static analyzer proves the lock-order graph is acyclic *as
written*; the witness proves the order actually executed matches. The
intentional-inversion tests here exercise the raise path
deterministically; the chaos suites (tests/test_fleet.py,
tests/test_continual.py) arm the witness in record mode over the real
fleet/continual code paths and assert zero violations at teardown.
"""

import threading

import pytest

from transmogrifai_tpu.utils import locks


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends with a disarmed, empty witness."""
    locks.disarm()
    locks.reset()
    yield
    locks.disarm()
    locks.reset()


def _run(fn):
    """Run ``fn`` on a named thread, re-raising anything it raised."""
    box = []

    def wrapper():
        try:
            fn()
        except BaseException as e:   # pragma: no cover - re-raised
            box.append(e)

    t = threading.Thread(target=wrapper, name="witness-helper")
    t.start()
    t.join()
    if box:
        raise box[0]


def test_intentional_inversion_raises_with_both_stacks():
    """The acceptance test: an AB/BA inversion raises deterministically,
    and the message names both locks and both acquisition stacks."""
    a = locks.witness_lock("wA")
    b = locks.witness_lock("wB")
    locks.arm(raise_on_violation=True)
    _run(lambda: _nest(a, b))        # establishes wA -> wB on a thread
    with pytest.raises(locks.LockOrderViolation) as ei:
        _nest(b, a)                  # inverts it on this thread
    msg = str(ei.value)
    assert "'wA'" in msg and "'wB'" in msg
    # both acquisition sites are named (this file appears twice: once
    # for the current acquisition, once inside the recorded edge)
    assert msg.count("test_lock_witness.py") >= 2
    assert "witness-helper" in msg   # the earlier thread is named
    # the failed acquisition did not leak: both locks are free again
    assert not a.locked() and not b.locked()
    assert len(locks.violations()) == 1


def _nest(outer, inner):
    with outer:
        with inner:
            pass


def test_record_mode_collects_without_raising():
    a = locks.witness_lock("rA")
    b = locks.witness_lock("rB")
    locks.arm(raise_on_violation=False)
    _run(lambda: _nest(a, b))
    _nest(b, a)                      # inversion: recorded, not raised
    v = locks.violations()
    assert len(v) == 1 and "'rA'" in v[0] and "'rB'" in v[0]


def test_consistent_order_and_reentrancy_are_clean():
    a = locks.witness_lock("cA")
    r = locks.witness_lock("cR", reentrant=True)
    locks.arm(raise_on_violation=True)
    for _ in range(3):
        with a:
            with r:
                with r:              # reentrant re-entry: no edge
                    pass
    _run(lambda: _nest(a, r))        # same order on another thread
    assert locks.violations() == []


def test_flock_brackets_join_the_order_graph():
    """witness_acquire/witness_release let kernel flocks participate:
    an in-process lock taken in opposite orders around a flock region
    is an inversion like any other."""
    a = locks.witness_lock("fA")
    locks.arm(raise_on_violation=True)

    def flock_then_lock():
        locks.witness_acquire("flock.pointer")
        try:
            with a:
                pass
        finally:
            locks.witness_release("flock.pointer")

    _run(flock_then_lock)
    with pytest.raises(locks.LockOrderViolation):
        with a:
            locks.witness_acquire("flock.pointer")
    locks.witness_release("flock.pointer")   # tidy the thread stack
    assert len(locks.violations()) == 1


def test_disarmed_witness_costs_nothing_and_records_nothing():
    a = locks.witness_lock("dA")
    b = locks.witness_lock("dB")
    _run(lambda: _nest(a, b))
    _nest(b, a)                      # inversion, but witness is off
    assert locks.violations() == []


def test_armed_context_manager_restores_state():
    assert not locks.is_armed()
    with locks.armed(raise_on_violation=True):
        assert locks.is_armed()
    assert not locks.is_armed()


def test_witnessed_lock_api_shape():
    """The proxy honors the blocking/timeout acquire contract product
    code relies on (fleet probe paths use non-blocking acquires)."""
    a = locks.witness_lock("sA")
    assert a.acquire() is True
    assert a.locked()
    # a second non-blocking acquire on another thread fails cleanly
    got = []
    _run(lambda: got.append(a.acquire(blocking=False)))
    assert got == [False]
    a.release()
    assert not a.locked()
    assert "sA" in repr(a)
