"""Linear model + evaluator tests."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, column_from_values
from transmogrifai_tpu.columns import VectorColumn
from transmogrifai_tpu.evaluators import (BinaryClassificationEvaluator,
                                          Evaluators, metrics as M)
from transmogrifai_tpu.models import (OpLinearRegression,
                                      OpLogisticRegression, OpNaiveBayes)
from transmogrifai_tpu.types import feature_types as ft


def _make_clf_store(rng, n=400, d=5, n_classes=2):
    X = rng.normal(size=(n, d))
    w_true = rng.normal(size=(d, n_classes))
    logits = X @ w_true
    y = np.argmax(logits + rng.normal(scale=0.3, size=logits.shape), axis=1)
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y.astype(float)),
        "features": VectorColumn(ft.OPVector, X),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    return store, label, feats, X, y


def test_logistic_regression_binary(rng):
    store, label, feats, X, y = _make_clf_store(rng, n_classes=2)
    est = OpLogisticRegression()
    label.transform_with(est, feats)
    model = est.fit(store)
    pred, raw, prob = model.predict_arrays(X)
    acc = (pred == y).mean()
    assert acc > 0.9
    assert prob.shape == (len(y), 2)
    np.testing.assert_allclose(prob.sum(1), 1.0, atol=1e-6)
    # row path
    row = model.transform_row({"label": 1.0, "features": X[0]})
    assert row["prediction"] == pred[0]


def test_logistic_regression_regularization_shrinks(rng):
    store, label, feats, X, y = _make_clf_store(rng, n_classes=2)
    e0 = OpLogisticRegression(reg_param=0.0)
    label.transform_with(e0, feats)
    m0 = e0.fit(store)
    e1 = OpLogisticRegression(reg_param=1.0, elastic_net_param=0.5)
    label.transform_with(e1, feats)
    m1 = e1.fit(store)
    assert np.abs(m1.coefficients).sum() < np.abs(m0.coefficients).sum()


def test_logistic_regression_multiclass(rng):
    store, label, feats, X, y = _make_clf_store(rng, n_classes=3)
    est = OpLogisticRegression()
    label.transform_with(est, feats)
    model = est.fit(store)
    pred, raw, prob = model.predict_arrays(X)
    assert prob.shape == (len(y), 3)
    assert (pred == y).mean() > 0.85


def test_linear_regression(rng):
    n, d = 300, 4
    X = rng.normal(size=(n, d))
    coef = np.array([1.0, -2.0, 0.5, 3.0])
    y = X @ coef + 0.7 + rng.normal(scale=0.01, size=n)
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "features": VectorColumn(ft.OPVector, X),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    est = OpLinearRegression()
    label.transform_with(est, feats)
    model = est.fit(store)
    np.testing.assert_allclose(model.coefficients, coef, atol=0.02)
    assert abs(model.intercept - 0.7) < 0.02


def test_naive_bayes(rng):
    n = 300
    y = rng.integers(0, 2, size=n)
    # multinomial NB discriminates on feature *proportions*: give each class
    # a different profile over the 3 count features
    lam = np.where(y[:, None] == 1, [5.0, 1.0, 1.0], [1.0, 1.0, 5.0])
    X = rng.poisson(lam=lam).astype(float)
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y.astype(float)),
        "features": VectorColumn(ft.OPVector, X),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    est = OpNaiveBayes()
    label.transform_with(est, feats)
    model = est.fit(store)
    pred, _, prob = model.predict_arrays(X)
    assert (pred == y).mean() > 0.8


def test_binary_metrics_known_values():
    y = np.array([1, 1, 0, 0])
    scores = np.array([0.9, 0.6, 0.4, 0.1])
    pred = (scores > 0.5).astype(float)
    m = M.binary_metrics(y, pred, scores)
    assert m["AuROC"] == 1.0  # perfect ranking
    assert m["Precision"] == 1.0 and m["Recall"] == 1.0 and m["Error"] == 0.0
    # worst ranking
    m2 = M.binary_metrics(y, 1 - pred, 1 - scores)
    assert m2["AuROC"] == 0.0


def test_auroc_matches_sklearn_formula(rng):
    # rank-statistic cross-check on random data
    y = rng.integers(0, 2, size=200).astype(float)
    s = rng.random(200)
    pos = s[y == 1]
    neg = s[y == 0]
    # Mann-Whitney U
    expected = np.mean([(p > q) + 0.5 * (p == q) for p in pos for q in neg])
    assert abs(M.auroc(y, s) - expected) < 1e-9


def test_multiclass_and_regression_metrics():
    y = np.array([0, 1, 2, 1])
    p = np.array([0, 1, 1, 1])
    m = M.multiclass_metrics(y, p)
    assert m["Error"] == 0.25
    r = M.regression_metrics(np.array([1.0, 2.0]), np.array([1.5, 2.5]))
    assert abs(r["RootMeanSquaredError"] - 0.5) < 1e-12
    assert abs(r["MeanAbsoluteError"] - 0.5) < 1e-12


def test_evaluator_factory():
    ev = Evaluators.BinaryClassification.auPR()
    assert ev.metric_name == "AuPR" and ev.is_larger_better
    ev2 = Evaluators.Regression.rmse()
    assert ev2.metric_name == "RootMeanSquaredError"
    assert not ev2.is_larger_better


def test_predict_host_matches_device(monkeypatch):
    """The slow-link host predict mirrors the device math: force the
    bandwidth gate low and compare the triples on a big-enough matrix."""
    import numpy as np
    from transmogrifai_tpu.models.linear import (LogisticRegressionModel,
                                                 LinearRegressionModel,
                                                 NaiveBayesModel)
    from transmogrifai_tpu import workflow as wf

    rng = np.random.default_rng(0)
    n, d = 4000, 520                     # n*d >= 2e6 engages the gate
    X = rng.normal(size=(n, d)).astype(np.float32)

    lr = LogisticRegressionModel(rng.normal(size=d), 0.3, 2)
    mlr = LogisticRegressionModel(rng.normal(size=(3, d)),
                                  rng.normal(size=3), 3)
    lin = LinearRegressionModel(rng.normal(size=d), -0.7)
    nb = NaiveBayesModel(np.log([0.2, 0.8]),
                         -np.abs(rng.normal(size=(2, d))))

    device = [m.predict_arrays(X) for m in (lr, mlr, lin, nb)]
    monkeypatch.setattr(wf, "_DEVICE_BW_MBPS", 1.0)   # force slow link
    host = [m.predict_arrays(X) for m in (lr, mlr, lin, nb)]
    for dev, hst in zip(device, host):
        for a, b in zip(dev, hst):
            np.testing.assert_allclose(b, a, rtol=1e-4, atol=1e-5)
