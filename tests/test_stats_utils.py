"""utils.stats — the OpStatistics analog (OpStatistics.scala:71-346)."""
import numpy as np

from transmogrifai_tpu.utils import stats


def test_moments_matches_numpy(rng):
    X = rng.normal(size=(200, 5))
    y = X[:, 0] * 0.5 + rng.normal(size=200)
    mean, var, corr_label, corr, zmin, zmax = stats.moments(
        X, y, label_corr_only=False)
    Z = np.column_stack([X, y])
    np.testing.assert_allclose(np.asarray(mean), Z.mean(0), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(var), Z.var(0, ddof=1), rtol=1e-9)
    ref_corr = np.corrcoef(Z, rowvar=False)
    np.testing.assert_allclose(np.asarray(corr), ref_corr, rtol=1e-8)
    np.testing.assert_allclose(np.asarray(corr_label), ref_corr[:-1, -1],
                               rtol=1e-8)
    np.testing.assert_allclose(np.asarray(zmin), Z.min(0))
    np.testing.assert_allclose(np.asarray(zmax), Z.max(0))


def test_contingency_and_cramers_v():
    # textbook 2x2 table: perfect association → V = 1
    cont = np.array([[30.0, 0.0], [0.0, 20.0]])
    v, support, confidence = stats.cramers_v_stats(cont)
    assert abs(v - 1.0) < 1e-12
    np.testing.assert_allclose(support, [0.6, 0.4])
    np.testing.assert_allclose(confidence, [1.0, 1.0])
    # independence → V = 0, MI = 0
    indep = np.outer([0.5, 0.5], [30.0, 20.0])
    v0, _, _ = stats.cramers_v_stats(indep)
    assert abs(v0) < 1e-12
    _pmi, mi = stats.pmi_mutual_info(indep)
    assert abs(mi) < 1e-12
    # perfect association: MI = label entropy (0.6/0.4 split → ~0.971 bits)
    _pmi, mi1 = stats.pmi_mutual_info(cont)
    ent = -(0.6 * np.log2(0.6) + 0.4 * np.log2(0.4))
    assert abs(mi1 - ent) < 1e-12


def test_average_ranks_ties():
    v = np.array([3.0, 1.0, 3.0, 2.0])
    np.testing.assert_allclose(stats.average_ranks(v), [3.5, 1.0, 3.5, 2.0])


def test_spearman_monotone_invariance(rng):
    # Spearman is invariant under monotone transforms; Pearson is not.
    x = rng.normal(size=300)
    y = np.exp(2.0 * x)           # monotone in x, wildly non-linear
    X = x[:, None]
    corr_label, _ = stats.spearman_with_label(X, y)
    assert abs(float(corr_label[0]) - 1.0) < 1e-9


def test_moments_host_matches_device_kernel():
    """moments_host (the slow-link host-BLAS twin) agrees with the jitted
    device kernel to f32 accuracy on identical inputs."""
    import numpy as np
    from transmogrifai_tpu.utils.stats import moments, moments_host
    rng = np.random.default_rng(3)
    X = rng.normal(size=(500, 7)).astype(np.float32)
    X[:, 3] = (X[:, 0] > 0)          # binary col
    y = (X[:, 0] + 0.1 * rng.normal(size=500) > 0).astype(np.float64)
    m_dev = [np.asarray(v) for v in moments(X.astype(np.float64), y)]
    m_host = list(moments_host(X, y))
    for dev, host, tol in zip(m_dev, m_host,
                              (1e-6, 1e-4, 1e-4, 1e-4, 1e-6, 1e-6)):
        if dev is None or host is None:
            assert dev is None and host is None
            continue
        np.testing.assert_allclose(np.asarray(host), dev, rtol=tol,
                                   atol=1e-5)
