"""Reader depth tests: Parquet/Avro ingestion, joined-aggregate windows,
time filters, streaming scoring (DataReadersTest / JoinedDataReaderTest
analogs)."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, Workflow
from transmogrifai_tpu.readers import (AvroReader, DataReaders,
                                       JoinedAggregateDataReader,
                                       ParquetReader, TimeBasedFilter,
                                       CutOffTime, read_avro_records,
                                       stream_score)
from transmogrifai_tpu.types import feature_types as ft

PARQUET = "/root/reference/test-data/PassengerDataAll.parquet"
AVRO = "/root/reference/test-data/PassengerDataAll.avro"
CSV = "/root/reference/test-data/PassengerDataAll.csv"


def test_avro_decoder_matches_csv_rows():
    recs = read_avro_records(AVRO)
    assert len(recs) == 891
    r0 = recs[0]
    assert r0["Name"] == "Braund, Mr. Owen Harris"
    assert r0["Age"] == 22.0 and r0["Cabin"] is None


def test_parquet_and_avro_readers_agree():
    pq = ParquetReader(PARQUET).read_records()
    av = AvroReader(AVRO).read_records()
    assert len(pq) == len(av) == 891
    for k in ("Name", "Sex", "Pclass"):
        assert pq[0][k] == av[0][k]
    # nullable float → None in both
    assert pq[5].get("Age") == av[5].get("Age")


def test_titanic_runs_off_parquet(rng):
    """The flagship workflow trains from a parquet file (VERDICT r1 #9)."""
    import sys
    sys.path.insert(0, "examples")
    from titanic import build_features

    survived, checked = build_features(with_sanity_check=False)
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import BinaryClassificationModelSelector
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None)
    pred = survived.transform_with(selector, checked)

    # parquet columns are capitalized; remap to the example's schema
    records = ParquetReader(PARQUET).read_records()
    remap = {"PassengerId": "id", "Survived": "survived", "Pclass": "pClass",
             "Name": "name", "Sex": "sex", "Age": "age", "SibSp": "sibSp",
             "Parch": "parCh", "Ticket": "ticket", "Fare": "fare",
             "Cabin": "cabin", "Embarked": "embarked"}
    records = [{remap[k]: v for k, v in r.items()} for r in records]
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())
    scores = model.score(records)
    assert scores.n_rows == 891


def test_joined_aggregate_reader_windows():
    """Post-join windowed aggregation (Conditional-Aggregation.md flow):
    left = profiles, right = events; events aggregate within the window
    before the cutoff."""
    profiles = [{"id": "a", "region": "west"}, {"id": "b", "region": "east"}]
    events = [
        {"id": "a", "ts": 100, "spend": 1.0},
        {"id": "a", "ts": 500, "spend": 2.0},
        {"id": "a", "ts": 900, "spend": 100.0},   # after cutoff → excluded
        {"id": "b", "ts": 650, "spend": 5.0},
    ]
    left = DataReaders.simple.records(profiles, key_fn=lambda r: r["id"])
    right = DataReaders.simple.records(events, key_fn=lambda r: r["id"])
    # join produces per-event records carrying the profile fields
    reader = JoinedAggregateDataReader(
        right, left, timestamp_fn=lambda r: r["ts"],
        cutoff=CutOffTime(800))

    from transmogrifai_tpu.utils.aggregators import SumAggregator
    region = FeatureBuilder.PickList("region").from_column().as_predictor()
    spend = (FeatureBuilder.Real("spend").from_column()
             .aggregate(SumAggregator()).as_predictor())
    store = reader.generate_store([region, spend])
    assert store.n_rows == 2
    vals = {store["region"].get_raw(i): store["spend"].get_raw(i)
            for i in range(2)}
    assert vals["west"] == pytest.approx(3.0)     # 1 + 2, cutoff excluded
    assert vals["east"] == pytest.approx(5.0)


def test_aggregate_cutoff_boundary_ts_equal_cutoff():
    """The pinned cutoff semantics (docs/readers.md): predictors fold
    ts < cutoff, responses fold ts > cutoff — STRICTLY after, so the
    event exactly AT the cutoff lands in NEITHER fold (the docstring
    said 'strictly after' while the code kept ts == cutoff in the
    response; the code now matches the contract)."""
    from transmogrifai_tpu.utils.aggregators import (LogicalOrAggregator,
                                                     SumAggregator)
    records = [
        {"id": "u", "ts": 99, "x": 1.0, "buy": 0},
        {"id": "u", "ts": 100, "x": 10.0, "buy": 1},    # AT the cutoff
        {"id": "u", "ts": 101, "x": 100.0, "buy": 0},
    ]
    before = (FeatureBuilder.Real("x").from_column()
              .aggregate(SumAggregator()).as_predictor())
    after = (FeatureBuilder.Real("after")
             .extract(lambda r: r["x"], "x")
             .aggregate(SumAggregator()).as_response())
    bought = (FeatureBuilder.Binary("bought")
              .extract(lambda r: bool(r["buy"]), "buy")
              .aggregate(LogicalOrAggregator()).as_response())
    reader = DataReaders.aggregate.records(
        records, timestamp_fn=lambda r: r["ts"],
        cutoff=CutOffTime.at(100), key_fn=lambda r: r["id"])
    store = reader.generate_store([before, after, bought])
    assert store["x"].get_raw(0) == 1.0         # ts=100 NOT a predictor
    assert store["after"].get_raw(0) == 100.0   # ts=100 NOT a response
    assert store["bought"].get_raw(0) is False  # the cutoff event itself
    # windowed predictor shares the same exclusive upper bound
    recent = (FeatureBuilder.Real("recent")
              .extract(lambda r: r["x"], "x")
              .aggregate(SumAggregator()).window(1).as_predictor())
    store2 = reader.generate_store([recent])
    assert store2["recent"].get_raw(0) == 1.0   # [99, 100) keeps ts=99


def test_conditional_reader_edge_cases():
    """ConditionalReader corners: a key with no condition-matching
    record under drop_if_no_condition True/False, a key whose group is
    empty after cutoff filtering on one side, and per-key cutoffs that
    genuinely differ across keys."""
    from transmogrifai_tpu.utils.aggregators import SumAggregator
    records = [
        # key a: buys at 200 → cutoff 200; pre-events at 100, post at 300
        {"id": "a", "ts": 100, "x": 1.0, "buy": 0},
        {"id": "a", "ts": 200, "x": 2.0, "buy": 1},
        {"id": "a", "ts": 300, "x": 4.0, "buy": 0},
        # key b: never buys
        {"id": "b", "ts": 150, "x": 8.0, "buy": 0},
        # key c: buys IMMEDIATELY (first event) → empty predictor fold
        {"id": "c", "ts": 50, "x": 16.0, "buy": 1},
        {"id": "c", "ts": 60, "x": 32.0, "buy": 0},
    ]
    before = (FeatureBuilder.Real("x").from_column()
              .aggregate(SumAggregator()).as_predictor())
    after = (FeatureBuilder.Real("after")
             .extract(lambda r: r["x"], "x")
             .aggregate(SumAggregator()).as_response())

    def build(drop):
        return DataReaders.conditional.records(
            records, timestamp_fn=lambda r: r["ts"],
            condition_fn=lambda r: r["buy"] == 1,
            key_fn=lambda r: r["id"], drop_if_no_condition=drop)

    # drop=True: key b (no condition event) is dropped entirely
    store = build(True).generate_store([before, after])
    assert store.n_rows == 2
    rows = {tuple(store[n].get_raw(i) for n in ("x", "after"))
            for i in range(2)}
    # a: predictors before 200 = 1.0; responses strictly after = 4.0
    # c: empty predictor fold (cutoff at its first event) → None;
    #    response = 32.0
    assert rows == {(1.0, 4.0), (None, 32.0)}

    # drop=False: key b stays; with no cutoff EVERYTHING folds into
    # both sides (the row-wise no-cutoff contract)
    store = build(False).generate_store([before, after])
    assert store.n_rows == 3
    by_key = {}
    # keys sort a, b, c
    for i, k in enumerate(("a", "b", "c")):
        by_key[k] = (store["x"].get_raw(i), store["after"].get_raw(i))
    assert by_key["a"] == (1.0, 4.0)
    assert by_key["b"] == (8.0, 8.0)      # no cutoff: folds both sides
    assert by_key["c"] == (None, 32.0)    # per-key cutoff differs from a


def test_time_based_filter():
    tf = TimeBasedFilter(timestamp_fn=lambda r: r["ts"], cutoff_ms=1000,
                         duration_ms=500)
    assert tf.keep({"ts": 700})
    assert not tf.keep({"ts": 1200})    # after cutoff
    assert not tf.keep({"ts": 300})     # before window


def test_stream_score(rng):
    n = 120
    y = rng.integers(0, 2, n).astype(float)
    x = rng.normal(size=n) + y
    records = [{"label": float(y[i]), "x": float(x[i])} for i in range(n)]
    from transmogrifai_tpu.dsl import transmogrify
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import BinaryClassificationModelSelector
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None)
    pred = label.transform_with(selector, transmogrify([fx]))
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())

    batches = [records[i:i + 50] for i in range(0, n, 50)]
    total = 0
    for scored in stream_score(model, batches):
        assert pred.name in scored.names()
        total += scored.n_rows
    assert total == n


def test_aggregator_defaults_cover_all_types():
    """aggregator_of mirrors MonoidAggregatorDefaults.aggregatorOf: every
    registered feature type has a default monoid."""
    from transmogrifai_tpu.types.feature_types import FEATURE_TYPE_REGISTRY
    from transmogrifai_tpu.utils.aggregators import (
        ConcatTextAggregator, LogicalOrAggregator, ModeAggregator,
        SumAggregator, aggregator_of)
    from transmogrifai_tpu.types import feature_types as ft

    for t in FEATURE_TYPE_REGISTRY.values():
        assert aggregator_of(t) is not None
    assert isinstance(aggregator_of(ft.Real), SumAggregator)
    assert isinstance(aggregator_of(ft.Binary), LogicalOrAggregator)
    assert isinstance(aggregator_of(ft.PickList), ModeAggregator)
    assert isinstance(aggregator_of(ft.Text), ConcatTextAggregator)

    assert aggregator_of(ft.Real).fold([1.0, None, 2.5]) == 3.5
    assert aggregator_of(ft.PickList).fold(["a", "b", "a"]) == "a"
    assert aggregator_of(ft.MultiPickList).fold([{"a"}, {"b"}]) == {"a", "b"}
    assert aggregator_of(ft.RealMap).fold(
        [{"k": 1.0}, {"k": 2.0, "j": 5.0}]) == {"k": 3.0, "j": 5.0}
    mid = aggregator_of(ft.Geolocation).fold([(0.0, 0.0, 1.0),
                                              (0.0, 90.0, 2.0)])
    assert mid[1] == pytest.approx(45.0)


def test_conditional_dataprep_example():
    """The conditional-aggregation walkthrough produces leak-free per-user
    rows (Conditional-Aggregation.md flow)."""
    import os
    import sys
    examples = os.path.join(os.path.dirname(__file__), os.pardir, "examples")
    sys.path.insert(0, examples)
    try:
        from dataprep import run
    finally:
        sys.path.remove(examples)
    store, rows = run()
    assert store.n_rows == 2             # user b dropped (never purchased)
    by_minutes = {r["minutes"] for r in rows.values()}
    assert 10.0 in by_minutes            # user a: 3 + 7 before first buy


def test_directory_stream_reader(tmp_path):
    """DirectoryStreamReader (StreamingReaders analog): each new file is
    one micro-batch; new_files_only skips the backlog; avro + csv route
    by extension."""
    from transmogrifai_tpu.readers import DirectoryStreamReader
    from transmogrifai_tpu.readers.avro import write_avro_records

    d = tmp_path / "incoming"
    d.mkdir()
    (d / "a.csv").write_text("x,y\n1,one\n2,two\n")
    write_avro_records(str(d / "b.avro"),
                       [{"x": 3, "y": "three"}, {"x": 4, "y": None}])

    r = DirectoryStreamReader(str(d), pattern="*", settle_s=0.0)
    batches = list(r.stream(max_batches=2))
    assert len(batches) == 2             # one batch per file, sorted order
    assert batches[0][0]["y"] == "one"   # a.csv first
    assert batches[1][0] == {"x": 3, "y": "three"}
    # nothing new -> poll_once drains empty
    assert r.poll_once() == []
    # a THIRD file appears mid-stream and is picked up
    (d / "c.csv").write_text("x,y\n9,nine\n")
    more = list(r.stream(max_batches=1, timeout_s=5.0))
    assert more == [[{"x": "9", "y": "nine"}]]

    # new_files_only: the existing backlog is invisible
    r2 = DirectoryStreamReader(str(d), new_files_only=True, settle_s=0.0)
    assert r2.poll_once() == []
    (d / "d.csv").write_text("x,y\n5,five\n")
    assert r2.read_records() == [{"x": "5", "y": "five"}]


def test_directory_stream_reader_error_paths(tmp_path, caplog):
    """Corrupt files are logged + skipped (not retried forever, not
    stream-fatal); files behind them still flow; unknown extensions
    raise a configuration error."""
    import logging

    import pytest

    from transmogrifai_tpu.readers import DirectoryStreamReader

    d = tmp_path / "in"
    d.mkdir()
    (d / "a.avro").write_bytes(b"not an avro container at all")
    (d / "b.csv").write_text("x\n1\n")
    r = DirectoryStreamReader(str(d), pattern="*", settle_s=0.0)
    with caplog.at_level(logging.WARNING):
        batches = list(r.stream(max_batches=1, timeout_s=3.0))
    assert batches == [[{"x": "1"}]]          # corrupt a.avro skipped
    assert any("quarantining unreadable" in rec.message
               for rec in caplog.records)
    assert r.poll_once() == []                # corrupt file not retried

    (d / "c.weird").write_text("zzz")
    r2 = DirectoryStreamReader(str(d), new_files_only=False, settle_s=0.0)
    with pytest.raises(ValueError, match="no reader"):
        with caplog.at_level(logging.WARNING):
            list(r2.stream(max_batches=5, timeout_s=1.0))


def test_directory_stream_reader_multi_pass(tmp_path):
    """``stream(passes=N)`` (PR 16): N bounded full scans of the
    directory — :meth:`rescan` runs between them, so multi-pass
    out-of-core training re-reads the same files from the same reader
    instead of reconstructing it; the stream ENDS after pass N instead
    of idle-waiting. Serial and parallel consumers agree."""
    from transmogrifai_tpu.readers import DirectoryStreamReader
    from transmogrifai_tpu.readers.avro import write_avro_records

    d = tmp_path / "in"
    d.mkdir()
    for i in range(3):
        write_avro_records(str(d / f"p{i}.avro"),
                           [{"v": float(i * 10 + j)} for j in range(4)])

    r = DirectoryStreamReader(str(d), settle_s=0.0)
    one = [[dict(x) for x in b] for b in r.stream(passes=1)]
    assert [b[0]["v"] for b in one] == [0.0, 10.0, 20.0]

    # explicit rescan re-offers exactly the delivered files
    assert r.rescan() == 3
    again = [[dict(x) for x in b] for b in r.stream(passes=1)]
    assert again == one

    # passes=2 on a fresh reader = the same two scans, one stream call
    r2 = DirectoryStreamReader(str(d), settle_s=0.0)
    two = [[dict(x) for x in b] for b in r2.stream(passes=2)]
    assert two == one + one

    # parallel decode keeps the per-pass order and the pass boundary
    r3 = DirectoryStreamReader(str(d), settle_s=0.0)
    par = [[dict(x) for x in b] for b in r3.stream(passes=2, workers=2)]
    assert par == two

    with pytest.raises(ValueError, match="passes"):
        list(DirectoryStreamReader(str(d), settle_s=0.0).stream(passes=0))


def test_multi_pass_quarantine_counted_once(tmp_path, caplog):
    """A poison file is quarantined (and counted) exactly ONCE across
    passes — rescan re-offers only DELIVERED files — and
    ``new_files_only`` pre-seeded files stay suppressed after rescan
    (they were never delivered either)."""
    import logging

    from transmogrifai_tpu import resilience
    from transmogrifai_tpu.readers import DirectoryStreamReader
    from transmogrifai_tpu.readers.avro import write_avro_records

    d = tmp_path / "in"
    d.mkdir()
    (d / "bad.avro").write_bytes(b"not an avro container")
    write_avro_records(str(d / "good.avro"), [{"v": 1.0}])

    before = resilience.resilience_stats()["quarantined_files"]
    r = DirectoryStreamReader(str(d), settle_s=0.0)
    with caplog.at_level(logging.WARNING):
        batches = [[dict(x) for x in b] for b in r.stream(passes=3)]
    assert batches == [[{"v": 1.0}]] * 3
    assert (resilience.resilience_stats()["quarantined_files"]
            == before + 1)

    # pre-seeded (new_files_only) files stay invisible across rescans
    r2 = DirectoryStreamReader(str(d), new_files_only=True, settle_s=0.0)
    assert list(r2.stream(passes=2)) == []
    write_avro_records(str(d / "later.avro"), [{"v": 2.0}])
    got = [[dict(x) for x in b] for b in r2.stream(passes=2)]
    assert got == [[{"v": 2.0}]] * 2


def test_stream_fit_train_matches_materialized(tmp_path):
    """PR 16 tentpole (a): a streamed train over a directory whose rows
    fit the sample budget is BIT-IDENTICAL to materializing — same
    fitted stage states, same scores — because the bounded subsample is
    then the whole stream in order and the host fitstats tier computes
    the exact same expressions."""
    import numpy as np

    from transmogrifai_tpu import FeatureBuilder, Workflow
    from transmogrifai_tpu import workflow as wfmod
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.readers import DirectoryStreamReader
    from transmogrifai_tpu.readers.avro import write_avro_records

    rng = np.random.default_rng(16)
    d = tmp_path / "in"
    d.mkdir()
    recs = [{"x0": float(rng.normal()), "x1": float(rng.normal() * 10)}
            for _ in range(240)]
    for i in range(3):
        write_avro_records(str(d / f"p{i}.avro"), recs[i * 80:(i + 1) * 80])

    def fit(stream):
        feats = [FeatureBuilder.Real(nm).from_column().as_predictor()
                 for nm in ("x0", "x1")]
        vec = transmogrify(feats)
        wf = Workflow().set_result_features(vec)
        wf.set_reader(DirectoryStreamReader(str(d), settle_s=0.0))
        prev = wfmod.set_stream_fit(stream=stream, passes=2,
                                    sample_rows=100_000)
        try:
            model = wf.train()
        finally:
            wfmod.set_stream_fit(**prev)
        return wf, model

    wf_m, mat = fit(stream=False)
    wf_s, st = fit(stream=True)
    assert wf_m._stream_state is None
    # 240 rows is below the fusion floor: the tiny-stream path behaves
    # exactly like materializing (no injected stream state either)
    assert wf_s._stream_state is None
    assert st.train_rows == mat.train_rows == len(recs)
    # each fit() builds its own graph (fresh uids) — compare the fitted
    # states positionally, in fit order
    assert len(mat.fitted_stages) == len(st.fitted_stages) > 0
    for fm, fs in zip(mat.fitted_stages.values(),
                      st.fitted_stages.values()):
        assert repr(sorted(fm.get_model_state().items())) \
            == repr(sorted(fs.get_model_state().items()))
    sm, ss = mat.score(recs), st.score(recs)
    # result column names carry the graph's uids too: positional again
    for nm_a, nm_b in zip(sm.names(), ss.names()):
        a, b = sm[nm_a], ss[nm_b]
        if hasattr(a, "values"):
            np.testing.assert_array_equal(a.values, b.values)


def test_stream_fit_bounded_sample_and_auto_mode(tmp_path):
    """The sample budget BOUNDS the materialized working set: a stream
    past the budget trains on exactly ``sample_rows`` rows. And the
    tri-state auto mode streams for directory readers by default but
    defers to a planner ``materialize`` ingest hint."""
    import numpy as np

    from transmogrifai_tpu import FeatureBuilder, Workflow
    from transmogrifai_tpu import workflow as wfmod
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.readers import DirectoryStreamReader
    from transmogrifai_tpu.readers.avro import write_avro_records

    d = tmp_path / "in"
    d.mkdir()
    write_avro_records(str(d / "p0.avro"),
                       [{"x0": float(i)} for i in range(500)])

    vec = transmogrify([FeatureBuilder.Real("x0").from_column()
                        .as_predictor()])
    wf = Workflow().set_result_features(vec)
    wf.set_reader(DirectoryStreamReader(str(d), settle_s=0.0))
    prev = wfmod.set_stream_fit(stream=True, passes=2, sample_rows=64)
    try:
        model = wf.train()
    finally:
        wfmod.set_stream_fit(**prev)
    assert model.train_rows == 64

    # auto mode: directory reader => stream, unless the measured ingest
    # hint says materializing is cheaper; a declared RSS cap outranks it
    prev = wfmod.set_stream_fit(stream=None, ingest_hint=None)
    try:
        assert wf._use_stream_fit() is True
        wfmod.set_stream_fit(ingest_hint="materialize")
        assert wf._use_stream_fit() is False
        wfmod.set_stream_fit(rss_cap_mb=256)
        assert wf._use_stream_fit() is True
    finally:
        wfmod.set_stream_fit(**prev)
    wf2 = Workflow().set_result_features(vec).set_input_records(
        [{"x0": 1.0}])
    assert wf2._use_stream_fit() is False


def _write_mixed_batch_dir(d, n_files=12, rows=7):
    """A directory of alternating avro/csv micro-batch files with
    distinct per-file payloads (order mistakes can't cancel out)."""
    from transmogrifai_tpu.readers.avro import write_avro_records

    for i in range(n_files):
        recs = [{"x": i * 100 + r, "y": f"f{i}r{r}"} for r in range(rows)]
        if i % 2 == 0:
            write_avro_records(str(d / f"b{i:03d}.avro"), recs)
        else:
            lines = ["x,y"] + [f"{r['x']},{r['y']}" for r in recs]
            (d / f"b{i:03d}.csv").write_text("\n".join(lines) + "\n")


def test_columnar_avro_decode_is_bit_identical_to_python(tmp_path):
    """The vectorized decode (fixed-stride numpy fast path) yields the
    SAME dicts as the per-record Python decoder — doubles bit-exact,
    booleans, all-null union fields as None — and multi-block
    containers merge."""
    from transmogrifai_tpu.readers.avro import (AvroWriter, ColumnarRecords,
                                                infer_avro_schema,
                                                read_avro_table,
                                                write_avro_records)

    rng = np.random.default_rng(3)
    recs = [{"label": float(i % 2), "flag": bool(i % 3 == 0),
             "gone": None,
             **{f"x{j}": float(v) for j, v in enumerate(rng.normal(size=4))}}
            for i in range(257)]
    fp = str(tmp_path / "t.avro")
    write_avro_records(fp, recs)
    tab = read_avro_table(fp)
    py = read_avro_records(fp)
    assert isinstance(tab, ColumnarRecords)
    assert len(tab) == len(py) == 257
    assert all(a == b for a, b in zip(tab, py))
    assert tab[0] == py[0] and tab[-1] == py[-1]       # indexing + negative
    # iterating consumers share ONE memoized dict materialization (the
    # pre-pipeline list(data) cost model: N fallback features must not
    # pay N × O(rows × fields) fresh-dict builds)
    assert all(a is b for a, b in zip(tab, tab))
    np.testing.assert_array_equal(
        tab.columns["x0"], np.array([r["x0"] for r in py]))
    # multi-block container
    fp2 = str(tmp_path / "m.avro")
    w = AvroWriter(fp2, infer_avro_schema(recs))
    w.append(recs[:100])
    w.append(recs[100:])
    w.close()
    tab2 = read_avro_table(fp2)
    assert isinstance(tab2, ColumnarRecords)
    assert list(tab2) == py


@pytest.mark.parametrize("poison", ["string", "int", "mixed_null"])
def test_columnar_avro_decode_falls_back_exactly(tmp_path, poison):
    """A schema/layout the strided decode can't verify (variable-width
    strings, varint longs, a union whose branch varies row to row)
    falls back to the Python decoder — same records, just dicts."""
    from transmogrifai_tpu.readers.avro import (read_avro_table,
                                                write_avro_records)

    if poison == "string":
        recs = [{"a": float(i), "s": f"r{i}"} for i in range(50)]
    elif poison == "int":
        recs = [{"a": i, "b": float(i)} for i in range(50)]
    else:
        recs = [{"a": None if i % 2 else 1.5} for i in range(50)]
    fp = str(tmp_path / "p.avro")
    write_avro_records(fp, recs)
    got = read_avro_table(fp)
    assert isinstance(got, list)
    assert got == read_avro_records(fp)


def test_columnar_batch_scores_bit_identical_to_dicts(tmp_path, rng):
    """Acceptance: a ColumnarRecords batch through the bulk extract
    lane (no dict ever materialized) scores EXACTLY like the same
    file's Python-decoded dicts — host path and engine path."""
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.readers.avro import (read_avro_table,
                                                write_avro_records)

    n = 300
    y = rng.integers(0, 2, n).astype(float)
    x1 = rng.normal(size=n) + y
    recs = [{"label": float(y[i]), "x1": float(x1[i])} for i in range(n)]
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=7)
    pred = label.transform_with(selector, transmogrify([f1]))
    model = (Workflow().set_input_records(recs)
             .set_result_features(pred).train())
    fp = str(tmp_path / "s.avro")
    write_avro_records(fp, recs)
    tab = read_avro_table(fp)
    py = read_avro_records(fp)
    want = model.score(py)
    got = model.score(tab)
    np.testing.assert_array_equal(got[pred.name].probability,
                                  want[pred.name].probability)
    eng = model.scoring_engine(gate_bandwidth=False)
    np.testing.assert_array_equal(
        eng.score_store(tab, use_cache=False)[pred.name].probability,
        eng.score_store(py, use_cache=False)[pred.name].probability)


def test_parallel_decode_order_matches_serial_bytes_identical(tmp_path):
    """Acceptance: N-worker parallel decode yields batches in the SAME
    order as serial decode, asserted bytes-identical (the reorder
    buffer makes worker interleaving invisible)."""
    import pickle

    from transmogrifai_tpu.readers import DirectoryStreamReader

    d = tmp_path / "in"
    d.mkdir()
    _write_mixed_batch_dir(d)
    serial = list(DirectoryStreamReader(str(d), settle_s=0.0)
                  .stream(max_batches=12))
    assert len(serial) == 12
    for workers in (2, 4):
        par = list(DirectoryStreamReader(str(d), settle_s=0.0)
                   .stream(max_batches=12, workers=workers))
        assert pickle.dumps(par) == pickle.dumps(serial)


def test_parallel_stream_picks_up_new_files_and_respects_max(tmp_path):
    from transmogrifai_tpu.readers import DirectoryStreamReader

    d = tmp_path / "in"
    d.mkdir()
    _write_mixed_batch_dir(d, n_files=4)
    r = DirectoryStreamReader(str(d), settle_s=0.0)
    got = list(r.stream(max_batches=2, workers=3))
    assert len(got) == 2
    # unread files were NOT marked seen: the next stream re-offers them
    more = list(r.stream(max_batches=2, workers=3))
    assert len(more) == 2
    assert got[0][0]["x"] == 0 and more[0][0]["x"] == 200


def test_stream_idle_wait_is_interruptible_and_timeout_clamped(tmp_path):
    """Satellite: stop() wakes a sleeping stream immediately (no full
    poll_interval_s block) and a timeout shorter than the poll interval
    is honored instead of overshooting by a whole interval."""
    import threading
    import time

    from transmogrifai_tpu.readers import DirectoryStreamReader

    d = tmp_path / "in"
    d.mkdir()
    # timeout < poll interval: the wait clamps to the remaining timeout
    r = DirectoryStreamReader(str(d), settle_s=0.0, poll_interval_s=30.0)
    t0 = time.perf_counter()
    assert list(r.stream(timeout_s=0.2)) == []
    assert time.perf_counter() - t0 < 5.0

    # stop() from another thread unblocks the idle wait promptly
    r2 = DirectoryStreamReader(str(d), settle_s=0.0, poll_interval_s=30.0)
    done = threading.Event()

    def drain():
        list(r2.stream())              # no timeout: would poll forever
        done.set()

    t = threading.Thread(target=drain, name="stream-drain", daemon=True)
    t.start()
    time.sleep(0.1)                    # let it reach the idle wait
    r2.stop()
    assert done.wait(5.0)


def test_stream_polls_again_immediately_after_productive_poll(tmp_path,
                                                              monkeypatch):
    """A productive poll is followed by another poll with NO sleep —
    only an idle poll waits."""
    from transmogrifai_tpu.readers import DirectoryStreamReader

    d = tmp_path / "in"
    d.mkdir()
    (d / "a.csv").write_text("x\n1\n")
    (d / "b.csv").write_text("x\n2\n")
    r = DirectoryStreamReader(str(d), settle_s=0.0, poll_interval_s=60.0)
    waits = []
    monkeypatch.setattr(r._stop, "wait",
                        lambda t=None: waits.append(t) or True)
    got = list(r.stream())             # ends at the first idle wait
    assert len(got) == 2               # both files drained, no sleep between
    assert len(waits) == 1
