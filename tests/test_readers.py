"""Reader depth tests: Parquet/Avro ingestion, joined-aggregate windows,
time filters, streaming scoring (DataReadersTest / JoinedDataReaderTest
analogs)."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, Workflow
from transmogrifai_tpu.readers import (AvroReader, DataReaders,
                                       JoinedAggregateDataReader,
                                       ParquetReader, TimeBasedFilter,
                                       CutOffTime, read_avro_records,
                                       stream_score)
from transmogrifai_tpu.types import feature_types as ft

PARQUET = "/root/reference/test-data/PassengerDataAll.parquet"
AVRO = "/root/reference/test-data/PassengerDataAll.avro"
CSV = "/root/reference/test-data/PassengerDataAll.csv"


def test_avro_decoder_matches_csv_rows():
    recs = read_avro_records(AVRO)
    assert len(recs) == 891
    r0 = recs[0]
    assert r0["Name"] == "Braund, Mr. Owen Harris"
    assert r0["Age"] == 22.0 and r0["Cabin"] is None


def test_parquet_and_avro_readers_agree():
    pq = ParquetReader(PARQUET).read_records()
    av = AvroReader(AVRO).read_records()
    assert len(pq) == len(av) == 891
    for k in ("Name", "Sex", "Pclass"):
        assert pq[0][k] == av[0][k]
    # nullable float → None in both
    assert pq[5].get("Age") == av[5].get("Age")


def test_titanic_runs_off_parquet(rng):
    """The flagship workflow trains from a parquet file (VERDICT r1 #9)."""
    import sys
    sys.path.insert(0, "examples")
    from titanic import build_features

    survived, checked = build_features(with_sanity_check=False)
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import BinaryClassificationModelSelector
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None)
    pred = survived.transform_with(selector, checked)

    # parquet columns are capitalized; remap to the example's schema
    records = ParquetReader(PARQUET).read_records()
    remap = {"PassengerId": "id", "Survived": "survived", "Pclass": "pClass",
             "Name": "name", "Sex": "sex", "Age": "age", "SibSp": "sibSp",
             "Parch": "parCh", "Ticket": "ticket", "Fare": "fare",
             "Cabin": "cabin", "Embarked": "embarked"}
    records = [{remap[k]: v for k, v in r.items()} for r in records]
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())
    scores = model.score(records)
    assert scores.n_rows == 891


def test_joined_aggregate_reader_windows():
    """Post-join windowed aggregation (Conditional-Aggregation.md flow):
    left = profiles, right = events; events aggregate within the window
    before the cutoff."""
    profiles = [{"id": "a", "region": "west"}, {"id": "b", "region": "east"}]
    events = [
        {"id": "a", "ts": 100, "spend": 1.0},
        {"id": "a", "ts": 500, "spend": 2.0},
        {"id": "a", "ts": 900, "spend": 100.0},   # after cutoff → excluded
        {"id": "b", "ts": 650, "spend": 5.0},
    ]
    left = DataReaders.simple.records(profiles, key_fn=lambda r: r["id"])
    right = DataReaders.simple.records(events, key_fn=lambda r: r["id"])
    # join produces per-event records carrying the profile fields
    reader = JoinedAggregateDataReader(
        right, left, timestamp_fn=lambda r: r["ts"],
        cutoff=CutOffTime(800))

    from transmogrifai_tpu.utils.aggregators import SumAggregator
    region = FeatureBuilder.PickList("region").from_column().as_predictor()
    spend = (FeatureBuilder.Real("spend").from_column()
             .aggregate(SumAggregator()).as_predictor())
    store = reader.generate_store([region, spend])
    assert store.n_rows == 2
    vals = {store["region"].get_raw(i): store["spend"].get_raw(i)
            for i in range(2)}
    assert vals["west"] == pytest.approx(3.0)     # 1 + 2, cutoff excluded
    assert vals["east"] == pytest.approx(5.0)


def test_time_based_filter():
    tf = TimeBasedFilter(timestamp_fn=lambda r: r["ts"], cutoff_ms=1000,
                         duration_ms=500)
    assert tf.keep({"ts": 700})
    assert not tf.keep({"ts": 1200})    # after cutoff
    assert not tf.keep({"ts": 300})     # before window


def test_stream_score(rng):
    n = 120
    y = rng.integers(0, 2, n).astype(float)
    x = rng.normal(size=n) + y
    records = [{"label": float(y[i]), "x": float(x[i])} for i in range(n)]
    from transmogrifai_tpu.dsl import transmogrify
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import BinaryClassificationModelSelector
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None)
    pred = label.transform_with(selector, transmogrify([fx]))
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())

    batches = [records[i:i + 50] for i in range(0, n, 50)]
    total = 0
    for scored in stream_score(model, batches):
        assert pred.name in scored.names()
        total += scored.n_rows
    assert total == n


def test_aggregator_defaults_cover_all_types():
    """aggregator_of mirrors MonoidAggregatorDefaults.aggregatorOf: every
    registered feature type has a default monoid."""
    from transmogrifai_tpu.types.feature_types import FEATURE_TYPE_REGISTRY
    from transmogrifai_tpu.utils.aggregators import (
        ConcatTextAggregator, LogicalOrAggregator, ModeAggregator,
        SumAggregator, aggregator_of)
    from transmogrifai_tpu.types import feature_types as ft

    for t in FEATURE_TYPE_REGISTRY.values():
        assert aggregator_of(t) is not None
    assert isinstance(aggregator_of(ft.Real), SumAggregator)
    assert isinstance(aggregator_of(ft.Binary), LogicalOrAggregator)
    assert isinstance(aggregator_of(ft.PickList), ModeAggregator)
    assert isinstance(aggregator_of(ft.Text), ConcatTextAggregator)

    assert aggregator_of(ft.Real).fold([1.0, None, 2.5]) == 3.5
    assert aggregator_of(ft.PickList).fold(["a", "b", "a"]) == "a"
    assert aggregator_of(ft.MultiPickList).fold([{"a"}, {"b"}]) == {"a", "b"}
    assert aggregator_of(ft.RealMap).fold(
        [{"k": 1.0}, {"k": 2.0, "j": 5.0}]) == {"k": 3.0, "j": 5.0}
    mid = aggregator_of(ft.Geolocation).fold([(0.0, 0.0, 1.0),
                                              (0.0, 90.0, 2.0)])
    assert mid[1] == pytest.approx(45.0)


def test_conditional_dataprep_example():
    """The conditional-aggregation walkthrough produces leak-free per-user
    rows (Conditional-Aggregation.md flow)."""
    import os
    import sys
    examples = os.path.join(os.path.dirname(__file__), os.pardir, "examples")
    sys.path.insert(0, examples)
    try:
        from dataprep import run
    finally:
        sys.path.remove(examples)
    store, rows = run()
    assert store.n_rows == 2             # user b dropped (never purchased)
    by_minutes = {r["minutes"] for r in rows.values()}
    assert 10.0 in by_minutes            # user a: 3 + 7 before first buy


def test_directory_stream_reader(tmp_path):
    """DirectoryStreamReader (StreamingReaders analog): each new file is
    one micro-batch; new_files_only skips the backlog; avro + csv route
    by extension."""
    from transmogrifai_tpu.readers import DirectoryStreamReader
    from transmogrifai_tpu.readers.avro import write_avro_records

    d = tmp_path / "incoming"
    d.mkdir()
    (d / "a.csv").write_text("x,y\n1,one\n2,two\n")
    write_avro_records(str(d / "b.avro"),
                       [{"x": 3, "y": "three"}, {"x": 4, "y": None}])

    r = DirectoryStreamReader(str(d), pattern="*", settle_s=0.0)
    batches = list(r.stream(max_batches=2))
    assert len(batches) == 2             # one batch per file, sorted order
    assert batches[0][0]["y"] == "one"   # a.csv first
    assert batches[1][0] == {"x": 3, "y": "three"}
    # nothing new -> poll_once drains empty
    assert r.poll_once() == []
    # a THIRD file appears mid-stream and is picked up
    (d / "c.csv").write_text("x,y\n9,nine\n")
    more = list(r.stream(max_batches=1, timeout_s=5.0))
    assert more == [[{"x": "9", "y": "nine"}]]

    # new_files_only: the existing backlog is invisible
    r2 = DirectoryStreamReader(str(d), new_files_only=True, settle_s=0.0)
    assert r2.poll_once() == []
    (d / "d.csv").write_text("x,y\n5,five\n")
    assert r2.read_records() == [{"x": "5", "y": "five"}]


def test_directory_stream_reader_error_paths(tmp_path, caplog):
    """Corrupt files are logged + skipped (not retried forever, not
    stream-fatal); files behind them still flow; unknown extensions
    raise a configuration error."""
    import logging

    import pytest

    from transmogrifai_tpu.readers import DirectoryStreamReader

    d = tmp_path / "in"
    d.mkdir()
    (d / "a.avro").write_bytes(b"not an avro container at all")
    (d / "b.csv").write_text("x\n1\n")
    r = DirectoryStreamReader(str(d), pattern="*", settle_s=0.0)
    with caplog.at_level(logging.WARNING):
        batches = list(r.stream(max_batches=1, timeout_s=3.0))
    assert batches == [[{"x": "1"}]]          # corrupt a.avro skipped
    assert any("quarantining unreadable" in rec.message
               for rec in caplog.records)
    assert r.poll_once() == []                # corrupt file not retried

    (d / "c.weird").write_text("zzz")
    r2 = DirectoryStreamReader(str(d), new_files_only=False, settle_s=0.0)
    with pytest.raises(ValueError, match="no reader"):
        with caplog.at_level(logging.WARNING):
            list(r2.stream(max_batches=5, timeout_s=1.0))
