"""Resilience layer tests — fault injection, retries, breakers,
quarantine, resumable fits (transmogrifai_tpu/resilience.py + wiring).

The ``chaos`` subset is deterministic (seeded FaultPlan, no real sleeps
over 0.1s) and tier-1 safe; run just it with ``-m chaos``.
"""
import json
import os
import threading

import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder, Workflow, resilience
from transmogrifai_tpu.columns import ColumnStore, column_from_values
from transmogrifai_tpu.types import feature_types as ft


@pytest.fixture(autouse=True)
def _clean_resilience():
    """Every test starts from a clean plan/breaker/sink/tally state and
    leaves none behind (the module state is process-wide)."""
    resilience.clear_plan()
    resilience.reset_breakers()
    prev = resilience.set_quarantine(None)
    resilience.reset_resilience_stats()
    yield
    resilience.clear_plan()
    resilience.reset_breakers()
    resilience.set_quarantine(prev)
    resilience.reset_resilience_stats()


# ---------------------------------------------------------------------------
# fault-injection harness
# ---------------------------------------------------------------------------


def test_fault_plan_at_indices_and_times():
    plan = resilience.FaultPlan(seed=1).on(
        "site.a", error=ValueError, at=[0, 2])
    with resilience.fault_plan(plan):
        with pytest.raises(ValueError, match="site.a"):
            resilience.inject("site.a")
        resilience.inject("site.a")          # call 1: clean
        with pytest.raises(ValueError):
            resilience.inject("site.a")
        resilience.inject("site.a")          # call 3: clean
        resilience.inject("site.unknown")    # unarmed site: no-op
    assert plan.calls("site.a") == 4
    assert plan.fired("site.a") == 2
    assert resilience.resilience_stats()["faults_injected"] == 2
    # uninstalled plan: inject is a no-op even for armed sites
    resilience.inject("site.a")
    assert plan.calls("site.a") == 4


def test_fault_plan_probability_is_seed_deterministic():
    fires = []
    for _ in range(2):
        plan = resilience.FaultPlan(seed=77).on(
            "s", error=OSError, probability=0.5)
        fires.append([plan.check("s") is not None for _ in range(40)])
    assert fires[0] == fires[1]
    assert 0 < sum(fires[0]) < 40          # actually probabilistic
    # times= caps fires even at probability 1
    plan = resilience.FaultPlan(seed=0).on("s", probability=1.0, times=2)
    assert sum(plan.check("s") is not None for _ in range(10)) == 2


def test_fault_plan_error_instance_is_raised_verbatim():
    sentinel = RuntimeError("the exact instance")
    plan = resilience.FaultPlan().on("s", error=sentinel, at=[0])
    with resilience.fault_plan(plan):
        with pytest.raises(RuntimeError) as ei:
            resilience.inject("s")
    assert ei.value is sentinel


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------


def test_retry_succeeds_after_transient_failures():
    sleeps = []
    pol = resilience.RetryPolicy(max_attempts=4, base_delay_s=0.01,
                                 seed=5, sleep=sleeps.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert pol.call("t", flaky) == "ok"
    assert calls["n"] == 3 and len(sleeps) == 2
    stats = resilience.resilience_stats()
    assert stats["retries"] == 2 and stats["retry_exhausted"] == 0


def test_retry_exhausts_and_reraises_original():
    pol = resilience.RetryPolicy(max_attempts=3, base_delay_s=0.001,
                                 sleep=lambda _d: None)
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise OSError("persistent")

    with pytest.raises(OSError, match="persistent"):
        pol.call("t", always)
    assert calls["n"] == 3
    assert resilience.resilience_stats()["retry_exhausted"] == 1


def test_retry_filter_skips_nonretryable():
    pol = resilience.RetryPolicy(max_attempts=5, retryable=(OSError,),
                                 sleep=lambda _d: None)
    calls = {"n": 0}

    def corrupt():
        calls["n"] += 1
        raise ValueError("decode error — not transient")

    with pytest.raises(ValueError):
        pol.call("t", corrupt)
    assert calls["n"] == 1                   # no retry for a decode error


def test_retry_backoff_is_exponential_capped_and_seeded():
    pol = resilience.RetryPolicy(max_attempts=9, base_delay_s=0.1,
                                 max_delay_s=0.9, multiplier=2.0,
                                 jitter=0.5, seed=11)
    pol2 = resilience.RetryPolicy(max_attempts=9, base_delay_s=0.1,
                                  max_delay_s=0.9, multiplier=2.0,
                                  jitter=0.5, seed=11)
    d1 = [pol.delay_s(a) for a in range(6)]
    assert d1 == [pol2.delay_s(a) for a in range(6)]     # seeded = replay
    for a, d in enumerate(d1):
        raw = min(0.1 * 2 ** a, 0.9)
        assert 0.5 * raw <= d <= 1.5 * raw               # jitter bounds


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


def test_breaker_trips_after_threshold_and_half_open_recovers():
    b = resilience.CircuitBreaker("t", failure_threshold=3,
                                  reset_timeout_s=0.02)
    for _ in range(2):
        b.record_failure()
    assert b.state == b.CLOSED and b.allow()
    b.record_failure()
    assert b.state == b.OPEN
    assert not b.allow()                       # open: fallback serves
    import time
    time.sleep(0.03)
    assert b.allow()                           # the half-open probe
    assert b.state == b.HALF_OPEN
    assert not b.allow()                       # only ONE probe in flight
    b.record_success()
    assert b.state == b.CLOSED and b.allow()
    stats = resilience.resilience_stats()
    assert stats["breaker_trips"] == 1
    assert stats["breaker_open_skips"] >= 2


def test_breaker_half_open_probe_timeout_rearms():
    """A probe handed out but never reported (its caller bailed on a
    later gate) must not wedge the tier: after another reset period the
    next caller becomes the probe."""
    import time
    b = resilience.CircuitBreaker("t", failure_threshold=1,
                                  reset_timeout_s=0.01)
    b.record_failure()
    time.sleep(0.02)
    assert b.allow()                           # probe 1: never reports
    assert not b.allow()                       # in flight: held
    time.sleep(0.02)
    assert b.allow()                           # re-armed probe
    b.record_success()
    assert b.state == b.CLOSED


def test_breaker_half_open_failure_reopens():
    b = resilience.CircuitBreaker("t", failure_threshold=1,
                                  reset_timeout_s=0.01)
    b.record_failure()
    assert b.state == b.OPEN
    import time
    time.sleep(0.02)
    assert b.allow()
    b.record_failure()                         # probe failed
    assert b.state == b.OPEN
    assert resilience.resilience_stats()["breaker_trips"] == 2


def test_breaker_success_resets_consecutive_count():
    b = resilience.CircuitBreaker("t", failure_threshold=3)
    b.record_failure(); b.record_failure()
    b.record_success()
    b.record_failure(); b.record_failure()
    assert b.state == b.CLOSED                 # never 3 consecutive


# ---------------------------------------------------------------------------
# quarantine sink
# ---------------------------------------------------------------------------


def test_quarantine_jsonl_format_and_counters(tmp_path):
    sink = resilience.set_quarantine(str(tmp_path / "dead.jsonl"))
    assert sink is None                        # returns previous
    resilience.quarantine("stream.read_file", "AvroDecodeError('x')",
                          kind="files", path="/data/a.avro")
    resilience.quarantine("stream.score_batch", "OSError('y')",
                          kind="batches", index=3, rows=128)
    entries = resilience.get_quarantine().entries()
    assert len(entries) == 2
    assert entries[0]["site"] == "stream.read_file"
    assert entries[0]["kind"] == "files"
    assert entries[0]["path"] == "/data/a.avro"
    assert entries[0]["reason"].startswith("AvroDecodeError")
    assert entries[1]["index"] == 3 and entries[1]["rows"] == 128
    assert all("ts" in e for e in entries)
    stats = resilience.resilience_stats()
    assert stats["quarantined_files"] == 1
    assert stats["quarantined_batches"] == 1
    # every line is standalone JSON (the contract downstream tooling has)
    with open(tmp_path / "dead.jsonl") as fh:
        for line in fh:
            json.loads(line)


def test_quarantine_counts_without_sink():
    resilience.quarantine("s", "r", kind="records", count=5)
    assert resilience.resilience_stats()["quarantined_records"] == 5


# ---------------------------------------------------------------------------
# streaming reader wiring (satellite: streaming.py:112)
# ---------------------------------------------------------------------------


def _write_csv(path, rows):
    with open(path, "w") as fh:
        fh.write("label,x\n")
        for r in rows:
            fh.write(f"{r[0]},{r[1]}\n")


@pytest.mark.chaos
def test_stream_reader_quarantines_unreadable_file(tmp_path):
    from transmogrifai_tpu.readers import DirectoryStreamReader
    d = tmp_path / "in"
    d.mkdir()
    _write_csv(d / "a.csv", [(1, 2.0)])
    (d / "b.avro").write_bytes(b"Obj\x01garbage-not-avro")   # poison
    _write_csv(d / "c.csv", [(0, 3.0)])
    resilience.set_quarantine(str(tmp_path / "dead.jsonl"))
    rdr = DirectoryStreamReader(str(d), settle_s=0.0)
    batches = rdr.poll_once()
    assert len(batches) == 2                   # both good files served
    stats = resilience.resilience_stats()
    assert stats["quarantined_files"] == 1
    entries = resilience.get_quarantine().entries()
    assert entries[0]["path"].endswith("b.avro")
    assert "b.avro" in entries[0]["reason"]    # decode error names file
    # the poison file is marked seen: a later poll does not re-offer it
    assert rdr.poll_once() == []
    assert resilience.resilience_stats()["quarantined_files"] == 1


@pytest.mark.chaos
def test_stream_poll_retries_transient_listing_fault(tmp_path):
    """`stream.poll` chaos: a transient directory-listing failure (a
    network-mount blip mid-poll) rides READER_RETRY instead of killing
    the stream — the poll retries and the batch still arrives."""
    from transmogrifai_tpu.readers import DirectoryStreamReader
    d = tmp_path / "in"
    d.mkdir()
    _write_csv(d / "a.csv", [(1, 2.0)])
    plan = resilience.FaultPlan(seed=5).on(
        "stream.poll", error=OSError, at=[0])        # transient: once
    with resilience.fault_plan(plan):
        rdr = DirectoryStreamReader(str(d), settle_s=0.0)
        batches = rdr.poll_once()
    assert len(batches) == 1                   # retry absorbed the fault
    assert resilience.resilience_stats()["retries"] == 1


@pytest.mark.chaos
def test_csv_decode_retries_transient_fault(tmp_path):
    """`csv.decode` chaos: a transient decode-time failure on a streamed
    CSV retries behind READER_RETRY; a persistent one quarantines the
    file instead of wedging the stream."""
    from transmogrifai_tpu.readers import DirectoryStreamReader
    d = tmp_path / "in"
    d.mkdir()
    _write_csv(d / "a.csv", [(1, 2.0)])
    plan = resilience.FaultPlan(seed=7).on(
        "csv.decode", error=OSError, at=[0])         # transient: once
    with resilience.fault_plan(plan):
        rdr = DirectoryStreamReader(str(d), settle_s=0.0)
        batches = rdr.poll_once()
    assert len(batches) == 1
    assert resilience.resilience_stats()["retries"] == 1
    # persistent decode failure: quarantined, not retried forever
    d2 = tmp_path / "in2"
    d2.mkdir()
    _write_csv(d2 / "b.csv", [(0, 3.0)])
    always = resilience.FaultPlan(seed=7).on(
        "csv.decode", error=OSError, probability=1.0)
    with resilience.fault_plan(always):
        rdr2 = DirectoryStreamReader(str(d2), settle_s=0.0)
        assert rdr2.poll_once() == []
    assert resilience.resilience_stats()["quarantined_files"] == 1


@pytest.mark.chaos
def test_stream_reader_retries_transient_io_then_succeeds(tmp_path):
    from transmogrifai_tpu.readers import DirectoryStreamReader
    d = tmp_path / "in"
    d.mkdir()
    _write_csv(d / "a.csv", [(1, 2.0)])
    plan = resilience.FaultPlan(seed=2).on(
        "stream.read_file", error=OSError, at=[0])   # transient: once
    with resilience.fault_plan(plan):
        rdr = DirectoryStreamReader(str(d), settle_s=0.0)
        batches = rdr.poll_once()
    assert len(batches) == 1                   # retry absorbed the fault
    stats = resilience.resilience_stats()
    assert stats["retries"] == 1
    assert stats["quarantined_files"] == 0


def test_avro_decode_error_names_file(tmp_path):
    """Truncated container → AvroDecodeError carrying the path, whatever
    low-level exception the cursor hit (satellite: descriptive decode
    errors)."""
    from transmogrifai_tpu.readers.avro import (AvroDecodeError,
                                                read_avro_records,
                                                write_avro_records)
    p = str(tmp_path / "t.avro")
    write_avro_records(p, [{"a": 1, "b": "x"}] * 20)
    whole = open(p, "rb").read()
    for cut in (10, len(whole) // 2, len(whole) - 3):
        bad = str(tmp_path / f"cut{cut}.avro")
        with open(bad, "wb") as fh:
            fh.write(whole[:cut])
        with pytest.raises(AvroDecodeError, match=f"cut{cut}"):
            read_avro_records(bad)


# ---------------------------------------------------------------------------
# a small 3-layer workflow shared by the chaos tests
# ---------------------------------------------------------------------------


def _records(n=120, seed=42):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, n).astype(float)
    x = rng.normal(size=n) + y
    z = rng.normal(size=n) - y
    return [{"label": float(y[i]), "x": float(x[i]), "z": float(z[i])}
            for i in range(n)]


def _three_layer_workflow():
    """vectorize → sanity-check → selector: three fitted DAG layers."""
    from transmogrifai_tpu.dsl import transmogrify
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import (
        BinaryClassificationModelSelector)
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    fz = FeatureBuilder.Real("z").from_column().as_predictor()
    vec = transmogrify([fx, fz])
    checked = label.sanity_check(vec)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None)
    pred = label.transform_with(selector, checked)
    return pred


def _train(records, pred):
    return (Workflow().set_input_records(records)
            .set_result_features(pred).train())


# ---------------------------------------------------------------------------
# chaos: IO fault on batch k of stream_score → quarantined, rest exact
# (acceptance criterion)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_stream_score_quarantines_faulted_batch_rest_bit_identical(
        tmp_path):
    from transmogrifai_tpu.readers import stream_score
    records = _records()
    pred = _three_layer_workflow()
    model = _train(records, pred)
    batches = [records[i:i + 30] for i in range(0, len(records), 30)]

    clean = [s[pred.name].prediction.copy()
             for s in stream_score(model, batches)]
    assert len(clean) == 4

    resilience.set_quarantine(str(tmp_path / "dead.jsonl"))
    k = 2
    plan = resilience.FaultPlan(seed=13).on(
        "stream.score_batch", error=IOError, at=[k])
    with resilience.fault_plan(plan):
        faulted = [s[pred.name].prediction.copy()
                   for s in stream_score(model, batches)]

    # the stream completed with exactly the bad batch missing...
    assert len(faulted) == len(clean) - 1
    survivors = [c for i, c in enumerate(clean) if i != k]
    # ...and every good batch's scores are bit-identical
    for got, want in zip(faulted, survivors):
        np.testing.assert_array_equal(got, want)
    stats = resilience.resilience_stats()
    assert stats["quarantined_batches"] == 1
    entry = resilience.get_quarantine().entries()[0]
    assert entry["site"] == "stream.score_batch"
    assert entry["index"] == k and entry["rows"] == 30
    # the dead letter is replayable: the batch's records ride in it (a
    # consumed stream batch exists nowhere else)
    assert entry["records"] == batches[k]


@pytest.mark.chaos
def test_parallel_decode_fault_quarantines_exactly_one_file(tmp_path):
    """Acceptance: a seeded ``avro.decode`` fault inside the worker
    pool quarantines exactly the batch it hit — identified through the
    dead-letter sink — and every surviving batch is bit-identical to
    the serial decode of the surviving files. The fault site moved onto
    worker threads; its semantics did not."""
    import pickle

    from transmogrifai_tpu.readers import DirectoryStreamReader
    from transmogrifai_tpu.readers.avro import write_avro_records

    d = tmp_path / "in"
    d.mkdir()
    n_files = 8
    by_file = {}
    for i in range(n_files):
        recs = [{"x": i * 10 + r, "y": f"f{i}"} for r in range(5)]
        fp = str(d / f"b{i:03d}.avro")
        write_avro_records(fp, recs)
        by_file[fp] = recs

    resilience.set_quarantine(str(tmp_path / "dead.jsonl"))
    # the j-th avro.decode CALL on the pool faults with a non-transient
    # decode error (retry must NOT mask it — AvroDecodeError is not
    # retryable); which file that call lands on is worker-schedule
    # dependent, so the sink entry names it
    plan = resilience.FaultPlan(seed=5).on(
        "avro.decode", error=ValueError("chaos: torn container"), at=[3])
    with resilience.fault_plan(plan):
        got = list(DirectoryStreamReader(str(d), settle_s=0.0)
                   .stream(max_batches=n_files - 1, workers=4,
                           timeout_s=5.0))

    stats = resilience.resilience_stats()
    assert stats["quarantined_files"] == 1
    entries = resilience.get_quarantine().entries()
    assert len(entries) == 1 and entries[0]["site"] == "stream.read_file"
    bad = entries[0]["path"]
    assert bad in by_file
    survivors = [by_file[fp] for fp in sorted(by_file) if fp != bad]
    assert pickle.dumps(got) == pickle.dumps(survivors)


@pytest.mark.chaos
def test_pipelined_stream_score_survivors_bit_identical(tmp_path):
    """The PR-4 stream chaos acceptance, re-run through the staged
    pipeline (N prep workers + staged uploads): an IO fault on batch k
    quarantines exactly batch k, survivors bit-identical, records in
    the dead letter."""
    from transmogrifai_tpu.readers import stream_score

    records = _records()
    pred = _three_layer_workflow()
    model = _train(records, pred)
    batches = [records[i:i + 30] for i in range(0, len(records), 30)]
    eng = model.scoring_engine(gate_bandwidth=False)
    clean = [eng.score_store(list(b), use_cache=False)[
        pred.name].prediction.copy() for b in batches]

    resilience.set_quarantine(str(tmp_path / "dead.jsonl"))
    k = 2
    # single worker first: call order == batch order, so at=[k] is
    # exactly batch k — the PR-4 assertion verbatim on the new path
    plan = resilience.FaultPlan(seed=13).on(
        "stream.score_batch", error=IOError, at=[k])
    with resilience.fault_plan(plan):
        faulted = [s[pred.name].prediction.copy()
                   for s in stream_score(model, batches, overlap=True,
                                         workers=1, prefetch=2)]
    assert len(faulted) == len(clean) - 1
    survivors = [c for i, c in enumerate(clean) if i != k]
    for got, want in zip(faulted, survivors):
        np.testing.assert_array_equal(got, want)
    entry = resilience.get_quarantine().entries()[0]
    assert entry["index"] == k and entry["records"] == batches[k]

    # with 2 workers the k-th CALL may land on a different batch
    # (worker interleaving orders the site's calls) — but exactly one
    # batch is quarantined, the sink names it, and every other batch
    # is bit-identical to its clean twin
    resilience.reset_resilience_stats()
    plan2 = resilience.FaultPlan(seed=13).on(
        "stream.score_batch", error=IOError, at=[k])
    with resilience.fault_plan(plan2):
        faulted2 = [s[pred.name].prediction.copy()
                    for s in stream_score(model, batches, overlap=True,
                                          workers=2)]
    assert len(faulted2) == len(clean) - 1
    assert resilience.resilience_stats()["quarantined_batches"] == 1
    dropped = resilience.get_quarantine().entries()[-1]["index"]
    survivors2 = [c for i, c in enumerate(clean) if i != dropped]
    for got, want in zip(faulted2, survivors2):
        np.testing.assert_array_equal(got, want)


def test_stream_score_on_error_raise_propagates():
    from transmogrifai_tpu.readers import stream_score
    records = _records(60)
    pred = _three_layer_workflow()
    model = _train(records, pred)
    batches = [records[i:i + 20] for i in range(0, 60, 20)]
    plan = resilience.FaultPlan().on("stream.score_batch",
                                     error=IOError, at=[1])
    with resilience.fault_plan(plan):
        with pytest.raises(IOError):
            list(stream_score(model, batches, on_error="raise"))


def test_stream_score_first_batch_failure_always_raises(tmp_path):
    """A head-of-stream failure is a configuration error, not poison —
    quarantining every batch of a misconfigured stream would be silence
    at scale. Holds even with a sink installed (quarantine mode)."""
    from transmogrifai_tpu.readers import stream_score
    records = _records(60)
    pred = _three_layer_workflow()
    model = _train(records, pred)
    batches = [records[i:i + 20] for i in range(0, 60, 20)]
    resilience.set_quarantine(str(tmp_path / "dead.jsonl"))
    plan = resilience.FaultPlan().on("stream.score_batch",
                                     error=IOError, at=[0])
    with resilience.fault_plan(plan):
        with pytest.raises(IOError):
            list(stream_score(model, batches))   # sink → quarantine mode
    assert resilience.resilience_stats()["quarantined_batches"] == 0


def test_stream_score_without_sink_stays_loud():
    """The sink-aware default: with NO dead-letter sink installed a
    poison batch re-raises even mid-stream — a quarantined batch whose
    records land nowhere would be silent data loss."""
    from transmogrifai_tpu.readers import stream_score
    records = _records(60)
    pred = _three_layer_workflow()
    model = _train(records, pred)
    batches = [records[i:i + 20] for i in range(0, 60, 20)]
    assert resilience.get_quarantine() is None
    plan = resilience.FaultPlan().on("stream.score_batch",
                                     error=IOError, at=[1])
    with resilience.fault_plan(plan):
        with pytest.raises(IOError):
            list(stream_score(model, batches))
    assert resilience.resilience_stats()["quarantined_batches"] == 0


# ---------------------------------------------------------------------------
# chaos: preemption after layer 1 of a 3-layer fit → resumable
# (acceptance criterion)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_preempted_fit_resumes_and_matches_uninterrupted(tmp_path):
    records = _records()
    pred = _three_layer_workflow()
    baseline = _train(records, pred)
    store = baseline.score(records)
    want = store[pred.name].prediction.copy()

    ckpt = str(tmp_path / "ckpt")
    # preempt DURING the second layer's checkpoint swap: layer 0's
    # checkpoint completed, layer 1 is fitted but its swap is mid-rename
    # — the worst window (target dir renamed away, .tmp complete)
    plan = resilience.FaultPlan(seed=4).on(
        "checkpoint.rename", error=RuntimeError("preempted"), at=[1])
    wf = (Workflow().set_input_records(records)
          .set_result_features(pred).with_checkpointing(ckpt))
    with resilience.fault_plan(plan):
        with pytest.raises(RuntimeError, match="preempted"):
            wf.train()
    assert os.path.exists(ckpt + ".tmp")       # the mid-swap state

    # resume: recovers the mid-swap checkpoint, skips layers 0-1, refits
    # only what the preemption interrupted
    wf2 = (Workflow().set_input_records(records)
           .set_result_features(pred))
    resumed = wf2.fit(resume_from=ckpt)
    warm = [uid for uid, m in resumed.stage_metrics.items()
            if m.get("warmStarted")]
    assert warm                                # something was skipped
    got = resumed.score(records)[pred.name].prediction
    np.testing.assert_array_equal(got, want)
    assert resilience.resilience_stats()["resumed_fits"] == 1


@pytest.mark.chaos
def test_fit_resume_from_missing_checkpoint_is_fresh_fit(tmp_path):
    records = _records(80)
    pred = _three_layer_workflow()
    model = (Workflow().set_input_records(records)
             .set_result_features(pred)
             .fit(resume_from=str(tmp_path / "never_written")))
    assert model.score(records).n_rows == 80
    assert resilience.resilience_stats()["resumed_fits"] == 0


# ---------------------------------------------------------------------------
# checkpoint robustness (satellite: rename race + leftover .tmp cleanup)
# ---------------------------------------------------------------------------


def _save_small_model(tmp_path, name="m"):
    store = ColumnStore({"x": column_from_values(
        ft.Real, [0.1, 0.2, 0.3, 0.4])})
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    model = (Workflow().set_input_store(store)
             .set_result_features(fx).train())
    path = str(tmp_path / name)
    model.save(path)
    return model, path


def test_concurrent_recover_checkpoint_rename_race(tmp_path):
    """Two recoverers racing on one mid-swap dir: exactly one wins the
    rename, both resolve to a loadable target (satellite: the
    FileNotFoundError retry branch in _recover_checkpoint)."""
    import shutil

    from transmogrifai_tpu import model_io

    for round_ in range(5):
        _model, path = _save_small_model(tmp_path, f"m{round_}")
        # mid-swap: target renamed away, complete .tmp waiting
        shutil.copytree(path, path + ".old")
        os.rename(path, path + ".tmp")

        results, errors = [], []
        barrier = threading.Barrier(2)

        def recover():
            try:
                barrier.wait()
                results.append(model_io._recover_checkpoint(path))
            except Exception as e:      # pragma: no cover - the failure
                errors.append(e)

        ts = [threading.Thread(target=recover) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errors
        assert results == [path, path]
        assert os.path.exists(os.path.join(path, model_io.MODEL_JSON))
        from transmogrifai_tpu.workflow import WorkflowModel
        assert WorkflowModel.load(path).result_features[0].name == "x"


@pytest.mark.chaos
def test_crash_mid_checkpoint_leftover_tmp_is_cleaned(tmp_path):
    """A kill between the save into .tmp and the swap leaves a complete
    .tmp next to the intact target; the NEXT checkpoint cycle must adopt
    nothing stale, clean the leftover and land the new save (satellite:
    crash-mid-_atomic_checkpoint cleanup)."""
    from transmogrifai_tpu import model_io
    from transmogrifai_tpu.workflow import WorkflowModel, _atomic_checkpoint

    model, path = _save_small_model(tmp_path)

    # crash AFTER the tmp save, BEFORE any rename: rename(directory, old)
    # never ran, so the target is intact and .tmp is a complete orphan
    plan = resilience.FaultPlan().on(
        "checkpoint.rename", error=RuntimeError("killed"), at=[0])
    with resilience.fault_plan(plan):
        with pytest.raises(RuntimeError, match="killed"):
            _atomic_checkpoint(model, path)
    # the fault fired between rename(directory, old) and rename(tmp,
    # directory): mid-swap, .tmp complete — recoverable by load
    assert os.path.exists(path + ".tmp")
    assert WorkflowModel.load(path).result_features[0].name == "x"

    # ALSO: a torn .tmp (no model.json — crash mid-save) must never be
    # adopted, and the next full checkpoint clears every leftover
    import shutil
    shutil.rmtree(path + ".tmp", ignore_errors=True)
    os.makedirs(path + ".tmp")
    with open(os.path.join(path + ".tmp", "weights-torn.npz"), "wb") as fh:
        fh.write(b"partial")
    _atomic_checkpoint(model, path)
    assert not os.path.exists(path + ".tmp")
    assert not os.path.exists(path + ".old")
    assert os.path.exists(os.path.join(path, model_io.MODEL_JSON))


@pytest.mark.chaos
def test_checkpoint_write_retries_transient_io(tmp_path):
    from transmogrifai_tpu.workflow import WorkflowModel, _atomic_checkpoint

    model, path = _save_small_model(tmp_path)
    plan = resilience.FaultPlan().on(
        "checkpoint.write", error=OSError, at=[0])    # transient
    with resilience.fault_plan(plan):
        _atomic_checkpoint(model, path)               # absorbed by retry
    assert resilience.resilience_stats()["retries"] == 1
    assert WorkflowModel.load(path).result_features[0].name == "x"


# ---------------------------------------------------------------------------
# device-tier breakers (workflow engine + fitstats)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_engine_breaker_trips_to_host_tier_and_scores_survive():
    """Persistent device-dispatch faults: every score still succeeds via
    the per-layer host fallback, and after the threshold the breaker
    stops routing through the failing engine at all."""
    records = _records()
    pred = _three_layer_workflow()
    model = _train(records, pred)
    store_fn = lambda: model.score(records, engine=True)  # noqa: E731

    want = store_fn()[pred.name].prediction.copy()
    plan = resilience.FaultPlan().on("scoring.device_dispatch",
                                     error=IOError, probability=1.0)
    # per-model breaker held on the instance: one model's failing
    # engine must not downgrade other models in the process
    brk = model._engine_breaker()
    with resilience.fault_plan(plan):
        for _ in range(4):
            got = store_fn()[pred.name].prediction
            np.testing.assert_array_equal(got, want)
    assert brk.state == brk.OPEN
    fired_while_open = plan.fired("scoring.device_dispatch")
    # breaker open: the engine is not even attempted any more
    with resilience.fault_plan(plan):
        np.testing.assert_array_equal(
            store_fn()[pred.name].prediction, want)
    assert plan.fired("scoring.device_dispatch") == fired_while_open
    assert resilience.resilience_stats()["breaker_trips"] == 1
    # faults gone + breaker reset: the device tier serves again
    brk.reset()
    np.testing.assert_array_equal(store_fn()[pred.name].prediction, want)
    assert brk.state == brk.CLOSED


@pytest.mark.chaos
def test_failed_engine_build_retries_under_breaker(monkeypatch):
    """A failed engine BUILD is a breaker-governed attempt, not a
    permanent death sentence: attempts stop once the breaker opens, and
    the half-open probe rebuilds after the reset timeout."""
    import time

    import transmogrifai_tpu.scoring as sc

    records = _records()
    pred = _three_layer_workflow()
    model = _train(records, pred)
    real = sc.ScoringEngine
    builds = {"n": 0}

    class Boom:
        def __init__(self, *a, **k):
            builds["n"] += 1
            raise RuntimeError("transient build failure")

    monkeypatch.setattr(sc, "ScoringEngine", Boom)
    brk = model._engine_breaker()
    brk.reset_timeout_s = 0.02
    for _ in range(6):
        assert model.score(records, engine=True).n_rows == len(records)
    assert builds["n"] == 3            # no more builds once OPEN
    assert brk.state == brk.OPEN
    time.sleep(0.03)
    monkeypatch.setattr(sc, "ScoringEngine", real)
    model.score(records, engine=True)  # the probe rebuilds + dispatches
    assert brk.state == brk.CLOSED
    assert model.scoring_engine() is not None


@pytest.mark.chaos
def test_overlapped_device_failure_falls_back_to_host_not_quarantine(
        tmp_path):
    """In the overlapped scorer a device compute failure is a TIER
    failure: the batch retries on the per-layer host path and nothing is
    quarantined — every row still gets scored."""
    from transmogrifai_tpu.readers import stream_score
    records = _records()
    pred = _three_layer_workflow()
    model = _train(records, pred)
    batches = [records[i:i + 30] for i in range(0, len(records), 30)]
    clean = [s[pred.name].probability.copy()
             for s in stream_score(model, batches, overlap=True)]
    resilience.set_quarantine(str(tmp_path / "dead.jsonl"))
    plan = resilience.FaultPlan().on("scoring.device_dispatch",
                                     error=IOError, probability=1.0)
    with resilience.fault_plan(plan):
        faulted = [s[pred.name].probability.copy()
                   for s in stream_score(model, batches, overlap=True)]
    assert len(faulted) == len(clean)          # no batch lost
    for got, want in zip(faulted, clean):
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    stats = resilience.resilience_stats()
    assert stats["quarantined_batches"] == 0
    assert stats["breaker_trips"] == 1         # tier reported, not hidden


@pytest.mark.chaos
def test_fitstats_device_fault_degrades_to_host_within_pass(monkeypatch):
    """A failing fitstats device pass must not lose the fused scan: the
    SAME pass re-runs on the host tier and the fitted stats match the
    clean run bit-for-bit (host tier is the bit-exact twin)."""
    from transmogrifai_tpu import fitstats

    rng = np.random.default_rng(3)
    store = ColumnStore({
        "a": column_from_values(ft.Real, list(rng.normal(size=500))),
        "b": column_from_values(ft.Real, list(rng.normal(size=500))),
    })
    reqs = [fitstats.StatRequest("mean", "a"),
            fitstats.StatRequest("std", "a", params=(0,)),
            fitstats.StatRequest("mean", "b"),
            fitstats.StatRequest("count", "b")]
    plan_clean = fitstats.LayerStatsPlan(reqs, n_stages=2)
    clean = plan_clean.run(store, device=False)

    fault = resilience.FaultPlan().on("fitstats.device_pass",
                                      error=IOError, probability=1.0)
    with resilience.fault_plan(fault):
        faulted = fitstats.LayerStatsPlan(reqs, n_stages=2).run(
            store, device=True)
    for r in reqs:
        assert faulted.for_request(r) == clean.for_request(r)
    assert resilience.breaker("fitstats.device").consecutive_failures == 1
    # two more failures trip the breaker; the gate then refuses device
    for _ in range(2):
        with resilience.fault_plan(fault):
            fitstats.LayerStatsPlan(reqs, n_stages=2).run(store,
                                                          device=True)
    assert resilience.breaker("fitstats.device").state == "open"
    monkeypatch.setattr("transmogrifai_tpu.workflow._DEVICE_BW_MBPS",
                        1e9)
    monkeypatch.setattr("transmogrifai_tpu.workflow.FUSE_MIN_ROWS", 1)
    assert not fitstats.LayerStatsPlan(reqs)._gate_device(store)


# ---------------------------------------------------------------------------
# runner satellites: numeric param validation, quarantine sink, run doc
# ---------------------------------------------------------------------------


def _score_runner(records, pred, model_dir):
    from transmogrifai_tpu.readers import DataReaders
    from transmogrifai_tpu.runner import OpWorkflowRunner
    wf = Workflow().set_result_features(pred)
    return OpWorkflowRunner(
        wf, training_reader=DataReaders.simple.records(records),
        scoring_reader=DataReaders.simple.records(records))


def test_runner_validates_numeric_custom_params(tmp_path):
    from transmogrifai_tpu.runner import OpParams, RunType

    records = _records(60)
    pred = _three_layer_workflow()
    model = _train(records, pred)
    mdir = str(tmp_path / "model")
    model.save(mdir)
    runner = _score_runner(records, pred, mdir)

    for key, val, match in [
            ("timeoutS", "soon", "customParams.timeoutS"),
            ("maxBatches", "many", "customParams.maxBatches"),
            ("maxBatches", 2.5, "customParams.maxBatches"),
            ("maxBatches", 0, "customParams.maxBatches"),
            ("batchSize", -5, "customParams.batchSize"),
            ("batchSize", "lots", "customParams.batchSize"),
            # NaN slips past any `v < minimum` check and an inf/nan
            # timeoutS hangs the stream's exit test forever
            ("timeoutS", float("nan"), "customParams.timeoutS"),
            ("timeoutS", float("inf"), "customParams.timeoutS"),
            # int(1e400) raises OverflowError, not ValueError — JSON
            # happily parses huge floats
            ("maxBatches", float("inf"), "customParams.maxBatches")]:
        params = OpParams(model_location=mdir, custom_params={key: val})
        with pytest.raises(ValueError, match=match):
            runner.run(RunType.STREAMING_SCORE, params)
    # valid values still work, including numeric strings; an explicit
    # JSON null means "use the default", same as omitting the key
    for cp in ({"batchSize": "30"}, {"batchSize": None},
               {"timeoutS": None, "maxBatches": None}):
        params = OpParams(model_location=mdir, custom_params=cp)
        res = runner.run("StreamingScore", params)
        assert res.metrics["rowsScored"] == 60


@pytest.mark.chaos
def test_runner_streaming_score_stamps_quarantine_counts(tmp_path):
    from transmogrifai_tpu.runner import OpParams, RunType

    records = _records()
    pred = _three_layer_workflow()
    model = _train(records, pred)
    mdir = str(tmp_path / "model")
    model.save(mdir)
    runner = _score_runner(records, pred, mdir)
    qfile = str(tmp_path / "dead.jsonl")
    plan = resilience.FaultPlan(seed=6).on(
        "stream.score_batch", error=IOError, at=[1])
    params = OpParams(model_location=mdir, quarantine_location=qfile,
                      custom_params={"batchSize": 30})
    with resilience.fault_plan(plan):
        res = runner.run(RunType.STREAMING_SCORE, params)
    assert res.metrics["batches"] == 3             # 4 - 1 quarantined
    assert res.metrics["rowsScored"] == 90
    assert res.metrics["quarantinedBatches"] == 1
    assert res.metrics["resilience"]["quarantined_batches"] == 1
    entries = resilience.Quarantine(qfile).entries()
    assert len(entries) == 1 and entries[0]["index"] == 1
    # run-scoped: the sink is uninstalled after the run
    assert resilience.get_quarantine() is None
    # the run doc reports THIS run's events, not the process totals: a
    # clean follow-up run must stamp zeros
    res2 = runner.run(
        RunType.STREAMING_SCORE,
        OpParams(model_location=mdir, custom_params={"batchSize": 30}))
    assert res2.metrics["quarantinedBatches"] == 0
    assert res2.metrics["resilience"]["quarantined_batches"] == 0
    # without a quarantineLocation the runner follows the sink-aware
    # default too: the poison batch fails LOUDLY (its records would
    # land nowhere)
    plan3 = resilience.FaultPlan(seed=6).on(
        "stream.score_batch", error=IOError, at=[1])
    with resilience.fault_plan(plan3):
        with pytest.raises(IOError):
            runner.run(RunType.STREAMING_SCORE,
                       OpParams(model_location=mdir,
                                custom_params={"batchSize": 30}))


# ---------------------------------------------------------------------------
# serving + model_io artifact integrity (satellite)
# ---------------------------------------------------------------------------


def test_load_scoring_fn_rejects_truncated_and_tampered(tmp_path):
    from transmogrifai_tpu import serving

    records = _records()
    pred = _three_layer_workflow()
    model = _train(records, pred)
    art = str(tmp_path / "art")
    meta = serving.export_scoring_fn(model, art, records[:8])
    assert meta["blobBytes"] > 0 and meta["blobDigest"]
    serving.load_scoring_fn(art)                   # intact: loads

    blob = os.path.join(art, "scoring_fn.stablehlo")
    whole = open(blob, "rb").read()
    with open(blob, "wb") as fh:
        fh.write(whole[:len(whole) // 2])
    with pytest.raises(ValueError, match="truncated serving artifact"):
        serving.load_scoring_fn(art)

    with open(blob, "wb") as fh:                   # same size, bit flip
        fh.write(bytes([whole[0] ^ 0xFF]) + whole[1:])
    with pytest.raises(ValueError, match="digest"):
        serving.load_scoring_fn(art)

    with open(blob, "wb") as fh:                   # restore for meta test
        fh.write(whole)
    meta_path = os.path.join(art, "scoring_export.json")
    doc = json.load(open(meta_path))
    doc["blobBytes"] = "12a34"                     # damaged metadata
    json.dump(doc, open(meta_path, "w"))
    with pytest.raises(ValueError, match="non-numeric blobBytes"):
        serving.load_scoring_fn(art)

    os.remove(blob)
    with pytest.raises(ValueError, match="missing"):
        serving.load_scoring_fn(art)
    with pytest.raises(ValueError, match="no serving artifact"):
        serving.load_scoring_fn(str(tmp_path / "nowhere"))


def test_load_prediction_fn_rejects_corrupt_blob(tmp_path):
    from transmogrifai_tpu import serving

    records = _records()
    pred = _three_layer_workflow()
    model = _train(records, pred)
    art = str(tmp_path / "art")
    serving.export_prediction_fn(model, art)
    blob = os.path.join(art, "prediction_fn.stablehlo")
    with open(blob, "wb") as fh:
        fh.write(b"not stablehlo")
    with pytest.raises(ValueError, match="truncated serving artifact"):
        serving.load_prediction_fn(art)


def test_load_model_rejects_corrupt_weights_and_json(tmp_path):
    from transmogrifai_tpu.workflow import WorkflowModel

    _model, path = _save_small_model(tmp_path)
    doc = json.load(open(os.path.join(path, "model.json")))
    wf_file = os.path.join(path, doc["weightsFile"])

    whole = open(wf_file, "rb").read()
    with open(wf_file, "wb") as fh:
        fh.write(b"garbage, not a zip archive")
    with pytest.raises(ValueError, match="corrupt model weights"):
        WorkflowModel.load(path)
    with open(wf_file, "wb") as fh:                # empty file
        pass
    with pytest.raises(ValueError, match="corrupt model weights"):
        WorkflowModel.load(path)
    with open(wf_file, "wb") as fh:
        fh.write(whole)
    WorkflowModel.load(path)                       # restored: loads

    with open(os.path.join(path, "model.json"), "w") as fh:
        fh.write('{"uid": "trunc')
    with pytest.raises(ValueError, match="not valid JSON"):
        WorkflowModel.load(path)
