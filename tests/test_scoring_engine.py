"""Compiled batched scoring engine tests (scoring.py).

Parity discipline: the engine's single-program output must match the
per-layer reference path (``WorkflowModel._transform_layers``) and the
row-level ``score_fn`` closure on every model family — binary,
multiclass incl. DataCutter label de-mapping, regression — within f32
tolerance. Plus the bucket-ladder compile guard: arbitrary batch sizes
must never compile more programs than the ladder holds.
"""
import numpy as np
import pytest

from transmogrifai_tpu import (ColumnStore, FeatureBuilder, Workflow,
                               column_from_values)
from transmogrifai_tpu.columns import VectorColumn
from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                      DataCutter,
                                      LinearRegressionFamily,
                                      LogisticRegressionFamily,
                                      MultiClassificationModelSelector,
                                      RegressionModelSelector)
from transmogrifai_tpu.ops.sanity_checker import SanityChecker
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.scoring import (SCORING_MIN_ROWS, ScoringEngine,
                                       bucket_for, bucket_ladder)
from transmogrifai_tpu.types import feature_types as ft


def _records(n, rng, n_classes=2, labels=None):
    y_vals = labels if labels is not None else list(range(n_classes))
    y = np.asarray([y_vals[i % len(y_vals)] for i in range(n)], float)
    rng.shuffle(y)
    x1 = rng.normal(size=n) + y
    x2 = rng.normal(size=n)
    cats = ["a", "b", "c", None]
    return [{"label": float(y[i]), "x1": float(x1[i]), "x2": float(x2[i]),
             "cat": cats[i % 4]} for i in range(n)], y


def _features():
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
    f2 = FeatureBuilder.Real("x2").from_column().as_predictor()
    f3 = FeatureBuilder.PickList("cat").from_column().as_predictor()
    return label, [f1, f2, f3]


def _binary_model(rng, n=300, with_sanity=True):
    records, _ = _records(n, rng)
    label, feats = _features()
    vec = transmogrify(feats)
    if with_sanity:
        checker = SanityChecker(remove_bad_features=True,
                                remove_feature_group=False)
        label.transform_with(checker, vec)
        vec = checker.get_output()
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=7)
    pred = label.transform_with(selector, vec)
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())
    return model, records, pred


def _assert_store_parity(engine_store, classic_store, pred_name,
                         rtol=1e-5, atol=1e-6):
    assert sorted(engine_store.names()) == sorted(classic_store.names())
    for nm in classic_store.names():
        ce, cc = engine_store[nm], classic_store[nm]
        if nm == pred_name:
            np.testing.assert_allclose(ce.prediction, cc.prediction,
                                       rtol=rtol, atol=atol)
            np.testing.assert_allclose(ce.raw_prediction, cc.raw_prediction,
                                       rtol=rtol, atol=atol)
            np.testing.assert_allclose(ce.probability, cc.probability,
                                       rtol=rtol, atol=atol)
        elif isinstance(cc, VectorColumn):
            np.testing.assert_allclose(np.asarray(ce.values, np.float64),
                                       np.asarray(cc.values, np.float64),
                                       rtol=rtol, atol=atol)


def test_engine_parity_binary_full_chain(rng):
    """vec + combine + sanity-select + predict fuse into ONE program whose
    outputs match the per-layer path column-for-column."""
    model, records, pred = _binary_model(rng)
    eng = model.scoring_engine(gate_bandwidth=False)
    assert eng.covers_prediction
    kinds = {it.kind for it in eng._plan}
    assert {"vec", "combine", "select", "predict"} <= kinds

    classic = model._transform_layers(records)
    engined = eng.transform_store(records)
    _assert_store_parity(engined, classic, pred.name)

    # score mode pulls only results and matches the forced-classic score
    s_classic = model.score(records, engine=False)
    s_engine = eng.score_store(records)
    assert s_engine.names() == s_classic.names()
    np.testing.assert_allclose(s_engine[pred.name].probability,
                               s_classic[pred.name].probability,
                               rtol=1e-5, atol=1e-6)


def test_engine_matches_score_fn_rows(rng):
    """Row-serving closure and batched engine agree row-by-row."""
    model, records, pred = _binary_model(rng, n=200)
    eng = model.scoring_engine(gate_bandwidth=False)
    fn = model.score_fn()
    batch = eng.score_store(records[:9])
    col = batch[pred.name]
    for i in range(9):
        row_out = fn(records[i])[pred.name]
        assert row_out["prediction"] == pytest.approx(
            float(col.prediction[i]), rel=1e-5, abs=1e-6)
        assert row_out["probability_1"] == pytest.approx(
            float(col.probability[i, 1]), rel=1e-4, abs=1e-5)


def test_engine_parity_multiclass_label_demapping(rng):
    """DataCutter re-indexes {0,2,7}; the fused program must de-map class
    ids back to the original label values, matching the host path."""
    records, y = _records(240, rng, labels=[0.0, 2.0, 7.0])
    label, feats = _features()
    vec = transmogrify(feats)
    selector = MultiClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()],
        splitter=DataCutter(min_label_fraction=0.05), seed=3)
    pred = label.transform_with(selector, vec)
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())
    sel = model.stage_of(pred)
    assert sel.label_mapping == [0.0, 2.0, 7.0]

    eng = model.scoring_engine(gate_bandwidth=False)
    assert eng.covers_prediction
    classic = model.score(records, engine=False)
    engined = eng.score_store(records)
    np.testing.assert_allclose(engined[pred.name].prediction,
                               classic[pred.name].prediction,
                               rtol=1e-5, atol=1e-6)
    assert set(np.unique(engined[pred.name].prediction)) <= {0.0, 2.0, 7.0}
    np.testing.assert_allclose(engined[pred.name].probability,
                               classic[pred.name].probability,
                               rtol=1e-5, atol=1e-6)


def test_engine_parity_regression(rng):
    n = 200
    X = rng.normal(size=(n, 3))
    y = X @ np.array([1.0, 2.0, -1.0]) + 0.5
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "features": VectorColumn(ft.OPVector, X.astype(np.float32)),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    sel = RegressionModelSelector.with_train_validation_split(
        families=[LinearRegressionFamily(
            grid=[{"regParam": 0.0, "elasticNetParam": 0.0}])])
    pred = label.transform_with(sel, feats)
    model = (Workflow().set_input_store(store)
             .set_result_features(pred).train())
    eng = model.scoring_engine(gate_bandwidth=False)
    assert eng.covers_prediction      # direct-vector upload feeds predict
    classic = model.score(store, engine=False)
    engined = eng.score_store(store)
    np.testing.assert_allclose(engined[pred.name].prediction,
                               classic[pred.name].prediction,
                               rtol=1e-5, atol=1e-5)


def test_bucket_ladder_shapes():
    assert bucket_for(1, 64) == 8
    assert bucket_for(8, 64) == 8
    assert bucket_for(9, 64) == 16
    assert bucket_for(64, 64) == 64
    assert bucket_for(1000, 64) == 64          # cap-clamped; chunking covers
    assert bucket_ladder(64) == [8, 16, 32, 64]


def test_compile_count_bounded_by_ladder(rng):
    """≥6 distinct batch sizes must stay within the bucket ladder's
    program budget — no per-shape recompiles."""
    model, records, pred = _binary_model(rng, n=140, with_sanity=False)
    eng = model.scoring_engine(gate_bandwidth=False, bucket_cap=64)
    sizes = [1, 5, 9, 17, 33, 50, 64]
    for k in sizes:
        out = eng.score_store(records[:k])
        assert out.n_rows == k
    assert len(set(sizes)) >= 6
    assert eng.compile_count <= len(bucket_ladder(64))
    # same-bucket reuse: a size inside an already-compiled bucket is free
    before = eng.compile_count
    eng.score_store(records[:6])      # bucket 8, already compiled
    eng.score_store(records[:30])     # bucket 32, already compiled
    assert eng.compile_count == before


def test_chunking_beyond_bucket_cap(rng):
    """Batches larger than the cap stream through the largest bucket in
    chunks; stitched output matches the classic path."""
    model, records, pred = _binary_model(rng, n=150, with_sanity=False)
    eng = model.scoring_engine(gate_bandwidth=False, bucket_cap=64)
    classic = model.score(records, engine=False)
    engined = eng.score_store(records)
    assert engined.n_rows == 150
    np.testing.assert_allclose(engined[pred.name].probability,
                               classic[pred.name].probability,
                               rtol=1e-5, atol=1e-6)
    assert eng.compile_count <= len(bucket_ladder(64))


def test_stream_score_overlapped_parity(rng):
    """Overlapped streaming (host prep of batch k+1 concurrent with batch
    k's device compute) yields the same stores as per-batch scoring."""
    from transmogrifai_tpu.readers import stream_score

    model, records, pred = _binary_model(rng, n=160, with_sanity=False)
    batches = [records[i:i + 40] for i in range(0, 160, 40)]
    plain = [model.score(list(b), engine=False) for b in batches]
    overlapped = list(stream_score(model, batches, overlap=True))
    assert len(overlapped) == len(plain)
    for po, pp in zip(overlapped, plain):
        assert po.n_rows == pp.n_rows
        np.testing.assert_allclose(po[pred.name].probability,
                                   pp[pred.name].probability,
                                   rtol=1e-5, atol=1e-6)


def test_stream_score_auto_stays_classic_for_tiny_batches(rng):
    """overlap='auto' must not pay engine compilation for toy batches."""
    from transmogrifai_tpu.readers import stream_score

    model, records, pred = _binary_model(rng, n=80, with_sanity=False)
    batches = [records[i:i + 20] for i in range(0, 80, 20)]
    assert 20 < SCORING_MIN_ROWS
    outs = list(stream_score(model, batches))
    assert sum(o.n_rows for o in outs) == 80


def test_auto_routing_thresholds(rng):
    """score(engine='auto') stays on the per-layer path under
    SCORING_MIN_ROWS and can be forced either way."""
    model, records, pred = _binary_model(rng, n=60, with_sanity=False)
    eng = model.scoring_engine()
    assert eng is not None
    # tiny batch + auto → no engine programs compiled via score()
    before = eng.compile_count
    model.score(records)
    assert eng.compile_count == before
    # forced → engine path runs (compiles its program)
    out = model.score(records, engine=True)
    assert out.n_rows == 60
    assert eng.compile_count > before


def test_export_scoring_fn_roundtrip(rng, tmp_path):
    """Full-chain StableHLO artifact reproduces the engine's outputs from
    host-prepared blocks, batch-size polymorphically."""
    from transmogrifai_tpu.serving import export_scoring_fn, load_scoring_fn

    model, records, pred = _binary_model(rng, n=200, with_sanity=False)
    meta = export_scoring_fn(model, str(tmp_path), records[:8])
    assert meta["coverage"] == "fused_chain"
    assert meta["resultFeatures"] == [pred.name]

    fn = load_scoring_fn(str(tmp_path))
    eng = model.scoring_engine(gate_bandwidth=False)
    for n in (3, 17):
        sub = records[:n]
        store, prepared, uploads = eng.host_blocks(eng._raw_store(sub))
        blocks = {}
        for uid, d in prepared.items():
            for k, v in d.items():
                blocks[f"{uid}/{k}"] = v
        blocks.update(uploads)
        out = fn(blocks)
        ref = eng.score_store(sub)[pred.name]
        np.testing.assert_allclose(
            np.asarray(out[f"{pred.name}.probability"], np.float64),
            ref.probability, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(out[f"{pred.name}.prediction"], np.float64),
            ref.prediction, rtol=1e-5, atol=1e-6)


def test_evaluate_routes_identically(rng):
    """score_and_evaluate through the engine-backed transform matches the
    forced-classic metrics (the evaluator reads label + prediction from
    the transformed store)."""
    from transmogrifai_tpu.evaluators import Evaluators

    model, records, pred = _binary_model(rng, n=250, with_sanity=False)
    label_f = pred.origin_stage.input_features[0]
    ev = Evaluators.BinaryClassification.auPR().set_columns(
        label_f.name, pred.name)
    m_classic = model.evaluate(records, ev)
    # force the engine path by dropping the row threshold
    import transmogrifai_tpu.scoring as scoring
    old = scoring.SCORING_MIN_ROWS
    scoring.SCORING_MIN_ROWS = 1
    try:
        m_engine = model.evaluate(records, ev)
    finally:
        scoring.SCORING_MIN_ROWS = old
    for k, v in m_classic.items():
        if isinstance(v, float):
            assert m_engine[k] == pytest.approx(v, rel=1e-6, abs=1e-8)


def test_metadata_less_vector_input_combines_cleanly(rng):
    """A raw OPVector without metadata (e.g. an embedding column) through
    combine + sanity-select: the engine must mirror the host combiner's
    provenance-lost guard (metadata → None, data kept correct) instead of
    attaching undersized metadata and crashing the select."""
    n = 200
    y = rng.integers(0, 2, n).astype(float)
    emb = (rng.normal(size=(n, 4)) + y[:, None]).astype(np.float32)
    x1 = rng.normal(size=n) + y
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "x1": column_from_values(ft.Real, list(x1)),
        "emb": VectorColumn(ft.OPVector, emb, None),      # no metadata
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
    fe = FeatureBuilder.OPVector("emb").from_column().as_predictor()
    vec = transmogrify([f1, fe])
    checker = SanityChecker(remove_bad_features=False)
    label.transform_with(checker, vec)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=2)
    pred = label.transform_with(selector, checker.get_output())
    model = (Workflow().set_input_store(store)
             .set_result_features(pred).train())
    eng = model.scoring_engine(gate_bandwidth=False)
    classic = model._transform_layers(store)
    engined = eng.transform_store(store)       # must not raise
    np.testing.assert_allclose(engined[pred.name].probability,
                               classic[pred.name].probability,
                               rtol=1e-5, atol=1e-6)
    cname = checker.get_output().name
    assert engined[cname].metadata is None \
        or engined[cname].metadata.size == engined[cname].values.shape[1]


def test_host_prepare_amortized_across_calls(rng):
    """Repeat scoring of the SAME ColumnStore skips the host half (the
    score → evaluate pattern); distinct stores and opt-out never hit."""
    model, records, pred = _binary_model(rng, n=120, with_sanity=False)
    eng = model.scoring_engine(gate_bandwidth=False)
    store = eng._raw_store(records)
    pb1 = eng.prepare_batch(store)
    assert eng.prepare_batch(store) is pb1              # amortized
    pb_fresh = eng.prepare_batch(store, use_cache=False)
    assert pb_fresh is not pb1                          # opt-out
    store2 = eng._raw_store(records)
    assert eng.prepare_batch(store2) is not pb1         # identity-keyed
    out_cached = eng.run_batch(pb1)
    out_fresh = eng.run_batch(pb_fresh)
    np.testing.assert_allclose(out_cached[pred.name].probability,
                               out_fresh[pred.name].probability)


# -- satellite coverage ----------------------------------------------------

def test_drop_indices_by_validates_without_asserts(rng):
    """dsl._drop_indices_by raises ValueError (not AssertionError), so the
    validation survives ``python -O``."""
    from transmogrifai_tpu.stages.base import LambdaTransformer

    f = FeatureBuilder.OPVector("v").from_column().as_predictor()
    out = f.drop_indices_by(lambda cm: False)
    stage = out.origin_stage
    store = ColumnStore({
        "v": VectorColumn(ft.OPVector, np.zeros((3, 2), np.float32), None),
    })
    with pytest.raises(ValueError, match="metadata-carrying"):
        stage.transform(store)
    store2 = ColumnStore({"v": column_from_values(ft.Real, [1.0, 2.0])})
    with pytest.raises(ValueError, match="OPVector"):
        stage.transform(store2)


def test_device_put_cache_blake2b_content_keyed():
    """Content-equal arrays held by different objects hit the same cache
    entry; different content misses."""
    from transmogrifai_tpu.models.base import (_DEVICE_PUT_CACHE,
                                               _content_tag, device_put_f32)

    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    b = a.copy()
    assert _content_tag(a) == _content_tag(b)
    assert len(_content_tag(a)) == 16          # blake2b digest_size=16
    da = device_put_f32(a)
    db = device_put_f32(b)
    assert da is db
    c = a.copy()
    c[0, 0] += 1.0
    assert _content_tag(c) != _content_tag(a)
    assert device_put_f32(c) is not da


def test_native_so_staleness_gate(tmp_path):
    """_stale: .so older than fasthash.cc ⇒ rebuild wanted."""
    import os
    import time as _time

    from transmogrifai_tpu.ops.hashing import _stale

    src = tmp_path / "fasthash.cc"
    so = tmp_path / "lib.so"
    src.write_text("// src")
    so.write_text("so")
    now = _time.time()
    os.utime(src, (now - 100, now - 100))
    os.utime(so, (now, now))
    assert not _stale(str(so), str(src))
    os.utime(src, (now + 100, now + 100))
    assert _stale(str(so), str(src))
    assert not _stale(str(so), str(tmp_path / "missing.cc"))


def test_committed_native_binary_gone():
    """The prebuilt .so must not ride in git (it rebuilds lazily from
    fasthash.cc; the freshness gate keeps it current)."""
    import os
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        tracked = subprocess.run(
            ["git", "ls-files", "native/"], cwd=repo, capture_output=True,
            text=True, timeout=30).stdout
    except (OSError, subprocess.SubprocessError):
        pytest.skip("git unavailable")
    assert "libtmogtpu.so" not in tracked
