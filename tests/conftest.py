"""Test harness: force an 8-device virtual CPU mesh.

Replaces the reference's ``TestSparkContext`` (shared local[2] Spark session,
``utils/.../test/TestSparkContext.scala:36-80``): tests exercise distributed
behavior on 8 virtual host devices so every sharding/collective path runs in
CI without TPU hardware.

NOTE the axon TPU shim (sitecustomize) registers itself at interpreter start
and pins ``jax_platforms``; the env var alone is NOT enough — we must
override via ``jax.config.update`` before any backend is initialized.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 "
        "suite (-m 'not slow')")
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection tests (seeded "
        "resilience.FaultPlan, no real sleeps > 0.1s — tier-1 safe; "
        "run just these with -m chaos)")


@pytest.fixture(autouse=True)
def _reset_uids():
    from transmogrifai_tpu.utils import uid
    uid.reset()
    yield


@pytest.fixture
def rng():
    return np.random.default_rng(42)
