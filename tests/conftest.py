"""Test harness: force an 8-device virtual CPU mesh before jax import.

Replaces the reference's ``TestSparkContext`` (shared local[2] Spark session,
``utils/.../test/TestSparkContext.scala:36-80``): tests exercise distributed
behavior on 8 virtual host devices so every sharding/collective path runs in
CI without TPU hardware.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_uids():
    from transmogrifai_tpu.utils import uid
    uid.reset()
    yield


@pytest.fixture
def rng():
    return np.random.default_rng(42)
