"""RawFeatureFilter tests (RawFeatureFilterTest analog)."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, Workflow, column_from_values
from transmogrifai_tpu.filters import (FeatureDistribution, RawFeatureFilter,
                                       RawFeatureFilterResults, Summary)
from transmogrifai_tpu.types import feature_types as ft


def _features(names, response="label"):
    label = FeatureBuilder.RealNN(response).from_column().as_response()
    feats = {}
    for name, kind in names.items():
        builder = getattr(FeatureBuilder, kind)(name)
        feats[name] = builder.from_column().as_predictor()
    return label, feats


def _basic_store(rng, n=400):
    y = rng.integers(0, 2, size=n).astype(float)
    age = rng.normal(40, 10, size=n)
    mostly_null = np.where(rng.random(n) < 0.999, np.nan, 1.0)
    leaky_null = np.where(y > 0, 1.0, np.nan)  # null iff label=0
    text = np.array([rng.choice(["a", "b", "c"]) for _ in range(n)],
                    dtype=object)
    return ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "age": column_from_values(ft.Real, [None if np.isnan(v) else v for v in age]),
        "mostly_null": column_from_values(
            ft.Real, [None if np.isnan(v) else v for v in mostly_null]),
        "leaky_null": column_from_values(
            ft.Real, [None if np.isnan(v) else v for v in leaky_null]),
        "word": column_from_values(ft.Text, list(text)),
    })


def test_distribution_monoid_and_metrics(rng):
    vals = rng.normal(size=200)
    col = column_from_values(ft.Real, list(vals))
    from transmogrifai_tpu.filters.distribution import (
        distributions_of_column, summaries_of_column)
    summ = summaries_of_column("x", col)
    (d,) = distributions_of_column("x", col, bins=20, summaries=summ)
    assert d.count == 200 and d.nulls == 0
    assert d.distribution.sum() == pytest.approx(200)
    combined = d + d
    assert combined.count == 400
    assert combined.distribution.sum() == pytest.approx(400)
    assert d.fill_rate() == 1.0
    assert d.js_divergence(d) == pytest.approx(0.0, abs=1e-12)


def test_relative_fill_ratio_of_two_empty_features_is_one():
    """Regression: hi/lo with hi == lo == 0 used to return inf — a
    false maximal-drift signal for two identically-EMPTY features.
    0/0 is ratio 1 (maximally similar); only 0-vs-nonzero is inf."""
    from transmogrifai_tpu.filters.distribution import FeatureDistribution
    empty_a = FeatureDistribution("x", count=10, nulls=10)
    empty_b = FeatureDistribution("x", count=4, nulls=4)
    assert empty_a.relative_fill_ratio(empty_b) == 1.0
    assert empty_a.relative_fill_ratio(empty_a) == 1.0
    full = FeatureDistribution("x", count=10, nulls=0)
    assert empty_a.relative_fill_ratio(full) == float("inf")
    assert full.relative_fill_ratio(empty_a) == float("inf")


def test_summary_monoid():
    s = Summary.of_values(np.array([1.0, 5.0])) + Summary.of_values(
        np.array([-2.0]))
    assert s.min == -2.0 and s.max == 5.0 and s.count == 3


def test_filters_unfilled_and_leaky_nulls(rng):
    store = _basic_store(rng)
    label, feats = _features(
        {"age": "Real", "mostly_null": "Real", "leaky_null": "Real",
         "word": "Text"})
    raw = [label] + list(feats.values())
    rff = RawFeatureFilter(min_fill=0.10, max_correlation=0.9)
    out = rff.filter_raw(store, raw)
    bad = {f.name for f in out.blacklisted_features}
    assert "mostly_null" in bad      # fill rate ~0.001 < 0.10
    assert "leaky_null" in bad       # null indicator == 1 - label
    assert "age" not in bad and "word" not in bad
    assert "mostly_null" not in out.clean_store.names()
    reasons = {(r.name): r for r in out.results.exclusion_reasons}
    assert reasons["mostly_null"].training_unfilled_state
    assert reasons["leaky_null"].training_null_label_leaker


def test_js_divergence_detects_distribution_shift(rng):
    n = 500
    y = rng.integers(0, 2, size=n).astype(float)
    train = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "x": column_from_values(ft.Real, list(rng.normal(0, 1, n))),
        "shifted": column_from_values(ft.Real, list(rng.normal(0, 1, n))),
    })
    score = ColumnStore({
        "x": column_from_values(ft.Real, list(rng.normal(0, 1, n))),
        "shifted": column_from_values(ft.Real, list(rng.normal(50, 0.1, n))),
    })
    label, feats = _features({"x": "Real", "shifted": "Real"})
    raw = [label] + list(feats.values())
    rff = RawFeatureFilter(max_js_divergence=0.5)
    out = rff.filter_raw(train, raw, scoring_data=score)
    bad = {f.name for f in out.blacklisted_features}
    assert "shifted" in bad and "x" not in bad
    m = {r.name: r for r in out.results.metrics}
    assert m["shifted"].js_divergence > 0.5
    assert m["x"].js_divergence < 0.5


def test_protected_features_never_removed(rng):
    store = _basic_store(rng)
    label, feats = _features(
        {"age": "Real", "mostly_null": "Real", "leaky_null": "Real",
         "word": "Text"})
    raw = [label] + list(feats.values())
    rff = RawFeatureFilter(min_fill=0.10, max_correlation=0.9,
                           protected_features=["mostly_null", "leaky_null"])
    out = rff.filter_raw(store, raw)
    assert out.blacklisted_features == []


def test_map_keys_filtered_individually(rng):
    n = 300
    y = rng.integers(0, 2, size=n).astype(float)
    maps = []
    for i in range(n):
        d = {"good": float(rng.normal())}
        if rng.random() < 0.02:
            d["rare"] = 1.0
        maps.append(d)
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "m": column_from_values(ft.RealMap, maps),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    m = FeatureBuilder.RealMap("m").from_column().as_predictor()
    rff = RawFeatureFilter(min_fill=0.10)
    out = rff.filter_raw(store, [label, m])
    assert out.blacklisted_features == []          # map itself survives
    assert out.blacklisted_map_keys.get("m") == ["rare"]
    kept = out.clean_store["m"]
    assert set(kept.children) == {"good"}


def test_results_json_roundtrip(rng):
    store = _basic_store(rng)
    label, feats = _features(
        {"age": "Real", "mostly_null": "Real", "word": "Text"})
    raw = [label] + list(feats.values())
    out = RawFeatureFilter(min_fill=0.10).filter_raw(store, raw)
    d = out.results.to_json()
    back = RawFeatureFilterResults.from_json(d)
    assert back.config == out.results.config
    assert len(back.metrics) == len(out.results.metrics)
    assert back.exclusion_reasons[0].name == out.results.exclusion_reasons[0].name
    assert np.allclose(back.training_distributions[0].distribution,
                       out.results.training_distributions[0].distribution)


def test_workflow_integration(rng):
    """Workflow.with_raw_feature_filter drops blacklisted raw features before
    fitting (OpWorkflow.scala:112-154 DAG rewiring analog)."""
    n = 300
    y = rng.integers(0, 2, size=n).astype(float)
    x = rng.normal(size=n) + y
    dead = [None] * n
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "x": column_from_values(ft.Real, list(x)),
        "dead": column_from_values(ft.Real, dead),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    fdead = FeatureBuilder.Real("dead").from_column().as_predictor()

    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import BinaryClassificationModelSelector
    vec = transmogrify([fx, fdead])
    pred = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()]) \
        .set_input(label, vec).get_output()

    wf = (Workflow()
          .set_result_features(pred)
          .set_input_store(store)
          .with_raw_feature_filter(RawFeatureFilter(min_fill=0.10)))
    model = wf.train()
    assert {f.name for f in model.blacklisted_features} == {"dead"}
    scores = model.score(store)
    assert pred.name in scores.names()


def test_predictor_missing_from_scoring_store_is_excluded(rng):
    n = 200
    y = rng.integers(0, 2, size=n).astype(float)
    train = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "x": column_from_values(ft.Real, list(rng.normal(size=n))),
        "gone": column_from_values(ft.Real, list(rng.normal(size=n))),
    })
    score = ColumnStore({  # 'gone' entirely absent at scoring time
        "x": column_from_values(ft.Real, list(rng.normal(size=n))),
    })
    label, feats = _features({"x": "Real", "gone": "Real"})
    raw = [label] + list(feats.values())
    out = RawFeatureFilter(min_fill=0.10).filter_raw(
        train, raw, scoring_data=score)
    bad = {f.name for f in out.blacklisted_features}
    assert "gone" in bad and "x" not in bad
    r = {x.name: x for x in out.results.exclusion_reasons}
    assert r["gone"].scoring_unfilled_state


def test_map_key_missing_from_scoring_store_is_excluded(rng):
    """A map key present in training but absent from the scoring store must
    face the scoring-side gates via a synthesized all-null distribution
    (ADVICE r1; ref: empty scoring FeatureDistribution → fill rate 0)."""
    n = 200
    y = rng.integers(0, 2, size=n).astype(float)
    train_maps = [{"stays": float(rng.normal()),
                   "vanishes": float(rng.normal())} for _ in range(n)]
    score_maps = [{"stays": float(rng.normal())} for _ in range(n)]
    train = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "m": column_from_values(ft.RealMap, train_maps),
    })
    score = ColumnStore({"m": column_from_values(ft.RealMap, score_maps)})
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    m = FeatureBuilder.RealMap("m").from_column().as_predictor()
    out = RawFeatureFilter(min_fill=0.10).filter_raw(
        train, [label, m], scoring_data=score)
    assert out.blacklisted_map_keys.get("m") == ["vanishes"]
    r = {(x.name, x.key): x for x in out.results.exclusion_reasons}
    assert r[("m", "vanishes")].scoring_unfilled_state
    assert not r[("m", "stays")].excluded


def test_distribution_monoid_is_total(rng):
    """Adding a populated distribution to an empty-histogram accumulator
    must work from BOTH sides (ADVICE r1)."""
    from transmogrifai_tpu.filters.distribution import FeatureDistribution
    full = FeatureDistribution("f", None, 10, 2, np.array([1.0, 2.0, 3.0]),
                               [0.0, 1.0, 2.0, 3.0])
    empty = FeatureDistribution("f")
    for a, b in ((full, empty), (empty, full)):
        s = a + b
        assert s.count == 10 and s.nulls == 2
        assert np.allclose(s.distribution, full.distribution)
