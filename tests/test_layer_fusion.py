"""Layer fusion: device_compute must execute inside ONE jitted program per
DAG layer (VERDICT r1 #3), and host_prepare must be vectorized (no per-row
Python) so large stores transmogrify in seconds."""
import os
import time

import numpy as np

import transmogrifai_tpu.workflow as wf
from transmogrifai_tpu import FeatureBuilder, Workflow
from transmogrifai_tpu.columns import ColumnStore, column_from_values
from transmogrifai_tpu.dsl import transmogrify
from transmogrifai_tpu.types import feature_types as ft


def _store(n, rng):
    cats = np.array(["a", "b", "c", "d", None], dtype=object)
    return ColumnStore({
        "num": column_from_values(ft.Real, [
            float(v) if v > 0.1 else None for v in rng.random(n)]),
        "cat": column_from_values(ft.PickList,
                                  cats[rng.integers(0, 5, n)].tolist()),
        "txt": column_from_values(ft.Text, [
            f"word{i % 9973} tail{i % 31} common" if i % 7 else None
            for i in range(n)]),
    }, n)


def _features():
    num = FeatureBuilder.Real("num").from_column().as_predictor()
    cat = FeatureBuilder.PickList("cat").from_column().as_predictor()
    txt = FeatureBuilder.Text("txt").from_column().as_predictor()
    return transmogrify([num, cat, txt])


def test_device_compute_runs_under_jit(rng, monkeypatch):
    """With the fusion threshold lowered, every vectorizer's device_compute
    must be handed jax.numpy (traced into the layer program), never plain
    numpy."""
    import jax.numpy as jnp

    import transmogrifai_tpu.ops.vectorizer_base as vb

    monkeypatch.setattr(wf, "FUSE_MIN_ROWS", 1)
    monkeypatch.setattr(wf, "_DEVICE_BW_MBPS", float("inf"))
    seen_xp = []
    patched = set()

    orig_apply = wf.apply_layer_vectorized

    def spying_apply(models, s, fuse_min_rows=None):
        for m in models:
            cls = type(m)
            if isinstance(m, vb.VectorizerModel) and cls not in patched:
                patched.add(cls)
                orig_fn = cls.device_compute

                def spy(self, xp, prepared, _orig=orig_fn):
                    seen_xp.append(xp)
                    return _orig(self, xp, prepared)
                monkeypatch.setattr(cls, "device_compute", spy)
        return orig_apply(models, s, fuse_min_rows)

    monkeypatch.setattr(wf, "apply_layer_vectorized", spying_apply)

    store = _store(300, rng)
    vec = _features()
    flow = Workflow().set_input_store(store).set_result_features(vec)
    model = flow.train()
    out = model.transform(store)
    assert out[vec.name].values.shape[0] == 300

    assert seen_xp, "no vectorizer ran"
    assert any(xp is jnp for xp in seen_xp), \
        "device_compute never executed under the jitted layer program"
    assert not any(xp is np for xp in seen_xp), \
        "a vectorizer fell back to the numpy path despite fusion threshold"


def test_fusion_matches_numpy_path(rng, monkeypatch):
    """Fused (jit) and numpy layer transforms must agree exactly."""
    monkeypatch.setattr(wf, "_DEVICE_BW_MBPS", float("inf"))
    store = _store(500, rng)
    vec = _features()
    flow = Workflow().set_input_store(store).set_result_features(vec)
    model = flow.train()

    mats = {}
    for fuse in (1, 10**9):
        out = None
        try:
            wf.FUSE_MIN_ROWS, saved = fuse, wf.FUSE_MIN_ROWS
            out = model.transform(store)
        finally:
            wf.FUSE_MIN_ROWS = saved
        mats[fuse] = np.asarray(out[vec.name].values)
    np.testing.assert_allclose(mats[1], mats[10**9], rtol=1e-6, atol=1e-9)


_X64_OFF_SCRIPT = r"""
import os
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
assert not jax.config.jax_enable_x64  # the production (TPU) configuration

import numpy as np
import transmogrifai_tpu.workflow as wf
import transmogrifai_tpu.ops.vectorizer_base as vb
from transmogrifai_tpu import FeatureBuilder, Workflow
from transmogrifai_tpu.columns import ColumnStore, column_from_values
from transmogrifai_tpu.dsl import transmogrify
from transmogrifai_tpu.types import feature_types as ft

rng = np.random.default_rng(7)
n = 400
cats = np.array(["a", "b", "c", "d", None], dtype=object)
store = ColumnStore({
    "num": column_from_values(ft.Real, [
        float(v) if v > 0.1 else None for v in rng.random(n)]),
    "cat": column_from_values(ft.PickList,
                              cats[rng.integers(0, 5, n)].tolist()),
}, n)
num = FeatureBuilder.Real("num").from_column().as_predictor()
cat = FeatureBuilder.PickList("cat").from_column().as_predictor()
vec = transmogrify([num, cat])
model = Workflow().set_input_store(store).set_result_features(vec).train()

seen = []
patched = set()
orig_apply = wf.apply_layer_vectorized
def spying_apply(models, s, fuse_min_rows=None):
    for m in models:
        cls = type(m)
        if isinstance(m, vb.VectorizerModel) and cls not in patched:
            patched.add(cls)
            orig_fn = cls.device_compute
            def spy(self, xp, prepared, _o=orig_fn):
                seen.append(xp.__name__)
                return _o(self, xp, prepared)
            cls.device_compute = spy
    return orig_apply(models, s, fuse_min_rows)
wf.apply_layer_vectorized = spying_apply

wf._DEVICE_BW_MBPS = float("inf")
wf.FUSE_MIN_ROWS = 1
fused = np.asarray(model.transform(store)[vec.name].values)
assert "jax.numpy" in seen, f"fused path did not engage under x64-off: {seen}"
seen.clear()
wf.FUSE_MIN_ROWS = 10**9
host = np.asarray(model.transform(store)[vec.name].values)
assert "numpy" in seen and "jax.numpy" not in seen
np.testing.assert_array_equal(fused, host)  # bit-identical, no skew
print("OK")
"""


def test_fused_path_engages_with_x64_off():
    """The production TPU configuration runs x64-off; the f32-native
    pipeline must fuse there AND match the host path bit-for-bit (this was
    the round-2 gap: the fused layer was gated off exactly where it
    mattered)."""
    import subprocess
    import sys

    env = dict(os.environ)
    env.pop("JAX_ENABLE_X64", None)
    res = subprocess.run([sys.executable, "-c", _X64_OFF_SCRIPT],
                         capture_output=True, text=True, env=env, timeout=300)
    assert res.returncode == 0, res.stderr[-3000:]
    assert "OK" in res.stdout


def test_large_store_transmogrify_is_fast(rng):
    """100k rows (numeric + categorical + hashed text) must prepare in
    seconds — the r1 per-row Python loops took minutes at this scale."""
    n = 100_000
    store = _store(n, rng)
    vec = _features()
    flow = Workflow().set_input_store(store).set_result_features(vec)
    t0 = time.time()
    model = flow.train()
    dt = time.time() - t0
    out = model.transform(store)
    assert out[vec.name].values.shape[0] == n
    # generous bound (single shared CPU core, suite runs under load):
    # catches a per-row-Python regression, which is >60s at this scale
    assert dt < 30, f"transmogrify too slow: {dt:.1f}s"


def test_fused_layer_executes_on_tpu_when_gate_passes():
    """VERDICT r3 #4: on a DIRECTLY-attached TPU (bandwidth above the
    fusion gate) the fused transform layer must actually execute on the
    device. Skipped off-TPU and behind slow tunnels, where the gate
    correctly keeps transforms on host."""
    import pytest

    import jax
    if jax.default_backend() != "tpu":
        pytest.skip("needs a real TPU backend")
    bw = wf.device_roundtrip_mbps()
    if bw < wf.FUSE_MIN_BANDWIDTH_MBPS:
        pytest.skip(f"link too slow for fusion ({bw:.0f} MB/s)")

    devices_touched = []
    import jax.numpy as jnp
    orig = jnp.concatenate

    rng_l = np.random.default_rng(0)
    store = _store(int(wf.FUSE_MIN_ROWS + 1), rng_l)
    vec = _features()
    model = (Workflow().set_input_store(store)
             .set_result_features(vec).train())
    out = model.transform(store)
    col = out[vec.name]
    # the fused layer produced the vector ON DEVICE: transform again and
    # assert the layer program ran on the TPU by checking the jitted
    # cache was used with TPU-resident output
    assert wf.fusion_state()["fusion"] == "ON"
    assert len(wf._LAYER_JIT_CACHE) > 0, \
        "fusion gate ON but no fused layer program was compiled"
    assert col.values.shape[0] == store.n_rows
