"""CLI project generator tests (op gen analog, cli/.../CLI.scala)."""
import os
import subprocess
import sys

import pytest

from transmogrifai_tpu.cli import (generate_project, infer_feature_types,
                                   infer_problem_kind)

CSV = "/root/reference/test-data/PassengerDataAllWithHeader.csv"


def test_type_and_problem_inference():
    header, types = infer_feature_types(CSV)
    assert types["Age"] in ("Real", "Integral")
    assert types["Sex"] == "PickList"
    assert types["Name"] == "Text"
    assert infer_problem_kind(CSV, "Survived") == "binary"


def test_generated_project_trains(tmp_path):
    """The scaffolded app must actually run end-to-end: generate, then
    execute its Train run type in a subprocess."""
    files = generate_project(CSV, response="Survived", id_column="PassengerId",
                             name="TitanicApp", output=str(tmp_path))
    assert set(files) == {"features.py", "app.py", "params.json",
                          "README.md"}
    # shrink the sweep for the 1-core CPU test runner: LR only, 2 folds
    # (the generated default is the full reference grid — TPU-sized)
    app = (tmp_path / "app.py").read_text()
    app = app.replace(
        "BinaryClassificationModelSelector.with_cross_validation()",
        "BinaryClassificationModelSelector.with_cross_validation("
        "num_folds=2, families=[LogisticRegressionFamily()])")
    app = app.replace(
        "from transmogrifai_tpu.models import BinaryClassificationModelSelector",
        "from transmogrifai_tpu.models import BinaryClassificationModelSelector\n"
        "from transmogrifai_tpu.models.linear import LogisticRegressionFamily")
    (tmp_path / "app.py").write_text(app)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = "/root/repo"
    out = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu');"
         "import runpy; import sys; sys.argv=['app.py', '--run-type',"
         "'Train', '--params', 'params.json'];"
         "runpy.run_path('app.py', run_name='__main__')"],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=500)
    assert out.returncode == 0, out.stderr[-2000:]
    assert os.path.exists(tmp_path / "model" / "model.json")
    assert os.path.exists(tmp_path / "metrics.json")
