"""Multiclass topN × confidence-threshold metrics — exact-parity tests vs
hand-computed values (VERDICT r3 #6;
``OpMultiClassificationEvaluator.calculateThresholdMetrics``
``core/.../evaluators/OpMultiClassificationEvaluator.scala:154-229``).
"""
import numpy as np

from transmogrifai_tpu.evaluators import (MultiClassificationEvaluator,
                                          multiclass_threshold_metrics)


def _reference(labels, probs, top_ns, thresholds):
    """Direct per-row transliteration of the Scala computeMetrics."""
    n_t = len(thresholds)
    out = {k: [np.zeros(n_t, np.int64), np.zeros(n_t, np.int64)]
           for k in top_ns}
    for scores, label in zip(probs, labels):
        label = int(label)
        true_score = scores[label]
        order = np.argsort(-np.asarray(scores), kind="stable")
        top_score = scores[order[0]]
        true_cut = next((i for i, t in enumerate(thresholds)
                         if t > true_score), n_t)
        max_cut = next((i for i, t in enumerate(thresholds)
                        if t > top_score), n_t)
        for k in top_ns:
            topk = order[:k]
            cor, inc = out[k]
            if label in topk:
                cor[0:true_cut] += 1
                inc[true_cut:max_cut] += 1
            else:
                inc[0:max_cut] += 1
    return out


def test_threshold_metrics_match_reference_semantics():
    rng = np.random.default_rng(5)
    n, k = 400, 4
    probs = rng.dirichlet(np.ones(k), size=n)
    labels = rng.integers(0, k, n).astype(float)
    thresholds = np.linspace(0.0, 1.0, 101)
    got = multiclass_threshold_metrics(labels, probs, top_ns=(1, 3),
                                       thresholds=thresholds)
    want = _reference(labels, probs, (1, 3), thresholds)
    for topn in (1, 3):
        cor, inc = want[topn]
        assert got["correctCounts"][topn] == cor.tolist()
        assert got["incorrectCounts"][topn] == inc.tolist()
        nop = np.asarray(got["noPredictionCounts"][topn])
        # the three counts partition the rows at every threshold
        assert (np.asarray(got["correctCounts"][topn])
                + np.asarray(got["incorrectCounts"][topn]) + nop == n).all()
        assert got["noPredictionCounts"][topn] == (n - cor - inc).tolist()


def test_threshold_metrics_hand_computed():
    """Tiny fixture checked by hand. thresholds = [0.0, 0.5, 0.9].

    row0: probs (0.6, 0.3, 0.1), label 0 → top1 hit, true=0.6 max=0.6:
          correct at t∈{0.0, 0.5}, noPred at 0.9.
    row1: probs (0.6, 0.3, 0.1), label 1 → top1 MISS (incorrect while
          max ≥ t: t∈{0.0, 0.5}); top3 hit with true=0.3: correct at 0.0,
          incorrect at 0.5 (true < t ≤ max — the serving-threshold case),
          noPred at 0.9.
    row2: probs (0.2, 0.1, 0.7), label 2 → hit, true=max=0.7: correct at
          {0.0, 0.5}, noPred at 0.9.
    """
    probs = np.array([[0.6, 0.3, 0.1], [0.6, 0.3, 0.1], [0.2, 0.1, 0.7]])
    labels = np.array([0.0, 1.0, 2.0])
    got = multiclass_threshold_metrics(labels, probs, top_ns=(1, 3),
                                       thresholds=[0.0, 0.5, 0.9])
    assert got["correctCounts"][1] == [2, 2, 0]
    assert got["incorrectCounts"][1] == [1, 1, 0]
    assert got["noPredictionCounts"][1] == [0, 0, 3]
    assert got["correctCounts"][3] == [3, 2, 0]
    assert got["incorrectCounts"][3] == [0, 1, 0]
    assert got["noPredictionCounts"][3] == [0, 0, 3]


def test_evaluator_bundle_includes_threshold_metrics():
    from transmogrifai_tpu.columns import (ColumnStore, PredictionColumn,
                                           column_from_values)
    from transmogrifai_tpu.types import feature_types as ft

    y = np.array([0.0, 1.0, 2.0, 1.0])
    prob = np.array([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1],
                     [0.3, 0.3, 0.4], [0.5, 0.4, 0.1]])
    pred = prob.argmax(1).astype(float)
    store = ColumnStore({
        "y": column_from_values(ft.RealNN, y),
        "p": PredictionColumn(pred, prob, prob),
    })
    ev = MultiClassificationEvaluator(label_col="y", prediction_col="p")
    out = ev.evaluate_all(store)
    assert {"Precision", "Recall", "F1", "Error"} <= set(out)
    tm = out["ThresholdMetrics"]
    assert tm["topNs"] == [1, 3]
    assert len(tm["thresholds"]) == 101      # 0.00..1.00 step 0.01
    n = len(y)
    assert all(c + i + np.asarray(tm["noPredictionCounts"][t]) [j] == n
               for t in (1, 3)
               for j, (c, i) in enumerate(zip(tm["correctCounts"][t],
                                              tm["incorrectCounts"][t])))
