"""Workflow persistence round-trip (OpWorkflowModelReaderWriterTest analog)."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, Workflow, column_from_values
from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                      LogisticRegressionFamily)
from transmogrifai_tpu.ops import transmogrify
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.workflow import WorkflowModel


def _make_store(n=120, seed=3):
    rng = np.random.default_rng(seed)
    age = rng.normal(40, 10, size=n)
    age[rng.random(n) < 0.2] = np.nan
    cls = rng.integers(1, 4, size=n).astype(float)
    sex = rng.choice(["m", "f"], size=n)
    y = ((sex == "f") | (rng.random(n) < 0.2)).astype(float)
    return ColumnStore.from_dict({
        "age": (ft.Real, [None if np.isnan(a) else a for a in age]),
        "cls": (ft.Integral, cls.tolist()),
        "sex": (ft.PickList, sex.tolist()),
        "y": (ft.RealNN, y.tolist()),
    })


def test_save_load_roundtrip(tmp_path):
    store = _make_store()
    y = FeatureBuilder.RealNN("y").from_column().as_response()
    age = FeatureBuilder.Real("age").from_column().as_predictor()
    cls = FeatureBuilder.Integral("cls").from_column().as_predictor()
    sex = FeatureBuilder.PickList("sex").from_column().as_predictor()
    vec = transmogrify([age, cls, sex])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])])
    pred = y.transform_with(sel, vec)
    model = Workflow().set_input_store(store).set_result_features(pred).train()

    scored1 = model.score(store)
    path = str(tmp_path / "model")
    model.save(path)

    loaded = WorkflowModel.load(path)
    scored2 = loaded.score(store)
    np.testing.assert_allclose(scored1[pred.name].prediction,
                               scored2[pred.name].prediction)
    np.testing.assert_allclose(scored1[pred.name].probability,
                               scored2[pred.name].probability, atol=1e-12)

    # row-level serving from the loaded model
    fn = loaded.score_fn()
    row = store.row(0)
    out = fn(row)
    assert abs(out[pred.name]["prediction"]
               - scored1[pred.name].prediction[0]) < 1e-9

    # overwrite protection
    with pytest.raises(FileExistsError):
        model.save(path)
    model.save(path, overwrite=True)


def test_loaded_model_summary(tmp_path):
    store = _make_store()
    y = FeatureBuilder.RealNN("y").from_column().as_response()
    age = FeatureBuilder.Real("age").from_column().as_predictor()
    vec = transmogrify([age])
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])])
    pred = y.transform_with(sel, vec)
    model = Workflow().set_input_store(store).set_result_features(pred).train()
    path = str(tmp_path / "m")
    model.save(path)
    loaded = WorkflowModel.load(path)
    assert loaded.uid == model.uid
    assert {f.name for f in loaded.result_features} == \
        {f.name for f in model.result_features}


def test_golden_model_pins_format(rng):
    """A serialized model checked into the repo must keep loading and
    producing identical scores — pins the persistence format across
    refactors (OpWorkflowModelReaderWriterTest OldModelVersion analog)."""
    import json
    import os

    from transmogrifai_tpu.workflow import WorkflowModel

    path = os.path.join(os.path.dirname(__file__), "resources",
                        "golden_model_v1")
    expected = json.load(open(os.path.join(path, "expected.json")))
    model = WorkflowModel.load(path)
    scored = model.score(expected["rows"])
    pcol = scored[expected["pred_name"]]
    np.testing.assert_allclose(
        np.asarray(pcol.prediction), expected["expected_pred"])
    np.testing.assert_allclose(
        np.asarray(pcol.probability[:, 1]), expected["expected_prob1"],
        rtol=1e-6)


def test_checkpoint_resume_after_crash(rng, tmp_path):
    """Layer-granular checkpointing + warm-start resume: kill training
    after the feature layers, resume, and the already-fitted stages are
    not refit (failure-recovery subsystem; VERDICT r1 item 58)."""
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.workflow import WorkflowModel

    n = 150
    y = rng.integers(0, 2, n).astype(float)
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "x": column_from_values(ft.Real, list(rng.normal(size=n) + y)),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    vec = transmogrify([fx])
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None)
    pred = label.transform_with(selector, vec)
    ckpt = str(tmp_path / "ckpt")

    # crash mid-train: fail the selector's fit on the first attempt
    calls = {"n": 0}
    orig = selector.fit_columns

    def crashing(store_):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("simulated preemption")
        return orig(store_)
    selector.fit_columns = crashing

    wf = (Workflow().set_input_store(store).set_result_features(pred)
          .with_checkpointing(ckpt))
    with pytest.raises(RuntimeError, match="preemption"):
        wf.train()

    # the vectorizer layer made it into the checkpoint
    partial = WorkflowModel.load(ckpt)
    assert partial.fitted_stages and \
        selector.uid not in partial.fitted_stages

    # resume: warm-start from the checkpoint; only the selector refits
    wf2 = (Workflow().set_input_store(store).set_result_features(pred)
           .with_model_stages(partial))
    model = wf2.train()
    m = model.stage_metrics[vec.origin_stage.uid]
    assert m.get("warmStarted") is True
    assert model.score(store).n_rows == n


def test_obj_codec_allowlist_and_var_kwargs():
    """The structural config codec only instantiates registered config
    base classes, and round-trips **kwargs-captured settings."""
    from transmogrifai_tpu import model_io
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily

    fam = LogisticRegressionFamily(grid=[{"regParam": 0.5,
                                          "elasticNetParam": 0.0}],
                                   some_fixed=7)
    arrays = {}
    enc = model_io._encode_param(fam, arrays, "t")
    back = model_io._decode_param(enc, arrays)
    assert type(back) is LogisticRegressionFamily
    assert back.grid == fam.grid
    assert back.fixed == {"some_fixed": 7}

    # out-of-package module: rejected BEFORE import (importing executes
    # the module's top-level code)
    evil = {"__obj__": "os:system", "params": {}}
    with pytest.raises(ValueError, match="Refusing to import"):
        model_io._decode_param(evil, {})
    # in-package but not a registered codec base: import ok, instantiate
    # refused
    sneaky = {"__obj__": "transmogrifai_tpu.model_io:save_workflow_model",
              "params": {}}
    with pytest.raises(ValueError, match="Refusing to instantiate"):
        model_io._decode_param(sneaky, {})


def test_checkpoint_swap_crash_windows(rng, tmp_path):
    """A preemption between the checkpoint swap's renames leaves the save
    at <dir>.tmp (complete) and the previous one at <dir>.old; load
    recovers from either, preferring .tmp (workflow._atomic_checkpoint /
    model_io._recover_checkpoint)."""
    import os
    import shutil

    from transmogrifai_tpu.workflow import WorkflowModel

    n = 60
    store = ColumnStore({
        "x": column_from_values(ft.Real, list(rng.normal(size=n))),
    })
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    model = (Workflow().set_input_store(store)
             .set_result_features(fx).train())
    ckpt = str(tmp_path / "ckpt")
    model.save(ckpt)

    # window: dir renamed away, tmp not yet renamed in
    shutil.copytree(ckpt, ckpt + ".tmp")
    os.rename(ckpt, ckpt + ".old")
    loaded = WorkflowModel.load(ckpt)
    assert loaded.result_features[0].name == "x"
    assert os.path.exists(ckpt)           # recovered sibling renamed in

    # window: only .old remains (torn .tmp was discarded by next cycle).
    # The .old leftover from the first recovery is cleared by the next
    # checkpoint cycle; do the same here.
    shutil.rmtree(ckpt + ".old")
    os.rename(ckpt, ckpt + ".old")
    loaded = WorkflowModel.load(ckpt)
    assert loaded.result_features[0].name == "x"


def test_direct_overwrite_save_survives_midsave_crash(tmp_path, monkeypatch):
    """ADVICE r2: a crash during an overwriting direct save (runner's
    model.save(loc, overwrite=True)) must leave the PREVIOUS save loadable
    — the marker always references a fully-written weights file."""
    from transmogrifai_tpu import model_io

    store = _make_store()
    y = FeatureBuilder.RealNN("y").from_column().as_response()
    age = FeatureBuilder.Real("age").from_column().as_predictor()
    vec = transmogrify([age])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])])
    pred = y.transform_with(sel, vec)
    model = Workflow().set_input_store(store).set_result_features(pred).train()
    loc = str(tmp_path / "m")
    model.save(loc)
    before = model_io.load_workflow_model(loc)

    real_savez = np.savez

    def dying_savez(path, **arrays):
        real_savez(path, **{k: v for k, v in list(arrays.items())[:1]})
        raise OSError("disk full mid-weights-write")

    monkeypatch.setattr(np, "savez", dying_savez)
    try:
        model.save(loc, overwrite=True)
    except OSError:
        pass
    monkeypatch.setattr(np, "savez", real_savez)

    after = model_io.load_workflow_model(loc)   # old save intact
    assert sorted(after.fitted_stages) == sorted(before.fitted_stages)
