"""Workflow persistence round-trip (OpWorkflowModelReaderWriterTest analog)."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, Workflow
from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                      LogisticRegressionFamily)
from transmogrifai_tpu.ops import transmogrify
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.workflow import WorkflowModel


def _make_store(n=120, seed=3):
    rng = np.random.default_rng(seed)
    age = rng.normal(40, 10, size=n)
    age[rng.random(n) < 0.2] = np.nan
    cls = rng.integers(1, 4, size=n).astype(float)
    sex = rng.choice(["m", "f"], size=n)
    y = ((sex == "f") | (rng.random(n) < 0.2)).astype(float)
    return ColumnStore.from_dict({
        "age": (ft.Real, [None if np.isnan(a) else a for a in age]),
        "cls": (ft.Integral, cls.tolist()),
        "sex": (ft.PickList, sex.tolist()),
        "y": (ft.RealNN, y.tolist()),
    })


def test_save_load_roundtrip(tmp_path):
    store = _make_store()
    y = FeatureBuilder.RealNN("y").from_column().as_response()
    age = FeatureBuilder.Real("age").from_column().as_predictor()
    cls = FeatureBuilder.Integral("cls").from_column().as_predictor()
    sex = FeatureBuilder.PickList("sex").from_column().as_predictor()
    vec = transmogrify([age, cls, sex])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])])
    pred = y.transform_with(sel, vec)
    model = Workflow().set_input_store(store).set_result_features(pred).train()

    scored1 = model.score(store)
    path = str(tmp_path / "model")
    model.save(path)

    loaded = WorkflowModel.load(path)
    scored2 = loaded.score(store)
    np.testing.assert_allclose(scored1[pred.name].prediction,
                               scored2[pred.name].prediction)
    np.testing.assert_allclose(scored1[pred.name].probability,
                               scored2[pred.name].probability, atol=1e-12)

    # row-level serving from the loaded model
    fn = loaded.score_fn()
    row = store.row(0)
    out = fn(row)
    assert abs(out[pred.name]["prediction"]
               - scored1[pred.name].prediction[0]) < 1e-9

    # overwrite protection
    with pytest.raises(FileExistsError):
        model.save(path)
    model.save(path, overwrite=True)


def test_loaded_model_summary(tmp_path):
    store = _make_store()
    y = FeatureBuilder.RealNN("y").from_column().as_response()
    age = FeatureBuilder.Real("age").from_column().as_predictor()
    vec = transmogrify([age])
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])])
    pred = y.transform_with(sel, vec)
    model = Workflow().set_input_store(store).set_result_features(pred).train()
    path = str(tmp_path / "m")
    model.save(path)
    loaded = WorkflowModel.load(path)
    assert loaded.uid == model.uid
    assert {f.name for f in loaded.result_features} == \
        {f.name for f in model.result_features}


def test_golden_model_pins_format(rng):
    """A serialized model checked into the repo must keep loading and
    producing identical scores — pins the persistence format across
    refactors (OpWorkflowModelReaderWriterTest OldModelVersion analog)."""
    import json
    import os

    from transmogrifai_tpu.workflow import WorkflowModel

    path = os.path.join(os.path.dirname(__file__), "resources",
                        "golden_model_v1")
    expected = json.load(open(os.path.join(path, "expected.json")))
    model = WorkflowModel.load(path)
    scored = model.score(expected["rows"])
    pcol = scored[expected["pred_name"]]
    np.testing.assert_allclose(
        np.asarray(pcol.prediction), expected["expected_pred"])
    np.testing.assert_allclose(
        np.asarray(pcol.probability[:, 1]), expected["expected_prob1"],
        rtol=1e-6)
