"""Text processing suite tests (TextTokenizer / OpCountVectorizer /
NGramSimilarity / parser analogs)."""
import base64

import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, column_from_values
from transmogrifai_tpu.ops.text import (STOPWORDS, TextTokenizer,
                                        detect_language, stem, tokenize)
from transmogrifai_tpu.ops.text_suite import (EmailParser, MimeTypeDetector,
                                              NGramSimilarity,
                                              OpCountVectorizer,
                                              PhoneNumberParser, UrlParser,
                                              detect_mime, parse_email,
                                              parse_phone, parse_url)
from transmogrifai_tpu.types import feature_types as ft


def test_tokenizer_pipeline():
    toks = tokenize("The Quick brown foxes были Jumping!",
                    remove_stopwords=True, stemming=True)
    assert "the" not in toks            # stopword removed
    assert "fox" in toks                # plural stemmed
    assert "jump" in toks               # -ing stripped


def test_language_detection():
    assert detect_language("the cat is on the mat and it is happy") == "en"
    assert detect_language("el gato esta en la casa y no quiere salir") == "es"
    assert detect_language("der Hund ist in dem Haus und die Katze auch") == "de"
    assert detect_language("le chien est dans la maison avec les chats") == "fr"
    assert detect_language("xyzzy plugh") == "en"   # no signal → default


def test_stopword_removal_per_language():
    toks = tokenize("der schnelle braune Fuchs", remove_stopwords=True,
                    auto_detect_language=True)
    assert "der" not in toks


def test_count_vectorizer(rng):
    docs = [["a", "b", "a"], ["b", "c"], ["a"], []]
    store = ColumnStore({"t": column_from_values(ft.TextList, docs)})
    f = FeatureBuilder.TextList("t").from_column().as_predictor()
    est = OpCountVectorizer(vocab_size=2, min_df=1)
    est.set_input(f)
    model = est.fit(store)
    # doc freq: a=3? no — per-doc unique: a in docs 0,2 → 2; b in 0,1 → 2;
    # c → 1. vocab_size=2 keeps [a, b] (count desc, token asc)
    assert model.vocabs == [["a", "b"]]
    out = model.transform(store)
    mat = np.asarray(out[model.output_name].values)
    np.testing.assert_allclose(mat, [[2, 1], [0, 1], [1, 0], [0, 0]])


def test_ngram_similarity():
    store = ColumnStore({
        "a": column_from_values(ft.Text, ["hello world", "abc", None]),
        "b": column_from_values(ft.Text, ["hello world", "xyz", "q"]),
    })
    fa = FeatureBuilder.Text("a").from_column().as_predictor()
    fb = FeatureBuilder.Text("b").from_column().as_predictor()
    sim = NGramSimilarity(n=3)
    sim.set_input(fa, fb)
    col = sim.transform_columns(store)
    assert col.values[0] == pytest.approx(1.0)
    assert col.values[1] < 0.3
    assert not col.mask[2]              # null input → null output


def test_email_parsing():
    assert parse_email("jane.doe@example.com") == ("jane.doe", "example.com")
    assert parse_email("not-an-email") == (None, None)
    assert parse_email(None) == (None, None)

    store = ColumnStore({"e": column_from_values(
        ft.Email, ["a@b.com", "bad", None])})
    f = FeatureBuilder.Email("e").from_column().as_predictor()
    p = EmailParser(part="domain")
    p.set_input(f)
    out = p.transform_columns(store)
    assert out.values.tolist() == ["b.com", None, None]


def test_phone_parsing():
    assert parse_phone("+1 (650) 555-1234") == (True, "6505551234")
    assert parse_phone("650-555-1234", "US") == (True, "6505551234")
    assert parse_phone("+44 20 7946 0958") == (True, "2079460958")
    assert parse_phone("12345", "US") == (False, "12345")
    assert parse_phone("+999 123") == (False, None)
    assert parse_phone(None) == (False, None)

    store = ColumnStore({"p": column_from_values(
        ft.Phone, ["+16505551234", "123", None])})
    f = FeatureBuilder.Phone("p").from_column().as_predictor()
    v = PhoneNumberParser(output="valid")
    v.set_input(f)
    col = v.transform_columns(store)
    assert col.values[:2].tolist() == [True, False]
    assert not col.mask[2]


def test_url_parsing():
    assert parse_url("https://docs.example.org/a?b=1") == \
        ("https", "docs.example.org")
    assert parse_url("ftp://files.example.com") == ("ftp", "files.example.com")
    assert parse_url("nonsense") == (None, None)


def test_mime_detection():
    pdf = base64.b64encode(b"%PDF-1.4 rest").decode()
    png = base64.b64encode(b"\x89PNG\r\n\x1a\n....").decode()
    txt = base64.b64encode(b"just plain text here").decode()
    assert detect_mime(pdf) == "application/pdf"
    assert detect_mime(png) == "image/png"
    assert detect_mime(txt) == "text/plain"
    assert detect_mime("!!!not base64!!!") is None
    assert detect_mime(None) is None


def test_dsl_text_methods(rng):
    store = ColumnStore({
        "email": column_from_values(ft.Email, ["x@y.com", "z@w.org"]),
        "desc": column_from_values(ft.Text, ["big red dog", "small red cat"]),
    })
    email = FeatureBuilder.Email("email").from_column().as_predictor()
    desc = FeatureBuilder.Text("desc").from_column().as_predictor()
    dom = email.to_email_domain()
    toks = desc.tokenize()
    counted = toks.count_vectorize(vocab_size=8)
    from transmogrifai_tpu import Workflow
    wf = Workflow().set_input_store(store).set_result_features(dom, counted)
    model = wf.train()
    out = model.transform(store)
    assert out[dom.name].values.tolist() == ["y.com", "w.org"]
    assert np.asarray(out[counted.name].values).sum() == 6.0


def test_ner_heuristic():
    from transmogrifai_tpu.ops.text_suite import NameEntityRecognizer
    store = ColumnStore({"t": column_from_values(ft.Text, [
        "Yesterday John Smith met Maria Garcia in New York.",
        "the quick brown fox", None])})
    f = FeatureBuilder.Text("t").from_column().as_predictor()
    ner = NameEntityRecognizer()
    ner.set_input(f)
    out = ner.transform_columns(store)
    ents = out.values[0]
    assert "John Smith" in ents and "Maria Garcia" in ents
    assert "New York" in ents
    assert out.values[1] == set() and out.values[2] == set()


def test_lda_topics(rng):
    """OpLDA separates two disjoint-vocabulary topics."""
    from transmogrifai_tpu.ops.topics import OpLDA
    sports = "game team score win player coach ball".split()
    cooking = "recipe oven flour sugar bake taste salt".split()
    docs = []
    for i in range(60):
        pool = sports if i % 2 == 0 else cooking
        docs.append([str(rng.choice(pool)) for _ in range(12)])
    store = ColumnStore({"t": column_from_values(ft.TextList, docs)})
    f = FeatureBuilder.TextList("t").from_column().as_predictor()
    est = OpLDA(n_topics=2, n_iter=80, seed=1)
    est.set_input(f)
    model = est.fit(store)
    theta = np.asarray(model.transform(store)[model.output_name].values)
    assert theta.shape == (60, 2)
    np.testing.assert_allclose(theta.sum(axis=1), 1.0, rtol=1e-5)
    # docs of the same class land on the same dominant topic
    dom = theta.argmax(axis=1)
    sports_dom = dom[::2]
    cooking_dom = dom[1::2]
    assert (sports_dom == sports_dom[0]).mean() > 0.9
    assert (cooking_dom == cooking_dom[0]).mean() > 0.9
    assert sports_dom[0] != cooking_dom[0]


def test_word2vec_embeddings(rng):
    """OpWord2Vec puts co-occurring tokens closer than unrelated ones."""
    from transmogrifai_tpu.ops.topics import OpWord2Vec
    docs = []
    for _ in range(200):
        docs.append(["king", "queen", "royal"])
        docs.append(["apple", "banana", "fruit"])
    store = ColumnStore({"t": column_from_values(ft.TextList, docs)})
    f = FeatureBuilder.TextList("t").from_column().as_predictor()
    est = OpWord2Vec(dim=16, epochs=100, lr=0.5, window=2, seed=0, min_count=1)
    est.set_input(f)
    model = est.fit(store)
    vec = {t: model.vectors[i] for i, t in enumerate(model.vocab)}

    def cos(a, b):
        return float(np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b)))
    assert cos(vec["king"], vec["queen"]) > cos(vec["king"], vec["banana"])
    out = model.transform(store)
    assert np.asarray(out[model.output_name].values).shape == (400, 16)


def test_hashed_text_block_native_parity(rng):
    """The fused C++ tokenize+hash+scatter kernel is bit-exact with the
    Python tokenize_simple+murmur3 path for ASCII text, and routes
    non-ASCII rows through the exact Python fallback (the parity claim
    native/fasthash.cc makes)."""
    import numpy as np
    from transmogrifai_tpu.ops._hostvec import hashed_text_block
    from transmogrifai_tpu.ops.hashing import _load_native, hash_tokens
    from transmogrifai_tpu.ops.text import tokenize_simple

    import pytest
    lib = _load_native()
    if not lib or getattr(lib, "tokenized_hash_counts", None) is None:
        pytest.skip("native kernel unavailable: the comparison would be "
                    "the Python path against itself")

    alphabet = list("abcXYZ0189_'() .,-!@é漢")
    texts = []
    for i in range(600):
        n_tok = int(rng.integers(0, 8))
        texts.append(" ".join(
            "".join(rng.choice(alphabet, size=int(rng.integers(1, 10))))
            for _ in range(n_tok)))
    texts += [None, "", "don't stop", "a_b c3", "Ümlaut mixé", "…", "x"]
    n, W, seed = len(texts), 64, 7

    out = np.zeros((n, W + 3), np.float32)      # wider mat + offset slice
    nullf = hashed_text_block(texts, W, seed, False, out=out, col_offset=2)
    ref = np.zeros((n, W), np.float32)
    for i, t in enumerate(texts):
        for tok in tokenize_simple(t or ""):
            ref[i, int(hash_tokens([tok], seed)[0]) % W] += 1
    np.testing.assert_array_equal(out[:, 2:2 + W], ref)
    assert out[:, :2].sum() == 0 and out[:, 2 + W:].sum() == 0
    np.testing.assert_array_equal(
        nullf, np.asarray([t is None for t in texts], np.float32))

    # binary_freq: presence flags, idempotent across repeated calls on
    # the SAME buffer (assignment, not accumulation)
    out_b = np.zeros((n, W), np.float32)
    hashed_text_block(texts, W, seed, True, out=out_b)
    hashed_text_block(texts, W, seed, True, out=out_b)
    np.testing.assert_array_equal(out_b, (ref > 0).astype(np.float32))
