"""Feature type system tests (parity with features/.../types tests)."""
import math

import numpy as np
import pytest

from transmogrifai_tpu.types import feature_types as ft


def test_registry_has_45_types():
    assert len(ft.FEATURE_TYPE_REGISTRY) == 52
    assert ft.feature_type_by_name("RealNN") is ft.RealNN
    with pytest.raises(ft.FeatureTypeError):
        ft.feature_type_by_name("NotAType")


def test_real_nullability():
    assert ft.Real(None).is_empty
    assert ft.Real(float("nan")).is_empty
    assert ft.Real(3).value == 3.0
    assert ft.Real(True).value == 1.0
    with pytest.raises(ft.FeatureTypeError):
        ft.RealNN(None)
    assert ft.RealNN(1.5).value == 1.5


def test_integral_and_binary():
    assert ft.Integral("7").value == 7
    assert ft.Integral(None).is_empty
    assert ft.Binary("true").value is True
    assert ft.Binary(0).value is False
    assert ft.Binary(None).to_double() is None
    assert ft.Binary(True).to_double() == 1.0
    with pytest.raises(ft.FeatureTypeError):
        ft.Binary("maybe")


def test_equality_is_on_value_and_type():
    assert ft.Real(1.0) == ft.Real(1.0)
    assert ft.Real(1.0) != ft.Currency(1.0)  # distinct types
    assert ft.Text("a") == ft.Text("a")
    assert hash(ft.Real(2.0)) == hash(ft.Real(2.0))


def test_subtyping_mirrors_reference():
    assert ft.is_subtype(ft.RealNN, ft.Real)
    assert ft.is_subtype(ft.Currency, ft.Real)
    assert ft.is_subtype(ft.DateTime, ft.Date)
    assert ft.is_subtype(ft.Date, ft.Integral)
    assert ft.is_subtype(ft.Email, ft.Text)
    assert not ft.is_subtype(ft.Real, ft.RealNN)
    assert ft.Binary.is_categorical()
    assert ft.PickList.is_categorical()
    assert ft.Country.is_location()


def test_email_parsing():
    e = ft.Email("bob@example.com")
    assert e.prefix == "bob"
    assert e.domain == "example.com"
    assert ft.Email("nonsense").prefix is None
    assert ft.Email(None).prefix is None


def test_url_validation():
    assert ft.URL("http://example.com/x").is_valid()
    assert ft.URL("https://example.com").domain == "example.com"
    assert not ft.URL("gopher://old.net").is_valid()
    assert not ft.URL("not a url").is_valid()


def test_vector():
    v = ft.OPVector([1.0, 2.0])
    assert v.value.tolist() == [1.0, 2.0]
    assert v.combine(ft.OPVector([3.0])).value.tolist() == [1.0, 2.0, 3.0]
    assert ft.OPVector(None).is_empty
    with pytest.raises(ft.FeatureTypeError):
        ft.OPVector([[1.0], [2.0]])


def test_geolocation():
    g = ft.Geolocation([37.77, -122.42, 5.0])
    assert g.lat == 37.77 and g.lon == -122.42 and g.accuracy == 5.0
    sphere = g.to_unit_sphere()
    assert abs(np.linalg.norm(sphere) - 1.0) < 1e-9
    assert ft.Geolocation(None).is_empty
    with pytest.raises(ft.FeatureTypeError):
        ft.Geolocation([100.0, 0.0, 1.0])  # lat out of range
    with pytest.raises(ft.FeatureTypeError):
        ft.Geolocation([1.0, 2.0])  # wrong arity


def test_sets_and_lists():
    s = ft.MultiPickList(["a", "b", "a"])
    assert s.value == {"a", "b"}
    tl = ft.TextList(["x", "y"])
    assert tl.value == ["x", "y"]
    dl = ft.DateList([1, 2])
    assert dl.value == [1, 2]
    assert ft.MultiPickList(None).is_empty


def test_maps():
    m = ft.RealMap({"a": 1, "b": None})
    assert m.value == {"a": 1.0, "b": None}
    tm = ft.TextMap({"k": "v"})
    assert tm.value == {"k": "v"}
    gm = ft.GeolocationMap({"home": [1.0, 2.0, 3.0]})
    assert gm.value["home"] == [1.0, 2.0, 3.0]
    assert ft.BinaryMap({"x": 1}).value == {"x": True}
    assert ft.MultiPickListMap({"x": ["a", "a"]}).value == {"x": {"a"}}
    assert ft.TextMap.element_type is ft.Text


def test_prediction():
    p = ft.Prediction(prediction=1.0, raw_prediction=[0.2, 0.8],
                      probability=[0.3, 0.7])
    assert p.prediction == 1.0
    assert p.raw_prediction == [0.2, 0.8]
    assert p.probability == [0.3, 0.7]
    with pytest.raises(ft.FeatureTypeError):
        ft.Prediction({"probability_0": 0.3})  # missing prediction key
    with pytest.raises(ft.FeatureTypeError):
        ft.Prediction({"prediction": 1.0, "bogus": 2.0})
