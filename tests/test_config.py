"""Declared-config registry tests (PR 18 tentpole a, config.py).

The contract: every customParams knob the runtime reads is DECLARED
once — name, type, default, bounds/choices, owner, tunability,
validator — and `cli gen`, `cli check`, the runner accessors and the
tuner's search space all derive from that one registry, so a knob
cannot drift between its emitter, its validator and its reader.
"""
import json

import pytest

from transmogrifai_tpu import config


# ---------------------------------------------------------------------------
# registry shape
# ---------------------------------------------------------------------------


def test_registry_declares_the_whole_surface():
    names = {k.name for k in config.iter_knobs()}
    assert len(names) >= 40
    # one knob per subsystem spot-checked: runner, pipeline, serving,
    # continual, fleet, observability, tuning
    for expected in ("validate", "pipelineWorkers", "serveBatchDeadlineMs",
                     "retrainCmd", "fleetWorkers", "traceDir",
                     "adaptDeadline", "costDb", "batchSize"):
        assert expected in names, expected
    for k in config.iter_knobs():
        assert k.owner, k.name
        assert k.doc, k.name
        assert k.type in ("int", "float", "bool", "str", "enum",
                          "dict", "list"), k.name


def test_knob_lookup_and_duplicate_rejection():
    k = config.knob("serveBatchDeadlineMs")
    assert k.type == "float" and k.tunable
    with pytest.raises(KeyError):
        config.knob("noSuchKnob")
    with pytest.raises(ValueError, match="duplicate knob"):
        config._declare("validate", "bool", True, "runner", "dup")
    # the failed redeclaration must not have clobbered the original
    assert config.knob("validate").owner


def test_tunable_knobs_carry_finite_bounds():
    tunables = {k.name for k in config.tunable_knobs()}
    assert "serveBatchDeadlineMs" in tunables
    assert "pipelineWorkers" in tunables
    assert "batchSize" in tunables
    for k in config.tunable_knobs():
        lo, hi = config.knob_bounds(k.name)
        assert lo < hi, k.name
        assert hi != float("inf"), (
            f"{k.name}: a tunable knob needs a finite search ceiling")


# ---------------------------------------------------------------------------
# coercion: the TMG001 error-message contract
# ---------------------------------------------------------------------------


def test_coerce_numeric_error_contract():
    assert config.coerce_numeric("8", "x", int) == 8
    assert config.coerce_numeric(2.5, "x", float) == 2.5
    with pytest.raises(ValueError,
                       match=r"customParams.x must be an integer, got "):
        config.coerce_numeric(2.5, "x", int)
    with pytest.raises(ValueError,
                       match=r"customParams.x must be a number, got "):
        config.coerce_numeric("soon", "x", float)
    with pytest.raises(ValueError,
                       match=r"customParams.x must be >= 1, got "):
        config.coerce_numeric(0, "x", int, minimum=1)
    with pytest.raises(ValueError, match="must be a number"):
        config.coerce_numeric(float("nan"), "x", float)


def test_coerce_bool_error_contract():
    assert config.coerce_bool(True, "x") is True
    assert config.coerce_bool("false", "x") is False
    assert config.coerce_bool("auto", "x", allow_auto=True) == "auto"
    with pytest.raises(ValueError,
                       match=r"must be a boolean \(true/false\), got "):
        config.coerce_bool("yes", "x")
    with pytest.raises(ValueError, match='or "auto"'):
        config.coerce_bool("maybe", "x", allow_auto=True)


# ---------------------------------------------------------------------------
# check_custom_params: one finding per bad knob, validators included
# ---------------------------------------------------------------------------


def test_check_custom_params_one_finding_per_bad_knob():
    errors = config.check_custom_params({
        "retrainCooldownS": "soon",          # numeric type error
        "retrainOnDrift": "yes",             # bool type error
        "canaryFraction": 1.5,               # validator (0, 1]
        "onBatchError": "explode",           # enum
        "serveModels": "notadict",           # dict
        "batchSize": 0,                      # minimum
    })
    by_key = {}
    for key, msg in errors:
        by_key.setdefault(key, []).append(msg)
        assert f"customParams.{key}" in msg or key in msg, (key, msg)
    assert sorted(by_key) == ["batchSize", "canaryFraction",
                              "onBatchError", "retrainCooldownS",
                              "retrainOnDrift", "serveModels"]
    # ONE finding per knob: a type error must not also fire the
    # validator (test_continual counts TMG001s exactly)
    assert all(len(v) == 1 for v in by_key.values()), by_key


def test_check_custom_params_accepts_valid_and_unknown():
    assert config.check_custom_params({}) == []
    assert config.check_custom_params({
        "batchSize": 512, "overlap": "auto", "failOn": "warning",
        "lintSuppress": "TMG301",            # bare string allowed
        "retrainCmd": ["python", "retrain.py"],
        "serveBatchDeadlineMs": 0,
        "someFutureKnob": object()}) == []   # undeclared: not checked


def test_check_custom_params_string_retrain_cmd_reaches_validator():
    # a bare-string retrainCmd passes the list type gate so the
    # continual validator owns the (single) finding
    errors = config.check_custom_params({"retrainCmd": "not-a-list"})
    assert len(errors) == 1 and errors[0][0] == "retrainCmd"


# ---------------------------------------------------------------------------
# gen emission + effective config
# ---------------------------------------------------------------------------


def test_default_custom_params_covers_scaffold_but_not_expert_knobs():
    cp = config.default_custom_params()
    for key in ("validate", "plan", "costDb", "registryDir",
                "driftWindow", "traceDir", "workloadDir"):
        assert key in cp, key
    # expert/serving knobs stay out of the scaffold (the gen'd file is
    # a starting point, not the full surface)
    for key in ("serveBatchDeadlineMs", "adaptDeadline", "batchSize"):
        assert key not in cp, key
    json.dumps(cp)                            # emission must be JSON


def test_effective_config_resolves_and_stamps_invalid():
    eff = config.effective_config({"batchSize": 512,
                                   "retrainCooldownS": "soon"})
    assert eff["batchSize"] == 512
    assert eff["validate"] is True            # default resolved
    assert eff["retrainCooldownS"] == {"invalid": "'soon'"}
    json.dumps(eff)


# ---------------------------------------------------------------------------
# round-trip: gen -> check clean (the satellite regression)
# ---------------------------------------------------------------------------


def test_registry_round_trip_gen_then_check_clean(tmp_path, capsys):
    from transmogrifai_tpu.cli import generate_project, run_check
    csv = tmp_path / "data.csv"
    csv.write_text("label,x\n1,0.5\n0,0.1\n1,0.9\n0,0.2\n")
    files = generate_project(str(csv), "label", str(tmp_path / "proj"))
    params = json.load(open(files["params.json"]))
    # every emitted knob is a declared one with its declared default
    for key, val in params["customParams"].items():
        assert config.knob(key).default == val, key
    assert run_check(files["params.json"]) == 0
    out = capsys.readouterr().out
    assert "TMG001" not in out


def test_check_catches_every_declared_knob_not_just_scaffold(tmp_path,
                                                             capsys):
    # a knob OUTSIDE the gen scaffold still validates through the same
    # registry path — the pre-registry code had per-knob ad-hoc checks
    # that silently missed new knobs
    p = tmp_path / "params.json"
    p.write_text(json.dumps({"customParams": {"adaptDeadline": "yes"}}))
    from transmogrifai_tpu.cli import run_check
    assert run_check(str(p)) == 1
    out = capsys.readouterr().out
    assert "TMG001" in out and "adaptDeadline" in out
