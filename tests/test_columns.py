"""Columnar layer tests."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, column_from_values
from transmogrifai_tpu.types import feature_types as ft


def test_numeric_column_roundtrip():
    col = column_from_values(ft.Real, [1.0, None, 3.5])
    assert len(col) == 3
    assert col.to_list() == [1.0, None, 3.5]
    assert col.mask.tolist() == [True, False, True]
    assert col.get_boxed(1) == ft.Real(None)


def test_integral_binary_columns():
    col = column_from_values(ft.Integral, [1, None, 3])
    assert col.values.dtype == np.int64
    assert col.to_list() == [1, None, 3]
    b = column_from_values(ft.Binary, [True, None, False])
    assert b.to_list() == [True, None, False]


def test_text_column():
    col = column_from_values(ft.Text, ["a", None, "c"])
    assert col.to_list() == ["a", None, "c"]
    assert col.mask.tolist() == [True, False, True]


def test_ragged_column():
    col = column_from_values(ft.DateList, [[1, 2], [], [3]])
    assert col.to_list() == [[1, 2], [], [3]]
    taken = col.take(np.array([2, 0]))
    assert taken.to_list() == [[3], [1, 2]]


def test_geo_column():
    col = column_from_values(ft.Geolocation, [[1.0, 2.0, 3.0], None])
    assert col.to_list() == [[1.0, 2.0, 3.0], []]


def test_map_column():
    col = column_from_values(ft.RealMap, [{"a": 1.0}, {"b": 2.0}, None])
    assert set(col.children.keys()) == {"a", "b"}
    assert col.to_list() == [{"a": 1.0}, {"b": 2.0}, {}]


def test_prediction_column():
    col = column_from_values(
        ft.Prediction,
        [ft.Prediction(prediction=1.0, probability=[0.3, 0.7]).value,
         ft.Prediction(prediction=0.0, probability=[0.8, 0.2]).value])
    assert col.prediction.tolist() == [1.0, 0.0]
    assert col.probability.shape == (2, 2)
    raw = col.get_raw(0)
    assert raw["prediction"] == 1.0 and raw["probability_1"] == 0.7


def test_vector_column():
    col = column_from_values(ft.OPVector, [[1.0, 2.0], [3.0, 4.0]])
    assert col.width == 2
    with pytest.raises(ValueError):
        column_from_values(ft.OPVector, [[1.0], [1.0, 2.0]])


def test_store_ops():
    store = ColumnStore.from_dict({
        "age": (ft.Real, [20.0, None, 40.0]),
        "name": (ft.Text, ["a", "b", "c"]),
    })
    assert store.n_rows == 3
    assert set(store.names()) == {"age", "name"}
    sub = store.filter_mask(np.array([True, False, True]))
    assert sub.n_rows == 2
    assert sub["age"].to_list() == [20.0, 40.0]
    assert store.row(0) == {"age": 20.0, "name": "a"}
    sel = store.select(["age"]).drop([])
    assert sel.names() == ["age"]
    with pytest.raises(ValueError):
        store.with_column("bad", column_from_values(ft.Real, [1.0]))
