"""Staged asynchronous input pipeline tests (pipeline.py + its wiring).

Covers the building blocks (ordered parallel map determinism, buffer
pool reuse, prefetch autotune dynamics, the sustained-bandwidth probe),
the scoring-engine integration (stage_batch parity, pipelined
stream_score bit-identity at N workers), the runner/CLI knob surface
(validated ``overlap``/``pipeline*`` customParams) and the telemetry
``on_pipeline_stats`` hook. The worker-pool chaos coverage lives in
tests/test_resilience.py; the directory-stream parallel-decode
determinism in tests/test_readers.py.
"""
import json
import threading
import time

import numpy as np
import pytest

from transmogrifai_tpu import pipeline, telemetry
from transmogrifai_tpu.pipeline import (BufferPool, PrefetchAutotuner,
                                        map_ordered, resolve_workers)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    yield
    telemetry.disable()
    telemetry.reset()


# ---------------------------------------------------------------------------
# map_ordered — the decode/prep stage
# ---------------------------------------------------------------------------


def test_map_ordered_is_deterministic_across_worker_counts():
    """N-worker output equals the serial loop in content AND order,
    whatever the per-item latencies do to completion order."""
    items = list(range(32))

    def slow_square(i):
        # reverse-staggered sleeps: later items finish FIRST on a pool
        time.sleep(0.002 * (32 - i) / 32)
        return i * i

    serial = [(i, i * i, None) for i in items]
    for workers in (1, 2, 4):
        got = list(map_ordered(slow_square, items, workers=workers))
        assert got == serial


def test_map_ordered_exceptions_ride_in_order_not_raise():
    def boom(i):
        if i == 3:
            raise ValueError("poison")
        return i

    got = list(map_ordered(boom, range(6), workers=3))
    assert [g[0] for g in got] == list(range(6))
    assert [g[1] for g in got] == [0, 1, 2, None, 4, 5]
    assert isinstance(got[3][2], ValueError)
    assert all(g[2] is None for i, g in enumerate(got) if i != 3)


def test_map_ordered_abandoned_consumer_stops_submitting():
    """Breaking out mid-stream must not drain the whole upstream
    iterator (max_batches leaves unread files re-offered)."""
    pulled = []

    def gen():
        for i in range(1000):
            pulled.append(i)
            yield i

    it = map_ordered(lambda x: x, gen(), workers=2)
    for _ in range(3):
        next(it)
    it.close()
    assert len(pulled) < 50          # bounded by the in-flight depth


def test_map_ordered_yields_ready_result_while_source_blocks():
    """A batch that finishes while ``next(it)`` is blocked on a sparse
    live source (a directory stream between file arrivals) must be
    delivered immediately, not withheld until the next item arrives —
    the feeder thread owns the blocking ``next()``."""
    gate = threading.Event()

    def gen():
        yield 1
        gate.wait(10.0)      # the "next file" arrives only when released
        yield 2

    it = map_ordered(lambda x: x * 10, gen(), workers=2)
    t0 = time.perf_counter()
    assert next(it) == (1, 10, None)
    assert time.perf_counter() - t0 < 5.0    # didn't wait out the gate
    gate.set()
    assert next(it) == (2, 20, None)
    assert list(it) == []


def test_map_ordered_worker_threads_are_named():
    names = set()

    def grab(i):
        names.add(threading.current_thread().name)
        return i

    list(map_ordered(grab, range(8), workers=2, name="decode-test"))
    assert names and all(n.startswith("decode-test") for n in names)


def test_slow_source_does_not_count_as_starvation():
    """A source-bound stream (items arrive slower than they decode)
    must not ratchet the prefetch depth: the consumer's wait is the
    SOURCE's fault, and extra depth cannot make items arrive faster."""
    tuner = PrefetchAutotuner(max_depth=8)
    d0 = tuner.depth()

    def slow_source():
        for i in range(8):
            time.sleep(0.02)
            yield i

    got = list(map_ordered(lambda i: i * i, slow_source(), workers=2,
                           tuner=tuner))
    assert [g[0] for g in got] == list(range(8))
    assert [g[1] for g in got] == [i * i for i in range(8)]
    assert tuner.starvations == 0
    assert tuner.depth() == d0


def test_slow_workers_still_count_as_starvation():
    """The flip side: with a fast source and slow work, the pipeline IS
    the bottleneck and starvations must still register."""
    tuner = PrefetchAutotuner(max_depth=8)

    def fast_source():
        yield from range(6)

    def slow_work(i):
        time.sleep(0.03)
        return i

    got = list(map_ordered(slow_work, fast_source(), workers=1,
                           tuner=tuner))
    assert [g[0] for g in got] == list(range(6))
    assert tuner.starvations >= 1


def test_resolve_workers():
    assert resolve_workers(3) == 3
    assert resolve_workers(0) == 1
    assert resolve_workers(None) == pipeline.DEFAULT_WORKERS


def test_kill_switch_forces_serial_directory_stream(monkeypatch,
                                                    tmp_path):
    """TMOG_PIPELINE=0 must not be overridable by an explicit
    ``stream(workers=N)``: the parallel pool never spins up and the
    batches still flow (serially)."""
    from transmogrifai_tpu.readers.avro import write_avro_records
    from transmogrifai_tpu.readers.streaming import DirectoryStreamReader

    rows = [{"a": float(i)} for i in range(20)]
    write_avro_records(str(tmp_path / "p0.avro"), rows)
    monkeypatch.setattr(pipeline, "PIPELINE_ENABLED", False)
    assert resolve_workers(4) == 1
    r = DirectoryStreamReader(str(tmp_path), poll_interval_s=0.05,
                              settle_s=0.0)

    def boom(*a, **k):
        raise AssertionError("parallel pool spun up under "
                             "TMOG_PIPELINE=0")

    monkeypatch.setattr(r, "_stream_parallel", boom)
    got = list(r.stream(max_batches=1, timeout_s=5, workers=4))
    assert len(got) == 1
    assert [dict(x) for x in got[0]] == rows


# ---------------------------------------------------------------------------
# SeededRowSample — the out-of-core bounded subsample (PR 16)
# ---------------------------------------------------------------------------


def _drain_sample(batches, k=64, seed=7):
    from transmogrifai_tpu.pipeline import SeededRowSample
    s = SeededRowSample(k, seed=seed)
    for batch in batches:
        loc = s.offer(len(batch))
        s.keep([batch[int(i)] for i in loc])
    return s.result(), s.total_rows


def test_seeded_row_sample_batch_boundary_invariant():
    """A row's keep/drop fate is a pure function of its GLOBAL stream
    index and the seed — re-batching the same stream (one batch, odd
    chunks, row-at-a-time) must select the identical rows in the
    identical order."""
    rows = [{"i": i} for i in range(1000)]
    ref, n_ref = _drain_sample([rows])
    assert n_ref == 1000 and len(ref) == 64
    for size in (100, 37, 1):
        got, n = _drain_sample(
            [rows[i:i + size] for i in range(0, len(rows), size)])
        assert n == 1000
        assert got == ref


def test_seeded_row_sample_deterministic_across_stream_workers(
        tmp_path):
    """The quantile-sketch subsample drawn from a parallel-decoded
    directory stream at workers 1/2/4 equals the one drawn from the
    materialized (read_records) order — the out-of-core fit's
    determinism contract."""
    from transmogrifai_tpu.readers.avro import write_avro_records
    from transmogrifai_tpu.readers.streaming import DirectoryStreamReader

    for s in range(6):
        write_avro_records(
            str(tmp_path / f"part-{s}.avro"),
            [{"v": float(s * 100 + i)} for i in range(100)])

    ref, n_ref = _drain_sample(
        [DirectoryStreamReader(str(tmp_path), settle_s=0.0)
         .read_records()])
    assert n_ref == 600
    for workers in (1, 2, 4):
        r = DirectoryStreamReader(str(tmp_path), settle_s=0.0)
        got, n = _drain_sample(r.stream(passes=1, workers=workers))
        assert n == 600
        assert [dict(x) for x in got] == [dict(x) for x in ref]


def test_seeded_row_sample_small_stream_is_identity():
    """n <= k: the sample IS the stream, in order — the degenerate
    path that makes small streamed fits exactly equal materialized."""
    rows = [{"i": i} for i in range(40)]
    got, n = _drain_sample([rows[:25], rows[25:]], k=64)
    assert n == 40 and got == rows
    with pytest.raises(ValueError):
        _drain_sample([rows], k=0)


# ---------------------------------------------------------------------------
# BufferPool — pinned-buffer reuse
# ---------------------------------------------------------------------------


def test_buffer_pool_reuses_and_pads_bit_identically():
    pool = BufferPool()
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    taken = []
    padded = pool.pad_rows(a, 3, 8, taken)
    assert padded.shape == (8, 4)
    np.testing.assert_array_equal(padded[:3], a)
    assert not padded[3:].any()
    # the reference padding (fresh-allocation path) is bit-identical
    ref = np.concatenate([a, np.zeros((5, 4), np.float32)])
    np.testing.assert_array_equal(padded, ref)
    assert taken == [padded]
    pool.give(padded)
    again = pool.take((8, 4), np.float32)
    assert again is padded           # recycled, not reallocated
    assert pool.reuses == 1 and pool.allocs == 1
    # different shape/dtype never collide
    other = pool.take((8, 4), np.float64)
    assert other is not padded


def test_buffer_pool_passthrough_for_constants_and_full_buckets():
    pool = BufferPool()
    taken = []
    const = np.asarray(3.0, np.float32)   # 0-d fitted constant
    assert pool.pad_rows(const, 4, 8, taken) is const
    full = np.zeros((8, 2), np.float32)
    assert pool.pad_rows(full, 8, 8, taken) is full
    assert taken == []


def test_buffer_pool_bounded_per_key():
    pool = BufferPool(max_per_key=2)
    bufs = [pool.take((4,), np.float32) for _ in range(5)]
    for b in bufs:
        pool.give(b)
    assert pool.free_buffers() == 2


# ---------------------------------------------------------------------------
# PrefetchAutotuner
# ---------------------------------------------------------------------------


def test_autotuner_grows_on_starvation_and_shrinks_when_calm():
    t = PrefetchAutotuner(min_depth=2, max_depth=4, window=2)
    assert t.depth() == 2
    # a starved window grows
    t.record_starvation()
    t.on_batch()
    t.on_batch()
    assert t.depth() == 3
    # growth is capped at max_depth
    for _ in range(4):
        t.record_starvation()
        t.on_batch()
        t.on_batch()
    assert t.depth() == 4
    # two calm windows shrink one step
    for _ in range(4):
        t.on_batch()
    assert t.depth() == 3
    assert t.grows >= 2 and t.shrinks == 1


def test_autotuner_never_leaves_bounds_and_cap_below_floor_wins():
    t = PrefetchAutotuner(min_depth=2, max_depth=8, window=1)
    for _ in range(50):
        t.on_batch()
    assert t.depth() == 2            # floor holds
    # pipelineDepth: 1 forces serial prefetch — the cap wins
    t1 = PrefetchAutotuner(max_depth=1)
    assert t1.depth() == 1
    t1.record_starvation()
    t1.on_batch()
    for _ in range(8):
        t1.on_batch()
    assert t1.depth() == 1


def test_map_ordered_depth_follows_tuner():
    """With a depth-1 tuner only one item is ever in flight ahead."""
    tuner = PrefetchAutotuner(max_depth=1)
    pulled = []

    def gen():
        for i in range(10):
            pulled.append(i)
            yield i

    it = map_ordered(lambda x: x, gen(), workers=4, tuner=tuner)
    next(it)
    assert len(pulled) <= 2
    it.close()


# ---------------------------------------------------------------------------
# sustained-bandwidth probe + fusion gate evidence
# ---------------------------------------------------------------------------


def test_probe_sustained_mbps_positive_and_tallied():
    mbps = pipeline.probe_sustained_mbps(n_transfers=4, buf_mb=1)
    assert mbps > 0
    assert pipeline.pipeline_stats()["sustained_mbps"] == round(mbps, 1)


def test_fusion_state_carries_probe_and_sustained(monkeypatch):
    from transmogrifai_tpu import workflow as wf
    monkeypatch.setattr(wf, "_DEVICE_BW_MBPS", 750.0)
    monkeypatch.setattr(wf, "_DEVICE_BW_PROBE_MBPS", 23.0)
    st = wf.fusion_state()
    assert st["fusion"] == "ON"               # sustained clears the gate
    assert st["sustained_mbps"] == 750.0
    assert st["mbps"] == 23.0                 # the cold probe stays visible


def test_device_roundtrip_uses_sustained_measurement(monkeypatch):
    from transmogrifai_tpu import workflow as wf
    monkeypatch.setattr(wf, "_DEVICE_BW_MBPS", None)
    monkeypatch.setattr(wf, "_DEVICE_BW_PROBE_MBPS", None)
    monkeypatch.setattr(telemetry, "probe_device_roundtrip_mbps",
                        lambda: 23.0)
    monkeypatch.setattr(pipeline, "probe_sustained_mbps", lambda: 900.0)
    assert wf.device_roundtrip_mbps() == 900.0
    assert wf._DEVICE_BW_PROBE_MBPS == 23.0
    st = wf.fusion_state()
    assert st["sustained_mbps"] == 900.0 and st["mbps"] == 23.0


def test_cost_db_records_both_bandwidth_numbers(tmp_path):
    from transmogrifai_tpu import planner
    db = planner.CostDatabase.load(str(tmp_path / "db.json"))
    db.record_bandwidth(850.0, probe_mbps=23.4)
    db.save()
    db2 = planner.CostDatabase.load(str(tmp_path / "db.json"))
    assert db2.bandwidth_mbps() == 850.0      # the tier-deciding number
    assert db2.doc["probe_mbps"] == 23.4


# ---------------------------------------------------------------------------
# scoring-engine integration
# ---------------------------------------------------------------------------


def _binary_model(rng, n=240):
    from transmogrifai_tpu import ColumnStore, FeatureBuilder, Workflow
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify

    y = rng.integers(0, 2, n).astype(float)
    x1 = rng.normal(size=n) + y
    x2 = rng.normal(size=n)
    records = [{"label": float(y[i]), "x1": float(x1[i]),
                "x2": float(x2[i])} for i in range(n)]
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
    f2 = FeatureBuilder.Real("x2").from_column().as_predictor()
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=7)
    pred = label.transform_with(selector, transmogrify([f1, f2]))
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())
    return model, records, pred


def test_stage_batch_is_bit_identical_to_unstaged(rng):
    """The double-buffered upload stage (program pre-resolved, blocks
    device_put ahead of dispatch) must change nothing downstream."""
    model, records, pred = _binary_model(rng)
    eng = model.scoring_engine(gate_bandwidth=False)
    prep = eng.prepare_batch(records, use_cache=False)
    plain = eng.run_batch(prep, results_only=True)
    prep2 = eng.prepare_batch(records, use_cache=False)
    staged = eng.stage_batch(prep2, results_only=True)
    out = eng.run_batch(staged, results_only=True)
    np.testing.assert_array_equal(out[pred.name].probability,
                                  plain[pred.name].probability)
    np.testing.assert_array_equal(out[pred.name].prediction,
                                  plain[pred.name].prediction)


def test_stage_batch_results_only_mismatch_is_loud(rng):
    model, records, _pred = _binary_model(rng, n=64)
    eng = model.scoring_engine(gate_bandwidth=False)
    staged = eng.stage_batch(eng.prepare_batch(records, use_cache=False),
                             results_only=True)
    with pytest.raises(ValueError, match="results_only mismatch"):
        eng.run_batch(staged, results_only=False)


def test_pooled_prepare_releases_buffers_after_run(rng):
    model, records, pred = _binary_model(rng, n=100)
    eng = model.scoring_engine(gate_bandwidth=False)
    pool = BufferPool()
    prep = eng.prepare_batch(records, use_cache=False, pool=pool)
    assert prep.buffers                       # padding went through the pool
    n_taken = len(prep.buffers)
    baseline = eng.run_batch(eng.prepare_batch(records, use_cache=False),
                             results_only=True)
    out = eng.run_batch(eng.stage_batch(prep, results_only=True),
                        results_only=True)
    np.testing.assert_array_equal(out[pred.name].probability,
                                  baseline[pred.name].probability)
    assert pool.free_buffers() == n_taken     # recycled after the pull
    # the next pooled prepare reuses instead of reallocating
    prep3 = eng.prepare_batch(records, use_cache=False, pool=pool)
    assert pool.reuses >= n_taken
    prep3.release()
    prep3.release()                           # idempotent


def test_pipelined_stream_bit_identical_across_worker_counts(rng):
    """The acceptance bit: pipelined streaming score (N prep workers,
    autotuned prefetch, staged uploads) equals the serial engine path
    EXACTLY, in batch order and bytes."""
    from transmogrifai_tpu.readers import stream_score

    model, records, pred = _binary_model(rng, n=320)
    batches = [records[i:i + 40] for i in range(0, 320, 40)]
    eng = model.scoring_engine(gate_bandwidth=False)
    want = [eng.score_store(list(b), use_cache=False)[pred.name]
            for b in batches]
    for workers in (1, 2, 4):
        got = list(stream_score(model, batches, overlap=True,
                                workers=workers))
        assert len(got) == len(batches)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g[pred.name].probability,
                                          w.probability)
            np.testing.assert_array_equal(g[pred.name].prediction,
                                          w.prediction)


def test_mid_stream_source_error_flushes_prepped_batches(rng):
    """A batch source that dies mid-stream must not swallow batches
    already decoded: the pipelined path yields every pre-error batch
    (exactly as the serial path scores them before raising) BEFORE
    surfacing the source exception — the staged one-batch skew may not
    drop the last prepped batch."""
    from transmogrifai_tpu.readers import stream_score

    model, records, pred = _binary_model(rng, n=160)
    batches = [records[i:i + 40] for i in range(0, 160, 40)]
    eng = model.scoring_engine(gate_bandwidth=False)
    want = [eng.score_store(list(b), use_cache=False)[pred.name]
            for b in batches]

    def dying_source():
        for b in batches:
            yield b
        raise RuntimeError("poll blew up")

    got = []
    with pytest.raises(RuntimeError, match="poll blew up"):
        for s in stream_score(model, dying_source(), overlap=True,
                              workers=2):
            got.append(s)
    assert len(got) == len(batches)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g[pred.name].probability,
                                      w.probability)
        np.testing.assert_array_equal(g[pred.name].prediction,
                                      w.prediction)


def test_pipelined_stream_records_stats_and_listener(rng):
    from transmogrifai_tpu.readers import stream_score

    class Grab(telemetry.RunListener):
        def __init__(self):
            self.seen = []

        def on_pipeline_stats(self, **kw):
            self.seen.append(kw)

    model, records, _pred = _binary_model(rng, n=160)
    batches = [records[i:i + 40] for i in range(0, 160, 40)]
    before = pipeline.pipeline_stats()
    telemetry.enable()
    grab = telemetry.add_listener(Grab())
    collector = telemetry.add_listener(telemetry.CollectingRunListener())
    try:
        list(stream_score(model, batches, overlap=True, workers=2,
                          prefetch=4))
    finally:
        telemetry.remove_listener(grab)
        telemetry.remove_listener(collector)
    after = pipeline.pipeline_stats()
    assert after["streams"] == before["streams"] + 1
    assert after["batches"] == before["batches"] + 4
    assert after["last_workers"] == 2
    assert after["last_prefetch_depth"] >= 1
    assert grab.seen and grab.seen[0]["batches"] == 4 \
        and grab.seen[0]["workers"] == 2
    summary = collector.summary()
    assert summary["pipeline"]["streams"] == 1
    assert summary["pipeline"]["batches"] == 4


@pytest.mark.chaos
def test_staged_upload_fault_falls_back_to_host_not_quarantine(rng):
    """A pipeline.upload fault is a TIER failure: the batch retries on
    the host path, the breaker hears about it, nothing is quarantined."""
    from transmogrifai_tpu import resilience
    from transmogrifai_tpu.readers import stream_score

    resilience.reset_breakers()
    resilience.reset_resilience_stats()
    model, records, pred = _binary_model(rng, n=160)
    batches = [records[i:i + 40] for i in range(0, 160, 40)]
    clean = [s[pred.name].probability.copy()
             for s in stream_score(model, batches, overlap=True)]
    plan = resilience.FaultPlan(seed=3).on("pipeline.upload",
                                           error=IOError, at=[1])
    with resilience.fault_plan(plan):
        got = [s[pred.name].probability.copy()
               for s in stream_score(model, batches, overlap=True,
                                     workers=2)]
    assert len(got) == len(clean)             # no batch lost
    for g, w in zip(got, clean):
        np.testing.assert_allclose(g, w, rtol=1e-5, atol=1e-6)
    stats = resilience.resilience_stats()
    assert stats["quarantined_batches"] == 0
    resilience.reset_breakers()


# ---------------------------------------------------------------------------
# runner + CLI knob surface
# ---------------------------------------------------------------------------


def test_bool_custom_param_validates_and_names_key():
    from transmogrifai_tpu.runner import OpParams, _bool_custom_param

    p = OpParams(custom_params={"overlap": "TRUE", "pipeline": False,
                                "bad": "yes"})
    assert _bool_custom_param(p, "overlap", allow_auto=True) is True
    assert _bool_custom_param(p, "pipeline") is False
    assert _bool_custom_param(p, "absent", default="auto",
                              allow_auto=True) == "auto"
    with pytest.raises(ValueError, match="customParams.bad"):
        _bool_custom_param(p, "bad")
    # "auto" only where the knob is tri-state
    p2 = OpParams(custom_params={"pipeline": "auto"})
    with pytest.raises(ValueError, match="customParams.pipeline"):
        _bool_custom_param(p2, "pipeline")


def test_runner_streaming_validates_pipeline_knobs(rng, tmp_path):
    from transmogrifai_tpu.runner import (OpParams, OpWorkflowRunner,
                                          RunType)

    model, records, _pred = _binary_model(rng, n=80)
    mdir = str(tmp_path / "model")
    model.save(mdir)

    class _Reader:
        def read_records(self):
            return records

    def run(custom):
        runner = OpWorkflowRunner(None, scoring_reader=_Reader())
        params = OpParams(model_location=mdir,
                          custom_params={"validate": False, "plan": False,
                                         **custom})
        return runner.run(RunType.STREAMING_SCORE, params)

    for bad in ({"overlap": "bogus"}, {"pipelineWorkers": "two"},
                {"pipelineWorkers": 0}, {"pipelineDepth": 1.5},
                {"pipeline": "maybe"}):
        key = next(iter(bad))
        with pytest.raises(ValueError, match=f"customParams.{key}"):
            run(bad)

    res = run({"batchSize": 40, "pipelineWorkers": 2,
               "pipelineDepth": 3, "overlap": "false"})
    assert res.metrics["rowsScored"] == 80
    assert res.metrics["overlap"] is False
    assert "prefetchDepth" in res.metrics
    assert "pipelineStarvations" in res.metrics
    assert res.metrics["pipeline"]["streams"] >= 0   # always-on stamp


def test_runner_pipeline_kill_switch_restores_reader_columnar(rng,
                                                              tmp_path):
    """``customParams.pipeline: false`` is run-scoped: the reader's
    columnar flag must come back after the run, so a later pipelined
    run on the SAME reader instance keeps the vectorized decode."""
    from transmogrifai_tpu.runner import (OpParams, OpWorkflowRunner,
                                          RunType)

    model, records, _pred = _binary_model(rng, n=80)
    mdir = str(tmp_path / "model")
    model.save(mdir)

    class _Reader:
        def __init__(self):
            self.columnar = True

        def read_records(self):
            return records

    reader = _Reader()
    runner = OpWorkflowRunner(None, scoring_reader=reader)
    params = OpParams(model_location=mdir,
                      custom_params={"validate": False, "plan": False,
                                     "pipeline": False, "batchSize": 40})
    res = runner.run(RunType.STREAMING_SCORE, params)
    assert res.metrics["rowsScored"] == 80
    assert reader.columnar is True


def test_runner_accepts_pre_pipeline_stream_contract(rng, tmp_path):
    """A duck-typed reader whose ``stream()`` predates the workers knob
    (``stream(max_batches, timeout_s)``) still streams — serially —
    instead of crashing on an unexpected kwarg."""
    from transmogrifai_tpu.runner import (OpParams, OpWorkflowRunner,
                                          RunType)

    model, records, _pred = _binary_model(rng, n=80)
    mdir = str(tmp_path / "model")
    model.save(mdir)

    class _OldReader:
        def stream(self, max_batches=None, timeout_s=None):
            for i in range(0, 80, 40):
                yield records[i:i + 40]

    runner = OpWorkflowRunner(None, scoring_reader=_OldReader())
    params = OpParams(model_location=mdir,
                      custom_params={"validate": False, "plan": False,
                                     "pipelineWorkers": 2})
    res = runner.run(RunType.STREAMING_SCORE, params)
    assert res.metrics["rowsScored"] == 80
    assert res.metrics["batches"] == 2


def test_cli_gen_emits_pipeline_knobs_and_check_validates(tmp_path,
                                                          capsys):
    from transmogrifai_tpu import cli

    csv = tmp_path / "d.csv"
    csv.write_text("label,x\n1,0.5\n0,0.3\n1,0.9\n0,0.1\n")
    out = cli.generate_project(str(csv), "label", str(tmp_path / "proj"))
    params = json.load(open(out["params.json"]))
    cp = params["customParams"]
    assert cp["overlap"] == "auto" and cp["pipeline"] is True
    assert cp["pipelineWorkers"] is None and cp["pipelineDepth"] is None
    # gen output round-trips clean through check
    assert cli.run_check(out["params.json"]) == 0

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({
        "customParams": {"overlap": "sometimes", "pipelineWorkers": 0.5,
                         "pipelineDepth": -1, "pipeline": "maybe"}}))
    rc = cli.run_check(str(bad))
    txt = capsys.readouterr().out
    assert rc == 1
    for key in ("overlap", "pipelineWorkers", "pipelineDepth",
                "pipeline"):
        assert f"customParams.{key}" in txt
    assert "TMG001" in txt


# ---------------------------------------------------------------------------
# fitstats double-buffered fold stays exact
# ---------------------------------------------------------------------------


def test_fitstats_double_buffered_fold_matches_host(monkeypatch):
    """Multi-chunk device fold (upload k+1 overlapping fold k, pooled
    pad staging) still merges to the host tier's exact counts/extrema
    and f64-close moments — run twice so the second pass exercises
    buffer REUSE, not just allocation."""
    from transmogrifai_tpu import ColumnStore, column_from_values, fitstats
    from transmogrifai_tpu.fitstats import LayerStatsPlan, StatRequest
    from transmogrifai_tpu.types import feature_types as ft

    monkeypatch.setattr(fitstats, "FITSTATS_CHUNK_ROWS", 1024)
    rng = np.random.default_rng(9)
    n = 2500                                   # 3 chunks, last one padded
    vals = rng.normal(size=n) * 3.0
    vals[rng.random(n) < 0.1] = np.nan
    store = ColumnStore({"x": column_from_values(ft.Real, vals)}, n)
    reqs = [StatRequest(k, "x")
            for k in ("count", "mean", "variance", "min", "max")]
    host = LayerStatsPlan(reqs).run(store, device=False)
    before = pipeline.pipeline_stats()
    dev1 = LayerStatsPlan(reqs).run(store, device=True, mesh=False)
    dev2 = LayerStatsPlan(reqs).run(store, device=True, mesh=False)
    after = pipeline.pipeline_stats()
    for dev in (dev1, dev2):
        assert dev.value("count", "x") == host.value("count", "x")
        assert dev.value("min", "x") == host.value("min", "x")
        assert dev.value("max", "x") == host.value("max", "x")
        np.testing.assert_allclose(dev.value("mean", "x"),
                                   host.value("mean", "x"), rtol=1e-6)
        np.testing.assert_allclose(dev.value("variance", "x"),
                                   host.value("variance", "x"), rtol=1e-5)
    assert after["buffer_reuses"] > before["buffer_reuses"]


def test_one_chunk_fold_immune_to_pool_churn(monkeypatch):
    """One-chunk (padded) fits upload through the content-keyed cache,
    which may hold a zero-copy alias of its source array: re-fitting
    store A after fit B churned the staging pool must reproduce A's
    stats exactly — the pad arrays feeding the cache are fresh, never
    recycled pool buffers."""
    from transmogrifai_tpu import ColumnStore, column_from_values, fitstats
    from transmogrifai_tpu.fitstats import LayerStatsPlan, StatRequest
    from transmogrifai_tpu.types import feature_types as ft

    monkeypatch.setattr(fitstats, "FITSTATS_CHUNK_ROWS", 1024)

    def mk(seed):
        v = np.random.default_rng(seed).normal(size=700)  # < chunk: padded
        return ColumnStore({"x": column_from_values(ft.Real, v)}, 700)

    reqs = [StatRequest(k, "x") for k in ("count", "mean", "variance")]
    a1 = LayerStatsPlan(reqs).run(mk(1), device=True, mesh=False)
    LayerStatsPlan(reqs).run(mk(2), device=True, mesh=False)
    a2 = LayerStatsPlan(reqs).run(mk(1), device=True, mesh=False)
    assert a2.value("count", "x") == a1.value("count", "x")
    np.testing.assert_array_equal(a2.value("mean", "x"),
                                  a1.value("mean", "x"))
    np.testing.assert_array_equal(a2.value("variance", "x"),
                                  a1.value("variance", "x"))
