"""AOT program bank tests (aot.py + the serving.py export/load story).

The acceptance contract: a cold process that loads an AOT-banked export
answers its first scoring request with ``compile_count == 0``, and every
corruption/incompatibility mode (version skew, wrong device kind,
tampered digest, truncated manifest, missing program) degrades to
per-bucket JIT with a TMG5xx advisory — never a crash."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder, Workflow, aot, serving
from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                      LogisticRegressionFamily)
from transmogrifai_tpu.ops.sanity_checker import SanityChecker
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.scoring import (PROGRAM_CACHE_CAP, ScoringEngine,
                                       engine_cache_stats)

BUCKET_CAP = 64


def _train(seed=7, n=240):
    rng = np.random.default_rng(seed)
    y = np.asarray([i % 2 for i in range(n)], float)
    rng.shuffle(y)
    cats = ["a", "b", "c", None]
    records = [{"label": float(y[i]),
                "x1": float(rng.normal() + y[i]),
                "x2": float(rng.normal()),
                "cat": cats[i % 4]} for i in range(n)]
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
    f2 = FeatureBuilder.Real("x2").from_column().as_predictor()
    f3 = FeatureBuilder.PickList("cat").from_column().as_predictor()
    vec = transmogrify([f1, f2, f3])
    checker = SanityChecker(remove_bad_features=True,
                            remove_feature_group=False)
    label.transform_with(checker, vec)
    vec = checker.get_output()
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=seed)
    pred = label.transform_with(sel, vec)
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())
    return model, records, pred


@pytest.fixture(scope="module")
def banked(tmp_path_factory):
    """One trained model + one AOT-banked export, shared module-wide."""
    model, records, pred = _train()
    export = str(tmp_path_factory.mktemp("export"))
    meta = serving.export_scoring_fn(model, export, records[:8],
                                     bucket_cap=BUCKET_CAP)
    return model, records, pred, export, meta


def _cold_engine(model):
    """A fresh engine — the per-engine program cache starts empty, so
    its ``compile_count`` is the cold-process compile oracle."""
    return ScoringEngine(model, gate_bandwidth=False, mesh=False,
                         bucket_cap=BUCKET_CAP)


def _assert_bitwise(a, b):
    for fld in ("prediction", "raw_prediction", "probability"):
        assert np.array_equal(getattr(a, fld), getattr(b, fld)), fld


# ---------------------------------------------------------------------------
# the happy path: bank → zero compiles, bit-identical
# ---------------------------------------------------------------------------


def test_bank_load_scores_with_zero_compiles(banked):
    model, records, pred, export, meta = banked
    assert meta["aot"] is not None and meta["aot"]["programs"] == 4
    eng = _cold_engine(model)
    report = aot.load_program_bank(eng, export)
    assert report["present"] and report["compatible"]
    assert report["loaded"] == [8, 16, 32, 64]
    assert report["findings"] == []
    assert len(eng.programs()) == 4
    # two different buckets, zero compiles — the acceptance criterion
    out_small = eng.score_store(records[:5])
    out_big = eng.score_store(records[:40])
    assert eng.compile_count == 0
    # bit-identical to a JIT-compiled engine on the same model
    jit = _cold_engine(model)
    _assert_bitwise(out_small[pred.name],
                    jit.score_store(records[:5])[pred.name])
    _assert_bitwise(out_big[pred.name],
                    jit.score_store(records[:40])[pred.name])
    assert jit.compile_count > 0


def test_export_metadata_stamped_even_without_aot(banked, tmp_path):
    """Satellite: bucket_cap, ladder, plan digest and versions land in
    the export metadata whether or not a bank ships."""
    model, records, pred, export, _ = banked
    meta = serving.export_scoring_fn(model, str(tmp_path), records[:8],
                                     bucket_cap=BUCKET_CAP, aot=False)
    assert meta["aot"] is None
    assert not os.path.isdir(aot.bank_dir(str(tmp_path)))
    assert meta["bucketCap"] == BUCKET_CAP
    assert meta["bucketLadder"] == [8, 16, 32, 64]
    env = meta["environment"]
    import jax
    import jaxlib
    assert env["jax"] == jax.__version__
    assert env["jaxlib"] == jaxlib.__version__
    assert env["platform"] == "cpu"
    eng = _cold_engine(model)
    assert meta["planDigest"] == eng.rewrite_digest()
    assert meta["stateDigest"] == eng.state_digest()
    # bankless artifacts still load (pre-bank compatibility)
    fn = serving.load_scoring_fn(str(tmp_path))
    assert fn.bank_buckets == []


def test_cold_process_first_request_zero_compiles(banked, tmp_path):
    """THE acceptance test: a genuinely cold process (fresh
    interpreter, nothing warm) loads the saved model + banked export
    and answers its first request without one XLA compile."""
    model, records, pred, export, _ = banked
    model_dir = str(tmp_path / "model")
    model.save(model_dir)
    script = r"""
import os, sys, json, time
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, sys.argv[4])
from transmogrifai_tpu import aot
from transmogrifai_tpu.cli import _populate_stage_registry
from transmogrifai_tpu.scoring import ScoringEngine
from transmogrifai_tpu.workflow import WorkflowModel
_populate_stage_registry()
model = WorkflowModel.load(sys.argv[1])
eng = ScoringEngine(model, gate_bandwidth=False, mesh=False,
                    bucket_cap=int(sys.argv[3]))
report = aot.load_program_bank(eng, sys.argv[2])
assert report["compatible"], report
records = json.load(open(os.path.join(sys.argv[2], "req.json")))
t0 = time.perf_counter()
out = eng.score_store(records)
ms = (time.perf_counter() - t0) * 1e3
assert eng.compile_count == 0, eng.compile_count
print(f"COLD_OK rows={out.n_rows} first_request_ms={ms:.2f}")
"""
    with open(os.path.join(export, "req.json"), "w") as fh:
        json.dump(records[:10], fh)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", script, model_dir, export,
         str(BUCKET_CAP), repo],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "COLD_OK rows=10" in proc.stdout, proc.stdout


# ---------------------------------------------------------------------------
# degradation matrix: every corruption falls back to JIT with an advisory
# ---------------------------------------------------------------------------


def _corrupt_manifest(export, mutate):
    mp = aot.manifest_path(export)
    with open(mp) as fh:
        manifest = json.load(fh)
    mutate(manifest)
    with open(mp, "w") as fh:
        json.dump(manifest, fh)


def _copy_export(export, tmp_path):
    import shutil
    dst = str(tmp_path / "export_copy")
    shutil.copytree(export, dst)
    return dst


@pytest.mark.parametrize("case", [
    "truncated_manifest", "not_json_manifest", "wrong_device_kind",
    "jax_version_skew", "tampered_program", "missing_program",
    "plan_digest_mismatch", "state_digest_mismatch",
    "format_version_bump",
])
def test_bank_corruption_degrades_to_jit(banked, tmp_path, case):
    model, records, pred, export, _ = banked
    export = _copy_export(export, tmp_path)
    whole_bank_dead = True
    if case == "truncated_manifest":
        with open(aot.manifest_path(export), "w") as fh:
            fh.write('{"formatVersion": 1, "programs"')
    elif case == "not_json_manifest":
        with open(aot.manifest_path(export), "wb") as fh:
            fh.write(b"\x00\x01garbage")
    elif case == "wrong_device_kind":
        _corrupt_manifest(
            export, lambda m: m["environment"].update(
                deviceKind="TPU v5e"))
    elif case == "jax_version_skew":
        _corrupt_manifest(
            export, lambda m: m["environment"].update(jax="0.0.1"))
    elif case == "plan_digest_mismatch":
        _corrupt_manifest(
            export, lambda m: m.update(planDigest="deadbeef" * 4))
    elif case == "state_digest_mismatch":
        _corrupt_manifest(
            export, lambda m: m.update(stateDigest="deadbeef" * 4))
    elif case == "format_version_bump":
        _corrupt_manifest(export, lambda m: m.update(formatVersion=99))
    elif case == "tampered_program":
        f = os.path.join(aot.bank_dir(export), "bucket_16.xbin")
        blob = bytearray(open(f, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(f, "wb").write(bytes(blob))
        whole_bank_dead = False
    elif case == "missing_program":
        os.remove(os.path.join(aot.bank_dir(export), "bucket_32.xbin"))
        whole_bank_dead = False

    eng = _cold_engine(model)
    report = aot.load_program_bank(eng, export)   # must not raise
    assert report["findings"], case
    rules = {f.rule for f in report["findings"]}
    assert rules <= {"TMG501", "TMG502"}, rules
    if whole_bank_dead:
        assert report["loaded"] == []
    else:
        # per-program damage: the OTHER buckets still serve from the bank
        assert report["loaded"] != []
        assert len(report["skipped"]) == 1
        assert {"TMG502"} == rules
    # scoring still works — JIT fills the holes, results identical
    out = eng.score_store(records[:12])           # bucket 16
    jit = _cold_engine(model)
    _assert_bitwise(out[pred.name],
                    jit.score_store(records[:12])[pred.name])
    if whole_bank_dead:
        assert eng.compile_count > 0
    elif case == "tampered_program":
        assert eng.compile_count == 1             # only bucket 16 re-JITs


def test_load_scoring_fn_warns_on_version_skew(banked, tmp_path, caplog):
    """Satellite: environment skew on the plain StableHLO artifact is a
    WARNING (TMG503), not a failure — the artifact still loads and
    scores."""
    import logging
    model, records, pred, export, _ = banked
    export = _copy_export(export, tmp_path)
    meta_path = os.path.join(export, "scoring_export.json")
    with open(meta_path) as fh:
        meta = json.load(fh)
    meta["environment"]["jax"] = "0.0.1"
    with open(meta_path, "w") as fh:
        json.dump(meta, fh)
    with caplog.at_level(logging.WARNING,
                         logger="transmogrifai_tpu.serving"):
        fn = serving.load_scoring_fn(export, prefer_bank=False)
    assert any("TMG503" in r.message for r in caplog.records)
    assert callable(fn)


def test_flat_bank_path_matches_stablehlo_path(banked):
    """load_scoring_fn's bank dispatch (padded to the ladder bucket,
    sliced back) returns the same arrays as the StableHLO JIT path, and
    batches beyond the bank's cap fall back."""
    model, records, pred, export, _ = banked
    eng = _cold_engine(model)
    store = eng._raw_store(records[:10])
    _, prepared, uploads = eng.host_blocks(store)
    blocks = {}
    for uid, bl in prepared.items():
        for k, v in bl.items():
            blocks[f"{uid}/{k}"] = v
    blocks.update(uploads)

    banked_fn = serving.load_scoring_fn(export)
    plain_fn = serving.load_scoring_fn(export, prefer_bank=False)
    assert banked_fn.bank_buckets == [8, 16, 32, 64]
    assert plain_fn.bank_buckets == []
    a = banked_fn(blocks)
    b = plain_fn(blocks)
    assert sorted(a) == sorted(b)
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-6, atol=1e-7)
    nm = pred.name
    assert a[f"{nm}.prediction"].shape == (10,)


# ---------------------------------------------------------------------------
# the engine preload seam + eviction tallies (satellite)
# ---------------------------------------------------------------------------


def test_preload_seam_and_eviction_counter(banked):
    model, records, pred, export, _ = banked
    eng = _cold_engine(model)
    assert eng.programs() == []
    before = engine_cache_stats()
    sentinel = object()
    for i in range(PROGRAM_CACHE_CAP + 3):
        eng.preload(("fake-key", i), sentinel)
    after = engine_cache_stats()
    assert after["preloads"] - before["preloads"] == PROGRAM_CACHE_CAP + 3
    assert after["evictions"] - before["evictions"] == 3
    assert len(eng.programs()) == PROGRAM_CACHE_CAP
    # LRU order: the oldest keys were the ones evicted
    assert ("fake-key", 0) not in eng.programs()
    assert ("fake-key", PROGRAM_CACHE_CAP + 2) in eng.programs()
    assert eng.compile_count == 0     # preloads are not compiles


def test_stale_bank_rejected_and_bankless_export_removes_bank(banked,
                                                              tmp_path):
    """A re-export that does NOT write a fresh bank must not leave the
    previous export's bank behind (it closes over the OLD weights); and
    if a stale bank does survive (copied back, partial rsync), the flat
    loader cross-checks the bank digests against the export metadata
    and refuses it with a TMG501 advisory."""
    import shutil
    model, records, pred, export, _ = banked
    d = str(tmp_path / "roundtrip")
    shutil.copytree(export, d)
    assert os.path.isdir(aot.bank_dir(d))
    # (b) bankless re-export removes the stale bank directory
    model2, records2, pred2 = _train(seed=99)
    serving.export_scoring_fn(model2, d, records2[:8],
                              bucket_cap=BUCKET_CAP, aot=False)
    assert not os.path.isdir(aot.bank_dir(d))
    # (a) resurrect model 1's bank beside model 2's StableHLO: the
    # digest cross-check must reject it — StableHLO path serves
    shutil.copytree(aot.bank_dir(export), aot.bank_dir(d))
    fn = serving.load_scoring_fn(d)
    assert fn.bank_buckets == []
    manifest, programs, findings = aot.load_flat_programs(
        d, expect_digests={"planDigest": fn.meta["planDigest"],
                           "stateDigest": fn.meta["stateDigest"]})
    assert programs == {}
    assert any(f.rule == "TMG501" and "STALE" in f.message
               for f in findings)


def test_aot_stats_tallies(banked, tmp_path):
    model, records, pred, export, _ = banked
    before = aot.aot_stats()
    serving.export_scoring_fn(model, str(tmp_path), records[:8],
                              bucket_cap=16)
    eng = ScoringEngine(model, gate_bandwidth=False, mesh=False,
                        bucket_cap=16)
    aot.load_program_bank(eng, str(tmp_path))
    after = aot.aot_stats()
    assert after["banks_exported"] - before["banks_exported"] == 1
    assert after["programs_exported"] - before["programs_exported"] == 2
    assert after["banks_loaded"] - before["banks_loaded"] == 1
    assert after["programs_loaded"] - before["programs_loaded"] == 2
