"""Worker program for the two-process jax.distributed test.

Each process joins a local coordinator, runs a GSPMD-sharded computation
over the 2-process global device set (a cross-host psum rides the
coordination backend), and routes a shared-filesystem write through the
coordinator gate. Invoked as:

    python _multihost_worker.py <coordinator host:port> <rank> <outdir>
"""
import json
import os
import sys


def main() -> None:
    addr, rank, outdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

    import jax

    # the axon shim pins jax_platforms at interpreter start; override
    # BEFORE any backend init (same as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")

    from transmogrifai_tpu.parallel import multihost

    assert multihost.initialize(coordinator_address=addr,
                                num_processes=2, process_id=rank) is True
    assert multihost.is_distributed(), "process_count should be 2"
    assert jax.process_count() == 2
    assert multihost.is_coordinator() == (rank == 0)

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # one global mesh over both processes' devices; a row-sharded gram
    # matrix forces a cross-process reduction (the fit path's collective)
    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("data",))
    n, d = 8, 3
    X_host = np.arange(n * d, dtype=np.float32).reshape(n, d)
    X = jax.make_array_from_callback(
        (n, d), NamedSharding(mesh, P("data")),
        lambda idx: X_host[idx])
    gram = jax.jit(lambda a: a.T @ a)(X)
    np.testing.assert_allclose(np.asarray(gram), X_host.T @ X_host,
                               rtol=1e-6)

    # coordinator-gated shared-filesystem write (runner metrics-sink path)
    from transmogrifai_tpu.runner import OpWorkflowRunner

    OpWorkflowRunner._write_metrics(
        os.path.join(outdir, "metrics.json"),
        {"writer_rank": rank, **multihost.process_summary()})

    # per-process completion marker (not coordinator-gated, for the parent)
    with open(os.path.join(outdir, f"done-{rank}"), "w") as fh:
        json.dump({"gram00": float(np.asarray(gram)[0, 0])}, fh)
    print(f"worker {rank} ok")


if __name__ == "__main__":
    main()
