"""Feature graph + stage abstraction tests (FeatureLikeTest analog)."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, column_from_values
from transmogrifai_tpu.columns import NumericColumn
from transmogrifai_tpu.graph import compute_dag
from transmogrifai_tpu.stages.base import (Estimator, FittedModel, FixedArity,
                                           LambdaTransformer, Transformer)
from transmogrifai_tpu.types import feature_types as ft


def _add_transformer(name="plus"):
    def fn(a, b):
        mask = a.mask & b.mask
        return NumericColumn(ft.Real, np.where(mask, a.values + b.values, 0.0), mask)
    return LambdaTransformer(name, fn, [ft.Real, ft.Real], ft.Real)


def test_feature_builder_and_raw_features():
    age = FeatureBuilder.Real("age").extract(lambda r: r["age"]).as_predictor()
    assert age.name == "age" and age.is_raw and not age.is_response
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    assert label.is_response and label.ftype is ft.RealNN


def test_transform_with_builds_dag():
    a = FeatureBuilder.Real("a").from_column().as_predictor()
    b = FeatureBuilder.Real("b").from_column().as_predictor()
    c = a.transform_with(_add_transformer(), b)
    assert c.parents == (a, b)
    assert c.ftype is ft.Real
    assert not c.is_response
    assert {f.name for f in c.raw_features()} == {"a", "b"}
    d = c.transform_with(_add_transformer(), a)
    layers = compute_dag([d])
    assert len(layers) == 2  # two stage layers deepest-first
    assert layers[0][0].get_output() is c


def test_input_type_checking():
    a = FeatureBuilder.Real("a").from_column().as_predictor()
    t = FeatureBuilder.Text("t").from_column().as_predictor()
    stage = _add_transformer()
    with pytest.raises(TypeError):
        stage.set_input(a, t)  # Text is not Real
    with pytest.raises(TypeError):
        stage.set_input(a)  # arity


def test_label_leak_gate():
    a = FeatureBuilder.Real("a").from_column().as_predictor()
    y = FeatureBuilder.RealNN("y").from_column().as_response()
    with pytest.raises(TypeError):
        _add_transformer().set_input(a, y)  # mixing label without AllowLabelAsInput


def test_response_propagation():
    y1 = FeatureBuilder.RealNN("y1").from_column().as_response()
    y2 = FeatureBuilder.RealNN("y2").from_column().as_response()
    out = y1.transform_with(_add_transformer(), y2)
    assert out.is_response  # all inputs are responses


def test_transform_columns_and_row_agree():
    a = FeatureBuilder.Real("a").from_column().as_predictor()
    b = FeatureBuilder.Real("b").from_column().as_predictor()
    stage = _add_transformer()
    c = a.transform_with(stage, b)
    store = ColumnStore.from_dict({
        "a": (ft.Real, [1.0, 2.0, None]),
        "b": (ft.Real, [10.0, 20.0, 30.0]),
    })
    out = stage.transform_columns(store)
    assert out.to_list() == [11.0, 22.0, None]
    assert stage.transform_row({"a": 2.0, "b": 3.0}) == 5.0
    assert stage.transform_row({"a": None, "b": 3.0}) is None


def test_cycle_detection():
    a = FeatureBuilder.Real("a").from_column().as_predictor()
    b = FeatureBuilder.Real("b").from_column().as_predictor()
    stage = _add_transformer()
    c = a.transform_with(stage, b)
    # force a cycle: make c a parent of its own ancestor
    object.__setattr__ if False else None
    a.parents = (c,)  # type: ignore[misc]
    from transmogrifai_tpu.features import FeatureCycleError
    with pytest.raises(FeatureCycleError):
        c.parent_stages()


class _MeanImputeEstimator(Estimator):
    operation_name = "meanImpute"
    output_type = ft.RealNN

    @property
    def input_spec(self):
        return FixedArity(ft.Real)

    def fit_columns(self, store):
        col = store[self.input_features[0].name]
        mean = float(col.values[col.mask].mean()) if col.mask.any() else 0.0
        return _MeanImputeModel(mean=mean)


class _MeanImputeModel(FittedModel):
    operation_name = "meanImpute"
    output_type = ft.RealNN

    def __init__(self, mean=0.0, uid=None):
        super().__init__(uid=uid)
        self.mean = mean

    @property
    def input_spec(self):
        return FixedArity(ft.Real)

    def transform_columns(self, store):
        col = store[self.input_features[0].name]
        vals = np.where(col.mask, col.values, self.mean)
        return NumericColumn(ft.RealNN, vals, np.ones_like(col.mask))

    def get_model_state(self):
        return {"mean": self.mean}


def test_estimator_fit_swaps_model():
    a = FeatureBuilder.Real("a").from_column().as_predictor()
    est = _MeanImputeEstimator()
    out = a.transform_with(est)
    store = ColumnStore.from_dict({"a": (ft.Real, [1.0, None, 3.0])})
    model = est.fit(store)
    assert model.uid == est.uid
    assert model.get_output() is out
    assert model.mean == 2.0
    assert model.transform_columns(store).to_list() == [1.0, 2.0, 3.0]
    assert model.transform_row({"a": None}) == 2.0


def test_stage_copy_and_params():
    est = _MeanImputeEstimator()
    m = _MeanImputeModel(mean=5.0)
    assert m.get_params()["mean"] == 5.0
    m2 = m.copy()
    assert m2.uid == m.uid and m2.mean == 5.0
    m.set_params(mean=7.0)
    assert m.mean == 7.0


def test_from_store_inference():
    store = ColumnStore.from_dict({
        "y": (ft.RealNN, [1.0, 0.0]),
        "x1": (ft.Real, [1.0, 2.0]),
        "t": (ft.Text, ["a", "b"]),
    })
    resp, preds = FeatureBuilder.from_store(store, "y")
    assert resp.is_response and resp.ftype is ft.RealNN
    assert {p.name: p.ftype for p in preds} == {"x1": ft.Real, "t": ft.Text}


def test_feature_graph_json_roundtrip(rng):
    """FeatureJsonHelper analog: an unfitted DAG round-trips through JSON
    and the rebuilt graph trains to the same result."""
    import json as _json

    import numpy as np

    from transmogrifai_tpu import ColumnStore, Workflow, column_from_values
    from transmogrifai_tpu.feature_json import (features_from_json,
                                                features_to_json)
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.types import feature_types as ft

    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    fc = FeatureBuilder.PickList("c").from_column().as_predictor()
    vec = transmogrify([fx, fc])
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None)
    pred = label.transform_with(selector, vec)

    doc = _json.loads(_json.dumps(features_to_json([pred])))
    (pred2,) = features_from_json(doc)
    assert pred2.name == pred.name and pred2.uid == pred.uid
    assert {s.uid for s in pred2.parent_stages()} == \
        {s.uid for s in pred.parent_stages()}

    n = 120
    y = rng.integers(0, 2, n).astype(float)
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "x": column_from_values(ft.Real, list(rng.normal(size=n) + y)),
        "c": column_from_values(ft.PickList,
                                ["a" if v else "b" for v in y]),
    })
    model = (Workflow().set_input_store(store)
             .set_result_features(pred2).train())
    assert model.score(store).n_rows == n
