"""OPCollectionTransformer family: scalar unary transforms lifted over
maps/lists/sets (OPCollectionTransformer.scala:1-209) — columnar lift,
type validation at wiring, empty-in → empty-out, persistence."""
import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder, Workflow, WorkflowModel
from transmogrifai_tpu.columns import ColumnStore, column_from_values
from transmogrifai_tpu.ops.collections import (OPListTransformer,
                                               OPMapTransformer,
                                               OPSetTransformer,
                                               lift_to_collection)
from transmogrifai_tpu.ops.scalers import ScalerTransformer
from transmogrifai_tpu.ops.text_suite import EmailParser
from transmogrifai_tpu.types import feature_types as ft


def test_map_values_lift():
    store = ColumnStore.from_dict({
        "m": (ft.RealMap, [{"a": 1.0, "b": 2.0}, {"a": 3.0}, {}])})
    feat = FeatureBuilder.RealMap("m").from_column().as_predictor()
    lifted = OPMapTransformer(ScalerTransformer(slope=10.0, intercept=1.0))
    out_feat = feat.transform_with(lifted)
    assert out_feat.ftype is ft.RealMap
    out = lifted.transform_columns(store)
    assert out.get_raw(0) == {"a": 11.0, "b": 21.0}
    assert out.get_raw(1) == {"a": 31.0}
    assert out.get_raw(2) == {}                      # empty in → empty out


def test_list_and_set_lift():
    store = ColumnStore.from_dict({
        "l": (ft.TextList, [["x@a.com", "y@b.org"], [], ["z@a.com"]])})
    sstore = ColumnStore.from_dict({
        "s": (ft.MultiPickList, [{"u@a.com", "v@a.com"}, set()])})
    lifted_l = OPListTransformer(EmailParser(part="domain"))
    lf = FeatureBuilder.TextList("l").from_column().as_predictor()
    out_feat = lf.transform_with(lifted_l)
    assert out_feat.ftype is ft.TextList
    out = lifted_l.transform_columns(store)
    assert out.get_raw(0) == ["a.com", "b.org"]
    assert out.get_raw(1) == []
    assert out.get_raw(2) == ["a.com"]

    lifted_s = OPSetTransformer(EmailParser(part="domain"))
    sf = FeatureBuilder.MultiPickList("s").from_column().as_predictor()
    sout_feat = sf.transform_with(lifted_s)
    assert sout_feat.ftype is ft.MultiPickList
    sout = lifted_s.transform_columns(sstore)
    assert sout.get_raw(0) == {"a.com"}              # set semantics dedupe
    assert sout.get_raw(1) == set()


def test_type_validation_at_wiring():
    # Real-scalar transformer cannot lift over a Text-element collection
    bad = OPListTransformer(ScalerTransformer())
    lf = FeatureBuilder.TextList("l").from_column().as_predictor()
    with pytest.raises(TypeError, match="not convertible"):
        lf.transform_with(bad)

    with pytest.raises(TypeError, match="not convertible"):
        lift_to_collection(ScalerTransformer(), ft.TextMap)
    # and the factory picks the right lift for a matching pair
    ok = lift_to_collection(ScalerTransformer(), ft.RealMap)
    assert isinstance(ok, OPMapTransformer)


def test_lifted_transform_in_workflow_and_persistence(tmp_path):
    """A lifted stage rides the DAG, and the nested scalar transformer
    round-trips through model save/load (the __stage__ codec)."""
    store = ColumnStore.from_dict({
        "m": (ft.RealMap, [{"a": 1.0}, {"a": 2.0, "b": -1.0}])})
    feat = FeatureBuilder.RealMap("m").from_column().as_predictor()
    lifted = OPMapTransformer(ScalerTransformer(slope=2.0))
    out_feat = feat.transform_with(lifted)
    model = (Workflow().set_input_store(store)
             .set_result_features(out_feat).train())
    scored = model.transform(store)
    assert scored[out_feat.name].get_raw(1) == {"a": 4.0, "b": -2.0}

    path = str(tmp_path / "m")
    model.save(path)
    loaded = WorkflowModel.load(path)
    re_scored = loaded.transform(store)
    assert re_scored[out_feat.name].get_raw(1) == {"a": 4.0, "b": -2.0}
