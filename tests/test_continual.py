"""Continuous-training tests (continual.py + the fitstats warm seam).

The self-healing contract: drift windows arm a retrain only after a
hysteresis streak, the job runs as a supervised subprocess behind a
flocked ACTIVE slot (exactly one retrainer fleet-wide), a warm-started
refit Chan-merges the persisted sufficient statistics with the fresh
slice and matches a cold full refit over the concatenated window, a
worse-on-holdout candidate is rejected before deploy, the consecutive-
failure budget disarms LOUDLY, and a SIGKILL mid-retrain (real, fresh
interpreter) leaves the CURRENT pointer serving the stable version with
the job record replayable and the storm controls honored on restart.
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import textwrap
import time
from types import SimpleNamespace

import numpy as np
import pytest

from transmogrifai_tpu import (FeatureBuilder, Workflow, continual,
                               fitstats, lifecycle, lint, resilience,
                               serving)
from transmogrifai_tpu import server as server_mod
from transmogrifai_tpu.continual import ContinualError, RetrainController
from transmogrifai_tpu.filters.raw_feature_filter import RawFeatureFilter
from transmogrifai_tpu.lifecycle import ModelRegistry
from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                      LogisticRegressionFamily)
from transmogrifai_tpu.ops.transmogrifier import transmogrify

BUCKET_CAP = 64
N_ROWS = 240

#: the shared data-generation recipe — the trainer subprocess embeds the
#: SAME code (via _GEN_SRC) so parent and trainer agree on distributions
_GEN_SRC = textwrap.dedent("""
    import numpy as np

    def gen(seed, n, shifted=False):
        rng = np.random.default_rng(seed)
        y = np.asarray([i % 2 for i in range(n)], float)
        rng.shuffle(y)
        recs = []
        for i in range(n):
            base = float(0.8 * rng.normal() + 2.0 * y[i])
            x1 = (30.0 - base) if shifted else base
            recs.append({
                "label": float(y[i]),
                "x1": (None if rng.random() < 0.1 else x1),
                "x2": float(rng.normal())})
        return recs

    def build(recs, seed=1):
        from transmogrifai_tpu import FeatureBuilder, Workflow
        from transmogrifai_tpu.filters.raw_feature_filter import \\
            RawFeatureFilter
        from transmogrifai_tpu.models import (
            BinaryClassificationModelSelector, LogisticRegressionFamily)
        from transmogrifai_tpu.ops.transmogrifier import transmogrify
        label = FeatureBuilder.RealNN("label").from_column().as_response()
        f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
        f2 = FeatureBuilder.Real("x2").from_column().as_predictor()
        vec = transmogrify([f1, f2])
        sel = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=2, families=[LogisticRegressionFamily()],
            splitter=None, seed=seed)
        pred = label.transform_with(sel, vec)
        return (Workflow().set_input_records(recs)
                .with_raw_feature_filter(RawFeatureFilter(bins=20))
                .set_result_features(pred))
""")

_ns: dict = {}
exec(_GEN_SRC, _ns)
gen, build = _ns["gen"], _ns["build"]


def _aupr(model_summary):
    from transmogrifai_tpu.continual import _metric_of
    return _metric_of(model_summary, "AuPR")


@pytest.fixture(autouse=True)
def _lock_order_witness():
    """Every test in this module doubles as a race harness: the
    TMG8xx runtime witness (utils/locks.py) records the cross-thread
    lock acquisition order the real code paths execute and the
    teardown asserts no inversion was observed. Record mode, not
    raise mode — a raise inside a never-raises boundary (dispatch
    workers, the fleet monitor) would be swallowed where an assert
    here cannot be."""
    from transmogrifai_tpu.utils import locks
    locks.arm(raise_on_violation=False)
    yield
    violations = locks.violations()
    locks.disarm()
    locks.reset()
    assert violations == [], "\n".join(violations)


@pytest.fixture(scope="module")
def stable(tmp_path_factory):
    """One trained stable model (missing values so fill means matter),
    saved + AOT-exported + registered as the promoted CURRENT."""
    model = build(gen(11, N_ROWS)).train()
    mdir = str(tmp_path_factory.mktemp("model_v0"))
    edir = str(tmp_path_factory.mktemp("export_v0"))
    model.save(mdir, overwrite=True)
    recs = gen(11, 16)
    serving.export_scoring_fn(model, edir, recs[:8],
                              bucket_cap=BUCKET_CAP)
    reg_dir = str(tmp_path_factory.mktemp("registry"))
    reg = ModelRegistry(reg_dir)
    vid = reg.register("churn", mdir, bank_dir=edir,
                       train_metrics={"AuPR": _aupr(model.summary())},
                       promote=True)
    yield {"model": model, "model_dir": mdir, "export_dir": edir,
           "registry": reg, "registry_dir": reg_dir, "vid": vid}
    model._engine_breaker().reset()


def _quick_fail_cmd(code=3):
    return [sys.executable, "-c", f"import sys; sys.exit({code})"]


def _no_delay_backoff():
    return resilience.RetryPolicy(max_attempts=4, base_delay_s=0.0,
                                  max_delay_s=0.0, jitter=0.0)


def _drifted():
    return [SimpleNamespace(rule="TMG601", feature="x1")]


# ---------------------------------------------------------------------------
# catalog / monoid / persistence
# ---------------------------------------------------------------------------


def test_fault_sites_and_rules_cataloged():
    for site in ("continual.retrain", "continual.register",
                 "continual.merge_stats"):
        assert site in resilience.FAULT_SITES
    for rule in ("TMG310", "TMG604", "TMG605"):
        assert rule in lint.RULES


def test_sufficient_stats_monoid_matches_concat():
    """merge(state(a), state(b)) == state(a ++ b) — the Chan-merge
    exactness the whole warm-start story rests on."""
    rng = np.random.default_rng(3)
    a, b = rng.normal(size=999), rng.normal(size=501) + 7.0

    class Col:
        def __init__(self, v):
            self.values = v
            self.mask = np.ones(v.size, bool)

    merged = fitstats.collect_column_state(Col(a)).merge(
        fitstats.collect_column_state(Col(b)))
    full = fitstats.collect_column_state(Col(np.concatenate([a, b])))
    assert merged.count == full.count
    assert merged.min == full.min and merged.max == full.max
    assert abs(merged.mean - full.mean) < 1e-12
    assert abs(merged.finalize("variance") - full.finalize("variance")) \
        < 1e-12
    assert abs(merged.finalize("std", (1,)) - full.finalize("std", (1,))) \
        < 1e-12
    # JSON round-trip is lossless
    rt = fitstats.SufficientStats.from_json(json.loads(
        json.dumps(merged.to_json())))
    assert rt.to_json() == merged.to_json()
    # empty-side identity
    assert fitstats.SufficientStats().merge(full).to_json() \
        == full.to_json()


def test_sufficient_stats_persist_with_model(stable):
    """Every train persists its moment sufficient stats in model.json;
    load_warm_stats round-trips them and degrades (TMG604 + tally) on a
    model dir without them."""
    assert stable["model"].fit_stats, "train collected no fit_stats"
    warm = continual.load_warm_stats(stable["model_dir"])
    assert warm and all(isinstance(v, fitstats.SufficientStats)
                        for v in warm.values())
    assert any(k.endswith(":x1") for k in warm)
    before = continual.continual_stats()["full_refit_fallbacks"]
    assert continual.load_warm_stats("/nonexistent/model/dir") is None
    assert continual.continual_stats()["full_refit_fallbacks"] \
        == before + 1


def test_warm_refit_matches_cold_concat_fresh_interpreter(
        stable, tmp_path):
    """Satellite: a warm-started refit (merged persisted stats + one
    pass over the fresh slice) matches a cold full refit over the
    concatenated window within tolerance, per opted-in estimator family
    — proven in a FRESH interpreter so the stats round-trip through the
    saved model on disk, not through process state."""
    script = _GEN_SRC + textwrap.dedent(f"""
        import sys
        from transmogrifai_tpu import continual

        old = gen(11, {N_ROWS})
        fresh = gen(12, 160, shifted=False)
        warm_stats = continual.load_warm_stats({stable['model_dir']!r})
        assert warm_stats, "persisted stats did not load"
        mw = build(fresh).with_warm_fit_stats(warm_stats).train()
        mc = build(old + fresh).train()

        def fills(m):
            return {{st.stage_name(): [float(v) for v in st.fill_values]
                     for st in m.fitted_stages.values()
                     if getattr(st, "fill_values", None) is not None}}

        fw, fc = fills(mw), fills(mc)
        assert fw and set(fw) == set(fc), (fw, fc)
        for k in fw:
            for a, b in zip(fw[k], fc[k]):
                assert abs(a - b) < 1e-6, (k, a, b, fw, fc)
        from transmogrifai_tpu import fitstats
        assert fitstats.fitstats_stats()["warm_state_merges"] >= 1
        print("WARM_PARITY_OK")
        sys.exit(0)
    """)
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=560)
    assert proc.returncode == 0, proc.stderr[-1500:]
    assert "WARM_PARITY_OK" in proc.stdout


def test_corrupt_warm_stats_degrade_with_tmg604(stable, tmp_path):
    """Corrupt persisted stats ⇒ load returns None (TMG604, tallied);
    a mismatched warm mapping ⇒ the train emits TMG604 and runs a full
    refit — never a crash."""
    broken = tmp_path / "broken_model"
    shutil.copytree(stable["model_dir"], broken)
    mj = broken / "model.json"
    doc = json.loads(mj.read_text())
    doc["fitSufficientStats"] = {"0:x1": {"count": "NOT A NUMBER"}}
    mj.write_text(json.dumps(doc))
    assert continual.load_warm_stats(str(broken)) is None
    # keys that match no fused layer: full refit + TMG604, not a crash
    bogus = {"9:no_such_column": fitstats.SufficientStats(1, 0, 0, 0, 0)}
    model = build(gen(21, 120)).with_warm_fit_stats(bogus).train()
    assert model.fitted_stages


def test_merge_stats_fault_degrades_column_to_fresh(stable):
    """An injected continual.merge_stats fault degrades that column to
    fresh-slice stats — the refit completes, nothing raises."""
    warm = continual.load_warm_stats(stable["model_dir"])
    plan = resilience.FaultPlan(seed=5).on("continual.merge_stats",
                                           error=ValueError)
    with resilience.fault_plan(plan):
        model = build(gen(22, 120)).with_warm_fit_stats(warm).train()
    assert model.fitted_stages
    assert plan.fired("continual.merge_stats") >= 1


# ---------------------------------------------------------------------------
# storm control (hysteresis, cooldown, failure budget, flock)
# ---------------------------------------------------------------------------


def test_hysteresis_then_cooldown(stable, tmp_path):
    """One drifted window never trains (arm_windows=2); a clean window
    resets the streak; two consecutive drifted windows launch ONE job;
    the cooldown then suppresses further triggers."""
    c = RetrainController("churn", stable["registry"], _quick_fail_cmd(),
                          job_dir=str(tmp_path / "jobs"),
                          arm_windows=2, cooldown_s=60.0,
                          max_failures=10,
                          backoff=_no_delay_backoff())
    c.on_window(_drifted(), {})
    assert c.status()["streak"] == 1 and not c.jobs()
    c.on_window([], {})                      # clean window resets
    assert c.status()["streak"] == 0
    c.on_window(_drifted(), {})
    c.on_window(_drifted(), {})              # second consecutive: arm
    assert c.wait_idle(60)
    jobs = c.jobs()
    assert len(jobs) == 1
    assert jobs[0]["state"] == "failed"
    assert "exited 3" in jobs[0]["error"]
    # cooldown: two more drifted windows are suppressed, no second job
    before = continual.continual_stats()["suppressed_cooldown"]
    c.on_window(_drifted(), {})
    c.on_window(_drifted(), {})
    assert continual.continual_stats()["suppressed_cooldown"] > before
    assert len(c.jobs()) == 1
    assert c.status()["cooldownRemainingS"] > 0


def test_failure_budget_disarms_loudly_and_rearm(stable, tmp_path):
    """max_failures consecutive failed jobs ⇒ TMG605 + disarm (never a
    retrain-crash-retrain hot loop); rearm() restores operation."""
    c = RetrainController("churn", stable["registry"], _quick_fail_cmd(),
                          job_dir=str(tmp_path / "jobs"),
                          arm_windows=1, cooldown_s=0.0, max_failures=2,
                          backoff=_no_delay_backoff())
    gave_before = continual.continual_stats()["gave_up"]
    c.on_window(_drifted(), {})
    assert c.wait_idle(60)
    assert not c.status()["disarmed"]
    c.on_window(_drifted(), {})
    assert c.wait_idle(60)
    st = c.status()
    assert st["disarmed"] and st["failures"] == 2
    assert continual.continual_stats()["gave_up"] == gave_before + 1
    # disarmed: further drift is suppressed, loudly tallied
    before = continual.continual_stats()["suppressed_disarmed"]
    c.on_window(_drifted(), {})
    assert continual.continual_stats()["suppressed_disarmed"] > before
    assert len(c.jobs()) == 2
    # operator re-arm restores the loop
    c.rearm()
    c.on_window(_drifted(), {})
    assert c.wait_idle(60)
    assert len(c.jobs()) == 3


def test_retrain_fault_site_counts_as_failure(stable, tmp_path):
    """An injected continual.retrain fault models a job dying at t=0:
    no subprocess spawns, the failure budget still advances."""
    c = RetrainController("churn", stable["registry"], _quick_fail_cmd(),
                          job_dir=str(tmp_path / "jobs"),
                          arm_windows=1, cooldown_s=0.0, max_failures=5,
                          backoff=_no_delay_backoff())
    plan = resilience.FaultPlan(seed=9).on("continual.retrain",
                                           error=OSError, times=1)
    with resilience.fault_plan(plan):
        c.on_window(_drifted(), {})
        assert c.wait_idle(60)
    assert plan.fired("continual.retrain") == 1
    assert c.status()["failures"] == 1
    assert not c.jobs() or c.jobs()[-1]["state"] != "running"


def test_active_slot_flock_single_retrainer(stable, tmp_path):
    """Two controllers sharing one job dir (the fleet-worker topology):
    the second trigger finds the ACTIVE slot flocked and drops — one
    job record, no double retrain."""
    jd = str(tmp_path / "shared_jobs")
    slow = [sys.executable, "-c",
            "import time, sys; time.sleep(2.0); sys.exit(4)"]
    a = RetrainController("churn", stable["registry"], slow, job_dir=jd,
                          arm_windows=1, cooldown_s=0.0,
                          max_failures=10, backoff=_no_delay_backoff())
    b = RetrainController("churn", stable["registry"], slow, job_dir=jd,
                          arm_windows=1, cooldown_s=0.0,
                          max_failures=10, backoff=_no_delay_backoff())
    suppressed = continual.continual_stats()["suppressed_active"]
    assert a.trigger() is not None
    deadline = time.monotonic() + 30
    while not a.jobs() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert a.jobs(), "first job never started"
    assert b.trigger() is not None        # thread starts, slot is held
    assert a.wait_idle(60) and b.wait_idle(60)
    assert continual.continual_stats()["suppressed_active"] > suppressed
    assert len(a.jobs()) == 1             # exactly ONE job record


def test_holdout_gate_rejects_worse_candidate(stable, tmp_path):
    """A trainer that produces a model measurably worse than stable on
    the holdout metric is REJECTED before deploy: nothing registers,
    the pointer stays, the rejection spends failure budget."""
    trainer = tmp_path / "bad_trainer.py"
    trainer.write_text(textwrap.dedent("""
        import json, os, shutil
        out = os.environ["TMOG_RETRAIN_OUT"]
        shutil.copytree(os.environ["TMOG_RETRAIN_STABLE"],
                        os.path.join(out, "model"))
        with open(os.path.join(out, "metrics.json"), "w") as fh:
            json.dump({"AuPR": 0.05}, fh)
    """))
    reg = stable["registry"]
    versions_before = len(reg.versions("churn"))
    rejected_before = continual.continual_stats()["candidates_rejected"]
    c = RetrainController("churn", reg,
                          [sys.executable, str(trainer)],
                          job_dir=str(tmp_path / "jobs"),
                          arm_windows=1, cooldown_s=0.0, max_failures=5,
                          backoff=_no_delay_backoff(),
                          holdout_metric="AuPR")
    c.on_window(_drifted(), {})
    assert c.wait_idle(120)
    job = c.jobs()[-1]
    assert job["state"] == "rejected", job
    assert "holdout" in job["error"]
    assert continual.continual_stats()["candidates_rejected"] \
        == rejected_before + 1
    assert len(reg.versions("churn")) == versions_before
    assert reg.current("churn") == stable["vid"]
    assert c.status()["failures"] == 1


def test_timeout_kills_stalled_job(stable, tmp_path):
    """A trainer that outlives timeout_s is SIGKILLed; the job records
    the kill reason and the budget advances."""
    slow = [sys.executable, "-c", "import time; time.sleep(120)"]
    c = RetrainController("churn", stable["registry"], slow,
                          job_dir=str(tmp_path / "jobs"),
                          arm_windows=1, cooldown_s=0.0, max_failures=5,
                          timeout_s=1.0, heartbeat_timeout_s=600.0,
                          backoff=_no_delay_backoff())
    killed_before = continual.continual_stats()["jobs_killed"]
    c.on_window(_drifted(), {})
    assert c.wait_idle(90)
    job = c.jobs()[-1]
    assert job["state"] == "killed" and "timeout" in job["error"]
    assert continual.continual_stats()["jobs_killed"] \
        == killed_before + 1
    assert not continual._pid_alive(job["pid"])


# ---------------------------------------------------------------------------
# crash safety: SIGKILL mid-retrain, recovery, replay
# ---------------------------------------------------------------------------


def test_sigkill_mid_retrain_pointer_safe_and_replayable(
        stable, tmp_path):
    """The acceptance chaos test: SIGKILL a REAL controller process
    mid-retrain. The CURRENT pointer keeps serving the stable version,
    the job record is on disk in `running`, a fresh controller's
    recover() marks it interrupted, kills the orphan trainer, and the
    cooldown + failure budget are honored on retry — a crash can never
    reset the storm controls."""
    jd = str(tmp_path / "jobs")
    child_src = textwrap.dedent(f"""
        import sys, time
        from transmogrifai_tpu.continual import RetrainController
        from transmogrifai_tpu.lifecycle import ModelRegistry
        reg = ModelRegistry({stable['registry_dir']!r})
        c = RetrainController(
            "churn", reg,
            [sys.executable, "-c", "import time; time.sleep(120)"],
            job_dir={jd!r}, arm_windows=1, cooldown_s=300.0,
            max_failures=3)
        assert c.trigger() is not None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            jobs = c.jobs()
            if jobs and jobs[-1]["state"] == "running":
                print("RUNNING", jobs[-1]["pid"], flush=True)
                break
            time.sleep(0.05)
        time.sleep(300)
    """)
    proc = subprocess.Popen([sys.executable, "-c", child_src],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    line = proc.stdout.readline()
    assert line.startswith("RUNNING"), line
    trainer_pid = int(line.split()[1])
    proc.send_signal(signal.SIGKILL)      # the crash, mid-retrain
    proc.wait(timeout=60)
    # the stable version never stopped being CURRENT
    reg = stable["registry"]
    assert reg.current("churn") == stable["vid"]
    # the job record survived the kill, still marked running
    probe = RetrainController(
        "churn", reg, _quick_fail_cmd(), job_dir=jd, arm_windows=1,
        cooldown_s=300.0, max_failures=3,
        backoff=_no_delay_backoff())
    jobs = probe.jobs()
    assert jobs and jobs[-1]["state"] == "running"
    assert continual._pid_alive(trainer_pid)      # orphan still alive
    repaired = probe.recover()
    assert len(repaired) == 1
    job = probe.jobs()[-1]
    assert job["state"] == "interrupted"
    assert job["replayable"] is False     # trainer never exported
    deadline = time.monotonic() + 10
    while continual._pid_alive(trainer_pid) \
            and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not continual._pid_alive(trainer_pid), "orphan not killed"
    # storm controls restored from the records: budget counted, the
    # cooldown window re-anchored to the crashed job's start
    st = probe.status()
    assert st["failures"] >= 1
    assert st["cooldownRemainingS"] > 0
    assert probe.trigger() is None        # cooldown honored on retry
    assert reg.current("churn") == stable["vid"]


def test_interrupted_job_with_export_is_replayable(stable, tmp_path):
    """A controller that died AFTER its trainer exported (crash
    mid-register): recover() marks the record replayable and replay()
    completes register+deploy from disk — no retrain."""
    jd = tmp_path / "jobs"
    (jd / "jobs").mkdir(parents=True)
    out_dir = jd / "jobs" / "job-x.out"
    shutil.copytree(stable["model_dir"], out_dir / "model")
    with open(out_dir / "metrics.json", "w") as fh:
        json.dump({"AuPR": 0.99}, fh)
    dead = subprocess.run([sys.executable, "-c", "pass"],
                          capture_output=True)
    assert dead.returncode == 0
    record = {"jobId": "job-x", "model": "churn", "state": "running",
              "trigger": None, "cmd": ["true"],
              "outDir": str(out_dir), "log": str(jd / "jobs/job-x.log"),
              "createdAt": 0.0, "controllerPid": 2 ** 22 + os.getpid(),
              "pid": None, "exitCode": 0, "version": None,
              "error": None, "replayable": False}
    with open(jd / "jobs" / "job-x.json", "w") as fh:
        json.dump(record, fh)
    reg = stable["registry"]
    versions_before = len(reg.versions("churn"))
    c = RetrainController("churn", reg, _quick_fail_cmd(),
                          job_dir=str(jd), cooldown_s=0.0,
                          backoff=_no_delay_backoff())
    c.recover()
    job = c.job("job-x")
    assert job["state"] == "interrupted" and job["replayable"]
    replayed = c.replay("job-x")
    assert replayed["state"] == "succeeded"
    assert replayed["version"]
    # the register half completed from the persisted record (no server
    # attached: registered, awaiting promote — the pointer is untouched)
    assert len(reg.versions("churn")) == versions_before + 1
    assert reg.current("churn") == stable["vid"]
    with pytest.raises(ContinualError):
        c.replay("job-x")                 # no longer interrupted


# ---------------------------------------------------------------------------
# satellite: sentinel thread catch-and-tally
# ---------------------------------------------------------------------------


def test_sentinel_thread_survives_poison_and_tallies(stable):
    """Satellite regression: a poison item on the drift queue used to
    kill the accumulation thread silently (and wedge drain_drift). Now
    it tallies lifecycle.sentinel_errors, stays accounted, and the
    thread keeps observing."""
    srv = server_mod.ModelServer(bucket_cap=BUCKET_CAP,
                                 batch_deadline_s=0.0,
                                 registry=stable["registry"],
                                 drift_window=64)
    try:
        srv.register_from_registry("churn")
        recs = gen(31, 64)
        srv.score("churn", recs[:8], timeout_s=600)
        srv.drain_drift()
        errors_before = lifecycle.lifecycle_stats()["sentinel_errors"]
        # a malformed queue item: the unpack/coalesce path raises
        srv._drift_queue.put(("poison item with no records",))
        srv.drain_drift()             # returns — task_done accounted
        assert lifecycle.lifecycle_stats()["sentinel_errors"] \
            == errors_before + 1
        # the thread is alive and still folds real observations
        entry = srv._entries["churn"]
        seen_before = entry.sentinel.rows_seen
        for i in range(4):
            srv.score("churn", recs[8 * (i + 1):8 * (i + 2)],
                      timeout_s=600)
        srv.drain_drift()
        assert entry.sentinel.rows_seen > seen_before
        assert srv._drift_thread.is_alive()
    finally:
        srv.shutdown(drain=True)


def test_drift_subscription_survives_sentinel_rebuild(stable):
    """subscribe_drift re-attaches across sentinel rebuilds (the
    promote/eviction path), so the controller's trigger cannot be lost
    to a reload."""
    srv = server_mod.ModelServer(bucket_cap=BUCKET_CAP,
                                 batch_deadline_s=0.0,
                                 registry=stable["registry"],
                                 drift_window=64)
    try:
        srv.register_from_registry("churn")
        srv.score("churn", gen(32, 8), timeout_s=600)
        seen = []
        srv.subscribe_drift("churn", lambda f, r: seen.append(len(f)))
        entry = srv._entries["churn"]
        # simulate the eviction/promote path: sentinel rebuilt
        with entry.lock:
            entry.sentinel = srv._build_sentinel(entry.model, "churn")
        assert entry.sentinel._subscribers, "subscription lost"
        for i in range(12):
            srv.score("churn", gen(33 + i, 16), timeout_s=600)
        srv.drain_drift()
        assert seen, "no window callback fired after rebuild"
    finally:
        srv.shutdown(drain=True)


# ---------------------------------------------------------------------------
# the closed loop (chaos acceptance)
# ---------------------------------------------------------------------------


def _prob_of(store):
    for n in store.names():
        col = store[n]
        if hasattr(col, "probability"):
            p = np.asarray(col.probability)
            return p[:, 1] if p.ndim == 2 and p.shape[1] >= 2 \
                else np.asarray(col.prediction, float)
    raise AssertionError("no prediction column in result store")


def test_self_healing_loop_end_to_end(stable, tmp_path):
    """The acceptance loop: a covariate-shifted stream trips TMG601, a
    supervised retrain job runs (warm-started, real subprocess), the
    candidate registers and canary-promotes on evidence, holdout AuPR
    recovers, and ZERO client requests drop end to end."""
    trainer = tmp_path / "trainer.py"
    trainer.write_text(_GEN_SRC + textwrap.dedent("""
        import json, os
        from transmogrifai_tpu import continual, serving

        out = os.environ["TMOG_RETRAIN_OUT"]
        stable_dir = os.environ.get("TMOG_RETRAIN_STABLE") or None
        recs = gen(77, 240, shifted=True)      # the fresh (live) slice
        wf = build(recs, seed=2)
        warm = continual.load_warm_stats(stable_dir)
        wf.with_warm_fit_stats(warm)
        model = wf.train()
        model.save(os.path.join(out, "model"))
        serving.export_scoring_fn(model, os.path.join(out, "export"),
                                  recs[:8], bucket_cap=64)
        doc = model.summary()
        doc["warmStarted"] = bool(warm)
        with open(os.path.join(out, "metrics.json"), "w") as fh:
            json.dump(doc, fh, default=str)
        print("TRAINER_DONE", flush=True)
    """))
    reg = stable["registry"]
    srv = server_mod.ModelServer(bucket_cap=BUCKET_CAP,
                                 batch_deadline_s=0.0,
                                 registry=reg, drift_window=128)
    ctrl = None
    try:
        srv.register_from_registry("churn")
        srv.score("churn", gen(40, 8), timeout_s=600)   # warm
        ctrl = RetrainController(
            "churn", reg, [sys.executable, str(trainer)],
            server=srv, job_dir=str(tmp_path / "jobs"),
            arm_windows=2, cooldown_s=600.0, max_failures=2,
            timeout_s=500.0, heartbeat_timeout_s=500.0,
            deploy_mode="canary", canary_fraction=0.35,
            window_requests=6, promote_windows=2,
            holdout_metric="AuPR", holdout_tolerance=0.3).attach()
        shifted = gen(99, 4096, shifted=True)
        labels, probs = [], []
        promoted_at = None
        batch = 8
        i = 0
        deadline = time.monotonic() + 420
        while time.monotonic() < deadline:
            lo = (i * batch) % (len(shifted) - batch)
            recs = shifted[lo:lo + batch]
            res = srv.score("churn", recs, timeout_s=600)
            assert res.rows == batch          # zero drops, every time
            labels.extend(r["label"] for r in recs)
            probs.extend(_prob_of(res.store))
            i += 1
            srv.drain_drift()
            if reg.current("churn") != stable["vid"]:
                promoted_at = len(labels)
                break
        assert promoted_at is not None, (
            f"loop never promoted: ctrl={ctrl.status()} "
            f"jobs={ctrl.jobs()}")
        # drift was detected (TMG601 fired) before anything retrained
        entry = srv._entries["churn"]
        job = ctrl.jobs()[-1]
        assert job["state"] == "deployed", job
        assert job["version"] and job["version"] != stable["vid"]
        assert reg.current("churn") == job["version"]
        # the retrain WARM-started from the persisted stats
        rec = reg.record("churn", job["version"])
        assert rec["trainMetrics"]["warmStarted"] is True
        # traffic keeps flowing on the promoted model; AuPR recovers
        post_labels, post_probs = [], []
        for k in range(32):
            lo = (k * batch) % (len(shifted) - batch)
            recs = shifted[lo:lo + batch]
            res = srv.score("churn", recs, timeout_s=600)
            assert res.rows == batch
            post_labels.extend(r["label"] for r in recs)
            post_probs.extend(_prob_of(res.store))
        from transmogrifai_tpu.evaluators.metrics import binary_metrics
        n_before = min(promoted_at, 256)
        y0 = np.asarray(labels[:n_before])
        s0 = np.asarray(probs[:n_before])
        before = binary_metrics(y0, (s0 > 0.5).astype(float), s0)["AuPR"]
        y1 = np.asarray(post_labels)
        s1 = np.asarray(post_probs)
        after = binary_metrics(y1, (s1 > 0.5).astype(float), s1)["AuPR"]
        assert after > before, (before, after)
        assert after > 0.7, (before, after)
        # the loop's evidence: drift advisories fired, a canary ran,
        # the auto-promotion is on the lifecycle tallies
        stats = lifecycle.lifecycle_stats()
        assert stats["drift_advisories"] >= 1
        assert stats["auto_promotions"] >= 1
    finally:
        srv.shutdown(drain=True)
        reg.promote("churn", stable["vid"])   # restore for other tests


# ---------------------------------------------------------------------------
# runner / CLI surface
# ---------------------------------------------------------------------------


def test_runner_stamps_continual_block(stable, tmp_path):
    from transmogrifai_tpu.runner import (OpParams, OpWorkflowRunner,
                                          RunType)
    runner = OpWorkflowRunner(build(gen(51, 80)))
    params = OpParams(metrics_location=str(tmp_path / "m.json"))
    res = runner.run(RunType.TRAIN, params)
    assert "continual" in res.metrics
    assert set(res.metrics["continual"]) \
        == set(continual.continual_stats())
    doc = json.loads((tmp_path / "m.json").read_text())
    assert "continual" in doc


def test_cli_gen_emits_retrain_knobs_and_check_validates(tmp_path,
                                                        capsys):
    from transmogrifai_tpu import cli
    csv = tmp_path / "d.csv"
    csv.write_text("label,x1\n1,0.5\n0,1.5\n" * 40)
    out = tmp_path / "proj"
    cli.generate_project(str(csv), "label", str(out))
    params = json.loads((out / "params.json").read_text())
    for key in ("retrainOnDrift", "retrainCmd", "retrainArmWindows",
                "retrainCooldownS", "retrainMaxFailures",
                "retrainTimeoutS"):
        assert key in params["customParams"]
    # a generated params file is clean
    assert cli.run_check(str(out / "params.json")) == 0
    capsys.readouterr()
    # malformed knobs are TMG001
    bad = dict(params)
    bad["customParams"] = dict(params["customParams"],
                               retrainCooldownS="soon",
                               retrainCmd="not-a-list",
                               retrainOnDrift="yes")
    bad_path = tmp_path / "bad.json"
    bad_path.write_text(json.dumps(bad))
    assert cli.run_check(str(bad_path)) == 1
    out_text = capsys.readouterr().out
    assert out_text.count("TMG001") == 3
    assert "retrainCooldownS" in out_text
    assert "retrainCmd" in out_text
    assert "retrainOnDrift" in out_text


def test_serve_retrain_wiring_validation(stable):
    """build_retrain_controllers: misuse fails loudly, a correct config
    attaches one recovered controller per promoted tenant."""
    from transmogrifai_tpu.cli import build_retrain_controllers
    from transmogrifai_tpu.runner import OpParams
    srv = server_mod.ModelServer(bucket_cap=BUCKET_CAP,
                                 registry=stable["registry"],
                                 drift_window=128)
    try:
        srv.register_from_registry("churn")
        off = OpParams()
        assert build_retrain_controllers(off, srv) == []
        p = OpParams(custom_params={"retrainOnDrift": True})
        with pytest.raises(ValueError, match="retrainCmd"):
            build_retrain_controllers(p, srv)
        p = OpParams(custom_params={
            "retrainOnDrift": True,
            "retrainCmd": [sys.executable, "-c", "pass"],
            "retrainArmWindows": 3, "retrainCooldownS": 1.0,
            "retrainMaxFailures": 4, "retrainTimeoutS": 60.0})
        ctrls = build_retrain_controllers(p, srv)
        assert len(ctrls) == 1
        assert ctrls[0].arm_windows == 3
        assert ctrls[0].max_failures == 4
    finally:
        srv.shutdown(drain=True)
    # driftless server: loud error, not a silent no-op loop
    srv2 = server_mod.ModelServer(bucket_cap=BUCKET_CAP,
                                  registry=stable["registry"])
    try:
        srv2.register_from_registry("churn")
        p = OpParams(custom_params={
            "retrainOnDrift": True,
            "retrainCmd": [sys.executable, "-c", "pass"]})
        with pytest.raises(ValueError, match="driftWindow"):
            build_retrain_controllers(p, srv2)
    finally:
        srv2.shutdown(drain=True)
