"""The bench's evidence machinery (VERDICT r4 #1) — unit-tested without
hardware: incremental emission, budget accounting, warm-rep statistics,
signal dumps, and the CPU-denominator derivation helper. Round 4 lost
its entire perf story to an unparseable rc=124; these tests pin the
properties that make that impossible now."""
import json
import os
import signal
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def test_bench_emits_cumulative_parseable_lines(capsys):
    from bench import Bench
    b = Bench()
    b.doc["configs"]["a"] = {"x": 1}
    b.emit()
    b.doc["configs"]["b"] = {"y": 2}
    b.emit(final=True)
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    assert len(lines) == 2
    first, last = json.loads(lines[0]), json.loads(lines[1])
    assert first["partial"] is True and "b" not in first["configs"]
    assert "partial" not in last and last["configs"]["b"] == {"y": 2}
    assert last["elapsed_s"] >= first["elapsed_s"]


def test_bench_budget_accounting(monkeypatch):
    monkeypatch.setenv("BENCH_BUDGET_S", "100")
    from bench import Bench
    b = Bench()
    assert 95 < b.remaining() <= 100


def test_bench_run_config_median_stats():
    from bench import Bench
    b = Bench()
    outs = iter([{"train_time_s": 9.0},     # cold
                 {"train_time_s": 3.0}, {"train_time_s": 1.0},
                 {"train_time_s": 2.0}])
    cold, warm, st = b.run_config("t", lambda: next(outs), reps=3)
    assert st["train_s_median"] == 2.0      # median, not last rep
    assert st["train_s_reps"] == [3.0, 1.0, 2.0]
    assert cold["train_time_s"] == 9.0 and warm["train_time_s"] == 2.0


def test_bench_sigterm_dumps_state():
    """A killed bench still leaves a parseable cumulative line."""
    code = (
        "import sys, os, signal;"
        "sys.path.insert(0, %r);"
        "from bench import Bench;"
        "b = Bench();"
        "b.doc['configs']['partial_cfg'] = {'v': 7};"
        "os.kill(os.getpid(), signal.SIGTERM)"
    ) % os.path.join(os.path.dirname(__file__), os.pardir)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=60)
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert lines, proc.stderr[-500:]
    doc = json.loads(lines[-1])
    assert doc["configs"]["partial_cfg"] == {"v": 7}
    assert doc["killed_by_signal"] == int(signal.SIGTERM)
    assert proc.returncode == 1


def test_apply_cpu_denominator_paths():
    from bench import _apply_cpu_denominator
    configs = {"titanic": {"cv_warm_s": 5.0},
               "synthetic_trees": {"cv_warm_s": 40.0}}
    # measured titanic + measured synth
    _apply_cpu_denominator(
        {"titanic_warm_s": 250.0, "synth_rows": 5000,
         "synth_s_incl_compile": 80.0}, configs, synth_rows=2_000_000)
    assert configs["titanic"]["speedup_vs_cpu_host"] == 50.0
    assert configs["synthetic_trees"]["speedup_vs_cpu_host_est"] == \
        pytest.approx(80.0 * 400 / 40.0)
    # timeout path: bounds keyed off each stage's OWN alarm
    configs2 = {"titanic": {"cv_warm_s": 5.0},
                "synthetic_trees": {"cv_warm_s": 40.0}}
    _apply_cpu_denominator(
        {"titanic_timeout_s": 160, "synth_rows": 5000,
         "synth_timeout_s": 90}, configs2, synth_rows=2_000_000)
    assert configs2["titanic"]["speedup_vs_cpu_host_at_least"] == 32.0
    assert configs2["synthetic_trees"]["speedup_vs_cpu_host_at_least"] \
        == pytest.approx(90.0 * 400 / 40.0)
    assert "speedup_vs_cpu_host" not in configs2["titanic"]
