"""LinearSVC + MLP stages and families (OpLinearSVC.scala,
OpMultilayerPerceptronClassifier.scala parity)."""
import jax.numpy as jnp
import numpy as np
import pytest

from transmogrifai_tpu.columns import (ColumnStore, VectorColumn,
                                       column_from_values)
from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.models.svm import (LinearSVCFamily, LinearSVCModel,
                                          MLPFamily, MLPModel, OpLinearSVC,
                                          OpMultilayerPerceptronClassifier)
from transmogrifai_tpu.types import feature_types as ft


@pytest.fixture(scope="module")
def linear_xy():
    rng = np.random.default_rng(5)
    n, d = 300, 4
    X = rng.normal(size=(n, d))
    y = (X[:, 0] - 0.5 * X[:, 1] + 0.3 > 0).astype(float)
    return X, y


@pytest.fixture(scope="module")
def xor_xy():
    rng = np.random.default_rng(6)
    n = 400
    X = rng.normal(size=(n, 2))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(float)
    return X, y


def _store(X, y):
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "features": VectorColumn(ft.OPVector, X)})
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    return store, label, feats


def test_linear_svc_stage(linear_xy):
    X, y = linear_xy
    store, label, feats = _store(X, y)
    model = OpLinearSVC(reg_param=0.01).set_input(label, feats).fit(store)
    pred, raw, prob = model.predict_arrays(X)
    assert float((pred == y).mean()) > 0.93
    np.testing.assert_allclose(raw[:, 0], -raw[:, 1], atol=1e-9)

    state = model.get_model_state()
    m2 = LinearSVCModel()
    for k, v in state.items():
        setattr(m2, k, v)
    pred2, _, _ = m2.predict_arrays(X)
    np.testing.assert_array_equal(pred, pred2)


def test_linear_svc_family_grid(linear_xy):
    X, y = linear_xy
    fam = LinearSVCFamily(grid=[{"regParam": 0.001}, {"regParam": 0.1}])
    params = fam.fit_batch(jnp.asarray(X), jnp.asarray(y),
                           jnp.ones(len(y)), fam.stack_grid())
    pred, _, prob = fam.predict_batch(params, jnp.asarray(X))
    assert np.asarray(pred).shape == (2, len(y))
    for g in range(2):
        assert float((np.asarray(pred)[g] == y).mean()) > 0.9


def test_mlp_learns_xor(xor_xy):
    X, y = xor_xy
    store, label, feats = _store(X, y)
    est = OpMultilayerPerceptronClassifier(
        hidden_layers=[16], step_size=0.05, max_iter=300).set_input(
        label, feats)
    model = est.fit(store)
    pred, _, prob = model.predict_arrays(X)
    assert float((pred == y).mean()) > 0.9     # XOR needs the hidden layer
    np.testing.assert_allclose(prob.sum(-1), 1.0, atol=1e-6)

    state = model.get_model_state()
    m2 = MLPModel()
    m2.apply_model_state(state)
    pred2, _, _ = m2.predict_arrays(X)
    np.testing.assert_array_equal(pred, pred2)


def test_mlp_family(xor_xy):
    X, y = xor_xy
    fam = MLPFamily(grid=[{"stepSize": 0.05, "layers": (16,)},
                          {"stepSize": 0.01, "layers": (16,)}],
                    max_iter=200)
    params = fam.fit_batch(jnp.asarray(X), jnp.asarray(y),
                           jnp.ones(len(y)), fam.stack_grid())
    pred, _, _ = fam.predict_batch(params, jnp.asarray(X))
    assert np.asarray(pred).shape == (2, len(y))
    model = fam.realize(
        __import__("jax").tree_util.tree_map(
            lambda a: np.asarray(a)[0], params),
        fam.grid[0])
    p1, _, _ = model.predict_arrays(X)
    np.testing.assert_array_equal(p1, np.asarray(pred)[0])


def test_selected_model_tree_roundtrip(linear_xy):
    """Regression: SelectedModel state round-trip must restore tree arrays
    through inner.apply_model_state (not raw setattr)."""
    from transmogrifai_tpu.models.selector import SelectedModel
    from transmogrifai_tpu.models.trees import (OpRandomForestClassifier,
                                                RandomForestFamily)

    X, y = linear_xy
    store, label, feats = _store(X, y)
    est = OpRandomForestClassifier(num_trees=3, max_depth=3,
                                   min_instances_per_node=5).set_input(
        label, feats)
    inner = est.fit(store)
    sel = SelectedModel(inner=inner, task="binary")
    sel.input_features = (label, feats)
    state = sel.get_model_state()

    sel2 = SelectedModel(task="binary")
    sel2.apply_model_state(state)
    p1, _, _ = sel.predict_arrays(X)
    p2, _, _ = sel2.predict_arrays(X)
    np.testing.assert_array_equal(p1, p2)
