"""parallel.multihost — env-driven initialization logic (single-process
semantics; real multi-process joins are exercised on pods, not in CI)."""
import jax

from transmogrifai_tpu.parallel import multihost


def test_single_host_is_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    assert multihost.initialize() is False
    assert multihost.is_distributed() is False


def test_process_summary_shape():
    s = multihost.process_summary()
    assert s["process_count"] == 1
    assert s["local_devices"] == s["global_devices"] == len(jax.devices())
    assert s["process_id"] == 0
