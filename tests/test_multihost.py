"""parallel.multihost — env-driven initialization logic plus a REAL
two-process ``jax.distributed`` join (VERDICT r2 #5): workers initialize
against a local coordinator, run a cross-process sharded reduction, and
only the coordinator touches the shared filesystem."""
import json
import os
import socket
import subprocess
import sys

import jax

from transmogrifai_tpu.parallel import multihost


def test_single_host_is_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    assert multihost.initialize() is False
    assert multihost.is_distributed() is False


def test_process_summary_shape():
    s = multihost.process_summary()
    assert s["process_count"] == 1
    assert s["local_devices"] == s["global_devices"] == len(jax.devices())
    assert s["process_id"] == 0


def test_two_process_distributed_fit_and_coordinator_writes(tmp_path):
    """Spawn 2 CPU processes that multihost.initialize() against a local
    coordinator, run a GSPMD-sharded gram computation over the global
    device set, and write metrics through the coordinator gate — exactly
    one writer, and it is process 0."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    addr = f"localhost:{port}"
    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)    # 1 local device per process
    procs = [subprocess.Popen(
        [sys.executable, worker, addr, str(rank), str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for rank in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        assert f"worker {rank} ok" in out

    # both processes computed the identical sharded result
    d0 = json.load(open(tmp_path / "done-0"))
    d1 = json.load(open(tmp_path / "done-1"))
    assert d0 == d1

    # the coordinator gate admitted exactly one writer: process 0
    metrics = json.load(open(tmp_path / "metrics.json"))
    assert metrics["writer_rank"] == 0
    assert metrics["process_count"] == 2
