"""Pallas histogram kernel ↔ XLA matmul path parity.

Runs the kernel in interpret mode on the CPU test mesh (the TPU bench path
compiles the same kernel via Mosaic). Reference: the histogram-build that
replaces xgboost4j's C++ core (SURVEY §2.9)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transmogrifai_tpu.models import _pallas_hist
from transmogrifai_tpu.models._treefit import _level_cumhist


def _ref_hist(stats, node, Xb, A, B):
    """O(n·A·B·F) dense reference, independent of both production paths."""
    n, F = Xb.shape
    C = stats.shape[1]
    out = np.zeros((A, C, B, F))
    for i in range(n):
        s = int(node[i])
        if s >= A:
            continue
        for f in range(F):
            out[s, :, Xb[i, f]:, f] += np.asarray(stats[i])[:, None]
    return out


@pytest.mark.parametrize("n,F,A,B,C", [(37, 5, 4, 8, 3), (64, 3, 2, 2, 4)])
def test_cumhist_matches_reference_and_xla(rng, n, F, A, B, C):
    stats = jnp.asarray(rng.normal(size=(n, C)))
    node = jnp.asarray(rng.integers(0, A + 1, size=(n,)), jnp.int32)
    Xb = jnp.asarray(rng.integers(0, B, size=(n, F)), jnp.int32)

    ref = _ref_hist(stats, node, Xb, A, B)
    xla = _level_cumhist(stats, node, Xb, A, B)
    pal = _pallas_hist.cumhist(stats, node, Xb.T, A, B, interpret=True)

    np.testing.assert_allclose(np.asarray(xla), ref, rtol=1e-9, atol=1e-9)
    np.testing.assert_allclose(np.asarray(pal), ref, rtol=1e-9, atol=1e-9)


def test_cumhist_feature_tiling_and_row_padding(rng):
    # F > Fc forces the feature grid axis; n not a multiple of the row
    # block exercises the idle-row (node == A) padding.
    n, F, A, B, C = 101, 9, 4, 4, 3
    stats = jnp.asarray(rng.normal(size=(n, C)))
    node = jnp.asarray(rng.integers(0, A, size=(n,)), jnp.int32)
    Xb = jnp.asarray(rng.integers(0, B, size=(n, F)), jnp.int32)
    pal = _pallas_hist.cumhist(stats, node, Xb.T, A, B,
                               block_lanes=32, max_sub=16, interpret=True)
    np.testing.assert_allclose(
        np.asarray(pal), _ref_hist(stats, node, Xb, A, B),
        rtol=1e-9, atol=1e-9)


def test_cumhist_under_vmap(rng):
    # The tree engine calls the kernel under fold/grid/tree-chunk vmaps.
    G, n, F, A, B, C = 3, 40, 4, 2, 8, 3
    stats = jnp.asarray(rng.normal(size=(G, n, C)))
    node = jnp.asarray(rng.integers(0, A, size=(G, n)), jnp.int32)
    Xb = jnp.asarray(rng.integers(0, B, size=(G, n, F)), jnp.int32)

    f = jax.vmap(lambda s, nd, xb: _pallas_hist.cumhist(
        s, nd, xb.T, A, B, interpret=True))
    out = f(stats, node, Xb)
    for g in range(G):
        np.testing.assert_allclose(
            np.asarray(out[g]), _ref_hist(stats[g], node[g], Xb[g], A, B),
            rtol=1e-9, atol=1e-9)


def test_forced_pallas_tree_fit_matches_xla(rng, monkeypatch):
    # Whole-tree parity: grow a forest with the kernel forced on
    # (interpret) and verify identical predictions vs the XLA path.
    from transmogrifai_tpu.models import _treefit

    n, F = 120, 6
    X = jnp.asarray(rng.normal(size=(n, F)))
    y = jnp.asarray((rng.normal(size=(n,)) + X[:, 0] > 0).astype(np.float64))
    w = jnp.ones((n,))
    kw = dict(task="classification", n_classes=2, n_trees=3, max_depth=4,
              n_bins=8, min_instances=jnp.asarray(1.0),
              min_info_gain=jnp.asarray(0.0),
              num_trees_used=jnp.asarray(3), subsample_rate=jnp.asarray(1.0))

    monkeypatch.setenv("TMOG_PALLAS", "0")
    base = _treefit.fit_forest(X, y, w, **kw)
    monkeypatch.setenv("TMOG_PALLAS", "1")
    forced = _treefit.fit_forest(X, y, w, **kw)

    np.testing.assert_array_equal(np.asarray(base["feat"]),
                                  np.asarray(forced["feat"]))
    np.testing.assert_allclose(np.asarray(base["thr"]),
                               np.asarray(forced["thr"]))
    np.testing.assert_allclose(np.asarray(base["leaf"]),
                               np.asarray(forced["leaf"]), rtol=1e-8)


def test_fit_level_pallas_fallback(monkeypatch):
    """ADVICE r2: the tiny-shape probe can pass while production shapes
    fail Mosaic. A kernel-shaped failure mid-fit must flip the gate off and
    retry (re-keying families onto the XLA path); unrelated errors and the
    user-forced TMOG_PALLAS=1 must propagate untouched."""
    import pytest

    import transmogrifai_tpu.models._pallas_hist as ph

    monkeypatch.delenv("TMOG_PALLAS", raising=False)
    monkeypatch.setattr(ph, "_PROBE", True)
    calls = []

    def boom():
        calls.append(ph._PROBE)
        if ph._PROBE:
            raise RuntimeError("Mosaic lowering failed: VMEM limit exceeded")
        return "ok"

    with pytest.warns(UserWarning, match="XLA matmul path"):
        assert ph.with_pallas_fallback(boom) == "ok"
    assert calls == [True, False] and ph._PROBE is False

    # unrelated errors propagate without flipping the gate
    monkeypatch.setattr(ph, "_PROBE", True)
    def unrelated():
        raise ValueError("user data has NaNs")
    with pytest.raises(ValueError):
        ph.with_pallas_fallback(unrelated)
    assert ph._PROBE is True

    # TMOG_PALLAS=1 means the user insists: fail loudly, don't fall back
    monkeypatch.setenv("TMOG_PALLAS", "1")
    def forced():
        raise RuntimeError("Mosaic lowering failed")
    with pytest.raises(RuntimeError):
        ph.with_pallas_fallback(forced)


def _xla_select(cum, crit, min_inst, mask2d=None):
    """The XLA selection chain the split-scan kernel replaces (the exact
    expressions from grow_tree's level body)."""
    from transmogrifai_tpu.models._treefit import _NEG
    A = cum.shape[0]
    sb = crit.score(cum)
    lcb = cum[:, -1, :-1, :]
    tcb = cum[:, -1, -1:, :]
    okb = (lcb >= min_inst) & (tcb - lcb >= min_inst)
    extra = crit.extra_ok(cum)
    if extra is not None:
        okb = okb & extra
    if mask2d is not None:
        okb = okb & (mask2d[:, None, :] > 0.5)
    flat = jnp.where(okb, sb, _NEG).reshape(A, -1)
    best = jnp.argmax(flat, axis=1)
    valid = jnp.take_along_axis(okb.reshape(A, -1), best[:, None],
                                axis=1)[:, 0]
    return best, valid


def _cum_hist(rng, A, C, B, F, dtype):
    """Random VALID cumulative histogram (monotone over bins, exact
    small-integer values so every float op is exact in both paths)."""
    raw = rng.integers(0, 4, size=(A, C, B, F)).astype(dtype)
    return jnp.asarray(np.cumsum(raw, axis=2))


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("kind", ["variance", "gini", "xgb"])
def test_split_scan_matches_xla_selection(rng, kind, dtype):
    """Interpret-mode bit-parity of the fused split-scan kernel against
    the XLA score→mask→argmax chain it replaces, across criteria and
    dtypes — including argmax's first-occurrence tie rule (small-integer
    histograms make score ties common) and the winner-validity gather."""
    from transmogrifai_tpu.models import _treefit as TF

    crit = {"variance": TF.VarianceCriterion(),
            "gini": TF.GiniCriterion(),
            "xgb": TF.XGBCriterion(1.0, 2.0)}[kind]
    C = 3 if kind == "xgb" else 4
    A, B, F = 6, 8, 11
    cum = _cum_hist(rng, A, C, B, F, dtype)
    mi = jnp.asarray(3.0, cum.dtype)
    mask2d = jnp.asarray(
        rng.integers(0, 2, size=(A, F)).astype(dtype))
    for mk in (None, mask2d):
        b0, v0 = _xla_select(cum, crit, mi, mk)
        _s, b1, v1 = _pallas_hist.split_scan(
            cum, kind, mi, lam=1.0, min_child_weight=jnp.asarray(2.0),
            mask=mk, interpret=True)
        np.testing.assert_array_equal(np.asarray(b0), np.asarray(b1))
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))


def test_split_scan_feature_tiling_and_all_masked(rng):
    """Feature-block tiling (grid > 1, padded F) must merge block
    winners on the global flat axis; an all-masked level must yield the
    XLA degenerate (index 0, valid False)."""
    from transmogrifai_tpu.models import _treefit as TF

    crit = TF.VarianceCriterion()
    A, C, B, F = 128, 4, 32, 50     # forces Fc < F in f64
    cum = _cum_hist(rng, A, C, B, F, np.float64)
    mi = jnp.asarray(2.0)
    b0, v0 = _xla_select(cum, crit, mi)
    _s, b1, v1 = _pallas_hist.split_scan(cum, "variance", mi,
                                         interpret=True)
    np.testing.assert_array_equal(np.asarray(b0), np.asarray(b1))
    np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))

    _s, b2, v2 = _pallas_hist.split_scan(
        cum[:4], "variance", jnp.asarray(1e9), interpret=True)
    assert np.array_equal(np.asarray(b2), np.zeros(4))
    assert not np.asarray(v2).any()


def test_sparse01_kernel_bit_identical_to_dense(rng):
    """The wide-sparse 2-bin kernel (zero bin = total − nonzero side)
    must match the dense bin-indicator kernel bit-for-bit on exact
    stats — including idle rows (node == A) and feature tiling."""
    n, F, A, C = 203, 9, 4, 3
    stats = jnp.asarray(rng.integers(0, 3, size=(n, C)).astype(np.float64))
    node = jnp.asarray(rng.integers(0, A + 1, size=(n,)), jnp.int32)
    Xb01 = jnp.asarray(rng.integers(0, 2, size=(n, F)), jnp.int32)
    dense = _pallas_hist.cumhist(stats, node, Xb01.T, A, 2,
                                 interpret=True)
    sparse = _pallas_hist.cumhist(stats, node, Xb01.T, A, 2,
                                  interpret=True, sparse01=True)
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(sparse))
    # and against the dense O(n·A·B·F) reference
    np.testing.assert_allclose(np.asarray(sparse),
                               _ref_hist(stats, node, Xb01, A, 2),
                               rtol=0, atol=0)


@pytest.mark.parametrize("depth", [2, 3, 5])
def test_forced_pallas_fit_with_sparse_and_scan_matches_xla(rng,
                                                            monkeypatch,
                                                            depth):
    """Whole-fit parity across depths with BOTH new kernels engaged
    (binary columns → sparse01 blocks; split scan on): trees grown with
    the kernels forced on (interpret) must match the XLA path."""
    from transmogrifai_tpu.models import _treefit

    n, Fc_, Fb_ = 140, 4, 3
    Xc = rng.normal(size=(n, Fc_))
    Xb01 = rng.integers(0, 2, size=(n, Fb_)).astype(np.float64)
    X = jnp.asarray(np.concatenate([Xc, Xb01], axis=1))
    bmask = np.array([False] * Fc_ + [True] * Fb_)
    y = jnp.asarray((rng.normal(size=(n,)) + np.asarray(X)[:, 0] > 0)
                    .astype(np.float64))
    w = jnp.ones((n,))
    kw = dict(task="classification", n_classes=2, n_trees=3,
              max_depth=depth, n_bins=8, min_instances=jnp.asarray(1.0),
              min_info_gain=jnp.asarray(0.0),
              num_trees_used=jnp.asarray(3),
              subsample_rate=jnp.asarray(1.0), binary_mask=bmask)

    monkeypatch.setenv("TMOG_PALLAS", "0")
    base = _treefit.fit_forest(X, y, w, **kw)
    monkeypatch.setenv("TMOG_PALLAS", "1")
    before = _pallas_hist.tree_kernel_stats()
    forced = _treefit.fit_forest(X, y, w, **kw)
    after = _pallas_hist.tree_kernel_stats()
    assert after["sparse01_traces"] > before["sparse01_traces"]
    assert after["split_scan_traces"] > before["split_scan_traces"]
    np.testing.assert_array_equal(np.asarray(base["feat"]),
                                  np.asarray(forced["feat"]))
    np.testing.assert_allclose(np.asarray(base["thr"]),
                               np.asarray(forced["thr"]))
    np.testing.assert_allclose(np.asarray(base["leaf"]),
                               np.asarray(forced["leaf"]), rtol=1e-8)


@pytest.mark.chaos
def test_split_scan_mosaic_failure_falls_back_to_xla(rng, monkeypatch):
    """A Mosaic rejection inside the NEW kernel (probe passed, the
    production shape dies) must flip the gate and re-run the fit on the
    XLA path with IDENTICAL selections — the with_pallas_fallback
    contract extended to the split scan."""
    from transmogrifai_tpu.models import _treefit

    monkeypatch.delenv("TMOG_PALLAS", raising=False)
    # gate "on" without the TPU backend: probe pretends to have passed
    monkeypatch.setattr(_pallas_hist, "_PROBE", True)
    monkeypatch.setattr(_pallas_hist, "pallas_histograms_enabled",
                        lambda: _pallas_hist._PROBE is True)

    n, F = 120, 5
    X = jnp.asarray(rng.normal(size=(n, F)))
    y = jnp.asarray((rng.normal(size=(n,)) + np.asarray(X)[:, 0] > 0)
                    .astype(np.float64))
    w = jnp.ones((n,))
    kw = dict(task="classification", n_classes=2, n_trees=2, max_depth=3,
              n_bins=8, min_instances=jnp.asarray(1.0),
              min_info_gain=jnp.asarray(0.0),
              num_trees_used=jnp.asarray(2),
              subsample_rate=jnp.asarray(1.0))

    real_scan = _pallas_hist.split_scan

    def boom(*a, **k):
        if _pallas_hist._PROBE:
            raise RuntimeError(
                "Mosaic lowering failed: VMEM limit exceeded in "
                "split-scan kernel")
        return real_scan(*a, **k)
    monkeypatch.setattr(_pallas_hist, "split_scan", boom)

    with pytest.warns(UserWarning, match="XLA matmul path"):
        out = _pallas_hist.with_pallas_fallback(
            lambda: _treefit.fit_forest(X, y, w, **kw))
    assert _pallas_hist._PROBE is False       # gate flipped process-wide

    monkeypatch.setenv("TMOG_PALLAS", "0")
    base = _treefit.fit_forest(X, y, w, **kw)
    for k in ("feat", "thr", "leaf"):
        np.testing.assert_allclose(np.asarray(base[k]),
                                   np.asarray(out[k]), rtol=0, atol=0)


def test_predict_kernel_matches_xla_routing(rng):
    """Routed ensemble prediction: the transposed-domain predict kernel
    must match per-tree XLA routing exactly (incl. +inf dead-split
    thresholds and tree weights folded into the leaves)."""
    from transmogrifai_tpu.models import _treefit
    from transmogrifai_tpu.models._pallas_hist import predict_trees

    n, F, T, D, K = 700, 9, 5, 4, 3
    X = jnp.asarray(rng.normal(size=(n, F)), jnp.float32)
    NN, L = (1 << D) - 1, 1 << D
    feat = jnp.asarray(rng.integers(0, F, (T, NN)), jnp.int32)
    thr = jnp.asarray(np.where(rng.random((T, NN)) < 0.3, np.inf,
                               rng.normal(size=(T, NN))), jnp.float32)
    leaf = jnp.asarray(rng.normal(size=(T, L, K)), jnp.float32)
    tw = jnp.asarray(rng.random(T), jnp.float32)
    ref = sum(float(tw[t]) * np.asarray(
        _treefit.predict_tree(feat[t], thr[t], leaf[t], X, D))
        for t in range(T))
    out = predict_trees(X, feat, thr, leaf * tw[:, None, None], D,
                        interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)
