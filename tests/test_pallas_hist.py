"""Pallas histogram kernel ↔ XLA matmul path parity.

Runs the kernel in interpret mode on the CPU test mesh (the TPU bench path
compiles the same kernel via Mosaic). Reference: the histogram-build that
replaces xgboost4j's C++ core (SURVEY §2.9)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transmogrifai_tpu.models import _pallas_hist
from transmogrifai_tpu.models._treefit import _level_cumhist


def _ref_hist(stats, node, Xb, A, B):
    """O(n·A·B·F) dense reference, independent of both production paths."""
    n, F = Xb.shape
    C = stats.shape[1]
    out = np.zeros((A, C, B, F))
    for i in range(n):
        s = int(node[i])
        if s >= A:
            continue
        for f in range(F):
            out[s, :, Xb[i, f]:, f] += np.asarray(stats[i])[:, None]
    return out


@pytest.mark.parametrize("n,F,A,B,C", [(37, 5, 4, 8, 3), (64, 3, 2, 2, 4)])
def test_cumhist_matches_reference_and_xla(rng, n, F, A, B, C):
    stats = jnp.asarray(rng.normal(size=(n, C)))
    node = jnp.asarray(rng.integers(0, A + 1, size=(n,)), jnp.int32)
    Xb = jnp.asarray(rng.integers(0, B, size=(n, F)), jnp.int32)

    ref = _ref_hist(stats, node, Xb, A, B)
    xla = _level_cumhist(stats, node, Xb, A, B)
    pal = _pallas_hist.cumhist(stats, node, Xb.T, A, B, interpret=True)

    np.testing.assert_allclose(np.asarray(xla), ref, rtol=1e-9, atol=1e-9)
    np.testing.assert_allclose(np.asarray(pal), ref, rtol=1e-9, atol=1e-9)


def test_cumhist_feature_tiling_and_row_padding(rng):
    # F > Fc forces the feature grid axis; n not a multiple of the row
    # block exercises the idle-row (node == A) padding.
    n, F, A, B, C = 101, 9, 4, 4, 3
    stats = jnp.asarray(rng.normal(size=(n, C)))
    node = jnp.asarray(rng.integers(0, A, size=(n,)), jnp.int32)
    Xb = jnp.asarray(rng.integers(0, B, size=(n, F)), jnp.int32)
    pal = _pallas_hist.cumhist(stats, node, Xb.T, A, B,
                               block_lanes=32, max_sub=16, interpret=True)
    np.testing.assert_allclose(
        np.asarray(pal), _ref_hist(stats, node, Xb, A, B),
        rtol=1e-9, atol=1e-9)


def test_cumhist_under_vmap(rng):
    # The tree engine calls the kernel under fold/grid/tree-chunk vmaps.
    G, n, F, A, B, C = 3, 40, 4, 2, 8, 3
    stats = jnp.asarray(rng.normal(size=(G, n, C)))
    node = jnp.asarray(rng.integers(0, A, size=(G, n)), jnp.int32)
    Xb = jnp.asarray(rng.integers(0, B, size=(G, n, F)), jnp.int32)

    f = jax.vmap(lambda s, nd, xb: _pallas_hist.cumhist(
        s, nd, xb.T, A, B, interpret=True))
    out = f(stats, node, Xb)
    for g in range(G):
        np.testing.assert_allclose(
            np.asarray(out[g]), _ref_hist(stats[g], node[g], Xb[g], A, B),
            rtol=1e-9, atol=1e-9)


def test_forced_pallas_tree_fit_matches_xla(rng, monkeypatch):
    # Whole-tree parity: grow a forest with the kernel forced on
    # (interpret) and verify identical predictions vs the XLA path.
    from transmogrifai_tpu.models import _treefit

    n, F = 120, 6
    X = jnp.asarray(rng.normal(size=(n, F)))
    y = jnp.asarray((rng.normal(size=(n,)) + X[:, 0] > 0).astype(np.float64))
    w = jnp.ones((n,))
    kw = dict(task="classification", n_classes=2, n_trees=3, max_depth=4,
              n_bins=8, min_instances=jnp.asarray(1.0),
              min_info_gain=jnp.asarray(0.0),
              num_trees_used=jnp.asarray(3), subsample_rate=jnp.asarray(1.0))

    monkeypatch.setenv("TMOG_PALLAS", "0")
    base = _treefit.fit_forest(X, y, w, **kw)
    monkeypatch.setenv("TMOG_PALLAS", "1")
    forced = _treefit.fit_forest(X, y, w, **kw)

    np.testing.assert_array_equal(np.asarray(base["feat"]),
                                  np.asarray(forced["feat"]))
    np.testing.assert_allclose(np.asarray(base["thr"]),
                               np.asarray(forced["thr"]))
    np.testing.assert_allclose(np.asarray(base["leaf"]),
                               np.asarray(forced["leaf"]), rtol=1e-8)


def test_fit_level_pallas_fallback(monkeypatch):
    """ADVICE r2: the tiny-shape probe can pass while production shapes
    fail Mosaic. A kernel-shaped failure mid-fit must flip the gate off and
    retry (re-keying families onto the XLA path); unrelated errors and the
    user-forced TMOG_PALLAS=1 must propagate untouched."""
    import pytest

    import transmogrifai_tpu.models._pallas_hist as ph

    monkeypatch.delenv("TMOG_PALLAS", raising=False)
    monkeypatch.setattr(ph, "_PROBE", True)
    calls = []

    def boom():
        calls.append(ph._PROBE)
        if ph._PROBE:
            raise RuntimeError("Mosaic lowering failed: VMEM limit exceeded")
        return "ok"

    with pytest.warns(UserWarning, match="XLA matmul path"):
        assert ph.with_pallas_fallback(boom) == "ok"
    assert calls == [True, False] and ph._PROBE is False

    # unrelated errors propagate without flipping the gate
    monkeypatch.setattr(ph, "_PROBE", True)
    def unrelated():
        raise ValueError("user data has NaNs")
    with pytest.raises(ValueError):
        ph.with_pallas_fallback(unrelated)
    assert ph._PROBE is True

    # TMOG_PALLAS=1 means the user insists: fail loudly, don't fall back
    monkeypatch.setenv("TMOG_PALLAS", "1")
    def forced():
        raise RuntimeError("Mosaic lowering failed")
    with pytest.raises(RuntimeError):
        ph.with_pallas_fallback(forced)


def test_predict_kernel_matches_xla_routing(rng):
    """Routed ensemble prediction: the transposed-domain predict kernel
    must match per-tree XLA routing exactly (incl. +inf dead-split
    thresholds and tree weights folded into the leaves)."""
    from transmogrifai_tpu.models import _treefit
    from transmogrifai_tpu.models._pallas_hist import predict_trees

    n, F, T, D, K = 700, 9, 5, 4, 3
    X = jnp.asarray(rng.normal(size=(n, F)), jnp.float32)
    NN, L = (1 << D) - 1, 1 << D
    feat = jnp.asarray(rng.integers(0, F, (T, NN)), jnp.int32)
    thr = jnp.asarray(np.where(rng.random((T, NN)) < 0.3, np.inf,
                               rng.normal(size=(T, NN))), jnp.float32)
    leaf = jnp.asarray(rng.normal(size=(T, L, K)), jnp.float32)
    tw = jnp.asarray(rng.random(T), jnp.float32)
    ref = sum(float(tw[t]) * np.asarray(
        _treefit.predict_tree(feat[t], thr[t], leaf[t], X, D))
        for t in range(T))
    out = predict_trees(X, feat, thr, leaf * tw[:, None, None], D,
                        interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)
