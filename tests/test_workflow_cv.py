"""Workflow-level CV (cutDAG): leak-free in-fold feature engineering
(FitStagesUtil.cutDAG :305-358, OpWorkflow.scala:388-443)."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, Workflow, column_from_values
from transmogrifai_tpu.graph import cut_dag
from transmogrifai_tpu.models.linear import LogisticRegressionFamily
from transmogrifai_tpu.models.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.ops.dt_bucketizer import DecisionTreeNumericBucketizer
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.workflow import WorkflowError


def _leaky_flow(rng, n=150, workflow_cv=False):
    """Label-aware bucketizer over pure noise: fitting it on ALL rows leaks
    validation labels into the bucket edges (deep tree + fine candidate
    grid makes the buckets nearly label-pure)."""
    y = rng.integers(0, 2, size=n).astype(float)
    noise = rng.normal(size=n)
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "noise": column_from_values(ft.Real, list(noise)),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("noise").from_column().as_predictor()
    bucketized = label.transform_with(
        DecisionTreeNumericBucketizer(max_depth=12, max_bins=256,
                                      min_info_gain=1e-9), fx)
    vec = transmogrify([bucketized])
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=3, validation_metric="AuROC",
        families=[LogisticRegressionFamily(grid=[
            {"regParam": 0.001, "elasticNetParam": 0.0}])],
        splitter=None, seed=7)
    pred = label.transform_with(selector, vec)
    wf = Workflow().set_result_features(pred).set_input_store(store)
    if workflow_cv:
        wf = wf.with_workflow_cv()
    model = wf.train()
    selected = model.fitted_stages[selector.uid]
    return selected.selector_summary.validator_summary.best.mean_metric


def test_workflow_cv_is_more_honest_than_selector_cv(rng):
    leaky = _leaky_flow(np.random.default_rng(1), workflow_cv=False)
    honest = _leaky_flow(np.random.default_rng(1), workflow_cv=True)
    # leakage inflates the fold AuROC on noise (~0.82 measured); in-fold
    # feature engineering must not
    assert leaky > 0.7, f"expected inflated leaky metric, got {leaky}"
    assert honest < leaky - 0.1, (leaky, honest)
    assert honest < 0.65, f"workflow CV still leaking: {honest}"


def test_cut_dag_splits_around_selector(rng):
    y = rng.integers(0, 2, 50).astype(float)
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "a": column_from_values(ft.Real, list(rng.normal(size=50))),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fa = FeatureBuilder.Real("a").from_column().as_predictor()
    bucketized = label.transform_with(DecisionTreeNumericBucketizer(), fa)
    vec = transmogrify([bucketized])
    checked = label.sanity_check(vec)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None)
    pred = label.transform_with(selector, checked)

    ms, before, during, after = cut_dag([pred])
    assert ms is selector or ms.uid == selector.uid
    during_names = {type(s).__name__ for layer in during for s in layer}
    assert "DecisionTreeNumericBucketizer" in during_names
    assert "SanityChecker" in during_names
    assert after == []
    before_names = {type(s).__name__ for layer in before for s in layer}
    assert "DecisionTreeNumericBucketizer" not in before_names


def test_at_most_one_selector_enforced(rng):
    y = rng.integers(0, 2, 40).astype(float)
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fa = FeatureBuilder.Real("a").from_column().as_predictor()
    vec = transmogrify([fa])
    mk = lambda: BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None)
    p1 = label.transform_with(mk(), vec)
    p2 = label.transform_with(mk(), vec)
    with pytest.raises(WorkflowError, match="at most 1 ModelSelector"):
        Workflow().set_result_features(p1, p2)
