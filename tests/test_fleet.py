"""Horizontal serving fleet tests (fleet.py + the server/cli satellites).

The fleet robustness contract: the router consistent-hash routes across
ready workers and fails over to a sibling when one is down (zero failed
client requests under a real SIGKILL), the supervisor respawns crashed
workers with backoff and zero registry-pointer corruption (the flock
discipline releases a dead holder's kernel lock — fresh-interpreter
SIGKILL verified), a promote issued during an outage is observed by the
respawned worker on rejoin, rolling drain-then-restart loses zero
requests, and every survivor score is bit-identical to a single-process
run."""
import http.client
import json
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from transmogrifai_tpu import (FeatureBuilder, Workflow, aot, resilience,
                               serving)
from transmogrifai_tpu import fleet as fleet_mod
from transmogrifai_tpu import server as server_mod
from transmogrifai_tpu.fleet import (FleetSupervisor, fleet_stats,
                                     serve_fleet_http)
from transmogrifai_tpu.lifecycle import ModelRegistry
from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                      LogisticRegressionFamily)
from transmogrifai_tpu.ops.transmogrifier import transmogrify

BUCKET_CAP = 64

#: fast respawn schedule for tests (the production default backs off to
#: seconds; a test fleet should come back as fast as the boot allows)
_FAST_BACKOFF = resilience.RetryPolicy(max_attempts=8, base_delay_s=0.05,
                                       max_delay_s=0.5, jitter=0.1,
                                       seed=3)


def _train(seed, n=160):
    rng = np.random.default_rng(seed)
    y = np.asarray([i % 2 for i in range(n)], float)
    rng.shuffle(y)
    records = [{"label": float(y[i]),
                "x1": float(rng.normal() + y[i]),
                "x2": float(rng.normal())} for i in range(n)]
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
    f2 = FeatureBuilder.Real("x2").from_column().as_predictor()
    vec = transmogrify([f1, f2])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()], splitter=None,
        seed=seed)
    pred = label.transform_with(sel, vec)
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())
    return model, records, pred


@pytest.fixture(autouse=True)
def _lock_order_witness():
    """Every test in this module doubles as a race harness: the
    TMG8xx runtime witness (utils/locks.py) records the cross-thread
    lock acquisition order the real code paths execute and the
    teardown asserts no inversion was observed. Record mode, not
    raise mode — a raise inside a never-raises boundary (dispatch
    workers, the fleet monitor) would be swallowed where an assert
    here cannot be."""
    from transmogrifai_tpu.utils import locks
    locks.arm(raise_on_violation=False)
    yield
    violations = locks.violations()
    locks.disarm()
    locks.reset()
    assert violations == [], "\n".join(violations)


@pytest.fixture(scope="module")
def fleet_env(tmp_path_factory):
    """Two trained versions of one registry model ('churn', v1
    promoted), AOT-exported, plus the shared params file every fleet
    worker boots from."""
    reg_dir = str(tmp_path_factory.mktemp("registry"))
    reg = ModelRegistry(reg_dir)
    env = {"registry": reg, "registry_dir": reg_dir}
    for tag, seed in (("v1", 11), ("v2", 12)):
        model, records, pred = _train(seed)
        mdir = str(tmp_path_factory.mktemp(f"model_{tag}"))
        edir = str(tmp_path_factory.mktemp(f"export_{tag}"))
        model.save(mdir, overwrite=True)
        serving.export_scoring_fn(model, edir, records[:8],
                                  bucket_cap=BUCKET_CAP)
        vid = reg.register("churn", mdir, bank_dir=edir,
                           promote=(tag == "v1"))
        env[tag] = {"model": model, "records": records, "pred": pred,
                    "model_dir": mdir, "export_dir": edir, "vid": vid}
    # a SECOND tenant (same artifacts as churn@v2 under its own name):
    # the fleet serves a mixed-model roster, like the PR 8 server tests
    reg.register("fraud", env["v2"]["model_dir"],
                 bank_dir=env["v2"]["export_dir"], promote=True)
    params = tmp_path_factory.mktemp("params") / "params.json"
    params.write_text(json.dumps({"customParams": {
        "registryDir": reg_dir, "serveBucketCap": BUCKET_CAP,
        "serveBatchDeadlineMs": 1.0}}))
    env["params_path"] = str(params)
    yield env
    for tag in ("v1", "v2"):
        env[tag]["model"]._engine_breaker().reset()


@pytest.fixture(scope="module")
def fleet4(fleet_env):
    """One live 4-worker fleet + router, shared by the module's tests
    (spawning real interpreters is the expensive part)."""
    sup = FleetSupervisor(fleet_env["params_path"], workers=4,
                          respawn_max=6, probe_interval_s=0.1,
                          backoff=_FAST_BACKOFF)
    sup.start()
    sup.wait_ready(timeout_s=240)
    httpd = serve_fleet_http(sup, port=0, retry_budget=3,
                             forward_timeout_s=60.0)
    port = httpd.server_address[1]
    yield sup, httpd, port
    httpd.shutdown()
    sup.stop(drain=True)


def _post(port, path, doc, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, json.dumps(doc),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        return r.status, json.loads(r.read() or b"{}")
    finally:
        conn.close()


def _get(port, path, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, json.loads(r.read() or b"{}")
    finally:
        conn.close()


def _oracle(env_tag, recs, bucket):
    """The single-process answer a fleet response must match
    BIT-IDENTICALLY, pushed through the same JSON encode/decode the
    HTTP front end applies (float repr round-trips exactly, so equal
    parsed docs ⇔ equal bits)."""
    eng = env_tag.setdefault("_oracle_engine", None)
    if eng is None:
        eng = env_tag["model"].scoring_engine(
            gate_bandwidth=False, mesh=False, bucket_cap=BUCKET_CAP)
        aot.load_program_bank(eng, env_tag["export_dir"])
        env_tag["_oracle_engine"] = eng
    store = eng.score_store(recs, bucket_min=bucket, use_cache=False)
    return json.loads(json.dumps(server_mod._store_rows(store),
                                 default=str))


# ---------------------------------------------------------------------------
# fault sites + cross-process canary agreement (no fleet needed)
# ---------------------------------------------------------------------------


def test_fleet_fault_sites_registered():
    assert "fleet.forward" in resilience.FAULT_SITES
    assert "fleet.spawn" in resilience.FAULT_SITES


def test_canary_routing_agrees_across_processes(tmp_path):
    """Router-free canary consistency: the deterministic blake2b
    hash-fraction routing (server._canaried) makes EVERY worker route a
    given request identically — asserted against a fresh interpreter,
    so the claim holds across real processes, not just call sites."""
    rng = np.random.default_rng(7)
    records = [{"x1": float(rng.normal()), "x2": float(rng.normal())}
               for _ in range(64)]
    local = [server_mod.ModelServer._canaried(
        server_mod._Request([r]), 0.3) for r in records]
    assert any(local) and not all(local)    # the fraction actually splits
    rec_file = tmp_path / "records.json"
    rec_file.write_text(json.dumps(records))
    probe = textwrap.dedent(f"""
        import json, sys
        from transmogrifai_tpu import server as server_mod
        records = json.load(open({str(rec_file)!r}))
        flags = [server_mod.ModelServer._canaried(
            server_mod._Request([r]), 0.3) for r in records]
        print("FLAGS " + json.dumps(flags))
    """)
    proc = subprocess.run([sys.executable, "-c", probe],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-800:]
    remote = next(json.loads(ln[len("FLAGS "):])
                  for ln in proc.stdout.splitlines()
                  if ln.startswith("FLAGS "))
    assert remote == local


# ---------------------------------------------------------------------------
# routing, aggregation, probes
# ---------------------------------------------------------------------------


def test_fleet_routes_bit_identical_and_aggregates(fleet_env, fleet4):
    sup, _httpd, port = fleet4
    recs_all = fleet_env["v1"]["records"]
    before = fleet_stats()
    for i in range(10):
        recs = recs_all[i * 4:(i + 1) * 4]
        status, doc = _post(port, "/v1/models/churn:score",
                            {"records": recs})
        assert status == 200, doc
        assert doc["rows"] == 4
        # bit-identical to a single-process run through the same
        # program (the dispatch bucket pinned, JSON roundtrip on both)
        assert doc["outputs"] == _oracle(fleet_env["v1"], recs,
                                         doc["bucket"])
    d = fleet_stats()
    assert d["routed_requests"] - before["routed_requests"] == 10
    assert d["routed_failed"] == before["routed_failed"]
    # router probes + aggregation
    status, doc = _get(port, "/healthz")
    assert status == 200 and len(doc["workers"]) == 4
    status, doc = _get(port, "/readyz")
    assert status == 200 and doc["readyWorkers"] == 4
    status, doc = _get(port, "/stats")
    assert status == 200
    assert doc["aggregate"]["requests"] >= 10
    assert doc["fleet"]["ready"] == 4
    served = [w for w in doc["workers"].values()
              if isinstance(w, dict) and w.get("server")]
    assert served, doc["workers"]
    # the consistent hash spread distinct payloads across workers
    assert sum(1 for w in served
               if (w["server"] or {}).get("requests", 0) > 0) >= 2


def test_worker_readyz_and_healthz_split(fleet4):
    """Probe semantics on a real worker: /healthz 200 (live) and
    /readyz 200 with the loadable-tenants + queue-headroom document."""
    sup, _httpd, _port = fleet4
    h = sup.ready_workers()[0]
    status, doc = _get(h.port, "/healthz")
    assert status == 200 and doc["status"] == "ok"
    status, doc = _get(h.port, "/readyz")
    assert status == 200 and doc["ready"] is True
    assert doc["models"] == 2 and doc["queueHeadroom"] == 1.0


def test_router_sheds_503_when_no_ready_worker(fleet_env):
    """An empty fleet sheds loudly: 503 with a reason, tallied — never
    a hang or a silent drop. (Supervisor never started: zero ready.)"""
    sup = FleetSupervisor(fleet_env["params_path"], workers=2)
    httpd = serve_fleet_http(sup, port=0)
    port = httpd.server_address[1]
    try:
        before = fleet_stats()["shed_503"]
        status, doc = _post(port, "/v1/models/churn:score",
                            {"records": [{"x1": 1.0, "x2": 2.0}]})
        assert status == 503 and "no ready worker" in doc["error"]
        status, _doc = _get(port, "/readyz")
        assert status == 503
        assert fleet_stats()["shed_503"] - before >= 1
    finally:
        httpd.shutdown()
        sup.stop(drain=False)


def test_forward_fault_site_fails_over(fleet_env, fleet4):
    """A chaos plan poisoning ``fleet.forward`` on its first attempt
    still answers the client 200 — the sibling retry absorbs it."""
    _sup, _httpd, port = fleet4
    plan = resilience.FaultPlan(seed=5).on("fleet.forward",
                                           error=OSError, at=[0])
    before = fleet_stats()["failovers"]
    with resilience.fault_plan(plan):
        status, doc = _post(port, "/v1/models/churn:score",
                            {"records": fleet_env["v1"]["records"][:3]})
    assert status == 200 and doc["rows"] == 3
    assert plan.fired("fleet.forward") == 1
    assert fleet_stats()["failovers"] - before >= 1


# ---------------------------------------------------------------------------
# rolling drain-then-restart: zero drops
# ---------------------------------------------------------------------------


def test_drained_restart_loses_zero_requests(fleet_env, fleet4):
    sup, _httpd, port = fleet4
    recs_all = fleet_env["v1"]["records"]
    results = []
    res_lock = threading.Lock()
    stop = threading.Event()

    def client(k):
        i = 0
        while not stop.is_set():
            lo = ((k * 37 + i * 11) % (len(recs_all) - 4))
            recs = recs_all[lo:lo + 4]
            status, doc = _post(port, "/v1/models/churn:score",
                                {"records": recs})
            with res_lock:
                results.append((status, recs, doc))
            i += 1
            time.sleep(0.02)

    threads = [threading.Thread(target=client, args=(k,),
                                name=f"fleet-roll-client-{k}",
                                daemon=True) for k in range(2)]
    for t in threads:
        t.start()
    try:
        before = fleet_stats()["drained_restarts"]
        sup.restart_worker(sup.workers[1], ready_timeout_s=240)
        assert fleet_stats()["drained_restarts"] - before == 1
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=240)
    assert results
    failed = [(s, d) for s, _r, d in results if s != 200]
    assert not failed, failed[:3]
    # every answer bit-identical to the single-process oracle
    for status, recs, doc in results:
        assert doc["outputs"] == _oracle(fleet_env["v1"], recs,
                                         doc["bucket"])
    assert len(sup.ready_workers()) == 4


# ---------------------------------------------------------------------------
# the chaos acceptance: SIGKILL mid-load
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_sigkill_failover_respawn_and_post_promote_rejoin(
        fleet_env, fleet4):
    """The acceptance chaos test (ISSUE 11): 4-worker fleet under
    sustained load, SIGKILL one worker → ZERO failed client requests
    (sibling failover absorbs the in-flight loss), the supervisor
    respawns it within the backoff budget, the registry CURRENT pointer
    is unmoved and uncorrupted (fresh-interpreter assert), a promote
    issued DURING the outage is observed by the respawned worker on
    rejoin, and every survivor score is bit-identical to a
    single-process run."""
    sup, _httpd, port = fleet4
    reg = fleet_env["registry"]
    v1, v2 = fleet_env["v1"], fleet_env["v2"]
    assert reg.current("churn") == v1["vid"]
    recs_all = v1["records"]
    # warm EVERY worker's BOTH tenants first: a lazy tenant resolves
    # CURRENT on its first load, so an un-warmed survivor would
    # legitimately serve v2 after the mid-outage promote and the
    # survivor bit-identity assertion below would be ill-posed
    for h in sup.ready_workers():
        for name in ("churn", "fraud"):
            status, _doc = _post(h.port, f"/v1/models/{name}:score",
                                 {"records": recs_all[:2]})
            assert status == 200, (name, _doc)
    results = []
    res_lock = threading.Lock()
    stop = threading.Event()

    def client(k):
        i = 0
        while not stop.is_set():
            lo = ((k * 53 + i * 17) % (len(recs_all) - 6))
            n = 2 + (i % 4)
            recs = recs_all[lo:lo + n]
            name = "churn" if (k + i) % 2 == 0 else "fraud"
            status, doc = _post(port, f"/v1/models/{name}:score",
                                {"records": recs})
            with res_lock:
                results.append((name, status, recs, doc))
            i += 1
            time.sleep(0.01)

    threads = [threading.Thread(target=client, args=(k,),
                                name=f"fleet-chaos-client-{k}",
                                daemon=True) for k in range(4)]
    for t in threads:
        t.start()
    victim = sup.workers[0]
    spawns_before = victim.spawns
    respawned_before = fleet_stats()["workers_respawned"]
    try:
        time.sleep(0.5)                       # load is flowing
        victim.proc.send_signal(signal.SIGKILL)   # a REAL crash
        # the promote lands while the victim is DOWN: the registry's
        # flock + atomic pointer swap work under fleet load, and the
        # respawned worker must observe the new CURRENT on rejoin
        reg.promote("churn", v2["vid"])
        time.sleep(1.5)                       # sustained load over the outage
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=240)

    # zero failed client requests: failover absorbed the kill
    assert len(results) >= 40
    assert {nm for nm, _s, _r, _d in results} == {"churn", "fraud"}
    failed = [(s, d) for _n, s, _r, d in results if s != 200]
    assert not failed, failed[:3]
    # survivors served churn@v1 / fraud@v2 throughout (loaded before
    # the promote): every answer bit-identical to the single-process
    # run of the version that tenant was serving
    for name, status, recs, doc in results:
        tag = v1 if name == "churn" else v2
        assert doc["outputs"] == _oracle(tag, recs, doc["bucket"])

    # the supervisor respawns the victim within the backoff budget
    deadline = time.monotonic() + 240
    while time.monotonic() < deadline:
        if victim.spawns > spawns_before \
                and victim.state == fleet_mod.READY:
            break
        time.sleep(0.05)
    assert victim.spawns > spawns_before, victim.status()
    assert victim.state == fleet_mod.READY, victim.status()
    assert fleet_stats()["workers_respawned"] - respawned_before >= 1

    # pointer unmoved by the crash, uncorrupted, readable by a FRESH
    # interpreter (the crashed holder's flock released automatically)
    probe = textwrap.dedent(f"""
        import sys
        from transmogrifai_tpu.lifecycle import ModelRegistry
        reg = ModelRegistry({fleet_env["registry_dir"]!r})
        assert reg.current("churn") == {v2["vid"]!r}, reg.current("churn")
        reg.promote("churn", {v2["vid"]!r})   # idempotent: not wedged
        sys.exit(0)
    """)
    proc = subprocess.run([sys.executable, "-c", probe],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-800:]

    # the respawned worker resolved the NEW pointer on boot: scoring
    # DIRECTLY against it answers with v2, bit-identical to v2's
    # single-process run (v1 and v2 genuinely disagree on this payload)
    recs = recs_all[:5]
    deadline = time.monotonic() + 240
    status, doc = 0, {}
    while time.monotonic() < deadline:
        try:
            status, doc = _post(victim.port, "/v1/models/churn:score",
                                {"records": recs})
            if status == 200:
                break
        except OSError:
            pass
        time.sleep(0.2)
    assert status == 200, doc
    v2_answer = _oracle(v2, recs, doc["bucket"])
    assert doc["outputs"] == v2_answer
    assert v2_answer != _oracle(v1, recs, doc["bucket"])
    # restore v1 for any later test using the shared registry
    reg.promote("churn", v1["vid"])


# ---------------------------------------------------------------------------
# flock crash-release: fresh-interpreter SIGKILL of the lock holder
# ---------------------------------------------------------------------------


def test_sigkill_of_pointer_lock_holder_releases_flock(tmp_path):
    """Extends the PR 10 crash-mid-promote test to REAL process death:
    a fresh interpreter takes the registry's pointer flock and is
    SIGKILLed while holding it. The kernel releases the lock with the
    process, so a sibling's promote proceeds — no staleness heuristic,
    no manual cleanup, no wedged fleet."""
    reg_dir = str(tmp_path / "reg")
    reg = ModelRegistry(reg_dir)
    reg.register("m", "/tmp/a", version="va", promote=True)
    reg.register("m", "/tmp/b", version="vb")
    holder = textwrap.dedent(f"""
        import sys, time
        from transmogrifai_tpu.lifecycle import ModelRegistry
        reg = ModelRegistry({reg_dir!r})
        with reg._pointer_mutation("m", timeout_s=5):
            print("LOCKED", flush=True)
            time.sleep(300)
    """)
    proc = subprocess.Popen([sys.executable, "-c", holder],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        assert "LOCKED" in line, proc.stderr.read()[-800:]
        # while the holder lives, a sibling CANNOT take the lock ...
        from transmogrifai_tpu.lifecycle import RegistryError
        with pytest.raises(RegistryError, match="held elsewhere"):
            with reg._pointer_mutation("m", timeout_s=0.3):
                pass
        # ... SIGKILL the holder: no unlock code runs, only the kernel
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=60)
    # the dead holder's flock released automatically: promote proceeds
    ptr = reg.promote("m", "vb")
    assert ptr["current"] == "vb" and ptr["previous"] == "va"


# ---------------------------------------------------------------------------
# cli satellites: gen knobs, check validation, fleet arg validation
# ---------------------------------------------------------------------------


def test_cli_gen_emits_fleet_knobs(tmp_path):
    from transmogrifai_tpu.cli import generate_project
    csv = tmp_path / "in.csv"
    csv.write_text("id,label,x\n1,0,0.5\n2,1,1.5\n3,0,0.7\n4,1,1.1\n")
    out = generate_project(str(csv), "label", str(tmp_path / "proj"),
                           id_column="id")
    params = json.loads(open(out["params.json"]).read())
    for knob in ("fleetWorkers", "fleetBasePort", "workerRespawnMax",
                 "routerRetryBudget"):
        assert knob in params["customParams"]
        assert params["customParams"][knob] is None


@pytest.mark.parametrize("key,val", [
    ("fleetWorkers", 0), ("fleetWorkers", 2.5),
    ("fleetBasePort", "ephemeral"), ("workerRespawnMax", -1),
    ("routerRetryBudget", "lots"),
])
def test_cli_check_validates_fleet_knobs(tmp_path, capsys, key, val):
    from transmogrifai_tpu.cli import run_check
    p = tmp_path / "params.json"
    p.write_text(json.dumps({"customParams": {key: val}}))
    assert run_check(str(p)) == 1
    out = capsys.readouterr().out
    assert "TMG001" in out and key in out


def test_cli_fleet_bad_params_exits_nonzero(tmp_path, capsys):
    from transmogrifai_tpu.cli import run_fleet
    assert run_fleet(None) == 1
    assert "params file is required" in capsys.readouterr().out
    p = tmp_path / "params.json"
    p.write_text(json.dumps({"customParams": {"fleetWorkers": "many"}}))
    assert run_fleet(str(p)) == 1
    assert "fleetWorkers" in capsys.readouterr().out
    # an explicit --workers 0 is a config error, not "use the knob"
    p.write_text(json.dumps({}))
    assert run_fleet(str(p), workers=0) == 1
    assert "--workers must be >= 1" in capsys.readouterr().out


def test_respawn_budget_resets_after_sustained_health(tmp_path):
    """Satellite regression: the consecutive-crash budget resets after
    a SUSTAINED-healthy interval (READY for >= the backoff max delay),
    so a worker crashing once a day never exhausts workerRespawnMax —
    while a flicker-ready crash loop (which the old instant reset let
    evade the budget forever) still exhausts it. Pure state-machine
    test: no processes are spawned."""
    backoff = resilience.RetryPolicy(max_attempts=4, base_delay_s=0.01,
                                     max_delay_s=0.05, jitter=0.0)
    sup = FleetSupervisor(str(tmp_path / "params.json"), workers=1,
                          respawn_max=1, backoff=backoff,
                          log_dir=str(tmp_path / "logs"))
    h = sup.workers[0]
    # crash #1: within budget, scheduled for respawn
    sup._note_crash(h)
    assert h.state == fleet_mod.DEAD and h.restarts == 1
    # back READY: the budget does NOT reset on the first probe
    sup._note_ready(h)
    assert h.state == fleet_mod.READY and h.restarts == 1
    # ... but does after the sustained-healthy interval
    time.sleep(backoff.max_delay_s + 0.02)
    sup._note_ready(h)
    assert h.restarts == 0
    # crash #2, a day-later-style spaced crash: a NEW incident — the
    # worker respawns instead of being given up on (was: FAILED once
    # the lifetime count crept past the budget)
    sup._note_crash(h)
    assert h.state == fleet_mod.DEAD and h.restarts == 1
    # flicker-ready crash loop: READY too briefly to reset, so the
    # SECOND crash exhausts respawn_max=1 and the worker goes FAILED
    sup._note_ready(h)
    sup._note_crash(h)
    assert h.restarts == 2
    assert h.state == fleet_mod.FAILED
