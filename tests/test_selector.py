"""ModelSelector + tuning tests (ModelSelectorTest / OpCrossValidationTest analogs)."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, column_from_values
from transmogrifai_tpu.columns import VectorColumn
from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                      CrossValidation, DataBalancer,
                                      DataCutter, LogisticRegressionFamily,
                                      MultiClassificationModelSelector,
                                      NaiveBayesFamily,
                                      RegressionModelSelector,
                                      LinearRegressionFamily)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.workflow import Workflow


def _clf_store(rng, n=300, d=4, n_classes=2):
    X = rng.normal(size=(n, d))
    w = rng.normal(size=(d, n_classes))
    y = np.argmax(X @ w + rng.normal(scale=0.5, size=(n, n_classes)), axis=1)
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y.astype(float)),
        "features": VectorColumn(ft.OPVector, X),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    return store, label, feats, y


def test_binary_selector_cv(rng):
    store, label, feats, y = _clf_store(rng)
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=3, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0},
                  {"regParam": 0.1, "elasticNetParam": 0.5}])])
    pred = label.transform_with(sel, feats)
    model = sel.fit(store)
    summ = model.selector_summary
    assert summ.best_model_name == "OpLogisticRegression"
    assert len(summ.validator_summary.results) == 2
    for r in summ.validator_summary.results:
        assert len(r.metric_values) == 3  # 3 folds
    assert summ.train_evaluation["AuROC"] > 0.8
    out = model.transform_columns(store)
    assert out.prediction.shape == (300,)


def test_multiclass_selector(rng):
    store, label, feats, y = _clf_store(rng, n_classes=3)
    sel = MultiClassificationModelSelector.with_cross_validation(
        num_folds=2,
        families=[LogisticRegressionFamily(grid=[{"regParam": 0.01,
                                                  "elasticNetParam": 0.0}]),
                  NaiveBayesFamily()])
    pred = label.transform_with(sel, feats)
    model = sel.fit(store)
    assert model.selector_summary.train_evaluation["F1"] > 0.6
    out = model.transform_columns(store)
    assert out.probability.shape == (300, 3)


def test_regression_selector(rng):
    n = 200
    X = rng.normal(size=(n, 3))
    y = X @ np.array([1.0, 2.0, -1.0]) + 0.5
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "features": VectorColumn(ft.OPVector, X),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    sel = RegressionModelSelector.with_train_validation_split(
        families=[LinearRegressionFamily(
            grid=[{"regParam": 0.0, "elasticNetParam": 0.0}])])
    pred = label.transform_with(sel, feats)
    model = sel.fit(store)
    assert model.selector_summary.train_evaluation["RootMeanSquaredError"] < 0.1


def test_balancer_weights():
    b = DataBalancer(sample_fraction=0.2)
    y = np.array([1.0] * 5 + [0.0] * 95)
    b.pre_validation_prepare(y)
    w = b.sample_weights(y)
    # weighted positive fraction should hit the target
    frac = w[y == 1].sum() / w.sum()
    assert abs(frac - 0.2) < 1e-9
    assert b.summary["positiveLabels"] == 5


def test_balancer_no_op_when_balanced():
    b = DataBalancer(sample_fraction=0.1)
    y = np.array([1.0] * 50 + [0.0] * 50)
    b.pre_validation_prepare(y)
    assert np.all(b.sample_weights(y) == 1.0)


def test_cutter_drops_rare_labels():
    c = DataCutter(min_label_fraction=0.2)
    y = np.array([0.0] * 50 + [1.0] * 45 + [2.0] * 5)
    c.pre_validation_prepare(y)
    keep = c.keep_mask(y)
    assert keep.sum() == 95
    assert c.summary["labelsDropped"] == [2.0]


def test_cv_fold_masks_partition():
    cv = CrossValidation(num_folds=3, task="binary")
    y = np.zeros(10)
    splits = cv._splits(y)
    assert len(splits) == 3
    val_total = sum(v for _, v in splits)
    np.testing.assert_allclose(val_total, np.ones(10))  # each row in 1 fold
    for tr, v in splits:
        np.testing.assert_allclose(tr + v, np.ones(10))


def test_selector_in_workflow_with_holdout(rng):
    store, label, feats, y = _clf_store(rng)
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2,
        families=[LogisticRegressionFamily(grid=[{"regParam": 0.01,
                                                  "elasticNetParam": 0.0}])],
        splitter=DataBalancer(reserve_test_fraction=0.2))
    pred = label.transform_with(sel, feats)
    wf = (Workflow().set_input_store(store).set_result_features(pred)
          .set_splitter(sel.splitter))
    model = wf.train()
    selected = model.fitted_stages[sel.uid]
    assert selected.selector_summary.holdout_evaluation is not None
    assert "AuPR" in selected.selector_summary.holdout_evaluation
    scored = model.score(store)
    assert pred.name in scored.names()


def test_chunked_sweep_matches_unchunked(rng):
    """fold/grid chunking (lax.map) must not change CV metrics — it only
    bounds HBM transients at large row counts."""
    import transmogrifai_tpu.models.tuning as tuning
    from transmogrifai_tpu.models.trees import RandomForestFamily

    n = 400
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    grid = [{"maxDepth": d, "minInstancesPerNode": 10, "minInfoGain": 0.001}
            for d in (3, 5)]

    def sweep():
        fam = RandomForestFamily(grid=[dict(g) for g in grid])
        cv = tuning.CrossValidation(num_folds=3, metric_name="AuROC",
                                    task="binary", seed=3)
        _, _, summ = cv.validate([fam], X, y)
        return np.array([r.mean_metric for r in summ.results])

    saved = tuning.CHUNK_MEM_BUDGET_BYTES
    try:
        tuning.CHUNK_MEM_BUDGET_BYTES = 1e18     # no chunking
        full = sweep()
        tuning.CHUNK_MEM_BUDGET_BYTES = 1        # fold_chunk=1, grid_chunk=1
        chunked = sweep()
    finally:
        tuning.CHUNK_MEM_BUDGET_BYTES = saved
    np.testing.assert_allclose(full, chunked, rtol=1e-5)


def test_balancer_exact_proportions():
    """DataBalancer fractions port DataBalancer.getProportions exactly
    (DataBalancer.scala:84-115) with reweighting as the mechanism."""
    from transmogrifai_tpu.models.tuning import DataBalancer

    # imbalanced, small enough to upsample: 50 pos / 950 neg, f=0.2
    b = DataBalancer(sample_fraction=0.2, max_training_sample=10_000)
    y = np.array([1.0] * 50 + [0.0] * 950)
    b.pre_validation_prepare(y)
    s = b.summary
    # checkUpSampleSize(4): 4*50*0.8=160 < 0.2*950=190 ✓ and 2000 > 200 ✓
    assert s["upSamplingFraction"] == 4.0
    assert s["downSamplingFraction"] == pytest.approx(
        (50 * 4 / 0.2 - 50 * 4) / 950)
    w = b.sample_weights(y)
    assert w[0] == 4.0 and w[-1] == pytest.approx(s["downSamplingFraction"])

    # already balanced but too big: uniform downsample
    b2 = DataBalancer(sample_fraction=0.1, max_training_sample=100)
    y2 = np.array([1.0] * 100 + [0.0] * 100)
    b2.pre_validation_prepare(y2)
    assert b2.summary["upSamplingFraction"] == 0.0
    assert b2.summary["downSamplingFraction"] == pytest.approx(0.5)
    assert np.allclose(b2.sample_weights(y2), 0.5)

    # too big AND imbalanced: downsample both
    b3 = DataBalancer(sample_fraction=0.5, max_training_sample=100)
    y3 = np.array([1.0] * 200 + [0.0] * 800)
    b3.pre_validation_prepare(y3)
    assert b3.summary["upSamplingFraction"] == pytest.approx(50 / 200)
    assert b3.summary["downSamplingFraction"] == pytest.approx(
        0.5 * 100 / 800)


def test_cutter_relabels_and_model_maps_back(rng):
    """DataCutter drops rare labels and re-indexes contiguously; the
    SelectedModel translates predictions back to original labels."""
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import MultiClassificationModelSelector
    from transmogrifai_tpu.models.tuning import DataCutter
    from transmogrifai_tpu.columns import VectorColumn
    from transmogrifai_tpu.vector_metadata import (VectorColumnMetadata,
                                                   VectorMetadata)

    n = 300
    # labels 0, 2, 7 frequent; 5 rare (dropped) → model classes 0,1,2
    base = np.array([0.0, 2.0, 7.0])
    y = base[rng.integers(0, 3, n)]
    y[:3] = 5.0
    X = np.stack([(y == v).astype(float) + 0.05 * rng.normal(size=n)
                  for v in base], axis=1)
    meta = VectorMetadata("features", [
        VectorColumnMetadata(f"x{i}", "Real") for i in range(3)])
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "features": VectorColumn(ft.OPVector, X, meta)})
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    selector = MultiClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(grid=[
            {"regParam": 0.01, "elasticNetParam": 0.0}])],
        splitter=DataCutter(min_label_fraction=0.05), seed=3)
    pred = label.transform_with(selector, feats)
    model = Workflow().set_input_store(store).set_result_features(pred).train()
    scored = model.transform(store)
    got = np.asarray(scored[pred.name].prediction)
    assert set(np.unique(got)) <= {0.0, 2.0, 7.0}   # original label values
    acc = (got[3:] == y[3:]).mean()
    assert acc > 0.9, acc
    sel = model.fitted_stages[selector.uid]
    assert sel.label_mapping == [0.0, 2.0, 7.0]


def test_ragged_grid_chunk_parity(rng):
    """ADVICE r2: a prime 7-point grid with a 3-point chunk budget must run
    a ragged [3,3,1] schedule — same metrics as the unchunked sweep, not
    seven 1-wide dispatches."""
    import transmogrifai_tpu.models.tuning as tuning
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily

    assert tuning._chunk_sizes(7, 3) == [3, 3, 1]

    n = 300
    X = rng.normal(size=(n, 5))
    y = (X[:, 0] - 0.3 * X[:, 2] > 0).astype(float)
    grid = [{"regParam": 10.0 ** -k, "elasticNetParam": 0.0}
            for k in range(7)]

    def sweep(chunk):
        fam = LogisticRegressionFamily(grid=[dict(g) for g in grid])
        if chunk:
            fam.grid_chunk = chunk
        cv = tuning.CrossValidation(num_folds=2, metric_name="AuROC",
                                    task="binary", seed=5)
        _, _, summ = cv.validate([fam], X, y)
        return np.array([r.mean_metric for r in summ.results])

    np.testing.assert_allclose(sweep(None), sweep(3), rtol=1e-5)


def test_balancer_physical_sample():
    """physical_sample drops rows Bernoulli(fraction) for fractions < 1
    (Spark's rebalance/maxTrainingSample), deterministically per seed;
    up-weights stay weights; balanced small data is untouched."""
    import numpy as np
    rng = np.random.default_rng(1)

    # big balanced data beyond max_training_sample: uniform downsample
    b = DataBalancer(sample_fraction=0.1, max_training_sample=50_000,
                     seed=9)
    y = (rng.random(200_000) < 0.4).astype(float)
    b.pre_validation_prepare(y)
    w = b.sample_weights(y)
    keep, w2 = b.physical_sample(y, w)
    assert keep is not None
    # expected mass preserved: kept rows ~= frac * n, weights reset to 1
    assert abs(keep.sum() - w.sum()) < 4 * np.sqrt(w.sum())
    assert np.all(w2 == 1.0)
    # deterministic per seed
    b2 = DataBalancer(sample_fraction=0.1, max_training_sample=50_000,
                      seed=9)
    b2.pre_validation_prepare(y)
    keep2, _ = b2.physical_sample(y, b2.sample_weights(y))
    assert np.array_equal(keep, keep2)

    # imbalanced: minority up-weight survives as a weight on ALL its rows
    b3 = DataBalancer(sample_fraction=0.3, seed=9)
    y3 = np.zeros(10_000); y3[:200] = 1.0
    b3.pre_validation_prepare(y3)
    w3 = b3.sample_weights(y3)
    up = b3._pos_weight
    assert up > 1.0
    keep3, w3k = b3.physical_sample(y3, w3)
    y3k = y3[keep3]
    assert (y3k == 1).sum() == 200              # minority fully kept
    assert np.all(w3k[y3k == 1] == up)          # ... at its up-weight
    assert np.all(w3k[y3k == 0] == 1.0)

    # small balanced data: no sampling at all
    b4 = DataBalancer(sample_fraction=0.1)
    y4 = (rng.random(1_000) < 0.4).astype(float)
    b4.pre_validation_prepare(y4)
    keep4, _ = b4.physical_sample(y4, b4.sample_weights(y4))
    assert keep4 is None
